"""Test config: 8-device CPU mesh + isolated state dir.

JAX note: this container routes JAX through the axon TPU plugin whose
sitecustomize forces the axon platform; `jax.config.update` (not the
JAX_PLATFORMS env var) is the reliable way to pin tests to CPU. Must
happen before any backend initialization, hence at conftest import.
"""
import os
import sys

# 8 virtual CPU devices for sharding tests (must precede backend init).
_flags = os.environ.get('XLA_FLAGS', '')
if '--xla_force_host_platform_device_count' not in _flags:
    os.environ['XLA_FLAGS'] = (
        _flags + ' --xla_force_host_platform_device_count=8').strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update('jax_platforms', 'cpu')

import pytest  # noqa: E402


@pytest.fixture()
def isolated_state(tmp_path, monkeypatch):
    """Point SKYPILOT_TPU_HOME at a fresh dir; clear db caches."""
    home = tmp_path / 'sky-home'
    monkeypatch.setenv('SKYPILOT_TPU_HOME', str(home))
    from skypilot_tpu import global_state
    global_state._db_for.cache_clear()  # pylint: disable=protected-access
    yield str(home)
    global_state._db_for.cache_clear()  # pylint: disable=protected-access


@pytest.fixture(scope='session')
def cpu_mesh8():
    from skypilot_tpu.parallel import mesh as mesh_lib
    return mesh_lib.make_mesh(mesh_lib.MeshConfig(data=2, fsdp=2, tensor=2))
