"""Test config: 8-device CPU mesh + isolated state dir.

JAX note: this container routes JAX through the axon TPU plugin whose
sitecustomize forces the axon platform; `jax.config.update` (not the
JAX_PLATFORMS env var) is the reliable way to pin tests to CPU. Must
happen before any backend initialization, hence at conftest import.
"""
import os
import sys

# 8 virtual CPU devices for sharding tests (must precede backend init).
_flags = os.environ.get('XLA_FLAGS', '')
if '--xla_force_host_platform_device_count' not in _flags:
    os.environ['XLA_FLAGS'] = (
        _flags + ' --xla_force_host_platform_device_count=8').strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update('jax_platforms', 'cpu')

# Daemons launched DIRECTLY by this test process (agents from
# in-process sky.launch, the API server fixture, controllers from an
# in-process scheduler) get PR_SET_PDEATHSIG so a killed pytest run
# cannot leak them. The value is this process's pid: intermediaries
# (request workers, controllers, the server) inherit the env but
# don't match it, so THEIR daemons keep production survival semantics
# (a cluster must outlive its launch request; a kill-9'd controller's
# cluster must stay adoptable). Under xdist each worker's conftest
# import re-pins it to that worker.
os.environ['SKYPILOT_DAEMON_PDEATHSIG'] = str(os.getpid())

import pytest  # noqa: E402

# The slow tier splits into `compile` (real XLA compiles) and `e2e`
# (live processes / full pipelines); classification is per-file here
# so `-m 'slow and compile'` / `-m 'slow and e2e'` select sub-tiers
# without per-test decorator churn.
_E2E_FILES = {
    'test_chaos.py', 'test_serve.py', 'test_job_pools.py',
    'test_api_server.py', 'test_e2e_local.py', 'test_managed_jobs.py',
    'test_batch.py', 'test_load.py', 'test_auth.py',
    'test_server_daemons.py', 'test_backward_compat.py',
    'test_sdk_async.py',
}
_COMPILE_FILES = {
    'test_hf_recipes.py', 'test_models.py', 'test_ring_attention.py',
    'test_spec_batching.py', 'test_generate.py', 'test_hf_import.py',
    'test_paged_attention.py', 'test_flash_dispatch.py',
    'test_multislice.py', 'test_prefix_caching.py', 'test_pipeline.py',
    'test_pipeline_schedule.py',
    'test_tp_serving.py', 'test_tp_sharded_pool.py',
    'test_pp_serving.py',
    'test_profile_trace.py', 'test_fused_xent.py',
}


def pytest_collection_modifyitems(config, items):
    del config
    unclassified = set()
    for item in items:
        if 'slow' not in item.keywords:
            continue
        fname = os.path.basename(str(item.fspath))
        if 'e2e' not in item.keywords and fname in _E2E_FILES:
            item.add_marker(pytest.mark.e2e)
        if 'compile' not in item.keywords and fname in _COMPILE_FILES:
            item.add_marker(pytest.mark.compile)
        if not ({'e2e', 'compile'} & set(item.keywords)):
            unclassified.add(fname)
    if unclassified:
        # Exhaustiveness gate: a slow test neither tier selects would
        # silently lose all CI coverage.
        raise pytest.UsageError(
            f'slow tests in {sorted(unclassified)} are in neither '
            f'_E2E_FILES nor _COMPILE_FILES (tests/conftest.py) — add '
            f'the file to a sub-tier or mark the tests explicitly.')


@pytest.fixture(scope='session', autouse=True)
def _reap_leaked_daemons():
    """End-of-session sweep: SIGTERM any still-running skypilot_tpu
    module processes that are DESCENDANTS of this pytest process (a
    fixture that failed mid-teardown can strand agents/replicas).
    Scoped to descendants so concurrent sessions are untouched."""
    yield
    try:
        import psutil
        me = psutil.Process()
        for child in me.children(recursive=True):
            try:
                cmd = ' '.join(child.cmdline())
            except (psutil.NoSuchProcess, psutil.AccessDenied):
                continue
            if 'skypilot_tpu.' in cmd and 'python' in cmd:
                try:
                    child.terminate()
                except psutil.NoSuchProcess:
                    pass
    except Exception:  # pylint: disable=broad-except
        pass


@pytest.fixture()
def isolated_state(tmp_path, monkeypatch):
    """Point SKYPILOT_TPU_HOME at a fresh dir; clear db caches."""
    home = tmp_path / 'sky-home'
    monkeypatch.setenv('SKYPILOT_TPU_HOME', str(home))
    from skypilot_tpu import global_state
    global_state._db_for.cache_clear()  # pylint: disable=protected-access
    yield str(home)
    global_state._db_for.cache_clear()  # pylint: disable=protected-access


@pytest.fixture(scope='session')
def cpu_mesh8():
    from skypilot_tpu.parallel import mesh as mesh_lib
    return mesh_lib.make_mesh(mesh_lib.MeshConfig(data=2, fsdp=2, tensor=2))
