"""HF checkpoint import: logit parity against torch/transformers.

For each supported family, builds a TINY model in transformers,
save_pretrained()s it (safetensors — the real on-disk format of an
hf:// download), converts via models/hf_import.py, and asserts
teacher-forced logit parity between the torch reference and our flax
model — the strongest correctness statement available without network
access (the conversion path is identical for real checkpoints; only
the tensor sizes differ).
"""
import json
import os

import numpy as np
import pytest

jnp = pytest.importorskip('jax.numpy')
import jax  # noqa: E402
import flax.linen as nn  # noqa: E402

torch = pytest.importorskip('torch')
transformers = pytest.importorskip('transformers')

from skypilot_tpu.models import hf_import  # noqa: E402


def _logits_ours(model, params, tokens_np):
    out = model.apply({'params': params},
                      jnp.asarray(tokens_np, jnp.int32))
    if isinstance(out, tuple):      # mixtral: (logits, aux)
        out = out[0]
    return np.asarray(out, np.float32)


def _logits_torch(tmodel, tokens_np):
    with torch.no_grad():
        return tmodel(torch.tensor(tokens_np)).logits.float().numpy()


def _save(tmodel, path):
    tmodel.save_pretrained(path, safe_serialization=True)


@pytest.fixture()
def tokens():
    rng = np.random.default_rng(0)
    return rng.integers(0, 120, size=(2, 12), dtype=np.int64)


@pytest.mark.slow
def test_llama_parity(tmp_path, tokens):
    cfg = transformers.LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=160,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, max_position_embeddings=64,
        rope_theta=10000.0, tie_word_embeddings=False)
    tmodel = transformers.LlamaForCausalLM(cfg).eval()
    _save(tmodel, tmp_path)
    model, params = hf_import.load_hf_checkpoint(
        str(tmp_path), dtype=jnp.float32)
    assert model.config.num_kv_heads == 2
    np.testing.assert_allclose(
        _logits_ours(model, params, tokens), _logits_torch(tmodel, tokens),
        rtol=2e-4, atol=2e-4)


def test_llama_tied_embeddings(tmp_path, tokens):
    cfg = transformers.LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=160,
        num_hidden_layers=1, num_attention_heads=4,
        num_key_value_heads=4, max_position_embeddings=64,
        tie_word_embeddings=True)
    tmodel = transformers.LlamaForCausalLM(cfg).eval()
    _save(tmodel, tmp_path)
    model, params = hf_import.load_hf_checkpoint(
        str(tmp_path), dtype=jnp.float32)
    np.testing.assert_allclose(
        _logits_ours(model, params, tokens), _logits_torch(tmodel, tokens),
        rtol=2e-4, atol=2e-4)


def test_llama3_rope_scaling_parity(tmp_path, tokens):
    """Llama 3.1-style rope_scaling rescales inv_freq; logits must
    match transformers' llama3 rule exactly (ADVICE r3: previously
    the scaling block was silently ignored)."""
    cfg = transformers.LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=160,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, max_position_embeddings=64,
        rope_theta=10000.0, tie_word_embeddings=False,
        rope_scaling={'rope_type': 'llama3', 'factor': 8.0,
                      'low_freq_factor': 1.0, 'high_freq_factor': 4.0,
                      'original_max_position_embeddings': 8})
    tmodel = transformers.LlamaForCausalLM(cfg).eval()
    _save(tmodel, tmp_path)
    model, params = hf_import.load_hf_checkpoint(
        str(tmp_path), dtype=jnp.float32)
    assert model.config.rope_scaling is not None
    assert model.config.rope_scaling.rope_type == 'llama3'
    np.testing.assert_allclose(
        _logits_ours(model, params, tokens), _logits_torch(tmodel, tokens),
        rtol=2e-4, atol=2e-4)


def test_linear_rope_scaling_parity(tmp_path, tokens):
    cfg = transformers.LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=160,
        num_hidden_layers=1, num_attention_heads=4,
        num_key_value_heads=4, max_position_embeddings=64,
        tie_word_embeddings=False,
        rope_scaling={'rope_type': 'linear', 'factor': 4.0})
    tmodel = transformers.LlamaForCausalLM(cfg).eval()
    _save(tmodel, tmp_path)
    model, params = hf_import.load_hf_checkpoint(
        str(tmp_path), dtype=jnp.float32)
    np.testing.assert_allclose(
        _logits_ours(model, params, tokens), _logits_torch(tmodel, tokens),
        rtol=2e-4, atol=2e-4)


def test_unsupported_rope_scaling_rejected(tmp_path):
    """yarn (and other unimplemented schemes) must raise, not import
    with silently wrong frequencies — and BEFORE weights are read."""
    (tmp_path / 'config.json').write_text(json.dumps({
        'model_type': 'llama', 'rope_scaling': {
            'rope_type': 'yarn', 'factor': 4.0}}))
    with pytest.raises(hf_import.HfImportError, match='yarn'):
        hf_import.load_hf_checkpoint(str(tmp_path))


def test_gpt2_parity(tmp_path, tokens):
    cfg = transformers.GPT2Config(
        vocab_size=128, n_positions=64, n_embd=64, n_layer=2, n_head=4,
        resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0)
    tmodel = transformers.GPT2LMHeadModel(cfg).eval()
    _save(tmodel, tmp_path)
    model, params = hf_import.load_hf_checkpoint(
        str(tmp_path), dtype=jnp.float32)
    np.testing.assert_allclose(
        _logits_ours(model, params, tokens), _logits_torch(tmodel, tokens),
        rtol=2e-4, atol=2e-4)


def test_mixtral_parity(tmp_path, tokens):
    cfg = transformers.MixtralConfig(
        vocab_size=128, hidden_size=64, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, num_local_experts=4,
        num_experts_per_tok=2, max_position_embeddings=64,
        tie_word_embeddings=False, router_jitter_noise=0.0)
    tmodel = transformers.MixtralForCausalLM(cfg).eval()
    _save(tmodel, tmp_path)
    # capacity_factor = num_experts: no capacity drops, so the
    # capacity-bounded einsum dispatch is EXACTLY HF's top-k gather.
    model, params = hf_import.load_hf_checkpoint(
        str(tmp_path), dtype=jnp.float32, capacity_factor=4.0)
    np.testing.assert_allclose(
        _logits_ours(model, params, tokens), _logits_torch(tmodel, tokens),
        rtol=5e-4, atol=5e-4)


@pytest.mark.parametrize('q_lora_rank', [None, 24])
def test_deepseek_parity(tmp_path, tokens, q_lora_rank):
    cfg = transformers.DeepseekV2Config(
        vocab_size=128, hidden_size=64, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=4, kv_lora_rank=32,
        q_lora_rank=q_lora_rank, qk_rope_head_dim=16,
        qk_nope_head_dim=32, v_head_dim=32,
        n_routed_experts=None, first_k_dense_replace=2,
        max_position_embeddings=64, tie_word_embeddings=False)
    tmodel = transformers.DeepseekV2ForCausalLM(cfg).eval()
    _save(tmodel, tmp_path)
    model, params = hf_import.load_hf_checkpoint(
        str(tmp_path), dtype=jnp.float32)
    np.testing.assert_allclose(
        _logits_ours(model, params, tokens), _logits_torch(tmodel, tokens),
        rtol=3e-4, atol=3e-4)


def test_deepseek_moe_rejected(tmp_path):
    (tmp_path / 'config.json').write_text(json.dumps({
        'model_type': 'deepseek_v2', 'n_routed_experts': 8}))
    with pytest.raises(hf_import.HfImportError, match='routed-expert'):
        hf_import.load_hf_checkpoint(str(tmp_path))


def test_unknown_model_type(tmp_path):
    (tmp_path / 'config.json').write_text(json.dumps(
        {'model_type': 'mamba'}))
    with pytest.raises(hf_import.HfImportError, match='unsupported'):
        hf_import.load_hf_checkpoint(str(tmp_path))


def test_max_seq_len_override_and_serving(tmp_path):
    """Serving path smoke: clamp max_seq_len, run the cached generate
    engine off imported weights, check greedy continuation matches the
    torch argmax at the prompt boundary."""
    cfg = transformers.LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=160,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, max_position_embeddings=4096,
        tie_word_embeddings=False)
    tmodel = transformers.LlamaForCausalLM(cfg).eval()
    _save(tmodel, tmp_path)
    model, params = hf_import.load_hf_checkpoint(
        str(tmp_path), max_seq_len=32, dtype=jnp.float32)
    assert model.config.max_seq_len == 32

    from skypilot_tpu.models.generate import make_generate_fn
    prompt_np = np.asarray([[5, 9, 2, 17]], np.int64)
    out = make_generate_fn(model, 8)(
        params, jnp.asarray(prompt_np, jnp.int32), jax.random.PRNGKey(0))
    want_next = int(np.argmax(_logits_torch(tmodel, prompt_np)[0, -1]))
    assert int(np.asarray(out)[0, 4]) == want_next


def test_sharded_safetensors(tmp_path, tokens):
    """Sharded checkpoints (model.safetensors.index.json) load too."""
    cfg = transformers.LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=160,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, max_position_embeddings=64,
        tie_word_embeddings=False)
    tmodel = transformers.LlamaForCausalLM(cfg).eval()
    tmodel.save_pretrained(tmp_path, safe_serialization=True,
                           max_shard_size='100KB')
    assert os.path.exists(tmp_path / 'model.safetensors.index.json')
    model, params = hf_import.load_hf_checkpoint(
        str(tmp_path), dtype=jnp.float32)
    np.testing.assert_allclose(
        _logits_ours(model, params, tokens), _logits_torch(tmodel, tokens),
        rtol=2e-4, atol=2e-4)


def test_max_seq_len_exceeding_trained_context(tmp_path):
    """GPT-2 (absolute positions) refuses an oversized override with a
    clear message; rope families warn about extrapolation."""
    cfg = transformers.GPT2Config(
        vocab_size=128, n_positions=64, n_embd=64, n_layer=1, n_head=4)
    transformers.GPT2LMHeadModel(cfg).eval().save_pretrained(
        tmp_path, safe_serialization=True)
    with pytest.raises(hf_import.HfImportError, match='cannot extrapolate'):
        hf_import.load_hf_checkpoint(str(tmp_path), max_seq_len=128)

    llama_dir = tmp_path / 'llama'
    cfg = transformers.LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=96,
        num_hidden_layers=1, num_attention_heads=4,
        num_key_value_heads=4, max_position_embeddings=64,
        tie_word_embeddings=False)
    transformers.LlamaForCausalLM(cfg).eval().save_pretrained(
        llama_dir, safe_serialization=True)
    with pytest.warns(UserWarning, match='untrained extrapolation'):
        hf_import.load_hf_checkpoint(str(llama_dir), max_seq_len=128)


@pytest.mark.slow
def test_qwen2_parity(tmp_path, tokens):
    """Qwen2/2.5 (llama backbone + q/k/v biases, tied embeddings —
    the 0.5B/1.5B shape): teacher-forced logit parity vs torch."""
    cfg = transformers.Qwen2Config(
        vocab_size=128, hidden_size=64, intermediate_size=160,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, max_position_embeddings=64,
        rope_theta=10000.0, tie_word_embeddings=True)
    tmodel = transformers.Qwen2ForCausalLM(cfg).eval()
    _save(tmodel, tmp_path)
    # save_pretrained writes model_type=qwen2 in config.json.
    with open(os.path.join(tmp_path, 'config.json'),
              encoding='utf-8') as f:
        assert json.load(f)['model_type'] == 'qwen2'
    model, params = hf_import.load_hf_checkpoint(
        str(tmp_path), dtype=jnp.float32)
    assert model.config.qkv_bias is True
    assert 'bias' in params['layer_0']['attn']['wq']
    np.testing.assert_allclose(
        _logits_ours(model, params, tokens),
        _logits_torch(tmodel, tokens), rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_mistral_parity(tmp_path, tokens):
    """Mistral config.json is llama-shaped; the shared converter
    handles it."""
    cfg = transformers.MistralConfig(
        vocab_size=128, hidden_size=64, intermediate_size=160,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, max_position_embeddings=64,
        sliding_window=None, tie_word_embeddings=False)
    tmodel = transformers.MistralForCausalLM(cfg).eval()
    _save(tmodel, tmp_path)
    model, params = hf_import.load_hf_checkpoint(
        str(tmp_path), dtype=jnp.float32)
    assert model.config.qkv_bias is False
    np.testing.assert_allclose(
        _logits_ours(model, params, tokens),
        _logits_torch(tmodel, tokens), rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_qwen2_cached_decode_matches_full_forward(tmp_path, tokens):
    """The serving path (KV-cache incremental decode) is exact for the
    biased-attention variant too."""
    cfg = transformers.Qwen2Config(
        vocab_size=128, hidden_size=64, intermediate_size=160,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, max_position_embeddings=64,
        tie_word_embeddings=True)
    _save(transformers.Qwen2ForCausalLM(cfg).eval(), tmp_path)
    model, params = hf_import.load_hf_checkpoint(
        str(tmp_path), dtype=jnp.float32)
    # Device placement, as serving does (the importer hands back
    # numpy f32 masters; traced code needs jax arrays).
    params = jax.tree.map(jnp.asarray, params)
    from skypilot_tpu.models.generate import teacher_forced_logits
    full, decoded = teacher_forced_logits(
        model, params, jnp.asarray(tokens[:, :8], jnp.int32))
    np.testing.assert_allclose(np.asarray(decoded), np.asarray(full),
                               rtol=2e-4, atol=2e-4)
