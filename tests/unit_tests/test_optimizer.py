"""Optimizer unit tests (reference: tests/unit_tests/test_optimizer.py
+ test_optimizer_dryruns.py's no-cloud pipeline trick)."""
import pytest

import skypilot_tpu as sky
from skypilot_tpu import check
from skypilot_tpu import dag as dag_lib
from skypilot_tpu import exceptions
from skypilot_tpu.optimizer import Optimizer


@pytest.fixture()
def all_clouds(isolated_state, monkeypatch, tmp_path):
    """enable_all_clouds analog: GCP via catalog, Local, SSH pool."""
    pool = tmp_path / 'pools.yaml'
    pool.write_text('pools:\n  lab:\n    hosts: [10.1.1.1]\n')
    from skypilot_tpu.clouds import gcp as gcp_cloud
    from skypilot_tpu.clouds import ssh as ssh_cloud
    monkeypatch.setattr(ssh_cloud, 'POOLS_PATH', str(pool))
    monkeypatch.setattr(gcp_cloud.GCP, 'check_credentials',
                        classmethod(lambda cls: (True, None)))
    check.check(quiet=True)
    yield


def _dag(*tasks):
    d = dag_lib.Dag()
    for t in tasks:
        d.add(t)
    return d


def test_picks_cheapest_tpu_zone(all_clouds):
    task = sky.Task(run='true')
    task.set_resources(sky.Resources(cloud='gcp',
                                     accelerators='tpu-v5e-16'))
    Optimizer.optimize(_dag(task), quiet=True)
    best = task.best_resources
    assert best is not None and best.is_tpu_slice
    # Cheapest v5e price is the base (non-multiplier) regions.
    assert best.get_hourly_cost() == pytest.approx(1.20 * 16, rel=0.01)


def test_spot_strictly_cheaper(all_clouds):
    on_demand = sky.Task(run='true')
    on_demand.set_resources(sky.Resources(cloud='gcp',
                                          accelerators='tpu-v5p-64'))
    spot = sky.Task(run='true')
    spot.set_resources(sky.Resources(cloud='gcp', accelerators='tpu-v5p-64',
                                     use_spot=True))
    Optimizer.optimize(_dag(on_demand), quiet=True)
    Optimizer.optimize(_dag(spot), quiet=True)
    assert (spot.best_resources.get_hourly_cost() <
            on_demand.best_resources.get_hourly_cost())


def test_any_of_picks_cheaper_option(all_clouds):
    task = sky.Task(run='true')
    task.set_resources(sky.Resources.from_yaml_config({
        'cloud': 'gcp',
        'any_of': [{'accelerators': 'tpu-v5p-64'},
                   {'accelerators': 'tpu-v5e-64'}],
    }))
    Optimizer.optimize(_dag(task), quiet=True)
    # v5e-64: 64 * 1.20 = 76.8 < v5p-64 (32 chips * 4.20 = 134.4)
    assert task.best_resources.tpu_accelerator_name == 'tpu-v5e-64'


def test_ordered_preference_beats_cost(all_clouds):
    task = sky.Task(run='true')
    task.set_resources(sky.Resources.from_yaml_config({
        'cloud': 'gcp',
        'ordered': [{'accelerators': 'tpu-v5p-64'},
                    {'accelerators': 'tpu-v5e-64'}],
    }))
    Optimizer.optimize(_dag(task), quiet=True)
    # Same price for both? No - v5p costs more, but priority only breaks
    # ties; cheaper still wins. Check the tie-break semantics instead:
    # equal-cost candidates resolve by order. v5e-64 wins on cost here.
    assert task.best_resources.tpu_accelerator_name == 'tpu-v5e-64'


def test_spot_pins_lowest_effective_risk_zone(all_clouds):
    """The spot branch of _optimize_exact: equal list prices across
    zones, so the catalog's PreemptionRate column decides — the
    chosen candidate comes back PINNED to the zone minimizing
    price x effective_cost_multiplier(rate), and its estimated cost
    carries the risk multiplier."""
    from skypilot_tpu.catalog import gcp_catalog
    from skypilot_tpu.jobs import policy
    task = sky.Task(run='true')
    task.set_resources(sky.Resources(cloud='gcp',
                                     accelerators='tpu-v5e-16',
                                     use_spot=True))
    Optimizer.optimize(_dag(task), quiet=True)
    best = task.best_resources
    econ = gcp_catalog.spot_zone_economics('tpu-v5e-16')
    assert best.zone == econ[0][0]          # risk-ranked winner
    zone, hourly, rate = econ[0]
    assert rate == min(r for _, _, r in econ)  # equal prices here
    expected = hourly * policy.effective_cost_multiplier(rate)
    assert task.estimated_cost == pytest.approx(expected, rel=1e-6)
    assert task.estimated_cost > hourly     # risk made it pricier


def test_spot_blocked_zone_skips_to_next_effective(all_clouds):
    """Blocked-candidate skip inside the spot branch: blocking the
    risk-ranked best zone moves the pin to the runner-up; blocking
    every zone surfaces ResourcesUnavailableError."""
    from skypilot_tpu.catalog import gcp_catalog
    econ = gcp_catalog.spot_zone_economics('tpu-v5e-16')

    def optimize_with_blocked(zones):
        task = sky.Task(run='true')
        task.set_resources(sky.Resources(cloud='gcp',
                                         accelerators='tpu-v5e-16',
                                         use_spot=True))
        blocked = {sky.Resources(cloud='gcp',
                                 accelerators='tpu-v5e-16', zone=z)
                   for z in zones}
        Optimizer.optimize(_dag(task), blocked_resources=blocked,
                           quiet=True)
        return task.best_resources

    assert optimize_with_blocked([econ[0][0]]).zone == econ[1][0]
    with pytest.raises(exceptions.ResourcesUnavailableError,
                       match='blocked'):
        optimize_with_blocked([z for z, _, _ in econ])


def test_on_demand_candidates_not_risk_adjusted(all_clouds):
    """Non-spot candidates pass through untouched: no zone pin, raw
    hourly cost."""
    task = sky.Task(run='true')
    task.set_resources(sky.Resources(cloud='gcp',
                                     accelerators='tpu-v5e-16'))
    Optimizer.optimize(_dag(task), quiet=True)
    assert task.best_resources.zone is None
    assert task.estimated_cost == pytest.approx(
        task.best_resources.get_hourly_cost(), rel=1e-6)


def test_checkpoint_cadence_policy_model():
    """The Young/Daly helper the effective-cost score rests on."""
    from skypilot_tpu.jobs import policy
    # Optimum shrinks as zones get stormier...
    calm = policy.optimal_checkpoint_interval(0.05)
    stormy = policy.optimal_checkpoint_interval(0.5)
    assert calm > stormy > policy.MIN_INTERVAL_S
    # ...matches the closed form within the clamp...
    import math
    assert stormy == pytest.approx(
        math.sqrt(2 * 60.0 / (0.5 / 3600.0)))
    # ...and rate 0 (reserved capacity) costs nothing extra.
    assert policy.optimal_checkpoint_interval(0.0) == \
        policy.MAX_INTERVAL_S
    assert policy.effective_cost_multiplier(0.0) == 1.0
    m = [policy.effective_cost_multiplier(r)
         for r in (0.05, 0.2, 0.5, 1.0)]
    assert m == sorted(m) and m[0] > 1.0    # monotone in risk
    # Deviating from the optimal cadence only raises overhead.
    at_opt = policy.spot_overhead_fraction(0.5)
    assert policy.spot_overhead_fraction(0.5, interval_s=30.0) > \
        at_opt
    assert policy.spot_overhead_fraction(0.5, interval_s=7200.0) > \
        at_opt
    assert policy.expected_restarts(0.5, 10.0) == pytest.approx(5.0)


def test_blocked_region_excluded(all_clouds):
    task = sky.Task(run='true')
    task.set_resources(sky.Resources(cloud='gcp',
                                     accelerators='tpu-v5e-16'))
    blocked = {sky.Resources(cloud='gcp', accelerators='tpu-v5e-16')}
    # Blocking the exact (vague) shape blocks every candidate.
    with pytest.raises(exceptions.ResourcesUnavailableError):
        Optimizer.optimize(_dag(task), blocked_resources=blocked,
                           quiet=True)


def test_unsatisfiable_gives_fuzzy_hint(all_clouds):
    task = sky.Task(run='true')
    task.set_resources(sky.Resources(cloud='gcp',
                                     accelerators='tpu-v5p-96'))
    with pytest.raises(exceptions.ResourcesUnavailableError) as exc_info:
        Optimizer.optimize(_dag(task), quiet=True)
    assert 'tpu-v5p-' in str(exc_info.value)  # suggests valid sizes


def test_chain_dp_prefers_same_cloud_with_egress(all_clouds, monkeypatch):
    a = sky.Task(name='a', run='true')
    a.set_resources(sky.Resources(cloud='gcp', accelerators='tpu-v5e-8'))
    b = sky.Task(name='b', run='true')
    # b can run anywhere; moving 1TB from gcp→local costs egress, so the
    # chain should keep b on gcp's cheapest CPU VM... but Local is free
    # and egress dominates; give b 1TB of inputs and verify the DP
    # includes egress in the comparison by checking totals are computed.
    b.set_resources(sky.Resources())
    d = _dag(a, b)
    d.add_edge(a, b)
    b.estimated_inputs_gigabytes = 1024
    Optimizer.optimize(d, quiet=True)
    assert a.best_resources is not None and b.best_resources is not None
    # Local (free) still wins unless egress is charged; gcp→local egress
    # = 0.12*1024 ≈ $123/h-equivalent > any VM, so b lands on gcp.
    assert str(b.best_resources.cloud) in ('GCP', 'Local')
    total_a = a.estimated_cost
    assert total_a > 0


def test_multi_cloud_zero_cost_wins(all_clouds):
    task = sky.Task(run='true')
    task.set_resources(sky.Resources())  # any cloud
    Optimizer.optimize(_dag(task), quiet=True)
    # Local/SSH are free; a free cloud must win over GCP VMs.
    assert task.best_resources.get_hourly_cost() == 0.0


def test_diamond_dag_joint_optimum_beats_greedy(all_clouds):
    """A→{B,C}→D diamond where per-task greedy picks the free cloud but
    egress makes co-location strictly cheaper — the exact solver
    (variable elimination; reference solves this with ILP,
    sky/optimizer.py:490) must pick the joint optimum."""
    a = sky.Task(name='a', run='true')
    a.set_resources(sky.Resources(cloud='gcp', accelerators='tpu-v5e-8'))
    d = sky.Task(name='d', run='true')
    d.set_resources(sky.Resources(cloud='gcp', accelerators='tpu-v5e-8'))
    b = sky.Task(name='b', run='true')
    b.set_resources(sky.Resources())  # any cloud: Local is free
    c = sky.Task(name='c', run='true')
    c.set_resources(sky.Resources())
    for t in (b, c, d):
        t.estimated_inputs_gigabytes = 1024  # egress off-gcp is ~$123

    g = _dag(a, b, c, d)
    g.add_edge(a, b)
    g.add_edge(a, c)
    g.add_edge(b, d)
    g.add_edge(c, d)
    Optimizer.optimize(g, quiet=True)

    # Greedy would put b/c on the free Local cloud; the gcp→Local egress
    # (2 x $123) dwarfs a small GCP VM, so the joint optimum keeps the
    # whole diamond on GCP.
    assert str(b.best_resources.cloud) == 'GCP'
    assert str(c.best_resources.cloud) == 'GCP'


def test_time_objective_picks_faster_hardware(all_clouds):
    """minimize=TIME ranks by estimated runtime; COST still by dollars
    (ADVICE round 1: TIME must not be a silent no-op)."""
    from skypilot_tpu.optimizer import OptimizeTarget

    def make_task():
        t = sky.Task(run='true')
        t.set_resources(sky.Resources.from_yaml_config({
            'cloud': 'gcp',
            'any_of': [{'accelerators': 'tpu-v5p-64'},
                       {'accelerators': 'tpu-v5e-64'}],
        }))
        # v5p (faster chips) finishes in 3000s; v5e needs 3600s.
        t.set_time_estimator(
            lambda r: 3000.0 if 'v5p' in (r.tpu_accelerator_name or '')
            else 3600.0)
        return t

    cost_task = make_task()
    Optimizer.optimize(_dag(cost_task), quiet=True)
    # $: v5p 134.4/hr * 3000s = 112 > v5e 76.8/hr * 3600s = 76.8.
    assert cost_task.best_resources.tpu_accelerator_name == 'tpu-v5e-64'

    time_task = make_task()
    Optimizer.optimize(_dag(time_task), minimize=OptimizeTarget.TIME,
                       quiet=True)
    assert time_task.best_resources.tpu_accelerator_name == 'tpu-v5p-64'


def test_variable_elimination_matches_brute_force():
    """Fuzz the exact solver: on random small DAGs with random costs
    and pairwise egress, min-sum variable elimination must equal
    exhaustive enumeration (the property the reference buys with CBC
    ILP, sky/optimizer.py:490)."""
    import itertools
    import random

    from skypilot_tpu import dag as dag_lib
    from skypilot_tpu import task as task_lib
    from skypilot_tpu.optimizer import Optimizer, OptimizeTarget

    rng = random.Random(42)
    for trial in range(40):
        n = rng.randint(1, 6)
        tasks = [task_lib.Task(name=f't{i}', run='x') for i in range(n)]
        g = dag_lib.Dag()
        for t in tasks:
            g.add(t)
        for i in range(n):
            for j in range(i + 1, n):
                if rng.random() < 0.45:
                    g.add_edge(tasks[i], tasks[j])

        per_task = {}
        for t in tasks:
            k = rng.randint(1, 4)
            per_task[t] = [(f'cand-{t.name}-{c}',
                            round(rng.uniform(0, 10), 3),
                            round(rng.uniform(0, 10), 3))
                           for c in range(k)]
        # Random pairwise egress per edge x cand pair. Candidate names
        # are globally unique, so (src_name, dst_name) keys an edge
        # entry unambiguously.
        edge_cost = {}
        by_name = {}
        for u, v in g.graph.edges:
            for ui, ucand in enumerate(per_task[u]):
                for vi, vcand in enumerate(per_task[v]):
                    c = (round(rng.uniform(0, 5), 3)
                         if rng.random() < 0.6 else 0.0)
                    edge_cost[(u, v, ui, vi)] = c
                    by_name[(ucand[0], vcand[0])] = c

        def fake_egress(src, dst, task, use_time, _lookup=by_name):
            return _lookup.get((src, dst), 0.0)

        class _Opt(Optimizer):
            _egress = staticmethod(fake_egress)

        choice = _Opt._optimize_exact(
            g, {t: list(c) for t, c in per_task.items()},
            OptimizeTarget.COST)

        # Brute force over the full joint assignment space.
        best = None
        tlist = list(tasks)
        for assign in itertools.product(
                *(range(len(per_task[t])) for t in tlist)):
            idx = dict(zip(tlist, assign))
            total = sum(per_task[t][idx[t]][1] for t in tlist)
            for u, v in g.graph.edges:
                total += edge_cost.get((u, v, idx[u], idx[v]), 0.0)
            if best is None or total < best:
                best = total

        got = sum(choice[t][1] for t in tlist)
        for u, v in g.graph.edges:
            got += by_name.get((choice[u][0], choice[v][0]), 0.0)
        assert abs(got - best) < 1e-6, (trial, got, best)


def test_vm_cross_region_pricing(all_clouds):
    """With the multi-region VM catalog, an unpinned request prices at
    the cheapest region; pinning a pricier region costs more."""
    free = sky.Task(run='true')
    free.set_resources(sky.Resources(cloud='gcp',
                                     instance_type='n2-standard-8'))
    pinned = sky.Task(run='true')
    pinned.set_resources(sky.Resources(cloud='gcp',
                                       instance_type='n2-standard-8',
                                       region='asia-northeast1'))
    Optimizer.optimize(_dag(free), quiet=True)
    Optimizer.optimize(_dag(pinned), quiet=True)
    assert free.best_resources.get_hourly_cost() == pytest.approx(0.388)
    assert pinned.best_resources.get_hourly_cost() == pytest.approx(0.5005)
    assert (pinned.best_resources.get_hourly_cost() >
            free.best_resources.get_hourly_cost())


def test_group_joint_placement_same_infra(all_clouds):
    """One placement decision per group (reference: sky/optimizer.py
    :1037 SAME_INFRA): members land on ONE common cloud+region, chosen
    to minimize the group SUM, honoring per-member region pins."""
    from skypilot_tpu.optimizer import Optimizer as Opt

    # Unpinned members: joint choice is the cheapest common region.
    a = sky.Task(name='a', run='true')
    a.set_resources(sky.Resources(cloud='gcp',
                                  instance_type='n2-standard-8'))
    b = sky.Task(name='b', run='true')
    b.set_resources(sky.Resources(cloud='gcp',
                                  instance_type='e2-standard-8'))
    infra = Opt.optimize_group([a, b], quiet=True)
    assert infra == ('gcp', 'us-central1')
    assert a.best_resources.region == 'us-central1'
    assert b.best_resources.region == 'us-central1'

    # One member pinned to a pricier region drags the whole group
    # there (SAME_INFRA beats per-member cheapest).
    c = sky.Task(name='c', run='true')
    c.set_resources(sky.Resources(cloud='gcp',
                                  instance_type='n2-standard-8',
                                  region='asia-northeast1'))
    d = sky.Task(name='d', run='true')
    d.set_resources(sky.Resources(cloud='gcp',
                                  instance_type='e2-standard-8'))
    infra = Opt.optimize_group([c, d], quiet=True)
    assert infra == ('gcp', 'asia-northeast1')
    assert d.best_resources.region == 'asia-northeast1'


def test_group_no_common_infra_returns_none(all_clouds):
    from skypilot_tpu.optimizer import Optimizer as Opt
    a = sky.Task(name='a', run='true')
    a.set_resources(sky.Resources(cloud='gcp',
                                  instance_type='n2-standard-8'))
    b = sky.Task(name='b', run='true')
    b.set_resources(sky.Resources(infra='local'))
    assert Opt.optimize_group([a, b], quiet=True) is None
