"""AWS cloud class + catalog: feasibility, pricing, failover iteration."""
import pytest

from skypilot_tpu import resources as resources_lib
from skypilot_tpu.catalog import aws_catalog
from skypilot_tpu.clouds import AWS


@pytest.fixture()
def aws():
    return AWS()


def test_accelerator_to_instance_type(aws):
    r = resources_lib.Resources(accelerators='A100:8')
    feas = aws.get_feasible_launchable_resources(r)
    assert [x.instance_type for x in feas.resources_list] == \
        ['p4d.24xlarge']


def test_cpu_default_instance_type(aws):
    r = resources_lib.Resources(cpus='8+')
    feas = aws.get_feasible_launchable_resources(r)
    assert len(feas.resources_list) == 1
    it = feas.resources_list[0].instance_type
    vcpus, _ = aws_catalog.get_vcpus_mem_from_instance_type(it)
    assert vcpus >= 8


def test_tpu_request_infeasible_with_fuzzy_none(aws):
    r = resources_lib.Resources(accelerators='tpu-v5e-8')
    feas = aws.get_feasible_launchable_resources(r)
    assert feas.resources_list == []


def test_unknown_gpu_gives_fuzzy_candidates(aws):
    r = resources_lib.Resources(accelerators='A100:3')
    feas = aws.get_feasible_launchable_resources(r)
    assert feas.resources_list == []
    assert any('A100' in c for c in feas.fuzzy_candidate_list)


def test_hourly_cost_spot_cheaper(aws):
    r = resources_lib.Resources(accelerators='A100:8').copy(
        cloud=aws, instance_type='p4d.24xlarge')
    on_demand = aws.get_hourly_cost(r)
    spot = aws.get_hourly_cost(r.copy(use_spot=True))
    assert 0 < spot < on_demand


def test_regions_with_offering_gpu(aws):
    regions = AWS.regions_with_offering('p4d.24xlarge', {'A100': 8},
                                        False, None, None)
    names = [r.name for r in regions]
    assert 'us-east-1' in names and 'us-west-2' in names
    # H100 is narrower:
    h100 = AWS.regions_with_offering('p5.48xlarge', {'H100': 8},
                                     False, None, None)
    assert {r.name for r in h100} == {'us-east-1', 'us-west-2'}


def test_zones_provision_loop(aws):
    batches = list(AWS.zones_provision_loop(
        region='us-east-1', num_nodes=1, instance_type='p4d.24xlarge',
        accelerators={'A100': 8}, use_spot=False))
    assert batches and batches[0][0].name == 'us-east-1a'


def test_deploy_variables(aws):
    from skypilot_tpu.clouds import cloud as cloud_lib
    r = resources_lib.Resources(accelerators='A100:8').copy(
        cloud=aws, instance_type='p4d.24xlarge')
    vars_ = aws.make_deploy_resources_variables(
        r, 'c-on-cloud', cloud_lib.Region('us-east-1'),
        [cloud_lib.Zone('us-east-1a')], 2)
    assert vars_['instance_type'] == 'p4d.24xlarge'
    assert vars_['region'] == 'us-east-1'
    assert vars_['zone'] == 'us-east-1a'
    assert vars_['num_nodes'] == 2
    assert vars_['tpu_vm'] is False


def test_egress_tiers(aws):
    assert aws.get_egress_cost(0) == 0.0
    assert aws.get_egress_cost(100) == pytest.approx(9.0)
    assert aws.get_egress_cost(20480) == pytest.approx(
        0.09 * 10240 + 0.085 * 10240)


def test_validate_region_zone():
    aws_catalog.validate_region_zone('us-east-1', 'us-east-1a')
    with pytest.raises(ValueError):
        aws_catalog.validate_region_zone('mars-central-1', None)


def test_trainium_listed():
    accs = aws_catalog.list_accelerators(name_filter='Trainium')
    assert 'Trainium' in accs
    assert accs['Trainium'][0].instance_type == 'trn1.32xlarge'
