"""OIDC verification (server) + OAuth login flow (client).

Reference analog: sky/server/auth tests — JWT validation paths, and
the PKCE code flow driven against a fake in-process IdP.
"""
import base64
import hashlib
import http.server
import json
import threading
import time
import urllib.parse

import pytest

from skypilot_tpu import sky_config
from skypilot_tpu.users import oidc


@pytest.fixture()
def oauth_config(isolated_state):  # pylint: disable=unused-argument
    cfg = {'oauth': {'issuer': 'https://idp.test',
                     'client_id': 'stpu-cli',
                     'hs256_secret': 'topsecret',
                     'admin_users': ['root@test']}}
    with sky_config.override(cfg):
        yield cfg


def _claims(**over):
    out = {'iss': 'https://idp.test', 'aud': 'stpu-cli',
           'email': 'alice@test', 'exp': time.time() + 600}
    out.update(over)
    return out


def test_hs256_roundtrip(oauth_config):
    token = oidc.make_hs256_jwt(_claims(), 'topsecret')
    assert oidc.looks_like_jwt(token)
    ident = oidc.verify_jwt(token)
    assert ident == {'user': 'alice@test', 'role': 'user'}


def test_admin_mapping(oauth_config):
    token = oidc.make_hs256_jwt(_claims(email='root@test'), 'topsecret')
    assert oidc.verify_jwt(token)['role'] == 'admin'


def test_wrong_secret_rejected(oauth_config):
    token = oidc.make_hs256_jwt(_claims(), 'not-the-secret')
    assert oidc.verify_jwt(token) is None


def test_expired_rejected(oauth_config):
    token = oidc.make_hs256_jwt(_claims(exp=time.time() - 10),
                                'topsecret')
    assert oidc.verify_jwt(token) is None


def test_wrong_issuer_and_audience_rejected(oauth_config):
    bad_iss = oidc.make_hs256_jwt(_claims(iss='https://evil.test'),
                                  'topsecret')
    assert oidc.verify_jwt(bad_iss) is None
    bad_aud = oidc.make_hs256_jwt(_claims(aud='other-app'), 'topsecret')
    assert oidc.verify_jwt(bad_aud) is None


def test_tampered_payload_rejected(oauth_config):
    token = oidc.make_hs256_jwt(_claims(), 'topsecret')
    header, payload, sig = token.split('.')
    forged = json.loads(
        base64.urlsafe_b64decode(payload + '=' * (-len(payload) % 4)))
    forged['email'] = 'root@test'
    payload2 = base64.urlsafe_b64encode(
        json.dumps(forged).encode()).decode().rstrip('=')
    assert oidc.verify_jwt(f'{header}.{payload2}.{sig}') is None


def test_rs256_without_cryptography_fails_closed(isolated_state,
                                                 monkeypatch):
    """No `cryptography` installed → an RS256 bearer is REJECTED
    (None), never an ImportError escaping into the request path."""
    monkeypatch.setattr(oidc, '_require_cryptography', lambda: False)
    header = base64.urlsafe_b64encode(json.dumps(
        {'alg': 'RS256', 'kid': 'k1'}).encode()).decode().rstrip('=')
    payload = base64.urlsafe_b64encode(json.dumps(
        _claims()).encode()).decode().rstrip('=')
    sig = base64.urlsafe_b64encode(b'not-a-signature')\
        .decode().rstrip('=')
    with sky_config.override({'oauth': {'issuer': 'https://idp.test',
                                        'client_id': 'stpu-cli',
                                        'jwks': {'keys': []}}}):
        assert oidc.verify_jwt(f'{header}.{payload}.{sig}') is None


def test_rs256_roundtrip(isolated_state):
    # `cryptography` is an OPTIONAL dependency (users/oidc.py fails
    # RS256 closed without it); environments without it skip rather
    # than fail.
    pytest.importorskip('cryptography')
    from cryptography.hazmat.primitives.asymmetric import padding, rsa
    from cryptography.hazmat.primitives import hashes
    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    pub = key.public_key().public_numbers()

    def b64url_uint(n):
        raw = n.to_bytes((n.bit_length() + 7) // 8, 'big')
        return base64.urlsafe_b64encode(raw).decode().rstrip('=')

    jwks = {'keys': [{'kty': 'RSA', 'kid': 'k1', 'alg': 'RS256',
                      'n': b64url_uint(pub.n), 'e': b64url_uint(pub.e)}]}
    header = base64.urlsafe_b64encode(json.dumps(
        {'alg': 'RS256', 'kid': 'k1'}).encode()).decode().rstrip('=')
    payload = base64.urlsafe_b64encode(json.dumps(
        _claims()).encode()).decode().rstrip('=')
    sig = key.sign(f'{header}.{payload}'.encode(), padding.PKCS1v15(),
                   hashes.SHA256())
    sig_b64 = base64.urlsafe_b64encode(sig).decode().rstrip('=')
    token = f'{header}.{payload}.{sig_b64}'

    with sky_config.override({'oauth': {'issuer': 'https://idp.test',
                                        'client_id': 'stpu-cli',
                                        'jwks': jwks}}):
        ident = oidc.verify_jwt(token)
        assert ident == {'user': 'alice@test', 'role': 'user'}
        # Flipping one signature byte must fail.
        bad = sig_b64[:-2] + ('AA' if not sig_b64.endswith('AA') else 'BB')
        assert oidc.verify_jwt(f'{header}.{payload}.{bad}') is None


# ---------------------------------------------------------------------------
# Client PKCE flow against a fake IdP
# ---------------------------------------------------------------------------
class FakeIdp(http.server.BaseHTTPRequestHandler):
    issued_code = 'authcode-123'
    seen_verifier = None
    refresh_count = 0

    def _json(self, obj, status=200):
        body = json.dumps(obj).encode()
        self.send_response(status)
        self.send_header('Content-Type', 'application/json')
        self.send_header('Content-Length', str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802
        base = f'http://127.0.0.1:{self.server.server_address[1]}'
        if self.path == '/.well-known/openid-configuration':
            self._json({
                'issuer': base,
                'authorization_endpoint': f'{base}/authorize',
                'token_endpoint': f'{base}/token',
            })
        else:
            self._json({'error': 'not found'}, 404)

    def do_POST(self):  # noqa: N802
        length = int(self.headers.get('Content-Length', 0))
        form = urllib.parse.parse_qs(self.rfile.read(length).decode())
        cls = type(self)
        if self.path == '/token':
            grant = form.get('grant_type', [''])[0]
            if grant == 'authorization_code':
                if form.get('code', [''])[0] != cls.issued_code:
                    self._json({'error': 'invalid_grant'}, 400)
                    return
                cls.seen_verifier = form.get('code_verifier', [''])[0]
                self._json({'access_token': 'at-1', 'id_token': 'h.i.d',
                            'refresh_token': 'rt-1', 'expires_in': 3600})
            elif grant == 'refresh_token':
                cls.refresh_count += 1
                self._json({'access_token': f'at-{1 + cls.refresh_count}',
                            'id_token': 'h.i.d2', 'expires_in': 3600})
            else:
                self._json({'error': 'unsupported_grant_type'}, 400)
        else:
            self._json({'error': 'not found'}, 404)

    def log_message(self, *args):
        del args


@pytest.fixture()
def fake_idp():
    server = http.server.HTTPServer(('127.0.0.1', 0), FakeIdp)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    yield f'http://127.0.0.1:{server.server_address[1]}'
    server.shutdown()
    thread.join(timeout=5)


def test_pkce_login_flow(isolated_state, fake_idp, monkeypatch):
    from skypilot_tpu.client import oauth as oauth_client
    FakeIdp.seen_verifier = None

    def fake_browser(url):
        """Play the IdP's role: redirect straight back with a code."""
        q = urllib.parse.parse_qs(urllib.parse.urlparse(url).query)
        assert q['code_challenge_method'] == ['S256']
        redirect = q['redirect_uri'][0]
        import requests as _requests
        _requests.get(redirect, params={
            'code': FakeIdp.issued_code, 'state': q['state'][0]},
            timeout=10)
        return True

    monkeypatch.setattr('webbrowser.open', fake_browser)
    tokens = oauth_client.login(issuer=fake_idp, client_id='stpu-cli',
                                timeout=30)
    assert tokens['access_token'] == 'at-1'
    # The token exchange proved possession of the PKCE verifier.
    assert FakeIdp.seen_verifier
    challenge = base64.urlsafe_b64encode(hashlib.sha256(
        FakeIdp.seen_verifier.encode()).digest()).decode().rstrip('=')
    assert challenge  # S256(verifier) was sent in the authorize URL
    # Cached token is served without refresh while fresh.
    assert oauth_client.get_access_token() == 'h.i.d'


def test_token_refresh(isolated_state, fake_idp):
    from skypilot_tpu.client import oauth as oauth_client
    FakeIdp.refresh_count = 0
    oauth_client._save_tokens({
        'access_token': 'stale', 'id_token': 'stale.i.d',
        'refresh_token': 'rt-1', 'issuer': fake_idp,
        'client_id': 'stpu-cli', 'expires_at': time.time() - 10})
    token = oauth_client.get_access_token()
    assert token == 'h.i.d2'
    assert FakeIdp.refresh_count == 1


def test_state_mismatch_rejected(isolated_state, fake_idp, monkeypatch):
    from skypilot_tpu import exceptions
    from skypilot_tpu.client import oauth as oauth_client

    def evil_browser(url):
        q = urllib.parse.parse_qs(urllib.parse.urlparse(url).query)
        import requests as _requests
        _requests.get(q['redirect_uri'][0], params={
            'code': 'stolen', 'state': 'wrong-state'}, timeout=10)
        return True

    monkeypatch.setattr('webbrowser.open', evil_browser)
    with pytest.raises(exceptions.SkyError):
        oauth_client.login(issuer=fake_idp, client_id='stpu-cli',
                           timeout=10)


def test_missing_exp_rejected(oauth_config):
    claims = _claims()
    del claims['exp']
    token = oidc.make_hs256_jwt(claims, 'topsecret')
    assert oidc.verify_jwt(token) is None


def test_stray_request_does_not_fail_login(isolated_state, fake_idp,
                                           monkeypatch):
    """A favicon fetch hitting the callback server must not poison the
    flow with a state-mismatch error."""
    from skypilot_tpu.client import oauth as oauth_client

    def browser_with_favicon(url):
        q = urllib.parse.parse_qs(urllib.parse.urlparse(url).query)
        redirect = q['redirect_uri'][0]
        base = redirect.rsplit('/', 1)[0]
        import requests as _requests
        _requests.get(f'{base}/favicon.ico', timeout=10)
        _requests.get(redirect, params={
            'code': FakeIdp.issued_code, 'state': q['state'][0]},
            timeout=10)
        return True

    monkeypatch.setattr('webbrowser.open', browser_with_favicon)
    tokens = oauth_client.login(issuer=fake_idp, client_id='stpu-cli',
                                timeout=30)
    assert tokens['access_token'] == 'at-1'


def test_refresh_failure_backoff(isolated_state, monkeypatch):
    """An unreachable IdP must not add timeouts to every call."""
    import requests as _requests
    from skypilot_tpu.client import oauth as oauth_client
    oauth_client._refresh_failed_at = 0.0
    oauth_client._save_tokens({
        'access_token': 'stale', 'refresh_token': 'rt',
        'issuer': 'http://127.0.0.1:1', 'client_id': 'x',
        'expires_at': time.time() - 10})
    calls = []

    def failing_get(*a, **k):
        calls.append(1)
        raise _requests.ConnectionError('down')

    monkeypatch.setattr(_requests, 'get', failing_get)
    assert oauth_client.get_access_token() is None
    assert oauth_client.get_access_token() is None  # backoff: no retry
    assert len(calls) == 1
    oauth_client._refresh_failed_at = 0.0


def test_rs256_key_rotation_no_kid(isolated_state):
    """Token signed with the NEWER key, no kid header, JWKS holding
    [old, new] — must verify against every candidate key."""
    pytest.importorskip('cryptography')
    from cryptography.hazmat.primitives.asymmetric import padding, rsa
    from cryptography.hazmat.primitives import hashes

    def b64url_uint(n):
        raw = n.to_bytes((n.bit_length() + 7) // 8, 'big')
        return base64.urlsafe_b64encode(raw).decode().rstrip('=')

    old_key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    new_key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    jwks = {'keys': []}
    for kid, key in (('old', old_key), ('new', new_key)):
        pub = key.public_key().public_numbers()
        jwks['keys'].append({'kty': 'RSA', 'kid': kid,
                             'n': b64url_uint(pub.n),
                             'e': b64url_uint(pub.e)})
    header = base64.urlsafe_b64encode(
        json.dumps({'alg': 'RS256'}).encode()).decode().rstrip('=')
    payload = base64.urlsafe_b64encode(json.dumps(
        _claims()).encode()).decode().rstrip('=')
    sig = new_key.sign(f'{header}.{payload}'.encode(), padding.PKCS1v15(),
                       hashes.SHA256())
    token = (f'{header}.{payload}.'
             f'{base64.urlsafe_b64encode(sig).decode().rstrip("=")}')
    with sky_config.override({'oauth': {'issuer': 'https://idp.test',
                                        'client_id': 'stpu-cli',
                                        'jwks': jwks}}):
        assert oidc.verify_jwt(token) == {'user': 'alice@test',
                                          'role': 'user'}


def test_refresh_drops_stale_id_token(isolated_state, fake_idp,
                                      monkeypatch):
    """A refresh response without id_token must not leave the old
    (expired) id_token looking fresh."""
    import requests as _requests
    from skypilot_tpu.client import oauth as oauth_client
    oauth_client._refresh_failed_at = 0.0
    oauth_client._save_tokens({
        'access_token': 'stale-at', 'id_token': 'stale.id.tok',
        'refresh_token': 'rt-1', 'issuer': fake_idp,
        'client_id': 'stpu-cli', 'expires_at': time.time() - 10})

    real_post = _requests.post

    def no_id_token_post(url, **kw):
        resp = real_post(url, **kw)
        if kw.get('data', {}).get('grant_type') == 'refresh_token':
            body = resp.json()
            body.pop('id_token', None)
            resp.json = lambda: body
        return resp

    monkeypatch.setattr(_requests, 'post', no_id_token_post)
    token = oauth_client.get_access_token()
    assert token is not None and token != 'stale.id.tok'
