"""Disaggregated prefill/decode + tiered prefix cache:

  - kv_transfer wire format: pack/unpack round trips are
    bit-identical (bf16/f32 AND int8-with-scales — payload travels
    in its storage dtype), malformed payloads raise;
  - engine export_chain/import_chain: a chain imported into another
    engine's pool serves the same prompt with bit-identical greedy
    output and full prefix-cache hits; adapter-salted chains never
    leak across tenants;
  - spill tier: pool-pressure evictions spill exact page bytes to
    host RAM (and a cold dir behind it), a later chain-key hit
    restores them, and the restored continuation equals the fresh
    compute bit for bit;
  - HTTP handoff: a prefill-role server whose transfer fails (fault
    injection or a dead decode peer) falls back to serving locally
    — the client always gets the same tokens, never an error;
  - the disaggregated stub fleet: long prompts route to the prefill
    pool, chains hand off to decode stubs, and killing the prefill
    replica mid-run degrades to decode-pool routing with zero 5xx.
"""
import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import flax.linen as nn

from skypilot_tpu.inference import kv_transfer
from skypilot_tpu.models.batching import ContinuousBatchingEngine

SYS_PROMPT = list(range(2, 34))    # 32 tokens = 4 full 8-token pages


def _build(kv_dtype='bf16', total_pages=24):
    from skypilot_tpu.models.llama import Llama, LlamaConfig
    cfg = LlamaConfig.tiny(dtype=jnp.float32, kv_page_size=8,
                           kv_total_pages=total_pages,
                           kv_dtype=kv_dtype)
    model = Llama(cfg)
    params = nn.meta.unbox(model.init(
        jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))['params'])
    return model, params


def _engine(model, params, **kw):
    kw.setdefault('num_slots', 2)
    kw.setdefault('max_total_len', 96)
    return ContinuousBatchingEngine(model, params, **kw)


def _wire_payload(data: bytes) -> bytes:
    off = len(kv_transfer.MAGIC)
    hlen = int.from_bytes(data[off:off + 8], 'big')
    return data[off + 8 + hlen:]


# ---------------------------------------------------------------------------
# Wire format
# ---------------------------------------------------------------------------
def test_pack_unpack_roundtrip_all_dtypes():
    import ml_dtypes
    blobs = {
        'k_pages': np.arange(24, dtype=np.float32).reshape(2, 3, 4),
        'q8': (np.arange(24, dtype=np.int8) - 12).reshape(2, 3, 4),
        'scales': np.linspace(0, 1, 6,
                              dtype=np.float32).reshape(2, 3),
        'bf16': np.arange(6, dtype=ml_dtypes.bfloat16).reshape(2, 3),
    }
    meta = {'kind': 'kv_chain', 'kv_dtype': 'int8', 'page_size': 8,
            'keys': ['ab' * 32, 'cd' * 32], 'salt': ''}
    data = kv_transfer.pack_pages(blobs, meta)
    meta2, blobs2 = kv_transfer.unpack_pages(data)
    assert meta2['kv_dtype'] == 'int8'
    assert meta2['n_pages'] == 2
    assert meta2['keys'] == meta['keys']
    for path, arr in blobs.items():
        assert blobs2[path].dtype == arr.dtype
        assert blobs2[path].tobytes() == arr.tobytes()
    # split/join round trip (the spill tier's per-page unit).
    pages = kv_transfer.split_pages(blobs2, 2)
    joined = kv_transfer.join_pages(pages)
    for path, arr in blobs.items():
        assert joined[path].tobytes() == arr.tobytes()


def test_unpack_rejects_garbage():
    with pytest.raises(ValueError):
        kv_transfer.unpack_pages(b'not a chain')
    blobs = {'x': np.zeros((1, 2), np.float32)}
    data = kv_transfer.pack_pages(blobs, {'kind': 'kv_chain'})
    with pytest.raises(ValueError):
        kv_transfer.unpack_pages(data[:-3])     # truncated payload
    with pytest.raises(ValueError):
        kv_transfer.unpack_pages(data + b'xx')  # trailing junk
    with pytest.raises(ValueError):
        # Mismatched page counts across leaves.
        kv_transfer.pack_pages(
            {'a': np.zeros((2, 2), np.float32),
             'b': np.zeros((3, 2), np.float32)}, {})


# ---------------------------------------------------------------------------
# Engine export/import
# ---------------------------------------------------------------------------
@pytest.mark.parametrize('kv_dtype', ['bf16', 'int8'])
def test_export_import_bit_identical(kv_dtype):
    """The tentpole contract: export -> import -> serve is
    bit-identical to serving locally, in BOTH storage formats (int8
    pages travel as int8 with their scales)."""
    model, params = _build(kv_dtype)
    prompt = SYS_PROMPT + [40, 41]
    src = _engine(model, params)
    dst = _engine(model, params)
    try:
        ref = src.submit(prompt, max_new_tokens=8).result(timeout=180)
        data = src.export_chain(prompt)
        assert data is not None
        meta, blobs = kv_transfer.unpack_pages(data)
        assert meta['kv_dtype'] == kv_dtype
        assert meta['n_pages'] == 4
        if kv_dtype == 'int8':
            assert any('k_scales' in p for p in blobs)
            assert any(b.dtype == np.int8 for b in blobs.values())
        summary = dst.import_chain(data)
        assert summary == {'pages': 4, 'imported': 4,
                           'already_cached': 0, 'dropped': 0}
        h0, m0 = dst.prefix_cache.hits, dst.prefix_cache.misses
        out = dst.submit(prompt, max_new_tokens=8).result(timeout=180)
        assert out == ref
        # Every full prompt page was served from the imported chain.
        assert dst.prefix_cache.hits - h0 == 4
        assert dst.prefix_cache.misses == m0
        # Round trip is bit-identical: re-exporting from the importer
        # yields the same payload bytes.
        data2 = dst.export_chain(prompt)
        assert _wire_payload(data2) == _wire_payload(data)
    finally:
        src.stop()
        dst.stop()


def test_import_rejects_mismatched_geometry():
    model, params = _build('bf16')
    model8, params8 = _build('int8')
    src = _engine(model, params)
    dst = _engine(model8, params8)
    try:
        src.submit(SYS_PROMPT, max_new_tokens=2).result(timeout=180)
        data = src.export_chain(SYS_PROMPT)
        with pytest.raises(ValueError, match='kv_dtype mismatch'):
            dst.import_chain(data)
        with pytest.raises(ValueError):
            dst.import_chain(b'garbage')
    finally:
        src.stop()
        dst.stop()


def test_adapter_salted_chains_stay_isolated(tmp_path):
    """An adapter's exported chain imports under its salted keys:
    the same prompt served WITHOUT the adapter gets zero hits (and
    vice versa) — handoff cannot leak one tenant's KV to another."""
    from skypilot_tpu.inference.adapters import AdapterRegistry
    from skypilot_tpu.models import lora as lora_lib
    model, params = _build()
    spec = lora_lib.LoraSpec(rank=4, alpha=8.0)
    ad_params = lora_lib.random_adapter_params(7, model.config, spec)
    lora_lib.save_adapter(str(tmp_path / 'ten_a'), ad_params, spec,
                          base_model='llama-tiny')
    prompt = SYS_PROMPT + [40]
    src_reg = AdapterRegistry(str(tmp_path), model, max_adapters=2)
    dst_reg = AdapterRegistry(str(tmp_path), model, max_adapters=2)
    src = _engine(model, params, adapter_store=src_reg)
    dst = _engine(model, params, adapter_store=dst_reg)
    try:
        ref = src.submit(prompt, max_new_tokens=6,
                         adapter='ten_a').result(timeout=180)
        data = src.export_chain(prompt, adapter='ten_a')
        assert data is not None
        meta, _ = kv_transfer.unpack_pages(data)
        assert meta['salt'] != ''
        assert dst.import_chain(data)['imported'] == 4
        # Base-model request: same prompt, different salt -> 0 hits.
        h0 = dst.prefix_cache.hits
        dst.submit(prompt, max_new_tokens=2).result(timeout=180)
        assert dst.prefix_cache.hits == h0
        # Same tenant: full hits, bit-identical output.
        h1 = dst.prefix_cache.hits
        out = dst.submit(prompt, max_new_tokens=6,
                         adapter='ten_a').result(timeout=180)
        assert out == ref
        assert dst.prefix_cache.hits - h1 >= 4
    finally:
        src.stop()
        dst.stop()


# ---------------------------------------------------------------------------
# Tiered cache: spill -> evict -> restore
# ---------------------------------------------------------------------------
@pytest.mark.parametrize('kv_dtype', ['bf16', 'int8'])
def test_spill_restore_bit_identical(kv_dtype):
    """Pool-pressure evictions spill; the next hit restores the
    exact bytes: greedy continuation == fresh compute, and the
    restore counts as prefix-cache hits (that is the hit-rate gain
    the spill-tier bench arm measures)."""
    model, params = _build(kv_dtype, total_pages=20)
    ref_eng = _engine(model, params)
    eng = _engine(model, params, kv_spill_bytes=64 << 20)
    prompt = SYS_PROMPT + [40, 41]
    try:
        ref = ref_eng.submit(prompt,
                             max_new_tokens=8).result(timeout=180)
        assert eng.submit(prompt,
                          max_new_tokens=8).result(timeout=180) == ref
        # Evict the cached chain with other (unshared) prompts.
        for i in range(4):
            eng.submit([100 + 7 * i + j for j in range(30)],
                       max_new_tokens=20).result(timeout=180)
        assert eng.prefix_cache.spilled_pages > 0
        assert eng.spill_tier.stats()['spilled_pages'] > 0
        h0, m0 = eng.prefix_cache.hits, eng.prefix_cache.misses
        out = eng.submit(prompt,
                         max_new_tokens=8).result(timeout=180)
        assert out == ref
        assert eng.kv_restored_pages > 0
        # Restored pages were recorded as HITS, not misses.
        assert eng.prefix_cache.hits - h0 >= eng.kv_restored_pages
        assert eng.kv_restore_hits > 0
        eng.update_metric_gauges()  # hit-ratio gauge renders
    finally:
        ref_eng.stop()
        eng.stop()


def test_cold_tier_restores_after_host_eviction(tmp_path):
    """Pages demoted from the tiny host tier land in the cold dir
    and still restore bit-identically (the giant-shared-system-
    prompt survival path)."""
    model, params = _build(total_pages=20)
    ref_eng = _engine(model, params)
    eng = _engine(model, params, kv_spill_bytes=1,
                  kv_cold_dir=str(tmp_path / 'cold'))
    prompt = SYS_PROMPT + [40, 41]
    try:
        ref = ref_eng.submit(prompt,
                             max_new_tokens=8).result(timeout=180)
        assert eng.submit(prompt,
                          max_new_tokens=8).result(timeout=180) == ref
        for i in range(5):
            eng.submit([200 + 11 * i + j for j in range(30)],
                       max_new_tokens=20).result(timeout=180)
        tier = eng.spill_tier.stats()
        assert tier['cold_demotions'] > 0
        assert tier['cold']['writes'] > 0
        out = eng.submit(prompt,
                         max_new_tokens=8).result(timeout=180)
        assert out == ref
        assert eng.kv_restored_pages > 0
    finally:
        ref_eng.stop()
        eng.stop()


def test_spill_requires_prefix_caching():
    model, params = _build()
    with pytest.raises(ValueError, match='spill'):
        _engine(model, params, prefix_caching=False,
                kv_spill_bytes=1 << 20)


# ---------------------------------------------------------------------------
# HTTP handoff: fallback + /kv endpoints
# ---------------------------------------------------------------------------
def _post(url, path, body, timeout=180):
    req = urllib.request.Request(
        url + path, data=json.dumps(body).encode(),
        headers={'Content-Type': 'application/json'})
    return urllib.request.urlopen(req, timeout=timeout)


@pytest.fixture()
def prefill_server():
    """A live prefill-role server with a dead decode peer: every
    handoff fails and must fall back to local serving."""
    from skypilot_tpu.inference.http_server import make_server
    from skypilot_tpu.inference.runtime import InferenceRuntime
    model, params = _build()
    engine = _engine(model, params, num_slots=2)
    rt = InferenceRuntime(
        model=model, params=params,
        vocab_size=model.config.vocab_size, model_name='llama-tiny',
        max_total_len=96, spec_total=96, speculative=0,
        engine=engine, request_timeout=120.0,
        role='prefill', decode_peers=['127.0.0.1:9'])  # discard port
    server = make_server(rt, 0)
    port = server.server_address[1]
    thread = threading.Thread(target=server.serve_forever,
                              daemon=True)
    thread.start()
    yield f'http://127.0.0.1:{port}', rt, engine
    try:
        server.shutdown()
    except Exception:  # pylint: disable=broad-except
        pass
    engine.stop()


def test_handoff_failure_falls_back_to_local(prefill_server):
    url, rt, engine = prefill_server
    prompt = SYS_PROMPT + [40, 41]
    # Reference from the engine directly (same process, same params).
    ref = engine.submit(list(prompt),
                        max_new_tokens=6).result(timeout=180)
    out = json.loads(_post(url, '/generate', {
        'tokens': [prompt], 'max_new_tokens': 6}).read())
    assert out['tokens'] == [ref]
    stats = json.loads(urllib.request.urlopen(
        url + '/stats', timeout=30).read())
    assert stats['role'] == 'prefill'
    assert stats['handoff']['handoffs'] >= 1
    assert stats['handoff']['failures'] >= 1


def test_injected_handoff_fault_falls_back(prefill_server):
    from skypilot_tpu.robustness import faults
    url, _rt, engine = prefill_server
    prompt = SYS_PROMPT + [50, 51]
    ref = engine.submit(list(prompt),
                        max_new_tokens=6).result(timeout=180)
    faults.install_plan({'rules': [
        {'point': 'kv.handoff', 'action': 'raise',
         'exc': 'RuntimeError', 'times': 1}]})
    try:
        out = json.loads(_post(url, '/generate', {
            'tokens': [prompt], 'max_new_tokens': 6}).read())
        assert out['tokens'] == [ref]
    finally:
        faults.clear()


def test_kv_import_endpoint_plain_and_embedded():
    """POST /kv/import with a bare payload registers the chain; with
    an embedded request it serves it immediately against the
    imported pages."""
    from skypilot_tpu.inference.http_server import make_server
    from skypilot_tpu.inference.runtime import InferenceRuntime
    import base64
    model, params = _build()
    src = _engine(model, params)
    engine = _engine(model, params, num_slots=2)
    rt = InferenceRuntime(
        model=model, params=params,
        vocab_size=model.config.vocab_size, model_name='llama-tiny',
        max_total_len=96, spec_total=96, speculative=0,
        engine=engine, request_timeout=120.0, role='decode')
    server = make_server(rt, 0)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever,
                     daemon=True).start()
    url = f'http://127.0.0.1:{port}'
    prompt = SYS_PROMPT + [60]
    try:
        ref = src.submit(prompt, max_new_tokens=6).result(timeout=180)
        data = src.export_chain(prompt)
        payload = base64.b64encode(data).decode()
        body = json.loads(_post(url, '/kv/import',
                                {'payload': payload}).read())
        assert body['imported']['imported'] == 4
        h0 = engine.prefix_cache.hits
        body = json.loads(_post(url, '/kv/import', {
            'payload': payload, 'path': '/generate',
            'request': {'tokens': [prompt],
                        'max_new_tokens': 6}}).read())
        assert body['tokens'] == [ref]
        assert engine.prefix_cache.hits - h0 == 4  # no re-prefill
        stats = json.loads(urllib.request.urlopen(
            url + '/stats', timeout=30).read())
        assert stats['handoff']['kv_imports'] == 2
        # Corrupt payload: a clean 400-class error, engine survives.
        with pytest.raises(urllib.error.HTTPError):
            _post(url, '/kv/import', {'payload': 'AAAA'})
        assert engine.healthy()
    finally:
        try:
            server.shutdown()
        except Exception:  # pylint: disable=broad-except
            pass
        src.stop()
        engine.stop()


# ---------------------------------------------------------------------------
# Disaggregated stub fleet: routing + chaos
# ---------------------------------------------------------------------------
def _stub_fleet(n_decode=2, n_prefill=1, threshold=64):
    from skypilot_tpu.serve import autoscalers
    from skypilot_tpu.serve import load_balancing_policies as lbp
    from skypilot_tpu.serve import service_spec as spec_lib
    from skypilot_tpu.serve.replica_plane import (FleetController,
                                                  PrefillPool,
                                                  ReplicaManager,
                                                  make_lb_server)
    from skypilot_tpu.serve.replica_plane.stub import \
        in_process_stub_factory
    factory = in_process_stub_factory(cache_pages=512,
                                      token_sleep_s=0.0)
    spec = spec_lib.SkyServiceSpec(min_replicas=n_decode,
                                   max_replicas=n_decode)
    pspec = spec_lib.SkyServiceSpec(min_replicas=n_prefill,
                                    max_replicas=n_prefill)
    policy = lbp.PrefixAffinityPolicy()
    pool = PrefillPool()
    manager = ReplicaManager(factory, drain_grace_s=5.0)
    controller = FleetController(
        manager, policy, autoscalers.EngineMetricsAutoscaler(spec),
        interval_s=0.2,
        prefill_autoscaler=autoscalers.EngineMetricsAutoscaler(pspec),
        prefill_pool=pool)
    lb = make_lb_server(policy, 0, policy_name='prefix_affinity',
                        manager=manager, disagg_threshold=threshold,
                        prefill_pool=pool)
    threading.Thread(target=lb.serve_forever, daemon=True).start()
    for _ in range(n_decode):
        manager.spawn(role='decode')
    for _ in range(n_prefill):
        manager.spawn(role='prefill')
    assert controller.wait_ready(n_decode + n_prefill, timeout_s=60)
    controller.tick()   # push roles + decode peers
    url = f'http://127.0.0.1:{lb.server_address[1]}'
    return url, controller, manager, lb


def test_disagg_stub_fleet_routes_and_hands_off():
    url, controller, manager, lb = _stub_fleet()
    try:
        long_prompt = list(range(2, 202))
        short_prompt = list(range(2, 20))
        for body in ({'tokens': [short_prompt], 'max_new_tokens': 4},
                     {'tokens': [long_prompt], 'max_new_tokens': 4}):
            assert _post(url, '/generate', body).status == 200
        prefill = [v for v in manager.views()
                   if v.role == 'prefill'][0]
        stats = json.loads(urllib.request.urlopen(
            f'http://{prefill.endpoint}/stats', timeout=10).read())
        assert stats['role'] == 'prefill'
        assert stats['handoff']['handoffs'] == 1
        assert stats['handoff']['failures'] == 0
        imports = 0
        for v in manager.views():
            if v.role == 'decode':
                s = json.loads(urllib.request.urlopen(
                    f'http://{v.endpoint}/stats', timeout=10).read())
                imports += s['handoff']['kv_imports']
        assert imports == 1
        # /fleet/status surfaces roles + the prefill pool.
        status = json.loads(urllib.request.urlopen(
            url + '/fleet/status', timeout=10).read())
        assert sorted(v['role'] for v in status['replicas']) == \
            ['decode', 'decode', 'prefill']
        assert len(status['disagg']['prefill_pool']) == 1
    finally:
        controller.shutdown()
        lb.shutdown()


def test_disagg_fleet_chaos_prefill_death_zero_5xx():
    """Kill the only prefill replica mid-run: long-prompt requests
    must complete via fallback (LB retry -> decode pool) with zero
    extra 5xx, and the controller replaces the dead replica."""
    url, controller, manager, lb = _stub_fleet()
    try:
        long_prompt = list(range(2, 202))
        assert _post(url, '/generate',
                     {'tokens': [long_prompt],
                      'max_new_tokens': 4}).status == 200
        prefill = [v for v in manager.views()
                   if v.role == 'prefill'][0]
        prefill.proc.die()   # abrupt crash, no drain
        # Every long-prompt request during and after the death still
        # answers 200: the LB excludes the dead prefill endpoint on
        # connection failure and falls back to the decode pool.
        for _ in range(4):
            assert _post(url, '/generate',
                         {'tokens': [long_prompt],
                          'max_new_tokens': 4}).status == 200
        # The controller notices and replaces it in the prefill pool.
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            controller.tick()
            live_prefill = [
                v for v in manager.views()
                if v.role == 'prefill' and v.ready]
            if live_prefill and \
                    live_prefill[0].replica_id != prefill.replica_id:
                break
            time.sleep(0.1)
        else:
            pytest.fail('prefill replica was not replaced')
        assert _post(url, '/generate',
                     {'tokens': [long_prompt],
                      'max_new_tokens': 4}).status == 200
    finally:
        controller.shutdown()
        lb.shutdown()


# ---------------------------------------------------------------------------
# Satellites: percentiles, scrape fields, catalog rows
# ---------------------------------------------------------------------------
def test_interpolated_percentiles_distinct_at_small_n():
    import importlib.util
    import os
    spec = importlib.util.spec_from_file_location(
        'serve_bench', os.path.join(
            os.path.dirname(__file__), '..', '..', 'benchmarks',
            'serve_bench.py'))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    # 60 samples (the BENCH_lora_r10 regime): nearest-rank made p95
    # and p99 the SAME sample; interpolation keeps them distinct.
    vals = sorted((i + 1) / 1000.0 for i in range(60))
    p95 = bench.pct_ms(vals, 0.95)
    p99 = bench.pct_ms(vals, 0.99)
    assert p95 != p99
    assert p95 == pytest.approx(57.05, abs=0.01)
    assert p99 == pytest.approx(59.41, abs=0.01)
    assert bench.pct_ms([], 0.99) is None

    from skypilot_tpu.inference.runtime import ServingMetrics
    m = ServingMetrics()
    for i in range(60):
        m.record(latency_s=(i + 1) / 1000.0, n_tokens=1,
                 ttft_s=(i + 1) / 1000.0)
    snap = m.snapshot()
    assert snap['ttft_ms_p95'] != snap['ttft_ms_p99']
    assert snap['ttft_ms_n'] == 60
    assert snap['latency_ms_n'] == 60
    assert snap['itl_ms_n'] == 0


def test_replica_view_scrapes_role_and_spill():
    from skypilot_tpu.serve.replica_plane.replica_manager import \
        ReplicaManager
    stats = {'queued': 1, 'prefill_backlog_tokens': 2,
             'requests_shed': 0, 'healthy': True, 'role': 'decode',
             'prefix_cache': {'hits': 30, 'misses': 10},
             'kv_spill': {'bytes': 4096, 'spilled_pages': 7,
                          'restored_pages': 5}}

    def fake_get(url, timeout):
        del timeout
        return 200, (stats if url.endswith('/stats') else {})

    manager = ReplicaManager(lambda rid, port: None,
                             http_get=fake_get)
    view = manager.spawn()
    view.proc = None
    manager.scrape_once()
    assert view.role == 'decode'
    assert view.prefix_hit_rate == pytest.approx(0.75)
    assert view.kv_spill_bytes == 4096
    assert view.kv_spilled_pages == 7
    assert view.kv_restored_pages == 5
    d = view.to_dict()
    for key in ('role', 'prefix_hit_rate', 'kv_spill_bytes',
                'kv_spilled_pages', 'kv_restored_pages'):
        assert key in d


def test_lb_prompt_length_estimation_and_pool():
    from skypilot_tpu.serve.replica_plane.lb import (
        PrefillPool, estimate_prompt_tokens)
    assert estimate_prompt_tokens(
        '/generate', {'tokens': [[1] * 300]}) == 300
    assert estimate_prompt_tokens(
        '/generate', {'tokens': [[1] * 10, [1] * 500]}) == 500
    assert estimate_prompt_tokens(
        '/v1/completions', {'prompt': 'x' * 1024}) == 256
    assert estimate_prompt_tokens(
        '/v1/chat/completions',
        {'messages': [{'content': 'y' * 400}]}) == 100
    assert estimate_prompt_tokens('/generate', {'tokens': None}) == 0
    pool = PrefillPool()
    assert pool.select() is None
    pool.set_ready_replicas(['a:1', 'b:2'])
    picks = {pool.select() for _ in range(4)}
    assert picks == {'a:1', 'b:2'}
    assert pool.select(exclude={'a:1'}) == 'b:2'
    assert pool.select(exclude={'a:1', 'b:2'}) is None


def test_committed_bench_record_claims():
    """The committed BENCH_disagg_r13.json must actually show what
    the docs claim: the disaggregated arm holds decode-pool p99 ITL
    within 1.25x of its long-prompt-frac=0 value at every swept
    fraction while the unified arm degrades past that, with zero
    client errors; and the spill arm's prefix hit rate is strictly
    above the no-spill arm's with real restores behind it."""
    import os
    path = os.path.join(os.path.dirname(__file__), '..', '..',
                        'BENCH_disagg_r13.json')
    with open(path, 'r', encoding='utf-8') as f:
        record = json.load(f)
    sweep = record['sweep']
    ratios = sweep['p99_itl_vs_frac0']
    for frac in ('0.25', '0.5'):
        assert ratios['disagg'][frac] <= 1.25, ratios
        assert ratios['unified'][frac] > 1.25, ratios
    for mode in ('unified', 'disagg'):
        for frac in ('0.0', '0.25', '0.5'):
            run = sweep['sweep'][mode][frac]
            assert run['client_errors'] == 0
            assert run['decode_itl_n_samples'] > 100
    spill = record['spill']
    assert spill['prefix_hit_rate_spill'] > \
        spill['prefix_hit_rate_no_spill']
    assert spill['evictions_no_spill'] > 0
    assert spill['restored_pages'] > 0


def test_new_catalog_rows_render():
    from skypilot_tpu.observability import REGISTRY
    from skypilot_tpu.observability import catalog as obs
    obs.counter('skypilot_serving_kv_spill_pages_total').labels(
        engine='t').inc(3)
    obs.counter('skypilot_serving_kv_restore_pages_total').labels(
        engine='t').inc(2)
    obs.gauge('skypilot_serving_kv_restore_hit_ratio').labels(
        engine='t').set(0.5)
    obs.histogram('skypilot_serving_kv_handoff_seconds').observe(0.1)
    obs.counter(
        'skypilot_serving_kv_handoff_bytes_total').inc(1024)
    text = REGISTRY.render()
    for name in ('skypilot_serving_kv_spill_pages_total',
                 'skypilot_serving_kv_restore_pages_total',
                 'skypilot_serving_kv_restore_hit_ratio',
                 'skypilot_serving_kv_handoff_seconds_bucket',
                 'skypilot_serving_kv_handoff_bytes_total'):
        assert name in text


# ---------------------------------------------------------------------------
# Live migration: engine evacuation + fleet drain-by-migration
# ---------------------------------------------------------------------------
def test_adapter_salted_migration_stays_isolated(tmp_path):
    """Evacuating a mid-generation LoRA session ships an adapter-
    salted chain: the record names the tenant, the payload imports
    under the salted keys (a base-model request on the receiver gets
    ZERO hits), and the tenant's continuation on the receiver rides
    the warm pages to a bit-identical finish."""
    from skypilot_tpu.inference.adapters import AdapterRegistry
    from skypilot_tpu.models import lora as lora_lib
    from skypilot_tpu.robustness.errors import SessionMigratedError
    model, params = _build(total_pages=48)
    spec = lora_lib.LoraSpec(rank=4, alpha=8.0)
    ad_params = lora_lib.random_adapter_params(7, model.config, spec)
    lora_lib.save_adapter(str(tmp_path / 'ten_a'), ad_params, spec,
                          base_model='llama-tiny')
    prompt = SYS_PROMPT + [40]
    regs = [AdapterRegistry(str(tmp_path), model, max_adapters=2)
            for _ in range(3)]
    ctrl = _engine(model, params, adapter_store=regs[0])
    src = _engine(model, params, adapter_store=regs[1])
    dst = _engine(model, params, adapter_store=regs[2])
    try:
        ref = ctrl.submit(prompt, max_new_tokens=48,
                          adapter='ten_a').result(timeout=300)
        got = threading.Event()
        fut = src.submit(prompt, max_new_tokens=48, adapter='ten_a',
                         on_token=lambda t: got.set())
        assert got.wait(timeout=300)
        res = src.evacuate_chains(reason='drain')
        assert res['evacuated'] == 1
        with pytest.raises(SessionMigratedError) as exc_info:
            fut.result(timeout=300)
        rec = exc_info.value.record
        assert rec['reason'] == 'drain'
        assert rec['adapter'] == 'ten_a'
        committed = rec['tokens']
        # Mid-generation: prompt plus at least one committed token,
        # and a strict prefix of the undisturbed control run.
        assert len(prompt) < len(committed) < rec['limit']
        assert committed == ref[:len(committed)]
        assert rec['payload'] is not None
        assert rec['pages'] == len(committed) // 8
        meta, _ = kv_transfer.unpack_pages(rec['payload'])
        assert meta['salt'] != ''
        assert dst.import_chain(rec['payload'])['imported'] == \
            rec['pages']
        # Base-model probe on the receiver: same tokens, different
        # salt -> the migrated tenant pages are invisible.
        h0 = dst.prefix_cache.hits
        dst.submit(list(committed),
                   max_new_tokens=2).result(timeout=300)
        assert dst.prefix_cache.hits == h0
        # Tenant continuation: warm imported pages + bit-identical
        # finish (exactly what the record tells a peer to run).
        h1 = dst.prefix_cache.hits
        out = dst.submit(list(committed),
                         max_new_tokens=rec['limit'] - len(committed),
                         adapter='ten_a').result(timeout=300)
        assert out == ref
        assert dst.prefix_cache.hits - h1 >= rec['pages']
    finally:
        ctrl.stop()
        src.stop()
        dst.stop()


def _migration_fleet(n=2, **stub_kw):
    """A unified (decode-only) in-process stub fleet behind a
    prefix-affinity LB; every replica learns its peers via the
    controller's /kv/peers push, so evacuations have targets."""
    from skypilot_tpu.serve import autoscalers
    from skypilot_tpu.serve import load_balancing_policies as lbp
    from skypilot_tpu.serve import service_spec as spec_lib
    from skypilot_tpu.serve.replica_plane import (FleetController,
                                                  ReplicaManager,
                                                  make_lb_server)
    from skypilot_tpu.serve.replica_plane.stub import \
        in_process_stub_factory
    factory = in_process_stub_factory(cache_pages=512, **stub_kw)
    spec = spec_lib.SkyServiceSpec(min_replicas=n, max_replicas=n)
    policy = lbp.PrefixAffinityPolicy()
    manager = ReplicaManager(factory, drain_grace_s=10.0)
    controller = FleetController(
        manager, policy, autoscalers.EngineMetricsAutoscaler(spec),
        interval_s=0.2)
    lb = make_lb_server(policy, 0, policy_name='prefix_affinity',
                        manager=manager)
    threading.Thread(target=lb.serve_forever, daemon=True).start()
    for _ in range(n):
        manager.spawn()
    assert controller.wait_ready(n, timeout_s=60)
    controller.tick()   # push peers
    url = f'http://127.0.0.1:{lb.server_address[1]}'
    return url, controller, manager, lb, policy


def test_drain_by_migration_finishes_stream_on_survivor():
    """THE drain chaos contract: drain the replica that owns an
    in-flight stream; its chain migrates to a survivor mid-stream,
    the client's token row stays bit-identical (the receiver
    re-derives the origin's sequence via _continuation), the victim
    exits 0 with migrations{drain} > 0, and the controller pins the
    migrated session key to the new owner."""
    import requests as requests_lib
    url, controller, manager, lb, policy = _migration_fleet(
        n=2, seed=2026, token_sleep_s=0.05)
    try:
        prompt = list(range(2, 26))   # 24 tokens
        max_new = 40
        expected = [(2026 * 1000003 + len(prompt) * 31 + j) % 50000
                    for j in range(max_new)]
        toks = []
        first = threading.Event()
        err = []

        def client():
            try:
                with requests_lib.post(
                        url + '/generate',
                        json={'tokens': [prompt],
                              'max_new_tokens': max_new,
                              'stream': True},
                        stream=True, timeout=(5, 120)) as resp:
                    assert resp.status_code == 200
                    for line in resp.iter_lines(chunk_size=1):
                        if not line.startswith(b'data: '):
                            continue
                        payload = line[len(b'data: '):]
                        if payload == b'[DONE]':
                            return
                        frame = json.loads(payload)
                        if 'token' in frame:
                            toks.append(int(frame['token']))
                            first.set()
            except Exception as e:  # pylint: disable=broad-except
                err.append(e)
            finally:
                first.set()

        t = threading.Thread(target=client, daemon=True)
        t.start()
        assert first.wait(timeout=60)
        assert not err, f'client failed early: {err[0]!r}'
        victim = None
        deadline = time.monotonic() + 30
        while victim is None and time.monotonic() < deadline:
            for v in manager.views():
                if v.proc.state.inflight > 0:
                    victim = v
                    break
            else:
                time.sleep(0.01)
        assert victim is not None, \
            'no replica owns the in-flight stream'
        controller.drain_replica(victim)
        t.join(timeout=120)
        assert not t.is_alive()
        assert not err, f'client saw {err[0]!r}'
        # Bit-identical across the migration: every token equals the
        # closed-form stub sequence an undisturbed replica emits.
        assert toks == expected
        vstate = victim.proc.state
        assert vstate.migrations.get('drain', 0) >= 1
        assert vstate.sessions_evacuated >= 1
        # The victim's own drain finishes cleanly once the tail has
        # been piped through (drain runs in a controller thread).
        deadline = time.monotonic() + 30
        while victim.proc.poll() is None and \
                time.monotonic() < deadline:
            time.sleep(0.05)
        assert victim.proc.poll() == 0
        survivors = [v for v in manager.views()
                     if v.replica_id != victim.replica_id]
        adopted = [v for v in survivors
                   if v.proc.state.migrations_in > 0]
        assert adopted, 'no survivor adopted the migrated chain'
        keys = list(adopted[0].proc.state.migrated_in_keys)
        assert keys
        # Scrape -> tick turns the receiver's migrated-in keys into
        # LB session pins: follow-ups land on the warm pages.
        manager.scrape_once()
        controller.tick()
        assert policy.select_replica(keys[-1]) == \
            adopted[0].endpoint
    finally:
        controller.shutdown()
        lb.shutdown()
