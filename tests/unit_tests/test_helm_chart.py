"""Render the Helm chart with a minimal template-subset renderer.

No helm binary in this environment; the chart's templates are
restricted (by policy, stated in the templates) to `{{ .Values.* }}`
interpolation and `{{- if .Values.* }}` / `{{- else }}` / `{{- end }}`
blocks, which this renderer implements — enough to prove every
manifest is valid YAML with the right structure under default and
overridden values.
"""
import os
import re

import yaml

CHART = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))),
    'deploy', 'helm', 'skypilot-tpu')


def _lookup(values, dotted):
    cur = values
    for part in dotted.split('.'):
        cur = cur[part]
    return cur


def render(template_text, values):
    out_lines = []
    skip_stack = []
    for line in template_text.splitlines():
        m_if = re.match(r'\s*\{\{-? if (.+?) \}\}\s*$', line)
        m_else = re.match(r'\s*\{\{-? else \}\}\s*$', line)
        m_end = re.match(r'\s*\{\{-? end \}\}\s*$', line)
        if m_if:
            expr = m_if.group(1).strip()
            assert expr.startswith('.Values.'), f'unsupported if: {expr}'
            val = _lookup(values, expr[len('.Values.'):])
            skip_stack.append(not bool(val))
            continue
        if m_else:
            skip_stack[-1] = not skip_stack[-1]
            continue
        if m_end:
            skip_stack.pop()
            continue
        if any(skip_stack):
            continue

        def sub(m):
            return str(_lookup(values, m.group(1)))

        rendered = re.sub(r'\{\{ \.Values\.([\w.]+) \}\}', sub, line)
        assert '{{' not in rendered, f'unrendered template in: {line}'
        out_lines.append(rendered)
    return '\n'.join(out_lines)


def _load_chart(value_overrides=None):
    with open(os.path.join(CHART, 'values.yaml'), encoding='utf-8') as f:
        values = yaml.safe_load(f)
    for dotted, v in (value_overrides or {}).items():
        cur = values
        parts = dotted.split('.')
        for p in parts[:-1]:
            cur = cur[p]
        cur[parts[-1]] = v
    docs = []
    tdir = os.path.join(CHART, 'templates')
    for name in sorted(os.listdir(tdir)):
        with open(os.path.join(tdir, name), encoding='utf-8') as f:
            rendered = render(f.read(), values)
        docs.extend(d for d in yaml.safe_load_all(rendered) if d)
    return docs


def test_chart_metadata():
    with open(os.path.join(CHART, 'Chart.yaml'), encoding='utf-8') as f:
        chart = yaml.safe_load(f)
    assert chart['name'] == 'skypilot-tpu'
    assert chart['apiVersion'] == 'v2'


def test_default_render():
    docs = _load_chart()
    kinds = [d['kind'] for d in docs]
    assert kinds.count('Deployment') == 1
    assert 'Service' in kinds and 'PersistentVolumeClaim' in kinds
    assert 'DaemonSet' not in kinds  # fuse-proxy off by default
    deploy = next(d for d in docs if d['kind'] == 'Deployment')
    assert deploy['spec']['replicas'] == 1
    container = deploy['spec']['template']['spec']['containers'][0]
    assert container['ports'][0]['containerPort'] == 46580
    env_names = [e['name'] for e in container['env']]
    assert 'SKYPILOT_API_TOKEN' not in env_names  # empty token -> off
    # Default workflow: operator-populated /app volume + PYTHONPATH
    # (the default image carries no repo code).
    mounts = [m['name'] for m in container['volumeMounts']]
    assert 'app' in mounts
    assert 'PYTHONPATH' in env_names


def test_multi_replica_render():
    """replicas > 1 + dbUrl + statePvc: false — the HA shape: no PVC
    object, /state on emptyDir, SKYPILOT_DB_URL + per-pod server id."""
    docs = _load_chart({'apiServer.replicas': 2,
                        'apiServer.dbUrl':
                            'postgresql://u:p@pg:5432/sky',
                        'apiServer.statePvc': False})
    kinds = [d['kind'] for d in docs]
    assert 'PersistentVolumeClaim' not in kinds
    deploy = next(d for d in docs if d['kind'] == 'Deployment')
    assert deploy['spec']['replicas'] == 2
    spec = deploy['spec']['template']['spec']
    state = next(v for v in spec['volumes'] if v['name'] == 'state')
    assert 'emptyDir' in state and 'persistentVolumeClaim' not in state
    env = {e['name']: e for e in spec['containers'][0]['env']}
    assert env['SKYPILOT_DB_URL']['value'] == \
        'postgresql://u:p@pg:5432/sky'
    # Identity HOST = pod IP (dialable by peers for cross-replica log
    # streaming); the server composes host:port itself.
    assert env['SKYPILOT_API_SERVER_HOST']['valueFrom']['fieldRef'][
        'fieldPath'] == 'status.podIP'


def test_overridden_render():
    docs = _load_chart({'fuseProxy.enabled': True,
                        'apiServer.port': 50000,
                        'apiServer.authToken': 123456,
                        'apiServer.codeVolume': False,
                        'namespace': 'custom-ns'})
    kinds = [d['kind'] for d in docs]
    assert 'DaemonSet' in kinds
    deploy = next(d for d in docs if d['kind'] == 'Deployment')
    assert deploy['metadata']['namespace'] == 'custom-ns'
    container = deploy['spec']['template']['spec']['containers'][0]
    assert container['ports'][0]['containerPort'] == 50000
    env = {e['name']: e.get('value') for e in container['env']}
    # Digits-only tokens must render as STRINGS (quoted interpolation)
    # or `kubectl apply` rejects the EnvVar.
    assert env['SKYPILOT_API_TOKEN'] == '123456'
    # Baked-image override: no empty /app mount shadowing the code.
    assert 'PYTHONPATH' not in env
    assert 'app' not in [m['name'] for m in container['volumeMounts']]
    volumes = [v['name']
               for v in deploy['spec']['template']['spec']['volumes']]
    assert 'app' not in volumes
    svc = next(d for d in docs if d['kind'] == 'Service')
    assert svc['spec']['ports'][0]['port'] == 50000
    ds = next(d for d in docs if d['kind'] == 'DaemonSet')
    assert ds['spec']['template']['spec']['hostPID'] is True
