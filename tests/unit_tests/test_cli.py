"""CLI tests for the offline command surface (no API server needed).

Reference analog: tests/test_cli.py drives sky's click app with
CliRunner; same pattern here for config/workspaces/ssh-node-pool/
recipes/dashboard.
"""
import os

import pytest
import yaml
from click.testing import CliRunner

from skypilot_tpu.client import cli


@pytest.fixture()
def runner(isolated_state):  # pylint: disable=unused-argument
    return CliRunner()


def test_config_set_get_list_unset(runner, isolated_state):
    r = runner.invoke(cli.cli, ['config', 'set', 'gcp.project_id', 'proj-1'])
    assert r.exit_code == 0, r.output
    path = os.path.join(isolated_state, 'config.yaml')
    assert os.path.exists(path)
    with open(path, 'r', encoding='utf-8') as f:
        assert yaml.safe_load(f) == {'gcp': {'project_id': 'proj-1'}}

    r = runner.invoke(cli.cli, ['config', 'get', 'gcp.project_id'])
    assert r.exit_code == 0
    assert 'proj-1' in r.output

    r = runner.invoke(cli.cli, ['config', 'list'])
    assert r.exit_code == 0
    assert 'project_id' in r.output

    r = runner.invoke(cli.cli, ['config', 'unset', 'gcp.project_id'])
    assert r.exit_code == 0
    r = runner.invoke(cli.cli, ['config', 'get', 'gcp.project_id'])
    assert r.exit_code != 0


def test_config_set_rejects_schema_violation(runner):
    # `workspaces` must be a mapping; a scalar must be rejected before
    # the file is written.
    r = runner.invoke(cli.cli, ['config', 'set', 'workspaces', 'nope'])
    assert r.exit_code != 0
    assert 'rejected' in r.output


def test_config_set_parses_yaml_values(runner, isolated_state):
    r = runner.invoke(cli.cli,
                      ['config', 'set', 'api_server.port', '46581'])
    assert r.exit_code == 0
    with open(os.path.join(isolated_state, 'config.yaml'),
              encoding='utf-8') as f:
        assert yaml.safe_load(f)['api_server']['port'] == 46581


def test_workspaces_ls_and_switch(runner, isolated_state):
    runner.invoke(cli.cli, ['config', 'set', 'workspaces',
                            '{team-a: {allowed_clouds: [gcp]}}'])
    r = runner.invoke(cli.cli, ['workspaces', 'ls'])
    assert r.exit_code == 0, r.output
    assert 'team-a' in r.output and 'default' in r.output

    r = runner.invoke(cli.cli, ['workspaces', 'switch', 'team-a'])
    assert r.exit_code == 0
    from skypilot_tpu.workspaces import core as ws_core
    assert ws_core.active_workspace() == 'team-a'

    r = runner.invoke(cli.cli, ['workspaces', 'switch', 'nope'])
    assert r.exit_code != 0


def test_workspaces_show(runner, isolated_state):
    runner.invoke(cli.cli, ['config', 'set', 'workspaces',
                            '{team-a: {allowed_clouds: [gcp]}}'])
    r = runner.invoke(cli.cli, ['workspaces', 'show', 'team-a'])
    assert r.exit_code == 0
    assert 'gcp' in r.output


def test_ssh_node_pool_ls(runner, tmp_path, monkeypatch):
    from skypilot_tpu.clouds import ssh as ssh_cloud
    pools_file = tmp_path / 'pools.yaml'
    pools_file.write_text(yaml.safe_dump({
        'pools': {'lab': {'user': 'ubuntu',
                          'identity_file': '~/.ssh/k',
                          'hosts': ['10.0.0.1', '10.0.0.2']}}}))
    monkeypatch.setattr(ssh_cloud, 'POOLS_PATH', str(pools_file))
    r = runner.invoke(cli.cli, ['ssh-node-pool', 'ls'])
    assert r.exit_code == 0, r.output
    assert 'lab' in r.output and '2' in r.output


def test_ssh_node_pool_check_unknown_pool(runner, tmp_path, monkeypatch):
    from skypilot_tpu.clouds import ssh as ssh_cloud
    monkeypatch.setattr(ssh_cloud, 'POOLS_PATH',
                        str(tmp_path / 'none.yaml'))
    r = runner.invoke(cli.cli, ['ssh-node-pool', 'check', 'nope'])
    assert r.exit_code != 0


def test_dashboard_prints_url(runner):
    r = runner.invoke(cli.cli, ['dashboard', '--no-open'])
    assert r.exit_code == 0
    assert '/dashboard' in r.output


def test_recipes_list_and_show(runner):
    r = runner.invoke(cli.cli, ['recipes', 'list'])
    assert r.exit_code == 0, r.output
    r = runner.invoke(cli.cli, ['recipes', 'show', 'nope-recipe'])
    assert r.exit_code != 0


def test_api_login_writes_endpoint(runner, isolated_state):
    r = runner.invoke(cli.cli, ['api', 'login', '-e',
                                'http://127.0.0.1:1'])
    assert r.exit_code == 0, r.output
    with open(os.path.join(isolated_state, 'config.yaml'),
              encoding='utf-8') as f:
        cfg = yaml.safe_load(f)
    assert cfg['api_server']['endpoint'] == 'http://127.0.0.1:1'


def test_env_file_parsing(tmp_path):
    from skypilot_tpu.client.cli import _parse_env_file
    env_file = tmp_path / '.env'
    env_file.write_text(
        '# comment\n\nFOO=bar\nQUOTED="with spaces"\n'
        "SINGLE='sq'\nNOEQ\nKEY=has=equals\n")
    out = _parse_env_file(str(env_file))
    assert out == {'FOO': 'bar', 'QUOTED': 'with spaces',
                   'SINGLE': 'sq', 'KEY': 'has=equals'}


def test_stop_requires_name_or_all(runner):
    r = runner.invoke(cli.cli, ['stop', '-y'])
    assert r.exit_code != 0
    assert '--all' in r.output


def test_down_requires_name_or_all(runner):
    r = runner.invoke(cli.cli, ['down', '-y'])
    assert r.exit_code != 0


def test_serve_down_requires_name_or_all(runner):
    r = runner.invoke(cli.cli, ['serve', 'down', '-y'])
    assert r.exit_code != 0


def test_completion_emits_script(runner):
    r = runner.invoke(cli.cli, ['completion', 'bash'])
    assert r.exit_code == 0, r.output
    assert '_STPU_COMPLETE=bash_complete' in r.output
    r = runner.invoke(cli.cli, ['completion', 'zsh'])
    assert r.exit_code == 0
    r = runner.invoke(cli.cli, ['completion', 'tcsh'])
    assert r.exit_code != 0


def test_ssh_node_pool_up_down_validate_pool(runner):
    r = runner.invoke(cli.cli, ['ssh-node-pool', 'up', 'nope'])
    assert r.exit_code != 0
    assert 'not declared' in r.output
    r = runner.invoke(cli.cli, ['ssh-node-pool', 'down', 'nope', '-y'])
    assert r.exit_code != 0
    assert 'not declared' in r.output


def test_local_group_and_pool_logs_registered(runner):
    r = runner.invoke(cli.cli, ['local', '--help'])
    assert r.exit_code == 0 and 'up' in r.output and 'down' in r.output
    r = runner.invoke(cli.cli, ['jobs', 'pool', '--help'])
    assert r.exit_code == 0 and 'logs' in r.output


def test_status_kubernetes_flag_no_context(runner, monkeypatch):
    from skypilot_tpu.provision.kubernetes import instance as k8s_inst
    monkeypatch.setattr(k8s_inst, 'list_skypilot_pods', lambda **kw: [
        {'name': 'c1-0', 'cluster': 'c1', 'node_rank': '0',
         'phase': 'Running', 'node': 'gke-n1', 'namespace': 'default'},
    ])
    r = runner.invoke(cli.cli, ['status', '--kubernetes'])
    assert r.exit_code == 0, r.output
    assert 'c1-0' in r.output and 'Running' in r.output
