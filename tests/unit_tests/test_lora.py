"""Multi-LoRA serving + LoRA finetuning (models/lora.py,
inference/adapters.py, the engine's batched per-slot application, and
the train_lm --lora produce-then-serve loop).

The parity contract under test: batched per-slot LoRA in the engine
must reproduce the merged-weights (W + a@b·alpha/rank) forward
exactly for greedy decode, paged AND dense; a mixed round (base +
several adapters in one dispatch) must equal running each adapter
alone; and KV prefix-cache pages must never cross adapter
boundaries (chain keys are adapter-salted).
"""
import os
import subprocess
import sys
import tempfile

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from skypilot_tpu.inference import affinity
from skypilot_tpu.inference.adapters import AdapterRegistry
from skypilot_tpu.models import lora as lora_lib
from skypilot_tpu.models.batching import (ContinuousBatchingEngine,
                                          PrefixCache)
from skypilot_tpu.models.llama import Llama, LlamaConfig
from skypilot_tpu.robustness import faults
from skypilot_tpu.robustness.errors import (AdapterLoadError,
                                            AdapterNotFoundError)

SPEC = lora_lib.LoraSpec(rank=4, alpha=8.0)


def _tiny(**kw):
    cfg = LlamaConfig.tiny(dtype=jnp.float32, kv_page_size=8,
                           kv_total_pages=40, **kw)
    model = Llama(cfg)
    params = nn.meta.unbox(model.init(
        jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))['params'])
    return model, params


@pytest.fixture(scope='module')
def base():
    return _tiny()


@pytest.fixture(scope='module')
def artifact_dir(base):
    """Three saved adapters + their raw factors."""
    model, _ = base
    tmp = tempfile.mkdtemp(prefix='lora_artifacts_')
    raw = {}
    for i in range(3):
        lp = lora_lib.random_adapter_params(i, model.config, SPEC)
        lora_lib.save_adapter(os.path.join(tmp, f'ad{i}'), lp, SPEC,
                              base_model='llama-tiny')
        raw[f'ad{i}'] = lp
    return tmp, raw


@pytest.fixture(scope='module')
def store_engine(base, artifact_dir):
    """ONE paged engine + registry shared by the serving tests (each
    test uses its own prompt range so prefix-cache state composes)."""
    model, params = base
    adir, _ = artifact_dir
    reg = AdapterRegistry(adir, model, max_adapters=4)
    eng = ContinuousBatchingEngine(model, params, num_slots=4,
                                   max_total_len=64,
                                   adapter_store=reg)
    assert eng.paged
    yield eng, reg
    eng.stop()


# -- artifact format --------------------------------------------------------
def test_artifact_roundtrip(base, artifact_dir):
    adir, raw = artifact_dir
    config, loaded = lora_lib.load_adapter(os.path.join(adir, 'ad0'))
    assert config['rank'] == SPEC.rank
    assert tuple(config['targets']) == SPEC.targets
    for layer, targets in raw['ad0'].items():
        for t, factors in targets.items():
            np.testing.assert_array_equal(factors['a'],
                                          loaded[layer][t]['a'])
            np.testing.assert_array_equal(factors['b'],
                                          loaded[layer][t]['b'])


def test_single_adapter_forward_matches_merged(base, artifact_dir):
    """The model-level oracle: lora kwargs == merged-weights forward
    (fp32 tolerance), and batched row 0 is exactly the base model."""
    model, params = base
    _, raw = artifact_dir
    toks = jnp.asarray(
        np.random.default_rng(3).integers(
            1, model.config.vocab_size, (2, 12)), jnp.int32)
    out_lora = model.apply(
        {'params': params}, toks,
        lora=lora_lib.as_model_lora(raw['ad0'], SPEC.scale))
    merged = lora_lib.merge_lora(params, raw['ad0'], SPEC)
    out_merged = model.apply({'params': merged}, toks)
    np.testing.assert_allclose(np.asarray(out_lora),
                               np.asarray(out_merged),
                               rtol=1e-5, atol=1e-5)


# -- engine parity ----------------------------------------------------------
def test_mixed_round_matches_each_alone_and_merged(base, artifact_dir,
                                                   store_engine):
    """base + 3 adapters in ONE dispatch round == each run alone ==
    (for ad1) a merged-weights engine, greedy, paged."""
    model, params = base
    _, raw = artifact_dir
    eng, _reg = store_engine
    prompt = list(range(2, 22))
    futs = [eng.submit(prompt, max_new_tokens=8)]
    futs += [eng.submit(prompt, max_new_tokens=8, adapter=f'ad{i}')
             for i in range(3)]
    mixed = [f.result(timeout=180) for f in futs]
    alone = [eng.submit(prompt, max_new_tokens=8).result(timeout=180)]
    alone += [eng.submit(prompt, max_new_tokens=8,
                         adapter=f'ad{i}').result(timeout=180)
              for i in range(3)]
    assert mixed == alone
    # 4 genuinely different models in one round.
    assert len({tuple(r) for r in mixed}) == 4
    # Merged-weights parity for one of them.
    merged = lora_lib.merge_lora(params, raw['ad1'], SPEC)
    ref_eng = ContinuousBatchingEngine(model, merged, num_slots=2,
                                       max_total_len=64)
    try:
        ref = ref_eng.submit(prompt,
                             max_new_tokens=8).result(timeout=180)
    finally:
        ref_eng.stop()
    assert ref == mixed[2]


def test_dense_engine_adapter_matches_merged(base, artifact_dir):
    """The same parity on the DENSE (non-paged) cache path."""
    model, params = base
    adir, raw = artifact_dir
    reg = AdapterRegistry(adir, model, max_adapters=2)
    prompt = list(range(40, 58))
    eng = ContinuousBatchingEngine(model, params, num_slots=2,
                                   max_total_len=64, paged=False,
                                   adapter_store=reg)
    merged = lora_lib.merge_lora(params, raw['ad2'], SPEC)
    ref_eng = ContinuousBatchingEngine(model, merged, num_slots=2,
                                       max_total_len=64, paged=False)
    try:
        assert not eng.paged and not ref_eng.paged
        got = eng.submit(prompt, max_new_tokens=8,
                         adapter='ad2').result(timeout=180)
        base_out = eng.submit(prompt,
                              max_new_tokens=8).result(timeout=180)
        ref = ref_eng.submit(prompt,
                             max_new_tokens=8).result(timeout=180)
    finally:
        eng.stop()
        ref_eng.stop()
    assert got == ref
    assert got != base_out  # the adapter actually changed the model


def test_fast_path_skips_lora_dispatch(store_engine):
    """No active adapter lane -> the dispatch kwargs are empty (the
    compiled base-only executables run untouched)."""
    eng, _reg = store_engine
    assert not eng.slot_adapter.any()
    assert eng._lora_args() == {}
    assert eng._slot_lora_args(0) == {}


# -- prefix-cache tenant isolation ------------------------------------------
def test_chain_key_isolation_across_adapters(store_engine):
    """Same prompt, two adapters -> NO prefix-cache hit (KV pages are
    adapter-dependent); same prompt + same adapter -> full hit."""
    eng, _reg = store_engine
    prompt = list(range(100, 125))  # 3 full 8-token pages
    pc = eng.prefix_cache

    eng.submit(prompt, max_new_tokens=4,
               adapter='ad0').result(timeout=180)
    h0 = pc.hits
    eng.submit(prompt, max_new_tokens=4,
               adapter='ad0').result(timeout=180)
    assert pc.hits == h0 + 3        # same tenant: all 3 pages hit
    h1 = pc.hits
    eng.submit(prompt, max_new_tokens=4,
               adapter='ad1').result(timeout=180)
    assert pc.hits == h1            # other tenant: zero hits
    h2 = pc.hits
    eng.submit(prompt, max_new_tokens=4).result(timeout=180)
    assert pc.hits == h2            # base model: zero hits too


def test_chain_key_salt_parity_with_affinity():
    """The LB re-derives the engine's salted chain keys without JAX —
    byte-identical, and the salt actually separates tenants."""
    tokens = list(range(1, 40))
    salt = affinity.adapter_salt('alice')
    assert PrefixCache.chain_keys(tokens, 8, salt=salt) == \
        affinity.chain_keys(tokens, 8, salt=salt)
    assert PrefixCache.chain_keys(tokens, 8) == \
        affinity.chain_keys(tokens, 8)
    assert affinity.chain_keys(tokens, 8, salt=salt) != \
        affinity.chain_keys(tokens, 8)
    # request_affinity_key folds the model field in.
    body = {'tokens': [tokens]}
    k_base = affinity.request_affinity_key('/generate', body, 8)
    k_alice = affinity.request_affinity_key(
        '/generate', dict(body, model='alice'), 8)
    k_bob = affinity.request_affinity_key(
        '/generate', dict(body, model='bob'), 8)
    assert len({k_base, k_alice, k_bob}) == 3
    assert k_alice == affinity.request_affinity_key(
        '/generate', dict(body, model='alice'), 8)


# -- registry residency -----------------------------------------------------
def test_registry_lru_evicts_unpinned_never_pinned(base, artifact_dir):
    model, _ = base
    adir, _ = artifact_dir
    reg = AdapterRegistry(adir, model, max_adapters=2)
    s0 = reg.acquire('ad0')            # pinned (ref 1)
    s1 = reg.acquire('ad1')
    reg.release(s1)                    # resident, evictable
    s2 = reg.acquire('ad2')            # evicts ad1, never ad0
    assert reg.stats()['evictions'] == 1
    assert sorted(reg.loaded_names()) == ['ad0', 'ad2']
    # Both slots pinned now: acquiring the third is back-pressure,
    # not an eviction of someone's running adapter.
    assert reg.acquire('ad1') is None
    reg.release(s2)
    s1b = reg.acquire('ad1')           # reloads over ad2's slot
    assert s1b == s2
    assert reg.stats()['evictions'] == 2
    assert reg.stats()['loads'] == 4
    reg.release(s1b)
    reg.release(s0)
    with pytest.raises(AdapterNotFoundError):
        reg.acquire('nope')


def test_registry_rank_ceiling_rejected(base, artifact_dir):
    """A hot-dropped artifact with rank > the store geometry fails as
    a load error (503), not silently wrong math."""
    model, _ = base
    adir, _ = artifact_dir
    reg = AdapterRegistry(adir, model, max_adapters=2)
    reg.acquire('ad0')                 # fixes the stack geometry
    big = lora_lib.LoraSpec(rank=16, alpha=16.0)
    lora_lib.save_adapter(
        os.path.join(adir, 'too-big'),
        lora_lib.random_adapter_params(9, model.config, big), big,
        base_model='llama-tiny')
    try:
        assert reg.exists('too-big')   # hot-load rescan finds it
        with pytest.raises(AdapterLoadError):
            reg.acquire('too-big')
        assert reg.stats()['load_failures'] == 1
    finally:
        import shutil
        shutil.rmtree(os.path.join(adir, 'too-big'))


def test_adapters_load_fault_fails_only_that_request(base,
                                                     artifact_dir):
    """An injected adapters.load fault -> AdapterLoadError (503) for
    the requesting client; the registry (and a later clean load)
    keep working — the chaos contract."""
    from skypilot_tpu.inference.http_server import classify_error
    model, _ = base
    adir, _ = artifact_dir
    reg = AdapterRegistry(adir, model, max_adapters=2)
    faults.install_plan({'rules': [{'point': 'adapters.load',
                                    'action': 'raise', 'times': 1}]})
    try:
        with pytest.raises(AdapterLoadError) as ei:
            reg.acquire('ad0')
        assert classify_error(ei.value)[0] == 503
        assert classify_error(AdapterNotFoundError('x'))[0] == 404
        # The injected failure consumed its one firing: the next
        # acquire loads cleanly.
        slot = reg.acquire('ad0')
        assert slot is not None
        reg.release(slot)
        assert reg.stats()['load_failures'] == 1
        assert reg.stats()['loads'] == 1
    finally:
        faults.clear()


# -- OpenAI model-field contract --------------------------------------------
def test_unknown_model_404_even_without_adapters(base):
    """The /v1 endpoints must validate `model` (and /generate too):
    unknown -> the OpenAI 404 error object, even when no adapters are
    configured (they used to silently serve the base model)."""
    import json
    import threading
    import urllib.request

    from skypilot_tpu.inference.http_server import make_server
    from skypilot_tpu.inference.runtime import InferenceRuntime
    model, params = base
    rt = InferenceRuntime(model=model, params=params,
                          vocab_size=model.config.vocab_size,
                          model_name='llama-tiny', max_total_len=48,
                          spec_total=48, speculative=0)
    server = make_server(rt, 0)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()

    def post(path, body):
        req = urllib.request.Request(
            f'http://127.0.0.1:{port}{path}',
            data=json.dumps(body).encode(),
            headers={'Content-Type': 'application/json'})
        try:
            with urllib.request.urlopen(req) as resp:
                return resp.status, json.loads(resp.read())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read())

    try:
        code, body = post('/v1/completions',
                          {'model': 'nope', 'prompt': 'x'})
        assert code == 404
        assert body['error']['code'] == 'model_not_found'
        assert body['error']['type'] == 'invalid_request_error'
        code, body = post('/v1/chat/completions',
                          {'model': 'nope',
                           'messages': [{'role': 'user',
                                         'content': 'x'}]})
        assert code == 404
        assert body['error']['code'] == 'model_not_found'
        code, body = post('/generate',
                          {'tokens': [[1, 2, 3]], 'model': 'nope'})
        assert code == 404
        # The base name resolves (no 404): it fails later on the
        # missing tokenizer instead — proving validation is about
        # the model field, not a blanket rejection.
        code, body = post('/v1/completions',
                          {'model': 'llama-tiny', 'prompt': 'x'})
        assert code == 400
        assert 'tokenizer' in body['error']['message']
        # /v1/models lists the base model.
        with urllib.request.urlopen(
                f'http://127.0.0.1:{port}/v1/models') as resp:
            models = json.loads(resp.read())
        assert [m['id'] for m in models['data']] == ['llama-tiny']
    finally:
        server.shutdown()


# -- trainer ----------------------------------------------------------------
def test_trainer_freezes_base_and_trains_factors(base):
    """ShardedTrainer(lora=...): base params bit-identical after
    steps, A/B factors move, loss finite — and the optimizer holds
    NO moments for the frozen base."""
    import optax

    from skypilot_tpu.parallel import mesh as mesh_lib
    from skypilot_tpu.parallel.train import ShardedTrainer
    model, _ = base
    mesh = mesh_lib.make_mesh(mesh_lib.MeshConfig(data=1),
                              devices=jax.devices()[:1])
    trainer = ShardedTrainer(model, mesh,
                             tx=optax.adam(1e-2),
                             lora=SPEC)
    example = jnp.zeros((2, 16), jnp.int32)
    state = trainer.init(jax.random.PRNGKey(0), example)
    assert set(state.params) == {'base', 'lora'}
    base_before = jax.device_get(state.params['base'])
    step = trainer.make_train_step(example, donate=False)
    tokens = jnp.asarray(np.random.default_rng(0).integers(
        1, model.config.vocab_size, (2, 16)), jnp.int32)
    for _ in range(2):
        state, loss = step(state, tokens)
    assert np.isfinite(float(loss))
    base_after = jax.device_get(state.params['base'])
    jax.tree.map(np.testing.assert_array_equal, base_before,
                 base_after)
    lora_after = jax.device_get(state.params['lora'])
    moved = jax.tree.leaves(jax.tree.map(
        lambda x: float(np.abs(x).sum()), lora_after))
    assert any(m > 0 for m in moved)
    # No Adam moments for the frozen base partition: masked leaves
    # are MaskedNode (zero-size), so total moment leaves track only
    # the lora tree.
    n_lora_leaves = len(jax.tree.leaves(state.params['lora']))
    n_base_leaves = len(jax.tree.leaves(state.params['base']))
    n_moment_leaves = len(jax.tree.leaves(state.opt_state))
    assert n_moment_leaves < 2 * (n_base_leaves + n_lora_leaves)


def test_train_lm_lora_artifact_hot_loads_into_registry(
        base, artifact_dir, store_engine):
    """The full produce-then-serve loop: `train_lm --lora` writes an
    artifact; dropping it into a LIVE registry's dir makes it
    servable with no restart and no conversion step."""
    model, _ = base
    adir, _ = artifact_dir
    eng, reg = store_engine
    env = dict(os.environ, JAX_PLATFORMS='cpu')
    out = os.path.join(adir, 'tuned')
    proc = subprocess.run(
        [sys.executable, '-m', 'skypilot_tpu.recipes.train_lm',
         '--model', 'llama-tiny', '--cpu', '--steps', '2',
         '--seq', '32', '--global-batch', '8', '--log-every', '1',
         '--lora', '4', '--lora-alpha', '8',
         '--adapter-out', out],
        env=env, capture_output=True, text=True, timeout=420)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert 'adapter artifact ->' in proc.stdout
    config, weights = lora_lib.load_adapter(out)
    assert config['base_model'] == 'llama-tiny'
    assert config['step'] == 2
    # Trained: the zero-init B factors moved.
    b_mass = sum(float(np.abs(t['b']).sum())
                 for layer in weights.values() for t in layer.values())
    assert b_mass > 0
    # Hot-load into the live engine (rescan on miss) and serve.
    assert reg.exists('tuned')
    row = eng.submit(list(range(200, 212)), max_new_tokens=4,
                     adapter='tuned').result(timeout=180)
    assert len(row) == 16
    assert 'tuned' in reg.loaded_names()
