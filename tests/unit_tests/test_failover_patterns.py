"""Failover pattern library: every row classifies a realistic error
text to the right (category, scope) — the declarative equivalent of
the reference's FailoverCloudErrorHandlerV1/V2 blocklist mapping
(sky/backends/cloud_vm_ray_backend.py:395,522), tested row by row.
"""
import pytest

from skypilot_tpu import exceptions
from skypilot_tpu.provision import failover_patterns as fp

P = exceptions.ProvisionerError

# Each case: (cloud, code, message, expected_category, expected_scope).
GCP_CASES = [
    ('ZONE_RESOURCE_POOL_EXHAUSTED',
     'The zone does not have enough resources', P.CAPACITY, fp.ZONE),
    ('ZONE_RESOURCE_POOL_EXHAUSTED_WITH_DETAILS',
     'us-central1-a does not have enough resources available',
     P.CAPACITY, fp.ZONE),
    ('insufficientCapacity', '', P.CAPACITY, fp.ZONE),
    ('8', 'There is no more capacity in the zone "europe-west4-a"',
     P.CAPACITY, fp.ZONE),
    ('9', 'Insufficient reserved capacity. Contact customer support',
     P.CAPACITY, fp.ZONE),
    ('3', 'Cloud TPU received a bad request. update is not supported '
     'while in state PREEMPTED', P.CAPACITY, fp.ZONE),
    ('UNSUPPORTED_OPERATION', 'operation not supported', P.CAPACITY,
     fp.ZONE),
    ('RESOURCE_NOT_READY', 'resource not ready', P.TRANSIENT, fp.ZONE),
    ('429', 'RESOURCE_EXHAUSTED', P.CAPACITY, fp.ZONE),
    ('RESOURCE_NOT_FOUND', 'instance disappeared during provisioning',
     P.CAPACITY, fp.ZONE),
    ('RESOURCE_OPERATION_RATE_EXCEEDED', '', P.TRANSIENT, fp.ZONE),
    ('429', 'Quota exceeded for quota metric requests per minute',
     P.TRANSIENT, fp.ZONE),
    ('QUOTA_EXCEEDED', "Quota 'GPUS_ALL_REGIONS' exceeded. Limit: 1.0 "
     'globally.', P.QUOTA, fp.CLOUD),
    ('QUOTA_EXCEEDED', "Quota 'CPUS' exceeded. Limit: 24.0 in region "
     'us-west1.', P.QUOTA, fp.REGION),
    ('type.googleapis.com/google.rpc.QuotaFailure',
     "Quota 'TPUV2sPreemptiblePodPerProjectPerZoneForTPUAPI' exhausted. "
     'Limit 32 in zone europe-west4-a', P.QUOTA, fp.ZONE),
    ('VPC_NOT_FOUND', 'vpc skypilot-vpc not found', P.CONFIG, fp.CLOUD),
    ('SUBNET_NOT_FOUND_FOR_VPC', 'no subnet for region', P.CONFIG,
     fp.REGION),
    ('400', 'Requested disk size cannot be smaller than the image size '
     '(10 GB)', P.CONFIG, fp.ABORT),
    ('400', 'Invalid value for field machineType', P.CONFIG, fp.ABORT),
    ('400', "Machine type a3-highgpu-8g does not exist in zone "
     'us-west1-a', P.CONFIG, fp.ZONE),
    ('IAM_PERMISSION_DENIED', 'Policy update access denied.',
     P.PERMISSION, fp.CLOUD),
    ('403', 'Location us-east1-d is not found or access is unauthorized.',
     P.PERMISSION, fp.ZONE),
    ('403', 'Billing must be enabled for activation of service',
     P.PERMISSION, fp.CLOUD),
    ('403', 'Project has not accepted the Terms of Service', P.PERMISSION,
     fp.CLOUD),
    ('403', 'The caller lacks permission tpu.nodes.create', P.PERMISSION,
     fp.CLOUD),
    ('401', 'ACCESS_TOKEN_EXPIRED', P.PERMISSION, fp.CLOUD),
    ('503', 'backendError', P.TRANSIENT, fp.ZONE),
    ('503', 'invalid state, please retry', P.TRANSIENT, fp.ZONE),
]

AWS_CASES = [
    ('InsufficientInstanceCapacity', 'We currently do not have sufficient '
     'p4d.24xlarge capacity', P.CAPACITY, fp.ZONE),
    ('InsufficientHostCapacity', '', P.CAPACITY, fp.ZONE),
    ('InsufficientReservedInstanceCapacity', '', P.CAPACITY, fp.ZONE),
    ('InsufficientCapacityOnOutpost', '', P.CAPACITY, fp.ZONE),
    ('UnfulfillableCapacity', '', P.CAPACITY, fp.ZONE),
    ('SpotMaxPriceTooLow', 'Your Spot request price of 0.1 is lower than '
     'the minimum', P.CAPACITY, fp.ZONE),
    ('MarketCapacityOversubscribed', '', P.CAPACITY, fp.ZONE),
    ('Unsupported', 'The requested configuration is currently not '
     'supported in your requested Availability Zone', P.CAPACITY, fp.ZONE),
    ('MaxSpotInstanceCountExceeded', '', P.QUOTA, fp.REGION),
    ('InstanceLimitExceeded', 'You have requested more vCPU capacity than '
     'your current limit', P.QUOTA, fp.REGION),
    ('VcpuLimitExceeded', '', P.QUOTA, fp.REGION),
    ('VolumeLimitExceeded', '', P.QUOTA, fp.REGION),
    ('AddressLimitExceeded', '', P.QUOTA, fp.REGION),
    ('OptInRequired', 'You are not subscribed to this service',
     P.PERMISSION, fp.REGION),
    ('PendingVerification', 'Your account is currently being verified',
     P.PERMISSION, fp.CLOUD),
    ('UnauthorizedOperation', 'You are not authorized to perform this '
     'operation', P.PERMISSION, fp.CLOUD),
    ('AuthFailure', 'AWS was not able to validate the provided access '
     'credentials', P.PERMISSION, fp.CLOUD),
    ('InvalidClientTokenId', '', P.PERMISSION, fp.CLOUD),
    ('ExpiredToken', '', P.PERMISSION, fp.CLOUD),
    ('SignatureDoesNotMatch', '', P.PERMISSION, fp.CLOUD),
    ('InvalidAMIID.NotFound', 'The image id does not exist', P.CONFIG,
     fp.REGION),
    ('InvalidSubnetID.NotFound', '', P.CONFIG, fp.REGION),
    ('InvalidKeyPair.NotFound', '', P.CONFIG, fp.REGION),
    ('InvalidParameterValue', '', P.CONFIG, fp.ABORT),
    ('MissingParameter', '', P.CONFIG, fp.ABORT),
    ('RequestLimitExceeded', 'Request limit exceeded', P.TRANSIENT,
     fp.ZONE),
    ('Throttling', '', P.TRANSIENT, fp.ZONE),
    ('InternalError', '', P.TRANSIENT, fp.ZONE),
    ('ServiceUnavailable', '', P.TRANSIENT, fp.ZONE),
]

AZURE_CASES = [
    ('ZonalAllocationFailed', 'Allocation failed in the zone',
     P.CAPACITY, fp.ZONE),
    ('OverconstrainedZonalAllocationRequest', '', P.CAPACITY, fp.ZONE),
    ('SkuNotAvailable', 'The requested VM size Standard_ND96asr is not '
     'available in the current region', P.CAPACITY, fp.REGION),
    ('AllocationFailed', '', P.CAPACITY, fp.REGION),
    ('OverconstrainedAllocationRequest', '', P.CAPACITY, fp.REGION),
    ('SpotEvictedNotAvailable', '', P.CAPACITY, fp.REGION),
    ('VMStartTimedOut', '', P.CAPACITY, fp.REGION),
    ('LowPriorityQuotaExceeded', '', P.QUOTA, fp.REGION),
    ('QuotaExceeded', 'Operation could not be completed as it results in '
     'exceeding approved quota', P.QUOTA, fp.REGION),
    ('OperationNotAllowed', 'Operation results in exceeding quota limits '
     'of Core', P.QUOTA, fp.REGION),
    ('ReadOnlyDisabledSubscription', 'The subscription is disabled',
     P.PERMISSION, fp.CLOUD),
    ('SubscriptionNotRegistered', '', P.PERMISSION, fp.CLOUD),
    ('SubscriptionNotFound', '', P.PERMISSION, fp.CLOUD),
    ('ResourcePurchaseValidationFailed', '', P.PERMISSION, fp.CLOUD),
    ('RequestDisallowedByPolicy', '', P.PERMISSION, fp.CLOUD),
    ('DisallowedProvider', '', P.PERMISSION, fp.CLOUD),
    ('AuthorizationFailed', 'The client does not have authorization',
     P.PERMISSION, fp.CLOUD),
    ('InvalidAuthenticationToken', '', P.PERMISSION, fp.CLOUD),
    ('ExpiredAuthenticationToken', '', P.PERMISSION, fp.CLOUD),
    ('ClientAuthenticationError', '', P.PERMISSION, fp.CLOUD),
    ('ProvisioningDisabled', '', P.PERMISSION, fp.REGION),
    ('ImageNotFound', '', P.CONFIG, fp.ABORT),
    ('InvalidTemplateDeployment', '', P.CONFIG, fp.ABORT),
    ('InvalidParameter', '', P.CONFIG, fp.ABORT),
    ('ResourceGroupNotFound', '', P.CONFIG, fp.REGION),
    ('VMMarketplaceInvalidInput', '', P.CONFIG, fp.ABORT),
    ('TooManyRequests', '', P.TRANSIENT, fp.ZONE),
    ('InternalServerError', '', P.TRANSIENT, fp.ZONE),
    ('GatewayTimeout', '', P.TRANSIENT, fp.ZONE),
]

_ALL = ([('gcp',) + c for c in GCP_CASES] +
        [('aws',) + c for c in AWS_CASES] +
        [('azure',) + c for c in AZURE_CASES])


@pytest.mark.parametrize('cloud,code,message,category,scope', _ALL,
                         ids=[f'{c[0]}-{c[1][:40]}-{i}'
                              for i, c in enumerate(_ALL)])
def test_pattern_classification(cloud, code, message, category, scope):
    pat = fp.classify(cloud, code, message)
    assert pat is not None, 'expected a table match'
    assert (pat.category, pat.scope) == (category, scope)


def test_real_gce_machine_type_text_stays_zone_scoped():
    """The REAL GCE 400 text prefixes the zone-coverage miss with
    'Invalid value for field ...' — the abort row must not shadow the
    zone row for it."""
    text = ("Invalid value for field 'resource.machineType': "
            "'zones/us-west1-a/machineTypes/a3-highgpu-8g'. "
            "Machine type a3-highgpu-8g does not exist in zone "
            "us-west1-a.")
    pat = fp.classify('gcp', '400', text)
    assert (pat.category, pat.scope) == (P.CONFIG, fp.ZONE)


def test_aws_resource_count_exceeded_is_transient():
    """ResourceCountExceeded is an API-side throttle, not quota — it
    must not region-block (ordering vs the *LimitExceeded catch-all)."""
    pat = fp.classify('aws', 'ResourceCountExceeded', '')
    assert (pat.category, pat.scope) == (P.TRANSIENT, fp.ZONE)


def test_minimum_pattern_breadth():
    """The library must keep >=20 distinct classified shapes per major
    cloud (VERDICT r3 item 3)."""
    assert len(fp.GCP_PATTERNS) >= 20
    assert len(fp.AWS_PATTERNS) >= 20
    assert len(fp.AZURE_PATTERNS) >= 20
    # And the cases above must actually exercise >=20 per cloud.
    assert len(GCP_CASES) >= 20
    assert len(AWS_CASES) >= 20
    assert len(AZURE_CASES) >= 20


def test_unknown_error_degrades_to_transient_zone():
    """Pattern misses fall to each cloud's PRODUCTION status-code
    fallback, which must walk on (transient/zone) for unknown shapes."""
    assert fp.classify('gcp', 'SOMETHING_NEW', 'never seen before') is None
    from skypilot_tpu.provision.aws import ec2_api
    from skypilot_tpu.provision.azure import arm_api
    from skypilot_tpu.provision.gcp import tpu_api
    for category, scope in (
            tpu_api._classify_error(500, 'SOMETHING_NEW'),
            ec2_api._classify_error('SomethingNew', 'never seen'),
            arm_api._classify_error('SomethingNew', 'never seen')):
        err = P('x', category=category, scope=scope)
        assert category == P.TRANSIENT
        assert not err.no_failover and not err.blocks_region \
            and not err.blocks_cloud


def test_scope_drives_error_flags():
    assert P('x', category=P.QUOTA, scope=fp.CLOUD).blocks_cloud
    assert P('x', category=P.CONFIG, scope=fp.REGION).blocks_region
    assert not P('x', category=P.CONFIG, scope=fp.REGION).no_failover
    assert P('x', category=P.CONFIG).no_failover  # default abort


def test_quota_body_with_resource_exhausted_status_region_blocks():
    """Real Google quota bodies carry status RESOURCE_EXHAUSTED next to
    the quota message — the quota row must win (region scope), not the
    bare capacity row."""
    body = ('{"error": {"code": 429, "message": "Quota '
            "'TPUSPerProjectPerRegion' exceeded. Limit: 32 in region "
            'europe-west4.", "status": "RESOURCE_EXHAUSTED"}}')
    pat = fp.classify('gcp', '429', body)
    assert (pat.category, pat.scope) == (P.QUOTA, fp.REGION)
    # The bare status with no quota text stays capacity/zone.
    pat = fp.classify('gcp', '429', 'RESOURCE_EXHAUSTED')
    assert (pat.category, pat.scope) == (P.CAPACITY, fp.ZONE)


K8S_CASES = [
    ('', '0/12 nodes are available: 12 Insufficient google.com/tpu. '
     'Unschedulable', P.CAPACITY, fp.ZONE),
    ('', 'FailedScheduling: No nodes are available', P.CAPACITY,
     fp.ZONE),
    ('', 'Pod was Evicted', P.CAPACITY, fp.ZONE),
    ('403', 'pods is forbidden: User cannot create resource',
     P.PERMISSION, fp.CLOUD),
    ('401', 'Unauthorized', P.PERMISSION, fp.CLOUD),
    ('403', 'exceeded quota: team-quota, requested: requests.cpu=64',
     P.QUOTA, fp.REGION),
    ('422', "Pod 'x' is invalid: spec.containers[0].image: "
     'Invalid value', P.CONFIG, fp.ABORT),
    ('400', 'admission webhook "policy.example.com" denied the request',
     P.CONFIG, fp.CLOUD),
    ('', 'Back-off pulling image: ImagePullBackOff', P.TRANSIENT,
     fp.ZONE),
    ('', 'InvalidImageName: invalid reference format', P.CONFIG,
     fp.ABORT),
    ('429', 'TooManyRequests: rate limited', P.TRANSIENT, fp.ZONE),
    ('500', 'etcdserver: request timed out', P.TRANSIENT, fp.ZONE),
]


@pytest.mark.parametrize('code,message,category,scope', K8S_CASES,
                         ids=[f'k8s-{i}' for i in range(len(K8S_CASES))])
def test_k8s_pattern_classification(code, message, category, scope):
    pat = fp.classify('kubernetes', code, message)
    assert pat is not None
    assert (pat.category, pat.scope) == (category, scope)
