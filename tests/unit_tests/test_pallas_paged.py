"""Fused pallas paged-attention + QKV LoRA kernels
(ops/pallas_paged.py), interpret mode on CPU.

Four contracts:

  - PARITY MATRIX: the interpret-mode kernel matches the XLA
    reference over {bf16-style, int8} x {GQA divisible, GQA
    remainder} x {decode S=1, chunk S>1} shapes, and the fused QKV
    LoRA kernel matches lora.apply_delta bit-for-bit;
  - NON-VACUITY: a deliberately perturbed kernel FAILS the same pin
    (the PR 15 collective-guard discipline — a pin that cannot fail
    proves nothing);
  - DISPATCH: resolve_impl's auto rules, the $SKYPILOT_TPU_PAGED_IMPL
    override, impl_scope, clean degradation to 'xla', and the
    module-level probe + unavailable_reason;
  - BIT IDENTITY end to end: an int8 + active-LoRA engine on the
    fused interpret path emits byte-identical greedy tokens to the
    XLA engine, and the mesh-sharded (tensor-2 host devices) kernel
    equals the unsharded kernel exactly.
"""
import os
import tempfile

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from skypilot_tpu.models import lora as lora_lib
from skypilot_tpu.ops import paged_attention as pa
from skypilot_tpu.ops import pallas_paged as pp

PAGE, PSEQ, TOTAL, D = 8, 4, 32, 16
ATOL = 1e-5


def _paged_inputs(batch, hkv, seed, quantized):
    """Random pool + a randomly-permuted page table (scattered
    physical pages — the layout the kernel must gather through)."""
    rng = np.random.default_rng(seed)
    perm = rng.permutation(TOTAL)
    tbl = jnp.asarray(perm[:batch * PSEQ].reshape(batch, PSEQ),
                      jnp.int32)
    shape = (hkv, TOTAL, PAGE, D)
    if quantized:
        k = jnp.asarray(rng.integers(-127, 128, shape), jnp.int8)
        v = jnp.asarray(rng.integers(-127, 128, shape), jnp.int8)
        ks = jnp.asarray(rng.random((TOTAL, PAGE)) * 0.02, jnp.float32)
        vs = jnp.asarray(rng.random((TOTAL, PAGE)) * 0.02, jnp.float32)
    else:
        k = jnp.asarray(rng.standard_normal(shape), jnp.float32)
        v = jnp.asarray(rng.standard_normal(shape), jnp.float32)
        ks = vs = None
    return tbl, k, v, ks, vs


# -- parity matrix: attention -----------------------------------------------
@pytest.mark.parametrize('quantized', [False, True],
                         ids=['bf16', 'int8'])
@pytest.mark.parametrize('hkv,hq', [(2, 4), (3, 6)],
                         ids=['gqa_divisible', 'gqa_remainder'])
def test_decode_parity(quantized, hkv, hq):
    batch = 4
    tbl, k, v, ks, vs = _paged_inputs(batch, hkv, 1, quantized)
    rng = np.random.default_rng(2)
    q = jnp.asarray(rng.standard_normal((batch, hq, D)), jnp.float32)
    lengths = jnp.asarray([1, 7, 20, 32], jnp.int32)  # cross-page mix
    ref = pa._reference_paged_attention(q, k, v, lengths, tbl,
                                        k_scales=ks, v_scales=vs)
    out = pp.fused_paged_attention(
        q[:, None], k, v, (lengths - 1)[:, None], tbl,
        k_scales=ks, v_scales=vs, interpret=True)[:, 0]
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=ATOL)


@pytest.mark.parametrize('quantized', [False, True],
                         ids=['bf16', 'int8'])
@pytest.mark.parametrize('hkv,hq', [(2, 4), (3, 6)],
                         ids=['gqa_divisible', 'gqa_remainder'])
def test_chunk_parity(quantized, hkv, hq):
    batch, chunk = 3, 5
    tbl, k, v, ks, vs = _paged_inputs(batch, hkv, 3, quantized)
    rng = np.random.default_rng(4)
    q = jnp.asarray(rng.standard_normal((batch, chunk, hq, D)),
                    jnp.float32)
    positions = jnp.asarray(
        rng.integers(0, PSEQ * PAGE, (batch, chunk)), jnp.int32)
    ref = pa.paged_chunk_attention(q, k, v, positions, tbl,
                                   k_scales=ks, v_scales=vs,
                                   impl='xla')
    out = pp.fused_paged_attention(q, k, v, positions, tbl,
                                   k_scales=ks, v_scales=vs,
                                   interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=ATOL)


def test_dispatch_entrypoints_route_to_fused():
    """paged_decode_attention / paged_chunk_attention themselves pick
    the fused kernel under impl='fused_interpret' (same numbers as the
    explicit call above — the integration llama/gpt decode uses)."""
    batch = 4
    tbl, k, v, ks, vs = _paged_inputs(batch, 2, 1, True)
    rng = np.random.default_rng(2)
    q = jnp.asarray(rng.standard_normal((batch, 4, D)), jnp.float32)
    lengths = jnp.asarray([1, 7, 20, 32], jnp.int32)
    ref = pa.paged_decode_attention(q, k, v, lengths, tbl,
                                    k_scales=ks, v_scales=vs,
                                    impl='xla')
    out = pa.paged_decode_attention(q, k, v, lengths, tbl,
                                    k_scales=ks, v_scales=vs,
                                    impl='fused_interpret')
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=ATOL)
    with pp.impl_scope('fused_interpret'):
        auto = pa.paged_decode_attention(q, k, v, lengths, tbl,
                                         k_scales=ks, v_scales=vs)
    np.testing.assert_allclose(np.asarray(auto), np.asarray(ref),
                               atol=ATOL)


def test_perturbed_kernel_fails_the_pin():
    """Non-vacuity control: a kernel with a deliberate temperature
    error must NOT pass the parity pin."""
    batch = 4
    tbl, k, v, ks, vs = _paged_inputs(batch, 2, 1, True)
    rng = np.random.default_rng(2)
    q = jnp.asarray(rng.standard_normal((batch, 4, D)), jnp.float32)
    lengths = jnp.asarray([1, 7, 20, 32], jnp.int32)
    ref = pa._reference_paged_attention(q, k, v, lengths, tbl,
                                        k_scales=ks, v_scales=vs)
    bad = pp.fused_paged_attention(
        q[:, None], k, v, (lengths - 1)[:, None], tbl,
        k_scales=ks, v_scales=vs, interpret=True, perturb=0.5)[:, 0]
    with pytest.raises(AssertionError):
        np.testing.assert_allclose(np.asarray(bad), np.asarray(ref),
                                   atol=ATOL)


# -- parity matrix: fused QKV LoRA ------------------------------------------
def test_fused_qkv_lora_matches_apply_delta():
    rng = np.random.default_rng(7)
    n_adapters, rank, d_model, batch, chunk = 4, 3, 32, 3, 5
    d_q, d_kv = 48, 24

    def factors(d_out):
        return {'a': jnp.asarray(rng.standard_normal(
                    (n_adapters, d_model, rank)) * 0.02, jnp.float32),
                'b': jnp.asarray(rng.standard_normal(
                    (n_adapters, rank, d_out)) * 0.02, jnp.float32)}

    fq, fk, fv = factors(d_q), factors(d_kv), factors(d_kv)
    x = jnp.asarray(rng.standard_normal((batch, chunk, d_model)),
                    jnp.float32)
    ids = jnp.asarray([0, 2, 3], jnp.int32)
    scale = jnp.asarray(2.0, jnp.float32)
    dq, dk, dv = pp.fused_qkv_lora_delta(x, fq, fk, fv, ids,
                                         interpret=True)
    for f, d in ((fq, dq), (fk, dk), (fv, dv)):
        y = jnp.zeros((batch, chunk, f['b'].shape[-1]), jnp.float32)
        want = lora_lib.apply_delta(y, x, f, ids, scale)
        got = y + (scale * d).astype(y.dtype)
        # Same contraction order in f32 -> exact, not just close.
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    assert pp.qkv_lora_dispatches_per_layer('fused_interpret') == 1
    assert pp.qkv_lora_dispatches_per_layer('xla') == 3


# -- dispatch resolution ----------------------------------------------------
def test_resolve_impl_cpu_rules():
    # CPU: no compiled kernel, upstream kernel TPU-only -> everything
    # degrades to 'xla' except the interpret route.
    assert pp.resolve_impl('auto', quantized=True) == 'xla'
    assert pp.resolve_impl('auto', quantized=False) == 'xla'
    assert pp.resolve_impl('kernel', quantized=False) == 'xla'
    assert pp.resolve_impl('kernel', quantized=True) == 'xla'
    assert pp.resolve_impl('fused', quantized=True) == 'xla'
    assert pp.resolve_impl('fused_interpret') == 'fused_interpret'
    with pytest.raises(ValueError):
        pp.resolve_impl('bogus')
    with pytest.raises(ValueError):
        pp.set_default_impl('bogus')


def test_env_and_scope_overrides(monkeypatch):
    monkeypatch.setenv(pp.ENV_VAR, 'fused_interpret')
    assert pp.resolve_impl('auto', quantized=True) == 'fused_interpret'
    monkeypatch.setenv(pp.ENV_VAR, 'nope')
    with pytest.raises(ValueError):
        pp.resolve_impl('auto')
    monkeypatch.delenv(pp.ENV_VAR)
    with pp.impl_scope('fused_interpret'):
        assert pp.resolve_impl('auto') == 'fused_interpret'
        assert pp.lora_fusion_impl() == 'fused_interpret'
    assert pp.default_impl() == 'auto'
    assert pp.lora_fusion_impl() is None


def test_probe_reports_why_kernel_is_off():
    """Module-level cached probe + recorded reason (the /stats
    storage field and skip-message source)."""
    assert pp.pallas_importable()
    assert not pp.available()            # CPU test environment
    reason = pp.unavailable_reason()
    assert reason is not None and 'fused_interpret' in reason
    assert pp.unavailable_reason() is reason or \
        pp.unavailable_reason() == reason       # stable across calls
    assert pa._pallas_paged_available() is False


def test_bytes_per_token_model_fused_beats_xla_at_int8():
    common = dict(num_layers=2, num_kv_heads=2, num_q_heads=4,
                  head_dim=16, page_size=8, pages_per_seq=4,
                  kv_elem_bytes=1, quantized=True, weight_bytes=1000,
                  batch=4, lora_bytes_per_row=64)
    xla = pp.bytes_per_token_model(impl='xla', **common)
    fused = pp.bytes_per_token_model(impl='fused_interpret', **common)
    assert xla['dequant_materialize_bytes'] > 0
    assert fused['dequant_materialize_bytes'] == 0
    assert (fused['total_bytes_per_token']
            < xla['total_bytes_per_token'])
    # Identical terms everywhere but the materialization:
    assert fused['kv_pool_bytes'] == xla['kv_pool_bytes']
    assert fused['kv_scale_bytes'] == xla['kv_scale_bytes']


# -- mesh-sharded bit identity (PR 15 harness: host-device mesh) ------------
def test_mesh_sharded_kernel_bit_identical():
    """tensor-2 mesh context -> the kernel shard_maps kv-heads over
    `tensor`; outputs must equal the unsharded kernel EXACTLY (each
    shard runs the identical per-head program)."""
    from skypilot_tpu.parallel import mesh as mesh_lib
    if len(jax.devices()) < 2:
        pytest.skip('needs >= 2 host devices')
    mesh = mesh_lib.make_mesh(mesh_lib.MeshConfig(tensor=2),
                              devices=jax.devices()[:2])
    batch = 3
    tbl, k, v, ks, vs = _paged_inputs(batch, 2, 11, True)
    rng = np.random.default_rng(12)
    q = jnp.asarray(rng.standard_normal((batch, 1, 4, D)), jnp.float32)
    pos = jnp.asarray([[0], [12], [31]], jnp.int32)
    ref = pp.fused_paged_attention(q, k, v, pos, tbl, k_scales=ks,
                                   v_scales=vs, interpret=True)
    with mesh:
        out = pp.fused_paged_attention(q, k, v, pos, tbl, k_scales=ks,
                                       v_scales=vs, interpret=True)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))
    # GQA remainder layout (3 kv heads, tensor=2): replicated pool ->
    # the unsharded path must be taken (and still be correct).
    tbl3, k3, v3, _, _ = _paged_inputs(batch, 3, 13, False)
    q3 = jnp.asarray(rng.standard_normal((batch, 1, 6, D)), jnp.float32)
    ref3 = pp.fused_paged_attention(q3, k3, v3, pos, tbl3,
                                    interpret=True)
    with mesh:
        out3 = pp.fused_paged_attention(q3, k3, v3, pos, tbl3,
                                        interpret=True)
    np.testing.assert_array_equal(np.asarray(out3), np.asarray(ref3))


# -- end-to-end engine bit identity (int8 KV + active LoRA) -----------------
SPEC = lora_lib.LoraSpec(rank=4, alpha=8.0)


@pytest.fixture(scope='module')
def int8_lora_setup():
    from skypilot_tpu.models.llama import Llama, LlamaConfig
    cfg = LlamaConfig.tiny(dtype=jnp.float32, kv_page_size=8,
                           kv_total_pages=40, kv_dtype='int8')
    model = Llama(cfg)
    params = nn.meta.unbox(model.init(
        jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))['params'])
    tmp = tempfile.mkdtemp(prefix='pallas_paged_lora_')
    for i in range(2):
        lp = lora_lib.random_adapter_params(i, cfg, SPEC)
        for layer in lp.values():          # default deltas are ~1e-3:
            for tgt in layer.values():     # amplify so adapters
                tgt['b'] *= 60.0           # actually flip greedy tokens
        lora_lib.save_adapter(os.path.join(tmp, f'ad{i}'), lp, SPEC,
                              base_model='llama-tiny')
    return model, params, tmp


def _greedy_tokens(model, params, adapter_dir, impl):
    from skypilot_tpu.inference.adapters import AdapterRegistry
    from skypilot_tpu.models.batching import ContinuousBatchingEngine
    with pp.impl_scope(impl):
        reg = AdapterRegistry(adapter_dir, model, max_adapters=4)
        eng = ContinuousBatchingEngine(model, params, num_slots=3,
                                       max_total_len=48,
                                       adapter_store=reg)
        assert eng.paged and eng.kv_dtype == 'int8'
        assert eng.attention_impl() == impl
        prompt = list(range(2, 18))
        futs = [eng.submit(prompt, max_new_tokens=6)]
        futs += [eng.submit(prompt, max_new_tokens=6,
                            adapter=f'ad{i}') for i in range(2)]
        out = [f.result(timeout=300) for f in futs]
        eng.stop()
        return out


def test_engine_greedy_bit_identity_int8_lora(int8_lora_setup):
    """The acceptance pin: fused interpret-mode engine == XLA engine,
    byte-identical greedy tokens, int8 KV + active multi-LoRA."""
    model, params, adapter_dir = int8_lora_setup
    fused = _greedy_tokens(model, params, adapter_dir,
                           'fused_interpret')
    xla = _greedy_tokens(model, params, adapter_dir, 'xla')
    assert fused == xla
    # Three genuinely different models in the round (base + 2
    # adapters) — identity is not vacuous agreement on one stream.
    assert len({tuple(t) for t in fused}) == 3
