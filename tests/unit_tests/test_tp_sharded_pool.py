"""Mesh-sharded KV page pool (parallel/serving.py + the engine's
explicit dispatch shardings, PR 15).

The contracts under test:

  - placement: paged pool values shard their kv-heads axis over
    `tensor`, scale pages and unknown leaves replicate, and the GQA
    remainder rule replicates when heads don't divide;
  - capacity: a per-chip --kv-pool-bytes budget buys ~shard_ways
    more pages (int8 slightly less — scales replicate);
  - zero resharding: the compiled decode step contains NO
    all-gather/all-to-all over a pool-shaped operand (the guard that
    keeps N-chip serving from silently re-materializing the pool
    every token), and the guard itself detects forced violations;
  - bit identity: the sharded engine's greedy outputs equal
    single-device across paged bf16, int8 KV, int8 weights, LoRA,
    speculative, and chunked decode;
  - handoff: a chain exported from a tensor-2 pool imports into a
    single-device pool (and back) with byte-identical re-export.
"""
import dataclasses

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from skypilot_tpu.inference import kv_transfer, quant
from skypilot_tpu.models.batching import ContinuousBatchingEngine
from skypilot_tpu.models.llama import Llama, LlamaConfig
from skypilot_tpu.parallel import mesh as mesh_lib
from skypilot_tpu.parallel.serving import (
    kv_shard_ways, pool_collective_lines, serving_cache_shardings,
    shard_params_for_serving)


@pytest.fixture(scope='module')
def setup():
    cfg = LlamaConfig.tiny(dtype=jnp.float32, kv_page_size=8,
                           kv_total_pages=40)
    model = Llama(cfg)
    params = nn.meta.unbox(model.init(
        jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))['params'])
    mesh = mesh_lib.make_mesh(mesh_lib.MeshConfig(tensor=2),
                              devices=jax.devices()[:2])
    return model, params, mesh


# -- placement rules --------------------------------------------------------
def test_kv_shard_ways_gqa_remainder():
    assert kv_shard_ways(2, 2) == 2
    assert kv_shard_ways(8, 4) == 4
    assert kv_shard_ways(2, 4) == 1     # remainder -> replicate
    assert kv_shard_ways(3, 2) == 1
    assert kv_shard_ways(0, 2) == 1     # MLA: no kv-heads axis
    assert kv_shard_ways(4, 1) == 1     # single device


def test_cache_shardings_layout(setup):
    _, _, mesh = setup
    cache = {'layers_0': {'attn': {
        'k_pages': jnp.zeros((2, 40, 8, 32), jnp.float32),
        'v_pages': jnp.zeros((2, 40, 8, 32), jnp.float32),
        'k_scales': jnp.zeros((40, 8), jnp.float32),
        'cached_key': jnp.zeros((2, 48, 2, 32), jnp.float32),
        'cache_index': jnp.zeros((2,), jnp.int32),
    }}}
    sh = serving_cache_shardings(cache, mesh)
    attn = sh['layers_0']['attn']
    assert attn['k_pages'].spec == P('tensor')
    assert attn['v_pages'].spec == P('tensor')
    assert attn['k_scales'].spec == P()         # scales replicate
    assert attn['cached_key'].spec == P(None, None, 'tensor')
    assert attn['cache_index'].spec == P()      # unknown leaves too


def test_cache_shardings_replicate_on_remainder():
    """2 kv heads over tensor=4: the pool replicates (all-or-nothing
    axis split), it never half-shards."""
    mesh = mesh_lib.make_mesh(mesh_lib.MeshConfig(tensor=4),
                              devices=jax.devices()[:4])
    cache = {'attn': {'k_pages': jnp.zeros((2, 40, 8, 32),
                                           jnp.float32)}}
    sh = serving_cache_shardings(cache, mesh)
    assert sh['attn']['k_pages'].spec == P()


# -- per-chip capacity math -------------------------------------------------
def test_page_bytes_per_chip():
    cfg = LlamaConfig.tiny(dtype=jnp.bfloat16, kv_page_size=16,
                           kv_total_pages=64)
    # bf16: value bytes halve exactly -> same budget buys 2x pages.
    assert quant.kv_page_bytes(cfg, 'bf16', 2) * 2 == \
        quant.kv_page_bytes(cfg, 'bf16', 1)
    budget = 1 << 20
    assert quant.pool_pages_for_bytes(cfg, 'bf16', budget, 2) == \
        2 * quant.pool_pages_for_bytes(cfg, 'bf16', budget, 1)
    # int8: scale rows replicate, so the per-chip page is MORE than
    # half a full page (ratio strictly < 2x).
    full = quant.kv_page_bytes(cfg, 'int8', 1)
    half = quant.kv_page_bytes(cfg, 'int8', 2)
    assert full // 2 < half < full
    # The GQA remainder rule is the caller's job: a non-dividing
    # shard request is a bug, not a rounding.
    with pytest.raises(ValueError):
        quant.kv_page_bytes(cfg, 'bf16', 3)


# -- the zero-resharding guard ----------------------------------------------
def test_decode_step_has_no_pool_resharding(setup):
    """Tier-1 guard: compile ONE decode step of the sharded engine
    and fail on any pool-shaped all-gather/all-to-all. This is the
    compiled-HLO proof that the donated cache's explicit
    out_shardings keep the pool in place step over step."""
    model, params, mesh = setup
    tp = shard_params_for_serving(model, params, mesh)
    eng = ContinuousBatchingEngine(model, tp, num_slots=2,
                                   max_total_len=48, mesh=mesh)
    try:
        assert eng.kv_shard_ways == 2
        z = jnp.zeros((2,), jnp.int32)
        zf = jnp.zeros((2,), jnp.float32)
        of = jnp.ones((2,), jnp.float32)
        pt = jnp.zeros((2, eng.pages_per_seq), jnp.int32)
        compiled = eng._decode.lower(  # pylint: disable=protected-access
            eng.params, eng.cache, z, z, zf, z, of,
            jax.random.PRNGKey(0), pt).compile()
        assert pool_collective_lines(compiled, eng.cache, mesh) == []
    finally:
        eng.stop()


def test_pool_guard_detects_forced_reshard(setup):
    """The guard is not vacuous: forcing the pool off its sharding
    (replicate = all-gather; axis move = all-to-all, whose per-shard
    chunks are size/ways^2) is detected."""
    _, _, mesh = setup
    cache = {'attn': {'k_pages': jnp.zeros((2, 40, 8, 32),
                                           jnp.float32)}}
    sh = serving_cache_shardings(cache, mesh)
    pinned = jax.device_put(cache, sh)

    def bump(c):
        return jax.tree.map(lambda x: x + 1.0, c)

    for forced in (P(), P(None, 'tensor')):
        bad_sh = jax.tree.map(
            lambda s, f=forced: NamedSharding(mesh, f), sh)
        bad = jax.jit(bump, out_shardings=bad_sh).lower(
            pinned).compile()
        assert pool_collective_lines(bad, cache, mesh)
    good = jax.jit(bump, out_shardings=sh).lower(pinned).compile()
    assert pool_collective_lines(good, cache, mesh) == []


# -- bit identity single-device vs sharded ----------------------------------
PROMPTS = ([5, 9, 2, 17], [30, 31, 32], [5, 9, 2, 17, 40])


def _run_engine(model, params, prompts, *, mesh=None, n=8, **kw):
    eng = ContinuousBatchingEngine(model, params, num_slots=2,
                                   max_total_len=48, mesh=mesh, **kw)
    try:
        assert (eng.kv_shard_ways == 2) == (mesh is not None)
        return [eng.submit(list(p), max_new_tokens=n).result(
            timeout=300) for p in prompts]
    finally:
        eng.stop()


@pytest.mark.slow
@pytest.mark.parametrize('variant', ['bf16', 'dense', 'int8kv',
                                     'chunk', 'spec'])
def test_sharded_engine_bit_identical(setup, variant):
    """Greedy outputs off the head-sharded pool equal single-device,
    across storage formats and decode modes."""
    model, params, mesh = setup
    kw = {}
    prompts = PROMPTS
    if variant == 'int8kv':
        cfg = dataclasses.replace(model.config, kv_dtype='int8')
        model = Llama(cfg)
    elif variant == 'dense':
        # The per-slot dense cache shards its kv-heads axis (axis 2)
        # the same way the pool does.
        kw['paged'] = False
    elif variant == 'chunk':
        kw['decode_chunk'] = 4
    elif variant == 'spec':
        kw['speculative_k'] = 3
        # Repetitive prompts: the regime prompt-lookup actually
        # drafts in (correctness must hold either way).
        prompts = ([5, 9, 2, 5, 9, 2, 5, 9], [30, 31, 30, 31, 30])
    tp = shard_params_for_serving(model, params, mesh)
    ref = _run_engine(model, params, prompts, **kw)
    got = _run_engine(model, tp, prompts, mesh=mesh, **kw)
    assert got == ref


@pytest.mark.slow
def test_sharded_engine_int8_weights_bit_identical(setup):
    """int8 per-channel weights + sharded pool == the same quantized
    model on one device (scales shard with their output channel)."""
    model, params, mesh = setup
    qparams = quant.quantize_params(params)
    qmodel = quant.QuantizedModel(model)
    qtp = quant.shard_quantized_for_serving(qmodel, qparams, mesh)
    ref = _run_engine(qmodel, qparams, PROMPTS)
    got = _run_engine(qmodel, qtp, PROMPTS, mesh=mesh)
    assert got == ref


@pytest.mark.slow
def test_sharded_engine_lora_bit_identical(setup, tmp_path):
    """An active adapter rides the sharded engine unchanged: the
    replicated factor store gathers per-slot rows without touching
    the pool's sharding."""
    from skypilot_tpu.inference.adapters import AdapterRegistry
    from skypilot_tpu.models import lora as lora_lib
    model, params, mesh = setup
    spec = lora_lib.LoraSpec(rank=4, alpha=8.0)
    lp = lora_lib.random_adapter_params(0, model.config, spec)
    lora_lib.save_adapter(str(tmp_path / 'ad0'), lp, spec,
                          base_model='llama-tiny')
    tp = shard_params_for_serving(model, params, mesh)

    def run(engine_params, eng_mesh):
        reg = AdapterRegistry(str(tmp_path), model, max_adapters=2,
                              mesh=eng_mesh)
        eng = ContinuousBatchingEngine(model, engine_params,
                                       num_slots=2, max_total_len=48,
                                       adapter_store=reg,
                                       mesh=eng_mesh)
        try:
            return [eng.submit(list(p), max_new_tokens=8,
                               adapter='ad0').result(timeout=300)
                    for p in PROMPTS]
        finally:
            eng.stop()

    assert run(tp, mesh) == run(params, None)


# -- cross-mesh chain handoff -----------------------------------------------
def _wire_payload(data: bytes) -> bytes:
    off = len(kv_transfer.MAGIC)
    hlen = int.from_bytes(data[off:off + 8], 'big')
    return data[off + 8 + hlen:]


@pytest.mark.slow
def test_export_import_across_mesh_sizes(setup):
    """A chain exported from a tensor-2 sharded pool (blobs carry
    GLOBAL page rows) imports into a single-device pool, serves
    bit-identically, and re-exports byte-identical payload bytes —
    the disaggregated-handoff contract across mesh sizes."""
    model, params, mesh = setup
    prompt = list(range(2, 34))      # 4 full 8-token pages
    tp = shard_params_for_serving(model, params, mesh)
    src = ContinuousBatchingEngine(model, tp, num_slots=2,
                                   max_total_len=48, mesh=mesh)
    dst = ContinuousBatchingEngine(model, params, num_slots=2,
                                   max_total_len=48)
    try:
        ref = src.submit(prompt, max_new_tokens=8).result(timeout=300)
        data = src.export_chain(prompt)
        assert data is not None
        meta, _ = kv_transfer.unpack_pages(data)
        # The header records kv-head geometry for cross-mesh import
        # validation (PR-13 payloads lack it and still import).
        assert meta['num_kv_heads'] == model.config.num_kv_heads
        assert meta['head_dim'] == model.config.head_dim
        summary = dst.import_chain(data)
        assert summary['imported'] == 4 and summary['dropped'] == 0
        out = dst.submit(prompt, max_new_tokens=8).result(timeout=300)
        assert out == ref
        data2 = dst.export_chain(prompt)
        assert _wire_payload(data2) == _wire_payload(data)
    finally:
        src.stop()
        dst.stop()
