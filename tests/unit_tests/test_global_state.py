"""Cluster state CRUD with isolated home dir."""
from skypilot_tpu.utils.status_lib import ClusterStatus


class FakeHandle:
    def __init__(self):
        self.cluster_name = 'c'
        self.launched_nodes = 2
        self.launched_resources = None


def test_cluster_lifecycle(isolated_state):
    from skypilot_tpu import global_state
    handle = FakeHandle()
    global_state.add_or_update_cluster('c1', handle, ready=False)
    assert global_state.get_cluster_status('c1') == ClusterStatus.INIT
    global_state.add_or_update_cluster('c1', handle, is_launch=False,
                                       ready=True)
    assert global_state.get_cluster_status('c1') == ClusterStatus.UP
    h = global_state.get_handle_from_cluster_name('c1')
    assert h.launched_nodes == 2

    global_state.set_cluster_autostop('c1', 10, True)
    row = global_state.get_cluster('c1')
    assert row['autostop_minutes'] == 10 and row['autostop_down'] == 1

    events = global_state.get_cluster_events('c1')
    assert events and events[0]['event_type'] == 'launched'

    global_state.remove_cluster('c1', terminate=False)
    assert global_state.get_cluster_status('c1') == ClusterStatus.STOPPED

    global_state.remove_cluster('c1', terminate=True)
    assert global_state.get_cluster('c1') is None
    hist = global_state.get_cluster_history()
    assert hist and hist[0]['name'] == 'c1'


def test_storage_and_config(isolated_state):
    from skypilot_tpu import global_state
    global_state.add_or_update_storage('bkt', {'url': 'gs://bkt'}, 'READY')
    assert global_state.get_storage('bkt')['handle'] == {'url': 'gs://bkt'}
    assert global_state.get_storage_names() == ['bkt']
    global_state.remove_storage('bkt')
    assert global_state.get_storage('bkt') is None

    assert global_state.get_system_config('k', 'd') == 'd'
    global_state.set_system_config('k', 'v1')
    global_state.set_system_config('k', 'v2')
    assert global_state.get_system_config('k') == 'v2'
