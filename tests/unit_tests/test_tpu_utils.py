"""Slice topology math: the TPU-first core must get host counts right."""
import pytest

from skypilot_tpu.utils import tpu_utils


@pytest.mark.parametrize(
    'name,chips,hosts,cph',
    [
        ('tpu-v5e-1', 1, 1, 1),
        ('tpu-v5e-4', 4, 1, 4),
        ('tpu-v5e-8', 8, 1, 8),
        ('tpu-v5e-16', 16, 2, 8),
        ('tpu-v5e-256', 256, 32, 8),
        ('tpu-v5p-8', 4, 1, 4),
        ('tpu-v5p-64', 32, 8, 4),
        ('tpu-v5p-128', 64, 16, 4),
        ('tpu-v5p-2048', 1024, 256, 4),
        ('tpu-v4-8', 4, 1, 4),
        ('tpu-v6e-8', 8, 1, 8),
        ('tpu-v6e-256', 256, 32, 8),
        ('tpu-v2-8', 4, 1, 4),
    ])
def test_slice_math(name, chips, hosts, cph):
    spec = tpu_utils.get_slice_spec(name)
    assert spec.num_chips == chips
    assert spec.num_hosts == hosts
    assert spec.chips_per_host == cph
    assert spec.is_pod_slice == (hosts > 1)


def test_topology_product_matches_chips():
    for name in ('tpu-v5e-16', 'tpu-v5p-128', 'tpu-v6e-64', 'tpu-v4-512'):
        spec = tpu_utils.get_slice_spec(name)
        prod = 1
        for d in spec.topology:
            prod *= d
        assert prod == spec.num_chips, (name, spec.topology)


def test_explicit_topology():
    spec = tpu_utils.get_slice_spec('tpu-v5p-128', topology='4x4x4')
    assert spec.topology == (4, 4, 4)
    with pytest.raises(ValueError):
        tpu_utils.get_slice_spec('tpu-v5p-128', topology='4x4x2')


def test_gcp_accelerator_type_naming():
    assert tpu_utils.get_slice_spec(
        'tpu-v5e-16').gcp_accelerator_type() == 'v5litepod-16'
    assert tpu_utils.get_slice_spec(
        'tpu-v5p-128').gcp_accelerator_type() == 'v5p-128'
    assert tpu_utils.get_slice_spec(
        'tpu-v6e-8').gcp_accelerator_type() == 'v6e-8'


def test_is_tpu():
    assert tpu_utils.is_tpu('tpu-v5e-8')
    assert not tpu_utils.is_tpu('A100')
    assert not tpu_utils.is_tpu(None)
    assert not tpu_utils.is_tpu('tpu-v5e')  # missing size


def test_bad_names():
    with pytest.raises(ValueError):
        tpu_utils.get_slice_spec('A100')
    with pytest.raises(ValueError):
        tpu_utils.parse_tpu_name('tpu-v99-8')
