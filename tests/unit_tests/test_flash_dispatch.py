"""Flash-attention dispatch under sharded meshes.

The pallas kernel itself is TPU-only; these tests inject a plain
attention kernel into `_flash` to validate the GSPMD-safety wrapper:
on a multi-device mesh the kernel must run under shard_map (batch over
data/fsdp, heads over tensor) and match the XLA reference exactly.
"""
import jax
import jax.numpy as jnp
import numpy as np

from skypilot_tpu.ops import attention as attn
from skypilot_tpu.parallel import mesh as mesh_lib


def _plain_kernel(q, k, v, causal):
    return jax.nn.dot_product_attention(q, k, v, is_causal=causal)


def _rand_qkv(batch=8, seq=64, heads=4, dim=16):
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    shape = (batch, seq, heads, dim)
    return tuple(jax.random.normal(k, shape, jnp.float32) for k in ks)


def test_flash_shard_map_matches_reference():
    mesh = mesh_lib.make_mesh(mesh_lib.MeshConfig(data=2, fsdp=2,
                                                  tensor=2))
    q, k, v = _rand_qkv()
    ref = _plain_kernel(q, k, v, True)
    with mesh:
        out = attn._flash(q, k, v, causal=True, kernel=_plain_kernel)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5)


def test_flash_shard_map_inside_jit_sharded():
    """The real usage: inside jit with sharded operands."""
    mesh = mesh_lib.make_mesh(mesh_lib.MeshConfig(data=2, fsdp=2,
                                                  tensor=2))
    q, k, v = _rand_qkv()
    ref = _plain_kernel(q, k, v, True)

    def f(q, k, v):
        return attn._flash(q, k, v, causal=True, kernel=_plain_kernel)

    with mesh:
        sharded = tuple(
            jax.device_put(
                x, jax.sharding.NamedSharding(
                    mesh, jax.sharding.PartitionSpec(
                        ('data', 'fsdp'), None, 'tensor', None)))
            for x in (q, k, v))
        out = jax.jit(f)(*sharded)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5)


def test_flash_gqa_expansion_under_mesh():
    mesh = mesh_lib.make_mesh(mesh_lib.MeshConfig(data=2, fsdp=4))
    q, _, _ = _rand_qkv(heads=4)
    _, k, v = _rand_qkv(heads=4)
    k2, v2 = k[:, :, :2], v[:, :, :2]  # 2 kv heads for 4 q heads
    k_exp = jnp.repeat(k2, 2, axis=2)
    v_exp = jnp.repeat(v2, 2, axis=2)
    ref = _plain_kernel(q, k_exp, v_exp, True)
    with mesh:
        out = attn._flash(q, k2, v2, causal=True, kernel=_plain_kernel)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5)


def test_flash_falls_back_when_batch_indivisible():
    """Batch 3 can't split over 8 shards: _flash must signal fallback
    (None) instead of crashing in shard_map."""
    mesh = mesh_lib.make_mesh(mesh_lib.MeshConfig(data=2, fsdp=4))
    q, k, v = _rand_qkv(batch=3)
    with mesh:
        assert attn._flash(q, k, v, causal=True,
                           kernel=_plain_kernel) is None


def test_flash_no_mesh_runs_kernel_directly():
    q, k, v = _rand_qkv(batch=2)
    calls = []

    def spy_kernel(q, k, v, causal):
        calls.append('direct')
        return _plain_kernel(q, k, v, causal)

    out = attn._flash(q, k, v, causal=False, kernel=spy_kernel)
    assert calls == ['direct']
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(_plain_kernel(q, k, v, False)),
                               atol=1e-6)
