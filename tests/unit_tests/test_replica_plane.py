"""Replica plane: affinity keys, engine-metrics autoscaling,
replica manager + drain-before-kill ordering, chaos (replica death
mid-stream -> LB reroute -> autoscaler replacement), and the
serve_bench fleet smoke.

Everything here is tier-1: replicas are in-process stubs
(serve/replica_plane/stub.py) or fake handles with injected scrapes;
the slow e2e in tests/test_serve.py repeats the chaos loop on real
serve_lm processes.
"""
import json
import os
import subprocess
import sys
import threading
import time

import pytest
import requests

from skypilot_tpu.inference import affinity
from skypilot_tpu.serve import autoscalers
from skypilot_tpu.serve import load_balancing_policies as lbp
from skypilot_tpu.serve import serve_state
from skypilot_tpu.serve import spot_placer
from skypilot_tpu.serve.replica_plane import (FleetController,
                                              ReplicaManager,
                                              make_lb_server)
from skypilot_tpu.serve.replica_plane import replica_manager as rm
from skypilot_tpu.serve.replica_plane.stub import (
    InProcessStubReplica, in_process_stub_factory)
from skypilot_tpu.serve.service_spec import SkyServiceSpec

REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))

UP = autoscalers.AutoscalerDecisionOperator.SCALE_UP
DOWN = autoscalers.AutoscalerDecisionOperator.SCALE_DOWN
NO_OP = autoscalers.AutoscalerDecisionOperator.NO_OP


# ---------------------------------------------------------------------------
# affinity keys
# ---------------------------------------------------------------------------
def test_chain_key_parity_with_engine_prefix_cache():
    """The LB-side chain hash must be byte-identical to the engine's
    (same pages -> same keys -> affinity routes to the replica that
    really holds them)."""
    from skypilot_tpu.models.batching import PrefixCache
    tokens = list(range(7, 7 + 57))
    for page_size in (8, 16):
        assert affinity.chain_keys(tokens, page_size) == \
            PrefixCache.chain_keys(tokens, page_size)
    assert affinity.chain_keys([1, 2, 3], 16) == []


def test_token_affinity_key_first_full_page():
    prefix = list(range(100, 116))  # exactly one 16-token page
    k1 = affinity.token_affinity_key(prefix + [1, 2, 3])
    k2 = affinity.token_affinity_key(prefix + [9, 9, 9, 9])
    assert k1 == k2 and k1 is not None
    # Different first page -> different key.
    assert affinity.token_affinity_key(
        [0] + prefix[1:] + [1]) != k1
    # No full page -> no key (caller falls back to load routing).
    assert affinity.token_affinity_key(prefix[:15]) is None


def test_request_affinity_key_per_endpoint():
    page = list(range(16))
    assert affinity.request_affinity_key(
        '/generate', {'tokens': [page + [5]]}) == \
        affinity.request_affinity_key(
            '/generate', {'tokens': [page + [6, 7]]})
    shared = 'You are a helpful assistant. ' * 10
    assert affinity.request_affinity_key(
        '/v1/completions', {'prompt': shared + 'user A'}) == \
        affinity.request_affinity_key(
            '/v1/completions', {'prompt': shared + 'user B'})
    chat_a = {'messages': [{'role': 'system', 'content': shared},
                           {'role': 'user', 'content': 'hi'}]}
    chat_b = {'messages': [{'role': 'system', 'content': shared},
                           {'role': 'user', 'content': 'bye'}]}
    assert affinity.request_affinity_key(
        '/v1/chat/completions', chat_a) == \
        affinity.request_affinity_key('/v1/chat/completions', chat_b)
    # Malformed bodies: keyless, never raising.
    assert affinity.request_affinity_key(
        '/generate', {'tokens': 'nope'}) is None
    assert affinity.request_affinity_key('/unknown', {}) is None


# ---------------------------------------------------------------------------
# clock-injectable autoscalers (satellite: no bare time.time left)
# ---------------------------------------------------------------------------
class _FakeClock:

    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


def _spec(**kw):
    kw.setdefault('min_replicas', 1)
    kw.setdefault('max_replicas', 5)
    kw.setdefault('upscale_delay_seconds', 10)
    kw.setdefault('downscale_delay_seconds', 20)
    return SkyServiceSpec(**kw)


def test_all_autoscalers_run_on_injected_clock():
    """Every scaler accepts `clock` and never consults the wall
    clock when one is injected — decisions move ONLY when the fake
    clock does."""
    clock = _FakeClock()
    scalers = [
        autoscalers.Autoscaler(_spec(), clock),
        autoscalers.RequestRateAutoscaler(
            _spec(target_qps_per_replica=2.0), clock),
        autoscalers.QueueLengthAutoscaler(_spec(), clock=clock),
        autoscalers.SpotRequestRateAutoscaler(
            _spec(target_qps_per_replica=2.0), clock),
        autoscalers.InstanceAwareRequestRateAutoscaler(
            _spec(target_qps_per_replica={'v5e': 2.0}), clock),
        autoscalers.EngineMetricsAutoscaler(_spec(), clock),
    ]
    for scaler in scalers:
        scaler.collect_request_information(600)  # timestamp = clock
        d = scaler.evaluate(1, 0)                # now = clock
        assert isinstance(d, autoscalers.AutoscalerDecision)

    rate = scalers[1]
    # 600 requests in-window = 10 qps -> desired 5; the wall clock
    # advancing (real time passing while this test runs) must not
    # commit it — only the fake clock can.
    assert rate.target_num_replicas == 1
    clock.t += 11
    rate.evaluate(1, 0)
    assert rate.target_num_replicas == 5


def test_queue_length_autoscaler_hysteresis_on_clock():
    clock = _FakeClock()
    a = autoscalers.QueueLengthAutoscaler(
        _spec(), target_queue_per_replica=2, clock=clock)
    a.collect_request_information(8)  # 8 in-flight -> desired 4
    a.evaluate(1, 0)
    assert a.target_num_replicas == 1
    clock.t += 10
    d = a.evaluate(1, 0)
    assert a.target_num_replicas == 4 and d.operator == UP


def test_spot_placer_preemption_now_injectable():
    loc = ('gcp', 'us-east5', 'us-east5-b')
    placer = spot_placer.DynamicFallbackSpotPlacer([loc])
    placer.handle_preemption(loc, now=1000.0)
    assert placer._last_preempted[loc] == 1000.0
    assert placer.all_hot(now=1000.0 + 60)
    assert not placer.all_hot(now=1000.0 + 31 * 60)


# ---------------------------------------------------------------------------
# EngineMetricsAutoscaler
# ---------------------------------------------------------------------------
def test_engine_metrics_scales_up_on_backlog_pressure():
    a = autoscalers.EngineMetricsAutoscaler(_spec())
    t = 1000.0
    a.observe('r1', prefill_backlog_tokens=16000, now=t)
    d = a.evaluate(1, 0, now=t)
    assert d.operator == NO_OP  # upscale delay not yet passed
    a.observe('r1', prefill_backlog_tokens=16000, now=t + 11)
    d = a.evaluate(1, 0, now=t + 11)
    # 16000 tokens / 4096 per replica -> 4.
    assert d.operator == UP and d.target_num_replicas == 4


def test_engine_metrics_scales_up_on_queue_depth():
    a = autoscalers.EngineMetricsAutoscaler(
        _spec(), target_queue_per_replica=4.0)
    t = 0.0
    for ep in ('r1', 'r2'):
        a.observe(ep, queue_depth=10, now=t)
    a.evaluate(2, 0, now=t)  # candidate starts here
    d = a.evaluate(2, 0, now=t + 11)
    assert d.operator == UP and d.target_num_replicas == 5  # ceil(20/4)


def test_engine_metrics_shed_rate_forces_growth():
    """A bounded queue caps queue_depth exactly when pressure is
    worst; the shed counter is the overflow signal — any sheds in
    the window demand a replica above the live fleet."""
    a = autoscalers.EngineMetricsAutoscaler(_spec())
    t = 0.0
    a.observe('r1', queue_depth=2, requests_shed_total=0, now=t)
    assert a.evaluate(1, 0, now=t).operator == NO_OP
    a.observe('r1', queue_depth=2, requests_shed_total=7, now=t + 5)
    assert a.shed_rate(now=t + 5) > 0
    a.evaluate(1, 0, now=t + 5)       # upscale candidate starts
    d = a.evaluate(1, 0, now=t + 16)  # persisted past upscale delay
    assert d.operator == UP and d.target_num_replicas == 2
    # Sheds stop -> the window drains -> rate returns to 0.
    a.observe('r1', queue_depth=0, requests_shed_total=7, now=t + 20)
    assert a.shed_rate(now=t + 90) == 0.0


def test_engine_metrics_shed_counter_reset_tolerated():
    """A replica restart resets its lifetime counter; the delta must
    not go negative or spuriously fire."""
    a = autoscalers.EngineMetricsAutoscaler(_spec())
    a.observe('r1', requests_shed_total=50, now=0.0)
    a.observe('r1', requests_shed_total=3, now=1.0)  # restarted
    assert a.shed_rate(now=1.0) == 0.0


def test_engine_metrics_scales_down_after_pressure_drops():
    a = autoscalers.EngineMetricsAutoscaler(_spec())
    t = 0.0
    a.observe('r1', prefill_backlog_tokens=16000, now=t)
    a.evaluate(1, 0, now=t)  # upscale candidate starts
    a.evaluate(1, 0, now=t + 11)
    assert a.target_num_replicas == 4
    # Pressure gone: desired falls to min, but only after the
    # downscale delay persists.
    a.observe('r1', prefill_backlog_tokens=0, now=t + 30)
    d = a.evaluate(4, 0, now=t + 30)
    assert d.operator == NO_OP
    d = a.evaluate(4, 0, now=t + 51)
    assert d.operator == DOWN and d.target_num_replicas == 1


def test_engine_metrics_forget_drops_dead_replica_signals():
    a = autoscalers.EngineMetricsAutoscaler(_spec())
    a.observe('r1', prefill_backlog_tokens=16000, now=0.0)
    a.forget('r1')
    assert a.total_backlog_tokens() == 0
    d = a.evaluate(1, 0, now=100.0)
    assert d.operator == NO_OP


def test_engine_metrics_selected_by_make():
    spec = _spec(autoscaler='engine_metrics')
    a = autoscalers.Autoscaler.make(spec)
    assert isinstance(a, autoscalers.EngineMetricsAutoscaler)


# ---------------------------------------------------------------------------
# replica manager (fake handles + injected scrapes)
# ---------------------------------------------------------------------------
class FakeProc:

    def __init__(self, on_sigterm=None):
        self.rc = None
        self.signals = []
        self._on_sigterm = on_sigterm

    def poll(self):
        return self.rc

    def send_signal(self, sig):
        self.signals.append(sig)
        if self._on_sigterm is not None:
            self._on_sigterm(self)

    def terminate(self):
        self.send_signal(15)

    def kill(self):
        self.rc = -9

    def wait(self, timeout=None):
        return self.rc


class FakeScrapes:
    """Injected http_get: endpoint -> (ready, stats) table; endpoints
    not in the table raise (unreachable)."""

    def __init__(self):
        self.table = {}

    def set(self, endpoint, ready=True, **stats):
        self.table[endpoint] = (ready, stats)

    def __call__(self, url, timeout):
        host = url.split('//')[1].split('/')[0]
        if host not in self.table:
            raise ConnectionError(f'unreachable {host}')
        ready, stats = self.table[host]
        if url.endswith('/readyz'):
            return (200 if ready else 503), {'ready': ready}
        return 200, stats


def _manager(scrapes, on_sigterm=None, **kw):
    events = []
    mgr = ReplicaManager(
        lambda rid, port: FakeProc(on_sigterm=on_sigterm),
        http_get=scrapes,
        on_event=lambda name, view: events.append(
            (name, view.replica_id)),
        **kw)
    return mgr, events


def test_manager_spawn_scrape_ready_cycle():
    scrapes = FakeScrapes()
    mgr, events = _manager(scrapes)
    view = mgr.spawn()
    assert view.state == serve_state.ReplicaStatus.STARTING
    scrapes.set(view.endpoint, ready=True, queued=3,
                prefill_backlog_tokens=700, requests_shed=2,
                healthy=True,
                prefix_cache={'hits': 10, 'misses': 5})
    mgr.scrape_once()
    assert view.state == serve_state.ReplicaStatus.READY
    assert view.queue_depth == 3
    assert view.prefill_backlog_tokens == 700
    assert view.requests_shed_total == 2
    assert view.prefix_hits == 10 and view.prefix_misses == 5
    assert mgr.ready_endpoints() == [view.endpoint]
    assert ('ready', view.replica_id) in events


def test_manager_consecutive_scrape_failures_mark_not_ready():
    scrapes = FakeScrapes()
    mgr, events = _manager(scrapes, max_scrape_failures=3)
    view = mgr.spawn()
    scrapes.set(view.endpoint, ready=True)
    mgr.scrape_once()
    assert view.state == serve_state.ReplicaStatus.READY
    del scrapes.table[view.endpoint]  # now unreachable
    mgr.scrape_once()
    mgr.scrape_once()
    assert view.state == serve_state.ReplicaStatus.READY  # <3 strikes
    mgr.scrape_once()
    assert view.state == serve_state.ReplicaStatus.NOT_READY
    assert mgr.ready_endpoints() == []


def test_manager_process_exit_marks_failed():
    scrapes = FakeScrapes()
    mgr, events = _manager(scrapes)
    view = mgr.spawn()
    scrapes.set(view.endpoint, ready=True)
    mgr.scrape_once()
    view.proc.rc = 1  # crashed
    mgr.scrape_once()
    assert view.state == serve_state.ReplicaStatus.FAILED
    assert ('dead', view.replica_id) in events


def test_manager_startup_grace_timeout_fails_replica():
    clock = _FakeClock()
    scrapes = FakeScrapes()
    mgr, events = _manager(scrapes, startup_grace_s=60.0, clock=clock)
    view = mgr.spawn()  # never scrapeable
    mgr.scrape_once()
    assert view.state == serve_state.ReplicaStatus.STARTING
    clock.t += 61
    mgr.scrape_once()
    assert view.state == serve_state.ReplicaStatus.FAILED


# ---------------------------------------------------------------------------
# drain-before-kill ordering (the PR-5 contract, plane-side)
# ---------------------------------------------------------------------------
def test_drain_contract_ordering_routing_stops_before_sigterm():
    """drain_replica: DRAINING mark -> routing set shrinks -> SIGTERM
    -> wait for self-exit. The fake proc snapshots the policy's ready
    set at SIGTERM time: the victim MUST already be gone from it."""
    scrapes = FakeScrapes()
    policy = lbp.PrefixAffinityPolicy()
    ready_at_sigterm = []

    def on_sigterm(proc):
        ready_at_sigterm.append(list(policy.ready_replicas))
        proc.rc = 0  # exits by itself, inside the grace window

    events = []
    mgr = ReplicaManager(
        lambda rid, port: FakeProc(on_sigterm=on_sigterm),
        http_get=scrapes, drain_grace_s=5.0,
        on_event=lambda name, view: events.append(name))
    auto = autoscalers.EngineMetricsAutoscaler(
        _spec(min_replicas=1, max_replicas=2))
    ctl = FleetController(mgr, policy, auto, drain_in_thread=False)
    v1, v2 = mgr.spawn(), mgr.spawn()
    for v in (v1, v2):
        scrapes.set(v.endpoint, ready=True)
    mgr.scrape_once()
    ctl._push_routing()
    assert sorted(policy.ready_replicas) == sorted(
        [v1.endpoint, v2.endpoint])

    ctl.drain_replica(v2)
    assert ready_at_sigterm == [[v1.endpoint]]  # victim gone FIRST
    assert v2.state == serve_state.ReplicaStatus.SHUTDOWN
    drain_events = [e for e in events
                    if e in ('draining', 'sigterm', 'drained',
                             'killed')]
    assert drain_events == ['draining', 'sigterm', 'drained']


def test_drain_grace_expiry_kills():
    scrapes = FakeScrapes()
    clock = _FakeClock()
    events = []
    # Proc that ignores SIGTERM entirely.
    mgr = ReplicaManager(lambda rid, port: FakeProc(),
                         http_get=scrapes, drain_grace_s=0.0,
                         clock=clock,
                         on_event=lambda name, view: events.append(
                             name))
    view = mgr.spawn()
    mgr.drain(view.replica_id)
    assert view.proc.rc == -9  # killed only after the grace window
    assert events[-1] == 'killed'
    assert view.state == serve_state.ReplicaStatus.SHUTDOWN


def test_stub_readyz_flips_503_before_exit():
    """Replica-side half of the contract: after SIGTERM, /readyz
    answers 503 (out of rotation) while the in-flight stream still
    completes; the process exits 0 only after."""
    handle = InProcessStubReplica(0, token_sleep_s=0.02)
    url = f'http://127.0.0.1:{handle.port}'
    got = {}

    def long_request():
        r = requests.post(f'{url}/generate', json={
            'tokens': [list(range(20))], 'max_new_tokens': 25,
            'stream': True}, stream=True, timeout=30)
        got['lines'] = [l for l in r.iter_lines()
                        if l.startswith(b'data')]

    t = threading.Thread(target=long_request)
    t.start()
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        if handle.state.inflight > 0:
            break
        time.sleep(0.005)
    assert handle.state.inflight > 0
    handle.send_signal(15)  # SIGTERM
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        if handle.state.draining.is_set():
            break
        time.sleep(0.005)
    code = requests.get(f'{url}/readyz', timeout=5).status_code
    assert code == 503          # drained out of rotation...
    assert handle.poll() is None  # ...but NOT dead yet
    t.join(timeout=30)
    assert got['lines'][-1] == b'data: [DONE]'  # stream completed
    assert handle.wait(timeout=10) == 0


# ---------------------------------------------------------------------------
# chaos: replica dies mid-stream -> reroute -> replace -> no extra 5xx
# ---------------------------------------------------------------------------
def _stub_fleet(n, per_replica=None, **stub_kw):
    policy = lbp.PrefixAffinityPolicy()
    mgr = ReplicaManager(
        in_process_stub_factory(per_replica=per_replica or {},
                                **stub_kw),
        drain_grace_s=5.0)
    auto = autoscalers.EngineMetricsAutoscaler(
        _spec(min_replicas=n, max_replicas=n))
    ctl = FleetController(mgr, policy, auto, interval_s=0.05)
    for _ in range(n):
        mgr.spawn()
    assert ctl.wait_ready(n, timeout_s=15)
    port = rm.free_port()
    lb = make_lb_server(policy, port, policy_name='prefix_affinity',
                        manager=mgr)
    threading.Thread(target=lb.serve_forever, daemon=True).start()
    return mgr, ctl, policy, lb, f'http://127.0.0.1:{port}'


def _prompt_targeting(policy, endpoint, salt=0):
    """A >=1-page prompt whose affinity target is `endpoint`."""
    for i in range(200):
        prompt = [salt * 1000 + i] * 16 + [7, 8, 9]
        key = affinity.token_affinity_key(prompt)
        if policy.affinity_target(key) == endpoint:
            return prompt
    raise AssertionError('no prompt mapped to the victim')


def test_chaos_replica_death_mid_stream_reroute_and_replace():
    mgr, ctl, policy, lb, url = _stub_fleet(
        3, per_replica={2: {'die_after_tokens': 5}},
        token_sleep_s=0.01)
    try:
        victim = mgr.view(2)
        prompt = _prompt_targeting(policy, victim.endpoint)

        # 1) The in-flight stream on the dying replica truncates (the
        # client got its 200 + some tokens; the blast radius).
        with requests.post(f'{url}/generate', json={
                'tokens': [prompt], 'max_new_tokens': 20,
                'stream': True}, stream=True, timeout=30) as resp:
            assert resp.status_code == 200
            lines = []
            try:
                for l in resp.iter_lines():
                    if l.startswith(b'data'):
                        lines.append(l)
            except requests.RequestException:
                pass  # truncation may surface as a broken read
        assert b'data: [DONE]' not in lines  # truncated mid-stream
        assert 0 < len(lines) < 20
        assert victim.state.value != 'SHUTDOWN'  # died, not drained

        # 2) The NEXT request (scrape has not noticed yet: the ready
        # set still lists the dead replica) is retried onto a live
        # one — the client sees 200, not 5xx.
        r = requests.post(f'{url}/generate', json={
            'tokens': [prompt], 'max_new_tokens': 4}, timeout=30)
        assert r.status_code == 200
        assert lb.lb_metrics.snapshot()['retried'] >= 1

        # 3) The controller notices the death, replaces the replica,
        # and the fleet returns to 3 ready.
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            ctl.tick()
            if len(mgr.ready_endpoints()) >= 3:
                break
            time.sleep(0.05)
        ready = mgr.ready_endpoints()
        assert len(ready) == 3
        assert victim.endpoint not in ready
        assert max(v.replica_id for v in mgr.views()) == 4  # spawned

        # 4) Steady state again: keyed requests route and succeed.
        r = requests.post(f'{url}/generate', json={
            'tokens': [prompt], 'max_new_tokens': 4}, timeout=30)
        assert r.status_code == 200
    finally:
        ctl.shutdown()
        lb.shutdown()


def test_lb_retries_request_to_dead_endpoint_before_streaming():
    """A dead-but-still-listed replica (connection refused) must be
    transparent to the client: the LB retries elsewhere."""
    mgr, ctl, policy, lb, url = _stub_fleet(2)
    try:
        views = {v.replica_id: v for v in mgr.views()}
        victim = views[1]
        prompt = _prompt_targeting(policy, victim.endpoint)
        victim.proc.die(1)  # abrupt: refuses new connections
        r = requests.post(f'{url}/generate', json={
            'tokens': [prompt], 'max_new_tokens': 3}, timeout=30)
        assert r.status_code == 200
        snap = lb.lb_metrics.snapshot()
        assert snap['retried'] >= 1
    finally:
        ctl.shutdown()
        lb.shutdown()


def test_scale_down_goes_through_drain_not_kill():
    """Autoscaler-driven scale-down drains: the victim finishes its
    in-flight stream and exits 0 — never killed mid-request."""
    mgr, ctl, policy, lb, url = _stub_fleet(3, token_sleep_s=0.02)
    try:
        # Force a lower target: shrink the autoscaler band.
        ctl.autoscaler.spec.min_replicas = 2
        ctl.autoscaler.spec.max_replicas = 2
        ctl.autoscaler.target_num_replicas = 2
        # Start a long stream; find its serving replica via a keyed
        # prompt so we know who the autoscaler might drain.
        done = {}

        def stream():
            r = requests.post(f'{url}/generate', json={
                'tokens': [list(range(16))], 'max_new_tokens': 30,
                'stream': True}, stream=True, timeout=60)
            done['lines'] = [l for l in r.iter_lines()
                             if l.startswith(b'data')]

        initial = {v.replica_id: v for v in mgr.views()}
        t = threading.Thread(target=stream)
        t.start()
        time.sleep(0.1)  # stream underway
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            ctl.tick()
            live = [v for v in mgr.views()
                    if not v.state.is_terminal()]
            if len(live) == 2:
                break
            time.sleep(0.05)
        t.join(timeout=60)
        # The stream completed in full despite the scale-down.
        assert done['lines'][-1] == b'data: [DONE]'
        assert len([l for l in done['lines'] if b'"token"' in l]) == 30
        # And the drained replica exited cleanly (rc 0, not killed).
        # (tick() removes terminal views from the manager, so check
        # the handles captured before the scale-down.)
        gone = [v for v in initial.values()
                if v.state == serve_state.ReplicaStatus.SHUTDOWN]
        assert gone and all(v.proc.poll() == 0 for v in gone)
    finally:
        ctl.shutdown()
        lb.shutdown()


# ---------------------------------------------------------------------------
# serve_state + dashboard surfaces
# ---------------------------------------------------------------------------
def test_draining_state_is_distinct_and_not_terminal():
    s = serve_state.ReplicaStatus.DRAINING
    assert not s.is_terminal()
    assert not s.is_serving
    assert s.value == 'DRAINING'


# ---------------------------------------------------------------------------
# serve_bench fleet smoke (N=2, stubs): deterministic replay + schema
# ---------------------------------------------------------------------------
def _run_bench_smoke():
    # --stub-cache-pages 24 >= the worst-case per-replica working set
    # (8 groups x 3 pages all pinned to one replica), so the
    # AGGREGATE hit rates are independent of which random ports the
    # replicas got (the consistent-hash ring hashes endpoint strings;
    # the per-replica split under affinity is therefore
    # port-dependent, the totals are not).
    cmd = [sys.executable,
           os.path.join(REPO, 'benchmarks', 'serve_bench.py'),
           '--replicas', '2', '--stub-replicas', '--ab-policies',
           '--requests', '24', '--concurrency', '1',
           '--shared-prefix', '48', '--prefix-groups', '8',
           '--stub-cache-pages', '24', '--max-new-tokens', '4']
    env = dict(os.environ)
    env['PYTHONPATH'] = f"{REPO}:{env.get('PYTHONPATH', '')}"
    out = subprocess.run(cmd, env=env, capture_output=True,
                         text=True, timeout=240, check=True)
    return json.loads(out.stdout.strip().splitlines()[-1])


def _deterministic_fields(run, per_replica):
    out = {
        'requests': run['requests'],
        'client_errors': run['client_errors'],
        'shed_requests': run['shed_requests'],
        'affinity_hit_ratio': run['affinity_hit_ratio'],
        'fleet_prefix_hit_rate': run['fleet_prefix_hit_rate'],
    }
    if per_replica:
        out['per_replica'] = [{
            'replica_id': p['replica_id'],
            'routed': p['routed'],
            'prefix_hits': p['prefix_hits'],
            'prefix_misses': p['prefix_misses'],
        } for p in run['per_replica']]
    return out


def test_serve_bench_fleet_smoke_deterministic_and_affinity_wins():
    """`serve_bench --replicas 2` (stub fleet): two invocations give
    identical control-plane results at concurrency 1 (full
    per-replica breakdown for round-robin; port-independent
    aggregates for affinity — see _run_bench_smoke), the affinity
    policy beats round-robin on prefix-cache hit rate, and the JSON
    schema matches the committed BENCH_serve_fleet_r07.json record
    (which was produced by the same harness on real serve_lm
    replicas)."""
    a = _run_bench_smoke()
    b = _run_bench_smoke()
    for pol, per_replica in (('prefix_affinity', False),
                             ('round_robin', True)):
        assert _deterministic_fields(a['runs'][pol], per_replica) == \
            _deterministic_fields(b['runs'][pol], per_replica), pol
    aff = a['runs']['prefix_affinity']
    rr = a['runs']['round_robin']
    assert aff['affinity_hit_ratio'] > 0.9
    assert rr['affinity_hit_ratio'] == 0.0
    assert aff['fleet_prefix_hit_rate'] > rr['fleet_prefix_hit_rate']
    assert aff['client_errors'] == 0 and rr['client_errors'] == 0

    committed = os.path.join(REPO, 'BENCH_serve_fleet_r07.json')
    with open(committed, 'r', encoding='utf-8') as f:
        record = json.load(f)
    # The schema may only GROW (the committed r07 record predates the
    # disaggregation/spill fields): every committed key must still be
    # produced, new keys are additive.
    assert set(record) <= set(a)
    for pol in ('prefix_affinity', 'round_robin'):
        assert set(record['runs'][pol]) <= set(a['runs'][pol])
        assert set(record['runs'][pol]['per_replica'][0]) <= \
            set(a['runs'][pol]['per_replica'][0])
    # The committed real-model record shows the same ordering.
    assert record['runs']['prefix_affinity'][
        'fleet_prefix_hit_rate'] > \
        record['runs']['round_robin']['fleet_prefix_hit_rate']
