"""Prefix caching (vLLM-style APC) in the paged serving engine:
shared-prompt pages are reused across requests with bit-identical
outputs, completed prompts stay resident for later hits, and cached
pages yield to live sequences under pool pressure.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import flax.linen as nn

from skypilot_tpu.models.batching import (ContinuousBatchingEngine,
                                          PrefixCache)

SYS_PROMPT = list(range(2, 34))  # 32 tokens = 4 full 8-token pages


def _build(family='llama', **cfg_kw):
    kw = dict(dtype=jnp.float32, kv_page_size=8, kv_total_pages=40)
    kw.update(cfg_kw)
    if family == 'llama':
        from skypilot_tpu.models.llama import Llama, LlamaConfig
        model = Llama(LlamaConfig.tiny(**kw))
    else:
        from skypilot_tpu.models.gpt import GPT, GPTConfig
        kw.pop('max_seq_len', None)  # tiny() pins block_size=128
        model = GPT(GPTConfig.tiny(**kw))
    params = nn.meta.unbox(model.init(
        jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))['params'])
    return model, params


def test_chain_keys_commit_to_full_history():
    k1 = PrefixCache.chain_keys(list(range(24)), 8)
    k2 = PrefixCache.chain_keys(list(range(24)) + [99], 8)
    assert len(k1) == 3 and k1 == k2[:3]  # partial page ignored
    # A differing FIRST page changes every later key (keys commit to
    # the whole history, not just their own page).
    k3 = PrefixCache.chain_keys([7] + list(range(1, 24)), 8)
    assert k3[0] != k1[0] and k3[2] != k1[2]


@pytest.mark.slow
@pytest.mark.parametrize('family', ['llama', 'gpt'])
def test_prefix_cached_outputs_are_identical(family):
    """Greedy outputs with prefix caching must equal the plain paged
    engine's, while later shared-prefix requests hit the cache."""
    model, params = _build(family)

    def run(prefix_caching):
        eng = ContinuousBatchingEngine(model, params, num_slots=4,
                                       max_total_len=96,
                                       prefix_caching=prefix_caching)
        assert eng.paged
        outs = []
        for extra in ([40, 41], [50, 51, 52], [60], [40, 41, 99]):
            outs.append(eng.submit(SYS_PROMPT + extra,
                                   max_new_tokens=8).result(timeout=180))
        stats = ((eng.prefix_cache.hits, eng.prefix_cache.misses)
                 if eng.prefix_cache else None)
        eng.stop()
        return outs, stats

    cached, stats = run(True)
    plain, none_stats = run(False)
    assert cached == plain
    assert none_stats is None
    hits, misses = stats
    # Request 1 misses its 4 full pages; requests 2-4 each hit them.
    assert misses == 4 and hits == 12


@pytest.mark.slow
def test_prefix_cache_saves_pages_and_prefill_work():
    """A shared-prefix admission allocates only suffix pages."""
    model, params = _build()
    eng = ContinuousBatchingEngine(model, params, num_slots=4,
                                   max_total_len=96)
    try:
        eng.submit(SYS_PROMPT + [40], max_new_tokens=4).result(timeout=180)
        free_before = eng.allocator.free_pages
        eng.submit(SYS_PROMPT + [50], max_new_tokens=4).result(timeout=180)
        # The 4 prompt pages were served from cache: the second request
        # only ever allocated suffix pages, and on completion its
        # prompt-suffix page went back / was promoted — the cache
        # never grows duplicates of the shared pages.
        assert eng.prefix_cache.hits >= 4
        assert eng.allocator.free_pages >= free_before - 1
    finally:
        eng.stop()


@pytest.mark.slow
def test_cached_pages_yield_under_pool_pressure():
    """Resident-but-unreferenced cached pages are evicted (LRU) when a
    live admission needs the pool — caching must never cause page
    starvation."""
    model, params = _build(kv_total_pages=10)  # 9 usable pages
    eng = ContinuousBatchingEngine(model, params, num_slots=2,
                                   max_total_len=40)
    try:
        # Fill the cache with two distinct completed prompts
        # (2x 3 full pages resident after completion).
        eng.submit(list(range(2, 26)) + [30],
                   max_new_tokens=2).result(timeout=180)
        eng.submit(list(range(40, 64)) + [70],
                   max_new_tokens=2).result(timeout=180)
        assert len(eng.prefix_cache.lru) >= 4
        # 6 of the 9 usable pages are cached-resident: the next
        # admission needs 4 > 3 free, so eviction MUST fire for it to
        # be admitted at all (and growth keeps evicting).
        out = eng.submit(list(range(80, 110)),
                         max_new_tokens=6).result(timeout=180)
        assert len(out) == 36
    finally:
        eng.stop()


@pytest.mark.slow
def test_prefix_caching_composes_with_speculative():
    """Speculative verify chunks write past the committed position, so
    shared pages stay read-only: greedy spec+cache == plain."""
    model, params = _build()
    plain = ContinuousBatchingEngine(model, params, num_slots=2,
                                     max_total_len=80,
                                     prefix_caching=False)
    spec = ContinuousBatchingEngine(model, params, num_slots=2,
                                    max_total_len=80,
                                    speculative_k=3)
    try:
        for extra in ([40, 41], [50]):
            a = plain.submit(SYS_PROMPT + extra,
                             max_new_tokens=8).result(timeout=180)
            b = spec.submit(SYS_PROMPT + extra,
                            max_new_tokens=8).result(timeout=180)
            assert a == b
        assert spec.prefix_cache.hits >= 4
    finally:
        plain.stop()
        spec.stop()


@pytest.mark.slow
def test_cached_prefix_with_near_max_suffix():
    """Regression: the suffix-prefill bucket is capped so the padded
    tail cannot run past the page-table row (an out-of-range logical
    page CLAMPS onto the last real page and shreds the prompt tail)."""
    model, params = _build()
    short = SYS_PROMPT[:9]  # caches exactly 1 full page on completion

    def run(prefix_caching):
        eng = ContinuousBatchingEngine(model, params, num_slots=2,
                                       max_total_len=96,
                                       prefix_caching=prefix_caching)
        try:
            eng.submit(short, max_new_tokens=2).result(timeout=180)
            long_prompt = SYS_PROMPT[:8] + list(range(100, 187))  # 95
            return eng.submit(long_prompt,
                              max_new_tokens=1).result(timeout=180), (
                eng.prefix_cache.hits if eng.prefix_cache else 0)
        finally:
            eng.stop()

    out_cached, hits = run(True)
    out_plain, _ = run(False)
    assert hits >= 1          # the long prompt reused the cached page
    assert out_cached == out_plain
