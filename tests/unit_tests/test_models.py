"""Sharded model tests on the 8-device CPU mesh (slow: real compiles)."""
import jax
import jax.numpy as jnp
import pytest

from skypilot_tpu.models.gpt import GPT, GPTConfig
from skypilot_tpu.models.llama import Llama, LlamaConfig
from skypilot_tpu.parallel import mesh as mesh_lib
from skypilot_tpu.parallel.train import (ShardedTrainer, next_token_loss,
                                         shard_batch)


def test_loss_math():
    logits = jnp.zeros((2, 4, 8))
    tokens = jnp.zeros((2, 4), jnp.int32)
    loss = next_token_loss(logits, tokens)
    assert loss == pytest.approx(jnp.log(8), rel=1e-5)


@pytest.mark.slow
def test_gpt_trains_on_mesh(cpu_mesh8):
    model = GPT(GPTConfig.tiny())
    tokens = jnp.ones((8, 64), jnp.int32)
    trainer = ShardedTrainer(model, cpu_mesh8)
    state = trainer.init(jax.random.PRNGKey(0), tokens)
    # The embedding table shards over tensor (vocab dim) but NOT fsdp:
    # fsdp-sharding its embed dim forces an involuntary full-remat
    # reshard in the gather's backward (see mesh.DEFAULT_RULES).
    wte_spec = str(state.params['wte'].sharding.spec)
    assert 'tensor' in wte_spec and 'fsdp' not in wte_spec
    # FSDP still shards the dense kernels' embed dim.
    fc_spec = str(state.params['h_0']['mlp']['c_fc']['kernel']
                  .sharding.spec)
    assert 'fsdp' in fc_spec
    step = trainer.make_train_step(tokens)
    batch = shard_batch(tokens, cpu_mesh8)
    state, l1 = step(state, batch)
    state, l2 = step(state, batch)
    assert float(l2) < float(l1)
    assert int(state.step) == 2


@pytest.mark.slow
def test_llama_trains_on_mesh(cpu_mesh8):
    model = Llama(LlamaConfig.tiny())
    tokens = jnp.ones((8, 64), jnp.int32)
    trainer = ShardedTrainer(model, cpu_mesh8)
    state = trainer.init(jax.random.PRNGKey(0), tokens)
    step = trainer.make_train_step(tokens)
    batch = shard_batch(tokens, cpu_mesh8)
    state, l1 = step(state, batch)
    state, l2 = step(state, batch)
    assert float(l2) < float(l1)


@pytest.mark.slow
def test_gqa_shapes():
    cfg = LlamaConfig.tiny()
    model = Llama(cfg)
    tokens = jnp.ones((2, 16), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)
    logits = model.apply(params, tokens)
    assert logits.shape == (2, 16, cfg.vocab_size)
    assert logits.dtype == jnp.float32


@pytest.mark.slow
def test_mixtral_expert_parallel_trains():
    from skypilot_tpu.models.mixtral import (Mixtral, MixtralConfig,
                                             moe_next_token_loss)
    mesh = mesh_lib.make_mesh(mesh_lib.MeshConfig(data=2, expert=4))
    cfg = MixtralConfig.tiny()
    model = Mixtral(cfg)
    tokens = jnp.ones((8, 64), jnp.int32)
    trainer = ShardedTrainer(model, mesh, loss_fn=moe_next_token_loss)
    state = trainer.init(jax.random.PRNGKey(0), tokens)
    # Expert weights actually sharded over the expert axis.
    w_gate = state.params['layer_0']['moe']['w_gate']
    assert 'expert' in str(w_gate.sharding.spec), w_gate.sharding
    step = trainer.make_train_step(tokens)
    batch = shard_batch(
        jax.random.randint(jax.random.PRNGKey(1), (8, 64), 0,
                           cfg.vocab_size, jnp.int32), mesh)
    state, l1 = step(state, batch)
    state, l2 = step(state, batch)
    state, l3 = step(state, batch)
    assert float(l3) < float(l1)


@pytest.mark.slow
def test_checkpoint_save_restore(cpu_mesh8, tmp_path):
    from skypilot_tpu.parallel.checkpoints import CheckpointManager
    model = GPT(GPTConfig.tiny())
    tokens = jnp.ones((8, 64), jnp.int32)
    trainer = ShardedTrainer(model, cpu_mesh8)
    state = trainer.init(jax.random.PRNGKey(0), tokens)
    step = trainer.make_train_step(tokens)
    batch = shard_batch(tokens, cpu_mesh8)
    state, _ = step(state, batch)

    mgr = CheckpointManager(str(tmp_path / 'ckpt'))
    assert mgr.latest_step() is None
    mgr.save(int(state.step), state)
    mgr.wait_until_finished()
    assert mgr.latest_step() == 1

    restored = mgr.restore(state)
    assert int(restored.step) == int(state.step)
    import numpy as np
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(restored.params['wte'])),
        np.asarray(jax.device_get(state.params['wte'])))
    # Restored state keeps the mesh shardings (resume training works).
    state2, loss = step(restored, batch)
    assert float(loss) > 0
    mgr.close()


@pytest.mark.slow
def test_multi_step_matches_sequential(cpu_mesh8):
    """make_multi_step (lax.scan inner loop) == N make_train_step calls."""
    from skypilot_tpu.parallel.train import shard_batch_stack
    model = GPT(GPTConfig.tiny())
    example = jnp.ones((8, 32), jnp.int32)
    data = jax.random.randint(jax.random.PRNGKey(3), (3, 8, 32), 0, 512,
                              jnp.int32)

    trainer = ShardedTrainer(model, cpu_mesh8)
    state = trainer.init(jax.random.PRNGKey(0), example)
    step = trainer.make_train_step(example, donate=False)
    seq_losses = []
    for i in range(3):
        state, loss = step(state, shard_batch(data[i], cpu_mesh8))
        seq_losses.append(float(loss))

    state2 = trainer.init(jax.random.PRNGKey(0), example)
    mstep = trainer.make_multi_step(example, 3, donate=False)
    state2, losses = mstep(state2, shard_batch_stack(data, cpu_mesh8))
    assert int(state2.step) == 3
    assert losses.shape == (3,)
    for a, b in zip(seq_losses, losses):
        assert a == pytest.approx(float(b), rel=1e-5)


@pytest.mark.slow
def test_deepseek_mla_trains_on_mesh(cpu_mesh8):
    from skypilot_tpu.models.deepseek import Deepseek, DeepseekConfig
    model = Deepseek(DeepseekConfig.tiny())
    tokens = jnp.ones((8, 64), jnp.int32)
    trainer = ShardedTrainer(model, cpu_mesh8)
    state = trainer.init(jax.random.PRNGKey(0), tokens)
    step = trainer.make_train_step(tokens)
    batch = shard_batch(tokens, cpu_mesh8)
    state, l1 = step(state, batch)
    state, l2 = step(state, batch)
    assert float(l2) < float(l1)


def test_deepseek_latent_cache_is_compressed():
    """The whole point of MLA: cached dims/token = kv_lora_rank +
    rope_head_dim, independent of heads."""
    from skypilot_tpu.models.deepseek import Deepseek, DeepseekConfig
    cfg = DeepseekConfig.tiny(dtype=jnp.float32)
    model = Deepseek(cfg)
    variables = model.init(
        jax.random.PRNGKey(0), jnp.zeros((2, 1), jnp.int32),
        positions=jnp.zeros((2, 1), jnp.int32), decode=True)
    cache = variables['cache']
    lat = cache['layer_0']['attn']['latent_cache']
    rope = cache['layer_0']['attn']['rope_cache']
    assert lat.shape == (2, cfg.max_seq_len, cfg.kv_lora_rank)
    assert rope.shape == (2, cfg.max_seq_len, cfg.rope_head_dim)
    cached_dims = lat.shape[-1] + rope.shape[-1]
    full_kv_dims = 2 * cfg.num_heads * cfg.v_head_dim
    assert cached_dims < full_kv_dims / 2


@pytest.mark.slow
def test_zero1_loss_parity_and_sharding():
    """ZeRO-1 (opt moments sharded over `data`) is step-for-step
    loss-identical to the replicated-moments trainer — the layout
    changes, the math does not. Also covers the multi-step lax.scan
    path (the inner-loop sharding constraint)."""
    import numpy as np
    from skypilot_tpu.parallel.train import (default_optimizer,
                                             shard_batch_stack)
    from skypilot_tpu.models.llama import Llama, LlamaConfig

    mesh = mesh_lib.make_mesh(mesh_lib.MeshConfig(data=4, fsdp=2))
    # qwen-tiny flavor, f32 compute: parity is about the UPDATE MATH —
    # f32 removes the bf16 rounding jitter different executables are
    # allowed to have, so the tolerance can stay tight.
    model = Llama(LlamaConfig.tiny(qkv_bias=True, dtype=jnp.float32))
    tokens = jax.random.randint(jax.random.PRNGKey(7), (8, 32), 0, 512,
                                jnp.int32)
    batch = shard_batch(tokens, mesh)
    curves = {}
    for zero1 in (False, True):
        trainer = ShardedTrainer(model, mesh, tx=default_optimizer(),
                                 zero1=zero1)
        state = trainer.init(jax.random.PRNGKey(0), tokens)
        if zero1:
            # The Adam moments really are data-sharded...
            specs = [str(x.sharding.spec)
                     for x in jax.tree.leaves(state.opt_state)]
            assert any("'data'" in s for s in specs), specs
            # ...while params keep their (fsdp/tensor) layout.
            assert not any(
                "'data'" in str(x.sharding.spec)
                for x in jax.tree.leaves(state.params))
        step = trainer.make_train_step(tokens, donate=False)
        losses = []
        for _ in range(5):
            state, loss = step(state, batch)
            losses.append(float(loss))
        curves[zero1] = losses
    np.testing.assert_allclose(curves[True], curves[False], rtol=1e-5)

    # Multi-step (lax.scan) parity under ZeRO-1 — compared against
    # the non-zero1 MULTI-STEP run (scan executables carry their own
    # bf16-level numeric identity vs single steps, zero1 or not).
    stack = jnp.broadcast_to(tokens, (3, *tokens.shape))
    mcurves = {}
    for zero1 in (False, True):
        trainer = ShardedTrainer(model, mesh, tx=default_optimizer(),
                                 zero1=zero1)
        state = trainer.init(jax.random.PRNGKey(0), tokens)
        mstep = trainer.make_multi_step(tokens, 3, donate=False)
        _, mlosses = mstep(state, shard_batch_stack(stack, mesh))
        mcurves[zero1] = [float(x) for x in mlosses]
    np.testing.assert_allclose(mcurves[True], mcurves[False], rtol=1e-5)
    np.testing.assert_allclose(mcurves[True], curves[False][:3],
                               rtol=1e-4)


@pytest.mark.slow
def test_zero1_checkpoint_roundtrip(tmp_path):
    """Sharded opt state survives save->restore, including a LAYOUT
    CHANGE across the boundary (replicated-moments checkpoint into a
    ZeRO-1 template — the `--zero1` flag flip on resume)."""
    import numpy as np
    from skypilot_tpu.parallel.checkpoints import CheckpointManager
    from skypilot_tpu.parallel.train import default_optimizer
    from skypilot_tpu.models.llama import Llama, LlamaConfig

    mesh = mesh_lib.make_mesh(mesh_lib.MeshConfig(data=4, fsdp=2))
    model = Llama(LlamaConfig.tiny(qkv_bias=True))
    tokens = jax.random.randint(jax.random.PRNGKey(7), (8, 32), 0, 512,
                                jnp.int32)
    batch = shard_batch(tokens, mesh)

    z1 = ShardedTrainer(model, mesh, tx=default_optimizer(), zero1=True)
    state = z1.init(jax.random.PRNGKey(0), tokens)
    step = z1.make_train_step(tokens, donate=False)
    state, _ = step(state, batch)

    mgr = CheckpointManager(str(tmp_path / 'ckpt'))
    mgr.save(int(state.step), state, force=True)
    mgr.wait_until_finished()

    # Round-trip into the sharded template: values AND layout.
    restored = mgr.restore(state)
    mu_path = lambda s: jax.tree.leaves(s.opt_state)
    for got, want in zip(mu_path(restored), mu_path(state)):
        np.testing.assert_array_equal(np.asarray(jax.device_get(got)),
                                      np.asarray(jax.device_get(want)))
        assert got.sharding == want.sharding
    # Resume training from the restored sharded state.
    state2, loss = step(restored, batch)
    assert np.isfinite(float(loss))
    mgr.close()

    # Cross-layout restore: checkpoint written WITHOUT zero1, resumed
    # WITH it (orbax reshards on read; fallback re-places if not).
    base = ShardedTrainer(model, mesh, tx=default_optimizer())
    bstate = base.init(jax.random.PRNGKey(0), tokens)
    bstep = base.make_train_step(tokens, donate=False)
    bstate, _ = bstep(bstate, batch)
    mgr2 = CheckpointManager(str(tmp_path / 'ckpt2'))
    mgr2.save(int(bstate.step), bstate, force=True)
    mgr2.wait_until_finished()
    z1_template = z1.init(jax.random.PRNGKey(1), tokens)
    cross = mgr2.restore(z1_template)
    for got, want in zip(mu_path(cross), mu_path(bstate)):
        np.testing.assert_array_equal(np.asarray(jax.device_get(got)),
                                      np.asarray(jax.device_get(want)))
    for got, want in zip(mu_path(cross), mu_path(z1_template)):
        assert got.sharding == want.sharding
    mgr2.close()


def test_overlap_requires_zero1_and_flag_list():
    """--overlap contract: the trainer rejects overlap without the
    ZeRO-1 layout it buckets onto, and the XLA flag helper is
    platform-aware (the CPU build aborts on unknown --xla_tpu_*
    flags, so CPU gets none)."""
    from skypilot_tpu.parallel.train import (OVERLAP_XLA_FLAGS,
                                             overlap_xla_flags)
    model = Llama(LlamaConfig.tiny(dtype=jnp.float32))
    mesh = mesh_lib.make_mesh(mesh_lib.MeshConfig(data=4, fsdp=2))
    with pytest.raises(ValueError, match='zero1'):
        ShardedTrainer(model, mesh, overlap=True)
    assert overlap_xla_flags('cpu') == ()
    assert overlap_xla_flags('tpu') == OVERLAP_XLA_FLAGS
    assert overlap_xla_flags() == OVERLAP_XLA_FLAGS
    assert all(f.startswith('--xla') for f in OVERLAP_XLA_FLAGS)


def test_overlap_grad_buckets_follow_zero1_layout():
    """Each grad leaf's bucket sharding layers `data` onto the same
    dim the ZeRO-1 moments got — derived via eval_shape, no compile."""
    from jax.sharding import NamedSharding
    model = Llama(LlamaConfig.tiny(dtype=jnp.float32))
    mesh = mesh_lib.make_mesh(mesh_lib.MeshConfig(data=4, fsdp=2))
    tr = ShardedTrainer(model, mesh, zero1=True, overlap=True)
    tokens = jnp.ones((8, 32), jnp.int32)
    tr.state_sharding(tokens)
    assert tr._grad_sharding is not None
    specs = [s.spec for s in jax.tree.leaves(tr._grad_sharding)
             if isinstance(s, NamedSharding)]
    assert specs, 'no grad bucket shardings derived'
    with_data = [s for s in specs if 'data' in str(s)]
    # The big kernels (the reduce-scatter payload) all bucket.
    assert len(with_data) >= len(specs) * 0.8, (len(with_data),
                                                len(specs))


@pytest.mark.slow
def test_overlap_is_loss_identical_under_zero1():
    """overlap=True only changes WHERE the reduce-scatter happens in
    the schedule (per-leaf, inside backward), never the math: the
    loss curve is bit-comparable to the non-overlap ZeRO-1 run."""
    import numpy as np
    from skypilot_tpu.parallel.train import default_optimizer
    mesh = mesh_lib.make_mesh(mesh_lib.MeshConfig(data=4, fsdp=2))
    model = Llama(LlamaConfig.tiny(qkv_bias=True, dtype=jnp.float32))
    tokens = jax.random.randint(jax.random.PRNGKey(7), (8, 32), 0,
                                512, jnp.int32)
    batch = shard_batch(tokens, mesh)
    curves = {}
    for overlap in (False, True):
        tr = ShardedTrainer(model, mesh, tx=default_optimizer(),
                            zero1=True, overlap=overlap)
        state = tr.init(jax.random.PRNGKey(0), tokens)
        step = tr.make_train_step(tokens, donate=False)
        losses = []
        for _ in range(5):
            state, loss = step(state, batch)
            losses.append(float(loss))
        curves[overlap] = losses
    np.testing.assert_allclose(curves[True], curves[False],
                               rtol=1e-6)
