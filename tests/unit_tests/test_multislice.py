"""Multislice: hybrid DCN mesh layout + the MEGASCALE env contract.

SURVEY §2.4 (megascale rows): cross-slice gang = N slices created
together; collective bootstrap across slices rides MEGASCALE_* env
over DCN. These tests pin (a) the mesh layout invariant — the data
axis enumerates slices so dp gradient psums are the only DCN
collectives — and (b) the codegen env contract every host of every
slice receives.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from skypilot_tpu.parallel import mesh as mesh_lib


def _devices(n):
    return jax.devices('cpu')[:n]


def test_faked_slices_layout():
    devices = _devices(8)
    mesh = mesh_lib.make_mesh(
        mesh_lib.MeshConfig(data=2, fsdp=4), devices=devices,
        slice_ids=[0, 0, 0, 0, 1, 1, 1, 1])
    assert mesh.devices.shape == (2, 1, 4, 1, 1, 1)  # incl. stage axis
    # data row r == slice r, exactly.
    assert set(mesh.devices[0].flatten()) == set(devices[:4])
    assert set(mesh.devices[1].flatten()) == set(devices[4:])


def test_faked_slices_interleaved_ids():
    """Slice membership comes from the ids, not device order."""
    devices = _devices(8)
    mesh = mesh_lib.make_mesh(
        mesh_lib.MeshConfig(data=2, fsdp=4), devices=devices,
        slice_ids=[0, 1, 0, 1, 0, 1, 0, 1])
    assert set(mesh.devices[0].flatten()) == set(devices[0::2])
    assert set(mesh.devices[1].flatten()) == set(devices[1::2])


def test_data_axis_must_cover_slices():
    with pytest.raises(ValueError, match='divisible by the number'):
        mesh_lib.make_mesh(
            mesh_lib.MeshConfig(data=1, fsdp=8), devices=_devices(8),
            slice_ids=[0] * 4 + [1] * 4)


def test_uneven_slices_rejected():
    with pytest.raises(ValueError, match='uneven'):
        mesh_lib.make_mesh(
            mesh_lib.MeshConfig(data=2, fsdp=4), devices=_devices(8),
            slice_ids=[0] * 6 + [1] * 2)


@pytest.mark.slow
def test_multislice_train_step_runs():
    """A dp(dcn) x fsdp train step executes on the hybrid mesh and
    matches the single-slice loss (same devices, same math)."""
    from skypilot_tpu.models.gpt import GPT, GPTConfig
    from skypilot_tpu.parallel.train import ShardedTrainer, shard_batch
    devices = _devices(8)
    tokens = jnp.ones((8, 32), jnp.int32)
    losses = []
    for slice_ids in (None, [0] * 4 + [1] * 4):
        mesh = mesh_lib.make_mesh(
            mesh_lib.MeshConfig(data=2, fsdp=4), devices=devices,
            slice_ids=slice_ids)
        trainer = ShardedTrainer(GPT(GPTConfig.tiny()), mesh)
        state = trainer.init(jax.random.PRNGKey(0), tokens)
        _, loss = trainer.make_train_step(tokens)(
            state, shard_batch(tokens, mesh))
        losses.append(float(loss))
    np.testing.assert_allclose(losses[0], losses[1], rtol=1e-5)


# ---------------------------------------------------------------------------
# Codegen: the per-host MEGASCALE/JAX env contract for a 2-slice task.


def _fake_cluster_info(num_slices, hosts_per_slice):
    from skypilot_tpu.provision import common
    instances = [
        common.InstanceInfo(
            instance_id=f'i-{node}-{h}', internal_ip=f'10.0.{node}.{h}',
            external_ip=None, node_rank=node, host_rank=h)
        for node in range(num_slices) for h in range(hosts_per_slice)]
    return common.ClusterInfo(
        instances=instances, head_instance_id='i-0-0',
        provider_name='local')


def test_codegen_megascale_env_contract():
    import skypilot_tpu as sky
    from skypilot_tpu.backends import task_codegen

    task = sky.Task(run='echo hi', num_nodes=2)
    res = sky.Resources(infra='gcp', accelerators='tpu-v5e-16')
    spec = task_codegen.build_job_spec(
        task, res, _fake_cluster_info(num_slices=2, hosts_per_slice=2))

    env = spec['env']
    # Multislice bootstrap: every host learns the slice count and the
    # DCN coordinator (rank-0 host of slice 0).
    assert env['MEGASCALE_NUM_SLICES'] == '2'
    assert env['MEGASCALE_COORDINATOR_ADDRESS'] == '10.0.0.0'
    # Global JAX process world spans all hosts of all slices.
    assert env['SKYPILOT_NUM_NODES'] == '4'
    assert env['JAX_NUM_PROCESSES'] == '4'
    assert env['JAX_COORDINATOR_ADDRESS'].startswith('10.0.0.0:')

    per_rank = spec['per_rank_env']
    assert len(per_rank) == 4
    for rank, rank_env in enumerate(per_rank):
        node, host = divmod(rank, 2)
        assert rank_env['SKYPILOT_NODE_RANK'] == str(rank)
        assert rank_env['JAX_PROCESS_ID'] == str(rank)
        # Slice-local identity: worker id restarts per slice; the
        # slice id is the MEGASCALE coordinate.
        assert rank_env['TPU_WORKER_ID'] == str(host)
        assert rank_env['MEGASCALE_SLICE_ID'] == str(node)
        hostnames = rank_env['TPU_WORKER_HOSTNAMES'].split(',')
        assert hostnames == [f'10.0.{node}.0', f'10.0.{node}.1']


def test_codegen_single_slice_has_no_megascale_env():
    import skypilot_tpu as sky
    from skypilot_tpu.backends import task_codegen

    task = sky.Task(run='echo hi', num_nodes=1)
    res = sky.Resources(infra='gcp', accelerators='tpu-v5e-16')
    spec = task_codegen.build_job_spec(
        task, res, _fake_cluster_info(num_slices=1, hosts_per_slice=2))
    assert 'MEGASCALE_NUM_SLICES' not in spec['env']
    for rank_env in spec['per_rank_env']:
        assert 'MEGASCALE_SLICE_ID' not in rank_env


def test_auto_config_multislice():
    """MeshConfig.auto puts one data dimension per slice (dp over DCN,
    the rest FSDP inside a slice)."""
    cfg = mesh_lib.MeshConfig.auto(8, num_slices=2)
    assert (cfg.data, cfg.fsdp) == (2, 4)
    cfg = mesh_lib.MeshConfig.auto(8, tensor=2, num_slices=2)
    assert (cfg.data, cfg.fsdp, cfg.tensor) == (2, 2, 2)
    # Single-slice behavior unchanged.
    cfg = mesh_lib.MeshConfig.auto(8)
    assert (cfg.data, cfg.fsdp) == (1, 8)
    with pytest.raises(ValueError, match='not divisible'):
        mesh_lib.MeshConfig.auto(8, num_slices=3)
