"""Tensor-parallel serving (parallel/serving.py): params sharded per
the training rules propagate through every serving fn with outputs
IDENTICAL to single-device serving."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import flax.linen as nn

from skypilot_tpu.models.batching import ContinuousBatchingEngine
from skypilot_tpu.models.llama import Llama, LlamaConfig
from skypilot_tpu.parallel import mesh as mesh_lib
from skypilot_tpu.parallel.serving import shard_params_for_serving


@pytest.fixture(scope='module')
def setup():
    cfg = LlamaConfig.tiny(dtype=jnp.float32, kv_page_size=8,
                           kv_total_pages=40)
    model = Llama(cfg)
    params = nn.meta.unbox(model.init(
        jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))['params'])
    mesh = mesh_lib.make_mesh(mesh_lib.MeshConfig(tensor=2),
                              devices=jax.devices()[:2])
    return model, params, mesh


def test_params_shard_over_tensor_axis(setup):
    model, params, mesh = setup
    tp = shard_params_for_serving(model, params, mesh)
    wq = tp['layer_0']['attn']['wq']['kernel']
    assert 'tensor' in str(wq.sharding.spec)
    mlp = tp['layer_0']['mlp']['w_gate']['kernel']
    assert 'tensor' in str(mlp.sharding.spec)


@pytest.mark.slow
def test_one_shot_generate_identical(setup):
    from skypilot_tpu.models import generate as gen
    model, params, mesh = setup
    tp = shard_params_for_serving(model, params, mesh)
    prompt = jnp.asarray([[5, 9, 2, 17]], jnp.int32)
    ref = np.asarray(gen.make_generate_fn(model, 8)(
        params, prompt, jax.random.PRNGKey(0)))
    got = np.asarray(gen.make_generate_fn(model, 8)(
        tp, prompt, jax.random.PRNGKey(0)))
    np.testing.assert_array_equal(ref, got)


@pytest.mark.slow
def test_continuous_engine_identical(setup):
    """The paged continuous-batching engine (prefill, decode, prefix
    caching) serves identically off TP-sharded params."""
    model, params, mesh = setup
    tp = shard_params_for_serving(model, params, mesh)
    e_ref = ContinuousBatchingEngine(model, params, num_slots=2,
                                     max_total_len=48)
    e_tp = ContinuousBatchingEngine(model, tp, num_slots=2,
                                    max_total_len=48)
    try:
        for p in ([5, 9, 2, 17], [30, 31, 32], [5, 9, 2, 17, 40]):
            a = e_ref.submit(p, max_new_tokens=8).result(timeout=180)
            b = e_tp.submit(p, max_new_tokens=8).result(timeout=180)
            assert a == b
    finally:
        e_ref.stop()
        e_tp.stop()


@pytest.mark.slow
@pytest.mark.parametrize('family', ['gpt', 'mixtral'])
def test_other_families_identical(family):
    """GPT (tied head) and Mixtral (expert einsums) also serve
    identically TP-sharded."""
    if family == 'gpt':
        from skypilot_tpu.models.gpt import GPT, GPTConfig
        model = GPT(GPTConfig.tiny(dtype=jnp.float32,
                                   logits_dtype=jnp.float32))
    else:
        from skypilot_tpu.models.mixtral import Mixtral, MixtralConfig
        model = Mixtral(MixtralConfig.tiny(dtype=jnp.float32,
                                           logits_dtype=jnp.float32))
    params = nn.meta.unbox(model.init(
        jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))['params'])
    mesh = mesh_lib.make_mesh(mesh_lib.MeshConfig(tensor=2),
                              devices=jax.devices()[:2])
    tp = shard_params_for_serving(model, params, mesh)
    e_ref = ContinuousBatchingEngine(model, params, num_slots=2,
                                     max_total_len=48)
    e_tp = ContinuousBatchingEngine(model, tp, num_slots=2,
                                    max_total_len=48)
    try:
        for p in ([5, 9, 2, 17], [30, 31, 32]):
            a = e_ref.submit(p, max_new_tokens=6).result(timeout=180)
            b = e_tp.submit(p, max_new_tokens=6).result(timeout=180)
            assert a == b
    finally:
        e_ref.stop()
        e_tp.stop()


@pytest.mark.slow
def test_deepseek_mla_identical():
    """DeepSeek's MLA serving path (latent KV cache, absorbed decode)
    also serves identically off TP-sharded params."""
    from skypilot_tpu.models.deepseek import Deepseek, DeepseekConfig
    from skypilot_tpu.models import generate as gen
    model = Deepseek(DeepseekConfig.tiny(dtype=jnp.float32,
                                         logits_dtype=jnp.float32))
    params = nn.meta.unbox(model.init(
        jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))['params'])
    mesh = mesh_lib.make_mesh(mesh_lib.MeshConfig(tensor=2),
                              devices=jax.devices()[:2])
    tp = shard_params_for_serving(model, params, mesh)
    prompt = jnp.asarray([[5, 9, 2, 17]], jnp.int32)
    ref = np.asarray(gen.make_generate_fn(model, 8)(
        params, prompt, jax.random.PRNGKey(0)))
    got = np.asarray(gen.make_generate_fn(model, 8)(
        tp, prompt, jax.random.PRNGKey(0)))
    np.testing.assert_array_equal(ref, got)


@pytest.mark.slow
def test_streaming_and_chunked_decode_identical_under_tp(setup):
    """Round-5 serving features ride tensor parallelism unchanged:
    on_token streaming delivers the SAME tokens in the same order, and
    chunked decode keeps its bit-identity, with TP-sharded params."""
    model, params, mesh = setup
    tp = shard_params_for_serving(model, params, mesh)
    p = [5, 9, 2, 17]

    def run(engine_params, chunk):
        streamed = []
        eng = ContinuousBatchingEngine(model, engine_params,
                                       num_slots=2, max_total_len=48,
                                       decode_chunk=chunk)
        try:
            out = eng.submit(p, max_new_tokens=12,
                             on_token=streamed.append).result(
                timeout=300)
        finally:
            eng.stop()
        assert streamed == out[len(p):]
        return out

    ref = run(params, 1)
    assert run(tp, 1) == ref      # TP streaming == single-device
    assert run(tp, 4) == ref      # TP + chunked decode == same
