"""Checkpoint integrity: sha256 manifests, typed restore errors,
and fall-back to the newest verifying step.

Pure manifest machinery (parallel/ckpt_integrity.py) is stdlib-only
and tested without orbax; the CheckpointManager round trips run
under orbax on the CPU backend (importorskip'd, matching the other
checkpoint tests). The torn-write chaos drill corrupts a finalized
step's bytes directly — exactly what a crash mid-upload leaves — and
asserts the restore lands on the previous step instead of failing
the job.
"""
import json
import os

import numpy as np
import pytest

from skypilot_tpu.observability import catalog as obs_catalog
from skypilot_tpu.parallel import ckpt_integrity
from skypilot_tpu.robustness import faults
from skypilot_tpu.robustness.errors import (CheckpointCorruptionError,
                                            CheckpointNotFoundError)


# ---------------------------------------------------------------------------
# manifest machinery (no orbax)
# ---------------------------------------------------------------------------
def _make_step(tmp_path, step, payload=b'weights-bytes'):
    step_dir = tmp_path / str(step)
    (step_dir / 'sub').mkdir(parents=True)
    (step_dir / 'array.bin').write_bytes(payload)
    (step_dir / 'sub' / 'meta.json').write_text('{"ok": true}')
    return str(step_dir)


def test_manifest_write_verify_roundtrip(tmp_path):
    _make_step(tmp_path, 4)
    path = ckpt_integrity.write_manifest(str(tmp_path), 4)
    assert os.path.exists(path)
    with open(path, 'r', encoding='utf-8') as f:
        manifest = json.load(f)
    assert manifest['step'] == 4
    assert sorted(manifest['files']) == [
        'array.bin', os.path.join('sub', 'meta.json')]
    assert manifest['total_bytes'] > 0
    assert ckpt_integrity.verify_step(str(tmp_path), 4) is True
    assert ckpt_integrity.manifest_steps(str(tmp_path)) == [4]


def test_verify_detects_corruption_and_missing_file(tmp_path):
    step_dir = _make_step(tmp_path, 7)
    ckpt_integrity.write_manifest(str(tmp_path), 7)
    # Torn write: the file exists but its bytes changed/truncated.
    with open(os.path.join(step_dir, 'array.bin'), 'wb') as f:
        f.write(b'torn')
    with pytest.raises(CheckpointCorruptionError, match='mismatch'):
        ckpt_integrity.verify_step(str(tmp_path), 7)
    os.remove(os.path.join(step_dir, 'array.bin'))
    with pytest.raises(CheckpointCorruptionError, match='missing'):
        ckpt_integrity.verify_step(str(tmp_path), 7)


def test_verify_without_manifest_is_unverified_not_corrupt(tmp_path):
    _make_step(tmp_path, 3)
    assert ckpt_integrity.verify_step(str(tmp_path), 3) is False


def test_unreadable_manifest_is_corruption(tmp_path):
    _make_step(tmp_path, 5)
    with open(ckpt_integrity.manifest_path(str(tmp_path), 5), 'w',
              encoding='utf-8') as f:
        f.write('{not json')
    with pytest.raises(CheckpointCorruptionError, match='manifest'):
        ckpt_integrity.verify_step(str(tmp_path), 5)


def test_prune_manifests_tracks_gc(tmp_path):
    for step in (1, 2, 3):
        _make_step(tmp_path, step)
        ckpt_integrity.write_manifest(str(tmp_path), step)
    ckpt_integrity.prune_manifests(str(tmp_path), [2, 3])
    assert ckpt_integrity.manifest_steps(str(tmp_path)) == [2, 3]


def test_preflight_reports_fallback_step(tmp_path):
    for step in (10, 20, 30):
        _make_step(tmp_path, step, payload=f'w{step}'.encode())
        ckpt_integrity.write_manifest(str(tmp_path), step)
    # Newest step torn; 20 intact; 10 intact.
    with open(tmp_path / '30' / 'array.bin', 'wb') as f:
        f.write(b'zzz')
    report = ckpt_integrity.preflight(str(tmp_path))
    assert report['steps'] == [10, 20, 30]
    assert report['corrupt_steps'] == [30]
    assert report['unverified_steps'] == []
    assert report['newest_verifying'] == 20


def test_preflight_never_raises_on_garbage_dir(tmp_path):
    report = ckpt_integrity.preflight(str(tmp_path / 'nope'))
    assert report == {'steps': [], 'corrupt_steps': [],
                      'unverified_steps': [],
                      'newest_verifying': None}


# ---------------------------------------------------------------------------
# recovery-strategy preflight (controller-side restore fallback)
# ---------------------------------------------------------------------------
class _FakeResource:

    def __init__(self, job_recovery=None):
        self.job_recovery = job_recovery
        self.use_spot = False
        self.is_tpu_slice = False


class _FakeTask:

    def __init__(self, resources):
        self.resources = resources


def test_recovery_strategy_checkpoint_preflight(tmp_path):
    from skypilot_tpu.jobs import recovery_strategy as rs
    for step in (1, 2):
        _make_step(tmp_path, step, payload=f's{step}'.encode())
        ckpt_integrity.write_manifest(str(tmp_path), step)
    with open(tmp_path / '2' / 'array.bin', 'wb') as f:
        f.write(b'corrupt')
    task = _FakeTask([_FakeResource(
        {'checkpoint_dir': str(tmp_path)})])
    ex = rs.FailoverStrategyExecutor('c-test', task)
    report = ex._checkpoint_preflight()
    assert report['corrupt_steps'] == [2]
    assert report['newest_verifying'] == 1
    # No checkpoint_dir configured / remote dir: preflight is a
    # no-op, never an error.
    assert rs.FailoverStrategyExecutor(
        'c2', _FakeTask([_FakeResource()]))._checkpoint_preflight() \
        is None
    assert rs.FailoverStrategyExecutor(
        'c3', _FakeTask([_FakeResource(
            {'checkpoint_dir': 'gs://bucket/ckpt'})])
    )._checkpoint_preflight() is None


# ---------------------------------------------------------------------------
# CheckpointManager: manifests + typed errors + fallback (orbax)
# ---------------------------------------------------------------------------
def _manager(tmp_path, **kw):
    pytest.importorskip('orbax.checkpoint')
    from skypilot_tpu.parallel.checkpoints import CheckpointManager
    return CheckpointManager(str(tmp_path / 'ckpt'), **kw)


def _template():
    return {'x': np.zeros(8, np.float32)}


def _save_steps(mgr, steps):
    for step in steps:
        assert mgr.save(step, {'x': np.full(8, float(step),
                                            np.float32)})
    mgr.wait_until_finished()


def test_manager_writes_and_prunes_manifests(tmp_path):
    mgr = _manager(tmp_path, max_to_keep=2)
    _save_steps(mgr, [1, 2])
    assert ckpt_integrity.manifest_steps(mgr.ckpt_dir) == [1, 2]
    assert mgr.verify_step(1) and mgr.verify_step(2)
    # max_to_keep=2: saving step 3 GCs step 1; its manifest follows.
    _save_steps(mgr, [3])
    assert ckpt_integrity.manifest_steps(mgr.ckpt_dir) == [2, 3]
    mgr.close()


def test_restore_not_found_is_typed_not_assert(tmp_path):
    mgr = _manager(tmp_path)
    with pytest.raises(CheckpointNotFoundError,
                       match='no checkpoint'):
        mgr.restore(_template())
    mgr.close()


def _corrupt_step(ckpt_dir, step):
    """Flip bytes in one data file of a finalized step (a torn
    write): the manifest no longer matches."""
    step_dir = os.path.join(ckpt_dir, str(step))
    for root, _dirs, names in os.walk(step_dir):
        for name in names:
            path = os.path.join(root, name)
            if os.path.getsize(path) > 0:
                with open(path, 'r+b') as f:
                    data = f.read()
                    f.seek(0)
                    f.write(bytes(b ^ 0xFF for b in data[:16]) +
                            data[16:])
                return path
    raise AssertionError(f'no non-empty file under {step_dir}')


def test_restore_falls_back_past_corrupt_latest(tmp_path):
    mgr = _manager(tmp_path)
    _save_steps(mgr, [1, 2])
    failures = obs_catalog.counter(
        'skypilot_checkpoint_integrity_failures_total')
    before = failures.value
    _corrupt_step(mgr.ckpt_dir, 2)
    restored = mgr.restore(_template())
    # Fell back to step 1 and restored ITS payload.
    assert mgr.last_restored_step == 1
    np.testing.assert_array_equal(np.asarray(restored['x']),
                                  np.full(8, 1.0, np.float32))
    assert failures.value == before + 1
    mgr.close()


def test_restore_explicit_step_also_falls_back(tmp_path):
    """train_lm passes latest_step() explicitly; corruption there
    must fall back the same way, and last_restored_step reports the
    step actually read."""
    mgr = _manager(tmp_path)
    _save_steps(mgr, [5, 9])
    _corrupt_step(mgr.ckpt_dir, 9)
    restored = mgr.restore(_template(), step=9)
    assert mgr.last_restored_step == 5
    np.testing.assert_array_equal(np.asarray(restored['x']),
                                  np.full(8, 5.0, np.float32))
    mgr.close()


def test_restore_all_corrupt_raises_corruption(tmp_path):
    mgr = _manager(tmp_path)
    _save_steps(mgr, [1, 2])
    _corrupt_step(mgr.ckpt_dir, 1)
    _corrupt_step(mgr.ckpt_dir, 2)
    with pytest.raises(CheckpointCorruptionError,
                       match='no uncorrupted checkpoint'):
        mgr.restore(_template())
    mgr.close()


def test_failed_save_leaves_no_manifest_and_restore_skips_it(
        tmp_path):
    """checkpoint.save chaos (the torn-save drill): an injected
    save failure means orbax never finalizes the step, no manifest
    is written, and restore serves the previous good step."""
    mgr = _manager(tmp_path)
    _save_steps(mgr, [1])
    faults.install_plan({'rules': [{
        'point': 'checkpoint.save', 'action': 'raise',
        'exc': 'OSError', 'message': 'bucket gone', 'times': 1}]})
    try:
        with pytest.raises(OSError, match='bucket gone'):
            mgr.save(2, {'x': np.full(8, 2.0, np.float32)})
    finally:
        faults.clear()
    mgr.wait_until_finished()
    assert ckpt_integrity.manifest_steps(mgr.ckpt_dir) == [1]
    restored = mgr.restore(_template())
    assert mgr.last_restored_step == 1
    np.testing.assert_array_equal(np.asarray(restored['x']),
                                  np.full(8, 1.0, np.float32))
    mgr.close()


def test_checkpoint_restore_fault_point_fires(tmp_path):
    mgr = _manager(tmp_path)
    _save_steps(mgr, [1])
    faults.install_plan({'rules': [{
        'point': 'checkpoint.restore', 'action': 'raise',
        'exc': 'OSError', 'message': 'store unreadable',
        'times': 1}]})
    try:
        with pytest.raises(OSError, match='store unreadable'):
            mgr.restore(_template())
        # The plan exhausted: the next restore succeeds normally.
        restored = mgr.restore(_template())
        assert mgr.last_restored_step == 1
        np.testing.assert_array_equal(
            np.asarray(restored['x']), np.full(8, 1.0, np.float32))
    finally:
        faults.clear()
    mgr.close()
