"""GCP TPU provisioner against a fake TPU API.

SURVEY §4 strategy: an in-memory tpu.googleapis.com emulating node
lifecycle + multi-host slice topologies, so create/wait/query/
get_cluster_info/terminate run without a cloud account.
"""
import os
import re

import pytest

from skypilot_tpu import exceptions
from skypilot_tpu.provision import common
from skypilot_tpu.provision.gcp import instance as gcp_instance
from skypilot_tpu.provision.gcp import tpu_api


class FakeTpuService:
    """Emulates the node/queuedResource endpoints of the TPU API."""

    def __init__(self, hosts_per_node=2, fail_zones=()):
        self.nodes = {}
        self.queued = {}
        self.hosts_per_node = hosts_per_node
        self.fail_zones = set(fail_zones)

    def request(self, method, path, json_body=None, params=None):
        params = params or {}
        m = re.match(r'projects/([^/]+)/locations/([^/]+)/(.*)', path)
        assert m, path
        _, zone, rest = m.groups()
        if method == 'POST' and rest == 'nodes':
            if zone in self.fail_zones:
                raise exceptions.ProvisionerError(
                    f'TPU API POST {path} -> 429: no capacity in {zone}')
            name = params['nodeId']
            self.nodes[(zone, name)] = self._new_node(zone, name, json_body)
            return {'name': f'operations/create-{name}'}
        if method == 'POST' and rest == 'queuedResources':
            name = json_body['tpu']['nodeSpec'][0]['nodeId']
            node = json_body['tpu']['nodeSpec'][0]['node']
            self.nodes[(zone, name)] = self._new_node(zone, name, node)
            self.queued[(zone, params['queuedResourceId'])] = {
                'state': {'state': 'ACTIVE'}}
            return {'name': f'operations/qr-{name}'}
        if rest.startswith('nodes'):
            parts = rest.split('/')
            if len(parts) == 1 and method == 'GET':  # list
                return {'nodes': [n for (z, _), n in self.nodes.items()
                                  if z == zone]}
            name = parts[1].split(':')[0]
            node = self.nodes.get((zone, name))
            if node is None:
                raise exceptions.FetchClusterInfoError(
                    exceptions.FetchClusterInfoError.Reason.HEAD)
            if method == 'GET':
                # Nodes become READY on second poll.
                if node['state'] == 'CREATING':
                    node['_polls'] = node.get('_polls', 0) + 1
                    if node['_polls'] >= 2:
                        node['state'] = 'READY'
                return node
            if rest.endswith(':stop'):
                node['state'] = 'STOPPED'
                return {}
            if rest.endswith(':start'):
                node['state'] = 'READY'
                return {}
            if method == 'DELETE':
                del self.nodes[(zone, name)]
                return {}
        if rest.startswith('queuedResources'):
            key = (zone, rest.split('/')[1])
            if method == 'DELETE':
                self.queued.pop(key, None)
                return {}
            if key not in self.queued:
                raise exceptions.FetchClusterInfoError(
                    exceptions.FetchClusterInfoError.Reason.HEAD)
            return self.queued[key]
        raise AssertionError(f'unhandled {method} {path}')

    def _new_node(self, zone, name, body):
        endpoints = []
        for h in range(self.hosts_per_node):
            endpoints.append({
                'ipAddress': f'10.0.{len(self.nodes)}.{h + 2}',
                'accessConfig': {'externalIp': f'34.1.{len(self.nodes)}.{h + 2}'},
            })
        return {
            'name': f'projects/p/locations/{zone}/nodes/{name}',
            'state': 'CREATING',
            'acceleratorType': body.get('acceleratorType'),
            'runtimeVersion': body.get('runtimeVersion'),
            'networkEndpoints': endpoints,
            'metadata': body.get('metadata', {}),
        }


@pytest.fixture()
def fake_api(monkeypatch):
    fake = FakeTpuService(hosts_per_node=2)
    monkeypatch.setattr(tpu_api, '_request',
                        lambda method, path, json_body=None, params=None:
                        fake.request(method, path, json_body, params))
    monkeypatch.setattr(gcp_instance, '_project', lambda *a, **k: 'p')
    monkeypatch.setattr(gcp_instance, '_ssh_pub_key',
                        lambda: 'ssh-ed25519 AAAA test')
    monkeypatch.setattr(tpu_api, 'wait_node_state',
                        lambda p, z, n, **kw: fake.request(
                            'GET', f'projects/{p}/locations/{z}/nodes/{n}')
                        and fake.request(
                            'GET', f'projects/{p}/locations/{z}/nodes/{n}'))
    return fake


def _config(zone='us-east5-a', count=1, spot=False, qr=False):
    return common.ProvisionConfig(
        provider_config={
            'zone': zone,
            'tpu_vm': True,
            'tpu_accelerator_type': 'v5litepod-16',
            'tpu_topology': '4x4',
            'runtime_version': 'v2-alpha-tpuv5-lite',
            'use_spot': spot,
            'tpu_use_queued_resources': qr,
            'num_nodes': count,
        },
        authentication_config={}, count=count, tags={})


def test_create_single_slice(fake_api):
    record = gcp_instance.run_instances('us-east5', 'c1', _config())
    assert record.created_instance_ids == ['c1']
    assert record.head_instance_id == 'c1'
    gcp_instance.wait_instances('us-east5', 'c1',
                                provider_config=_config().provider_config)
    info = gcp_instance.get_cluster_info(
        'us-east5', 'c1', _config().provider_config)
    # One v5e-16 slice = 2 hosts, ranks 0/1.
    assert info.num_instances == 2
    ranks = [(i.node_rank, i.host_rank) for i in info.sorted_instances()]
    assert ranks == [(0, 0), (0, 1)]
    assert info.get_head_instance().external_ip.startswith('34.')


def test_multislice_creates_n_nodes(fake_api):
    record = gcp_instance.run_instances('us-east5', 'c2',
                                        _config(count=2))
    assert record.created_instance_ids == ['c2-0', 'c2-1']
    info = gcp_instance.get_cluster_info(
        'us-east5', 'c2', _config(count=2).provider_config)
    assert info.num_instances == 4  # 2 slices x 2 hosts
    node_ranks = {i.node_rank for i in info.instances}
    assert node_ranks == {0, 1}


def test_spot_uses_queued_resources(fake_api):
    gcp_instance.run_instances('us-east5', 'c3',
                               _config(spot=True, qr=True))
    assert ('us-east5-a', 'c3-qr') in fake_api.queued
    # terminate removes both QR and node
    gcp_instance.terminate_instances(
        'c3', _config(spot=True, qr=True).provider_config)
    assert not fake_api.nodes
    assert not fake_api.queued


def test_stop_resume_and_query(fake_api):
    cfg = _config()
    gcp_instance.run_instances('us-east5', 'c4', cfg)
    gcp_instance.stop_instances('c4', cfg.provider_config)
    statuses = gcp_instance.query_instances('c4', cfg.provider_config)
    assert statuses == {'c4': 'stopped'}
    record = gcp_instance.run_instances('us-east5', 'c4', cfg)
    assert record.resumed_instance_ids == ['c4']
    statuses = gcp_instance.query_instances('c4', cfg.provider_config)
    assert statuses == {'c4': 'running'}


def test_capacity_error_classified(fake_api):
    fake_api.fail_zones.add('us-central2-b')
    with pytest.raises(exceptions.ProvisionerError, match='no capacity'):
        gcp_instance.run_instances('us-central2', 'c5',
                                   _config(zone='us-central2-b'))


def test_error_classification():
    from skypilot_tpu.provision.gcp.tpu_api import _classify_error
    P = exceptions.ProvisionerError
    assert _classify_error(429, 'no more capacity in zone')[0] == P.CAPACITY
    assert _classify_error(429, 'Quota exceeded for quota metric '
                           'requests per minute')[0] == P.TRANSIENT
    assert _classify_error(403,
                           'Quota TPUS_PER_PROJECT exceeded')[0] == P.QUOTA
    assert _classify_error(403, 'caller lacks permission')[0] == P.PERMISSION
    assert _classify_error(400, 'Invalid acceleratorType')[0] == P.CONFIG
    assert _classify_error(503,
                           'invalid state, please retry')[0] == P.TRANSIENT
    assert _classify_error(503, 'backend error')[0] == P.TRANSIENT
    assert P('x', category=P.PERMISSION).no_failover
    assert P('x', category=P.QUOTA).blocks_region
    assert not P('x', category=P.CAPACITY).no_failover
    # Explicit scope overrides the category default.
    assert P('x', category=P.PERMISSION, scope='cloud').blocks_cloud
    assert not P('x', category=P.PERMISSION, scope='cloud').no_failover


def test_failover_engine_honors_categories(fake_api, monkeypatch):
    """Permission errors abort failover; capacity errors keep walking."""
    from skypilot_tpu.backends.tpu_backend import RetryingProvisioner
    from skypilot_tpu import task as task_lib
    from skypilot_tpu import resources as resources_lib

    task = task_lib.Task(run='true')
    r = resources_lib.Resources(infra='gcp', accelerators='tpu-v5e-16')
    task.set_resources(r)

    # All zones fail with capacity -> walks every candidate, then raises
    # a retryable ResourcesUnavailableError with full history.
    from skypilot_tpu.provision.gcp import tpu_api
    calls = []

    def cap_fail(method, path, json_body=None, params=None):
        if method == 'POST' and ('nodes' in path or
                                 'queuedResources' in path):
            calls.append(path)
            raise exceptions.ProvisionerError(
                'no more capacity',
                category=exceptions.ProvisionerError.CAPACITY)
        return fake_api.request(method, path, json_body, params)

    monkeypatch.setattr(tpu_api, '_request', cap_fail)
    prov = RetryingProvisioner()
    with pytest.raises(exceptions.ResourcesUnavailableError) as exc_info:
        prov.provision_with_retries(task, r, 'cf', 'cf')
    assert not exc_info.value.no_failover
    assert len(calls) >= 2  # tried multiple zones
    assert len(prov.failover_history) == len(calls)

    # Permission error -> immediate no-failover abort after 1 attempt.
    calls.clear()

    def perm_fail(method, path, json_body=None, params=None):
        if method == 'POST' and ('nodes' in path or
                                 'queuedResources' in path):
            calls.append(path)
            raise exceptions.ProvisionerError(
                'permission denied',
                category=exceptions.ProvisionerError.PERMISSION)
        return fake_api.request(method, path, json_body, params)

    monkeypatch.setattr(tpu_api, '_request', perm_fail)
    prov = RetryingProvisioner()
    with pytest.raises(exceptions.ResourcesUnavailableError) as exc_info:
        prov.provision_with_retries(task, r, 'pf', 'pf')
    assert exc_info.value.no_failover
    assert len(calls) == 1


def test_failover_engine_cloud_scope_stops_walk(fake_api, monkeypatch):
    """A cloud-scoped error (e.g. billing disabled) stops the walk
    after ONE attempt but stays retryable on other clouds
    (no_failover=False) — unlike abort-scope config errors."""
    from skypilot_tpu import resources as resources_lib
    from skypilot_tpu import task as task_lib
    from skypilot_tpu.backends.tpu_backend import RetryingProvisioner
    from skypilot_tpu.provision.gcp import tpu_api

    task = task_lib.Task(run='true')
    r = resources_lib.Resources(infra='gcp', accelerators='tpu-v5e-16')
    task.set_resources(r)
    calls = []

    def billing_fail(method, path, json_body=None, params=None):
        if method == 'POST' and ('nodes' in path or
                                 'queuedResources' in path):
            calls.append(path)
            raise exceptions.ProvisionerError(
                'Billing must be enabled for activation',
                category=exceptions.ProvisionerError.PERMISSION,
                scope='cloud')
        return fake_api.request(method, path, json_body, params)

    monkeypatch.setattr(tpu_api, '_request', billing_fail)
    prov = RetryingProvisioner()
    with pytest.raises(exceptions.ResourcesUnavailableError) as exc_info:
        prov.provision_with_retries(task, r, 'bf', 'bf')
    assert not exc_info.value.no_failover  # other clouds may work
    assert len(calls) == 1                 # but THIS cloud stopped cold
    assert 'account-level' in str(exc_info.value)


def test_blocked_cloud_surfaces_to_callers(fake_api, monkeypatch):
    """provision(retry_until_up=True) must NOT spin on a cloud-scoped
    error; the raised ResourcesUnavailableError names the blocked
    cloud so re-optimizing callers (managed jobs) can exclude it."""
    from skypilot_tpu import resources as resources_lib
    from skypilot_tpu import task as task_lib
    from skypilot_tpu.backends.tpu_backend import TpuVmBackend
    from skypilot_tpu.provision.gcp import tpu_api

    task = task_lib.Task(run='true')
    r = resources_lib.Resources(infra='gcp', accelerators='tpu-v5e-16')
    task.set_resources(r)
    calls = []

    def billing_fail(method, path, json_body=None, params=None):
        if method == 'POST' and ('nodes' in path or
                                 'queuedResources' in path):
            calls.append(path)
            raise exceptions.ProvisionerError(
                'Billing must be enabled',
                category=exceptions.ProvisionerError.PERMISSION,
                scope='cloud')
        return fake_api.request(method, path, json_body, params)

    monkeypatch.setattr(tpu_api, '_request', billing_fail)
    with pytest.raises(exceptions.ResourcesUnavailableError) as exc_info:
        TpuVmBackend().provision(task, r, dryrun=False, stream_logs=False,
                                 cluster_name='bc',
                                 retry_until_up=True)
    assert exc_info.value.blocked_cloud == 'gcp'
    assert len(calls) == 1  # no retry-until-up spin on a dead cloud


def test_provision_renders_debug_artifact(fake_api, isolated_state):
    """Each provision attempt appends its exact request to
    ~/.sky-tpu/generated/<cluster>.yaml (the debug-inspectable
    equivalent of the reference's rendered cluster YAML)."""
    import yaml

    from skypilot_tpu import resources as resources_lib
    from skypilot_tpu import task as task_lib
    from skypilot_tpu.backends.tpu_backend import RetryingProvisioner

    task = task_lib.Task(run='true')
    r = resources_lib.Resources(infra='gcp', accelerators='tpu-v5e-16')
    task.set_resources(r)
    RetryingProvisioner().provision_with_retries(task, r, 'art', 'art')
    path = os.path.join(isolated_state, 'generated', 'art.yaml')
    assert os.path.exists(path)
    docs = list(yaml.safe_load_all(open(path, encoding='utf-8')))
    assert docs and docs[0]['cloud'] == 'gcp'
    assert docs[0]['provider_config']['tpu_accelerator_type'] == \
        'v5litepod-16'
    assert docs[0]['zones']
