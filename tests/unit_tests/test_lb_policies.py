"""Load-balancing policies: round-robin wrap/reset, least-load
tie-breaking, and the prefix-affinity policy's contract (stability,
minimal remap on death, saturation fallback)."""
import collections

from skypilot_tpu.serve import load_balancing_policies as lbp

R3 = ['127.0.0.1:9001', '127.0.0.1:9002', '127.0.0.1:9003']


# ---------------------------------------------------------------------------
# round-robin
# ---------------------------------------------------------------------------
def test_round_robin_wraps():
    p = lbp.RoundRobinPolicy()
    p.set_ready_replicas(R3)
    picks = [p.select_replica() for _ in range(7)]
    assert picks == R3 + R3 + R3[:1]


def test_round_robin_resets_on_ready_set_change():
    p = lbp.RoundRobinPolicy()
    p.set_ready_replicas(R3)
    for _ in range(2):
        p.select_replica()
    # Membership change -> index reset (stale indices into a changed
    # list are how a dead replica keeps receiving every Nth request).
    p.set_ready_replicas(R3[:2])
    assert p.select_replica() == R3[0]
    # Same membership, different order: NOT a change.
    p.set_ready_replicas(list(reversed(R3[:2])))
    assert p.select_replica() == R3[0]


def test_round_robin_exclude_and_empty():
    p = lbp.RoundRobinPolicy()
    p.set_ready_replicas(R3[:2])
    assert p.select_replica(exclude={R3[0], R3[1]}) is None
    assert p.select_replica(exclude={R3[0]}) == R3[1]
    p.set_ready_replicas([])
    assert p.select_replica() is None


# ---------------------------------------------------------------------------
# least-load
# ---------------------------------------------------------------------------
def test_least_load_tie_break_and_done():
    p = lbp.LeastLoadPolicy()
    p.set_ready_replicas(R3)
    # All at 0 in-flight: ties break by ready-list order (min is
    # stable), and each selection loads the pick.
    assert p.select_replica() == R3[0]
    assert p.select_replica() == R3[1]
    assert p.select_replica() == R3[2]
    # 1,1,1 -> back to the first.
    assert p.select_replica() == R3[0]
    # Completion rebalances: R3[1] done -> it is now least loaded.
    p.request_done(R3[1])
    assert p.select_replica() == R3[1]


def test_least_load_done_never_negative():
    p = lbp.LeastLoadPolicy()
    p.set_ready_replicas(R3[:1])
    for _ in range(3):
        p.request_done(R3[0])
    assert p._in_flight[R3[0]] == 0


# ---------------------------------------------------------------------------
# prefix affinity
# ---------------------------------------------------------------------------
def test_affinity_same_key_same_replica():
    p = lbp.PrefixAffinityPolicy()
    p.set_ready_replicas(R3)
    first = p.select_replica(key='chain-key-a')
    for _ in range(10):
        r = p.select_replica(key='chain-key-a')
        assert r == first
        p.request_done(r)
    # And it matches the pure mapping the LB uses for hit accounting.
    assert p.affinity_target('chain-key-a') == first


def test_affinity_keys_spread_across_replicas():
    p = lbp.PrefixAffinityPolicy()
    p.set_ready_replicas(R3)
    owners = collections.Counter(
        p.affinity_target(f'key-{i}') for i in range(200))
    # Consistent hashing with vnodes: every replica owns a
    # non-trivial share (no degenerate all-on-one mapping).
    assert set(owners) == set(R3)
    assert min(owners.values()) > 20


def test_affinity_remap_on_death_moves_only_dead_keys():
    p = lbp.PrefixAffinityPolicy()
    p.set_ready_replicas(R3)
    keys = [f'key-{i}' for i in range(300)]
    before = {k: p.affinity_target(k) for k in keys}
    dead = R3[1]
    p.set_ready_replicas([r for r in R3 if r != dead])
    after = {k: p.affinity_target(k) for k in keys}
    for k in keys:
        if before[k] != dead:
            # Survivors' keys did NOT move.
            assert after[k] == before[k], k
        else:
            assert after[k] in (set(R3) - {dead})
    # And the dead replica's keys actually existed (the test tested
    # something).
    assert any(v == dead for v in before.values())


def test_affinity_rejoin_restores_mapping():
    p = lbp.PrefixAffinityPolicy()
    p.set_ready_replicas(R3)
    before = {f'key-{i}': p.affinity_target(f'key-{i}')
              for i in range(100)}
    p.set_ready_replicas(R3[:2])
    p.set_ready_replicas(R3)  # replacement replica, same endpoint
    after = {k: p.affinity_target(k) for k in before}
    assert after == before


def test_affinity_keyless_uses_least_load():
    p = lbp.PrefixAffinityPolicy()
    p.set_ready_replicas(R3)
    p.set_replica_load({R3[0]: 100.0, R3[1]: 0.0, R3[2]: 50.0})
    assert p.select_replica(key=None) == R3[1]


def test_affinity_saturation_falls_back_to_least_loaded():
    p = lbp.PrefixAffinityPolicy(saturation_inflight=2)
    p.set_ready_replicas(R3)
    target = p.affinity_target('hot-key')
    others = [r for r in R3 if r != target]
    # Saturate the target: 2 in-flight hits the cap.
    assert p.select_replica(key='hot-key') == target
    assert p.select_replica(key='hot-key') == target
    fallback = p.select_replica(key='hot-key')
    assert fallback in others
    # Load drains -> affinity routing resumes.
    p.request_done(target)
    p.request_done(target)
    assert p.select_replica(key='hot-key') == target


def test_affinity_backlog_saturation():
    p = lbp.PrefixAffinityPolicy(saturation_backlog=1000.0)
    p.set_ready_replicas(R3)
    target = p.affinity_target('k')
    p.set_replica_load({target: 5000.0})
    assert p.select_replica(key='k') != target


def test_affinity_exclude_dead_replica():
    p = lbp.PrefixAffinityPolicy()
    p.set_ready_replicas(R3)
    target = p.affinity_target('k')
    # The LB retries with the failed replica excluded (scrape has not
    # caught up yet): selection must avoid it without erroring.
    r = p.select_replica(key='k', exclude={target})
    assert r is not None and r != target
    assert p.select_replica(key='k', exclude=set(R3)) is None


def test_instance_aware_weighted_selection():
    p = lbp.InstanceAwareLeastLoadPolicy()
    p.set_ready_replicas(R3[:2])
    p.set_replica_weights({R3[0]: 4.0, R3[1]: 1.0})
    # The 4x replica should absorb ~4 of 5 first picks.
    picks = collections.Counter(p.select_replica() for _ in range(5))
    assert picks[R3[0]] == 4
    assert picks[R3[1]] == 1
