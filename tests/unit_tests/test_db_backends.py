"""Dual DB backend seam (reference: sky/global_user_state.py:68-331,
sqlite default + Postgres option). The translation layer is fully
unit-tested here; end-to-end Postgres coverage runs when a live server
is provided via SKYPILOT_TEST_PG_URL (deploy/docker-compose.pg.yaml).
"""
import os

import pytest

from skypilot_tpu.utils import db_utils

CREATE = """\
CREATE TABLE IF NOT EXISTS jobs (
    job_id INTEGER PRIMARY KEY AUTOINCREMENT,
    name TEXT,
    payload BLOB,
    score REAL
);
CREATE TABLE IF NOT EXISTS replicas (
    service TEXT,
    replica_id INTEGER,
    status TEXT,
    PRIMARY KEY (service, replica_id)
);
CREATE TABLE IF NOT EXISTS kv (
    k TEXT PRIMARY KEY,
    v TEXT
);
"""


def test_parse_schema():
    pks, autoinc = db_utils.parse_schema(CREATE)
    assert pks == {'jobs': ['job_id'],
                   'replicas': ['service', 'replica_id'],
                   'kv': ['k']}
    assert autoinc == {'jobs': 'job_id'}


def test_translate_create_sql():
    out = db_utils.translate_create_sql(CREATE)
    assert 'BIGSERIAL PRIMARY KEY' in out
    assert 'AUTOINCREMENT' not in out
    assert 'BYTEA' in out and 'BLOB' not in out


def test_translate_statements():
    pks, _ = db_utils.parse_schema(CREATE)
    t = lambda s: db_utils.translate_sql(s, pks)  # noqa: E731
    assert t('SELECT * FROM jobs WHERE job_id=?') == \
        'SELECT * FROM jobs WHERE job_id=%s'
    assert t('PRAGMA journal_mode=WAL') == ''
    assert t('INSERT OR IGNORE INTO kv (k, v) VALUES (?,?)') == \
        'INSERT INTO kv (k, v) VALUES (%s,%s) ON CONFLICT DO NOTHING'
    up = t('INSERT OR REPLACE INTO replicas (service, replica_id, '
           'status) VALUES (?,?,?)')
    assert up.startswith('INSERT INTO replicas')
    assert 'ON CONFLICT (service, replica_id) DO UPDATE SET ' in up
    assert 'status = EXCLUDED.status' in up
    assert 'service = EXCLUDED.service' not in up  # pk cols not updated
    with pytest.raises(ValueError, match='PRIMARY KEY'):
        t('INSERT OR REPLACE INTO nopk (a) VALUES (?)')


def test_open_db_routes_on_env(monkeypatch, tmp_path):
    monkeypatch.delenv('SKYPILOT_DB_URL', raising=False)
    db = db_utils.open_db(str(tmp_path / 'x.db'), CREATE)
    assert isinstance(db, db_utils.SQLiteDB)
    # A postgres URL selects the PG backend (routing asserted without
    # a live connection — error text varies by driver/environment).
    created = {}
    monkeypatch.setattr(
        db_utils.PostgresDB, '__init__',
        lambda self, url, sql: created.update(url=url) or None)
    monkeypatch.setenv('SKYPILOT_DB_URL', 'postgresql://u@127.0.0.1/db')
    db = db_utils.open_db(str(tmp_path / 'y.db'), CREATE)
    assert isinstance(db, db_utils.PostgresDB)
    assert created['url'] == 'postgresql://u@127.0.0.1/db'


@pytest.mark.skipif(not os.environ.get('SKYPILOT_TEST_PG_URL'),
                    reason='set SKYPILOT_TEST_PG_URL to a live Postgres '
                           '(deploy/docker-compose.pg.yaml) to run')
def test_postgres_end_to_end():
    """Same operations the server stores perform, against live PG:
    create, upsert, lastrowid via RETURNING, blob round-trip."""
    url = os.environ['SKYPILOT_TEST_PG_URL']
    db = db_utils.PostgresDB(url, CREATE)
    db.execute('DELETE FROM replicas')
    db.execute('DELETE FROM jobs')
    with db.conn() as conn:
        cur = conn.execute(
            'INSERT INTO jobs (name, payload, score) VALUES (?,?,?)',
            ('a', b'\x00\x01', 1.5))
        first = cur.lastrowid
        cur = conn.execute(
            'INSERT INTO jobs (name, payload, score) VALUES (?,?,?)',
            ('b', b'\x02', 2.5))
        assert cur.lastrowid == first + 1
    db.execute('INSERT OR REPLACE INTO replicas (service, replica_id, '
               'status) VALUES (?,?,?)', ('svc', 1, 'STARTING'))
    db.execute('INSERT OR REPLACE INTO replicas (service, replica_id, '
               'status) VALUES (?,?,?)', ('svc', 1, 'READY'))
    rows = db.query('SELECT * FROM replicas WHERE service=?', ('svc',))
    assert len(rows) == 1 and rows[0]['status'] == 'READY'
    row = db.query_one('SELECT payload FROM jobs WHERE name=?', ('a',))
    assert bytes(row['payload']) == b'\x00\x01'
    db.add_column_if_missing('kv', 'extra', 'TEXT')
    db.add_column_if_missing('kv', 'extra', 'TEXT')  # idempotent
