"""Server maintenance daemons (reference: sky/server/daemons.py)."""
import os
import time

import pytest

from skypilot_tpu.server import daemons as daemons_lib
from skypilot_tpu.server.requests import executor


def test_request_gc_drops_old_terminal_rows(isolated_state):
    rid_old = executor.schedule_request('old', 'noop', {})
    rid_new = executor.schedule_request('new', 'noop', {})
    rid_live = executor.schedule_request('live', 'noop', {})
    # Old + finished long ago; new + finished now; live still pending.
    executor._set_status(rid_old, executor.RequestStatus.SUCCEEDED)
    executor._set_status(rid_new, executor.RequestStatus.FAILED)
    executor._db().execute(
        'UPDATE requests SET finished_at=? WHERE request_id=?',
        (time.time() - 10 * 86400, rid_old))
    log_path = executor._log_path(rid_old)
    with open(log_path, 'w', encoding='utf-8') as f:
        f.write('x')

    removed = executor.gc_requests(retention_seconds=86400)
    assert removed == 1
    assert executor.get_request(rid_old) is None
    assert executor.get_request(rid_new) is not None  # inside retention
    assert executor.get_request(rid_live) is not None  # not terminal
    assert not os.path.exists(log_path)


def test_daemons_run_on_interval_and_survive_failures(monkeypatch):
    calls = {'status': 0, 'sweep': 0}

    def failing_status():
        calls['status'] += 1
        raise RuntimeError('boom')  # must not kill the thread

    monkeypatch.setattr(daemons_lib, '_refresh_cluster_status',
                        failing_status)
    monkeypatch.setattr(daemons_lib, '_sweep_controllers',
                        lambda: calls.__setitem__(
                            'sweep', calls['sweep'] + 1))
    d = daemons_lib.ServerDaemons(status_interval=0.2,
                                  liveness_interval=0.2,
                                  gc_interval=3600,
                                  poll=0.05)
    d.start()
    try:
        deadline = time.time() + 5
        while time.time() < deadline and (calls['status'] < 2 or
                                          calls['sweep'] < 2):
            time.sleep(0.05)
    finally:
        d.stop()
    # Both jobs ran repeatedly; the failing one kept being rescheduled.
    assert calls['status'] >= 2
    assert calls['sweep'] >= 2


@pytest.mark.slow
@pytest.mark.e2e
def test_preempted_cluster_flips_out_of_up(isolated_state):
    """VERDICT r3 item 6's done-criterion: a Local cluster whose agents
    die flips out of UP after one daemon tick with NOBODY calling
    status(refresh=True) from the outside."""
    import skypilot_tpu as sky
    from skypilot_tpu import check, core
    from skypilot_tpu.utils import subprocess_utils
    from skypilot_tpu.utils.status_lib import ClusterStatus

    check.check(quiet=True)
    task = sky.Task(name='boot', run='true')
    task.set_resources(sky.Resources(infra='local',
                                     accelerators='tpu-v5e-16'))
    _, handle = sky.launch(task, cluster_name='t-daemon',
                           _quiet_optimizer=True)
    try:
        assert core.status(['t-daemon'])[0]['status'] == ClusterStatus.UP

        # "Preempt": kill every agent process out-of-band, by pid.
        from skypilot_tpu.provision.local import instance as local_instance
        meta = local_instance._load_meta(handle.cluster_name_on_cloud)
        for host in meta['hosts']:
            subprocess_utils.kill_process_tree(host['agent_pid'])
        deadline = time.time() + 10
        while time.time() < deadline and any(
                subprocess_utils.process_alive(h['agent_pid'])
                for h in meta['hosts']):
            time.sleep(0.2)

        # Plain status (no refresh) still believes UP...
        assert core.status(['t-daemon'])[0]['status'] == ClusterStatus.UP
        # ...until one daemon tick reconciles it.
        d = daemons_lib.ServerDaemons(status_interval=3600,
                                      liveness_interval=3600,
                                      gc_interval=3600)
        d.tick_all()
        assert core.status(['t-daemon'])[0]['status'] == \
            ClusterStatus.STOPPED
    finally:
        try:
            core.down('t-daemon')
        except Exception:  # pylint: disable=broad-except
            pass


def test_zero_interval_disables_only_that_job(monkeypatch):
    calls = {'sweep': 0}
    monkeypatch.setattr(daemons_lib, '_refresh_cluster_status',
                        lambda: (_ for _ in ()).throw(
                            AssertionError('status job must be disabled')))
    monkeypatch.setattr(daemons_lib, '_sweep_controllers',
                        lambda: calls.__setitem__(
                            'sweep', calls['sweep'] + 1))
    d = daemons_lib.ServerDaemons(status_interval=0,
                                  liveness_interval=0.1,
                                  gc_interval=0, poll=0.02)
    d.start()
    try:
        deadline = time.time() + 5
        while time.time() < deadline and calls['sweep'] < 2:
            time.sleep(0.02)
    finally:
        d.stop()
    assert calls['sweep'] >= 2
