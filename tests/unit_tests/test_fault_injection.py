"""Chaos suite: deterministic fault injection + the hardening it
drives (deadlines, load shedding, crash-only engine containment,
health probes, drain, jobs recovery).

Determinism contract: every test uses seeded/counting fault plans
(rule firing is a pure function of the plan and the hit sequence) and
no wall-clock sleep beyond ~100ms. The acceptance invariants from the
robustness PR:

  (a) a poisoned decode step leaves every slot's output bit-identical
      (the fault fires before the dispatch and before RNG is
      consumed);
  (b) a poisoned prefill chunk fails exactly ONE request;
  (c) saturated requests shed with 429 + Retry-After while /readyz
      reflects draining/dead/saturated states;
  (d) with no plan installed, every point is a no-op and greedy
      serving output is byte-identical to the unarmed engine.
"""
import json
import os
import threading
import time
import urllib.error
import urllib.request

import pytest

from skypilot_tpu.robustness import faults
from skypilot_tpu.robustness.errors import (DeadlineExceededError,
                                            EngineDeadError,
                                            QueueSaturatedError)
from skypilot_tpu.utils import common_utils


@pytest.fixture(autouse=True)
def _no_leaked_plan():
    """A leaked plan would inject faults into every later test in the
    process — clear unconditionally."""
    faults.clear()
    yield
    faults.clear()


# ---------------------------------------------------------------------------
# plan machinery (no jax)
# ---------------------------------------------------------------------------
def test_unknown_point_rejected_at_install():
    with pytest.raises(ValueError, match='unknown point'):
        faults.install_plan({'rules': [{'point': 'engine.nope'}]})
    with pytest.raises(ValueError, match='unknown action'):
        faults.install_plan({'rules': [
            {'point': 'engine.decode_step', 'action': 'explode'}]})
    with pytest.raises(ValueError, match='non-empty'):
        faults.install_plan({'rules': []})


def test_no_plan_points_are_noops():
    assert not faults.active()
    for name in faults.KNOWN_POINTS:
        assert faults.point(name) is None
    assert faults.stats() == {}


def test_counting_triggers_every_nth_after_times():
    faults.install_plan({'rules': [
        {'point': 'engine.decode_step', 'action': 'raise',
         'exc': 'RuntimeError', 'message': 'boom',
         'after': 2, 'every_nth': 3, 'times': 2}]})
    fired = []
    for i in range(1, 15):
        try:
            faults.point('engine.decode_step')
        except RuntimeError:
            fired.append(i)
    # Eligible hits start after 2; every 3rd eligible = hits 5, 8,
    # then the times=2 cap holds.
    assert fired == [5, 8]
    assert faults.stats()['engine.decode_step'] == {'hits': 14,
                                                    'fired': 2}


def test_at_trigger_fires_on_exact_hits():
    faults.install_plan({'rules': [
        {'point': 'http.handler', 'action': 'drop', 'at': [3, 7]}]})
    out = [faults.point('http.handler') for _ in range(8)]
    assert [i + 1 for i, o in enumerate(out) if o is faults.DROP] == \
        [3, 7]


def test_prob_trigger_is_seeded_and_replayable():
    def run():
        faults.install_plan({'seed': 123, 'rules': [
            {'point': 'jobs.monitor_probe', 'action': 'drop',
             'prob': 0.5}]})
        return [faults.point('jobs.monitor_probe') is faults.DROP
                for _ in range(64)]

    a, b = run(), run()
    assert a == b                      # same seed -> same firings
    assert any(a) and not all(a)       # actually probabilistic
    faults.install_plan({'seed': 124, 'rules': [
        {'point': 'jobs.monitor_probe', 'action': 'drop',
         'prob': 0.5}]})
    c = [faults.point('jobs.monitor_probe') is faults.DROP
         for _ in range(64)]
    assert c != a                      # different seed -> different


def test_plan_from_json_string_and_file(tmp_path):
    spec = {'rules': [{'point': 'checkpoint.save', 'action': 'raise',
                       'exc': 'OSError', 'message': 'disk gone'}]}
    faults.install_plan(json.dumps(spec))
    with pytest.raises(OSError, match='disk gone'):
        faults.point('checkpoint.save')
    path = tmp_path / 'plan.json'
    path.write_text(json.dumps(spec), encoding='utf-8')
    faults.install_plan(str(path))
    with pytest.raises(OSError, match='disk gone'):
        faults.point('checkpoint.save')
    faults.clear()
    assert faults.point('checkpoint.save') is None


def test_dotted_exception_path_and_default_type():
    faults.install_plan({'rules': [
        {'point': 'jobs.launch', 'action': 'raise',
         'exc': 'skypilot_tpu.robustness.errors.DeadlineExceededError',
         'times': 1},
        {'point': 'jobs.launch', 'action': 'raise', 'times': 1}]})
    with pytest.raises(DeadlineExceededError):
        faults.point('jobs.launch')
    with pytest.raises(faults.InjectedFault):
        faults.point('jobs.launch')


def test_delay_action_sleeps():
    faults.install_plan({'rules': [
        {'point': 'engine.device_get', 'action': 'delay',
         'delay_s': 0.03}]})
    t0 = time.monotonic()
    assert faults.point('engine.device_get') is None
    assert time.monotonic() - t0 >= 0.025


def test_scoped_rule_matches_only_its_context():
    """A scope is an eligibility filter BEFORE hit counting: calls
    outside the scope are invisible to the rule, so counters see
    only the matching stream."""
    faults.install_plan({'rules': [
        {'point': 'jobs.monitor_probe', 'action': 'drop',
         'scope': {'zone': 'us-east5-b'}, 'after': 1}]})
    # No context / wrong zone: never matches, never counts.
    assert faults.point('jobs.monitor_probe') is None
    assert faults.point('jobs.monitor_probe',
                        zone='us-west4-a') is None
    # First in-zone hit is eligible but after=1 defers it; second
    # fires — proving the wrong-zone calls above did not count.
    assert faults.point('jobs.monitor_probe',
                        zone='us-east5-b') is None
    assert faults.point('jobs.monitor_probe',
                        zone='us-east5-b') is faults.DROP
    assert faults.stats()['jobs.monitor_probe'] == {'hits': 2,
                                                    'fired': 1}


def test_scope_multi_key_and_validation():
    faults.install_plan({'rules': [
        {'point': 'jobs.monitor_probe', 'action': 'drop',
         'scope': {'zone': 'z1', 'job': '7'}}]})
    assert faults.point('jobs.monitor_probe', zone='z1') is None
    assert faults.point('jobs.monitor_probe', zone='z1',
                        job='8') is None
    assert faults.point('jobs.monitor_probe', zone='z1',
                        job='7') is faults.DROP
    with pytest.raises(ValueError, match='scope'):
        faults.install_plan({'rules': [
            {'point': 'jobs.monitor_probe',
             'scope': {'zone': 1}}]})


def test_windowed_rule_fires_only_inside_window():
    t = {'now': 0.0}
    faults.install_plan({'rules': [
        {'point': 'jobs.launch', 'action': 'raise',
         'exc': 'RuntimeError', 'start_s': 10.0,
         'duration_s': 5.0}]}, clock=lambda: t['now'])
    assert faults.point('jobs.launch') is None       # before
    t['now'] = 12.0
    with pytest.raises(RuntimeError):
        faults.point('jobs.launch')
    t['now'] = 15.0                                  # end exclusive
    assert faults.point('jobs.launch') is None
    with pytest.raises(ValueError, match='partial window'):
        faults.install_plan({'rules': [
            {'point': 'jobs.launch', 'start_s': 1.0}]})


def test_preempt_storm_drops_probes_for_scoped_jobs_in_window():
    """The derived point: one jobs.preempt_storm rule == a windowed,
    zone-scoped drop on jobs.monitor_probe, with a SEEDED start."""
    t = {'now': 0.0}
    plan = faults.install_plan({'seed': 11, 'rules': [
        {'point': 'jobs.preempt_storm',
         'scope': {'zone': 'us-east5-b'},
         'start_range': [20.0, 40.0], 'duration_s': 30.0}]},
        clock=lambda: t['now'])
    (window,) = plan.windows('jobs.monitor_probe')
    assert 20.0 <= window['start_s'] < 40.0
    assert window['end_s'] == pytest.approx(window['start_s'] + 30.0)
    assert window['scope'] == {'zone': 'us-east5-b'}
    # Same seed -> same storm start; different seed -> different.
    again = faults.FaultPlan(
        {'seed': 11, 'rules': [
            {'point': 'jobs.preempt_storm',
             'scope': {'zone': 'us-east5-b'},
             'start_range': [20.0, 40.0], 'duration_s': 30.0}]},
        clock=lambda: 0.0)
    assert again.windows('jobs.monitor_probe')[0]['start_s'] == \
        window['start_s']
    other = faults.FaultPlan(
        {'seed': 12, 'rules': [
            {'point': 'jobs.preempt_storm',
             'scope': {'zone': 'us-east5-b'},
             'start_range': [20.0, 40.0], 'duration_s': 30.0}]},
        clock=lambda: 0.0)
    assert other.windows('jobs.monitor_probe')[0]['start_s'] != \
        window['start_s']

    t['now'] = window['start_s'] + 1.0
    assert faults.point('jobs.monitor_probe',
                        zone='us-east5-b', job='1') is faults.DROP
    assert faults.point('jobs.monitor_probe',
                        zone='us-east5-b', job='2') is faults.DROP
    assert faults.point('jobs.monitor_probe',
                        zone='us-west4-a', job='3') is None
    t['now'] = window['end_s'] + 1.0
    assert faults.point('jobs.monitor_probe',
                        zone='us-east5-b', job='1') is None
    # Stats report under the derived point's own name.
    assert faults.stats()['jobs.preempt_storm']['fired'] == 2
    # A storm without a window fails at install, not silently.
    with pytest.raises(ValueError, match='requires a window'):
        faults.install_plan({'rules': [
            {'point': 'jobs.preempt_storm',
             'scope': {'zone': 'z'}}]})


def test_committed_example_storm_plan_installs():
    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))),
        'examples', 'fault_plans', 'zone_storm.json')
    plan = faults.install_plan(path)
    assert plan.windows('jobs.monitor_probe')
    assert plan.windows('jobs.launch')


# ---------------------------------------------------------------------------
# Backoff jitter (satellite)
# ---------------------------------------------------------------------------
def test_backoff_decorrelated_jitter_bounds_and_determinism():
    import random
    mk = lambda: common_utils.Backoff(1.0, max_backoff=8.0,
                                      jitter=True,
                                      rng=random.Random(7))
    a = [mk().current_backoff() for _ in range(1)]  # seeded first draw
    b1, b2 = mk(), mk()
    seq1 = [b1.current_backoff() for _ in range(20)]
    seq2 = [b2.current_backoff() for _ in range(20)]
    assert seq1 == seq2                    # seeded -> reproducible
    assert all(1.0 <= s <= 8.0 for s in seq1)
    assert len(set(seq1)) > 5              # actually jittered
    assert a[0] == seq1[0]


def test_backoff_without_jitter_is_unchanged():
    b = common_utils.Backoff(2.0, max_backoff=10.0, multiplier=2.0)
    assert [b.current_backoff() for _ in range(4)] == \
        [2.0, 4.0, 8.0, 10.0]


# ---------------------------------------------------------------------------
# engine chaos (tiny llama)
# ---------------------------------------------------------------------------
@pytest.fixture(scope='module')
def tiny_model():
    import jax
    jax.config.update('jax_platforms', 'cpu')
    import flax.linen as nn
    import jax.numpy as jnp

    from skypilot_tpu.models.llama import Llama, LlamaConfig
    model = Llama(LlamaConfig.tiny(kv_page_size=8, kv_total_pages=40))
    params = nn.meta.unbox(model.init(
        jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))['params'])
    return model, params


def _engine(tiny_model, **kw):
    from skypilot_tpu.models.batching import ContinuousBatchingEngine
    model, params = tiny_model
    kw.setdefault('num_slots', 2)
    kw.setdefault('max_total_len', 64)
    return ContinuousBatchingEngine(model, params, **kw)


def test_no_plan_greedy_output_byte_identical(tiny_model):
    """(d): an armed-but-never-firing plan and no plan at all produce
    byte-identical greedy output — the points really are no-ops."""
    eng = _engine(tiny_model)
    try:
        clean = eng.submit([5, 6, 7], max_new_tokens=8).result(
            timeout=120)
        faults.install_plan({'rules': [
            {'point': 'checkpoint.save', 'action': 'raise'}]})
        armed = eng.submit([5, 6, 7], max_new_tokens=8).result(
            timeout=120)
        assert armed == clean
    finally:
        eng.stop()


def test_poison_decode_step_outputs_bit_identical(tiny_model):
    """(a): one injected decode-step exception is contained — no
    request fails, no engine reset, and the output matches the clean
    run token for token (the fault fires before dispatch and before
    RNG is consumed)."""
    eng = _engine(tiny_model)
    try:
        clean = eng.submit([1, 2, 3, 4], max_new_tokens=10).result(
            timeout=120)
        faults.install_plan({'rules': [
            {'point': 'engine.decode_step', 'action': 'raise',
             'exc': 'RuntimeError', 'message': 'poison step',
             'after': 2, 'times': 1}]})
        poisoned = eng.submit([1, 2, 3, 4], max_new_tokens=10).result(
            timeout=120)
        assert poisoned == clean
        assert faults.stats()['engine.decode_step']['fired'] == 1
        assert eng.engine_restarts == 0
        assert eng.healthy()
    finally:
        faults.clear()
        eng.stop()


def test_poison_prefill_chunk_fails_only_that_slot(tiny_model):
    """(b): crash-only isolation — the poisoned request fails with
    the injected error; a sibling admitted alongside completes, and
    the engine serves bit-identically afterwards."""
    eng = _engine(tiny_model, prefill_chunk=8)
    try:
        clean = eng.submit(list(range(1, 20)),
                           max_new_tokens=5).result(timeout=120)
        faults.install_plan({'rules': [
            {'point': 'engine.prefill_chunk', 'action': 'raise',
             'exc': 'RuntimeError', 'message': 'poison prefill',
             'times': 1}]})
        victim = eng.submit(list(range(1, 20)), max_new_tokens=5)
        sibling = eng.submit([30, 31, 32], max_new_tokens=5)
        with pytest.raises(RuntimeError, match='poison prefill'):
            victim.result(timeout=120)
        assert len(sibling.result(timeout=120)) == 8
        faults.clear()
        again = eng.submit(list(range(1, 20)),
                           max_new_tokens=5).result(timeout=120)
        assert again == clean
        assert eng.healthy() and eng.engine_restarts == 0
    finally:
        faults.clear()
        eng.stop()


def test_deadline_reaps_mid_decode(tiny_model):
    eng = _engine(tiny_model)
    try:
        expired = eng.submit([1, 2, 3], max_new_tokens=4096,
                             deadline_s=0.02)
        healthy = eng.submit([4, 5, 6], max_new_tokens=5)
        with pytest.raises(DeadlineExceededError):
            expired.result(timeout=60)
        assert len(healthy.result(timeout=120)) == 8
        assert eng.deadline_exceeded == 1
        # The reaped slot's resources came back: a new request fits.
        assert len(eng.submit([7, 8], max_new_tokens=3).result(
            timeout=120)) == 5
    finally:
        eng.stop()


def test_deadline_reaps_queued_requests(tiny_model):
    eng = _engine(tiny_model, num_slots=1)
    try:
        hog = eng.submit([1, 2, 3], max_new_tokens=40)
        queued = eng.submit([4, 5, 6], max_new_tokens=40,
                            deadline_s=0.01)
        with pytest.raises(DeadlineExceededError):
            queued.result(timeout=60)
        hog.result(timeout=120)
        assert eng.queued_tokens() == 0
    finally:
        eng.stop()


def test_admission_control_sheds_by_request_count(tiny_model):
    eng = _engine(tiny_model, num_slots=1, max_queue_requests=2)
    try:
        futs, shed = [], 0
        for _ in range(10):
            try:
                futs.append(eng.submit([1, 2, 3], max_new_tokens=20))
            except QueueSaturatedError as e:
                assert e.retry_after_s > 0
                shed += 1
        assert shed > 0 and len(futs) >= 1
        assert eng.requests_shed == shed
        for f in futs:
            f.result(timeout=120)
        assert eng.queued_tokens() == 0
        assert not eng.saturated()
    finally:
        eng.stop()


def test_admission_control_sheds_by_token_budget(tiny_model):
    eng = _engine(tiny_model, num_slots=1, max_queue_tokens=16)
    try:
        hog = eng.submit(list(range(1, 9)), max_new_tokens=30)
        accepted, shed = [], 0
        for _ in range(6):
            try:
                accepted.append(eng.submit(list(range(1, 9)),
                                           max_new_tokens=2))
            except QueueSaturatedError:
                shed += 1
        assert shed > 0   # 8-token prompts trip a 16-token budget
        hog.result(timeout=120)
        for f in accepted:
            f.result(timeout=120)
        assert eng.queued_tokens() == 0
    finally:
        eng.stop()


@pytest.mark.filterwarnings(
    'ignore::pytest.PytestUnhandledThreadExceptionWarning')
def test_scheduler_death_fails_fast_not_hangs(tiny_model):
    """An injected SystemExit kills the scheduler thread (it is not
    an Exception, so the containment tiers can't catch it): pending
    futures fail with EngineDeadError, submit refuses new work, and
    healthy() flips — nobody hangs."""
    eng = _engine(tiny_model)
    try:
        faults.install_plan({'rules': [
            {'point': 'engine.decode_step', 'action': 'raise',
             'exc': 'SystemExit', 'times': 1}]})
        doomed = eng.submit([1, 2, 3], max_new_tokens=10)
        with pytest.raises(EngineDeadError):
            doomed.result(timeout=60)
        assert not eng.healthy()
        with pytest.raises(EngineDeadError):
            eng.submit([1], max_new_tokens=1)
    finally:
        faults.clear()
        eng.stop()


# ---------------------------------------------------------------------------
# HTTP plane: health probes, 429/504, metrics, drain
# ---------------------------------------------------------------------------
@pytest.fixture()
def robust_server(tiny_model):
    """A live inference HTTP server over a hardened engine: bounded
    queue, 30s deadline ceiling."""
    from skypilot_tpu.inference.http_server import make_server
    from skypilot_tpu.inference.runtime import InferenceRuntime
    model, params = tiny_model
    engine = _engine(tiny_model, num_slots=2, max_queue_requests=3)
    rt = InferenceRuntime(
        model=model, params=params,
        vocab_size=model.config.vocab_size, model_name='llama-tiny',
        max_total_len=64, spec_total=64, speculative=0, engine=engine,
        request_timeout=30.0, max_queue_requests=3)
    server = make_server(rt, 0)
    port = server.server_address[1]
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    yield f'http://127.0.0.1:{port}', server, rt, engine
    try:
        server.shutdown()
    except Exception:  # pylint: disable=broad-except
        pass
    engine.stop()


def _post(url, path, body, timeout=120):
    req = urllib.request.Request(
        url + path, data=json.dumps(body).encode(),
        headers={'Content-Type': 'application/json'})
    return urllib.request.urlopen(req, timeout=timeout)


def test_healthz_and_readyz(robust_server):
    url, server, _rt, engine = robust_server
    assert json.loads(urllib.request.urlopen(
        url + '/healthz', timeout=10).read()) == {'status': 'alive'}
    ready = json.loads(urllib.request.urlopen(
        url + '/readyz', timeout=10).read())
    assert ready == {'ready': True, 'reasons': []}

    # Draining: readiness flips (with the reason), liveness does not.
    server.draining.set()
    with pytest.raises(urllib.error.HTTPError) as exc:
        urllib.request.urlopen(url + '/readyz', timeout=10)
    assert exc.value.code == 503
    assert 'draining' in json.loads(exc.value.read())['reasons']
    assert urllib.request.urlopen(url + '/healthz',
                                  timeout=10).status == 200
    server.draining.clear()
    assert urllib.request.urlopen(url + '/readyz',
                                  timeout=10).status == 200
    assert engine.healthy()


def test_timeout_field_maps_to_504(robust_server):
    url, _server, rt, engine = robust_server
    before = engine.deadline_exceeded
    with pytest.raises(urllib.error.HTTPError) as exc:
        _post(url, '/generate', {'tokens': [[1, 2, 3]],
                                 'max_new_tokens': 4096,
                                 'timeout': 0.02})
    assert exc.value.code == 504
    assert 'DeadlineExceededError' in json.loads(
        exc.value.read())['error']
    assert engine.deadline_exceeded == before + 1
    stats = json.loads(urllib.request.urlopen(
        url + '/stats', timeout=30).read())
    assert stats['serving']['deadline_exceeded'] >= 1
    assert stats['deadline_exceeded'] >= 1
    assert rt.metrics.deadline_exceeded >= 1


def test_saturation_sheds_429_with_retry_after(robust_server):
    url, _server, _rt, engine = robust_server
    import concurrent.futures as cf

    def post_one(_):
        try:
            with _post(url, '/generate', {'tokens': [[1, 2, 3]],
                                          'max_new_tokens': 50}) as r:
                r.read()
            return 200, None
        except urllib.error.HTTPError as e:
            retry = e.headers.get('Retry-After')
            e.read()
            return e.code, retry

    with cf.ThreadPoolExecutor(10) as ex:
        results = list(ex.map(post_one, range(10)))
    codes = sorted(c for c, _ in results)
    assert codes.count(200) >= 2          # slots kept serving
    assert codes.count(429) >= 1          # overload was shed
    assert all(r is not None and int(r) >= 1
               for c, r in results if c == 429)
    assert engine.requests_shed >= codes.count(429)
    stats = json.loads(urllib.request.urlopen(
        url + '/stats', timeout=30).read())
    assert stats['serving']['requests_shed'] >= 1
    assert stats['max_queue_requests'] == 3


def test_metrics_expose_robustness_counters(robust_server):
    url, _server, _rt, _engine = robust_server
    text = urllib.request.urlopen(url + '/metrics',
                                  timeout=30).read().decode()
    for family in ('skypilot_serving_requests_shed_total',
                   'skypilot_serving_deadline_exceeded_total',
                   'skypilot_serving_engine_restarts_total'):
        assert f'# TYPE {family} counter' in text, family


def test_graceful_drain_completes_inflight_then_exits(robust_server):
    """Satellite: the SIGTERM drain contract — in-flight requests
    complete, new connections are refused after the accept loop
    stops, /readyz is 503 throughout, and the process 'exits' 0 (via
    the injectable exit_fn)."""
    from skypilot_tpu.inference.http_server import drain
    url, server, rt, _engine = robust_server

    results = []

    def inflight():
        with _post(url, '/generate', {'tokens': [[1, 2, 3]],
                                      'max_new_tokens': 120}) as r:
            results.append(json.loads(r.read()))

    t = threading.Thread(target=inflight)
    t.start()
    time.sleep(0.05)   # let the POST reach the handler

    exited = []
    drained = threading.Thread(
        target=lambda: drain(server, rt, drain_grace=60,
                             straggler_grace=0.5,
                             exit_fn=exited.append))
    drained.start()
    # Event-driven: the drain flips the flag BEFORE its straggler
    # window, so a probe issued right after the event lands inside it.
    assert server.draining.wait(timeout=10)
    with pytest.raises(urllib.error.HTTPError) as exc:
        urllib.request.urlopen(url + '/readyz', timeout=5)
    assert exc.value.code == 503
    assert 'draining' in json.loads(exc.value.read())['reasons']
    drained.join(timeout=60)
    t.join(timeout=60)
    assert exited == [0]
    # The in-flight request completed with its full generation
    # (capped at the engine's max_total_len=64).
    assert results and len(results[0]['tokens'][0]) == 64
    # New connections are refused (or never served) now.
    with pytest.raises(OSError):
        urllib.request.urlopen(url + '/healthz', timeout=2)


# ---------------------------------------------------------------------------
# jobs plane: launch retries, probe-drop recovery, recovery metric
# ---------------------------------------------------------------------------
def test_launch_retries_ride_out_injected_failures(monkeypatch):
    """Two injected ResourcesUnavailableErrors at jobs.launch are
    retried with (jittered) backoff; the third attempt lands."""
    from skypilot_tpu.jobs import recovery_strategy as rs

    launches = []
    monkeypatch.setattr(
        rs.execution, 'launch',
        lambda task, **kw: (launches.append(kw) or (7, object())))
    sleeps = []
    monkeypatch.setattr(rs.time, 'sleep', sleeps.append)

    class _Task:
        resources = ()

    ex = rs.StrategyExecutor('chaos-cluster', _Task())
    faults.install_plan({'rules': [
        {'point': 'jobs.launch', 'action': 'raise',
         'exc': 'skypilot_tpu.exceptions.ResourcesUnavailableError',
         'message': 'injected preemption', 'times': 2}]})
    assert ex._launch_with_retries(first_launch=False) == 7
    assert len(launches) == 1           # only the surviving attempt
    assert len(sleeps) == 2             # backoff between retries
    assert all(s > 0 for s in sleeps)
    assert faults.stats()['jobs.launch']['fired'] == 2


def test_launch_gives_up_after_max_attempts(monkeypatch):
    from skypilot_tpu import exceptions
    from skypilot_tpu.jobs import recovery_strategy as rs
    monkeypatch.setattr(rs.time, 'sleep', lambda s: None)
    monkeypatch.setattr(rs.execution, 'launch',
                        lambda task, **kw: (_ for _ in ()).throw(
                            AssertionError('must not launch')))

    class _Task:
        resources = ()

    ex = rs.StrategyExecutor('chaos-cluster', _Task())
    faults.install_plan({'rules': [
        {'point': 'jobs.launch', 'action': 'raise',
         'exc': 'skypilot_tpu.exceptions.ResourcesUnavailableError',
         'message': 'zone is gone'}]})
    with pytest.raises(exceptions.ResourcesUnavailableError):
        ex._launch_with_retries(first_launch=False, max_attempts=3)


def test_monitor_probe_drop_drives_recovery(monkeypatch):
    """A fault plan dropping agent probes is a synthetic preemption:
    the controller walks its real unreachable-grace machinery into
    _recover(), after which (probes restored) the job completes."""
    from skypilot_tpu.agent import job_lib as agent_job_lib
    from skypilot_tpu.jobs import controller as ctrl_mod
    from skypilot_tpu.jobs import failure_sources
    from skypilot_tpu.jobs import state

    monkeypatch.setattr(ctrl_mod, '_POLL_SECONDS', 0.005)
    monkeypatch.setattr(ctrl_mod, '_UNREACHABLE_GRACE_SECONDS', 0.02)
    monkeypatch.setattr(failure_sources, 'check_failed',
                        lambda name: None)
    status_log = []
    monkeypatch.setattr(state, 'set_status',
                        lambda jid, st, **kw: status_log.append(st))
    monkeypatch.setattr(state, 'bump_recovery', lambda jid: None)
    monkeypatch.setattr(state, 'set_stage', lambda jid, s: None)
    monkeypatch.setattr(state, 'set_agent_job_id', lambda jid, a: None)

    ctrl = ctrl_mod.JobController.__new__(ctrl_mod.JobController)
    ctrl.job_id = 1
    ctrl.cluster_name = 'chaos-managed'
    ctrl.group = None
    ctrl.pooled = False
    ctrl.stage = 0
    ctrl.stage_configs = [{}]
    ctrl.stage_max_restarts = 0
    ctrl._stage_restarts = 0
    ctrl._cancelled = False

    recovered = []

    class _Agent:
        def get_job(self, agent_job_id):
            st = (agent_job_lib.JobStatus.SUCCEEDED if recovered
                  else agent_job_lib.JobStatus.RUNNING)
            return {'status': st}

    ctrl._agent = lambda: _Agent()
    ctrl._cleanup = lambda cancel_job: None

    def _recover():
        recovered.append(True)
        faults.clear()   # the preempted zone "comes back"
        return 2

    ctrl._recover = _recover

    # Probes succeed twice, then every probe drops until recovery.
    faults.install_plan({'rules': [
        {'point': 'jobs.monitor_probe', 'action': 'drop',
         'after': 2, 'times': 100}]})
    final = ctrl._monitor_loop(agent_job_id=1)
    assert recovered == [True]
    assert final == state.ManagedJobStatus.SUCCEEDED
    assert state.ManagedJobStatus.RUNNING in status_log


def test_recovery_attempt_metric_labeled_by_strategy():
    from skypilot_tpu.jobs import recovery_strategy as rs
    from skypilot_tpu.observability import catalog
    child = catalog.counter(
        'skypilot_jobs_recovery_attempts_total').labels(
            strategy='failover')
    before = child.value
    rs._count_recovery_attempt(rs.FailoverStrategyExecutor.NAME)
    assert child.value == before + 1
    assert rs.FailoverStrategyExecutor.NAME == 'failover'
    assert rs.EagerNextRegionStrategyExecutor.NAME == \
        'eager_next_region'


# ---------------------------------------------------------------------------
# checkpoint.save point + hygiene
# ---------------------------------------------------------------------------
def test_checkpoint_save_point_fires(tmp_path):
    pytest.importorskip('orbax.checkpoint')
    from skypilot_tpu.parallel.checkpoints import CheckpointManager
    mgr = CheckpointManager(str(tmp_path / 'ckpt'))
    faults.install_plan({'rules': [
        {'point': 'checkpoint.save', 'action': 'raise',
         'exc': 'OSError', 'message': 'bucket unreachable',
         'times': 1}]})
    with pytest.raises(OSError, match='bucket unreachable'):
        mgr.save(0, {'x': 1})
    faults.clear()


def test_robustness_package_is_static_clean():
    """Satellite: `stpu check` has nothing to say about robustness/
    (no baseline rows, no suppressions needed)."""
    from skypilot_tpu import analysis
    pkg = os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))),
        'skypilot_tpu', 'robustness')
    assert analysis.run_paths([pkg]) == []
