"""KV-cache decode correctness + generation behavior."""
import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from skypilot_tpu.models import generate as gen
from skypilot_tpu.models.llama import Llama, LlamaConfig


@pytest.fixture(scope='module')
def llama_tiny():
    cfg = LlamaConfig.tiny(dtype=jnp.float32)
    model = Llama(cfg)
    tokens = jnp.ones((2, 8), jnp.int32)
    params = nn.meta.unbox(
        model.init(jax.random.PRNGKey(0), tokens)['params'])
    return model, params


@pytest.mark.slow
def test_cached_decode_matches_full_forward(llama_tiny):
    model, params = llama_tiny
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 12), 0,
                                model.config.vocab_size, jnp.int32)
    full, decoded = gen.teacher_forced_logits(model, params, tokens)
    np.testing.assert_allclose(np.asarray(decoded), np.asarray(full),
                               rtol=2e-3, atol=2e-3)


@pytest.mark.slow
def test_greedy_generation(llama_tiny):
    model, params = llama_tiny
    prompt = jax.random.randint(jax.random.PRNGKey(2), (2, 4), 0,
                                model.config.vocab_size, jnp.int32)
    fn = gen.make_generate_fn(model, max_total_len=12)
    out = fn(params, prompt, jax.random.PRNGKey(0))
    assert out.shape == (2, 12)
    # Prompt preserved.
    np.testing.assert_array_equal(np.asarray(out[:, :4]),
                                  np.asarray(prompt))
    # Greedy is deterministic.
    out2 = fn(params, prompt, jax.random.PRNGKey(99))
    np.testing.assert_array_equal(np.asarray(out), np.asarray(out2))
    # Greedy continuation matches argmax of the full forward at the
    # prompt boundary.
    logits = model.apply({'params': params}, prompt)
    expected_next = jnp.argmax(logits[:, -1], axis=-1)
    np.testing.assert_array_equal(np.asarray(out[:, 4]),
                                  np.asarray(expected_next))


@pytest.mark.slow
def test_sampled_generation_varies_with_rng(llama_tiny):
    model, params = llama_tiny
    prompt = jnp.ones((1, 3), jnp.int32)
    fn = gen.make_generate_fn(model, max_total_len=16, temperature=1.0)
    a = fn(params, prompt, jax.random.PRNGKey(0))
    b = fn(params, prompt, jax.random.PRNGKey(1))
    assert not np.array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.slow
def test_continuous_batching_matches_batch_generate():
    """Continuous batching (models/batching.py) must produce exactly the
    greedy tokens of the one-shot scan engine, including for requests
    admitted mid-decode (the whole point of slot-based serving)."""
    import numpy as np
    from skypilot_tpu.models import generate as gen
    from skypilot_tpu.models.batching import ContinuousBatchingEngine
    from skypilot_tpu.models.llama import Llama, LlamaConfig

    # float32: random-init logits are nearly flat, and the engine's
    # batched decode may fuse differently than the batch-1 reference —
    # bf16 argmax ties would make the comparison flaky.
    cfg = LlamaConfig.tiny(dtype=jnp.float32)
    model = Llama(cfg)
    params = nn.meta.unbox(model.init(
        jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))['params'])

    max_total = 32
    rng = np.random.RandomState(0)
    prompts = [list(rng.randint(1, cfg.vocab_size, size=n))
               for n in (5, 9, 3, 12)]

    # Reference outputs: the one-shot scan engine, one prompt at a time.
    expected = []
    for p in prompts:
        fn = gen.make_generate_fn(model, max_total, temperature=0.0)
        out = fn(params, jnp.asarray([p], jnp.int32),
                 jax.random.PRNGKey(1))
        expected.append(np.asarray(out)[0].tolist())

    engine = ContinuousBatchingEngine(model, params, num_slots=2,
                                      max_total_len=max_total,
                                      temperature=0.0)
    try:
        # Submit all four into TWO slots: the later ones are admitted
        # while earlier ones are mid-decode.
        futs = [engine.submit(p, max_new_tokens=max_total - len(p))
                for p in prompts]
        results = [f.result(timeout=300) for f in futs]
    finally:
        engine.stop()

    for p, got, want in zip(prompts, results, expected):
        assert got[:len(p)] == list(p)
        # Compare the generated continuation (engine stops at
        # max_total; scan engine pads to max_total identically).
        assert got == want[:len(got)], (p, got, want)


@pytest.mark.slow
def test_mixtral_kv_decode_matches_full_forward():
    """Mixtral serving path: incremental KV-cache decode must produce
    the same greedy tokens as re-running the full (training-path)
    forward over the growing prefix."""
    from skypilot_tpu.models.mixtral import Mixtral, MixtralConfig

    cfg = MixtralConfig.tiny(dtype=jnp.float32)
    model = Mixtral(cfg)
    params = nn.meta.unbox(model.init(
        jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))['params'])

    prompt = [7, 3, 11, 42]
    max_total = 12

    # Reference rollout: full forward (decode=False) per step.
    seq = list(prompt)
    for _ in range(max_total - len(prompt)):
        logits, _aux = model.apply(
            {'params': params}, jnp.asarray([seq], jnp.int32))
        seq.append(int(jnp.argmax(logits[0, -1])))

    fn = gen.make_generate_fn(model, max_total, temperature=0.0)
    out = fn(params, jnp.asarray([prompt], jnp.int32),
             jax.random.PRNGKey(1))
    assert np.asarray(out)[0].tolist() == seq


@pytest.mark.slow
def test_gpt_kv_decode_matches_full_forward():
    """GPT serving path: the KV-cache decode (absolute position
    embeddings + per-row cache) must match the full-forward greedy
    rollout — all three model families share the serving engines."""
    from skypilot_tpu.models.gpt import GPT, GPTConfig

    cfg = GPTConfig.tiny(dtype=jnp.float32)
    model = GPT(cfg)
    params = nn.meta.unbox(model.init(
        jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))['params'])

    prompt = [9, 1, 33]
    max_total = 10
    seq = list(prompt)
    for _ in range(max_total - len(prompt)):
        logits = model.apply({'params': params},
                             jnp.asarray([seq], jnp.int32))
        seq.append(int(jnp.argmax(logits[0, -1])))

    fn = gen.make_generate_fn(model, max_total, temperature=0.0)
    out = fn(params, jnp.asarray([prompt], jnp.int32),
             jax.random.PRNGKey(1))
    assert np.asarray(out)[0].tolist() == seq


@pytest.mark.slow
def test_paged_engine_under_page_pressure():
    """A page pool SMALLER than num_slots*max_total_len still serves
    every request: admission stalls until a finishing sequence
    releases pages (the whole point of paged KV)."""
    import numpy as np
    from skypilot_tpu.models.batching import ContinuousBatchingEngine
    from skypilot_tpu.models.llama import Llama, LlamaConfig

    # 2 slots x max_total 32 tokens = 64 dense-equivalent tokens, but
    # the pool holds only 5 pages x 8 tokens = 40 (incl. trash page):
    # both slots cannot be at full depth simultaneously.
    cfg = LlamaConfig.tiny(dtype=jnp.float32, kv_page_size=8,
                           kv_total_pages=5)
    model = Llama(cfg)
    params = nn.meta.unbox(model.init(
        jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))['params'])
    engine = ContinuousBatchingEngine(model, params, num_slots=2,
                                      max_total_len=32, temperature=0.0)
    assert engine.paged
    assert 'k_pages' in str(jax.tree_util.tree_structure(engine.cache))
    rng = np.random.RandomState(0)
    prompts = [list(rng.randint(1, cfg.vocab_size, size=n))
               for n in (9, 12, 5)]
    try:
        futs = [engine.submit(p, max_new_tokens=32 - len(p))
                for p in prompts]
        results = [f.result(timeout=300) for f in futs]
    finally:
        engine.stop()
    for p, got in zip(prompts, results):
        assert got[:len(p)] == list(p)
        assert len(got) > len(p)  # actually generated
    # No page leaked: every usable page (4; page 0 is trash) is either
    # free or resident-evictable in the prefix cache (completed
    # prompts' full pages stay cached for reuse).
    cached = len(engine.prefix_cache.lru) if engine.prefix_cache else 0
    assert engine.allocator.free_pages + cached == 4


@pytest.mark.slow
def test_paged_pool_too_small():
    """An explicit paged=True with a pool that cannot hold one
    full-depth sequence fails FAST at construction; with paged=None
    the engine silently falls back to dense (no servable-length
    regression vs the dense path)."""
    from skypilot_tpu.models.batching import ContinuousBatchingEngine
    from skypilot_tpu.models.llama import Llama, LlamaConfig

    cfg = LlamaConfig.tiny(dtype=jnp.float32, kv_page_size=8,
                           kv_total_pages=3)  # 2 usable pages = 16 tok
    model = Llama(cfg)
    params = nn.meta.unbox(model.init(
        jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))['params'])
    with pytest.raises(ValueError, match='kv_total_pages'):
        ContinuousBatchingEngine(model, params, num_slots=2,
                                 max_total_len=32, paged=True)
    engine = ContinuousBatchingEngine(model, params, num_slots=2,
                                      max_total_len=32)
    try:
        assert not engine.paged  # auto-detect refuses the small pool
    finally:
        engine.stop()


def _paged_vs_dense_decode(model_ctor, cfg):
    """Teacher-force tokens through dense and paged decode paths with
    identical params; logits must match."""
    import numpy as np
    model = model_ctor(cfg)
    params = nn.meta.unbox(model.init(
        jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))['params'])

    def init_cache(**kw):
        cache = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((2, 1), jnp.int32),
                           positions=jnp.zeros((2, 1), jnp.int32),
                           decode=True, **kw)['cache']
        return jax.tree.map(jnp.zeros_like, nn.meta.unbox(cache))

    pages_per_seq = -(-32 // cfg.kv_page_size)
    page_indices = jnp.asarray(
        [[1 + i for i in range(pages_per_seq)],
         [1 + pages_per_seq + i for i in range(pages_per_seq)]],
        jnp.int32)
    dense_cache = init_cache()
    paged_cache = init_cache(page_indices=page_indices)
    rs = np.random.RandomState(1)
    for t in range(10):
        tok = jnp.asarray(rs.randint(1, cfg.vocab_size, (2, 1)),
                          jnp.int32)
        pos = jnp.full((2, 1), t, jnp.int32)
        dense_out, mut_d = model.apply(
            {'params': params, 'cache': dense_cache}, tok,
            positions=pos, decode=True, mutable=['cache'])
        paged_out, mut_p = model.apply(
            {'params': params, 'cache': paged_cache}, tok,
            positions=pos, decode=True, mutable=['cache'],
            page_indices=page_indices)
        dense_cache, paged_cache = mut_d['cache'], mut_p['cache']
        np.testing.assert_allclose(np.asarray(paged_out),
                                   np.asarray(dense_out),
                                   atol=2e-2, rtol=2e-2,
                                   err_msg=f'step {t}')


@pytest.mark.slow
def test_gpt_paged_decode_matches_dense():
    from skypilot_tpu.models.gpt import GPT, GPTConfig
    _paged_vs_dense_decode(GPT, GPTConfig.tiny(kv_page_size=8,
                                               kv_total_pages=16))


@pytest.mark.slow
def test_mixtral_paged_decode_matches_dense():
    from skypilot_tpu.models.mixtral import Mixtral, MixtralConfig
    _paged_vs_dense_decode(Mixtral,
                           MixtralConfig.tiny(kv_page_size=8,
                                              kv_total_pages=16))


@pytest.mark.slow
def test_deepseek_absorbed_decode_matches_full_forward():
    """Greedy rollout through the absorbed latent-cache decode path
    must reproduce the full-forward logits path token-for-token."""
    from skypilot_tpu.models.deepseek import Deepseek, DeepseekConfig
    from skypilot_tpu.models.generate import make_generate_fn
    cfg = DeepseekConfig.tiny(dtype=jnp.float32)
    model = Deepseek(cfg)
    rng = jax.random.PRNGKey(7)
    prompt = jax.random.randint(rng, (2, 6), 0, cfg.vocab_size, jnp.int32)
    params = model.init(jax.random.PRNGKey(1), prompt)['params']
    import flax.linen as nn
    params = nn.meta.unbox(params)

    gen = make_generate_fn(model, max_total_len=12)
    out = gen(params, prompt, jax.random.PRNGKey(0))
    assert out.shape[1] == 12

    # Teacher-forcing check: replay the generated sequence through the
    # full (non-decode) forward pass; argmax at each step must equal
    # the next generated token.
    logits = model.apply({'params': params}, out)
    for t in range(6 - 1, 12 - 1):
        expect = jnp.argmax(logits[:, t], axis=-1)
        assert jnp.array_equal(expect, out[:, t + 1]), t


@pytest.mark.slow
def test_deepseek_continuous_batching_smoke():
    """MLA's latent cache rides the engine's dense (non-paged) path —
    DeepseekConfig declares no page pool, so paged auto-disables."""
    import numpy as np
    from skypilot_tpu.models.batching import ContinuousBatchingEngine
    from skypilot_tpu.models.deepseek import Deepseek, DeepseekConfig
    cfg = DeepseekConfig.tiny(dtype=jnp.float32)
    model = Deepseek(cfg)
    import flax.linen as nn
    params = nn.meta.unbox(model.init(
        jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))['params'])
    engine = ContinuousBatchingEngine(model, params, num_slots=2,
                                      max_total_len=24, temperature=0.0)
    assert engine.paged is False
    try:
        rng = np.random.RandomState(3)
        prompts = [list(rng.randint(1, cfg.vocab_size, size=n))
                   for n in (4, 7, 5)]
        futs = [engine.submit(p, max_new_tokens=8) for p in prompts]
        results = [f.result(timeout=300) for f in futs]
    finally:
        engine.stop()
    for p, got in zip(prompts, results):
        assert got[:len(p)] == list(p)
        assert len(got) > len(p)


@pytest.mark.slow
@pytest.mark.parametrize('family', ['llama', 'gpt', 'deepseek'])
def test_speculative_matches_greedy(family):
    """Prompt-lookup speculative decoding must produce EXACTLY the
    greedy tokens of the plain scan engine, for every model family,
    on a repetitive prompt (exercises multi-token accepts) and a
    random one (exercises rejects)."""
    from skypilot_tpu.models.generate import (make_generate_fn,
                                              make_speculative_generate_fn)
    if family == 'llama':
        from skypilot_tpu.models.llama import Llama, LlamaConfig
        cfg = LlamaConfig.tiny(dtype=jnp.float32)
        model = Llama(cfg)
    elif family == 'gpt':
        from skypilot_tpu.models.gpt import GPT, GPTConfig
        cfg = GPTConfig.tiny(dtype=jnp.float32)
        model = GPT(cfg)
    else:
        from skypilot_tpu.models.deepseek import Deepseek, DeepseekConfig
        cfg = DeepseekConfig.tiny(dtype=jnp.float32)
        model = Deepseek(cfg)
    params = nn.meta.unbox(model.init(
        jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))['params'])

    repetitive = jnp.asarray(
        [[5, 9, 2, 5, 9, 2, 5, 9], [3, 3, 3, 3, 3, 3, 3, 3]], jnp.int32)
    random_p = jax.random.randint(jax.random.PRNGKey(5), (2, 8), 0,
                                  cfg.vocab_size, jnp.int32)
    for prompt in (repetitive, random_p):
        want = make_generate_fn(model, 24)(params, prompt,
                                           jax.random.PRNGKey(0))
        got = make_speculative_generate_fn(model, 24, draft_k=4,
                                           ngram=2)(
            params, prompt, jax.random.PRNGKey(0))
        assert jnp.array_equal(got, want), (family, got, want)


@pytest.mark.slow
@pytest.mark.parametrize('family', ['llama', 'gpt', 'deepseek', 'mixtral'])
def test_prefill_chunk_only_matches_full_cache_path(family):
    """The prefill fast path (chunk-local S x S attention,
    flax kwarg prefill=True) must produce the same logits and the same
    cache contents as the general chunked path — the empty-cache
    contract makes them mathematically identical, and subsequent
    decode steps must continue correctly off the prefill'd cache."""
    if family == 'llama':
        from skypilot_tpu.models.llama import Llama, LlamaConfig
        model = Llama(LlamaConfig.tiny(dtype=jnp.float32))
    elif family == 'gpt':
        from skypilot_tpu.models.gpt import GPT, GPTConfig
        model = GPT(GPTConfig.tiny(dtype=jnp.float32))
    elif family == 'mixtral':
        from skypilot_tpu.models.mixtral import Mixtral, MixtralConfig
        model = Mixtral(MixtralConfig.tiny(dtype=jnp.float32))
    else:
        from skypilot_tpu.models.deepseek import Deepseek, DeepseekConfig
        model = Deepseek(DeepseekConfig.tiny(dtype=jnp.float32))
    params = nn.meta.unbox(model.init(
        jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))['params'])
    prompt = jax.random.randint(jax.random.PRNGKey(7), (2, 8), 0,
                                model.config.vocab_size, jnp.int32)
    positions = jnp.broadcast_to(jnp.arange(8), (2, 8))

    def fresh_cache():
        cache = model.init(
            jax.random.PRNGKey(0), jnp.zeros((2, 1), jnp.int32),
            positions=jnp.zeros((2, 1), jnp.int32), decode=True)['cache']
        return jax.tree.map(jnp.zeros_like, nn.meta.unbox(cache))

    logits_fast, mut_fast = model.apply(
        {'params': params, 'cache': fresh_cache()}, prompt,
        positions=positions, decode=True, mutable=['cache'],
        prefill=True)
    logits_slow, mut_slow = model.apply(
        {'params': params, 'cache': fresh_cache()}, prompt,
        positions=positions, decode=True, mutable=['cache'])
    np.testing.assert_allclose(np.asarray(logits_fast),
                               np.asarray(logits_slow),
                               rtol=2e-4, atol=2e-4)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-4),
        mut_fast['cache'], mut_slow['cache'])
    if family == 'mixtral':
        # MoE expert capacity scales with seq, so decode-mode logits
        # differ from the training forward by capacity drops — greedy-
        # token parity is covered by
        # test_mixtral_kv_decode_matches_full_forward; the fast-vs-slow
        # prefill equivalence above is the contract under test here.
        return
    # One more decode step off the prefill'd cache matches the full
    # forward's next-position logits.
    nxt = jnp.full((2, 1), 3, jnp.int32)
    step_logits, _ = model.apply(
        {'params': params, 'cache': mut_fast['cache']}, nxt,
        positions=jnp.full((2, 1), 8, jnp.int32), decode=True,
        mutable=['cache'])
    full = model.apply({'params': params},
                       jnp.concatenate([prompt, nxt], axis=1))
    np.testing.assert_allclose(np.asarray(step_logits[:, 0]),
                               np.asarray(full[:, -1]),
                               rtol=2e-3, atol=2e-3)


def test_speculative_total_len_contract():
    """make_speculative_generate_fn needs K tokens of headroom below
    max_seq_len; serve_lm clamps at startup — this pins the contract
    both ways (builds at max_seq_len - K, refuses at max_seq_len)."""
    from skypilot_tpu.models.generate import make_speculative_generate_fn
    from skypilot_tpu.models.llama import Llama, LlamaConfig
    cfg = LlamaConfig.tiny(dtype=jnp.float32)
    model = Llama(cfg)
    params = nn.meta.unbox(model.init(
        jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))['params'])
    k = 4
    fn = make_speculative_generate_fn(
        model, cfg.max_seq_len - k, draft_k=k)
    prompt = jnp.asarray([[5, 9, 2, 5, 9, 2, 5, 9]], jnp.int32)
    out = fn(params, prompt, jax.random.PRNGKey(0))
    assert out.shape == (1, cfg.max_seq_len - k)
    with pytest.raises(AssertionError):
        make_speculative_generate_fn(model, cfg.max_seq_len, draft_k=k)
