"""Resources parsing/validation/cost (reference analog:
tests/unit_tests/test_resources.py)."""
import pytest

from skypilot_tpu import exceptions
from skypilot_tpu.resources import Resources


def test_tpu_slice_basic():
    r = Resources(accelerators='tpu-v5p-128', infra='gcp')
    assert r.is_tpu_slice
    assert r.hosts_per_node == 16
    assert r.is_launchable()
    assert r.get_hourly_cost() == pytest.approx(4.20 * 64, rel=0.01)


def test_spot_cheaper():
    on_demand = Resources(accelerators='tpu-v5e-16', infra='gcp')
    spot = Resources(accelerators='tpu-v5e-16', infra='gcp', use_spot=True)
    assert spot.get_hourly_cost() < on_demand.get_hourly_cost()


def test_accelerator_string_forms():
    assert Resources(accelerators='tpu-v5e-8').accelerators == {
        'tpu-v5e-8': 1}
    assert Resources(accelerators='A100:4').accelerators == {'A100': 4}
    assert Resources(accelerators={'a100': 8}).accelerators == {'A100': 8}


def test_infra_parsing():
    r = Resources(infra='gcp/us-central2/us-central2-b')
    assert str(r.cloud) == 'GCP'
    assert r.region == 'us-central2'
    assert r.zone == 'us-central2-b'
    r2 = Resources(infra='gcp/*/us-central1-a')
    assert r2.region == 'us-central1'


def test_zone_infers_region_and_cloud():
    r = Resources(zone='us-central2-b')
    assert r.region == 'us-central2'
    assert str(r.cloud) == 'GCP'


def test_copy_override():
    r = Resources(accelerators='tpu-v5e-16', use_spot=True)
    r2 = r.copy(use_spot=False)
    assert r2.accelerators == {'tpu-v5e-16': 1}
    assert not r2.use_spot
    r3 = r.copy(infra='gcp/us-west4')
    assert r3.region == 'us-west4'
    assert r3.use_spot


def test_yaml_round_trip():
    cfgs = [
        {'infra': 'gcp', 'accelerators': 'tpu-v5p-64',
         'accelerator_args': {'runtime_version': 'v2-alpha-tpuv5'},
         'use_spot': True, 'disk_size': 512},
        {'cpus': '8+', 'memory': '32+'},
        {'accelerators': 'H100:8', 'ports': ['8080', '9000-9010'],
         'labels': {'team': 'ml'}},
    ]
    for cfg in cfgs:
        rs = Resources.from_yaml_config(cfg)
        assert len(rs) == 1
        r = rs.pop()
        again = Resources.from_yaml_config(r.to_yaml_config()).pop()
        assert r == again


def test_any_of():
    rs = Resources.from_yaml_config({
        'any_of': [{'accelerators': 'tpu-v5e-8'},
                   {'accelerators': 'tpu-v6e-8'}],
        'use_spot': True,
    })
    assert len(rs) == 2
    assert all(r.use_spot for r in rs)


def test_invalid():
    with pytest.raises(exceptions.InvalidResourcesError):
        Resources(accelerators={'tpu-v5e-8': 2})
    with pytest.raises(exceptions.InvalidResourcesError):
        Resources(accelerators='tpu-v5e-8', instance_type='n2-standard-8')
    with pytest.raises(ValueError):
        Resources(infra='gcp/nowhere')
    with pytest.raises(exceptions.InvalidTaskYAMLError):
        Resources.from_yaml_config({'bogus_field': 1})


def test_autostop_forms():
    assert Resources(autostop=True).autostop == {
        'idle_minutes': 5, 'down': False}
    assert Resources(autostop=10).autostop == {
        'idle_minutes': 10, 'down': False}
    assert Resources(autostop={'idle_minutes': 3, 'down': True}).autostop == {
        'idle_minutes': 3, 'down': True}
    assert Resources(autostop=False).autostop is None


def test_less_demanding_than():
    vague = Resources(accelerators='tpu-v5e-16')
    pinned = Resources(accelerators='tpu-v5e-16', infra='gcp/us-west4',
                       use_spot=True)
    assert vague.less_demanding_than(pinned)
    assert not pinned.less_demanding_than(vague)
    other = Resources(accelerators='tpu-v6e-16')
    assert not other.less_demanding_than(pinned)
