"""Fleet-scale spot orchestration: storm simulation, herd-free
relaunch, recovery-event timestamps, launch retry deadline.

The tier-1 smoke runs N=20 real JobControllers through a zone-storm
fault plan in virtual time (wall time: a few seconds); the N=500
acceptance run lives in the slow tier and must reproduce the
committed BENCH_fleet JSON's invariants.
"""
import json
import os
import random

import pytest

from skypilot_tpu.robustness import faults
from skypilot_tpu.robustness import fleet_sim

SEED = 7
N_SMOKE = 20


@pytest.fixture(autouse=True)
def _no_leaked_plan():
    faults.clear()
    yield
    faults.clear()


def _run(n=N_SMOKE, seed=SEED, jitter=True, **kw):
    return fleet_sim.FleetSim(
        num_jobs=n, plan_spec=fleet_sim.default_storm_plan(),
        seed=seed, jitter=jitter, **kw).run()


# ---------------------------------------------------------------------------
# tier-1 smoke: the whole tentpole at N=20
# ---------------------------------------------------------------------------
def test_storm_hits_fleet_and_every_job_recovers():
    """A zone-wide probe-drop storm takes down a majority of a
    20-job fleet; every hit job walks the real grace -> recover ->
    relaunch path back to SUCCEEDED, with its lost work rolled back
    to the last checkpoint."""
    s = _run()
    assert s['final_statuses'] == {'SUCCEEDED': N_SMOKE}
    assert 0 < s['storm_hit_jobs'] <= N_SMOKE
    assert s['storm_hit_recovered'] == s['storm_hit_jobs']
    assert s['recovery_events'] >= s['storm_hit_jobs']
    assert s['recovery_events_open'] == 0
    # Storm scoping: every preemption happened in the storm zone.
    assert set(s['preemptions_by_zone']) == {'us-east5-b'}
    # Recovery latency comes from the recorded preempted_at /
    # recovered_at pairs: detection needs the 30s grace window, so
    # the floor is well above the poll interval.
    assert s['recovery_latency_s']['p50'] > 5.0
    assert s['recovery_latency_s']['max'] < 600.0
    # Checkpoint rollback lost work, but bounded by
    # preemptions x ckpt_every.
    assert 0.0 < s['steps_lost'] <= \
        s['preemptions_total'] * s['ckpt_every_s']
    assert s['tokens_lost'] > 0
    assert s['sim_cost_usd'] > 0


def test_same_seed_same_plan_reproduces_identical_summary():
    a, b = _run(), _run()
    assert json.dumps(a, sort_keys=True) == \
        json.dumps(b, sort_keys=True)
    c = _run(seed=SEED + 1)
    assert json.dumps(c, sort_keys=True) != \
        json.dumps(a, sort_keys=True)


def test_jittered_relaunch_bounds_the_herd():
    """The acceptance invariant at smoke scale: with the capacity
    crunch forcing every storm victim onto its retry timer, jittered
    backoff keeps peak relaunch concurrency strictly below the
    lockstep no-jitter herd."""
    jit = _run()
    herd = _run(jitter=False)
    assert herd['final_statuses'] == {'SUCCEEDED': N_SMOKE}
    assert 0 < jit['relaunch_concurrency']['max'] < \
        herd['relaunch_concurrency']['max']
    # The histogram's time-weighted levels are what the assertion
    # reads from — sanity-check its integrity: levels are positive
    # durations and the peak level appears in it.
    hist = herd['relaunch_concurrency']['histogram']
    assert all(v > 0 for v in hist.values())
    assert str(herd['relaunch_concurrency']['max']) in hist


def test_fleet_metrics_flow_through_observability_catalog():
    from skypilot_tpu.observability import catalog as obs_catalog
    zone_counter = obs_catalog.counter(
        'skypilot_jobs_preemptions_total').labels(zone='us-east5-b')
    before = zone_counter.value
    s = _run()
    assert zone_counter.value == before + s['preemptions_total']
    # The in-flight gauge went up and came back down.
    assert obs_catalog.gauge(
        'skypilot_jobs_relaunch_inflight').value == 0


# ---------------------------------------------------------------------------
# recovery-event timestamps (jobs/state.py satellite)
# ---------------------------------------------------------------------------
def test_recovery_event_round_trip(isolated_state):
    from skypilot_tpu.jobs import state
    job_id = state.submit_job('evt', {'run': 'true'}, 'failover', 0,
                              'tester')
    state.record_preemption(job_id, 'us-east5-b')
    events = state.get_recovery_events(job_id)
    assert len(events) == 1
    assert events[0]['zone'] == 'us-east5-b'
    assert events[0]['preempted_at'] is not None
    assert events[0]['recovered_at'] is None
    state.record_recovered(job_id)
    events = state.get_recovery_events(job_id)
    assert events[0]['recovered_at'] >= events[0]['preempted_at']
    # A second event closes independently of the first.
    state.record_preemption(job_id, 'us-west4-a')
    state.record_recovered(job_id)
    events = state.get_recovery_events(job_id)
    assert len(events) == 2
    assert all(e['recovered_at'] is not None for e in events)
    assert state.get_recovery_events() == events


# ---------------------------------------------------------------------------
# launch retry deadline (recovery_strategy satellite)
# ---------------------------------------------------------------------------
class _Task:
    resources = ()


def test_launch_retry_deadline_surfaces_failure(monkeypatch):
    """A permanently failing launch stops retrying once the overall
    deadline would be crossed, raising ResourcesUnavailableError
    (-> FAILED_NO_RESOURCE at the controller) instead of spinning."""
    from skypilot_tpu import exceptions
    from skypilot_tpu.jobs import recovery_strategy as rs
    monkeypatch.setattr(rs.time, 'sleep', lambda s: None)
    ex = rs.StrategyExecutor('deadline-cluster', _Task())
    ex.launch_deadline_s = 0.0     # first backoff already crosses it
    faults.install_plan({'rules': [
        {'point': 'jobs.launch', 'action': 'raise',
         'exc': 'skypilot_tpu.exceptions.ResourcesUnavailableError',
         'message': 'zone is gone'}]})
    with pytest.raises(exceptions.ResourcesUnavailableError,
                       match='retry deadline'):
        ex._launch_with_retries(first_launch=False, max_attempts=10)
    # Only ONE attempt was made: the deadline check runs before the
    # backoff sleep, not after another futile round.
    assert faults.stats()['jobs.launch']['hits'] == 1


def test_launch_deadline_configurable_via_job_recovery():
    from skypilot_tpu.jobs import recovery_strategy as rs

    class _Res:
        job_recovery = {'strategy': 'failover',
                        'launch_deadline_seconds': 123.0}

    class _TaskWithRecovery:
        resources = (_Res(),)

    ex = rs.StrategyExecutor('c', _TaskWithRecovery())
    assert ex.launch_deadline_s == 123.0
    default = rs.StrategyExecutor('c', _Task())
    assert default.launch_deadline_s == \
        rs._DEFAULT_LAUNCH_DEADLINE_SECONDS


def test_seeded_backoff_rng_reproduces_schedule(monkeypatch):
    """The fleet sim's determinism hook: an executor with a seeded
    rng produces the same jittered retry schedule every time."""
    from skypilot_tpu.jobs import recovery_strategy as rs

    def schedule():
        sleeps = []
        monkeypatch.setattr(rs.time, 'sleep', sleeps.append)
        ex = rs.StrategyExecutor('sched-cluster', _Task())
        ex.rng = random.Random('42:backoff:0')
        faults.install_plan({'rules': [
            {'point': 'jobs.launch', 'action': 'raise',
             'exc':
             'skypilot_tpu.exceptions.ResourcesUnavailableError',
             'times': 4}]})
        monkeypatch.setattr(
            rs.execution, 'launch', lambda task, **kw: (1, object()))
        ex._launch_with_retries(first_launch=False, max_attempts=10)
        return sleeps

    a, b = schedule(), schedule()
    assert a == b
    assert len(a) == 4 and len(set(a)) > 1   # jittered, seeded


# ---------------------------------------------------------------------------
# N=500 acceptance run (slow tier) — must match the committed JSON
# ---------------------------------------------------------------------------
@pytest.mark.slow
@pytest.mark.e2e
def test_fleet_bench_n500_matches_committed_json(tmp_path):
    """Re-runs the exact committed configuration and requires the
    byte-identical BENCH_fleet JSON plus all acceptance checks: 100%
    of storm-hit jobs recover, jitter peak strictly below the
    no-jitter herd peak, deterministic replay."""
    import subprocess
    import sys
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    out = tmp_path / 'fleet.json'
    subprocess.run(
        [sys.executable,
         os.path.join(repo, 'benchmarks', 'fleet_bench.py'),
         '--jobs', '500', '--seed', '2026',
         '--plan',
         os.path.join(repo, 'examples', 'fault_plans',
                      'zone_storm.json'),
         '--out', str(out)],
        check=True, capture_output=True, timeout=560)
    got = json.loads(out.read_text())
    assert all(got['checks'].values()), got['checks']
    committed_path = os.path.join(repo, 'BENCH_fleet_r06.json')
    committed = json.loads(open(committed_path).read())
    assert got == committed, (
        'N=500 storm run no longer reproduces BENCH_fleet_r06.json '
        '— regenerate it (benchmarks/fleet_bench.py --jobs 500 '
        '--seed 2026 --plan examples/fault_plans/zone_storm.json '
        '--out BENCH_fleet_r06.json) and justify the behavior '
        'change in the PR')
