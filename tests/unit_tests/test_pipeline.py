"""Pipeline parallelism (parallel/pipeline.py): the GPipe schedule
inside shard_map must be BIT-FAITHFUL to the sequential model —
same loss, same gradients — and train end-to-end on a stage x data
mesh.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import flax.linen as nn

from skypilot_tpu.models.gpt import GPT, GPTConfig
from skypilot_tpu.parallel import mesh as mesh_lib
from skypilot_tpu.parallel.pipeline import (PipelinedGPT,
                                            stack_layer_params,
                                            unstack_layer_params)
from skypilot_tpu.parallel.train import default_optimizer, next_token_loss

CFG = GPTConfig(vocab_size=256, block_size=64, num_layers=4,
                num_heads=4, embed_dim=64, dtype=jnp.float32,
                logits_dtype=jnp.float32)


@pytest.fixture(scope='module')
def setup():
    model = GPT(CFG)
    params = nn.meta.unbox(model.init(
        jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))['params'])
    mesh = mesh_lib.make_mesh(mesh_lib.MeshConfig(stage=4, data=2))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (16, 32), 0,
                                CFG.vocab_size, jnp.int32)
    return model, params, mesh, tokens


def test_stack_roundtrip(setup):
    _, params, _, _ = setup
    stacked, rest = stack_layer_params(params, 'h_', CFG.num_layers)
    for leaf in jax.tree.leaves(stacked):
        assert leaf.shape[0] == CFG.num_layers
    back = unstack_layer_params(stacked, rest, 'h_', CFG.num_layers)
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(back)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.slow
def test_pipeline_loss_matches_sequential(setup):
    model, params, mesh, tokens = setup
    pp = PipelinedGPT(model, mesh, num_microbatches=4)
    stacked, rest = pp.split_params(params)
    ref = next_token_loss(model.apply({'params': params}, tokens), tokens)
    got = pp.loss(stacked, rest, tokens)
    np.testing.assert_allclose(float(got), float(ref), rtol=2e-5)
    # Microbatch count must not change the answer (mean of equal-size
    # microbatch means == full-batch mean).
    got2 = PipelinedGPT(model, mesh, num_microbatches=8).loss(
        stacked, rest, tokens)
    np.testing.assert_allclose(float(got2), float(ref), rtol=2e-5)


@pytest.mark.slow
def test_pipeline_grads_match_sequential(setup):
    """jax.grad through the scan + ppermutes reproduces sequential
    gradients for BOTH the stage-sharded stacks and the shared
    embeddings/head (wte is tied: embed + head grads combine)."""
    model, params, mesh, tokens = setup
    pp = PipelinedGPT(model, mesh, num_microbatches=4)
    stacked, rest = pp.split_params(params)

    ref_grads = jax.grad(lambda p: next_token_loss(
        model.apply({'params': p}, tokens), tokens))(params)
    ref_stacked, ref_rest = stack_layer_params(ref_grads, 'h_',
                                               CFG.num_layers)
    g_stacked, g_rest = jax.grad(
        lambda s, r: pp.loss(s, r, tokens), argnums=(0, 1))(stacked, rest)
    for a, b in zip(jax.tree.leaves(ref_stacked),
                    jax.tree.leaves(g_stacked)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=2e-4, atol=2e-5)
    for a, b in zip(jax.tree.leaves(ref_rest), jax.tree.leaves(g_rest)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=2e-4, atol=2e-5)


@pytest.mark.slow
def test_pipeline_train_step_descends(setup):
    model, _, mesh, tokens = setup
    pp = PipelinedGPT(model, mesh, num_microbatches=4)
    tx = default_optimizer()
    state = pp.init(jax.random.PRNGKey(0), tokens, tx)
    # Stage shards actually land on the stage axis.
    stacked, _ = state.params
    leaf = jax.tree.leaves(stacked)[0]
    assert 'stage' in str(leaf.sharding.spec)
    step = pp.make_train_step(tx)
    state, loss0 = step(state, tokens)
    for _ in range(3):
        state, loss = step(state, tokens)
    assert float(loss) < float(loss0)
    assert int(state.step) == 4


@pytest.mark.slow
def test_uneven_layers_pad_to_stages(setup):
    """num_layers % stages != 0: the stack zero-pads and the padded
    slots are masked to identity — the loss still matches the
    sequential model (4 layers over 8 stages: half the slots pad)."""
    model, params, _, tokens = setup
    mesh = mesh_lib.make_mesh(mesh_lib.MeshConfig(stage=8))
    pp = PipelinedGPT(model, mesh, num_microbatches=4)
    assert pp.layers_per_stage == 1 and pp.padded_layers == 8
    stacked, rest = pp.split_params(params)
    assert jax.tree.leaves(stacked)[0].shape[0] == 8
    ref = next_token_loss(model.apply({'params': params}, tokens),
                          tokens)
    np.testing.assert_allclose(float(pp.loss(stacked, rest, tokens)),
                               float(ref), rtol=2e-5)
    # Round-trip drops the padding.
    back = pp.merge_params(stacked, rest)
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(back)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.slow
def test_train_lm_pipeline_cli(tmp_path):
    """The product surface: train_lm --pipeline-stages runs end-to-end
    on a stage x data mesh, checkpoints the (stacked, rest) state, and
    RESUMES from it."""
    import os
    import subprocess
    import sys
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    env = dict(os.environ)
    env['XLA_FLAGS'] = '--xla_force_host_platform_device_count=8'
    env['PYTHONPATH'] = f"{repo}:{env.get('PYTHONPATH', '')}"
    base = [sys.executable, '-m', 'skypilot_tpu.recipes.train_lm',
            '--cpu', '--model', 'tiny', '--pipeline-stages', '2',
            '--seq', '64', '--global-batch', '32', '--log-every', '2',
            '--ckpt-dir', str(tmp_path / 'ckpt'), '--ckpt-every', '2']
    out = subprocess.run(base + ['--steps', '2'], capture_output=True,
                         text=True, env=env, timeout=420)
    assert out.returncode == 0, out.stdout + out.stderr
    assert 'stage=2' in out.stdout
    out = subprocess.run(base + ['--steps', '4'], capture_output=True,
                         text=True, env=env, timeout=420)
    assert out.returncode == 0, out.stdout + out.stderr
    assert 'resumed from checkpoint step 2' in out.stdout


# Probe-based gate (re-triaged in the schedule-object PR): the probe
# compiles the failing ingredient itself — axis_index over a manual
# mesh axis with another axis left auto — so these tests re-enable
# automatically the moment the pinned jax/XLA partitions the
# PartitionId HLO, and until then the skip names the exact missing
# feature verbatim (on jax 0.4.37: "UNIMPLEMENTED: PartitionId
# instruction is not supported for SPMD partitioning").
_pm_reason = __import__(
    'skypilot_tpu.utils.jax_compat',
    fromlist=['x']).partial_manual_unsupported_reason()
_needs_partial_manual = pytest.mark.skipif(
    _pm_reason is not None,
    reason=f'partial-manual shard_map (tensor-within-stages) '
           f'unsupported by the pinned jax/XLA: {_pm_reason}')


@pytest.mark.slow
@_needs_partial_manual
def test_train_lm_pipeline_with_tensor_cli(tmp_path):
    """dp x pp x tp from the CLI: v2 shards tensor WITHIN stages."""
    import os
    import subprocess
    import sys
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    env = dict(os.environ)
    env['XLA_FLAGS'] = '--xla_force_host_platform_device_count=8'
    env['PYTHONPATH'] = f"{repo}:{env.get('PYTHONPATH', '')}"
    out = subprocess.run(
        [sys.executable, '-m', 'skypilot_tpu.recipes.train_lm',
         '--cpu', '--model', 'tiny', '--pipeline-stages', '2',
         '--tensor', '2', '--seq', '64', '--global-batch', '32',
         '--log-every', '2', '--steps', '2'],
        capture_output=True, text=True, env=env, timeout=420)
    assert out.returncode == 0, out.stdout + out.stderr
    assert 'stage=2, tensor=2' in out.stdout
    assert 'training done' in out.stdout


@pytest.mark.slow
def test_pipeline_llama_matches_sequential():
    """The Llama family pipelines too: loss AND grads match the
    sequential model (rope/GQA blocks, untied head, RMSNorm)."""
    from skypilot_tpu.models.llama import Llama, LlamaConfig
    from skypilot_tpu.parallel.pipeline import PipelinedLM
    cfg = LlamaConfig(vocab_size=256, max_seq_len=64, num_layers=4,
                      num_heads=4, num_kv_heads=2, embed_dim=64,
                      mlp_dim=128, dtype=jnp.float32,
                      logits_dtype=jnp.float32)
    model = Llama(cfg)
    params = nn.meta.unbox(model.init(
        jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))['params'])
    mesh = mesh_lib.make_mesh(mesh_lib.MeshConfig(stage=4, data=2))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (16, 32), 0,
                                cfg.vocab_size, jnp.int32)
    pp = PipelinedLM(model, mesh, num_microbatches=4)
    stacked, rest = pp.split_params(params)
    ref = next_token_loss(model.apply({'params': params}, tokens), tokens)
    got = pp.loss(stacked, rest, tokens)
    np.testing.assert_allclose(float(got), float(ref), rtol=2e-5)

    ref_grads = jax.grad(lambda p: next_token_loss(
        model.apply({'params': p}, tokens), tokens))(params)
    ref_stacked, ref_rest = stack_layer_params(ref_grads, 'layer_', 4)
    g_stacked, g_rest = jax.grad(
        lambda s, r: pp.loss(s, r, tokens), argnums=(0, 1))(stacked, rest)
    for a, b in zip(jax.tree.leaves(ref_stacked),
                    jax.tree.leaves(g_stacked)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=3e-4, atol=3e-5)
    for a, b in zip(jax.tree.leaves(ref_rest), jax.tree.leaves(g_rest)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=3e-4, atol=3e-5)


@pytest.mark.slow
@_needs_partial_manual
def test_pipeline_tp_within_stages():
    """dp x pp x tp: tensor parallelism composes INSIDE pipeline
    stages (v2) — block leaves shard over `tensor` on their logical
    inner dims while the stack dim shards over `stage`, and the loss
    still matches the sequential model when params enter with those
    placements (GSPMD handles the within-stage collectives under the
    shard_map's auto axes)."""
    from skypilot_tpu.models.llama import Llama, LlamaConfig
    from skypilot_tpu.parallel.pipeline import PipelinedLM
    cfg = LlamaConfig(vocab_size=256, max_seq_len=64, num_layers=4,
                      num_heads=4, num_kv_heads=2, embed_dim=64,
                      mlp_dim=128, dtype=jnp.float32,
                      logits_dtype=jnp.float32)
    model = Llama(cfg)
    params = nn.meta.unbox(model.init(
        jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))['params'])
    mesh = mesh_lib.make_mesh(
        mesh_lib.MeshConfig(stage=2, tensor=2, data=2))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (16, 32), 0,
                                cfg.vocab_size, jnp.int32)
    pp = PipelinedLM(model, mesh, num_microbatches=4)
    stacked, rest = pp.split_params(params)
    s_stacked, s_rest = pp.param_shardings(stacked, rest)
    # The derived shardings really put tensor on inner dims (an MLP
    # or attention kernel) and stage on the stack dim.
    specs = [s.spec for s in jax.tree.leaves(s_stacked)]
    assert all(spec[0] == 'stage' for spec in specs)
    assert any('tensor' in str(spec[1:]) for spec in specs), specs
    # Vocab tables stage-shard (not replicated per stage).
    assert 'stage' in str(s_rest['tok_embed'].spec)
    assert 'stage' in str(s_rest['lm_head'].spec)

    stacked = jax.device_put(stacked, s_stacked)
    rest = jax.device_put(rest, s_rest)
    ref = next_token_loss(model.apply({'params': params}, tokens),
                          tokens)
    np.testing.assert_allclose(float(pp.loss(stacked, rest, tokens)),
                               float(ref), rtol=3e-5)

    # And it trains: init born-sharded + a few descending steps.
    tx = default_optimizer()
    state = pp.init(jax.random.PRNGKey(0), tokens, tx)
    step = pp.make_train_step(tx)
    state, l0 = step(state, tokens)
    for _ in range(3):
        state, l1 = step(state, tokens)
    assert float(l1) < float(l0)


def test_pipeline_rejects_unsupported_family():
    from skypilot_tpu.parallel.pipeline import PipelinedLM

    class NotAModel:
        config = None

    mesh = mesh_lib.make_mesh(mesh_lib.MeshConfig(stage=2, data=4))
    with pytest.raises(ValueError, match='DeepSeek families'):
        PipelinedLM(NotAModel(), mesh)


@pytest.mark.slow
def test_pipeline_deepseek_matches_sequential():
    """DeepSeek (MLA) pipelines too: llama-shaped at the pipeline
    seam; loss matches the sequential model."""
    from skypilot_tpu.models.deepseek import Deepseek, DeepseekConfig
    from skypilot_tpu.parallel.pipeline import PipelinedLM
    import dataclasses
    cfg = dataclasses.replace(DeepseekConfig.tiny(),
                              dtype=jnp.float32,
                              logits_dtype=jnp.float32)
    model = Deepseek(cfg)
    params = nn.meta.unbox(model.init(
        jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))['params'])
    mesh = mesh_lib.make_mesh(mesh_lib.MeshConfig(stage=2, data=4))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (16, 32), 0,
                                cfg.vocab_size, jnp.int32)
    pp = PipelinedLM(model, mesh, num_microbatches=4)
    stacked, rest = pp.split_params(params)
    ref = next_token_loss(model.apply({'params': params}, tokens),
                          tokens)
    np.testing.assert_allclose(float(pp.loss(stacked, rest, tokens)),
                               float(ref), rtol=3e-5)
    # Gradients flow end to end: one step descends.
    tx = default_optimizer()
    state = pp.init(jax.random.PRNGKey(0), tokens, tx)
    step = pp.make_train_step(tx)
    state, l0 = step(state, tokens)
    for _ in range(3):
        state, l1 = step(state, tokens)
    assert float(l1) < float(l0)


@pytest.mark.slow
def test_tick_remat_preserves_loss_and_grads(setup):
    """Per-tick rematerialization (the pipeline's memory profile)
    changes nothing numerically."""
    from skypilot_tpu.parallel.pipeline import PipelinedLM
    model, params, mesh, tokens = setup
    on = PipelinedLM(model, mesh, num_microbatches=4, remat_ticks=True)
    off = PipelinedLM(model, mesh, num_microbatches=4,
                      remat_ticks=False)
    stacked, rest = on.split_params(params)
    np.testing.assert_allclose(float(on.loss(stacked, rest, tokens)),
                               float(off.loss(stacked, rest, tokens)),
                               rtol=1e-6)
    g_on = jax.grad(lambda s: on.loss(s, rest, tokens))(stacked)
    g_off = jax.grad(lambda s: off.loss(s, rest, tokens))(stacked)
    for a, b in zip(jax.tree.leaves(g_on), jax.tree.leaves(g_off)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-7)


@pytest.mark.slow
def test_pipeline_mixtral_matches_per_microbatch_reference():
    """Mixtral pipelines with exact equality to the sequential model
    evaluated per microbatch (the router aux is a product of
    batch-means, so the faithful reference is the mean of per-
    microbatch losses; with M=1 this IS the full-batch loss)."""
    from skypilot_tpu.models.mixtral import (Mixtral, MixtralConfig,
                                             moe_next_token_loss)
    from skypilot_tpu.parallel.pipeline import PipelinedLM
    cfg = MixtralConfig(vocab_size=256, max_seq_len=64, num_layers=4,
                        num_heads=4, num_kv_heads=2, embed_dim=64,
                        mlp_dim=96, num_experts=4, experts_per_token=2,
                        dtype=jnp.float32, logits_dtype=jnp.float32)
    model = Mixtral(cfg)
    params = nn.meta.unbox(model.init(
        jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))['params'])
    mesh = mesh_lib.make_mesh(mesh_lib.MeshConfig(stage=4, data=2))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 32), 0,
                                cfg.vocab_size, jnp.int32)

    # M=1: pipeline loss == sequential full-batch loss EXACTLY.
    pp1 = PipelinedLM(model, mesh, num_microbatches=1)
    stacked, rest = pp1.split_params(params)
    ref_full = moe_next_token_loss(
        model.apply({'params': params}, tokens), tokens)
    np.testing.assert_allclose(float(pp1.loss(stacked, rest, tokens)),
                               float(ref_full), rtol=3e-4)

    # M=4: pipeline == mean of per-microbatch sequential losses.
    pp4 = PipelinedLM(model, mesh, num_microbatches=4)
    mbs = tokens.reshape(4, 2, 32)
    ref_mb = np.mean([float(moe_next_token_loss(
        model.apply({'params': params}, mb), mb)) for mb in mbs])
    np.testing.assert_allclose(float(pp4.loss(stacked, rest, tokens)),
                               ref_mb, rtol=3e-4)

    # Gradients flow (router included): one step descends.
    from skypilot_tpu.parallel.train import default_optimizer
    tx = default_optimizer()
    state = pp4.init(jax.random.PRNGKey(0), tokens, tx)
    step = pp4.make_train_step(tx)
    state, l0 = step(state, tokens)
    for _ in range(3):
        state, l1 = step(state, tokens)
    assert float(l1) < float(l0)
