"""Speculative decoding inside the continuous-batching engine.

The flagship serving path (paged KV + slot engine) now rides
prompt-lookup verify chunks: greedy outputs must be EXACTLY the
non-speculative engine's outputs (which are themselves pinned to the
full-forward greedy rollout by test_generate.py), sampled slots stay
valid, and the vLLM-style page-pressure preemption keeps working with
chunk lookahead allocation.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import flax.linen as nn

from skypilot_tpu.models.batching import ContinuousBatchingEngine


def _build(family, **cfg_kw):
    if family == 'llama':
        from skypilot_tpu.models.llama import Llama, LlamaConfig
        cfg = LlamaConfig.tiny(dtype=jnp.float32, **cfg_kw)
        model = Llama(cfg)
    elif family == 'gpt':
        from skypilot_tpu.models.gpt import GPT, GPTConfig
        cfg = GPTConfig.tiny(dtype=jnp.float32, **cfg_kw)
        model = GPT(cfg)
    else:
        from skypilot_tpu.models.deepseek import Deepseek, DeepseekConfig
        cfg = DeepseekConfig.tiny(dtype=jnp.float32, **cfg_kw)
        model = Deepseek(cfg)
    params = nn.meta.unbox(model.init(
        jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))['params'])
    return model, params


_PROMPTS = [
    [5, 9, 2, 5, 9, 2, 5, 9],       # repetitive: multi-token accepts
    [3, 3, 3, 3],
    [17, 41, 7, 99, 23, 5],          # random-ish: rejects
]


def _run_engine(model, params, *, spec_k, paged=None, temps=None,
                max_new=16):
    engine = ContinuousBatchingEngine(
        model, params, num_slots=4, max_total_len=48,
        paged=paged, speculative_k=spec_k)
    try:
        temps = temps or [0.0] * len(_PROMPTS)
        futs = [engine.submit(p, max_new_tokens=max_new, temperature=t)
                for p, t in zip(_PROMPTS, temps)]
        return [f.result(timeout=300) for f in futs]
    finally:
        engine.stop()


@pytest.mark.slow
@pytest.mark.parametrize('family,paged', [
    ('llama', None),      # paged auto-on (the flagship path)
    ('llama', False),     # dense per-slot cache
    ('gpt', None),
    ('deepseek', None),   # MLA latent cache (dense-only family)
])
def test_spec_engine_matches_plain_greedy(family, paged):
    model, params = _build(family)
    want = _run_engine(model, params, spec_k=0, paged=paged)
    got = _run_engine(model, params, spec_k=4, paged=paged)
    assert got == want
    for prompt, row in zip(_PROMPTS, got):
        assert row[:len(prompt)] == prompt
        assert len(row) == len(prompt) + 16


@pytest.mark.slow
def test_spec_engine_sampled_slots():
    """Sampled slots ride the same verify chunks: outputs are valid
    (right lengths, prompt preserved, tokens in-vocab) and greedy
    slots in the same batch stay exactly greedy."""
    model, params = _build('llama')
    temps = [0.0, 1.0, 0.7]
    got = _run_engine(model, params, spec_k=4, temps=temps)
    greedy = _run_engine(model, params, spec_k=0,
                         temps=[0.0] * 3)
    for prompt, row in zip(_PROMPTS, got):
        assert row[:len(prompt)] == prompt
        assert len(row) == len(prompt) + 16
        assert all(0 <= t < model.config.vocab_size for t in row)
    # The greedy slot is unaffected by its sampled neighbors.
    assert got[0] == greedy[0]


@pytest.mark.slow
def test_spec_engine_eos_truncation():
    """EOS committed mid-chunk finishes the request exactly where the
    one-token engine would."""
    model, params = _build('llama')
    base = _run_engine(model, params, spec_k=0)[0]
    eos = base[len(_PROMPTS[0]) + 3]   # a token the model WILL emit
    for spec_k in (0, 4):
        engine = ContinuousBatchingEngine(
            model, params, num_slots=2, max_total_len=48,
            eos_id=eos, speculative_k=spec_k)
        try:
            out = engine.submit(_PROMPTS[0],
                                max_new_tokens=16).result(timeout=300)
        finally:
            engine.stop()
        if spec_k == 0:
            want = out
        else:
            assert out == want
    assert want[-1] == eos or len(want) == len(_PROMPTS[0]) + 16


@pytest.mark.slow
def test_spec_engine_page_pressure_preemption():
    """A pool too small for all slots at once still serves every
    request with speculation on (chunk-lookahead allocation preempts
    instead of failing)."""
    # 15 usable pages x 4 tokens = 60 tokens live; 3 requests needing
    # ~28 tokens each can't all fit -> preemption must kick in.
    model, params = _build('llama', kv_page_size=4, kv_total_pages=16)
    engine = ContinuousBatchingEngine(
        model, params, num_slots=3, max_total_len=28,
        speculative_k=4)
    assert engine.paged
    try:
        futs = [engine.submit(p, max_new_tokens=20) for p in _PROMPTS]
        rows = [f.result(timeout=300) for f in futs]
    finally:
        engine.stop()
    for prompt, row in zip(_PROMPTS, rows):
        assert row[:len(prompt)] == prompt
        assert len(row) == len(prompt) + 20


def test_filter_logits_topk_topp():
    from skypilot_tpu.models.generate import filter_logits
    logits = jnp.asarray([[1.0, 3.0, 2.0, -1.0]])
    # top_k=2 keeps exactly the 2 largest.
    out = filter_logits(logits, jnp.asarray([2]), jnp.asarray([1.0]))
    assert np.isfinite(np.asarray(out[0, [1, 2]])).all()
    assert np.isneginf(np.asarray(out[0, [0, 3]])).all()
    # top_k=0 / top_p=1: untouched.
    out = filter_logits(logits, jnp.asarray([0]), jnp.asarray([1.0]))
    np.testing.assert_array_equal(np.asarray(out), np.asarray(logits))
    # top_p tiny: only the argmax survives.
    out = filter_logits(logits, jnp.asarray([0]), jnp.asarray([1e-6]))
    assert np.isfinite(np.asarray(out[0, 1]))
    assert np.isneginf(np.asarray(out)[0, [0, 2, 3]]).all()
    # Per-row independence.
    two = jnp.tile(logits, (2, 1))
    out = filter_logits(two, jnp.asarray([1, 0]),
                        jnp.asarray([1.0, 1.0]))
    assert np.isneginf(np.asarray(out)[0, [0, 2, 3]]).all()
    assert np.isfinite(np.asarray(out)[1]).all()


def test_sample_tokens_temperature_before_top_p():
    """The HF/vLLM/OpenAI order: logits are temperature-scaled FIRST,
    then the nucleus is computed — low temperature sharpens the
    distribution, narrowing the kept set. (The reverse order samples
    from a broader nucleus than requested.)"""
    from skypilot_tpu.models.generate import sample_tokens
    # probs ~ [0.5, 0.3, 0.15, 0.05]; at temperature 0.3 the scaled
    # probs put > 0.6 mass on token 0 alone, so top_p=0.6 keeps ONLY
    # token 0. Unscaled, the nucleus would keep {0, 1}.
    logits = jnp.log(jnp.asarray([[0.5, 0.3, 0.15, 0.05]]))
    temps = jnp.asarray([0.3])
    out = [int(sample_tokens(jax.random.PRNGKey(s), logits, temps,
                             jnp.asarray([0]), jnp.asarray([0.6]))[0])
           for s in range(64)]
    assert set(out) == {0}, set(out)


def test_sample_tokens_default_matches_plain_categorical():
    """top_k=0/top_p=1 consumes the identical rng stream as plain
    categorical — the no-filter path is bit-compatible."""
    from skypilot_tpu.models.generate import sample_tokens
    rng = jax.random.PRNGKey(7)
    logits = jax.random.normal(jax.random.PRNGKey(1), (4, 32))
    temps = jnp.asarray([0.7, 1.3, 0.0, 1.0])
    want = jax.random.categorical(rng, logits / temps[:, None]
                                  .clip(1e-6), axis=-1)
    got = sample_tokens(rng, logits, temps, jnp.zeros((4,), jnp.int32),
                        jnp.ones((4,)))
    # temp==0 row is greedy; others match categorical exactly.
    np.testing.assert_array_equal(np.asarray(got[2]),
                                  np.argmax(np.asarray(logits[2])))
    np.testing.assert_array_equal(np.asarray(got)[[0, 1, 3]],
                                  np.asarray(want)[[0, 1, 3]])


@pytest.mark.slow
def test_engine_topk1_equals_greedy():
    """top_k=1 with temperature > 0 must reproduce the greedy rollout
    (only the argmax survives the filter) — on the plain AND the
    speculative engine."""
    model, params = _build('llama')
    for spec_k in (0, 3):
        eng = ContinuousBatchingEngine(model, params, num_slots=2,
                                       max_total_len=64,
                                       speculative_k=spec_k)
        try:
            for p in ([5, 9, 2, 17], [30, 31, 32]):
                greedy = eng.submit(p, max_new_tokens=8,
                                    temperature=0.0).result(timeout=180)
                k1 = eng.submit(p, max_new_tokens=8, temperature=0.9,
                                top_k=1).result(timeout=180)
                assert greedy == k1
        finally:
            eng.stop()


@pytest.mark.slow
def test_per_request_stop_tokens():
    """A request ends on any of ITS stop tokens (stop included in the
    output), independent of other slots — plain and speculative."""
    model, params = _build('llama')
    for spec_k in (0, 3):
        eng = ContinuousBatchingEngine(model, params, num_slots=2,
                                       max_total_len=64,
                                       speculative_k=spec_k)
        try:
            p = [5, 9, 2, 17]
            full = eng.submit(p, max_new_tokens=10).result(timeout=180)
            generated = full[len(p):]
            assert len(generated) == 10
            stop = generated[3]  # stop at the 4th generated token
            stopped = eng.submit(p, max_new_tokens=10,
                                 stop_token_ids=[stop]).result(
                timeout=180)
            idx = generated.index(stop)
            assert stopped == p + generated[:idx + 1]
            # A concurrent request WITHOUT the stop id runs to limit.
            again = eng.submit(p, max_new_tokens=10).result(timeout=180)
            assert again == full
        finally:
            eng.stop()


@pytest.mark.slow
def test_on_token_streams_commits_in_order():
    """The engine's on_token callback (the SSE streaming feed)
    delivers exactly the generated tokens, in commit order, BEFORE the
    future resolves — for both the plain and speculative decode loops
    (verify chunks commit 1..K+1 tokens per call)."""
    import threading
    model, params = _build('llama')
    for spec_k in (0, 3):
        eng = ContinuousBatchingEngine(model, params, num_slots=2,
                                       max_total_len=64,
                                       speculative_k=spec_k)
        try:
            p = [5, 9, 2, 5, 9, 2, 5, 9]
            streamed = []
            resolved = threading.Event()

            def on_token(tok, streamed=streamed, resolved=resolved):
                # Every token must arrive before the future resolves.
                assert not resolved.is_set()
                streamed.append(tok)

            fut = eng.submit(p, max_new_tokens=12, on_token=on_token)
            full = fut.result(timeout=180)
            resolved.set()
            assert streamed == full[len(p):]
            # The callback is per-request: a plain submit streams none.
            assert eng.submit(p, max_new_tokens=4).result(
                timeout=180) == full[:len(p) + 4]
        finally:
            eng.stop()


def test_on_token_exception_does_not_kill_request():
    """A broken stream consumer (client hung up) must not fail the
    request or the shared scheduler loop."""
    model, params = _build('llama')
    eng = ContinuousBatchingEngine(model, params, num_slots=2,
                                   max_total_len=64)
    try:
        calls = []

        def bad(tok):
            calls.append(tok)
            raise RuntimeError('client gone')

        p = [5, 9, 2]
        full = eng.submit(p, max_new_tokens=6, on_token=bad).result(
            timeout=180)
        assert len(full) == len(p) + 6      # request completed
        assert len(calls) == 1              # callback dropped after 1
        # The engine still serves subsequent requests.
        again = eng.submit(p, max_new_tokens=2).result(timeout=180)
        assert again == full[:len(p) + 2]
    finally:
        eng.stop()


@pytest.mark.slow
@pytest.mark.parametrize('paged', [None, False])
def test_chunk_decode_matches_single_step(paged):
    """decode_chunk=N (N single-token steps per jitted dispatch — the
    serving dispatch-overhead amortizer) is output-IDENTICAL to the
    step-by-step engine: greedy across concurrent ragged requests, and
    the sampled first request (same jax.random.split chain)."""
    model, params = _build('llama')
    want = _run_engine(model, params, spec_k=0, paged=paged)
    for chunk in (2, 4):
        eng = ContinuousBatchingEngine(
            model, params, num_slots=4, max_total_len=48,
            paged=paged, decode_chunk=chunk)
        assert eng.decode_chunk == chunk
        try:
            futs = [eng.submit(p, max_new_tokens=16)
                    for p in _PROMPTS]
            got = [f.result(timeout=300) for f in futs]
            # Dispatch amortization is observable: far fewer decode
            # calls than committed tokens.
            assert eng.tokens_committed >= \
                chunk * (eng.decode_calls - len(_PROMPTS) - 1)
        finally:
            eng.stop()
        assert got == want

    # Sampled: the rng split chain matches step-by-step for the
    # first request (later requests may see a shifted stream when a
    # final partial chunk consumed extra splits).
    def first_sampled(chunk):
        eng = ContinuousBatchingEngine(
            model, params, num_slots=2, max_total_len=48,
            paged=paged, decode_chunk=chunk)
        try:
            return eng.submit(_PROMPTS[0], max_new_tokens=16,
                              temperature=0.9).result(timeout=300)
        finally:
            eng.stop()

    assert first_sampled(4) == first_sampled(1)


def test_chunk_decode_streams_and_stops():
    """Chunked decode preserves the streaming and stop-token
    contracts: on_token fires per committed token in order; a stop
    token mid-chunk truncates exactly where single-step would."""
    model, params = _build('llama')
    single = ContinuousBatchingEngine(model, params, num_slots=2,
                                      max_total_len=48)
    try:
        p = [5, 9, 2, 17]
        base = single.submit(p, max_new_tokens=12).result(timeout=300)
    finally:
        single.stop()
    stop = base[len(p) + 4]  # stop mid-way (and mid-chunk for N=3)

    eng = ContinuousBatchingEngine(model, params, num_slots=2,
                                   max_total_len=48, decode_chunk=3)
    try:
        streamed = []
        out = eng.submit(p, max_new_tokens=12,
                         stop_token_ids=[stop],
                         on_token=streamed.append).result(timeout=300)
        idx = base[len(p):].index(stop)
        assert out == base[:len(p) + idx + 1]
        assert streamed == out[len(p):]
    finally:
        eng.stop()


def test_chunk_decode_rejects_speculation():
    model, params = _build('llama')
    with pytest.raises(AssertionError, match='decode_chunk'):
        ContinuousBatchingEngine(model, params, max_total_len=48,
                                 speculative_k=2, decode_chunk=4)


@pytest.mark.slow
def test_cancel_frees_slots_mid_generation():
    """Abandoned streams (client disconnect) cancel: the active slot
    resolves NOW with its partial output, a queued request resolves
    unrun, and the engine keeps serving."""
    model, params = _build('llama')
    eng = ContinuousBatchingEngine(model, params, num_slots=1,
                                   max_total_len=256)
    try:
        import threading
        first_token = threading.Event()
        p = [5, 9, 2, 17]
        # A LONG generation so the cancel deterministically lands
        # mid-run (decode is ~ms/token once compiled).
        fut = eng.submit(p, max_new_tokens=240,
                         on_token=lambda t: first_token.set())
        queued = eng.submit(p, max_new_tokens=240)  # waits for a slot
        assert first_token.wait(timeout=120)
        eng.cancel([fut, queued])
        out = fut.result(timeout=60)
        assert out[:len(p)] == p
        assert len(p) < len(out) < len(p) + 240  # partial
        assert queued.result(timeout=60) == p    # never ran
        # The slot is free again: a fresh request completes fully.
        full = eng.submit(p, max_new_tokens=6).result(timeout=120)
        assert len(full) == len(p) + 6
    finally:
        eng.stop()


@pytest.mark.slow
def test_cancel_sweeps_request_still_in_queue():
    """A cancelled request that the scheduler has NOT yet drained out
    of _queue must resolve unrun (it used to be admitted later and
    decoded to completion). Deterministic: stop the scheduler thread
    so the request provably sits in _queue, then apply cancellations
    directly."""
    model, params = _build('llama')
    eng = ContinuousBatchingEngine(model, params, num_slots=1,
                                   max_total_len=48)
    eng.stop()  # freeze the scheduler: nothing drains _queue
    prompt = [5, 9, 2]
    fut = eng.submit(prompt, max_new_tokens=8)
    assert not eng._queue.empty()  # still queued, never admitted
    eng.cancel([fut])
    eng._apply_cancellations()
    assert fut.result(timeout=5) == prompt  # resolved unrun
    assert eng._queue.empty()
    assert not eng._ready
