"""Tensor × pipeline serving (parallel/serving.py stage split +
models/batching.py staged engine): the checklist for PR 19.

  - layer split: contiguous [lo, hi) ranges, remainder front-loaded,
    stage 0 owns the embedding and the last stage the head;
  - page math: a per-chip byte budget buys ~stages x the pages on
    top of the kv-heads shard split (each stage stores only its own
    layers' pages), widest-stage bound when layers don't divide;
  - bubble: the closed-form prefill fill/drain fraction
    (S-1)/(M+S-1) from the inference schedule;
  - zero resharding PER STAGE: every stage's compiled decode
    dispatch contains NO all-gather/all-to-all over a pool-shaped
    operand, and the guard still detects forced violations on a
    stage submesh (non-vacuous);
  - bit identity: greedy outputs of a (stage=2, tensor=2) engine
    equal single-device across paged bf16, int8 KV, chunked
    prefill, speculative decode, and an active LoRA adapter;
  - handoff: a chain exported from a staged pool imports into a
    single-device pool (and back) with byte-identical re-export —
    the wire format never sees the stage split;
  - guardrails: the staged engine rejects configurations it cannot
    serve bit-identically (dense cache, decode chunks, ragged slot
    groups, int8 weights).
"""
import dataclasses
import os

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from skypilot_tpu.inference import kv_transfer, quant
from skypilot_tpu.models.batching import ContinuousBatchingEngine
from skypilot_tpu.models.llama import Llama, LlamaConfig
from skypilot_tpu.parallel import mesh as mesh_lib
from skypilot_tpu.parallel.pipeline_schedule import \
    make_inference_schedule
from skypilot_tpu.parallel.serving import (
    build_staged_serving, pool_collective_lines, stage_layer_ranges)


@pytest.fixture(scope='module')
def setup():
    cfg = LlamaConfig.tiny(dtype=jnp.float32, kv_page_size=8,
                           kv_total_pages=40)
    model = Llama(cfg)
    params = nn.meta.unbox(model.init(
        jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))['params'])
    mesh = mesh_lib.make_mesh(
        mesh_lib.MeshConfig(stage=2, tensor=2),
        devices=jax.devices()[:4])
    return model, params, mesh


# -- layer split + schedule units -------------------------------------------
def test_stage_layer_ranges():
    assert stage_layer_ranges(4, 2) == [(0, 2), (2, 4)]
    assert stage_layer_ranges(2, 2) == [(0, 1), (1, 2)]
    # Remainder front-loads: earlier stages take the extra layer.
    assert stage_layer_ranges(7, 3) == [(0, 3), (3, 5), (5, 7)]
    assert stage_layer_ranges(5, 1) == [(0, 5)]
    with pytest.raises(ValueError):
        stage_layer_ranges(2, 3)        # more stages than layers
    with pytest.raises(ValueError):
        stage_layer_ranges(2, 0)


def test_prefill_bubble_closed_form():
    # (S-1)/(M+S-1): one microbatch through 2 stages idles each
    # stage half the time; a deep stream amortizes the fill/drain.
    assert make_inference_schedule(2, 1).bubble_fraction == 0.5
    sched = make_inference_schedule(2, 3)
    assert sched.bubble_fraction == pytest.approx(0.25)
    assert make_inference_schedule(1, 4).bubble_fraction == 0.0
    deep = make_inference_schedule(4, 61)
    assert deep.bubble_fraction == pytest.approx(3 / 64)


def test_staged_page_math():
    """Splitting layers over stages divides the per-chip page cost —
    the same budget buys ~stages x the pages, multiplying with the
    kv-heads shard split."""
    cfg = LlamaConfig.tiny(kv_page_size=8, kv_total_pages=40)
    assert cfg.num_layers == 2 and cfg.num_kv_heads == 2
    full = quant.kv_page_bytes(cfg, 'bf16', 1)
    assert quant.kv_page_bytes(cfg, 'bf16', 1, stages=2) == full // 2
    # Compose with the tensor shard: S=2 x T=2 -> quarter the bytes.
    assert quant.kv_page_bytes(cfg, 'bf16', 2, stages=2) == full // 4
    budget = 64 * full
    assert quant.pool_pages_for_bytes(cfg, 'bf16', budget) == 64
    assert quant.pool_pages_for_bytes(cfg, 'bf16', budget,
                                      stages=2) == 128
    assert quant.pool_pages_for_bytes(cfg, 'bf16', budget, 2,
                                      stages=2) == 256
    # int8 scale rows replicate across the head shard but DO split
    # by stage (each stage stores scales for its own layers only).
    i8_full = quant.kv_page_bytes(cfg, 'int8', 1)
    assert quant.kv_page_bytes(cfg, 'int8', 1, stages=2) == \
        i8_full // 2
    # Widest stage bounds the cost: 3 layers over 2 stages price 2.
    cfg3 = dataclasses.replace(cfg, num_layers=3)
    assert quant.kv_page_bytes(cfg3, 'bf16', 1, stages=2) == full
    with pytest.raises(ValueError):
        quant.kv_page_bytes(cfg, 'bf16', 1, stages=3)


# -- param split + placement ------------------------------------------------
def test_build_staged_serving_partition(setup):
    model, params, mesh = setup
    stage_models, stage_params, submeshes, ranges = \
        build_staged_serving(model, params, mesh)
    assert ranges == [(0, 1), (1, 2)]
    assert sorted(stage_params[0]) == ['layer_0', 'tok_embed']
    assert sorted(stage_params[1]) == ['final_norm', 'layer_1',
                                       'lm_head']
    # Disjoint top-level partition whose union is the full tree.
    assert set(stage_params[0]) | set(stage_params[1]) == set(params)
    # Each stage's devices are one row of the (stage, tensor) grid,
    # and TP sharding applies within the row.
    grid = np.asarray(mesh.devices).reshape(2, 2)
    for s, sub in enumerate(submeshes):
        assert list(np.asarray(sub.devices).ravel()) == list(grid[s])
    wq = stage_params[0]['layer_0']['attn']['wq']['kernel']
    assert 'tensor' in str(wq.sharding.spec)
    head = jax.tree.leaves(stage_params[1]['lm_head'])[0]
    assert 'tensor' in str(head.sharding.spec)


def test_staged_rejects_unsupported(setup):
    model, params, mesh = setup
    for kw in ({'paged': False}, {'decode_chunk': 4},
               {'num_slots': 3}):
        base = {'num_slots': 2, 'max_total_len': 48, 'mesh': mesh}
        base.update(kw)
        with pytest.raises(ValueError):
            ContinuousBatchingEngine(model, params, **base)
    qparams = quant.quantize_params(params)
    with pytest.raises(ValueError):
        ContinuousBatchingEngine(quant.QuantizedModel(model), qparams,
                                 num_slots=2, max_total_len=48,
                                 mesh=mesh)


# -- the per-stage zero-resharding guard ------------------------------------
def test_staged_decode_has_no_pool_resharding(setup):
    """Compile each stage's decode dispatch and fail on any
    pool-shaped all-gather/all-to-all: the donated per-stage cache's
    explicit out_shardings keep EVERY stage's pool in place step
    over step (PR 15's guard, now per stage)."""
    model, params, mesh = setup
    eng = ContinuousBatchingEngine(model, params, num_slots=2,
                                   max_total_len=48, mesh=mesh)
    try:
        assert eng.stages == 2 and eng.kv_shard_ways == 2
        cfg = model.config
        z = jnp.zeros((2, 1), jnp.int32)
        pt = jnp.zeros((2, eng.pages_per_seq), jnp.int32)
        hid = jnp.zeros((2, 1, cfg.embed_dim), cfg.dtype)
        for s in range(eng.stages):
            fn = eng._stage_decode_fn(s)  # pylint: disable=protected-access
            if s == eng.stages - 1:
                lowered = fn.lower(
                    eng.params[s], eng.cache[s], hid, z,
                    jnp.zeros((2,), jnp.float32),
                    jnp.zeros((2,), jnp.int32),
                    jnp.ones((2,), jnp.float32),
                    jax.random.PRNGKey(0), pt)
            else:
                lowered = fn.lower(eng.params[s], eng.cache[s], z, z,
                                   pt)
            compiled = lowered.compile()
            hits = pool_collective_lines(
                compiled, eng.cache[s], eng._stage_submeshes[s])  # pylint: disable=protected-access
            assert hits == [], (s, hits)
    finally:
        eng.stop()


def test_staged_guard_detects_forced_reshard(setup):
    """Non-vacuous: forcing a stage's pool off its sharding on the
    stage SUBMESH (replicate = all-gather) is detected by the same
    guard the green path runs."""
    model, params, mesh = setup
    eng = ContinuousBatchingEngine(model, params, num_slots=2,
                                   max_total_len=48, mesh=mesh)
    try:
        s = 0
        sub = eng._stage_submeshes[s]  # pylint: disable=protected-access
        good_sh = eng._cache_shardings[s]  # pylint: disable=protected-access

        def bump(c):
            return jax.tree.map(lambda x: x + 1, c)

        bad_sh = jax.tree.map(
            lambda _: NamedSharding(sub, P()), good_sh)
        bad = jax.jit(bump, out_shardings=bad_sh).lower(
            eng.cache[s]).compile()
        assert pool_collective_lines(bad, eng.cache[s], sub)
        good = jax.jit(bump, out_shardings=good_sh).lower(
            eng.cache[s]).compile()
        assert pool_collective_lines(good, eng.cache[s], sub) == []
    finally:
        eng.stop()


def test_staged_pool_split_accounting(setup):
    """The per-chip KV figure is the widest stage's single shard —
    S=2 stages x 2-way heads store a quarter of the single-device
    pool per chip — and /stats' per-stage view shows each stage
    holding the full page count for only its own layers."""
    model, params, mesh = setup
    eng = ContinuousBatchingEngine(model, params, num_slots=2,
                                   max_total_len=48, mesh=mesh)
    ref = ContinuousBatchingEngine(model, params, num_slots=2,
                                   max_total_len=48)
    try:
        assert eng.kv_cache_bytes_per_device() * 4 == \
            ref.kv_cache_bytes_per_device()
        stats = eng.stage_pool_stats()
        assert [st['layers'] for st in stats] == [[0, 1], [1, 2]]
        assert all(st['pages'] == eng.total_pages for st in stats)
        assert ref.stage_pool_stats() == []
        # Roofline inputs follow the split: per-stage weights and a
        # per-stage layer count shrink bytes_per_token_model's
        # amortized terms.
        bpt = eng.attention_bytes_per_token()
        assert bpt['total_bytes_per_token'] > 0
        assert bpt['weight_bytes_amortized'] < \
            ref.attention_bytes_per_token()['weight_bytes_amortized']
    finally:
        eng.stop()
        ref.stop()


# -- bit identity single-device vs staged -----------------------------------
PROMPTS = ([5, 9, 2, 17], [30, 31, 32], [5, 9, 2, 17, 40])


def _run_engine(model, params, prompts, *, mesh=None, n=8, slots=2,
                **kw):
    eng = ContinuousBatchingEngine(model, params, num_slots=slots,
                                   max_total_len=48, mesh=mesh, **kw)
    try:
        assert (eng.stages == 2) == (mesh is not None)
        futs = [eng.submit(list(p), max_new_tokens=n) for p in prompts]
        return [f.result(timeout=300) for f in futs]
    finally:
        eng.stop()


@pytest.mark.slow
@pytest.mark.parametrize('variant', ['bf16', 'int8kv', 'chunk_prefill',
                                     'spec'])
def test_staged_engine_bit_identical(setup, variant):
    """Greedy outputs of the (stage=2, tensor=2) engine equal
    single-device, across KV storage formats and decode modes — the
    group decode ring and the pipelined prefill chain change only
    WHEN work runs, never what it computes."""
    model, params, mesh = setup
    kw = {}
    prompts = PROMPTS
    if variant == 'int8kv':
        model = Llama(dataclasses.replace(model.config,
                                          kv_dtype='int8'))
    elif variant == 'chunk_prefill':
        kw['prefill_chunk'] = 4
        prompts = PROMPTS + ([5, 9, 2, 17, 40, 41, 42, 43, 44],)
    elif variant == 'spec':
        kw['speculative_k'] = 3
        prompts = ([5, 9, 2, 5, 9, 2, 5, 9], [30, 31, 30, 31, 30])
    ref = _run_engine(model, params, prompts, slots=4, **kw)
    got = _run_engine(model, params, prompts, mesh=mesh, slots=4,
                      **kw)
    assert got == ref


@pytest.mark.slow
def test_staged_lora_bit_identical(setup, tmp_path):
    """An active LoRA adapter rides the stage chain: the uncommitted
    host-backed stacks feed every stage's submesh dispatch, and
    outputs stay bit-identical to single-device LoRA serving."""
    from skypilot_tpu.inference.adapters import AdapterRegistry
    from skypilot_tpu.models import lora as lora_lib
    model, params, mesh = setup
    spec = lora_lib.LoraSpec(rank=4, alpha=8.0)
    lp = lora_lib.random_adapter_params(0, model.config, spec)
    lora_lib.save_adapter(str(tmp_path / 'ad0'), lp, spec,
                          base_model='llama-tiny')

    def run(eng_mesh):
        reg = AdapterRegistry(str(tmp_path), model, max_adapters=2,
                              mesh=None)
        eng = ContinuousBatchingEngine(model, params, num_slots=2,
                                       max_total_len=48,
                                       adapter_store=reg,
                                       mesh=eng_mesh)
        try:
            return [eng.submit(list(p), max_new_tokens=8,
                               adapter='ad0').result(timeout=300)
                    for p in PROMPTS[:2]]
        finally:
            eng.stop()

    assert run(mesh) == run(None)


# -- chain handoff across stage splits --------------------------------------
def _wire_payload(data):
    off = len(kv_transfer.MAGIC)
    hlen = int.from_bytes(data[off:off + 8], 'big')
    return data[off + 8 + hlen:]


@pytest.mark.slow
def test_chain_export_import_across_stage_split(setup):
    """KV page chains are mesh-agnostic across stage splits: export
    from a staged pool, import into a single-device pool, serve
    bit-identically, re-export BYTE-identically, and import back
    into a second staged pool — the wire format addresses layers by
    path, never by stage."""
    model, params, mesh = setup
    prompt = list(range(2, 34))
    src = ContinuousBatchingEngine(model, params, num_slots=2,
                                   max_total_len=48, mesh=mesh)
    dst = ContinuousBatchingEngine(model, params, num_slots=2,
                                   max_total_len=48)
    try:
        ref = src.submit(prompt, max_new_tokens=8).result(timeout=300)
        data = src.export_chain(prompt)
        assert data is not None
        stats = dst.import_chain(data)
        assert stats['imported'] > 0
        assert dst.submit(prompt, max_new_tokens=8).result(
            timeout=300) == ref
        back = dst.export_chain(prompt)
        assert _wire_payload(back) == _wire_payload(data)
        src2 = ContinuousBatchingEngine(model, params, num_slots=2,
                                        max_total_len=48, mesh=mesh)
        try:
            src2.import_chain(back)
            assert src2.submit(prompt, max_new_tokens=8).result(
                timeout=300) == ref
            assert _wire_payload(src2.export_chain(prompt)) == \
                _wire_payload(data)
        finally:
            src2.stop()
    finally:
        src.stop()
        dst.stop()


def test_chain_header_rejects_layer_mismatch(setup):
    """The chain header now pins num_layers like num_kv_heads: a
    payload from a different depth fails validation instead of
    corrupting the pool."""
    model, params, _ = setup
    src = ContinuousBatchingEngine(model, params, num_slots=2,
                                   max_total_len=48)
    deep_cfg = dataclasses.replace(model.config, num_layers=3)
    deep = Llama(deep_cfg)
    deep_params = nn.meta.unbox(deep.init(
        jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))['params'])
    dst = ContinuousBatchingEngine(deep, deep_params, num_slots=2,
                                   max_total_len=48)
    try:
        prompt = list(range(2, 34))
        src.submit(prompt, max_new_tokens=4).result(timeout=300)
        data = src.export_chain(prompt)
        assert data is not None
        with pytest.raises(ValueError, match='num_layers'):
            dst.import_chain(data)
    finally:
        src.stop()
        dst.stop()
