"""Azure cloud class + catalog: feasibility, pricing, failover."""
import pytest

from skypilot_tpu import resources as resources_lib
from skypilot_tpu.catalog import azure_catalog
from skypilot_tpu.clouds import Azure


@pytest.fixture()
def azure():
    return Azure()


def test_accelerator_to_instance_type(azure):
    r = resources_lib.Resources(accelerators='A100-80GB:4')
    feas = azure.get_feasible_launchable_resources(r)
    assert [x.instance_type for x in feas.resources_list] == \
        ['Standard_NC96ads_A100_v4']


def test_cpu_default_instance_type(azure):
    r = resources_lib.Resources(cpus='8+')
    feas = azure.get_feasible_launchable_resources(r)
    assert len(feas.resources_list) == 1
    it = feas.resources_list[0].instance_type
    vcpus, _ = azure_catalog.get_vcpus_mem_from_instance_type(it)
    assert vcpus >= 8


def test_tpu_request_infeasible(azure):
    r = resources_lib.Resources(accelerators='tpu-v5e-8')
    feas = azure.get_feasible_launchable_resources(r)
    assert feas.resources_list == []


def test_unknown_gpu_gives_fuzzy_candidates(azure):
    r = resources_lib.Resources(accelerators='A100-80GB:3')
    feas = azure.get_feasible_launchable_resources(r)
    assert feas.resources_list == []
    assert any('A100' in c for c in feas.fuzzy_candidate_list)


def test_hourly_cost_spot_cheaper(azure):
    r = resources_lib.Resources(accelerators='H100:8').copy(
        cloud=azure, instance_type='Standard_ND96isr_H100_v5')
    on_demand = azure.get_hourly_cost(r)
    spot = azure.get_hourly_cost(r.copy(use_spot=True))
    assert 0 < spot < on_demand


def test_regions_with_offering_gpu(azure):
    regions = Azure.regions_with_offering(
        'Standard_NC24ads_A100_v4', {'A100-80GB': 1}, False, None, None)
    names = [r.name for r in regions]
    assert 'eastus' in names and 'westus2' in names


def test_zones_provision_loop_walks_zones(azure):
    """Zonal rows in the catalog: the loop offers each zone in turn
    (GCP-style), so ZONE-scoped failover patterns have zones to walk."""
    batches = list(Azure.zones_provision_loop(
        region='eastus', num_nodes=1,
        instance_type='Standard_NC24ads_A100_v4',
        accelerators={'A100-80GB': 1}, use_spot=False))
    assert [[z.name for z in b] for b in batches] == [['1'], ['2'],
                                                      ['3']]


def test_validate_zone_is_region_scoped():
    with pytest.raises(ValueError, match='valid zones'):
        azure_catalog.validate_region_zone('eastus', '9')
    assert azure_catalog.get_zones('eastus') == ['1', '2', '3']


def test_validate_region_zone():
    azure_catalog.validate_region_zone('eastus', None)
    azure_catalog.validate_region_zone('eastus', '2')
    with pytest.raises(ValueError):
        azure_catalog.validate_region_zone('mars-east', None)
    with pytest.raises(ValueError):
        azure_catalog.validate_region_zone('eastus', 'a')


def test_deploy_variables(azure):
    from skypilot_tpu.clouds import cloud as cloud_lib
    r = resources_lib.Resources(accelerators='A100-80GB:1').copy(
        cloud=azure, instance_type='Standard_NC24ads_A100_v4')
    vars_ = azure.make_deploy_resources_variables(
        r, 'c-on-cloud', cloud_lib.Region('eastus'), None, 2)
    assert vars_['instance_type'] == 'Standard_NC24ads_A100_v4'
    assert vars_['region'] == 'eastus'
    assert vars_['zone'] is None
    assert vars_['num_nodes'] == 2
    assert vars_['tpu_vm'] is False


def test_egress_cost_tiers(azure):
    assert azure.get_egress_cost(0) == 0.0
    assert azure.get_egress_cost(100) == pytest.approx(8.75)
    assert azure.get_egress_cost(20000) > azure.get_egress_cost(10000)
