"""Ring attention numerical equivalence on a seq-sharded CPU mesh."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from skypilot_tpu.ops import ring_attention as ra
from skypilot_tpu.parallel import mesh as mesh_lib


@pytest.fixture(scope='module')
def seq_mesh():
    # 2 batch-parallel x 4 sequence-parallel
    return mesh_lib.make_mesh(mesh_lib.MeshConfig(data=2, seq=4))


@pytest.mark.slow
@pytest.mark.parametrize('causal', [True, False])
def test_ring_matches_reference(seq_mesh, causal):
    key = jax.random.PRNGKey(0)
    b, s, h, d = 4, 64, 2, 16
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, s, h, d), jnp.float32)
    k = jax.random.normal(kk, (b, s, h, d), jnp.float32)
    v = jax.random.normal(kv, (b, s, h, d), jnp.float32)

    expected = ra.reference_attention(q, k, v, causal=causal)
    with seq_mesh:
        got = jax.jit(
            lambda q, k, v: ra.ring_attention(
                q, k, v, mesh=seq_mesh, heads_axis=None, causal=causal)
        )(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_ring_grads_flow(seq_mesh):
    key = jax.random.PRNGKey(1)
    b, s, h, d = 2, 32, 2, 8
    q, k, v = (jax.random.normal(kk, (b, s, h, d), jnp.float32)
               for kk in jax.random.split(key, 3))

    def loss_ring(q, k, v):
        with seq_mesh:
            return jnp.sum(ra.ring_attention(q, k, v, mesh=seq_mesh,
                                             heads_axis=None) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(ra.reference_attention(q, k, v) ** 2)

    g_ring = jax.grad(loss_ring)(q, k, v)
    g_ref = jax.grad(loss_ref)(q, k, v)
    np.testing.assert_allclose(np.asarray(g_ring), np.asarray(g_ref),
                               rtol=1e-3, atol=1e-3)


@pytest.mark.slow
def test_context_parallel_gpt_matches_single_device():
    """GPT forward loss identical on a seq-parallel mesh vs one device."""
    import numpy as _np
    from skypilot_tpu.models.gpt import GPT, GPTConfig
    from skypilot_tpu.parallel.train import ShardedTrainer, shard_batch

    cfg = GPTConfig.tiny(dtype=jnp.float32)
    model = GPT(cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(7), (4, 64), 0,
                                cfg.vocab_size, jnp.int32)

    # Single-device loss.
    params = model.init(jax.random.PRNGKey(0), tokens)['params']
    import flax.linen as nn
    from skypilot_tpu.parallel.train import next_token_loss
    unboxed = nn.meta.unbox(params)
    ref_loss = float(next_token_loss(
        model.apply({'params': unboxed}, tokens), tokens))

    # Seq-parallel mesh loss with the same params.
    seq_mesh = mesh_lib.make_mesh(mesh_lib.MeshConfig(data=2, seq=4))
    trainer = ShardedTrainer(model, seq_mesh)
    state = trainer.init(jax.random.PRNGKey(0), tokens)
    eval_step = trainer.make_eval_step(tokens)
    cp_loss = float(eval_step(state, shard_batch(tokens, seq_mesh)))
    assert abs(cp_loss - ref_loss) < 1e-3, (cp_loss, ref_loss)
