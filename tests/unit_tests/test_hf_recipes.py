"""Recipe-level HF integration: serve_lm --hf and train_lm --init-from-hf.

Drives the real entrypoints as subprocesses against a tiny HF
checkpoint written to disk (the same on-disk shape an hf:// storage
COPY produces), including the tokenizer-backed /generate_text path —
the e2e statement that a user can point the serving/finetune recipes
at a downloaded repo and get a real model.
"""
import json
import os
import socket
import subprocess
import sys
import time
import urllib.request

import numpy as np
import pytest

transformers = pytest.importorskip('transformers')

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


@pytest.fixture(scope='module')
def hf_ckpt(tmp_path_factory):
    """Tiny llama HF repo dir: config + safetensors + tokenizer."""
    path = tmp_path_factory.mktemp('hf_llama')
    cfg = transformers.LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=160,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, max_position_embeddings=64,
        tie_word_embeddings=False)
    transformers.LlamaForCausalLM(cfg).eval().save_pretrained(
        path, safe_serialization=True)
    # A real (fast) tokenizer with ids inside the model vocab.
    from tokenizers import Tokenizer, models, pre_tokenizers
    vocab = {'<unk>': 0, 'hello': 1, 'world': 2, 'the': 3, 'tpu': 4,
             'flies': 5, 'fast': 6, '.': 7}
    tok = Tokenizer(models.WordLevel(vocab, unk_token='<unk>'))
    tok.pre_tokenizer = pre_tokenizers.Whitespace()
    fast = transformers.PreTrainedTokenizerFast(
        tokenizer_object=tok, unk_token='<unk>')
    fast.save_pretrained(path)
    return str(path)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(('127.0.0.1', 0))
        return s.getsockname()[1]


def _post(url, payload, timeout=120):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={'Content-Type': 'application/json'})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


@pytest.mark.slow
def test_serve_lm_hf_checkpoint(hf_ckpt):
    port = _free_port()
    proc = subprocess.Popen(
        [sys.executable, '-m', 'skypilot_tpu.recipes.serve_lm',
         '--cpu', '--hf', hf_ckpt, '--max-total-len', '48',
         '--port', str(port)],
        cwd=_REPO, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True)
    try:
        deadline = time.time() + 120
        ready = None
        while time.time() < deadline:
            try:
                with urllib.request.urlopen(
                        f'http://127.0.0.1:{port}/', timeout=5) as r:
                    ready = json.loads(r.read())
                break
            except OSError:
                assert proc.poll() is None, proc.stdout.read()
                time.sleep(1.0)
        assert ready is not None, 'server never became ready'
        assert ready['vocab_size'] == 128
        assert ready['max_total_len'] == 48

        # Token-ids path off the imported weights.
        out = _post(f'http://127.0.0.1:{port}/generate',
                    {'tokens': [[1, 2, 3, 4]], 'max_new_tokens': 8})
        assert len(out['tokens'][0]) == 48
        assert out['tokens'][0][:4] == [1, 2, 3, 4]

        # Text path through the checkpoint's tokenizer.
        out = _post(f'http://127.0.0.1:{port}/generate_text',
                    {'prompts': ['hello world the tpu'],
                     'max_new_tokens': 4})
        assert isinstance(out['texts'][0], str), out

        # OpenAI-compatible completions shim (the contract vLLM
        # clients speak): choices/usage shape, greedy determinism,
        # stop strings, and proper 400s on unsupported options.
        body = {'prompt': 'hello world the tpu', 'max_tokens': 4,
                'temperature': 0}
        out = _post(f'http://127.0.0.1:{port}/v1/completions', body)
        assert out['object'] == 'text_completion'
        choice = out['choices'][0]
        assert choice['finish_reason'] == 'length'
        assert out['usage']['prompt_tokens'] == 4
        assert out['usage']['completion_tokens'] == 4
        again = _post(f'http://127.0.0.1:{port}/v1/completions', body)
        assert again['choices'][0]['text'] == choice['text']
        words = choice['text'].split()
        if len(words) > 1:
            stopped = _post(f'http://127.0.0.1:{port}/v1/completions',
                            {**body, 'stop': [words[1]]})
            assert words[1] not in stopped['choices'][0]['text']
        from urllib.error import HTTPError
        try:
            _post(f'http://127.0.0.1:{port}/v1/completions',
                  {**body, 'stream': True})
            raise AssertionError('stream=true must 400')
        except HTTPError as e:
            assert e.code == 400

        # Chat shim: messages render through the chat template (plain
        # role fallback for template-less checkpoints like this one)
        # and the answer comes back as an assistant message.
        out = _post(f'http://127.0.0.1:{port}/v1/chat/completions',
                    {'messages': [
                        {'role': 'system', 'content': 'hello world'},
                        {'role': 'user', 'content': 'the tpu'}],
                     'max_tokens': 4, 'temperature': 0})
        assert out['object'] == 'chat.completion'
        msg = out['choices'][0]['message']
        assert msg['role'] == 'assistant'
        assert isinstance(msg['content'], str)
        assert out['usage']['completion_tokens'] == 4
    finally:
        proc.terminate()
        proc.wait(timeout=10)


@pytest.mark.slow
def test_train_lm_init_from_hf(hf_ckpt):
    out = subprocess.run(
        [sys.executable, '-m', 'skypilot_tpu.recipes.train_lm',
         '--cpu', '--init-from-hf', hf_ckpt, '--steps', '2',
         '--seq', '16', '--global-batch', '8', '--log-every', '1'],
        cwd=_REPO, capture_output=True, text=True, timeout=600)
    assert out.returncode == 0, out.stdout + out.stderr
    assert 'initializing from HF checkpoint' in out.stdout
    assert 'training done' in out.stdout
    # Finetuning a real checkpoint: the loss of step 2 is finite.
    losses = [float(line.split('loss=')[1].split()[0])
              for line in out.stdout.splitlines() if 'loss=' in line]
    assert losses and np.isfinite(losses).all(), out.stdout


@pytest.mark.slow
@pytest.mark.xfail(
    strict=False,
    reason='this container\'s axon-wrapped XLA runtime intermittently '
           'SIGABRTs in C++ teardown (~1 in 5) when the process '
           'handles SIGTERM — "FATAL: exception not rethrown" from a '
           'runtime thread, after the drain has already begun. The '
           'drain logic itself passes repeatedly; the abort is '
           'environmental (no such wrapper on real serving hosts).')
def test_serve_lm_graceful_drain():
    """SIGTERM (rolling update / replica cull) drains: the in-flight
    generation completes and the process exits 0 — no client resets."""
    import signal
    import threading
    port = _free_port()
    env = {k: v for k, v in os.environ.items() if k != 'XLA_FLAGS'}
    # Production shape: one serving process per host, default device
    # count. (The conftest's forced-8-virtual-CPU-devices XLA runtime
    # SIGABRTs in C++ teardown on exit — an XLA quirk unrelated to
    # the drain logic under test.)
    proc = subprocess.Popen(
        [sys.executable, '-m', 'skypilot_tpu.recipes.serve_lm', '--cpu',
         '--model', 'llama-tiny', '--max-total-len', '128',
         '--continuous-batching', '--port', str(port)],
        cwd=_REPO, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True)
    try:
        deadline = time.time() + 120
        while time.time() < deadline:
            try:
                urllib.request.urlopen(f'http://127.0.0.1:{port}/',
                                       timeout=2)
                break
            except OSError:
                assert proc.poll() is None, proc.stdout.read()
                time.sleep(1)
        # Warm compiles so the drained request is pure decode.
        _post(f'http://127.0.0.1:{port}/generate',
              {'tokens': [[5, 9, 2, 17]], 'max_new_tokens': 100},
              timeout=300)
        result = {}

        def slow_request():
            result['body'] = _post(
                f'http://127.0.0.1:{port}/generate',
                {'tokens': [[7, 8, 9]], 'max_new_tokens': 120},
                timeout=120)

        t = threading.Thread(target=slow_request)
        t.start()
        # Deterministic: fire SIGTERM only once the request is
        # OBSERVABLY in a decode slot (a sleep races the accept under
        # a loaded host).
        deadline = time.time() + 60
        while time.time() < deadline:
            try:
                with urllib.request.urlopen(
                        f'http://127.0.0.1:{port}/stats',
                        timeout=5) as r:
                    if json.loads(r.read())['active_slots'] >= 1:
                        break
            except OSError:
                pass
            time.sleep(0.05)
        else:
            raise AssertionError('request never became active')
        proc.send_signal(signal.SIGTERM)
        t.join(timeout=120)
        rc = proc.wait(timeout=60)
        assert 'body' in result, (
            f'in-flight request was dropped (rc={rc}): '
            f'{proc.stdout.read()[-2000:]}')
        assert len(result['body']['tokens'][0]) == 123
        assert rc == 0
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=10)
