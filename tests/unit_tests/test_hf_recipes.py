"""Recipe-level HF integration: serve_lm --hf and train_lm --init-from-hf.

Drives the real entrypoints as subprocesses against a tiny HF
checkpoint written to disk (the same on-disk shape an hf:// storage
COPY produces), including the tokenizer-backed /generate_text path —
the e2e statement that a user can point the serving/finetune recipes
at a downloaded repo and get a real model.
"""
import json
import os
import socket
import subprocess
import sys
import time
import urllib.request

import numpy as np
import pytest

transformers = pytest.importorskip('transformers')

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


@pytest.fixture(scope='module')
def hf_ckpt(tmp_path_factory):
    """Tiny llama HF repo dir: config + safetensors + tokenizer."""
    path = tmp_path_factory.mktemp('hf_llama')
    cfg = transformers.LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=160,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, max_position_embeddings=64,
        tie_word_embeddings=False)
    transformers.LlamaForCausalLM(cfg).eval().save_pretrained(
        path, safe_serialization=True)
    # A real (fast) tokenizer covering the FULL model vocab: the
    # randomly-initialized checkpoint can emit any id, and an id
    # outside the tokenizer vocab decodes to '' (which would make
    # text-streaming assertions vacuous/flaky).
    from tokenizers import Tokenizer, models, pre_tokenizers
    vocab = {'<unk>': 0, 'hello': 1, 'world': 2, 'the': 3, 'tpu': 4,
             'flies': 5, 'fast': 6, '.': 7}
    vocab.update({f'w{i}': i for i in range(8, 128)})
    tok = Tokenizer(models.WordLevel(vocab, unk_token='<unk>'))
    tok.pre_tokenizer = pre_tokenizers.Whitespace()
    fast = transformers.PreTrainedTokenizerFast(
        tokenizer_object=tok, unk_token='<unk>')
    fast.save_pretrained(path)
    return str(path)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(('127.0.0.1', 0))
        return s.getsockname()[1]


def _post(url, payload, timeout=120):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={'Content-Type': 'application/json'})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


@pytest.mark.slow
def test_serve_lm_hf_checkpoint(hf_ckpt):
    port = _free_port()
    proc = subprocess.Popen(
        [sys.executable, '-m', 'skypilot_tpu.recipes.serve_lm',
         '--cpu', '--hf', hf_ckpt, '--max-total-len', '48',
         '--port', str(port)],
        cwd=_REPO, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True)
    try:
        deadline = time.time() + 120
        ready = None
        while time.time() < deadline:
            try:
                with urllib.request.urlopen(
                        f'http://127.0.0.1:{port}/', timeout=5) as r:
                    ready = json.loads(r.read())
                break
            except OSError:
                assert proc.poll() is None, proc.stdout.read()
                time.sleep(1.0)
        assert ready is not None, 'server never became ready'
        assert ready['vocab_size'] == 128
        assert ready['max_total_len'] == 48

        # Token-ids path off the imported weights.
        out = _post(f'http://127.0.0.1:{port}/generate',
                    {'tokens': [[1, 2, 3, 4]], 'max_new_tokens': 8})
        assert len(out['tokens'][0]) == 48
        assert out['tokens'][0][:4] == [1, 2, 3, 4]

        # Text path through the checkpoint's tokenizer.
        out = _post(f'http://127.0.0.1:{port}/generate_text',
                    {'prompts': ['hello world the tpu'],
                     'max_new_tokens': 4})
        assert isinstance(out['texts'][0], str), out

        # OpenAI-compatible completions shim (the contract vLLM
        # clients speak): choices/usage shape, greedy determinism,
        # stop strings, and proper 400s on unsupported options.
        body = {'prompt': 'hello world the tpu', 'max_tokens': 4,
                'temperature': 0}
        out = _post(f'http://127.0.0.1:{port}/v1/completions', body)
        assert out['object'] == 'text_completion'
        choice = out['choices'][0]
        assert choice['finish_reason'] == 'length'
        assert out['usage']['prompt_tokens'] == 4
        assert out['usage']['completion_tokens'] == 4
        again = _post(f'http://127.0.0.1:{port}/v1/completions', body)
        assert again['choices'][0]['text'] == choice['text']
        words = choice['text'].split()
        if len(words) > 1:
            stopped = _post(f'http://127.0.0.1:{port}/v1/completions',
                            {**body, 'stop': [words[1]]})
            assert words[1] not in stopped['choices'][0]['text']
        # n>1 fan-out: n greedy samples are distinct choices with
        # correct indices (identical text — greedy by definition).
        multi = _post(f'http://127.0.0.1:{port}/v1/completions',
                      {**body, 'n': 3})
        assert [c['index'] for c in multi['choices']] == [0, 1, 2]
        assert all(c['text'] == choice['text']
                   for c in multi['choices'])
        assert multi['usage']['completion_tokens'] == 12
        # The prompt is counted ONCE regardless of n (OpenAI usage
        # contract — it used to be summed per choice).
        assert multi['usage']['prompt_tokens'] == 4
        assert multi['usage']['total_tokens'] == 16
        n2 = _post(f'http://127.0.0.1:{port}/v1/completions',
                   {**body, 'n': 2})
        assert n2['usage']['prompt_tokens'] == 4
        assert n2['usage']['completion_tokens'] == 8
        from urllib.error import HTTPError
        try:
            _post(f'http://127.0.0.1:{port}/v1/completions',
                  {**body, 'n': 99})
            raise AssertionError('n=99 must 400')
        except HTTPError as e:
            assert e.code == 400

        # logprobs + echo (the lm-eval scoring contract): max_tokens=0
        # returns the prompt's per-token logprobs with no generation.
        scored = _post(f'http://127.0.0.1:{port}/v1/completions',
                       {'prompt': 'hello world the tpu',
                        'max_tokens': 0, 'echo': True, 'logprobs': 2})
        lp = scored['choices'][0]['logprobs']
        assert scored['usage']['completion_tokens'] == 0
        assert len(lp['tokens']) == 4
        assert lp['token_logprobs'][0] is None       # no prefix
        assert all(isinstance(v, float) and v <= 0
                   for v in lp['token_logprobs'][1:])
        assert all(len(t) == 2 for t in lp['top_logprobs'][1:])
        assert lp['text_offset'][0] == 0

        # Generated-token logprobs: greedy decoding means every
        # generated token is its position's argmax — its logprob must
        # equal the max of the reported top alternatives.
        gen = _post(f'http://127.0.0.1:{port}/v1/completions',
                    {**body, 'logprobs': 3})
        glp = gen['choices'][0]['logprobs']
        assert len(glp['tokens']) == 4               # completion only
        for got, top in zip(glp['token_logprobs'], glp['top_logprobs']):
            assert got == pytest.approx(max(top.values()), abs=1e-4)

        # Chat shim: messages render through the chat template (plain
        # role fallback for template-less checkpoints like this one)
        # and the answer comes back as an assistant message.
        out = _post(f'http://127.0.0.1:{port}/v1/chat/completions',
                    {'messages': [
                        {'role': 'system', 'content': 'hello world'},
                        {'role': 'user', 'content': 'the tpu'}],
                     'max_tokens': 4, 'temperature': 0})
        assert out['object'] == 'chat.completion'
        msg = out['choices'][0]['message']
        assert msg['role'] == 'assistant'
        assert isinstance(msg['content'], str)
        assert out['usage']['completion_tokens'] == 4

        # Modern chat logprobs format: per-token content entries with
        # sorted top_logprobs.
        out = _post(f'http://127.0.0.1:{port}/v1/chat/completions',
                    {'messages': [{'role': 'user', 'content':
                                   'hello world'}],
                     'max_tokens': 3, 'temperature': 0,
                     'logprobs': True, 'top_logprobs': 2})
        content = out['choices'][0]['logprobs']['content']
        assert len(content) == 3
        for entry in content:
            assert isinstance(entry['token'], str)
            assert entry['logprob'] <= 0
            assert len(entry['top_logprobs']) == 2
            # Greedy: chosen token's logprob == the best alternative.
            assert entry['logprob'] == pytest.approx(
                entry['top_logprobs'][0]['logprob'], abs=1e-4)
    finally:
        proc.terminate()
        proc.wait(timeout=10)


def _post_sse(url, payload, timeout=300):
    """POST expecting an SSE response; returns (events, wall_times)
    — one wall-clock stamp per data frame, [DONE] excluded from
    events but stamped."""
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={'Content-Type': 'application/json'})
    events, times = [], []
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        ctype = resp.headers.get('Content-Type', '')
        assert ctype.startswith('text/event-stream'), ctype
        for raw in resp:
            line = raw.decode().rstrip('\n')
            if not line.startswith('data: '):
                continue
            times.append(time.time())
            data = line[len('data: '):]
            if data == '[DONE]':
                break
            events.append(json.loads(data))
    return events, times


@pytest.mark.slow
def test_serve_lm_streaming(hf_ckpt):
    """SSE streaming: chunks arrive incrementally (first chunk well
    before completion — the p50-TTFT north-star measured e2e), OpenAI
    chunk schemas hold for completions and chat, and /stats records
    TTFT percentiles."""
    port = _free_port()
    proc = subprocess.Popen(
        [sys.executable, '-m', 'skypilot_tpu.recipes.serve_lm',
         '--cpu', '--hf', hf_ckpt, '--max-total-len', '64',
         '--port', str(port)],
        cwd=_REPO, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True)
    try:
        deadline = time.time() + 120
        while time.time() < deadline:
            try:
                urllib.request.urlopen(f'http://127.0.0.1:{port}/',
                                       timeout=5)
                break
            except OSError:
                assert proc.poll() is None, proc.stdout.read()
                time.sleep(1.0)

        base = f'http://127.0.0.1:{port}'
        # Warmup: the first streaming request builds the lazy stream
        # engine + compiles prefill/decode; timing asserts come after.
        warm, _ = _post_sse(f'{base}/v1/completions',
                            {'prompt': 'hello world', 'max_tokens': 4,
                             'temperature': 0, 'stream': True})
        assert warm, 'no stream chunks'

        # Completions chunks: OpenAI schema, incremental arrival.
        t0 = time.time()
        events, times = _post_sse(
            f'{base}/v1/completions',
            {'prompt': 'hello world the tpu', 'max_tokens': 40,
             'temperature': 0, 'stream': True})
        text_chunks = [e for e in events
                       if e['choices'][0]['finish_reason'] is None]
        finals = [e for e in events
                  if e['choices'][0]['finish_reason'] is not None]
        assert text_chunks and len(finals) == 1
        assert all(e['object'] == 'text_completion' for e in events)
        assert finals[0]['choices'][0]['finish_reason'] == 'length'
        # Incrementality: the first chunk lands well before the
        # stream completes (non-streaming would deliver everything
        # at completion time).
        t_first, t_done = times[0] - t0, times[-1] - t0
        assert t_first < 0.6 * t_done, (t_first, t_done)

        # Streamed text == non-streaming text (same greedy path).
        whole = _post(f'{base}/v1/completions',
                      {'prompt': 'hello world the tpu',
                       'max_tokens': 40, 'temperature': 0})
        streamed = ''.join(e['choices'][0]['text']
                           for e in text_chunks)
        assert streamed == whole['choices'][0]['text']

        # Chat chunks: role delta first, then content deltas.
        events, _ = _post_sse(
            f'{base}/v1/chat/completions',
            {'messages': [{'role': 'user', 'content': 'hello world'}],
             'max_tokens': 6, 'temperature': 0, 'stream': True})
        assert events[0]['choices'][0]['delta'] == {'role': 'assistant'}
        assert all(e['object'] == 'chat.completion.chunk'
                   for e in events)
        content = ''.join(
            e['choices'][0]['delta'].get('content', '')
            for e in events)
        assert isinstance(content, str)

        # n>1 streaming: chunks carry choice indices 0 and 1.
        events, _ = _post_sse(
            f'{base}/v1/completions',
            {'prompt': 'hello world', 'max_tokens': 5,
             'temperature': 0, 'stream': True, 'n': 2})
        idx = {e['choices'][0]['index'] for e in events}
        assert idx == {0, 1}

        # Native token-stream endpoint.
        events, _ = _post_sse(
            f'{base}/generate',
            {'tokens': [[1, 2, 3]], 'max_new_tokens': 6,
             'stream': True})
        toks = [e['token'] for e in events if 'token' in e]
        final = [e for e in events if e.get('done')]
        assert len(toks) == 6 and len(final) == 1
        assert final[0]['tokens'][0][:3] == [1, 2, 3]

        # Text-stream endpoint: deltas concatenate to the full text.
        events, _ = _post_sse(
            f'{base}/generate_text',
            {'prompts': ['hello world'], 'max_new_tokens': 6,
             'stream': True})
        assert all('delta' in e for e in events)

        # TTFT percentiles landed in /stats.
        with urllib.request.urlopen(f'{base}/stats', timeout=5) as r:
            stats = json.loads(r.read())
        assert stats['serving']['ttft_ms_p50'] is not None
        assert stats['serving']['requests'] >= 6
    finally:
        proc.terminate()
        proc.wait(timeout=10)


@pytest.mark.slow
def test_train_lm_init_from_hf(hf_ckpt):
    out = subprocess.run(
        [sys.executable, '-m', 'skypilot_tpu.recipes.train_lm',
         '--cpu', '--init-from-hf', hf_ckpt, '--steps', '2',
         '--seq', '16', '--global-batch', '8', '--log-every', '1'],
        cwd=_REPO, capture_output=True, text=True, timeout=600)
    assert out.returncode == 0, out.stdout + out.stderr
    assert 'initializing from HF checkpoint' in out.stdout
    assert 'training done' in out.stdout
    # Finetuning a real checkpoint: the loss of step 2 is finite.
    losses = [float(line.split('loss=')[1].split()[0])
              for line in out.stdout.splitlines() if 'loss=' in line]
    assert losses and np.isfinite(losses).all(), out.stdout


@pytest.mark.slow
def test_serve_lm_graceful_drain():
    """SIGTERM (rolling update / replica cull) drains: the in-flight
    generation completes and the process exits 0 — no client resets."""
    import signal
    import threading
    port = _free_port()
    env = {k: v for k, v in os.environ.items() if k != 'XLA_FLAGS'}
    # Production shape: one serving process per host, default device
    # count. (The conftest's forced-8-virtual-CPU-devices XLA runtime
    # SIGABRTs in C++ teardown on exit — an XLA quirk unrelated to
    # the drain logic under test.)
    proc = subprocess.Popen(
        [sys.executable, '-m', 'skypilot_tpu.recipes.serve_lm', '--cpu',
         '--model', 'llama-tiny', '--max-total-len', '128',
         '--continuous-batching', '--port', str(port)],
        cwd=_REPO, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True)
    try:
        deadline = time.time() + 120
        while time.time() < deadline:
            try:
                urllib.request.urlopen(f'http://127.0.0.1:{port}/',
                                       timeout=2)
                break
            except OSError:
                assert proc.poll() is None, proc.stdout.read()
                time.sleep(1)
        # Warm compiles so the drained request is pure decode.
        _post(f'http://127.0.0.1:{port}/generate',
              {'tokens': [[5, 9, 2, 17]], 'max_new_tokens': 100},
              timeout=300)
        result = {}

        def slow_request():
            result['body'] = _post(
                f'http://127.0.0.1:{port}/generate',
                {'tokens': [[7, 8, 9]], 'max_new_tokens': 120},
                timeout=120)

        t = threading.Thread(target=slow_request)
        t.start()
        # Deterministic: fire SIGTERM only once the request is
        # OBSERVABLY in a decode slot (a sleep races the accept under
        # a loaded host).
        deadline = time.time() + 60
        while time.time() < deadline:
            try:
                with urllib.request.urlopen(
                        f'http://127.0.0.1:{port}/stats',
                        timeout=5) as r:
                    if json.loads(r.read())['active_slots'] >= 1:
                        break
            except OSError:
                pass
            time.sleep(0.05)
        else:
            raise AssertionError('request never became active')
        proc.send_signal(signal.SIGTERM)
        t.join(timeout=120)
        rc = proc.wait(timeout=60)
        assert 'body' in result, (
            f'in-flight request was dropped (rc={rc}): '
            f'{proc.stdout.read()[-2000:]}')
        assert len(result['body']['tokens'][0]) == 123
        assert rc == 0
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=10)
