"""Azure ARM provisioner against a fake ARM REST API.

Mirrors test_aws_provisioner.py: the fake patches the `_request` seam
(JSON dict shapes), so run/wait/query/stop/terminate/get_cluster_info
and the error classifier are exercised without the network.
"""
import pytest

from skypilot_tpu import exceptions
from skypilot_tpu.provision import common
from skypilot_tpu.provision.azure import arm_api
from skypilot_tpu.provision.azure import instance as az_instance


class FakeArm:

    def __init__(self):
        self.resources = {}  # normalized path -> body
        self.vm_state = {}   # vm name -> {'state', 'polls'}
        self.fail_vm_with = None  # (Code, Message)
        self.deleted_rgs = []
        self.port_rules = []
        self._n = 0

    def request(self, method, path, body=None, api_version=None):
        del api_version
        path_only, _, _query = path.partition('?')
        if method == 'PUT':
            return self._put(path_only, body or {})
        if method == 'GET':
            return self._get(path_only)
        if method == 'POST':
            _, vm_name, action = path_only.rsplit('/', 2)
            if action == 'deallocate':
                self.vm_state[vm_name]['state'] = 'stopped'
            elif action == 'start':
                self.vm_state[vm_name].update(state='running', polls=9)
            return {}
        if method == 'DELETE':
            rg = path_only.split('/resourceGroups/')[1].split('/')[0]
            self.deleted_rgs.append(rg)
            keep = {}
            for p, b in self.resources.items():
                if f'/resourceGroups/{rg}/' in p or \
                        p.endswith(f'/resourceGroups/{rg}'):
                    if '/virtualMachines/' in p:
                        self.vm_state.pop(p.rsplit('/', 1)[-1], None)
                    continue
                keep[p] = b
            self.resources = keep
            return {}
        raise AssertionError(f'unhandled {method} {path}')

    def _put(self, path, body):
        name = path.rsplit('/', 1)[-1]
        body = dict(body)
        body['id'] = path
        body['name'] = name
        if '/securityRules/' in path:
            self.port_rules.append(body)
        elif '/virtualMachines/' in path:
            if self.fail_vm_with:
                code, msg = self.fail_vm_with
                category, scope = arm_api._classify_error(code, msg)
                raise exceptions.ProvisionerError(
                    f'Azure PUT {name} -> {code}: {msg}',
                    category=category, scope=scope)
            self.vm_state[name] = {'state': 'creating', 'polls': 0}
        elif '/publicIPAddresses/' in path:
            self._n += 1
            body.setdefault('properties', {})['ipAddress'] = \
                f'20.1.0.{self._n}'
        elif '/networkInterfaces/' in path:
            self._n += 1
            cfg = body['properties']['ipConfigurations'][0]
            cfg['properties']['privateIPAddress'] = f'10.20.0.{self._n}'
        self.resources[path] = body
        return body

    def _in_rg(self, path, kind):
        rg = path.split('/resourceGroups/')[1].split('/')[0]
        return [b for p, b in sorted(self.resources.items())
                if f'/resourceGroups/{rg}/' in p and f'/{kind}/' in p
                and '/securityRules/' not in p and '/subnets/' not in p]

    def _get(self, path):
        if path.endswith('/virtualMachines'):
            items = []
            for b in self._in_rg(path, 'virtualMachines'):
                st = self.vm_state[b['name']]
                if st['state'] == 'creating':
                    st['polls'] += 1
                    if st['polls'] >= 2:
                        st['state'] = 'running'
                code = {'creating': 'PowerState/creating',
                        'running': 'PowerState/running',
                        'stopped': 'PowerState/deallocated'}[st['state']]
                item = dict(b)
                item['properties'] = dict(b.get('properties', {}))
                item['properties']['instanceView'] = {
                    'statuses': [{'code': code}]}
                items.append(item)
            return {'value': items}
        if path.endswith('/networkInterfaces'):
            return {'value': self._in_rg(path, 'networkInterfaces')}
        if path.endswith('/publicIPAddresses'):
            return {'value': self._in_rg(path, 'publicIPAddresses')}
        return self.resources.get(path, {})


@pytest.fixture()
def fake_arm(monkeypatch):
    fake = FakeArm()
    monkeypatch.setattr(arm_api, '_request', fake.request)
    monkeypatch.setattr(arm_api, '_subscription', lambda: 'sub-1')
    monkeypatch.setattr(az_instance, '_ssh_pub_key',
                        lambda: 'ssh-ed25519 AAAA test')
    monkeypatch.setattr(az_instance.time, 'sleep', lambda s: None)
    return fake


def _config(count=1, **pc):
    base = {'region': 'eastus', 'zone': None,
            'instance_type': 'Standard_NC24ads_A100_v4',
            'num_nodes': count, 'use_spot': False, 'disk_size': 100}
    base.update(pc)
    return common.ProvisionConfig(provider_config=base,
                                  authentication_config={}, count=count,
                                  tags={})


def test_run_wait_query_lifecycle(fake_arm):
    record = az_instance.run_instances('eastus', 'c1', _config(2))
    assert record.provider_name == 'azure'
    assert record.created_instance_ids == ['c1-0', 'c1-1']
    az_instance.wait_instances('eastus', 'c1',
                               provider_config=record.provider_config,
                               poll=0)
    status = az_instance.query_instances(
        'c1', provider_config=record.provider_config)
    assert status == {'c1-0': 'running', 'c1-1': 'running'}

    info = az_instance.get_cluster_info(
        'eastus', 'c1', provider_config=record.provider_config)
    assert info.head_instance_id == 'c1-0'
    assert len(info.instances) == 2
    assert info.instances[0].internal_ip.startswith('10.20.')
    assert info.instances[0].external_ip.startswith('20.')
    assert info.ssh_user == 'skypilot'
    # VM carries the ssh key and delete-with-VM resource options.
    vm = fake_arm.resources[
        '/subscriptions/sub-1/resourceGroups/sky-c1-eastus/providers'
        '/Microsoft.Compute/virtualMachines/c1-0']
    os_prof = vm['properties']['osProfile']
    assert 'test' in \
        os_prof['linuxConfiguration']['ssh']['publicKeys'][0]['keyData']
    assert vm['properties']['storageProfile']['osDisk']['deleteOption'] \
        == 'Delete'


def test_stop_resume(fake_arm):
    record = az_instance.run_instances('eastus', 'c2', _config(1))
    az_instance.wait_instances('eastus', 'c2',
                               provider_config=record.provider_config,
                               poll=0)
    az_instance.stop_instances('c2',
                               provider_config=record.provider_config)
    assert az_instance.query_instances(
        'c2', provider_config=record.provider_config) == {'c2': 'stopped'}
    record2 = az_instance.run_instances('eastus', 'c2', _config(1))
    assert record2.resumed_instance_ids == ['c2']
    assert record2.created_instance_ids == []


def test_terminate_deletes_resource_group(fake_arm):
    record = az_instance.run_instances('eastus', 'c3', _config(1))
    az_instance.terminate_instances(
        'c3', provider_config=record.provider_config)
    assert fake_arm.deleted_rgs == ['sky-c3-eastus']
    with pytest.raises(exceptions.FetchClusterInfoError):
        az_instance.get_cluster_info(
            'eastus', 'c3', provider_config=record.provider_config)


def test_spot_priority_in_vm_body(fake_arm):
    az_instance.run_instances('c4s', 'c4s', _config(1, use_spot=True))
    vm = fake_arm.resources[
        '/subscriptions/sub-1/resourceGroups/sky-c4s-eastus/providers'
        '/Microsoft.Compute/virtualMachines/c4s']
    assert vm['properties']['priority'] == 'Spot'
    assert vm['properties']['evictionPolicy'] == 'Delete'


def test_open_ports_adds_nsg_rules(fake_arm):
    record = az_instance.run_instances('eastus', 'c5', _config(1))
    az_instance.open_ports('c5', ['8080', '9000-9010'],
                           provider_config=record.provider_config)
    ranges = [r['properties']['destinationPortRange']
              for r in fake_arm.port_rules]
    assert ranges == ['8080', '9000-9010']


def test_capacity_error_category(fake_arm):
    fake_arm.fail_vm_with = ('SkuNotAvailable',
                             'The requested size is not available')
    with pytest.raises(exceptions.ProvisionerError) as e:
        az_instance.run_instances('eastus', 'c6', _config(1))
    assert e.value.category == exceptions.ProvisionerError.CAPACITY
    assert not e.value.no_failover


def test_quota_error_category(fake_arm):
    fake_arm.fail_vm_with = ('QuotaExceeded', 'Family vCPU quota 0')
    with pytest.raises(exceptions.ProvisionerError) as e:
        az_instance.run_instances('eastus', 'c7', _config(1))
    assert e.value.category == exceptions.ProvisionerError.QUOTA


def test_auth_error_category():
    assert arm_api._classify_error('AuthorizationFailed', 'no role')[0] == \
        exceptions.ProvisionerError.PERMISSION
    assert arm_api._classify_error('InvalidParameter', 'bad')[0] == \
        exceptions.ProvisionerError.CONFIG
    assert arm_api._classify_error('TooManyRequests', 'throttle')[0] == \
        exceptions.ProvisionerError.TRANSIENT


def test_failover_engine_walks_azure_zones(fake_arm, monkeypatch,
                                           isolated_state):
    """ZonalAllocationFailed is ZONE-scoped: zones 1 and 2 of eastus
    fail, the walk stays in the region and lands in zone 3."""
    from skypilot_tpu import resources as resources_lib
    from skypilot_tpu import task as task_lib
    from skypilot_tpu.backends.tpu_backend import RetryingProvisioner

    task = task_lib.Task(run='true')
    r = resources_lib.Resources(infra='azure',
                                accelerators='A100-80GB:1').copy(
        instance_type='Standard_NC24ads_A100_v4')
    task.set_resources(r)

    real_request = fake_arm.request
    failed_zones = []

    def exhausted_zones_1_2(method, path, body=None, api_version=None):
        if method == 'PUT' and '/virtualMachines/' in path and body:
            zones = body.get('zones') or []
            if body.get('location') == 'eastus' and \
                    zones and zones[0] in ('1', '2'):
                failed_zones.append(zones[0])
                raise exceptions.ProvisionerError(
                    'Azure PUT vm -> ZonalAllocationFailed: cannot '
                    'allocate in the requested zone',
                    category=exceptions.ProvisionerError.CAPACITY)
        return real_request(method, path, body, api_version)

    monkeypatch.setattr(arm_api, '_request', exhausted_zones_1_2)
    prov = RetryingProvisioner()
    record, resolved, region = prov.provision_with_retries(
        task, r, 'azz', 'azz')
    assert failed_zones == ['1', '2']
    assert region.name == 'eastus'          # same region throughout
    assert resolved.zone == '3'
    assert record.region == 'eastus'
    assert len(prov.failover_history) == 2


def test_failover_engine_walks_azure_regions(fake_arm, monkeypatch,
                                             isolated_state):
    """SkuNotAvailable is REGION-scoped: the walk skips eastus's
    remaining zones and moves to the next offering region."""
    from skypilot_tpu import resources as resources_lib
    from skypilot_tpu import task as task_lib
    from skypilot_tpu.backends.tpu_backend import RetryingProvisioner

    task = task_lib.Task(run='true')
    r = resources_lib.Resources(infra='azure',
                                accelerators='A100-80GB:1').copy(
        instance_type='Standard_NC24ads_A100_v4')
    task.set_resources(r)

    real_request = fake_arm.request
    failed_regions = []

    def capacity_in_eastus(method, path, body=None, api_version=None):
        if method == 'PUT' and '/virtualMachines/' in path and \
                body and body.get('location') == 'eastus':
            failed_regions.append('eastus')
            # Mirror arm_api's real classification: SkuNotAvailable is
            # REGION-scoped in the pattern table (pinned by
            # test_failover_patterns), so eastus's other zones are
            # skipped, not walked.
            from skypilot_tpu.provision import failover_patterns
            pat = failover_patterns.classify(
                'azure', 'SkuNotAvailable', 'not available')
            raise exceptions.ProvisionerError(
                'Azure PUT vm -> SkuNotAvailable: not available',
                category=pat.category, scope=pat.scope)
        return real_request(method, path, body, api_version)

    monkeypatch.setattr(arm_api, '_request', capacity_in_eastus)
    prov = RetryingProvisioner()
    record, resolved, region = prov.provision_with_retries(
        task, r, 'azf', 'azf')
    assert failed_regions == ['eastus']
    # Price-ordered offering walk: eastus (cheapest) -> westus2.
    assert region.name == 'westus2'
    assert record.region == 'westus2'
    assert resolved.region == 'westus2'
    assert len(prov.failover_history) == 1
