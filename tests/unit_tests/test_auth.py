"""API token auth middleware."""
import pytest

from tests.test_api_server import _free_port


@pytest.mark.slow
def test_token_auth(isolated_state, monkeypatch):
    import os
    import subprocess
    import sys
    import time

    import requests

    port = _free_port()
    url = f'http://127.0.0.1:{port}'
    env = dict(os.environ)
    env['SKYPILOT_TPU_HOME'] = isolated_state
    env['SKYPILOT_API_TOKEN'] = 'sekrit'
    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    env['PYTHONPATH'] = f"{repo_root}:{env.get('PYTHONPATH', '')}"
    proc = subprocess.Popen(
        [sys.executable, '-m', 'skypilot_tpu.server.server',
         '--port', str(port)], env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    try:
        deadline = time.time() + 30
        while time.time() < deadline:
            try:
                if requests.get(f'{url}/api/health', timeout=2).ok:
                    break
            except requests.RequestException:
                time.sleep(0.3)
        # Health open; everything else gated.
        assert requests.get(f'{url}/api/health', timeout=5).status_code == 200
        assert requests.post(f'{url}/check', json={},
                             timeout=5).status_code == 401
        assert requests.post(
            f'{url}/check', json={},
            headers={'Authorization': 'Bearer wrong'},
            timeout=5).status_code == 401
        ok = requests.post(f'{url}/check', json={},
                           headers={'Authorization': 'Bearer sekrit'},
                           timeout=5)
        assert ok.status_code == 200 and 'request_id' in ok.json()
    finally:
        proc.terminate()
        proc.wait(timeout=10)


@pytest.mark.slow
def test_oidc_auth(isolated_state):
    """OIDC posture end to end: JWT-bearing requests pass, others 401."""
    import os
    import subprocess
    import sys
    import time

    import requests
    import yaml

    from skypilot_tpu.users import oidc

    os.makedirs(isolated_state, exist_ok=True)
    with open(os.path.join(isolated_state, 'config.yaml'), 'w',
              encoding='utf-8') as f:
        yaml.safe_dump({'oauth': {'issuer': 'https://idp.test',
                                  'client_id': 'stpu-cli',
                                  'hs256_secret': 'jwtsecret',
                                  'admin_users': ['root@test']}}, f)
    port = _free_port()
    url = f'http://127.0.0.1:{port}'
    env = dict(os.environ)
    env['SKYPILOT_TPU_HOME'] = isolated_state
    env.pop('SKYPILOT_API_TOKEN', None)
    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    env['PYTHONPATH'] = f"{repo_root}:{env.get('PYTHONPATH', '')}"
    proc = subprocess.Popen(
        [sys.executable, '-m', 'skypilot_tpu.server.server',
         '--port', str(port)], env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    try:
        # Generous readiness window: server startup imports are slow
        # under a loaded host (parallel test runs on 1 core).
        deadline = time.time() + 120
        ready = False
        while time.time() < deadline:
            try:
                if requests.get(f'{url}/api/health', timeout=2).ok:
                    ready = True
                    break
            except requests.RequestException:
                assert proc.poll() is None, proc.stdout.read()
                time.sleep(0.3)
        assert ready, 'server never became healthy'
        # No bearer -> 401 (OIDC configured means auth required).
        assert requests.post(f'{url}/check', json={},
                             timeout=5).status_code == 401
        claims = {'iss': 'https://idp.test', 'aud': 'stpu-cli',
                  'email': 'alice@test', 'exp': time.time() + 600}
        good = oidc.make_hs256_jwt(claims, 'jwtsecret')
        ok = requests.post(f'{url}/check', json={},
                           headers={'Authorization': f'Bearer {good}'},
                           timeout=5)
        assert ok.status_code == 200 and 'request_id' in ok.json()
        bad = oidc.make_hs256_jwt(claims, 'wrong-secret')
        assert requests.post(
            f'{url}/check', json={},
            headers={'Authorization': f'Bearer {bad}'},
            timeout=5).status_code == 401
        expired = oidc.make_hs256_jwt(
            {**claims, 'exp': time.time() - 10}, 'jwtsecret')
        assert requests.post(
            f'{url}/check', json={},
            headers={'Authorization': f'Bearer {expired}'},
            timeout=5).status_code == 401
    finally:
        proc.terminate()
        proc.wait(timeout=10)
