"""--profile: jax.profiler traces from the training entrypoints.

The MFU triage loop (BASELINE.md north-star #1) starts from a trace;
these pin that both drivers actually produce TensorBoard/Perfetto
artifacts (xplane.pb + trace.json.gz) for the requested step window.
"""
import glob
import os
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _trace_files(root):
    return (glob.glob(os.path.join(root, 'plugins', 'profile', '*',
                                   '*.xplane.pb')) +
            glob.glob(os.path.join(root, 'plugins', 'profile', '*',
                                   '*.trace.json.gz')))


@pytest.mark.slow
def test_train_lm_profile_trace(tmp_path):
    prof = str(tmp_path / 'trace')
    out = subprocess.run(
        [sys.executable, '-m', 'skypilot_tpu.recipes.train_lm',
         '--cpu', '--model', 'tiny', '--steps', '10', '--seq', '32',
         '--global-batch', '8', '--log-every', '5',
         '--profile', prof, '--profile-steps', '4:7'],
        cwd=_REPO, capture_output=True, text=True, timeout=420)
    assert out.returncode == 0, out.stdout + out.stderr
    assert 'profile: steps 4..7 traced' in out.stdout
    files = _trace_files(prof)
    assert any(f.endswith('.xplane.pb') for f in files), files
    assert any(f.endswith('.trace.json.gz') for f in files), files


@pytest.mark.slow
def test_bench_profile_trace(tmp_path):
    prof = str(tmp_path / 'trace')
    out = subprocess.run(
        [sys.executable, 'bench.py', '--smoke', '--repeats', '1',
         '--steps', '4', '--profile', prof],
        cwd=_REPO, capture_output=True, text=True, timeout=420)
    assert out.returncode == 0, out.stdout + out.stderr
    assert _trace_files(prof), os.listdir(prof)
