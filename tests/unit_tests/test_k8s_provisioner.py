"""Kubernetes provisioner against a fake k8s API server (in-memory)."""
import re

import pytest

from skypilot_tpu import exceptions
from skypilot_tpu.provision import common
from skypilot_tpu.provision.kubernetes import instance as k8s_instance
from skypilot_tpu.utils import kubeconfig


class FakeK8s:
    """Emulates the pods/services endpoints used by the provisioner."""

    def __init__(self):
        self.pods = {}
        self.services = {}
        self._ip = 10

    def request(self, method, path, json_body=None):
        m = re.match(r'/api/v1/namespaces/([^/]+)/(pods|services)'
                     r'(?:/([^?]+))?(?:\?labelSelector=(.*))?$', path)
        assert m, path
        ns, kind, name, selector = m.groups()
        store = self.pods if kind == 'pods' else self.services
        if method == 'POST':
            manifest = dict(json_body)
            pod_name = manifest['metadata']['name']
            if kind == 'pods':
                manifest['status'] = {
                    'phase': 'Pending', '_polls': 0,
                    'podIP': f'10.0.0.{self._ip}'}
                self._ip += 1
            store[(ns, pod_name)] = manifest
            return manifest
        if method == 'GET' and name:
            item = store.get((ns, name))
            if item is None:
                raise exceptions.FetchClusterInfoError(
                    exceptions.FetchClusterInfoError.Reason.HEAD)
            return item
        if method == 'GET':
            items = list(store.values())
            if selector:
                key, value = selector.replace('%3D', '=').split('=')
                items = [i for i in items
                         if i['metadata'].get('labels', {}).get(key) ==
                         value]
                # pods become Running on second list
                for i in items:
                    st = i.get('status')
                    if st and st['phase'] == 'Pending':
                        st['_polls'] += 1
                        if st['_polls'] >= 2:
                            st['phase'] = 'Running'
            return {'items': items}
        if method == 'DELETE':
            if (ns, name) not in store:
                raise exceptions.FetchClusterInfoError(
                    exceptions.FetchClusterInfoError.Reason.HEAD)
            del store[(ns, name)]
            return {}
        raise AssertionError(f'unhandled {method} {path}')


@pytest.fixture()
def fake_k8s(monkeypatch):
    fake = FakeK8s()
    ctx = kubeconfig.KubeContext('gke_test', 'https://fake')
    monkeypatch.setattr(k8s_instance, '_ctx', lambda pc: ctx)
    monkeypatch.setattr(
        k8s_instance, '_request',
        lambda ctx_, method, path, json_body=None:
        fake.request(method, path, json_body))
    import skypilot_tpu.provision.kubernetes.instance as mod
    monkeypatch.setattr(mod.time, 'sleep', lambda s: None)
    return fake


def _config(count=1):
    return common.ProvisionConfig(
        provider_config={
            'context': 'gke_test',
            'tpu_vm': True,
            'tpu_accelerator_type': 'v5litepod-16',
            'tpu_topology': '4x4',
            'tpu_num_hosts': 2,
            'tpu_chips_per_host': 8,
            'num_nodes': count,
        },
        authentication_config={}, count=count, tags={})


def test_create_slice_pods_and_service(fake_k8s):
    record = k8s_instance.run_instances('gke_test', 'kc1', _config())
    assert record.created_instance_ids == ['kc1-0-0', 'kc1-0-1']
    assert ('default', 'kc1') in fake_k8s.services
    pod = fake_k8s.pods[('default', 'kc1-0-0')]
    sel = pod['spec']['nodeSelector']
    assert sel['cloud.google.com/gke-tpu-accelerator'] == \
        'tpu-v5-lite-podslice'
    assert sel['cloud.google.com/gke-tpu-topology'] == '4x4'
    limits = pod['spec']['containers'][0]['resources']['limits']
    assert limits['google.com/tpu'] == 8

    k8s_instance.wait_instances('gke_test', 'kc1',
                                provider_config=_config().provider_config)
    info = k8s_instance.get_cluster_info('gke_test', 'kc1',
                                         _config().provider_config)
    assert info.num_instances == 2
    assert [(i.node_rank, i.host_rank) for i in info.sorted_instances()] \
        == [(0, 0), (0, 1)]
    assert info.get_head_instance().internal_ip.startswith('10.0.0.')


def test_query_and_terminate(fake_k8s):
    cfg = _config(count=2)
    k8s_instance.run_instances('gke_test', 'kc2', cfg)
    statuses = k8s_instance.query_instances('kc2', cfg.provider_config)
    assert len(statuses) == 4
    k8s_instance.terminate_instances('kc2', cfg.provider_config)
    assert not fake_k8s.pods
    assert not fake_k8s.services
    with pytest.raises(exceptions.FetchClusterInfoError):
        k8s_instance.get_cluster_info('gke_test', 'kc2',
                                      cfg.provider_config)


def test_stop_unsupported(fake_k8s):
    with pytest.raises(exceptions.NotSupportedError):
        k8s_instance.stop_instances('kc3', {})


def test_kubeconfig_parsing(tmp_path):
    import base64
    cfg = tmp_path / 'config'
    ca = base64.b64encode(b'CERT').decode()
    cfg.write_text(f"""
apiVersion: v1
current-context: ctx-a
contexts:
- name: ctx-a
  context: {{cluster: c1, user: u1, namespace: ml}}
clusters:
- name: c1
  cluster:
    server: https://1.2.3.4:6443
    certificate-authority-data: {ca}
users:
- name: u1
  user:
    token: tok123
""")
    assert kubeconfig.load_contexts(str(cfg)) == ['ctx-a']
    ctx = kubeconfig.load_context(path=str(cfg))
    assert ctx.server == 'https://1.2.3.4:6443'
    assert ctx.namespace == 'ml'
    kwargs = ctx.request_kwargs()
    assert kwargs['headers']['Authorization'] == 'Bearer tok123'
    assert kwargs['verify'].endswith('.ca.crt')


def test_pod_manifest_mounts_pvc_volumes():
    """Named volumes ride the pod spec as PVC volumeMounts (k8s attach
    happens at provision, not at runtime)."""
    from skypilot_tpu.provision.kubernetes import instance as k8s
    pc = {'tpu_vm': False, 'cpus': 2,
          'volumes': {'ckpts': 'vol-ckpt', '/abs/data': 'vol-data'}}
    pod = k8s._pod_manifest('c1', 'c1-pod-0', pc, 0, 0)
    spec = pod['spec']
    claims = {v['persistentVolumeClaim']['claimName']
              for v in spec['volumes']}
    assert claims == {'vol-ckpt', 'vol-data'}
    mounts = {m['mountPath'] for m in spec['containers'][0]['volumeMounts']}
    assert '/abs/data' in mounts
    assert '/root/sky_workdir/ckpts' in mounts  # relative path anchored
