"""Catalog queries feeding the optimizer."""
import http.server
import os
import threading
import time

import pytest

from skypilot_tpu.catalog import common as catalog_common
from skypilot_tpu.catalog import gcp_catalog


def test_tpu_zones():
    zones = gcp_catalog.get_tpu_zones('tpu-v5p-128')
    assert zones, 'v5p must be offered somewhere'
    assert all(z.count('-') >= 2 for z in zones)
    # Huge pods only in big zones:
    big = gcp_catalog.get_tpu_zones('tpu-v5p-3072')
    assert set(big).issubset({'us-east5-a', 'us-central2-b'})


def test_tpu_cost_scales_with_chips():
    c16 = gcp_catalog.get_accelerator_hourly_cost('tpu-v5e-16', 1, False)
    c32 = gcp_catalog.get_accelerator_hourly_cost('tpu-v5e-32', 1, False)
    assert c32 == pytest.approx(2 * c16, rel=0.01)
    spot = gcp_catalog.get_accelerator_hourly_cost('tpu-v5e-16', 1, True)
    assert spot < c16


def test_vm_selection():
    it = gcp_catalog.get_instance_type_for_cpus_mem('8', None)
    assert it is not None
    vcpus, mem = gcp_catalog.get_vcpus_mem_from_instance_type(it)
    assert vcpus == 8
    # default: 8+ cpus, >=4GiB/cpu
    default = gcp_catalog.get_default_instance_type()
    vcpus, mem = gcp_catalog.get_vcpus_mem_from_instance_type(default)
    assert vcpus >= 8 and mem >= vcpus * 4


def test_gpu_instance_lookup():
    its = gcp_catalog.get_instance_type_for_accelerator('A100', 8)
    assert its == ['a2-highgpu-8g']
    accs = gcp_catalog.get_accelerators_from_instance_type('a2-highgpu-8g')
    assert accs == {'A100': 8}


def test_list_accelerators_filter():
    out = gcp_catalog.list_accelerators(name_filter='tpu-v6e')
    assert all(k.startswith('tpu-v6e') for k in out)
    assert 'tpu-v6e-8' in out


def test_validate_region_zone():
    region, zone = gcp_catalog.validate_region_zone(None, 'us-central2-b')
    assert region == 'us-central2'
    with pytest.raises(ValueError):
        gcp_catalog.validate_region_zone('mars', None)


def test_unknown_accelerator_pricing():
    with pytest.raises(ValueError):
        gcp_catalog.get_accelerator_hourly_cost('tpu-v5p-128', 1, False,
                                                region='mars')


def test_vm_zones_are_real_multi_zone():
    """VM zone enumeration reads the catalog (multi-zone regions), not
    a synthesized '<region>-a'."""
    zones = gcp_catalog.get_vm_zones(instance_type='n2-standard-8',
                                     region='us-central1')
    assert set(zones) == {'us-central1-a', 'us-central1-b',
                          'us-central1-c'}


def test_regions_by_price_cheapest_first():
    regions = gcp_catalog.regions_by_price(instance_type='n2-standard-8')
    # 0.388 group (us-central1/2, us-east1/5) before the pricier
    # regions; asia-northeast1 (0.5005) last.
    assert regions[0] == 'us-central1'
    assert regions[-1] == 'asia-northeast1'
    assert regions.index('us-west4') > regions.index('us-east5')

    # TPU table routes through the same interface (v5e list price is
    # uniform across regions, so the order is just deterministic).
    tpu_regions = gcp_catalog.regions_by_price(acc_name='tpu-v5e-16')
    assert 'us-central1' in tpu_regions and len(tpu_regions) >= 4


def test_failover_walk_is_price_ordered_with_real_zones():
    from skypilot_tpu.clouds.gcp import GCP
    regions = GCP.regions_with_offering('n2-standard-8', None, False,
                                        None, None)
    assert regions[0].name == 'us-central1'
    assert [z.name for z in regions[0].zones] == [
        'us-central1-a', 'us-central1-b', 'us-central1-c']
    assert regions[-1].name == 'asia-northeast1'


# ---------------------------------------------------------------------------
# Hosted-mirror refresh (fetch_remote_catalog)


class _MirrorHandler(http.server.BaseHTTPRequestHandler):
    files = {}
    hits = []

    def do_GET(self):  # noqa: N802
        self.__class__.hits.append(self.path)
        body = self.files.get(self.path)
        if body is None:
            self.send_response(404)
            self.end_headers()
            return
        self.send_response(200)
        self.end_headers()
        self.wfile.write(body.encode())

    def log_message(self, *args):
        pass


@pytest.fixture()
def mirror(tmp_path, monkeypatch):
    server = http.server.HTTPServer(('127.0.0.1', 0), _MirrorHandler)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    _MirrorHandler.files = {}
    _MirrorHandler.hits = []
    monkeypatch.setenv('SKYPILOT_CATALOG_MIRROR',
                       f'http://127.0.0.1:{server.server_port}')
    monkeypatch.setenv('SKYPILOT_CATALOG_CACHE', str(tmp_path / 'cache'))
    catalog_common.clear_cache()
    yield _MirrorHandler
    server.shutdown()
    catalog_common.clear_cache()


def test_fetch_remote_catalog_refresh_and_ttl(mirror):
    # Mirror carries a changed price for n2-standard-8 in us-central1-a.
    bundled = os.path.join(catalog_common._CATALOG_DIR, 'gcp_vms.csv')
    with open(bundled, 'r', encoding='utf-8') as f:
        content = f.read()
    changed = content.replace(
        'n2-standard-8,,,8,32,0.388,0.1164,us-central1,us-central1-a',
        'n2-standard-8,,,8,32,0.111,0.0333,us-central1,us-central1-a')
    assert changed != content
    mirror.files['/v1/gcp_vms.csv'] = changed

    path = catalog_common.fetch_remote_catalog('gcp_vms.csv')
    assert path is not None and os.path.exists(path)
    assert len(mirror.hits) == 1

    # read_catalog now serves the refreshed copy.
    df = catalog_common.read_catalog('gcp_vms.csv')
    row = df[(df['InstanceType'] == 'n2-standard-8')
             & (df['AvailabilityZone'] == 'us-central1-a')]
    assert float(row['Price'].iloc[0]) == pytest.approx(0.111)

    # Within the TTL the mirror is NOT re-contacted.
    assert catalog_common.fetch_remote_catalog('gcp_vms.csv') == path
    assert len(mirror.hits) == 1

    # Expired TTL refetches.
    old = time.time() - 100 * 3600
    os.utime(path, (old, old))
    assert catalog_common.fetch_remote_catalog('gcp_vms.csv') == path
    assert len(mirror.hits) == 2


def test_fetch_remote_catalog_rejects_bad_schema(mirror):
    mirror.files['/v1/gcp_vms.csv'] = 'InstanceType,Price\nn2,1.0\n'
    assert catalog_common.fetch_remote_catalog('gcp_vms.csv') is None
    # Bundled snapshot still serves.
    df = catalog_common.read_catalog('gcp_vms.csv')
    assert 'AvailabilityZone' in df.columns


def test_fetch_remote_catalog_offline_graceful(monkeypatch, tmp_path):
    monkeypatch.setenv('SKYPILOT_CATALOG_MIRROR',
                       'http://127.0.0.1:9')  # discard port: refused
    monkeypatch.setenv('SKYPILOT_CATALOG_CACHE', str(tmp_path))
    catalog_common.clear_cache()
    assert catalog_common.fetch_remote_catalog('gcp_vms.csv',
                                               timeout=0.5) is None
    assert catalog_common.read_catalog('gcp_vms.csv') is not None
    catalog_common.clear_cache()


def test_no_mirror_configured_is_a_noop(monkeypatch):
    monkeypatch.delenv('SKYPILOT_CATALOG_MIRROR', raising=False)
    assert catalog_common.fetch_remote_catalog('gcp_vms.csv') is None
    assert catalog_common.refresh_catalogs() == []


def test_newer_bundled_snapshot_beats_stale_cache(mirror):
    """A package upgrade (bundled file newer than the cached mirror
    copy) must win over a stale refresh from a dead mirror."""
    bundled = os.path.join(catalog_common._CATALOG_DIR, 'gcp_vms.csv')
    with open(bundled, 'r', encoding='utf-8') as f:
        content = f.read()
    mirror.files['/v1/gcp_vms.csv'] = content.replace(
        'n2-standard-8,,,8,32,0.388,0.1164,us-central1,us-central1-a',
        'n2-standard-8,,,8,32,0.222,0.0666,us-central1,us-central1-a')
    path = catalog_common.fetch_remote_catalog('gcp_vms.csv')
    assert path is not None
    # Make the cached copy look months older than the bundled file.
    old = os.path.getmtime(bundled) - 90 * 86400
    os.utime(path, (old, old))
    catalog_common.clear_cache()
    df = catalog_common.read_catalog('gcp_vms.csv')
    row = df[(df['InstanceType'] == 'n2-standard-8')
             & (df['AvailabilityZone'] == 'us-central1-a')]
    assert float(row['Price'].iloc[0]) == pytest.approx(0.388)  # bundled


# ---------------------------------------------------------------------------
# PreemptionRate column + spot-zone economics
# ---------------------------------------------------------------------------
def test_bundled_tpu_catalog_carries_preemption_rate():
    df = gcp_catalog._tpu_df()
    assert 'PreemptionRate' in df.columns
    assert (df['PreemptionRate'] > 0).all()
    # Bundled snapshot agrees with the generator (the CSV is the
    # generator's frozen output; regenerating must not drift).
    gen = gcp_catalog._generate_tpu_df()
    assert set(gen.columns) == set(df.columns)
    assert len(gen) == len(df)


def test_get_preemption_rate_scopes_by_region_and_zone():
    rate = gcp_catalog.get_preemption_rate('tpu-v5e-16')
    assert rate is not None and rate > 0
    pinned = gcp_catalog.get_preemption_rate('tpu-v5e-16',
                                             zone='us-east5-b')
    assert pinned == pytest.approx(
        gcp_catalog._ZONE_PREEMPTION_RATE['us-east5-b'])
    # Unpinned returns the best (min) matching zone's rate.
    assert rate <= pinned
    assert gcp_catalog.get_preemption_rate('a100') is None  # not TPU


def test_spot_zone_economics_orders_by_risk_adjusted_price():
    import pandas as pd
    from skypilot_tpu.jobs import policy
    econ = gcp_catalog.spot_zone_economics('tpu-v5e-16')
    assert len(econ) >= 2
    keys = [p * policy.effective_cost_multiplier(r)
            for _, p, r in econ]
    assert keys == sorted(keys)
    # The flip the column exists for: a CHEAPER but stormier zone
    # loses to a pricier stable one once risk is priced in.
    synthetic = pd.DataFrame([
        {'AcceleratorName': 'tpu-v5e-16', 'Region': 'r1',
         'AvailabilityZone': 'r1-a', 'SpotPrice': 10.0,
         'PreemptionRate': 2.0},
        {'AcceleratorName': 'tpu-v5e-16', 'Region': 'r2',
         'AvailabilityZone': 'r2-a', 'SpotPrice': 11.0,
         'PreemptionRate': 0.05},
    ])
    assert (10.0 < 11.0 <
            10.0 * policy.effective_cost_multiplier(2.0))
    orig = gcp_catalog._tpu_df
    gcp_catalog._tpu_df = lambda: synthetic
    try:
        flipped = gcp_catalog.spot_zone_economics('tpu-v5e-16')
    finally:
        gcp_catalog._tpu_df = orig
    assert [z for z, _, _ in flipped] == ['r2-a', 'r1-a']
