"""Catalog queries feeding the optimizer."""
import pytest

from skypilot_tpu.catalog import gcp_catalog


def test_tpu_zones():
    zones = gcp_catalog.get_tpu_zones('tpu-v5p-128')
    assert zones, 'v5p must be offered somewhere'
    assert all(z.count('-') >= 2 for z in zones)
    # Huge pods only in big zones:
    big = gcp_catalog.get_tpu_zones('tpu-v5p-3072')
    assert set(big).issubset({'us-east5-a', 'us-central2-b'})


def test_tpu_cost_scales_with_chips():
    c16 = gcp_catalog.get_accelerator_hourly_cost('tpu-v5e-16', 1, False)
    c32 = gcp_catalog.get_accelerator_hourly_cost('tpu-v5e-32', 1, False)
    assert c32 == pytest.approx(2 * c16, rel=0.01)
    spot = gcp_catalog.get_accelerator_hourly_cost('tpu-v5e-16', 1, True)
    assert spot < c16


def test_vm_selection():
    it = gcp_catalog.get_instance_type_for_cpus_mem('8', None)
    assert it is not None
    vcpus, mem = gcp_catalog.get_vcpus_mem_from_instance_type(it)
    assert vcpus == 8
    # default: 8+ cpus, >=4GiB/cpu
    default = gcp_catalog.get_default_instance_type()
    vcpus, mem = gcp_catalog.get_vcpus_mem_from_instance_type(default)
    assert vcpus >= 8 and mem >= vcpus * 4


def test_gpu_instance_lookup():
    its = gcp_catalog.get_instance_type_for_accelerator('A100', 8)
    assert its == ['a2-highgpu-8g']
    accs = gcp_catalog.get_accelerators_from_instance_type('a2-highgpu-8g')
    assert accs == {'A100': 8}


def test_list_accelerators_filter():
    out = gcp_catalog.list_accelerators(name_filter='tpu-v6e')
    assert all(k.startswith('tpu-v6e') for k in out)
    assert 'tpu-v6e-8' in out


def test_validate_region_zone():
    region, zone = gcp_catalog.validate_region_zone(None, 'us-central2-b')
    assert region == 'us-central2'
    with pytest.raises(ValueError):
        gcp_catalog.validate_region_zone('mars', None)


def test_unknown_accelerator_pricing():
    with pytest.raises(ValueError):
        gcp_catalog.get_accelerator_hourly_cost('tpu-v5p-128', 1, False,
                                                region='mars')
