"""Every bundled example YAML must parse into a valid Task."""
import glob
import os

import pytest

from skypilot_tpu import task as task_lib
from skypilot_tpu.utils import common_utils

_EXAMPLES = sorted(glob.glob(os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), 'examples', '*.yaml')))


@pytest.mark.parametrize('path', _EXAMPLES,
                         ids=[os.path.basename(p) for p in _EXAMPLES])
def test_example_parses(path, monkeypatch):
    monkeypatch.setenv('CKPT_DIR', '/tmp/x')
    monkeypatch.setenv('CKPT_BUCKET', 'gs://x')
    # Multi-document YAML = a managed-job pipeline: every stage must
    # parse as its own task.
    configs = [c for c in common_utils.read_yaml_all(path) if c]
    assert configs, path
    for config in configs:
        task = task_lib.Task.from_yaml_config(config)
        assert task.run, path
        resources = next(iter(task.resources))
        assert resources.cloud is not None
        if 'serve' in os.path.basename(path):
            assert task.service is not None


def test_examples_exist():
    assert len(_EXAMPLES) >= 6
