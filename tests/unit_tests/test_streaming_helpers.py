"""Pure-Python streaming helpers: stop-string holdback + metrics.

(The end-to-end SSE path — engine callbacks, chunk schemas, TTFT —
is exercised in test_hf_recipes.py::test_serve_lm_streaming.)
"""
from skypilot_tpu.inference.openai_compat import (StopStringScanner,
                                                  trim_stops)
from skypilot_tpu.inference.runtime import ServingMetrics


def test_scanner_no_stops_passthrough():
    s = StopStringScanner([])
    assert s.push('hello ') == 'hello '
    assert s.push('world') == 'world'
    assert not s.hit
    assert s.flush() == ''


def test_scanner_cuts_at_stop():
    s = StopStringScanner(['END'])
    assert s.push('abc') == 'abc'
    assert s.push('dENDxyz') == 'd'
    assert s.hit
    assert s.push('more') == ''  # post-stop: nothing
    assert s.flush() == ''


def test_scanner_holds_back_possible_prefix():
    """Text that might be the start of a stop string is withheld
    until disambiguated — a client must never see part of a stop."""
    s = StopStringScanner(['END'])
    assert s.push('abcE') == 'abc'      # 'E' could start 'END'
    assert s.push('N') == ''            # 'EN' still ambiguous
    assert s.push('Dtail') == ''        # 'END' found: cut before it
    assert s.hit


def test_scanner_prefix_resolves_negative():
    s = StopStringScanner(['END'])
    assert s.push('abcE') == 'abc'
    assert s.push('xyz') == 'Exyz'      # 'Ex' != 'EN': release
    assert not s.hit


def test_scanner_stop_split_across_many_pushes():
    s = StopStringScanner(['<|eot|>'])
    out = ''
    for ch in 'hi there<|eot|>IGNORED':
        out += s.push(ch)
    assert out == 'hi there'
    assert s.hit


def test_scanner_earliest_of_multiple_stops_wins():
    s = StopStringScanner(['YY', 'XX'])
    assert s.push('aXXbYYc') == 'a'
    assert s.hit


def test_trim_stops():
    assert trim_stops('a b c', []) == ('a b c', False)
    assert trim_stops('a b c', ['b']) == ('a ', True)
    assert trim_stops('a b c', ['z']) == ('a b c', False)
    assert trim_stops('a b c', ['c', 'b']) == ('a ', True)


def test_metrics_percentiles():
    m = ServingMetrics()
    assert m.snapshot()['ttft_ms_p50'] is None
    for i in range(100):
        m.record(latency_s=(i + 1) / 1000.0, n_tokens=10,
                 ttft_s=(i + 1) / 10000.0)
    snap = m.snapshot()
    assert snap['requests'] == 100
    assert abs(snap['latency_ms_p50'] - 50) <= 2
    assert abs(snap['latency_ms_p95'] - 95) <= 2
    assert abs(snap['ttft_ms_p50'] - 5.0) <= 0.3
    assert snap['completion_tokens_total'] == 1000
    assert snap['gen_tokens_per_sec'] > 0


def test_usage_counts_prompt_once_for_n():
    """usage.prompt_tokens counts each prompt ONCE regardless of n
    (OpenAI contract) — row_prompt holds one entry per CHOICE, so
    summing it over-reported the prompt n-fold."""
    from skypilot_tpu.inference.openai_compat import (CompletionRequest,
                                                      run_completion)

    class _Tok:
        def __call__(self, prompt):
            return {'input_ids': [1, 2, 3, 4]}

        def decode(self, ids, skip_special_tokens=True):
            return 'x' * len(ids)

    class _Metrics:
        def record(self, *args, **kwargs):
            pass

    class _RT:
        engine = None
        model_name = 'stub'
        metrics = _Metrics()

        def get_tokenizer(self):
            return _Tok()

        def limit_for(self, temperature, streaming=False):
            return 64

        def engine_for(self, adapter=None):
            return self.engine

    # max_new=0 scoring mode: no generation, usage still reported.
    req = CompletionRequest(prompts=['hello'], max_new=0,
                            temperature=0.0, top_p=1.0,
                            stop_strings=None, n=2, stream=False)
    out = run_completion(_RT(), req)
    assert len(out['choices']) == 2
    assert out['usage']['prompt_tokens'] == 4      # once, not 2 x 4
    assert out['usage']['completion_tokens'] == 0
    assert out['usage']['total_tokens'] == 4

    # Two prompts x n=2: both prompts counted, each once.
    req2 = CompletionRequest(prompts=['a', 'b'], max_new=0,
                             temperature=0.0, top_p=1.0,
                             stop_strings=None, n=2, stream=False)
    out2 = run_completion(_RT(), req2)
    assert len(out2['choices']) == 4
    assert out2['usage']['prompt_tokens'] == 8
