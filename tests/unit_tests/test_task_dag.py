"""Task YAML + Dag behavior."""
import textwrap

import pytest

from skypilot_tpu import dag as dag_lib
from skypilot_tpu import exceptions
from skypilot_tpu.task import Task


def test_from_yaml_config_full():
    t = Task.from_yaml_config({
        'name': 'train',
        'resources': {'accelerators': 'tpu-v5e-16', 'infra': 'gcp'},
        'num_nodes': 2,
        'envs': {'LR': '3e-4'},
        'secrets': {'TOKEN': 'abc'},
        'setup': 'echo setup',
        'run': 'python train.py --lr ${LR} --token ${TOKEN}',
    })
    assert t.run == 'python train.py --lr 3e-4 --token abc'
    assert t.num_nodes == 2
    assert t.envs == {'LR': '3e-4'}
    assert t.secrets == {'TOKEN': 'abc'}


def test_env_override_and_null(monkeypatch):
    monkeypatch.setenv('FROM_CALLER', 'xyz')
    t = Task.from_yaml_config({'envs': {'FROM_CALLER': None}, 'run': 'true'})
    assert t.envs == {'FROM_CALLER': 'xyz'}
    monkeypatch.delenv('FROM_CALLER')
    with pytest.raises(exceptions.InvalidTaskYAMLError):
        Task.from_yaml_config({'envs': {'FROM_CALLER': None}})


def test_secrets_redacted():
    t = Task.from_yaml_config({'secrets': {'K': 'v'}, 'run': 'true'})
    assert t.to_yaml_config(redact_secrets=True)['secrets'] == {
        'K': '<redacted>'}


def test_dag_chain():
    with dag_lib.Dag('pipeline') as d:
        a = Task(name='a', run='true')
        b = Task(name='b', run='true')
        c = Task(name='c', run='true')
        for t in (a, b, c):
            d.add(t)
        a >> b >> c
    assert d.is_chain()
    assert d.get_sorted_tasks() == [a, b, c]


def test_dag_not_chain():
    with dag_lib.Dag() as d:
        a, b, c = (Task(name=n, run='true') for n in 'abc')
        for t in (a, b, c):
            d.add(t)
        a >> c
        b >> c
    assert not d.is_chain()
    d.validate()


def test_rshift_outside_dag_raises():
    a, b = Task(run='true'), Task(run='true')
    with pytest.raises(RuntimeError):
        a >> b
