"""Native + fallback token loader: determinism, sharding, shapes.

Tests that NEED the C++ core skip-with-reason where it cannot build
or load (no toolchain / GLIBC mismatch); the loader itself falls back
to numpy there, so the behavioral tests still run.
"""
import numpy as np
import pytest

from skypilot_tpu.data import token_loader

requires_native = pytest.mark.skipif(
    not token_loader.native_available(),
    reason=f'native token_loader unavailable: '
           f'{token_loader.native_unavailable_reason()}')


@pytest.fixture(scope='module')
def shards(tmp_path_factory):
    d = tmp_path_factory.mktemp('tokens')
    paths = []
    offset = 0
    for i in range(3):
        n = 5000 + i * 1000
        arr = (np.arange(offset, offset + n) % 50257).astype(np.uint16)
        p = d / f'shard{i}.bin'
        arr.tofile(p)
        paths.append(str(p))
        offset += n
    return paths


@requires_native
def test_native_builds_and_loads(shards):
    assert token_loader.native_available(), 'C++ loader must build'
    loader = token_loader.TokenLoader(shards, batch=4, seq=32, seed=1)
    assert loader.total_tokens == 5000 + 6000 + 7000
    batch = loader.next_batch()
    assert batch.shape == (4, 33)
    assert batch.dtype == np.uint32
    assert batch.max() < 50257
    loader.close()


def test_sequential_crosses_shard_boundaries(shards):
    # Tokens were written as consecutive integers (mod 50257) across
    # shards, so any window must be consecutive — including windows
    # spanning shard boundaries.
    loader = token_loader.TokenLoader(shards, batch=2, seq=128, seed=0,
                                      shuffle=False)
    for _ in range(40):
        batch = loader.next_batch()
        for row in batch:
            diffs = np.diff(row.astype(np.int64)) % 50257
            assert (diffs == 1).all(), row[:5]
    loader.close()


@requires_native
def test_native_matches_fallback_sequential(shards):
    native = token_loader.TokenLoader(shards, batch=2, seq=16,
                                      shuffle=False, use_native=True)
    fallback = token_loader.TokenLoader(shards, batch=2, seq=16,
                                        shuffle=False, use_native=False)
    # Native prefetches asynchronously but steps are deterministic;
    # collect a few batches and compare as sets of rows.
    n_batches = 5
    native_rows = sorted(tuple(r) for _ in range(n_batches)
                         for r in native.next_batch())
    fallback_rows = sorted(tuple(r) for _ in range(n_batches)
                           for r in fallback.next_batch())
    assert native_rows == fallback_rows
    native.close()


def test_rank_disjoint_streams(shards):
    a = token_loader.TokenLoader(shards, batch=2, seq=16, shuffle=False,
                                 rank=0, world=2)
    b = token_loader.TokenLoader(shards, batch=2, seq=16, shuffle=False,
                                 rank=1, world=2)
    rows_a = {tuple(r) for _ in range(3) for r in a.next_batch()}
    rows_b = {tuple(r) for _ in range(3) for r in b.next_batch()}
    assert not rows_a & rows_b
    a.close()
    b.close()


def test_too_small_dataset(tmp_path):
    p = tmp_path / 'tiny.bin'
    np.arange(10, dtype=np.uint16).tofile(p)
    with pytest.raises(ValueError):
        token_loader.TokenLoader([str(p)], batch=1, seq=32)
