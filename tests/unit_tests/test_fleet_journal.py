"""Crash-only control plane: durable fleet journal, orphan adoption
on restart, and the controller tick-failure fuse.

Everything tier-1. The journal/adoption machinery is exercised three
ways: pure journal unit tests (replay determinism, torn-tail
tolerance, compaction), the adoption verification matrix on fake
handles with injected pid probes and scrapes (live+match /
live+UUID-mismatch / dead pid / port reused), and chaos runs on REAL
stub subprocesses where the controller "crashes" (its in-memory
state is abandoned) mid-scale-down or mid-drain and a fresh
manager+controller adopts the fleet from the same state dir — zero
healthy replicas killed, zero leaked processes, affinity routing
preserved. The end-to-end SIGKILL-the-entrypoint version lives in
tests/test_serve.py.
"""
import json
import os
import sys
import threading
import time

import pytest
import requests

from skypilot_tpu.observability import catalog as obs_catalog
from skypilot_tpu.robustness import faults
from skypilot_tpu.serve import autoscalers
from skypilot_tpu.serve import load_balancing_policies as lbp
from skypilot_tpu.serve import serve_state
from skypilot_tpu.serve.replica_plane import (FleetController,
                                              FleetJournal,
                                              ReplicaManager,
                                              make_lb_server)
from skypilot_tpu.serve.replica_plane import journal as journal_lib
from skypilot_tpu.serve.replica_plane import replica_manager as rm
from skypilot_tpu.serve.service_spec import SkyServiceSpec

REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))


def _spec(**kw):
    kw.setdefault('min_replicas', 1)
    kw.setdefault('max_replicas', 5)
    kw.setdefault('upscale_delay_seconds', 10)
    kw.setdefault('downscale_delay_seconds', 20)
    return SkyServiceSpec(**kw)


class _FakeClock:

    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


# ---------------------------------------------------------------------------
# journal: append/replay/compaction
# ---------------------------------------------------------------------------
def _record(rid, port=7000, state='READY', uuid='u', pid=None):
    return dict(replica_id=rid, port=port,
                endpoint=f'127.0.0.1:{port}', instance_uuid=uuid,
                state=state, pid=pid)


def test_journal_append_replay_roundtrip(tmp_path):
    j = FleetJournal(str(tmp_path / 'fleet.journal'))
    j.append('spawn', **_record(1, 7001, 'STARTING', 'aaa', 101))
    j.append('spawn', **_record(2, 7002, 'STARTING', 'bbb', 102))
    j.append('state', replica_id=1, state='READY')
    j.append('state', replica_id=2, state='FAILED')
    j.append('spawn', **_record(3, 7003, 'STARTING', 'ccc', 103))
    j.append('terminate', replica_id=3)
    live = j.replay()
    # 2 is terminal (FAILED), 3 terminated: only 1 survives, with
    # its LAST state folded in.
    assert sorted(live) == [1]
    assert live[1].state == 'READY'
    assert live[1].port == 7001
    assert live[1].instance_uuid == 'aaa'
    assert live[1].pid == 101
    assert journal_lib.max_journaled_id(j.path) == 3


def test_journal_replay_tolerates_torn_tail(tmp_path):
    path = str(tmp_path / 'fleet.journal')
    j = FleetJournal(path)
    j.append('spawn', **_record(1, 7001, 'READY', 'aaa', 101))
    j.append('spawn', **_record(2, 7002, 'READY', 'bbb', 102))
    j.close()
    # The controller died mid-append: a torn, non-JSON final line.
    with open(path, 'a', encoding='utf-8') as f:
        f.write('{"event": "state", "replica_id": 2, "sta')
    live = journal_lib.replay_journal(path)
    assert sorted(live) == [1, 2]  # every COMPLETE line intact
    assert live[2].state == 'READY'  # torn update ignored


def test_journal_compaction_state_identical_and_file_shrinks(tmp_path):
    j = FleetJournal(str(tmp_path / 'fleet.journal'))
    j.append('spawn', **_record(1, 7001, 'STARTING', 'aaa', 101))
    for _ in range(30):
        j.append('state', replica_id=1, state='NOT_READY')
        j.append('state', replica_id=1, state='READY')
    j.append('spawn', **_record(2, 7002, 'STARTING', 'bbb', 102))
    j.append('state', replica_id=2, state='SHUTDOWN')
    before = j.replay()
    size_before = os.path.getsize(j.path)
    j.compact()
    after = j.replay()
    assert before == after  # replayed state is identical
    assert os.path.getsize(j.path) < size_before
    with open(j.path, 'r', encoding='utf-8') as f:
        lines = [json.loads(l) for l in f]
    # One snapshot line per LIVE record; terminal ones dropped.
    assert [l['event'] for l in lines] == ['snapshot']
    assert lines[0]['replica_id'] == 1
    # The journal keeps accepting appends after compaction.
    j.append('state', replica_id=1, state='DRAINING')
    assert j.replay()[1].state == 'DRAINING'


def test_journal_auto_compacts_on_threshold(tmp_path):
    j = FleetJournal(str(tmp_path / 'fleet.journal'),
                     compact_every=10)
    j.append('spawn', **_record(1, 7001, 'READY', 'aaa', 101))
    for i in range(25):
        j.append('state', replica_id=1, state='READY')
    with open(j.path, 'r', encoding='utf-8') as f:
        n_lines = sum(1 for _ in f)
    # 26 appends with compact_every=10: compacted at least twice,
    # so the file holds far fewer lines than events appended.
    assert n_lines <= 10
    assert j.replay()[1].state == 'READY'


def test_journal_skips_malformed_interior_line(tmp_path):
    path = str(tmp_path / 'fleet.journal')
    with open(path, 'w', encoding='utf-8') as f:
        f.write(json.dumps({'event': 'spawn', **_record(
            1, 7001, 'READY', 'aaa', 101)}) + '\n')
        f.write('not json at all\n')
        f.write(json.dumps({'event': 'spawn', **_record(
            2, 7002, 'READY', 'bbb', 102)}) + '\n')
    live = journal_lib.replay_journal(path)
    assert sorted(live) == [1, 2]


# ---------------------------------------------------------------------------
# manager write-through journaling
# ---------------------------------------------------------------------------
class FakeProc:

    def __init__(self, pid=None, on_sigterm=None):
        self.pid = pid
        self.rc = None
        self.signals = []
        self._on_sigterm = on_sigterm

    def poll(self):
        return self.rc

    def send_signal(self, sig):
        self.signals.append(sig)
        if self._on_sigterm is not None:
            self._on_sigterm(self)

    def terminate(self):
        self.send_signal(15)

    def kill(self):
        self.rc = -9

    def wait(self, timeout=None):
        return self.rc


class FakeScrapes:
    """endpoint -> (ready, stats); unknown endpoints raise."""

    def __init__(self):
        self.table = {}

    def set(self, endpoint, ready=True, **stats):
        self.table[endpoint] = (ready, stats)

    def __call__(self, url, timeout):
        host = url.split('//')[1].split('/')[0]
        if host not in self.table:
            raise ConnectionError(f'unreachable {host}')
        ready, stats = self.table[host]
        if url.endswith('/readyz'):
            return (200 if ready else 503), {'ready': ready}
        return 200, stats


def test_manager_journals_every_lifecycle_change(tmp_path):
    scrapes = FakeScrapes()
    pids = iter([501, 502])
    mgr = ReplicaManager(
        lambda rid, port: FakeProc(pid=next(pids),
                                   on_sigterm=lambda p: setattr(
                                       p, 'rc', 0)),
        http_get=scrapes, state_dir=str(tmp_path),
        drain_grace_s=5.0)
    v1 = mgr.spawn()
    v2 = mgr.spawn()
    scrapes.set(v1.endpoint, ready=True,
                instance_uuid=v1.instance_uuid)
    scrapes.set(v2.endpoint, ready=True,
                instance_uuid=v2.instance_uuid)
    mgr.scrape_once()
    path = os.path.join(str(tmp_path), 'fleet.journal')
    live = journal_lib.replay_journal(path)
    assert sorted(live) == [1, 2]
    assert live[1].state == 'READY'
    assert live[1].pid == 501
    assert live[1].instance_uuid == v1.instance_uuid
    assert live[1].instance_uuid != live[2].instance_uuid  # per spawn
    # Drain 2: DRAINING then SHUTDOWN journaled; after remove() the
    # record is terminated — replay shows only replica 1.
    mgr.mark_draining(2)
    assert journal_lib.replay_journal(path)[2].state == 'DRAINING'
    mgr.drain(2)
    assert 2 not in journal_lib.replay_journal(path)  # SHUTDOWN
    mgr.remove(2)
    live = journal_lib.replay_journal(path)
    assert sorted(live) == [1]
    # Crash detection journals FAILED.
    v1.proc.rc = 1
    mgr.scrape_once()
    assert 1 not in journal_lib.replay_journal(path)


def test_manager_without_state_dir_journals_nothing(tmp_path):
    mgr = ReplicaManager(lambda rid, port: FakeProc(),
                         http_get=FakeScrapes())
    mgr.spawn()
    assert os.listdir(str(tmp_path)) == []
    assert mgr.adopt() == {'adopted': [], 'resumed_drains': [],
                           'orphans': []}


# ---------------------------------------------------------------------------
# adoption verification matrix
# ---------------------------------------------------------------------------
def _seed_journal(tmp_path, rows):
    """rows: list of (rid, port, uuid, pid, state)."""
    j = FleetJournal(os.path.join(str(tmp_path), 'fleet.journal'))
    for rid, port, uuid, pid, state in rows:
        j.append('spawn', **_record(rid, port, state, uuid, pid))
    j.close()


def test_adopt_verification_matrix(tmp_path):
    """One journaled replica per verification outcome:
      1: pid alive + /stats echoes the journaled UUID -> ADOPTED
      2: pid alive + /stats echoes a DIFFERENT UUID    -> orphan
      3: pid dead, port unreachable                    -> orphan
      4: pid dead, port answers with a foreign UUID
         (port reused by a stranger)                   -> orphan
    Orphans with a live pid get SIGTERM — never SIGKILL; dead pids
    are never signaled at all."""
    _seed_journal(tmp_path, [
        (1, 7101, 'uuid-1', 201, 'READY'),
        (2, 7102, 'uuid-2', 202, 'READY'),
        (3, 7103, 'uuid-3', 203, 'NOT_READY'),
        (4, 7104, 'uuid-4', 204, 'READY'),
    ])
    scrapes = FakeScrapes()
    scrapes.set('127.0.0.1:7101', instance_uuid='uuid-1')
    scrapes.set('127.0.0.1:7102', instance_uuid='uuid-OTHER')
    scrapes.set('127.0.0.1:7104', instance_uuid='uuid-STRANGER')
    alive = {201, 202}
    signals = []
    mgr = ReplicaManager(
        lambda rid, port: FakeProc(), http_get=scrapes,
        state_dir=str(tmp_path),
        pid_probe=lambda pid: pid in alive,
        signal_pid=lambda pid, sig: signals.append((pid, sig)))
    adoptions_before = obs_catalog.counter(
        'skypilot_fleet_adoptions_total').value
    orphans_before = obs_catalog.counter(
        'skypilot_fleet_orphans_reaped_total').value
    summary = mgr.adopt()
    assert summary == {'adopted': [1], 'resumed_drains': [],
                       'orphans': [2, 3, 4]}
    # Only the live unverifiable pid was signaled, with SIGTERM.
    assert signals == [(202, 15)]
    view = mgr.view(1)
    assert view.adopted
    assert view.state == serve_state.ReplicaStatus.STARTING
    assert view.instance_uuid == 'uuid-1'
    assert view.endpoint == '127.0.0.1:7101'
    assert obs_catalog.counter(
        'skypilot_fleet_adoptions_total').value == \
        adoptions_before + 1
    assert obs_catalog.counter(
        'skypilot_fleet_orphans_reaped_total').value == \
        orphans_before + 3
    # The journal now only knows the adopted replica.
    live = journal_lib.replay_journal(
        os.path.join(str(tmp_path), 'fleet.journal'))
    assert sorted(live) == [1]
    # A scrape pass re-earns READY and routing.
    mgr.scrape_once()
    assert mgr.ready_endpoints() == ['127.0.0.1:7101']


def test_adopt_resumes_interrupted_drain(tmp_path):
    """A replica journaled DRAINING was mid-scale-down when the
    controller died: adoption resumes the drain (SIGTERM -> wait for
    self-exit) and never readmits it to routing."""
    _seed_journal(tmp_path, [(1, 7201, 'uuid-1', 301, 'DRAINING')])
    scrapes = FakeScrapes()
    scrapes.set('127.0.0.1:7201', ready=False,
                instance_uuid='uuid-1')
    alive = {301}
    signals = []

    def signal_pid(pid, sig):
        signals.append((pid, sig))
        if sig == 15:
            alive.discard(pid)  # drains and exits by itself

    mgr = ReplicaManager(
        lambda rid, port: FakeProc(), http_get=scrapes,
        state_dir=str(tmp_path), drain_grace_s=5.0,
        pid_probe=lambda pid: pid in alive, signal_pid=signal_pid)
    summary = mgr.adopt(block_drains=True)
    assert summary == {'adopted': [], 'resumed_drains': [1],
                       'orphans': []}
    assert signals == [(301, 15)]  # SIGTERM only, no SIGKILL
    view = mgr.view(1)
    assert view.state == serve_state.ReplicaStatus.SHUTDOWN
    assert mgr.ready_endpoints() == []


def test_adopt_resumes_id_counter_above_journal(tmp_path):
    """Replica ids stay unique across controller generations — even
    past terminated records (id reuse would alias journal replay)."""
    _seed_journal(tmp_path, [(7, 7301, 'uuid-7', None, 'READY')])
    j = FleetJournal(os.path.join(str(tmp_path), 'fleet.journal'))
    j.append('terminate', replica_id=7)
    j.close()
    mgr = ReplicaManager(lambda rid, port: FakeProc(),
                         http_get=FakeScrapes(),
                         state_dir=str(tmp_path))
    assert mgr.adopt() == {'adopted': [], 'resumed_drains': [],
                           'orphans': []}
    view = mgr.spawn()
    assert view.replica_id == 8


def test_adopt_requires_uuid_and_pid(tmp_path):
    """A record with no instance UUID or no pid can never verify
    (legacy or fake-handle fleets): it is an orphan, and with no pid
    there is nothing to signal."""
    _seed_journal(tmp_path, [
        (1, 7401, '', 401, 'READY'),      # no uuid
        (2, 7402, 'uuid-2', None, 'READY'),  # no pid
    ])
    scrapes = FakeScrapes()
    scrapes.set('127.0.0.1:7401', instance_uuid='')
    scrapes.set('127.0.0.1:7402', instance_uuid='uuid-2')
    signals = []
    mgr = ReplicaManager(
        lambda rid, port: FakeProc(), http_get=scrapes,
        state_dir=str(tmp_path),
        pid_probe=lambda pid: pid == 401,
        signal_pid=lambda pid, sig: signals.append((pid, sig)))
    summary = mgr.adopt()
    assert summary['adopted'] == []
    assert summary['orphans'] == [1, 2]
    assert signals == [(401, 15)]


# ---------------------------------------------------------------------------
# controller: tick fuse, drain-thread pruning, clocked wait_ready
# ---------------------------------------------------------------------------
def _controller(tmp_path=None, **mgr_kw):
    scrapes = FakeScrapes()
    mgr = ReplicaManager(lambda rid, port: FakeProc(),
                         http_get=scrapes, **mgr_kw)
    auto = autoscalers.EngineMetricsAutoscaler(_spec())
    ctl = FleetController(mgr, lbp.RoundRobinPolicy(), auto)
    return ctl, mgr, scrapes


def test_tick_error_fuse_three_strikes_and_recovery():
    ctl, _mgr, _scrapes = _controller()
    errors = obs_catalog.counter('skypilot_fleet_tick_errors_total')
    degraded = obs_catalog.gauge(
        'skypilot_fleet_controller_degraded')
    before = errors.value
    faults.install_plan({'rules': [{
        'point': 'fleet.tick', 'action': 'raise',
        'exc': 'RuntimeError', 'message': 'injected tick failure',
        'times': 3}]})
    try:
        assert not ctl.safe_tick()
        assert not ctl.safe_tick()
        assert degraded.value == 0  # two strikes: not degraded yet
        assert not ctl.safe_tick()
        assert degraded.value == 1  # third consecutive: degraded
        assert ctl.consecutive_tick_failures == 3
        assert errors.value == before + 3
        # Plan exhausted (times=3): the next tick succeeds and
        # resets the fuse.
        assert ctl.safe_tick()
        assert degraded.value == 0
        assert ctl.consecutive_tick_failures == 0
    finally:
        faults.clear()


def test_tick_fault_point_reaches_plain_tick():
    ctl, _mgr, _scrapes = _controller()
    faults.install_plan({'rules': [{
        'point': 'fleet.tick', 'action': 'raise',
        'exc': 'ValueError', 'message': 'tick poisoned',
        'times': 1}]})
    try:
        with pytest.raises(ValueError, match='tick poisoned'):
            ctl.tick()
    finally:
        faults.clear()


def test_drain_threads_pruned():
    """Long-running fleets must not accumulate one dead Thread per
    scale-down forever."""
    scrapes = FakeScrapes()
    mgr = ReplicaManager(
        lambda rid, port: FakeProc(
            on_sigterm=lambda p: setattr(p, 'rc', 0)),
        http_get=scrapes, drain_grace_s=5.0)
    auto = autoscalers.EngineMetricsAutoscaler(_spec())
    ctl = FleetController(mgr, lbp.RoundRobinPolicy(), auto)
    for _ in range(6):
        view = mgr.spawn()
        ctl.drain_replica(view)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            if view.state == serve_state.ReplicaStatus.SHUTDOWN:
                break
            time.sleep(0.01)
    # Let the last drain thread finish, then one more drain prunes.
    for t in list(ctl._drain_threads):
        t.join(5)
    view = mgr.spawn()
    ctl.drain_replica(view)
    assert len(ctl._drain_threads) <= 2


def test_wait_ready_runs_on_injected_clock():
    """wait_ready's deadline moves only when the injected clock
    does: with a frozen clock it would loop forever, with a jumped
    clock it returns immediately — no wall-clock reads."""
    clock = _FakeClock()
    scrapes = FakeScrapes()
    mgr = ReplicaManager(lambda rid, port: FakeProc(),
                         http_get=scrapes, clock=clock)
    auto = autoscalers.EngineMetricsAutoscaler(_spec(), clock)
    ctl = FleetController(mgr, lbp.RoundRobinPolicy(), auto,
                          clock=clock)
    ticks = {'n': 0}
    orig_tick = ctl.tick

    def counting_tick(now=None):
        ticks['n'] += 1
        clock.t += 100.0  # each tick advances virtual time
        orig_tick(now=clock.t)

    ctl.tick = counting_tick
    assert not ctl.wait_ready(1, timeout_s=250.0, poll_s=0.0)
    # 250 virtual seconds at 100 per tick: exactly 3 ticks ran —
    # the loop consulted the INJECTED clock, not the wall clock.
    assert ticks['n'] == 3


# ---------------------------------------------------------------------------
# chaos: controller crash + restart over REAL stub subprocesses
# ---------------------------------------------------------------------------
def _stub_env():
    env = dict(os.environ)
    env['PYTHONPATH'] = f"{REPO}:{env.get('PYTHONPATH', '')}"
    return env


def _wait_ready(ctl, mgr, n, timeout=30):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        ctl.tick()
        if len(mgr.ready_endpoints()) >= n:
            return True
        time.sleep(0.05)
    return False


def _reap(procs, timeout=10):
    for p in procs:
        try:
            if p.poll() is None:
                p.terminate()
            p.wait(timeout=timeout)
        except Exception:  # pylint: disable=broad-except
            p.kill()


def test_chaos_controller_crash_midscaledown_adopts_fleet(tmp_path):
    """SIGKILL-shaped controller death mid-scale-down: replica 3 is
    journaled DRAINING (routing already stopped) but the controller
    dies before SIGTERM is sent. A NEW manager+controller on the
    same state dir adopts the two healthy replicas (zero healthy
    replicas killed — they never see a signal), resumes the
    interrupted drain (the victim exits 0, not killed), and the LB
    ring rebuilt from the adopted set routes affinity keys exactly
    as before the crash. Zero leaked processes at the end."""
    state_dir = str(tmp_path)
    spawned = []

    def tracking_factory(env):
        inner = rm.stub_factory(
            extra_args=['--token-sleep-ms', '0'], env=env)

        def spawn(rid, port, instance_uuid=''):
            proc = inner(rid, port, instance_uuid=instance_uuid)
            spawned.append(proc)
            return proc

        return spawn

    policy1 = lbp.PrefixAffinityPolicy()
    mgr1 = ReplicaManager(tracking_factory(_stub_env()),
                          state_dir=state_dir, drain_grace_s=10.0)
    auto1 = autoscalers.EngineMetricsAutoscaler(
        _spec(min_replicas=3, max_replicas=3))
    ctl1 = FleetController(mgr1, policy1, auto1)
    try:
        for _ in range(3):
            mgr1.spawn()
        assert _wait_ready(ctl1, mgr1, 3), \
            [v.to_dict() for v in mgr1.views()]
        endpoints = sorted(mgr1.ready_endpoints())
        victim = mgr1.view(3)
        survivors = [v for v in mgr1.views() if v.replica_id != 3]
        # Affinity snapshot: where 20 keys route pre-crash.
        keys = [f'key-{i}' for i in range(20)]
        pre = {k: policy1.affinity_target(k) for k in keys}

        # Scale-down begins: DRAINING journaled, routing stopped...
        mgr1.mark_draining(3)
        # ...and the controller DIES here (before SIGTERM). All its
        # in-memory state is gone; the stub processes live on.
        del ctl1, mgr1, auto1, policy1

        # --- restart: fresh control plane, same state dir --------------
        policy2 = lbp.PrefixAffinityPolicy()
        mgr2 = ReplicaManager(tracking_factory(_stub_env()),
                              state_dir=state_dir,
                              drain_grace_s=10.0)
        auto2 = autoscalers.EngineMetricsAutoscaler(
            _spec(min_replicas=2, max_replicas=2))
        ctl2 = FleetController(mgr2, policy2, auto2)
        summary = mgr2.adopt(block_drains=True)
        assert sorted(summary['adopted']) == [1, 2]
        assert summary['resumed_drains'] == [3]
        assert summary['orphans'] == []
        # The interrupted drain finished: the victim exited 0 on its
        # own (SIGTERM drain), it was NOT killed.
        assert spawned[2].wait(timeout=10) == 0
        # The healthy replicas were never signaled and still serve.
        assert _wait_ready(ctl2, mgr2, 2)
        adopted_eps = sorted(mgr2.ready_endpoints())
        assert adopted_eps == sorted(
            v.endpoint for v in survivors)
        assert victim.endpoint not in adopted_eps
        for ep in adopted_eps:
            assert requests.get(f'http://{ep}/stats',
                                timeout=5).status_code == 200
        # Ring rebuilt from the adopted set: every key that routed
        # to a SURVIVOR pre-crash routes to the same replica now
        # (its KV pages are still there), and the dead replica's
        # keys remapped onto live ones.
        for k in keys:
            if pre[k] in adopted_eps:
                assert policy2.affinity_target(k) == pre[k]
            else:
                assert policy2.affinity_target(k) in adopted_eps
        # New generation spawns do not collide with journaled ids.
        assert next(mgr2._ids) == 4
        ctl2.shutdown()
    finally:
        _reap(spawned)
    # Zero leaked processes: every stub we ever spawned has exited.
    assert all(p.poll() is not None for p in spawned)


def test_chaos_restarted_fleet_serves_through_lb(tmp_path):
    """After adoption the full serving path works end to end: the
    restarted controller's LB answers keyed POSTs from the adopted
    replicas with zero 5xx."""
    state_dir = str(tmp_path)
    spawned = []
    env = _stub_env()
    inner = rm.stub_factory(extra_args=['--token-sleep-ms', '0'],
                            env=env)

    def factory(rid, port, instance_uuid=''):
        proc = inner(rid, port, instance_uuid=instance_uuid)
        spawned.append(proc)
        return proc

    mgr1 = ReplicaManager(factory, state_dir=state_dir,
                          drain_grace_s=10.0)
    ctl1 = FleetController(
        mgr1, lbp.PrefixAffinityPolicy(),
        autoscalers.EngineMetricsAutoscaler(
            _spec(min_replicas=2, max_replicas=2)))
    try:
        mgr1.spawn()
        mgr1.spawn()
        assert _wait_ready(ctl1, mgr1, 2)
        del ctl1, mgr1  # controller crash

        policy = lbp.PrefixAffinityPolicy()
        mgr2 = ReplicaManager(factory, state_dir=state_dir,
                              drain_grace_s=10.0)
        ctl2 = FleetController(
            mgr2, policy, autoscalers.EngineMetricsAutoscaler(
                _spec(min_replicas=2, max_replicas=2)))
        assert sorted(mgr2.adopt()['adopted']) == [1, 2]
        assert _wait_ready(ctl2, mgr2, 2)
        lb_port = rm.free_port()
        lb = make_lb_server(policy, lb_port,
                            policy_name='prefix_affinity',
                            manager=mgr2)
        threading.Thread(target=lb.serve_forever,
                         daemon=True).start()
        url = f'http://127.0.0.1:{lb_port}'
        try:
            for i in range(8):
                r = requests.post(f'{url}/generate', json={
                    'tokens': [[100 + i] * 16 + [1, 2]],
                    'max_new_tokens': 3}, timeout=30)
                assert r.status_code == 200
            snap = lb.lb_metrics.snapshot()
            assert snap['routed'] >= 8 and snap['retried'] == 0
        finally:
            ctl2.shutdown()
            lb.shutdown()
    finally:
        _reap(spawned)
    assert all(p.poll() is not None for p in spawned)
