"""Quantized serving: int8 KV pages + per-channel int8 weights.

The contracts under test:

  - int8 KV page round-trips are exact for constant pages and
    bounded (scale/2 per element) otherwise; scales live in parallel
    scale pages and travel with their physical page.
  - Greedy decode under kv_dtype=int8 stays within the documented
    logprob tolerance of the bf16 path on the echo+logprobs scoring
    harness (the /v1/completions eval contract), and the scheduler
    invariants (pipelined == unpipelined, chunked-decode bit-
    identity, preempt/recover determinism) survive quantized
    storage.
  - Prefix-cache hits return QUANTIZED pages with their scales: a
    cache-hit continuation is bit-identical to recomputing the same
    pages fresh.
  - weight_dtype=int8 per-channel projections serve within tolerance
    of the f32 model, compose with batched LoRA (parity vs the
    merged-weights oracle) and with --tensor 2 on CPU host devices
    (bit-identical to the single-device int8 run).
"""
import os
import tempfile

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from skypilot_tpu.inference import quant as quant_lib
from skypilot_tpu.inference.adapters import AdapterRegistry
from skypilot_tpu.inference.runtime import InferenceRuntime
from skypilot_tpu.models import lora as lora_lib
from skypilot_tpu.models.batching import ContinuousBatchingEngine
from skypilot_tpu.models.llama import Llama, LlamaConfig
from skypilot_tpu.ops import paged_attention as paged_ops

#: Documented tolerance (docs/guides.md "Quantized serving"): mean
#: per-token logprob of a quantized greedy continuation, scored by
#: the exact (full-forward) scorer, within this of the bf16 path's.
LOGPROB_TOL = 0.1


def _build(kv_dtype='bf16', **kw):
    cfg = LlamaConfig.tiny(dtype=jnp.float32, kv_page_size=8,
                           kv_total_pages=40, kv_dtype=kv_dtype, **kw)
    model = Llama(cfg)
    params = nn.meta.unbox(model.init(
        jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))['params'])
    return model, params


@pytest.fixture(scope='module')
def base():
    return _build()


@pytest.fixture(scope='module')
def base_int8(base):
    """Same weights as `base`, int8 KV config."""
    model_q, _ = _build(kv_dtype='int8')
    return model_q, base[1]


class _IntTok:
    """Space-separated-int 'tokenizer': enough for the OpenAI
    completions contract functions on a registry model."""

    def __call__(self, prompt):
        return {'input_ids': [int(t) for t in prompt.split()]}

    def decode(self, ids, skip_special_tokens=True):
        return ' '.join(str(int(t)) for t in ids)

    def convert_ids_to_tokens(self, ids):
        return [str(int(t)) for t in ids]


def _runtime(model, params, engine) -> InferenceRuntime:
    rt = InferenceRuntime(
        model=model, params=params,
        vocab_size=model.config.vocab_size, model_name='llama-tiny',
        max_total_len=48, spec_total=48, speculative=0,
        engine=engine, engine_total=48)
    rt._tok_holder['tok'] = _IntTok()
    return rt


def _score_continuation(rt: InferenceRuntime, row, prompt_len: int
                        ) -> float:
    """Mean per-token logprob of row[prompt_len:] under rt's exact
    scorer — THE echo+logprobs quantity: /v1/completions with
    echo+logprobs reports exactly score_logprobs values."""
    lp = rt.score_logprobs(list(row))
    gen = [float(lp[i - 1, row[i]]) for i in
           range(prompt_len, len(row))]
    return sum(gen) / max(len(gen), 1)


# -- page round-trip --------------------------------------------------------
def test_constant_page_roundtrip_bit_exact():
    """A page of constant K/V values survives quantization exactly:
    absmax symmetric int8 maps c -> +/-127 -> c."""
    x = jnp.full((5, 2, 32), -3.25, jnp.float32)
    q, scale = paged_ops.quantize_kv_rows(x)
    assert q.dtype == jnp.int8
    np.testing.assert_array_equal(np.asarray(q), -127)
    back = paged_ops.dequantize_kv(q, scale)
    np.testing.assert_array_equal(np.asarray(back), -3.25)


def test_write_kv_quant_roundtrip_bounded():
    """write -> gather -> dequant reproduces the written rows within
    scale/2 per element, with scales landing at the written page
    slots of the parallel scale array."""
    rng = np.random.default_rng(0)
    heads, pages, page, hd, batch = 2, 6, 8, 16, 3
    kp = jnp.zeros((heads, pages, page, hd), jnp.int8)
    vp = jnp.zeros_like(kp)
    ks = jnp.zeros((pages, page), jnp.float32)
    vs = jnp.zeros_like(ks)
    k_new = jnp.asarray(rng.normal(size=(batch, heads, hd)),
                        jnp.float32)
    v_new = jnp.asarray(rng.normal(size=(batch, heads, hd)),
                        jnp.float32)
    positions = jnp.asarray([0, 9, 17], jnp.int32)
    table = jnp.asarray([[1, 2, 3], [2, 3, 4], [3, 4, 5]], jnp.int32)
    kp, vp, ks, vs = paged_ops.write_kv_quant(
        kp, vp, ks, vs, k_new, v_new, positions, table)
    ks_np = np.asarray(ks)
    # Rows wrote (physical page, slot) = (1,0), (3,1), (5,1).
    for b, (phys, slot) in enumerate([(1, 0), (3, 1), (5, 1)]):
        scale = ks_np[phys, slot]
        assert scale > 0
        got = np.asarray(kp)[:, phys, slot, :].astype(np.float32) * \
            scale
        want = np.asarray(k_new)[b]
        assert np.abs(got - want).max() <= scale / 2 + 1e-7
        assert scale == pytest.approx(
            np.abs(want).max() / 127.0, rel=1e-6)


def test_chunk_write_equals_tokenwise_write():
    """write_kv_chunk_quant == repeated write_kv_quant: per-token
    scales make chunked prefill and single-token decode write the
    SAME quantized bytes (what makes cache-hit continuations
    bit-identical to fresh computation)."""
    rng = np.random.default_rng(1)
    heads, pages, page, hd, S = 2, 5, 4, 8, 6
    k_new = jnp.asarray(rng.normal(size=(1, S, heads, hd)),
                        jnp.float32)
    v_new = jnp.asarray(rng.normal(size=(1, S, heads, hd)),
                        jnp.float32)
    table = jnp.asarray([[1, 2, 3]], jnp.int32)
    positions = jnp.arange(S, dtype=jnp.int32)[None, :]

    def fresh():
        return (jnp.zeros((heads, pages, page, hd), jnp.int8),
                jnp.zeros((heads, pages, page, hd), jnp.int8),
                jnp.zeros((pages, page), jnp.float32),
                jnp.zeros((pages, page), jnp.float32))

    chunked = paged_ops.write_kv_chunk_quant(
        *fresh(), k_new, v_new, positions, table)
    kp, vp, ks, vs = fresh()
    for s in range(S):
        kp, vp, ks, vs = paged_ops.write_kv_quant(
            kp, vp, ks, vs, k_new[:, s], v_new[:, s],
            positions[:, s], table)
    for a, b in zip(chunked, (kp, vp, ks, vs)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# -- weight quantization ----------------------------------------------------
def test_weight_quantize_targets_and_bounds(base):
    """Only the projection kernels quantize (embeddings/norms/head
    untouched); per-output-channel dequant error is bounded by
    scale/2; a constant column round-trips exactly."""
    _, params = base
    q = quant_lib.quantize_params(params)
    attn = q['layer_0']['attn']
    for t in ('wq', 'wk', 'wv', 'wo'):
        assert attn[t]['kernel_q'].dtype == np.int8
        assert 'kernel' not in attn[t]
    for t in ('w_gate', 'w_up', 'w_down'):
        assert q['layer_0']['mlp'][t]['kernel_q'].dtype == np.int8
    assert q['tok_embed'].dtype == np.float32       # untouched
    assert q['lm_head'].dtype == np.float32
    assert 'kernel' not in q['final_norm']          # norm unchanged
    w = np.asarray(params['layer_0']['attn']['wq']['kernel'],
                   np.float32)
    scale = np.asarray(attn['wq']['kernel_scale'])
    back = attn['wq']['kernel_q'].astype(np.float32) * scale
    assert np.abs(back - w).max() <= scale.max() / 2 + 1e-7
    # Constant column: exact.
    w2 = np.full((4, 3), 0.5, np.float32)
    q2 = quant_lib.quantize_params({'wq': {'kernel': w2}})
    back2 = q2['wq']['kernel_q'].astype(np.float32) * \
        q2['wq']['kernel_scale']
    np.testing.assert_array_equal(back2, w2)


def test_quantized_model_wrapper_delegates(base):
    model, params = base
    qm = quant_lib.QuantizedModel(model)
    assert qm.config is model.config
    assert lora_lib.supports(qm)
    qparams = jax.tree.map(jnp.asarray,
                           quant_lib.quantize_params(params))
    toks = jnp.asarray([[5, 9, 2, 17]], jnp.int32)
    out_q = qm.apply({'params': qparams}, toks)
    out_f = model.apply({'params': params}, toks)
    assert out_q.shape == out_f.shape
    # Quantization noise is small but nonzero on random weights.
    assert not np.array_equal(np.asarray(out_q), np.asarray(out_f))
    np.testing.assert_allclose(np.asarray(out_q), np.asarray(out_f),
                               atol=0.2)


# -- logprob-tolerance harness (the echo+logprobs contract) -----------------
def test_int8_kv_greedy_within_logprob_tolerance(base, base_int8):
    """Greedy continuations from the int8-KV engine score within
    LOGPROB_TOL of the bf16 engine's under the exact scorer (the
    quantity /v1/completions echo+logprobs reports)."""
    model, params = base
    model_q, _ = base_int8
    e_ref = ContinuousBatchingEngine(model, params, num_slots=2,
                                     max_total_len=48)
    e_q = ContinuousBatchingEngine(model_q, params, num_slots=2,
                                   max_total_len=48)
    assert e_q.kv_dtype == 'int8' and e_q.paged
    rt = _runtime(model, params, e_ref)
    try:
        for prompt in ([5, 9, 2, 17], [30, 31, 32, 33, 34],
                       list(range(40, 60))):
            a = e_ref.submit(prompt, max_new_tokens=10).result(
                timeout=180)
            b = e_q.submit(prompt, max_new_tokens=10).result(
                timeout=180)
            lp_ref = _score_continuation(rt, a, len(prompt))
            lp_q = _score_continuation(rt, b, len(prompt))
            assert lp_q >= lp_ref - LOGPROB_TOL, (
                f'int8 KV continuation scores {lp_q:.4f} vs bf16 '
                f'{lp_ref:.4f} (tol {LOGPROB_TOL})')
    finally:
        e_ref.stop()
        e_q.stop()
        rt.stop()


def test_int8_kv_completions_echo_logprobs_endpoint(base_int8, base):
    """The actual /v1/completions scoring contract runs against an
    int8-KV runtime: echo+logprobs+max_tokens=0 returns finite
    per-token logprobs that match the bf16 runtime's exactly (the
    scorer is the cache-free full forward — quantized KV changes
    GENERATION, never scoring)."""
    from skypilot_tpu.inference.openai_compat import (
        CompletionRequest, run_completion)
    model, params = base
    model_q, _ = base_int8
    rt_q = _runtime(model_q, params, None)
    rt_f = _runtime(model, params, None)
    req = CompletionRequest(prompts=['5 9 2 17'], max_new=0,
                            temperature=0.0, top_p=1.0,
                            stop_strings=None, n=1, stream=False,
                            logprobs=0, echo=True)
    try:
        out_q = run_completion(rt_q, req)
        out_f = run_completion(rt_f, req)
        lp_q = out_q['choices'][0]['logprobs']['token_logprobs']
        lp_f = out_f['choices'][0]['logprobs']['token_logprobs']
        assert lp_q[0] is None and len(lp_q) == 4
        assert lp_q[1:] == pytest.approx(lp_f[1:], abs=1e-6)
    finally:
        rt_q.stop()
        rt_f.stop()


def test_int8_weights_within_logprob_tolerance(base):
    """weight_dtype=int8 greedy continuations score within tolerance
    of the f32 model's."""
    model, params = base
    qm = quant_lib.QuantizedModel(model)
    qparams = jax.tree.map(jnp.asarray,
                           quant_lib.quantize_params(params))
    e_ref = ContinuousBatchingEngine(model, params, num_slots=2,
                                     max_total_len=48)
    e_q = ContinuousBatchingEngine(qm, qparams, num_slots=2,
                                   max_total_len=48)
    rt = _runtime(model, params, e_ref)
    try:
        for prompt in ([5, 9, 2, 17], [7] * 12):
            a = e_ref.submit(prompt, max_new_tokens=10).result(
                timeout=180)
            b = e_q.submit(prompt, max_new_tokens=10).result(
                timeout=180)
            lp_ref = _score_continuation(rt, a, len(prompt))
            lp_q = _score_continuation(rt, b, len(prompt))
            assert lp_q >= lp_ref - LOGPROB_TOL
    finally:
        e_ref.stop()
        e_q.stop()
        rt.stop()


# -- scheduler invariants under int8 KV -------------------------------------
def test_pipelined_equals_unpipelined_int8(base_int8):
    """Greedy bit-identity of the pipelined decode loop survives
    quantized storage (both loops read the same quantized pages)."""
    model_q, params = base_int8
    outs = []
    for pipeline in (True, False):
        eng = ContinuousBatchingEngine(model_q, params, num_slots=2,
                                       max_total_len=48,
                                       pipeline_decode=pipeline)
        try:
            outs.append([
                eng.submit(p, max_new_tokens=10).result(timeout=180)
                for p in ([5, 9, 2, 17], [30, 31, 32])])
        finally:
            eng.stop()
    assert outs[0] == outs[1]


def test_chunked_decode_bit_identical_int8(base_int8):
    """decode_chunk=4 == step-by-step under int8 KV (deterministic
    elementwise quantization keeps the scan/loop equivalence)."""
    model_q, params = base_int8
    outs = []
    for chunk in (1, 4):
        eng = ContinuousBatchingEngine(model_q, params, num_slots=2,
                                       max_total_len=40,
                                       decode_chunk=chunk,
                                       pipeline_decode=False)
        try:
            outs.append(eng.submit([5, 9, 2, 17],
                                   max_new_tokens=12).result(
                timeout=180))
        finally:
            eng.stop()
    assert outs[0] == outs[1]


def test_speculative_decode_int8(base_int8):
    """Verify chunks ride quantized pages: speculative greedy output
    == plain greedy output (acceptance only commits model-confirmed
    tokens, and both paths read the same quantized history)."""
    model_q, params = base_int8
    prompt = [7, 8, 7, 8, 7, 8]
    outs = []
    for k in (0, 3):
        eng = ContinuousBatchingEngine(model_q, params, num_slots=2,
                                       max_total_len=40,
                                       speculative_k=k)
        try:
            outs.append(eng.submit(prompt, max_new_tokens=10).result(
                timeout=180))
        finally:
            eng.stop()
    assert outs[0] == outs[1]


def test_chunked_prefill_preempt_recover_int8():
    """Chunked prefill + page-pressure preemption + re-admission all
    run under kv_dtype=int8, deterministically: two identical runs
    produce identical outputs and the pressured run preempts."""
    model_q, _ = _build(kv_dtype='int8')
    # A pool just big enough for one deep sequence: two concurrent
    # requests must preempt under page pressure.
    cfg = LlamaConfig.tiny(dtype=jnp.float32, kv_page_size=8,
                           kv_total_pages=8, kv_dtype='int8')
    model_small = Llama(cfg)
    params = nn.meta.unbox(model_small.init(
        jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))['params'])

    def run():
        eng = ContinuousBatchingEngine(model_small, params,
                                       num_slots=2, max_total_len=40,
                                       prefill_chunk=16,
                                       prefix_caching=False)
        try:
            futs = [eng.submit(list(range(2 + i, 22 + i)),
                               max_new_tokens=16) for i in range(2)]
            rows = [f.result(timeout=300) for f in futs]
            return rows, eng.preemptions
        finally:
            eng.stop()
    rows1, preempts1 = run()
    rows2, _ = run()
    assert preempts1 >= 1
    assert rows1 == rows2
    assert all(len(r) == 36 for r in rows1)


def test_prefix_cache_hit_returns_quantized_pages(base_int8):
    """A cache-hit continuation reads SHARED quantized pages + scales
    and is bit-identical to a fresh engine computing the same pages:
    the prefix cache shares int8 storage correctly (one copy, same
    refcounting, scales travel with the page)."""
    model_q, params = base_int8
    prefix = list(range(2, 34))          # 4 full pages of 8
    suffix = [40, 41, 42]
    e1 = ContinuousBatchingEngine(model_q, params, num_slots=2,
                                  max_total_len=48, prefill_chunk=16)
    e2 = ContinuousBatchingEngine(model_q, params, num_slots=2,
                                  max_total_len=48, prefill_chunk=16)
    try:
        e1.submit(prefix, max_new_tokens=4).result(timeout=180)
        hits_before = e1.prefix_cache.hits
        out_hit = e1.submit(prefix + suffix,
                            max_new_tokens=8).result(timeout=180)
        assert e1.prefix_cache.hits > hits_before
        out_fresh = e2.submit(prefix + suffix,
                              max_new_tokens=8).result(timeout=180)
        assert out_hit == out_fresh
    finally:
        e1.stop()
        e2.stop()


# -- LoRA composition -------------------------------------------------------
def test_int8_weights_with_lora_matches_merged_oracle(base):
    """Batched LoRA on a quantized base: the delta applies in f32 on
    top of the DEQUANTIZED projections, so the continuation scores
    within tolerance of the merged-weights f32 oracle (and the LoRA
    actually bites: adapter output != quantized-base output)."""
    model, params = base
    # A deliberately LOUD adapter (big alpha): the delta must flip
    # greedy tokens, or the base_out inequality below is vacuous.
    spec = lora_lib.LoraSpec(rank=4, alpha=64.0)
    lp = lora_lib.random_adapter_params(0, model.config, spec)
    tmp = tempfile.mkdtemp(prefix='quant_lora_')
    lora_lib.save_adapter(os.path.join(tmp, 'ad0'), lp, spec,
                          base_model='llama-tiny')
    qm = quant_lib.QuantizedModel(model)
    qparams = jax.tree.map(jnp.asarray,
                           quant_lib.quantize_params(params))
    reg = AdapterRegistry(tmp, qm, max_adapters=2)
    merged = lora_lib.merge_lora(params, lp, spec)
    e_oracle = ContinuousBatchingEngine(model, merged, num_slots=2,
                                        max_total_len=48)
    e_q = ContinuousBatchingEngine(qm, qparams, num_slots=2,
                                   max_total_len=48,
                                   adapter_store=reg)
    rt = _runtime(model, merged, e_oracle)
    prompt = [5, 9, 2, 17, 30]
    try:
        a = e_oracle.submit(prompt, max_new_tokens=10).result(
            timeout=180)
        b = e_q.submit(prompt, max_new_tokens=10,
                       adapter='ad0').result(timeout=180)
        base_out = e_q.submit(prompt, max_new_tokens=10).result(
            timeout=180)
        assert b != base_out            # the adapter changed decode
        lp_oracle = _score_continuation(rt, a, len(prompt))
        lp_q = _score_continuation(rt, b, len(prompt))
        assert lp_q >= lp_oracle - LOGPROB_TOL
    finally:
        e_oracle.stop()
        e_q.stop()
        rt.stop()


# -- tensor-parallel composition (CPU host devices) -------------------------
def test_int8_kv_tensor2_identical(base_int8):
    """Acceptance: int8 KV under --tensor 2 == the single-device int8
    run, token for token."""
    from skypilot_tpu.parallel import mesh as mesh_lib
    from skypilot_tpu.parallel.serving import shard_params_for_serving
    model_q, params = base_int8
    mesh = mesh_lib.make_mesh(mesh_lib.MeshConfig(tensor=2),
                              devices=jax.devices()[:2])
    tp = shard_params_for_serving(model_q, params, mesh)
    e_sd = ContinuousBatchingEngine(model_q, params, num_slots=2,
                                    max_total_len=48)
    e_tp = ContinuousBatchingEngine(model_q, tp, num_slots=2,
                                    max_total_len=48)
    try:
        for p in ([5, 9, 2, 17], [30, 31, 32, 33, 34]):
            a = e_sd.submit(p, max_new_tokens=8).result(timeout=180)
            b = e_tp.submit(p, max_new_tokens=8).result(timeout=180)
            assert a == b
    finally:
        e_sd.stop()
        e_tp.stop()


def test_int8_weights_tensor2_scales_shard_and_match(base):
    """Quantized kernels place with the base kernel's sharding, the
    per-channel scales shard over the output-channel mesh axis, and
    serving is bit-identical to single-device int8."""
    from skypilot_tpu.parallel import mesh as mesh_lib
    model, params = base
    qm = quant_lib.QuantizedModel(model)
    qparams = quant_lib.quantize_params(params)
    mesh = mesh_lib.make_mesh(mesh_lib.MeshConfig(tensor=2),
                              devices=jax.devices()[:2])
    tp = quant_lib.shard_quantized_for_serving(qm, qparams, mesh)
    wq = tp['layer_0']['attn']['wq']
    assert 'tensor' in str(wq['kernel_q'].sharding.spec)
    assert str(wq['kernel_scale'].sharding.spec) == \
        "PartitionSpec('tensor',)"
    sd = jax.tree.map(jnp.asarray, qparams)
    e_sd = ContinuousBatchingEngine(qm, sd, num_slots=2,
                                    max_total_len=48)
    e_tp = ContinuousBatchingEngine(qm, tp, num_slots=2,
                                    max_total_len=48)
    try:
        for p in ([5, 9, 2, 17],):
            a = e_sd.submit(p, max_new_tokens=8).result(timeout=180)
            b = e_tp.submit(p, max_new_tokens=8).result(timeout=180)
            assert a == b
    finally:
        e_sd.stop()
        e_tp.stop()


def test_adapter_store_replicated_under_tensor2(base):
    """Satellite: the stacked adapter store places REPLICATED over
    the mesh (not left to default placement), and a LoRA request
    under --tensor 2 matches the single-device output exactly."""
    from skypilot_tpu.parallel import mesh as mesh_lib
    from skypilot_tpu.parallel.serving import shard_params_for_serving
    model, params = base
    spec = lora_lib.LoraSpec(rank=4, alpha=8.0)
    lp = lora_lib.random_adapter_params(1, model.config, spec)
    tmp = tempfile.mkdtemp(prefix='quant_tp_lora_')
    lora_lib.save_adapter(os.path.join(tmp, 'ad0'), lp, spec,
                          base_model='llama-tiny')
    mesh = mesh_lib.make_mesh(mesh_lib.MeshConfig(tensor=2),
                              devices=jax.devices()[:2])
    reg_sd = AdapterRegistry(tmp, model, max_adapters=2)
    reg_tp = AdapterRegistry(tmp, model, max_adapters=2, mesh=mesh)
    tp = shard_params_for_serving(model, params, mesh)
    e_sd = ContinuousBatchingEngine(model, params, num_slots=2,
                                    max_total_len=48,
                                    adapter_store=reg_sd)
    e_tp = ContinuousBatchingEngine(model, tp, num_slots=2,
                                    max_total_len=48,
                                    adapter_store=reg_tp)
    try:
        prompt = [5, 9, 2, 17]
        a = e_sd.submit(prompt, max_new_tokens=8,
                        adapter='ad0').result(timeout=180)
        b = e_tp.submit(prompt, max_new_tokens=8,
                        adapter='ad0').result(timeout=180)
        assert a == b
        # The store is explicitly replicated over BOTH mesh devices.
        stack = reg_tp.model_lora()['layers']
        leaf = stack['layer_0']['wq']['a']
        assert len(leaf.sharding.device_set) == 2
    finally:
        e_sd.stop()
        e_tp.stop()


# -- engine validation + observability --------------------------------------
def test_int8_requires_paged():
    cfg = LlamaConfig.tiny(dtype=jnp.float32, kv_dtype='int8',
                           kv_total_pages=0)
    model = Llama(cfg)
    params = nn.meta.unbox(model.init(
        jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))['params'])
    with pytest.raises(ValueError, match='paged'):
        ContinuousBatchingEngine(model, params, num_slots=2,
                                 max_total_len=48)


def test_kv_pool_bytes_math_and_gauges(base, base_int8):
    """int8 halves+ the pool bytes at equal page count; the same
    byte budget buys >= 1.8x the pages (the bench acceptance ratio
    is deterministic geometry, not load-dependent); gauges render."""
    model, params = base
    model_q, _ = base_int8
    cfg_bf = model.config
    cfg_q = model_q.config
    bf16_cfg = LlamaConfig.tiny()            # bf16 storage dtype
    per_bf = quant_lib.kv_page_bytes(bf16_cfg, 'bf16')
    per_q = quant_lib.kv_page_bytes(bf16_cfg, 'int8')
    assert per_bf / per_q >= 1.8
    budget = 1 << 20
    assert quant_lib.pool_pages_for_bytes(bf16_cfg, 'int8', budget) \
        >= 1.8 * quant_lib.pool_pages_for_bytes(bf16_cfg, 'bf16',
                                                budget)
    e_bf = ContinuousBatchingEngine(model, params, num_slots=2,
                                    max_total_len=48)
    e_q = ContinuousBatchingEngine(model_q, params, num_slots=2,
                                   max_total_len=48)
    try:
        assert 0 < e_q.kv_cache_bytes() < e_bf.kv_cache_bytes()
        e_q.update_metric_gauges()
        from skypilot_tpu.observability import REGISTRY
        text = REGISTRY.render()
        assert 'skypilot_serving_kv_pool_bytes' in text
        assert cfg_q.kv_dtype == 'int8' and cfg_bf.kv_dtype == 'bf16'
    finally:
        e_bf.stop()
        e_q.stop()


def test_stats_reports_storage(base_int8):
    """/stats carries the storage section + page-pool kv_dtype and
    pool bytes (what serve_bench scrapes into the A/B record)."""
    model_q, params = base_int8
    eng = ContinuousBatchingEngine(model_q, params, num_slots=2,
                                   max_total_len=48)
    rt = _runtime(model_q, params, eng)
    rt.kv_dtype = 'int8'
    try:
        assert rt.weight_bytes > 0
        assert eng.kv_dtype == 'int8'
        assert eng.kv_cache_bytes() > 0
    finally:
        eng.stop()
        rt.stop()
