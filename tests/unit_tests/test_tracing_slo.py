"""Tier-1 coverage for the serving-plane observability layer (PR 17):

  - distributed tracing (observability/tracing.py): header
    round-trip, deterministic head sampling under a fixed seed, span
    parenting, Chrome-trace shape, and the real propagation chain —
    an in-process disaggregated stub fleet where one request's
    trace_id crosses LB -> prefill -> decode over `x-skypilot-trace`
    and merges into one timeline with per-role process rows;
  - the engine flight recorder (observability/flight.py): ring
    wraparound with absolute sequence numbers, snapshot files, and
    the injected decode-poison -> 3-strike -> reset escalation
    appearing in the dump with victim slots;
  - SLO accounting (observability/slo.py): burn-rate window edges,
    per-dimension denominators, clock restarts, and the bench-side
    `evaluate` contract (an unmeasured targeted dimension fails).

Everything runs on CPU with stubs or the tiny llama engine.
"""
import glob
import json
import os
import threading
import urllib.error
import urllib.request

import pytest

from skypilot_tpu.observability import flight as flight_lib
from skypilot_tpu.observability import slo as slo_lib
from skypilot_tpu.observability import tracing


@pytest.fixture()
def clean_tracing():
    """Tracing state is module-global (it models a process); reset
    around every test so sampling/config never leaks."""
    tracing.reset()
    yield
    tracing.reset()


# ---------------------------------------------------------------------------
# tracing core: header, sampling, spans
# ---------------------------------------------------------------------------
def test_header_roundtrip_and_malformed(clean_tracing):
    ctx = tracing.Ctx('00ff00ff00ff00ff', 'abcd1234')
    parsed = tracing.parse_header(tracing.format_header(ctx))
    assert (parsed.trace_id, parsed.span_id) == \
        (ctx.trace_id, ctx.span_id)
    for bad in (None, '', 'garbage', 'a:b', 'a:b:c:d',
                'tid:sid:0',   # unsampled flag -> no tracing
                ':sid:1', 'tid:sid:x'):
        assert tracing.parse_header(bad) is None, bad


def test_head_sampling_deterministic_under_fixed_seed(clean_tracing):
    def draw(n=64):
        tracing.configure(sample=0.5, seed=1234, process='t')
        out = []
        for _ in range(n):
            ctx = tracing.new_ctx()
            out.append(ctx.trace_id if ctx is not None else None)
        return out

    a, b = draw(), draw()
    assert a == b                       # decisions AND ids reproduce
    sampled = [x for x in a if x is not None]
    assert sampled and len(sampled) < len(a)  # neither 0% nor 100%
    tracing.configure(sample=0.5, seed=4321)  # different seed
    c = [getattr(tracing.new_ctx(), 'trace_id', None)
         for _ in range(64)]
    assert c != a


def test_sampling_off_is_noop_and_free(clean_tracing):
    tracing.configure(sample=0.0)
    assert tracing.new_ctx() is None
    assert not tracing.enabled()
    sp = tracing.span('x', None)
    assert sp is tracing.NOOP and sp.ctx is None
    sp.add(k=1)
    sp.end()
    tracing.record_span('x', None, 0.5)
    assert tracing.trace_ids() == []


def test_span_parenting_shape_and_record_span(clean_tracing):
    tracing.configure(sample=1.0, seed=0, process='proc0')
    ctx = tracing.new_ctx()
    with tracing.span('root', ctx, path='/x') as root:
        assert root.ctx.trace_id == ctx.trace_id
        assert root.ctx.span_id != ctx.span_id
        with tracing.span('child', root.ctx, process='proc1'):
            pass
        tracing.record_span('measured', root.ctx, dur_s=0.25,
                            slot=3)
    body = tracing.get_trace(ctx.trace_id)
    by_name = {e['name']: e for e in body['traceEvents']}
    assert set(by_name) == {'root', 'child', 'measured'}
    for ev in by_name.values():   # timeline.py-compatible shape
        assert ev['ph'] == 'X' and ev['cat'] == 'skypilot_tpu'
        assert ev['dur'] >= 0 and ev['ts'] > 0
        assert ev['args']['trace_id'] == ctx.trace_id
    assert by_name['child']['args']['parent_id'] == \
        by_name['root']['args']['span_id']
    assert by_name['measured']['args']['parent_id'] == \
        by_name['root']['args']['span_id']
    # per-span process override beats the configured default
    assert by_name['root']['pid'] == 'proc0'
    assert by_name['child']['pid'] == 'proc1'
    # record_span backdates: ~0.25s duration, ends ~now
    assert by_name['measured']['dur'] == pytest.approx(0.25e6,
                                                       rel=0.05)
    assert by_name['measured']['args']['slot'] == 3


def test_span_exit_records_error_name(clean_tracing):
    tracing.configure(sample=1.0, seed=0)
    ctx = tracing.new_ctx()
    with pytest.raises(ValueError):
        with tracing.span('boom', ctx):
            raise ValueError('nope')
    ev = tracing.get_trace(ctx.trace_id)['traceEvents'][0]
    assert ev['args']['error'] == 'ValueError'


def test_merge_traces_dedups_and_sorts(clean_tracing):
    def ev(sid, ts, name='n'):
        return {'name': name, 'ts': ts, 'ph': 'X',
                'args': {'span_id': sid}}

    a = {'traceEvents': [ev('s1', 30.0), ev('s2', 10.0)]}
    b = {'traceEvents': [ev('s1', 30.0), ev('s3', 20.0)]}
    merged = tracing.merge_traces([a, b, None])
    assert [e['args']['span_id'] for e in merged['traceEvents']] == \
        ['s2', 's3', 's1']


def test_trace_store_is_lru_bounded(clean_tracing):
    tracing.configure(sample=1.0, seed=0)
    first = tracing.new_ctx()
    tracing.span('s', first).end()
    for _ in range(tracing.MAX_TRACES):
        tracing.span('s', tracing.new_ctx()).end()
    ids = tracing.trace_ids()
    assert len(ids) == tracing.MAX_TRACES
    assert first.trace_id not in ids  # oldest evicted


# ---------------------------------------------------------------------------
# one request, one trace_id, three process rows (LB/prefill/decode)
# ---------------------------------------------------------------------------
def _disagg_stub_fleet(trace_sample=1.0, slo_targets=None,
                       threshold=64):
    from skypilot_tpu.serve import autoscalers
    from skypilot_tpu.serve import load_balancing_policies as lbp
    from skypilot_tpu.serve import service_spec as spec_lib
    from skypilot_tpu.serve.replica_plane import (FleetController,
                                                  PrefillPool,
                                                  ReplicaManager,
                                                  make_lb_server)
    from skypilot_tpu.serve.replica_plane.stub import \
        in_process_stub_factory
    factory = in_process_stub_factory(cache_pages=512,
                                      token_sleep_s=0.0)
    policy = lbp.PrefixAffinityPolicy()
    pool = PrefillPool()
    manager = ReplicaManager(factory, drain_grace_s=5.0)
    controller = FleetController(
        manager, policy,
        autoscalers.EngineMetricsAutoscaler(
            spec_lib.SkyServiceSpec(min_replicas=2, max_replicas=2)),
        interval_s=0.2,
        prefill_autoscaler=autoscalers.EngineMetricsAutoscaler(
            spec_lib.SkyServiceSpec(min_replicas=1, max_replicas=1)),
        prefill_pool=pool)
    lb = make_lb_server(policy, 0, policy_name='prefix_affinity',
                        manager=manager, disagg_threshold=threshold,
                        prefill_pool=pool, trace_sample=trace_sample,
                        trace_seed=7, slo_targets=slo_targets)
    threading.Thread(target=lb.serve_forever, daemon=True).start()
    for _ in range(2):
        manager.spawn(role='decode')
    manager.spawn(role='prefill')
    assert controller.wait_ready(3, timeout_s=60)
    controller.tick()   # push roles + decode peers
    url = f'http://127.0.0.1:{lb.server_address[1]}'
    return url, controller, manager, lb


def _post(url, path, body, timeout=60):
    req = urllib.request.Request(
        url + path, data=json.dumps(body).encode(),
        headers={'Content-Type': 'application/json'})
    return urllib.request.urlopen(req, timeout=timeout)


def test_disagg_fleet_one_trace_id_across_three_processes(
        clean_tracing):
    """The acceptance path: a long-prompt request through the
    disaggregated stub fleet produces ONE trace whose spans carry
    lb, prefill, and decode process rows — propagated over the
    `x-skypilot-trace` header at both hops (LB->prefill and the
    prefill stub's handoff POST to its decode peer), fetched back
    via each node's /debug/trace, and merged `stpu trace`-style."""
    url, controller, manager, lb = _disagg_stub_fleet()
    try:
        long_prompt = list(range(2, 202))   # >= threshold -> disagg
        assert _post(url, '/generate',
                     {'tokens': [long_prompt],
                      'max_new_tokens': 4}).status == 200
        ids = tracing.trace_ids()
        assert len(ids) == 1    # sample=1.0: exactly this request
        tid = ids[0]

        # Per-node /debug/trace (the in-process fleet shares one
        # store; the endpoint surface is what `stpu trace` scrapes).
        bodies = []
        endpoints = [url] + [f'http://{v.endpoint}'
                             for v in manager.views()]
        for base in endpoints:
            bodies.append(json.loads(urllib.request.urlopen(
                f'{base}/debug/trace/{tid}', timeout=10).read()))
        merged = tracing.merge_traces(bodies)
        events = merged['traceEvents']
        assert events and all(
            e['args']['trace_id'] == tid for e in events)
        # dedup worked: merging N identical bodies adds nothing
        assert len(events) == len(bodies[0]['traceEvents'])
        names = {e['name'] for e in events}
        assert {'lb.request', 'lb.route', 'replica.request',
                'kv.post'} <= names
        procs = {e['pid'] for e in events}
        assert {'lb', 'prefill', 'decode'} <= procs
        # the merge is a timeline: sorted by wall-clock ts
        ts = [e['ts'] for e in events]
        assert ts == sorted(ts)
        # child spans point back into the same trace
        roots = [e for e in events if e['name'] == 'lb.request']
        assert len(roots) == 1
        # unknown id -> 404 with the known-ids hint
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(f'{url}/debug/trace/deadbeef',
                                   timeout=10)
        assert err.value.code == 404

        # `stpu trace` does the same fetch+merge end to end.
        import tempfile

        from click.testing import CliRunner

        from skypilot_tpu.client.cli import cli as stpu_cli
        with tempfile.TemporaryDirectory() as td:
            out = os.path.join(td, 'merged.json')
            argv = ['trace', tid, '-o', out]
            for base in endpoints:
                argv += ['-e', base]
            res = CliRunner().invoke(stpu_cli, argv)
            assert res.exit_code == 0, res.output
            saved = json.loads(open(out, encoding='utf-8').read())
            assert len(saved['traceEvents']) == len(events)
    finally:
        controller.shutdown()
        lb.shutdown()


def test_fleet_unsampled_requests_trace_nothing(clean_tracing):
    url, controller, manager, lb = _disagg_stub_fleet(
        trace_sample=0.0,
        slo_targets={'p99_ttft_ms': 5000.0, 'error_rate': 0.1})
    try:
        assert _post(url, '/generate',
                     {'tokens': [list(range(2, 202))],
                      'max_new_tokens': 2}).status == 200
        assert tracing.trace_ids() == []
        # ... but the SLO section still accounts the request
        status = json.loads(urllib.request.urlopen(
            url + '/fleet/status', timeout=10).read())
        slo = status['slo']
        assert slo['targets'] == {'p99_ttft_ms': 5000.0,
                                  'error_rate': 0.1}
        windows = slo['windows']
        assert any(w['requests'] >= 1 for w in windows.values())
        assert slo['ok'] is True
    finally:
        controller.shutdown()
        lb.shutdown()


# ---------------------------------------------------------------------------
# flight recorder ring
# ---------------------------------------------------------------------------
def test_flight_ring_wraparound_keeps_absolute_seq():
    fr = flight_lib.FlightRecorder(capacity=8, name='t')
    for i in range(20):
        fr.record('tick', i=i)
    events = fr.events()
    assert len(events) == 8
    assert [e['seq'] for e in events] == list(range(12, 20))
    assert [e['i'] for e in events] == list(range(12, 20))
    dump = fr.dump()
    assert dump['recorded'] == 20
    assert dump['dropped'] == 12
    assert dump['capacity'] == 8
    assert dump['name'] == 't'


def test_flight_under_capacity_drops_nothing():
    fr = flight_lib.FlightRecorder(capacity=8)
    fr.record('a')
    fr.record('b', slot=1)
    dump = fr.dump()
    assert dump['dropped'] == 0
    assert [e['kind'] for e in dump['events']] == ['a', 'b']
    assert dump['events'][1]['slot'] == 1
    with pytest.raises(ValueError):
        flight_lib.FlightRecorder(capacity=0)


def test_flight_snapshot_writes_json(tmp_path, monkeypatch):
    monkeypatch.setenv('STPU_FLIGHT_DIR', str(tmp_path))
    fr = flight_lib.FlightRecorder(capacity=4, name='snap')
    for i in range(6):
        fr.record('ev', i=i)
    path = fr.snapshot('reset')
    assert path and os.path.exists(path)
    body = json.loads(open(path, encoding='utf-8').read())
    assert body['reason'] == 'reset'
    assert body['dropped'] == 2
    assert [e['seq'] for e in body['events']] == [2, 3, 4, 5]


# ---------------------------------------------------------------------------
# engine: injected decode poison -> 3-strike escalation in the dump
# ---------------------------------------------------------------------------
@pytest.fixture(scope='module')
def tiny_model():
    import jax
    jax.config.update('jax_platforms', 'cpu')
    import flax.linen as nn
    import jax.numpy as jnp

    from skypilot_tpu.models.llama import Llama, LlamaConfig
    model = Llama(LlamaConfig.tiny(kv_page_size=8, kv_total_pages=40))
    params = nn.meta.unbox(model.init(
        jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))['params'])
    return model, params


def test_decode_poison_three_strikes_escalate_in_flight_dump(
        tiny_model, tmp_path, monkeypatch):
    """ISSUE acceptance: a fault-plan decode poison shows up in
    /debug/flight's dump — per-strike soft_error events naming the
    victim slot, then the strike-3 reset — and the reset snapshots
    the ring to a postmortem file."""
    from skypilot_tpu.models.batching import ContinuousBatchingEngine
    from skypilot_tpu.robustness import faults
    monkeypatch.setenv('STPU_FLIGHT_DIR', str(tmp_path))
    model, params = tiny_model
    eng = ContinuousBatchingEngine(model, params, num_slots=2,
                                   max_total_len=64)
    try:
        # A clean request first: the recorder is always on, so the
        # ordinary admit/chunk_dispatch/round_commit lifecycle lands.
        eng.submit([5, 6, 7], max_new_tokens=4).result(timeout=120)
        kinds = [e['kind'] for e in eng.flight.events()]
        assert 'admit' in kinds
        assert 'round_commit' in kinds

        faults.install_plan({'rules': [
            {'point': 'engine.decode_step', 'action': 'raise',
             'exc': 'RuntimeError', 'message': 'poison step',
             'times': 3}]})
        doomed = eng.submit([1, 2, 3, 4], max_new_tokens=8)
        with pytest.raises(Exception):
            doomed.result(timeout=120)
        faults.clear()

        events = eng.flight.events()
        softs = [e for e in events if e['kind'] == 'soft_error']
        assert [e['strikes'] for e in softs] == [1, 2, 3]
        victim_slots = set()
        for e in softs:
            assert e['error'] == 'RuntimeError'
            assert e['slots'], 'soft_error must name victim slots'
            victim_slots.update(e['slots'])
        resets = [e for e in events if e['kind'] == 'reset']
        assert len(resets) == 1
        assert resets[0]['strikes'] == 3
        assert set(resets[0]['slots']) == victim_slots
        assert eng.engine_restarts == 1

        # The reset snapshotted the ring to STPU_FLIGHT_DIR.
        files = glob.glob(str(tmp_path / 'stpu-flight-*reset*.json'))
        assert files, 'reset must write a flight snapshot file'
        body = json.loads(open(files[0], encoding='utf-8').read())
        assert body['reason'] == 'reset'
        assert any(e['kind'] == 'soft_error'
                   for e in body['events'])

        # Crash-only: the engine keeps serving after the reset.
        assert eng.healthy()
        out = eng.submit([5, 6, 7], max_new_tokens=4).result(
            timeout=120)
        assert out
    finally:
        faults.clear()
        eng.stop()


# ---------------------------------------------------------------------------
# SLO burn-rate math
# ---------------------------------------------------------------------------
def _tracker(targets, **kw):
    clock = {'t': 10_000.0}
    kw.setdefault('windows', (60.0, 600.0))
    kw.setdefault('publish', False)
    tr = slo_lib.SloTracker(targets, clock=lambda: clock['t'], **kw)
    return tr, clock


def test_parse_slo_spec_and_errors():
    assert slo_lib.parse_slo(' p99_ttft_ms=500, error_rate=0.01 ') \
        == {'p99_ttft_ms': 500.0, 'error_rate': 0.01}
    for bad in ('', 'p99_ttft_ms', 'nope=1', 'p99_ttft_ms=x',
                'p99_ttft_ms=-1', 'error_rate=2'):
        with pytest.raises(ValueError):
            slo_lib.parse_slo(bad)


def test_burn_rate_window_edges():
    """Bucket inclusion is `lo < idx <= hi`: an event exactly
    window seconds ago is OUT, one bucket later is IN."""
    tr, clock = _tracker({'error_rate': 0.01}, bucket_s=5.0)
    tr.record_request(error=True)            # t=10_000, idx=2000
    # 9 good requests in the same bucket -> 10% bad, burn 10x
    for _ in range(9):
        tr.record_request()
    assert tr.burn_rate('error_rate', 60.0) == pytest.approx(10.0)
    # Advance so the bad bucket sits exactly at the 60s edge:
    # hi = idx+12, lo = idx, lo < idx is False -> excluded.
    clock['t'] = 10_000.0 + 60.0
    assert tr.burn_rate('error_rate', 60.0) == 0.0
    # One bucket earlier it was still included.
    clock['t'] = 10_000.0 + 55.0
    assert tr.burn_rate('error_rate', 60.0) == pytest.approx(10.0)
    # The slow window still sees it either way.
    clock['t'] = 10_000.0 + 60.0
    assert tr.burn_rate('error_rate', 600.0) == pytest.approx(10.0)


def test_burn_rate_denominators_per_dimension():
    targets = {'shed_rate': 0.1, 'error_rate': 0.1,
               'p99_itl_ms': 50.0}
    tr, _ = _tracker(targets)
    # 8 completed (1 error) + 2 shed = 10 offered
    tr.record_request(error=True)
    for _ in range(7):
        tr.record_request()
    tr.record_request(shed=True)
    tr.record_request(shed=True)
    # 5 ITL gaps, 1 over target
    for gap in (10.0, 10.0, 10.0, 10.0, 80.0):
        tr.record_itl(gap)
    # shed: 2/10 offered / 0.1 budget = 2x
    assert tr.burn_rate('shed_rate', 60.0) == pytest.approx(2.0)
    # error: 1/8 completed / 0.1 = 1.25x (shed not in denominator)
    assert tr.burn_rate('error_rate', 60.0) == pytest.approx(1.25)
    # itl: 1/5 gaps / 0.01 p99 budget = 20x (gap count, not requests)
    assert tr.burn_rate('p99_itl_ms', 60.0) == pytest.approx(20.0)


def test_ttft_burns_against_p99_budget():
    tr, _ = _tracker({'p99_ttft_ms': 100.0})
    for _ in range(99):
        tr.record_request(ttft_ms=50.0)
    tr.record_request(ttft_ms=500.0)
    # 1/100 slow at a 1% budget: exactly on budget.
    assert tr.burn_rate('p99_ttft_ms', 60.0) == pytest.approx(1.0)
    snap = tr.snapshot()
    assert snap['ok'] is True          # burn > 1.0 flips it, not ==
    assert snap['budget_remaining']['p99_ttft_ms'] == \
        pytest.approx(0.0)


def test_clock_restart_and_empty_windows_are_safe():
    """A monotonic-clock restart (process restart reusing the
    tracker's math) or long idle gap must never produce negative
    burn or resurrect stale buckets."""
    tr, clock = _tracker({'error_rate': 0.01}, bucket_s=5.0)
    tr.record_request(error=True)
    # Clock jumps far forward: every bucket falls out of range and
    # its ring slot is lazily reused; totals stay untouched.
    clock['t'] = 10_000.0 + 7 * 24 * 3600.0
    assert tr.burn_rate('error_rate', 60.0) == 0.0
    assert tr.burn_rate('error_rate', 600.0) == 0.0
    tr.record_request(error=True)
    assert tr.burn_rate('error_rate', 60.0) == pytest.approx(100.0)
    # Clock jumps BACKWARD (restart at 0): writes land in fresh
    # buckets; nothing crashes, windows read consistently.
    clock['t'] = 3.0
    tr.record_request()
    assert tr.burn_rate('error_rate', 60.0) == 0.0
    snap = tr.snapshot()
    assert snap['bad_total']['error_rate'] == 2  # lifetime counter


def test_snapshot_shape_ok_flag_and_gauges():
    tr, _ = _tracker({'error_rate': 0.01})
    for _ in range(4):
        tr.record_request(error=True)
    snap = tr.snapshot()
    assert snap['ok'] is False      # 100% errors >> 1% budget
    assert set(snap['windows']) == {'60s', '600s'}
    w = snap['windows']['600s']
    assert w['requests'] == 4 and w['offered'] == 4
    assert w['dimensions']['error_rate']['bad'] == 4
    assert snap['budget_remaining']['error_rate'] == 0.0
    assert snap['targets'] == {'error_rate': 0.01}


def test_evaluate_scores_and_missing_observation_fails():
    targets = {'p99_ttft_ms': 500.0, 'error_rate': 0.01}
    out = slo_lib.evaluate(targets, {'p99_ttft_ms': 250.0,
                                     'error_rate': 0.02})
    by_dim = {r['dimension']: r for r in out['results']}
    assert by_dim['p99_ttft_ms']['ok'] is True
    assert by_dim['p99_ttft_ms']['budget_consumed'] == 0.5
    assert by_dim['error_rate']['ok'] is False
    assert out['ok'] is False
    assert out['budget_consumed'] == 2.0    # worst dimension
    # Unmeasured targeted dimension: a broken promise, not a pass.
    out = slo_lib.evaluate(targets, {'p99_ttft_ms': 250.0})
    assert out['ok'] is False
    by_dim = {r['dimension']: r for r in out['results']}
    assert by_dim['error_rate']['observed'] is None
    assert by_dim['error_rate']['ok'] is False


def test_serve_bench_attach_slo_maps_record_keys():
    """The bench-side mapping: engine ITL beats SSE fallback, 504s
    fold into the error rate, shed_rate uses offered, and A/B `runs`
    maps get per-run verdicts plus a rollup."""
    import importlib.util
    repo = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    spec = importlib.util.spec_from_file_location(
        'serve_bench_for_test',
        os.path.join(repo, 'benchmarks', 'serve_bench.py'))
    sb = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(sb)

    targets = slo_lib.parse_slo(
        'p99_ttft_ms=500,p99_itl_ms=80,error_rate=0.01,'
        'shed_rate=0.05')
    fleet = {'requests': 100, 'client_errors': 1, 'shed_requests': 5,
             'p99_ttft_ms': 450.0, 'decode_itl_ms_p99': 60.0,
             'sse_itl_ms_p99': 999.0}
    sb.attach_slo(fleet, targets)
    by_dim = {r['dimension']: r for r in fleet['slo']['results']}
    assert by_dim['p99_itl_ms']['observed'] == 60.0  # engine-side
    assert by_dim['error_rate']['observed'] == 0.01
    assert by_dim['shed_rate']['observed'] == \
        pytest.approx(5 / 105, abs=1e-4)
    assert fleet['slo']['ok'] is True

    single = {'requests': 64, 'shed_requests': 0,
              'server_deadline_exceeded': 2, 'p99_ttft_ms': 700.0,
              'itl_ms_p99': 90.0}
    sb.attach_slo(single, targets)
    by_dim = {r['dimension']: r for r in single['slo']['results']}
    assert by_dim['error_rate']['observed'] == \
        pytest.approx(2 / 64, abs=1e-4)
    assert by_dim['p99_itl_ms']['observed'] == 90.0
    assert single['slo']['ok'] is False

    ab = {'runs': {'good': dict(fleet), 'bad': dict(single)}}
    sb.attach_slo(ab, targets)
    assert ab['slo']['ok'] is False
    assert ab['slo']['runs'] == {'good': True, 'bad': False}
