"""fuse-proxy protocol tests (no privileges / no real FUSE needed).

Reference analog: addons/fuse-proxy (Go) tests. The real fusermount is
replaced by a fake that sends back an fd to a regular file, so the
whole chain — shim -> unix socket -> server -> fusermount(_FUSE_COMMFD,
SCM_RIGHTS) -> server -> shim -> libfuse(_FUSE_COMMFD) — runs as the
test user. Receiving the fake's fd and reading its content through it
proves fd identity end to end.
"""
import os
import shutil
import socket
import subprocess
import sys
import time

import pytest

NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), 'native')

FAKE_FUSERMOUNT = r'''#!/usr/bin/env python3
"""Fake fusermount: mount -> send an fd over _FUSE_COMMFD;
-u -> write an unmount marker."""
import os
import socket
import sys
import array

args = sys.argv[1:]
if '-u' in args:
    mountpoint = args[-1]
    with open(os.environ['FAKE_MARKER'], 'w') as f:
        f.write('unmounted ' + mountpoint)
    sys.exit(0)
mountpoint = args[-1]
# The server passes a pinned /proc/self/fd/N path (TOCTOU hardening);
# realpath() through it proves the fd points at the validated dir.
with open(os.environ['FAKE_MARKER'], 'w') as f:
    f.write('mounted ' + os.path.realpath(mountpoint))
payload = os.environ['FAKE_PAYLOAD']
fd = os.open(payload, os.O_RDONLY)
comm = socket.socket(fileno=int(os.environ['_FUSE_COMMFD']))
comm.sendmsg([b'F'], [(socket.SOL_SOCKET, socket.SCM_RIGHTS,
                       array.array('i', [fd]).tobytes())])
comm.close()
sys.exit(0)
'''


@pytest.fixture(scope='module')
def binaries():
    if shutil.which('g++') is None:
        pytest.skip('no g++')
    subprocess.run(['make', '-s', 'fusermount-shim', 'fuse-proxy-server'],
                   cwd=NATIVE_DIR, check=True)
    return {
        'shim': os.path.join(NATIVE_DIR, 'fusermount-shim'),
        'server': os.path.join(NATIVE_DIR, 'fuse-proxy-server'),
    }


@pytest.fixture()
def proxy(binaries, tmp_path):
    fake = tmp_path / 'fake_fusermount.py'
    fake.write_text(FAKE_FUSERMOUNT)
    fake.chmod(0o755)
    payload = tmp_path / 'payload.txt'
    payload.write_text('hello-through-the-fd')
    marker = tmp_path / 'marker.txt'
    sock = tmp_path / 'proxy.sock'
    allowed = tmp_path / 'mounts'
    allowed.mkdir()
    env = dict(os.environ)
    env.update({
        'FUSE_PROXY_SOCKET': str(sock),
        'FUSE_PROXY_ALLOWED_ROOT': str(allowed),
        'FUSE_PROXY_FUSERMOUNT': str(fake),
        'FAKE_PAYLOAD': str(payload),
        'FAKE_MARKER': str(marker),
    })
    proc = subprocess.Popen([binaries['server']], env=env,
                            stderr=subprocess.PIPE)
    deadline = time.time() + 10
    while not sock.exists():
        if time.time() > deadline or proc.poll() is not None:
            raise RuntimeError('fuse-proxy server did not start')
        time.sleep(0.05)
    yield {'sock': str(sock), 'allowed': str(allowed),
           'marker': str(marker), 'env': env, 'shim': binaries['shim']}
    proc.terminate()
    proc.wait(timeout=10)


def _run_shim(proxy, args, with_commfd=True):
    env = dict(proxy['env'])
    pass_fds = ()
    ours = None
    if with_commfd:
        ours, theirs = socket.socketpair()
        env['_FUSE_COMMFD'] = str(theirs.fileno())
        pass_fds = (theirs.fileno(),)
    proc = subprocess.run([proxy['shim']] + args, env=env,
                          pass_fds=pass_fds, capture_output=True,
                          timeout=30)
    if with_commfd:
        theirs.close()
    return proc, ours


def test_mount_fd_relay(proxy):
    mountpoint = os.path.join(proxy['allowed'], 'bucket')
    os.makedirs(mountpoint, exist_ok=True)
    proc, ours = _run_shim(
        proxy, ['-o', 'rw,nosuid,nodev', '--', mountpoint])
    assert proc.returncode == 0, proc.stderr.decode()
    # libfuse's side: the fd must arrive over _FUSE_COMMFD…
    msg, fds, _flags, _addr = socket.recv_fds(ours, 16, 1)
    ours.close()
    assert msg == b'F' and len(fds) == 1
    # …and be THE fake's payload fd (content readable through it).
    with os.fdopen(fds[0], 'r') as f:
        assert f.read() == 'hello-through-the-fd'
    # The server resolved the mountpoint before exec'ing fusermount.
    with open(proxy['marker'], 'r', encoding='utf-8') as f:
        assert f.read() == f'mounted {os.path.realpath(mountpoint)}'


def test_mountpoint_outside_allowed_root_refused(proxy, tmp_path):
    outside = tmp_path / 'not-allowed'
    outside.mkdir()
    proc, ours = _run_shim(proxy, ['--', str(outside)])
    assert proc.returncode != 0
    assert b'proxy status 201' in proc.stderr
    ours.close()
    assert not os.path.exists(proxy['marker'])


def test_relative_mountpoint_resolved_against_client_cwd(proxy):
    sub = os.path.join(proxy['allowed'], 'rel')
    os.makedirs(sub, exist_ok=True)
    env = dict(proxy['env'])
    ours, theirs = socket.socketpair()
    env['_FUSE_COMMFD'] = str(theirs.fileno())
    proc = subprocess.run([proxy['shim'], '--', 'rel'], env=env,
                          cwd=proxy['allowed'],
                          pass_fds=(theirs.fileno(),),
                          capture_output=True, timeout=30)
    theirs.close()
    assert proc.returncode == 0, proc.stderr.decode()
    _msg, fds, _f, _a = socket.recv_fds(ours, 16, 1)
    ours.close()
    for fd in fds:
        os.close(fd)
    with open(proxy['marker'], 'r', encoding='utf-8') as f:
        assert f.read() == f'mounted {os.path.realpath(sub)}'


def test_unmount_no_fd(proxy):
    mountpoint = os.path.join(proxy['allowed'], 'bucket2')
    os.makedirs(mountpoint, exist_ok=True)
    proc, _ = _run_shim(proxy, ['-u', mountpoint], with_commfd=False)
    assert proc.returncode == 0, proc.stderr.decode()
    with open(proxy['marker'], 'r', encoding='utf-8') as f:
        assert f.read().startswith('unmounted ')


def test_missing_mountpoint_bad_request(proxy):
    proc, ours = _run_shim(proxy, ['-o', 'rw'])
    assert proc.returncode != 0
    assert b'proxy status 200' in proc.stderr
    if ours:
        ours.close()


def test_server_unreachable(binaries, tmp_path):
    env = dict(os.environ)
    env['FUSE_PROXY_SOCKET'] = str(tmp_path / 'nope.sock')
    proc = subprocess.run(
        [binaries['shim'], '--', str(tmp_path)], env=env,
        capture_output=True, timeout=30)
    assert proc.returncode != 0
    assert b'cannot reach fuse-proxy' in proc.stderr


def test_symlink_escape_refused(proxy, tmp_path):
    """A symlink under the allowed root pointing outside must not be
    mountable (realpath-based validation)."""
    link = os.path.join(proxy['allowed'], 'escape')
    os.symlink(str(tmp_path), link)
    proc, ours = _run_shim(proxy, ['--', link])
    assert proc.returncode != 0
    assert b'proxy status 201' in proc.stderr
    if ours:
        ours.close()


def test_unmount_dead_mountpoint(proxy):
    """Unmounting a mountpoint that cannot be stat'ed (dead FUSE
    endpoint) must still reach fusermount -u: only the PARENT dir is
    resolved for unmounts."""
    ghost = os.path.join(proxy['allowed'], 'ghost')  # does not exist
    proc, _ = _run_shim(proxy, ['-u', ghost], with_commfd=False)
    assert proc.returncode == 0, proc.stderr.decode()
    with open(proxy['marker'], 'r', encoding='utf-8') as f:
        assert f.read() == f'unmounted {os.path.realpath(ghost)}'
