"""CI checker for the metric catalog: every exported Prometheus
metric name is snake_case, `skypilot_`-prefixed, and listed in the
docs metric-catalog table — and the docs list nothing stale. Keeps
`observability/catalog.py` and `docs/guides.md` from drifting."""
import os
import re

from skypilot_tpu.observability import catalog
from skypilot_tpu.observability import metrics as m

_DOCS = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     '..', '..', 'docs', 'guides.md')

_SNAKE = re.compile(r'^[a-z][a-z0-9_]*$')


def _docs_table_names():
    """Metric names from the docs catalog table (backticked first
    column of `| \\`skypilot_...\\` | ... |` rows)."""
    with open(_DOCS, 'r', encoding='utf-8') as f:
        text = f.read()
    return set(re.findall(r'^\|\s*`(skypilot_[a-z0-9_]+)`\s*\|',
                          text, re.MULTILINE))


def test_metric_names_are_snake_case_and_prefixed():
    for name in catalog.SPECS:
        assert _SNAKE.match(name), f'{name} is not snake_case'
        assert name.startswith('skypilot_'), \
            f'{name} lacks the skypilot_ prefix'


def test_counter_names_end_in_total():
    """Prometheus convention: counters (and counter-exposed totals)
    end in _total; non-counters must not."""
    for name, spec in catalog.SPECS.items():
        if spec[0] in ('counter', 'gauge_as_counter'):
            assert name.endswith('_total'), name
        else:
            assert not name.endswith('_total'), name


def test_every_metric_is_documented():
    documented = _docs_table_names()
    exported = set(catalog.SPECS)
    missing = exported - documented
    assert not missing, (
        f'metrics missing from the docs/guides.md catalog table: '
        f'{sorted(missing)}')
    stale = documented - exported
    assert not stale, (
        f'docs/guides.md lists metrics no longer in '
        f'observability/catalog.py: {sorted(stale)}')


def test_label_names_are_snake_case():
    for name, spec in catalog.SPECS.items():
        for label in spec[2]:
            assert _SNAKE.match(label), f'{name} label {label!r}'


def test_slo_metrics_documented_and_set_in_tree():
    """The `skypilot_serving_slo_*` family must be real: every row is
    in the docs table (the generic check covers that too, but a
    missing row should name THIS family) and every row is actually
    set/incremented by non-catalog code — a catalog-only orphan gauge
    would scrape as permanently absent."""
    slo_rows = sorted(n for n in catalog.SPECS
                      if n.startswith('skypilot_serving_slo_'))
    assert slo_rows, 'the SLO metric family is gone from the catalog'
    documented = _docs_table_names()
    missing = [n for n in slo_rows if n not in documented]
    assert not missing, (
        f'SLO metrics missing from the docs table: {missing}')
    pkg = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       '..', '..', 'skypilot_tpu')
    sources = []
    for dirpath, _dirnames, filenames in os.walk(pkg):
        for fn in filenames:
            if fn.endswith('.py') and fn != 'catalog.py':
                with open(os.path.join(dirpath, fn), 'r',
                          encoding='utf-8') as f:
                    sources.append(f.read())
    tree = '\n'.join(sources)
    orphans = [n for n in slo_rows if n not in tree]
    assert not orphans, (
        f'cataloged SLO metrics never set by any code: {orphans}')


def test_registry_contains_only_cataloged_skypilot_metrics():
    """Ad-hoc families must not sneak into the default registry under
    the skypilot_ prefix without a catalog row (test-local registries
    are exempt — they are not scraped)."""
    for name in catalog.SPECS:
        catalog._create(name)  # materialize the full catalog
    for name in m.REGISTRY.names():
        if name.startswith('skypilot_'):
            assert name in catalog.SPECS, (
                f'{name} is registered in the default registry but '
                f'not cataloged in observability/catalog.py')
