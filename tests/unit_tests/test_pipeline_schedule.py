"""The explicit pipeline schedule (parallel/pipeline_schedule.py) and
its runner (parallel/pipeline.py schedule='1f1b'/'interleaved').

Two layers of proof:
  1. Schedule invariants — pure host-side accounting, no devices:
     tick exclusivity, fwd-before-bwd and chain ordering, the exact
     closed forms (span 2(M*v + S - 1), per-device bubble 2(S - 1)),
     1F1B's peak-live-activation cap at S vs GPipe's M, and the
     slot/ring table consistency the runner relies on.
  2. Runner parity — the hand-rolled backward must reproduce the
     fused-scan GPipe engine (jax.grad oracle) and the sequential
     model, loss AND grads, on CPU host-device meshes.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import flax.linen as nn

from skypilot_tpu.parallel import mesh as mesh_lib
from skypilot_tpu.parallel import pipeline_schedule as ps

SHAPES = [(2, 4), (2, 8), (3, 6), (4, 8), (4, 16), (8, 8)]
STYLE_V = [('gpipe', 1), ('1f1b', 1), ('interleaved', 2),
           ('interleaved', 4)]


def _all_schedules():
    for style, v in STYLE_V:
        for S, M in SHAPES:
            if style == 'interleaved' and M % S:
                continue
            yield ps.make_schedule(S, M, style, v)


def test_one_op_per_stage_per_tick():
    for sched in _all_schedules():
        seen = set()
        for op in sched.ops:
            key = (op.tick, op.stage)
            assert key not in seen, (sched.style, key)
            seen.add(key)
            assert 0 <= op.tick < sched.num_ticks
            assert op.stage == op.virtual % sched.stages


def test_every_fwd_precedes_its_bwd_and_chains_order():
    for sched in _all_schedules():
        V = sched.stages * sched.virtual_stages
        fwd = {}
        bwd = {}
        for op in sched.ops:
            (fwd if op.kind == ps.FWD else bwd)[
                (op.virtual, op.microbatch)] = op.tick
        for vs in range(V):
            for m in range(sched.microbatches):
                assert fwd[(vs, m)] < bwd[(vs, m)], (sched.style, vs, m)
                if vs > 0:
                    assert fwd[(vs - 1, m)] < fwd[(vs, m)]
                if vs < V - 1:
                    assert bwd[(vs + 1, m)] < bwd[(vs, m)]


def test_closed_form_span_and_bubble_count():
    """Every style spans exactly 2(M*v + S - 1) ticks; every device is
    busy for exactly 2*M*v of them — the bubble is always 2(S - 1)
    ticks per device, 2*S*(S - 1) slots total."""
    for sched in _all_schedules():
        S, M, v = sched.stages, sched.microbatches, sched.virtual_stages
        assert sched.num_ticks == ps.closed_form_span(S, M, sched.style,
                                                      v)
        assert sched.num_ticks == 2 * (M * v + S - 1)
        assert sched.bubble_slots == 2 * S * (S - 1)
        per_dev = [0] * S
        for op in sched.ops:
            per_dev[op.stage] += 1
        assert all(n == 2 * M * v for n in per_dev)
        expect_frac = (S - 1) / (M * v + S - 1)
        assert abs(sched.bubble_fraction - expect_frac) < 1e-12


def test_1f1b_peak_live_capped_at_stages_vs_gpipe_m():
    """THE 1F1B claim: peak concurrently-stored chunk inputs drop
    from GPipe's M (every stage holds the whole flush) to min(M, S),
    and per-stage residency decays downstream (S, S-1, ..., 1)."""
    for S, M in SHAPES:
        g = ps.make_schedule(S, M, 'gpipe')
        f = ps.make_schedule(S, M, '1f1b')
        assert g.peak_live_activations == M
        assert all(p == M for p in g.live_peak_per_stage)
        assert f.peak_live_activations == min(M, S)
        assert f.live_peak_per_stage == tuple(
            min(M, S - s) for s in range(S))
        if M > S:
            assert f.peak_live_activations < g.peak_live_activations


def test_interleaved_divides_bubble_fraction():
    """v virtual stages divide the bubble fraction (Megatron
    interleaved-1F1B): exactly (S-1)/(M*v+S-1), strictly below 1f1b
    at the same S, M — paying with ~v-times the stored chunk inputs."""
    for S, M in ((2, 4), (4, 8), (4, 16), (8, 8)):
        f = ps.make_schedule(S, M, '1f1b')
        for v in (2, 4):
            i = ps.make_schedule(S, M, 'interleaved', v)
            assert i.bubble_fraction < f.bubble_fraction
            assert abs(i.bubble_fraction -
                       (S - 1) / (M * v + S - 1)) < 1e-12
            assert i.peak_live_activations <= \
                2 * (S - 1) + (v - 1) * S + 1


def test_activation_bytes_proxy_orders_styles():
    g = ps.make_schedule(4, 16, 'gpipe')
    f = ps.make_schedule(4, 16, '1f1b')
    assert g.activation_bytes(64, 128) == 16 * 64 * 128 * 2
    assert f.activation_bytes(64, 128) == 4 * 64 * 128 * 2


def test_schedule_is_pure_and_deterministic():
    a = ps.make_schedule(4, 8, '1f1b')
    b = ps.make_schedule(4, 8, '1f1b')
    assert a.ops == b.ops
    for k in a.tables:
        np.testing.assert_array_equal(a.tables[k], b.tables[k])


def test_validation_errors():
    with pytest.raises(ValueError, match='style'):
        ps.make_schedule(2, 4, 'pipedream')
    with pytest.raises(ValueError, match='stages'):
        ps.make_schedule(1, 4, 'gpipe')
    with pytest.raises(ValueError, match='virtual_stages'):
        ps.make_schedule(2, 4, 'interleaved', 1)
    with pytest.raises(ValueError, match='multiple'):
        ps.make_schedule(4, 6, 'interleaved', 2)
    with pytest.raises(ValueError, match='virtual_stages == 1'):
        ps.make_schedule(2, 4, '1f1b', 2)


def test_slot_lifetimes_never_collide():
    """Replay the runner's buffer discipline from the tables: an
    activation slot written by a forward must not be rewritten before
    its backward reads it; same for the loss-cotangent ring and the
    two receive rings."""
    for sched in _all_schedules():
        S = sched.stages
        tb = sched.tables
        live = [dict() for _ in range(S)]  # stage -> slot -> (vs, m)
        for t in range(sched.num_ticks):
            for s in range(S):
                kind = tb['op_kind'][t, s]
                if kind == 0:
                    continue
                slot = int(tb['act_slot'][t, s])
                vs = int(tb['op_virtual'][t, s])
                m = int(tb['op_mb'][t, s])
                if kind == ps.FWD:
                    assert slot not in live[s], (
                        f'{sched.style}: stage {s} overwrites live '
                        f'slot {slot} at tick {t}')
                    live[s][slot] = (vs, m)
                else:
                    assert live[s].get(slot) == (vs, m), (
                        f'{sched.style}: stage {s} bwd reads slot '
                        f'{slot} expecting {(vs, m)}, holds '
                        f'{live[s].get(slot)}')
                    del live[s][slot]
        assert all(not lv for lv in live)


def test_rx_ring_routes_every_handoff():
    """Every non-entry forward consumes exactly the slot its
    producer's message was parked in one-or-more ticks earlier (and
    mirrored for backward cotangents)."""
    for sched in _all_schedules():
        S = sched.stages
        V = S * sched.virtual_stages
        tb = sched.tables
        fwd_tick = {}
        bwd_tick = {}
        for op in sched.ops:
            (fwd_tick if op.kind == ps.FWD else bwd_tick)[
                (op.virtual, op.microbatch)] = op.tick
        for (vs, m), t in fwd_tick.items():
            if vs == 0:
                continue
            pt = fwd_tick[(vs - 1, m)]
            wslot = tb['rxf_wslot'][pt, (vs - 1) % S]
            rslot = tb['rxf_rslot'][t, vs % S]
            assert wslot == rslot >= 0, (sched.style, vs, m)
        for (vs, m), t in bwd_tick.items():
            if vs == V - 1:
                continue
            pt = bwd_tick[(vs + 1, m)]
            wslot = tb['rxb_wslot'][pt, (vs + 1) % S]
            rslot = tb['rxb_rslot'][t, vs % S]
            assert wslot == rslot >= 0, (sched.style, vs, m)


# ---------------------------------------------------------------------------
# Runner parity: explicit 1F1B/interleaved backward vs the fused-scan
# GPipe engine (jax.grad oracle) and the sequential model.

CFG_KW = dict(vocab_size=128, block_size=32, num_layers=2, num_heads=2,
              embed_dim=32, dtype=jnp.float32, logits_dtype=jnp.float32)


@pytest.fixture(scope='module')
def tiny_setup():
    from skypilot_tpu.models.gpt import GPT, GPTConfig
    model = GPT(GPTConfig(**CFG_KW))
    params = nn.meta.unbox(model.init(
        jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))['params'])
    mesh = mesh_lib.make_mesh(mesh_lib.MeshConfig(stage=2, data=4))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (16, 16), 0,
                                CFG_KW['vocab_size'], jnp.int32)
    return model, params, mesh, tokens


def _tree_close(a, b, rtol, atol):
    fa = sorted(jax.tree_util.tree_leaves_with_path(a),
                key=lambda x: str(x[0]))
    fb = sorted(jax.tree_util.tree_leaves_with_path(b),
                key=lambda x: str(x[0]))
    assert len(fa) == len(fb)
    for (pa, xa), (_, xb) in zip(fa, fb):
        np.testing.assert_allclose(
            np.asarray(xb), np.asarray(xa), rtol=rtol, atol=atol,
            err_msg=jax.tree_util.keystr(pa))


def test_runner_1f1b_matches_gpipe_engine(tiny_setup):
    """GPipe <-> 1F1B parity on a CPU mesh: same loss (fp32
    tolerance — the explicit runner re-orders the reductions) and
    same grads as the fused-scan engine differentiated by jax.grad."""
    from skypilot_tpu.parallel.pipeline import PipelinedLM
    model, params, mesh, tokens = tiny_setup
    gp = PipelinedLM(model, mesh, num_microbatches=4,
                     schedule='gpipe')
    stacked, rest = gp.split_params(params)
    ref_loss = gp.loss(stacked, rest, tokens)
    ref_gs, ref_gr = jax.grad(
        lambda s, r: gp.loss(s, r, tokens), argnums=(0, 1))(stacked,
                                                            rest)
    pp = PipelinedLM(model, mesh, num_microbatches=4,
                     schedule='1f1b')
    loss, (gs, gr) = pp.loss_and_grad(stacked, rest, tokens)
    np.testing.assert_allclose(float(loss), float(ref_loss),
                               rtol=1e-6)
    _tree_close(ref_gs, gs, rtol=1e-5, atol=1e-7)
    _tree_close(ref_gr, gr, rtol=1e-5, atol=1e-7)


def test_runner_guarded_step_skips_poisoned_update(tiny_setup):
    """The per-stage guard hook: a NaN loss_scale flags the step bad
    on device and the update is skipped — params bit-identical, step
    still consumed (the train_lm --guard x --pipeline-stages path)."""
    from skypilot_tpu.parallel.pipeline import PipelinedLM
    from skypilot_tpu.parallel.train import default_optimizer
    model, _, mesh, tokens = tiny_setup
    pp = PipelinedLM(model, mesh, num_microbatches=4,
                     schedule='1f1b')
    tx = default_optimizer()
    state = pp.init(jax.random.PRNGKey(0), tokens, tx)
    step = pp.make_train_step(tx, guard=True)
    state, (l0, g0, b0) = step(state, tokens)
    assert not bool(b0) and np.isfinite(float(l0)) \
        and np.isfinite(float(g0))
    before = [np.asarray(x) for x in jax.tree.leaves(state.params)]
    state, (lp, gp_, bp) = step(state, tokens, float('inf'),
                                float('nan'))
    assert bool(bp) and not np.isfinite(float(lp))
    after = [np.asarray(x) for x in jax.tree.leaves(state.params)]
    for a, b in zip(before, after):
        np.testing.assert_array_equal(a, b)
    assert int(state.step) == 2
    # And a spike past max_grad_norm is also a skip.
    state, (_, g2, b2) = step(state, tokens, 1e-9, 1.0)
    assert bool(b2) and float(g2) > 1e-9


@pytest.mark.slow
def test_runner_interleaved_matches_gpipe_engine(tiny_setup):
    from skypilot_tpu.parallel.pipeline import PipelinedLM
    model, params, mesh, tokens = tiny_setup
    gp = PipelinedLM(model, mesh, num_microbatches=4,
                     schedule='gpipe')
    stacked, rest = gp.split_params(params)
    ref_loss = gp.loss(stacked, rest, tokens)
    ref_grads = jax.grad(
        lambda s, r: gp.loss(s, r, tokens), argnums=(0, 1))(stacked,
                                                            rest)
    pp = PipelinedLM(model, mesh, num_microbatches=4,
                     schedule='interleaved', virtual_stages=2)
    # Interleaving PERMUTES the stacked layout (device s hosts chunks
    # s, S+s, ...): split/merge round-trips it.
    i_stacked, i_rest = pp.split_params(params)
    back = pp.merge_params(i_stacked, i_rest)
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(back)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    loss, (gs, gr) = pp.loss_and_grad(i_stacked, i_rest, tokens)
    np.testing.assert_allclose(float(loss), float(ref_loss),
                               rtol=1e-6)
    ref_merged = gp.merge_params(*ref_grads)
    got_merged = pp.merge_params(gs, gr)
    _tree_close(ref_merged, got_merged, rtol=1e-5, atol=1e-7)


@pytest.mark.slow
@pytest.mark.parametrize('family', ['llama', 'mixtral'])
def test_runner_1f1b_family_parity(family):
    """GPipe <-> 1F1B loss/grad parity for the Llama and Mixtral
    families (rope/GQA untied-head blocks; router aux accumulation
    and its gradient) — the fused-scan engine is the oracle because
    it is itself pinned to the sequential model by the legacy tests."""
    from skypilot_tpu.parallel.pipeline import PipelinedLM
    if family == 'llama':
        from skypilot_tpu.models.llama import Llama, LlamaConfig
        model = Llama(LlamaConfig(
            vocab_size=256, max_seq_len=64, num_layers=4, num_heads=4,
            num_kv_heads=2, embed_dim=64, mlp_dim=128,
            dtype=jnp.float32, logits_dtype=jnp.float32))
    else:
        from skypilot_tpu.models.mixtral import Mixtral, MixtralConfig
        model = Mixtral(MixtralConfig(
            vocab_size=256, max_seq_len=64, num_layers=4, num_heads=4,
            num_kv_heads=2, embed_dim=64, mlp_dim=96, num_experts=4,
            experts_per_token=2, dtype=jnp.float32,
            logits_dtype=jnp.float32))
    params = nn.meta.unbox(model.init(
        jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))['params'])
    mesh = mesh_lib.make_mesh(mesh_lib.MeshConfig(stage=4, data=2))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 32), 0,
                                256, jnp.int32)
    gp = PipelinedLM(model, mesh, num_microbatches=4,
                     schedule='gpipe')
    stacked, rest = gp.split_params(params)
    ref_loss = gp.loss(stacked, rest, tokens)
    ref_gs, ref_gr = jax.grad(
        lambda s, r: gp.loss(s, r, tokens), argnums=(0, 1))(stacked,
                                                            rest)
    pp = PipelinedLM(model, mesh, num_microbatches=4,
                     schedule='1f1b')
    loss, (gs, gr) = pp.loss_and_grad(stacked, rest, tokens)
    np.testing.assert_allclose(float(loss), float(ref_loss),
                               rtol=1e-6)
    _tree_close(ref_gs, gs, rtol=2e-5, atol=1e-7)
    _tree_close(ref_gr, gr, rtol=2e-5, atol=1e-7)


@pytest.mark.slow
def test_runner_1f1b_train_step_descends_and_checkpoints(tmp_path):
    """train_lm --pipeline-schedule 1f1b end-to-end on a stage x data
    mesh: runs, reports the schedule, checkpoints, RESUMES."""
    import os
    import subprocess
    import sys
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    env = dict(os.environ)
    env['XLA_FLAGS'] = '--xla_force_host_platform_device_count=8'
    env['PYTHONPATH'] = f"{repo}:{env.get('PYTHONPATH', '')}"
    base = [sys.executable, '-m', 'skypilot_tpu.recipes.train_lm',
            '--cpu', '--model', 'tiny', '--pipeline-stages', '2',
            '--pipeline-schedule', '1f1b', '--seq', '64',
            '--global-batch', '32', '--log-every', '2',
            '--ckpt-dir', str(tmp_path / 'ckpt'), '--ckpt-every', '2']
    out = subprocess.run(base + ['--steps', '2'], capture_output=True,
                         text=True, env=env, timeout=420)
    assert out.returncode == 0, out.stdout + out.stderr
    assert '1f1b(S=2' in out.stdout
    out = subprocess.run(base + ['--steps', '4'], capture_output=True,
                         text=True, env=env, timeout=420)
    assert out.returncode == 0, out.stdout + out.stderr
    assert 'resumed from checkpoint step 2' in out.stdout


@pytest.mark.slow
def test_train_lm_guard_under_pipeline_skips_bad_step(tmp_path):
    """The lifted --guard x --pipeline-stages incompatibility: a
    fault-plan NaN on step 1 drives the REAL on-device isfinite guard
    under the 1f1b pipeline — the step is skipped, counted, and the
    run completes rc=0."""
    import json
    import os
    import subprocess
    import sys
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    env = dict(os.environ)
    env['XLA_FLAGS'] = '--xla_force_host_platform_device_count=4'
    env['PYTHONPATH'] = f"{repo}:{env.get('PYTHONPATH', '')}"
    env['STPU_FAULT_PLAN'] = json.dumps({'rules': [
        {'point': 'train.step', 'action': 'drop', 'at': [2]}]})
    out = subprocess.run(
        [sys.executable, '-m', 'skypilot_tpu.recipes.train_lm',
         '--cpu', '--model', 'tiny', '--pipeline-stages', '2',
         '--pipeline-schedule', '1f1b', '--guard',
         '--guard-warmup', '1', '--seq', '64', '--global-batch',
         '16', '--steps', '4', '--log-every', '1'],
        capture_output=True, text=True, env=env, timeout=420)
    assert out.returncode == 0, out.stdout + out.stderr
    assert 'injected NaN into step 1' in out.stdout
    assert 'update skipped' in out.stdout
    assert "'skipped_steps': 1" in out.stdout
    assert 'training done' in out.stdout


def test_bench_pipe_artifact_backs_the_memory_claim():
    """The committed BENCH_pipe artifact must show what the schedule
    refactor is FOR: GPipe's activation proxy exceeds the budget at
    the microbatch counts 1F1B sustains, and the best in-budget
    bubble fraction beats GPipe's in-budget floor."""
    import json
    import os
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        '..', '..', 'BENCH_pipe_r12.json')
    with open(path, 'r', encoding='utf-8') as f:
        art = json.load(f)
    assert art['metric'] == 'pipeline_schedule_sweep'
    budget = art['summary']['budget_live_activations']
    arms = art['arms']
    over = [a for a in arms if a['style'] == 'gpipe'
            and not a['fits_budget']]
    assert over, 'no gpipe arm exceeds the activation budget'
    sustained = [a for a in arms if a['style'] == '1f1b'
                 and a['fits_budget']
                 and a['microbatches'] >= min(
                     o['microbatches'] for o in over)]
    assert sustained, '1f1b does not sustain the over-budget M'
    assert all(a['peak_live_activations'] <= budget
               for a in sustained)
    assert art['summary']['best_bubble_at_budget'] < \
        art['summary']['gpipe_bubble_at_budget']
    # MFU column present, null off-TPU.
    assert 'mfu' in art
    if art['platform'] != 'tpu':
        assert art['mfu'] is None
