"""Fused blockwise LM-head cross-entropy (ops/fused_xent.py).

Fast tier (CPU, tiny shapes — runs in `-m 'not slow'`): the chunked
custom_vjp forward/backward is pinned against the naive
`head-matmul + next_token_loss` reference at fp32 rtol 1e-5, across
tied/untied head orientation, a vocab not divisible by the chunk
(padding+masking path), and bf16 hidden states.

Slow tier (real mesh compiles): 5-step train-loss-curve equality on
qwen-tiny with fused on/off, and XLA memory_analysis() proving the
fused loss+backward peak temp memory sits strictly below the naive
path.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from skypilot_tpu.ops import fused_xent
from skypilot_tpu.parallel.train import next_token_loss


def _naive_loss(hidden, weight, tokens, vocab_in_rows):
    """The reference path: dense head matmul + next_token_loss."""
    eq = 'bsh,vh->bsv' if vocab_in_rows else 'bsh,hv->bsv'
    logits = jnp.einsum(eq, hidden, weight,
                        preferred_element_type=jnp.float32)
    return next_token_loss(logits, tokens)


def _rand(vocab, vocab_in_rows, dtype=jnp.float32, b=2, s=9, h=32):
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(vocab), 3)
    hidden = jax.random.normal(k1, (b, s, h), dtype)
    shape = (vocab, h) if vocab_in_rows else (h, vocab)
    weight = jax.random.normal(k2, shape, jnp.float32) * 0.3
    tokens = jax.random.randint(k3, (b, s), 0, vocab)
    return hidden, weight, tokens


@pytest.mark.parametrize('vocab_in_rows', [True, False],
                         ids=['tied', 'untied'])
@pytest.mark.parametrize('vocab,block', [(64, 16), (70, 16)],
                         ids=['divisible', 'odd_vocab'])
def test_fused_matches_naive_fp32(vocab, block, vocab_in_rows):
    """Gradcheck: fused loss AND grads == naive at fp32 rtol 1e-5,
    through the chunked custom_vjp (vocab > block), including the
    pad+mask path when the chunk does not divide the vocab."""
    hidden, weight, tokens = _rand(vocab, vocab_in_rows)

    def fused(h, w):
        return fused_xent.fused_next_token_loss(
            h, w, tokens, vocab_in_rows=vocab_in_rows, block_size=block)

    def naive(h, w):
        return _naive_loss(h, w, tokens, vocab_in_rows)

    loss_f, grads_f = jax.value_and_grad(fused, argnums=(0, 1))(
        hidden, weight)
    loss_n, grads_n = jax.value_and_grad(naive, argnums=(0, 1))(
        hidden, weight)
    np.testing.assert_allclose(loss_f, loss_n, rtol=1e-5)
    for got, want in zip(grads_f, grads_n):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-6)


def test_fused_single_chunk_degenerates_to_naive():
    """block >= vocab: the dense fallback is bit-compatible with the
    naive path (the smoke-config contract — zero overhead)."""
    hidden, weight, tokens = _rand(48, True)
    loss_f = fused_xent.fused_next_token_loss(
        hidden, weight, tokens, vocab_in_rows=True, block_size=64)
    loss_n = _naive_loss(hidden, weight, tokens, True)
    np.testing.assert_allclose(loss_f, loss_n, rtol=1e-6)


def test_fused_bf16_hidden():
    """bf16 hidden states (the models' compute dtype): the chunked
    path matmuls in bf16 with f32 accumulation, same as the naive
    einsum — losses agree tightly."""
    hidden, weight, tokens = _rand(70, True, dtype=jnp.bfloat16)
    loss_f = fused_xent.fused_next_token_loss(
        hidden, weight, tokens, vocab_in_rows=True, block_size=16)
    loss_n = _naive_loss(hidden.astype(jnp.bfloat16),
                         weight.astype(jnp.bfloat16), tokens, True)
    np.testing.assert_allclose(float(loss_f), float(loss_n), rtol=2e-3)
    # And the backward runs + returns the primal dtypes.
    grads = jax.grad(
        lambda h, w: fused_xent.fused_next_token_loss(
            h, w, tokens, vocab_in_rows=True, block_size=16),
        argnums=(0, 1))(hidden, weight)
    assert grads[0].dtype == jnp.bfloat16
    assert grads[1].dtype == jnp.float32
    assert all(bool(jnp.all(jnp.isfinite(g.astype(jnp.float32))))
               for g in grads)


def test_fused_inside_jit_smoke():
    """Tier-1 smoke: the chunked path compiles and runs under jit on
    CPU tiny shapes (the shipping trainer wraps it in jit)."""
    hidden, weight, tokens = _rand(40, False)

    @jax.jit
    def step(h, w):
        return jax.value_and_grad(
            lambda h_, w_: fused_xent.fused_next_token_loss(
                h_, w_, tokens, vocab_in_rows=False, block_size=8))(h, w)

    loss, grad = step(hidden, weight)
    np.testing.assert_allclose(
        float(loss), float(_naive_loss(hidden, weight, tokens, False)),
        rtol=1e-5)
    assert grad.shape == hidden.shape


def test_pick_block_autotune():
    # Exact divisors from the candidate set, largest with >= 4 chunks.
    assert fused_xent.pick_block(152064) == 512      # qwen2 vocab
    assert fused_xent.pick_block(16384) == 4096
    assert fused_xent.pick_block(512) == 512         # single chunk
    # Nothing divides: least-padding candidate (masked tail).
    assert fused_xent.pick_block(50304) == 512       # gpt2 padded vocab
    assert fused_xent.pick_block(128256) == 512      # llama3 vocab


def test_find_lm_head():
    head, rows = fused_xent.find_lm_head(
        {'lm_head': jnp.zeros((4, 8)), 'wte': jnp.zeros((8, 4))})
    assert not rows and head.shape == (4, 8)
    head, rows = fused_xent.find_lm_head({'wte': jnp.zeros((8, 4))})
    assert rows and head.shape == (8, 4)
    with pytest.raises(ValueError):
        fused_xent.find_lm_head({'dense': jnp.zeros((4, 8))})


def _qwen_tiny(dtype=jnp.float32, vocab=None):
    from skypilot_tpu.models.llama import Llama, LlamaConfig
    kw = dict(qkv_bias=True, dtype=dtype)
    if vocab is None:
        cfg = LlamaConfig.tiny(**kw)
    else:
        cfg = LlamaConfig(vocab_size=vocab, max_seq_len=256,
                          num_layers=2, num_heads=4, num_kv_heads=2,
                          embed_dim=128, mlp_dim=384, **kw)
    return Llama(cfg), cfg


def _train_curve(model, mesh, tokens, fused, steps=5):
    from skypilot_tpu.parallel.train import (ShardedTrainer,
                                             default_optimizer,
                                             shard_batch)
    trainer = ShardedTrainer(model, mesh, tx=default_optimizer(),
                             fused_xent=fused)
    state = trainer.init(jax.random.PRNGKey(0), tokens)
    step = trainer.make_train_step(tokens)
    batch = shard_batch(tokens, mesh)
    losses = []
    for _ in range(steps):
        state, loss = step(state, batch)
        losses.append(float(loss))
    return losses


@pytest.mark.slow
def test_train_loss_curve_fused_vs_naive_qwen_tiny(cpu_mesh8):
    """5-step loss-curve equality on qwen-tiny, fused on vs off."""
    model, cfg = _qwen_tiny()
    tokens = jax.random.randint(jax.random.PRNGKey(3), (8, 64), 0,
                                cfg.vocab_size, jnp.int32)
    fused = _train_curve(model, cpu_mesh8, tokens, True)
    naive = _train_curve(model, cpu_mesh8, tokens, False)
    np.testing.assert_allclose(fused, naive, rtol=1e-4)
    assert fused[-1] < fused[0]


@pytest.mark.slow
def test_train_loss_curve_chunked_vocab(cpu_mesh8):
    """Same curve equality with a vocab large enough (2048 -> 4x512
    chunks) that the blockwise custom_vjp path actually engages."""
    model, cfg = _qwen_tiny(vocab=2048)
    assert fused_xent.pick_block(cfg.vocab_size) < cfg.vocab_size
    tokens = jax.random.randint(jax.random.PRNGKey(4), (8, 64), 0,
                                cfg.vocab_size, jnp.int32)
    fused = _train_curve(model, cpu_mesh8, tokens, True)
    naive = _train_curve(model, cpu_mesh8, tokens, False)
    np.testing.assert_allclose(fused, naive, rtol=1e-4)


@pytest.mark.slow
def test_fused_peak_temp_memory_below_naive():
    """XLA's own accounting: peak temp memory of the jitted
    loss+backward is strictly below the naive path on qwen-tiny
    shapes (the acceptance bar for the fused op)."""
    model, cfg = _qwen_tiny()
    tokens = jax.random.randint(jax.random.PRNGKey(5), (4, 128), 0,
                                cfg.vocab_size, jnp.int32)
    import flax.linen as nn
    params = nn.meta.unbox(
        model.init(jax.random.PRNGKey(0), tokens)['params'])
    hidden = model.apply({'params': params}, tokens, return_hidden=True)
    head = params['lm_head']
    block = cfg.vocab_size // 4

    def fused(h, w):
        return fused_xent.fused_next_token_loss(
            h, w, tokens, vocab_in_rows=False, block_size=block)

    def naive(h, w):
        return _naive_loss(h.astype(cfg.dtype), w.astype(cfg.dtype),
                           tokens, False)

    temps = {}
    for name, fn in (('fused', fused), ('naive', naive)):
        compiled = jax.jit(
            jax.value_and_grad(fn, argnums=(0, 1))).lower(
                hidden, head).compile()
        temps[name] = compiled.memory_analysis().temp_size_in_bytes
    assert temps['fused'] < temps['naive'], temps
