"""Multi-replica API server: atomic request claiming, heartbeats,
stale-request requeue, leader-elected daemons, and the two-server
kill-one-mid-request chaos e2e.

Beats the reference's charts/skypilot/values.yaml:22-23 ("replicas > 1
is not well tested"): here the multi-replica semantics ARE tested —
exactly-one-claim, failover of in-flight requests, singleton daemons.
"""
import json
import os
import signal
import socket
import subprocess
import sys
import time
import urllib.request

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


@pytest.fixture()
def exec_state(isolated_state):
    """isolated_state + a cleared executor DB cache (the conftest
    fixture only clears global_state's)."""
    from skypilot_tpu.server.requests import executor
    executor._db_for.cache_clear()
    yield isolated_state
    executor._db_for.cache_clear()


def test_claim_is_exclusive(exec_state):
    """Two replicas race one PENDING row: exactly one UPDATE wins."""
    from skypilot_tpu.server.requests import executor
    rid = executor.schedule_request('r', 'noop', {})

    def claim(server_id):
        return executor._db().execute_rowcount(
            'UPDATE requests SET status=?, server_id=? '
            'WHERE request_id=? AND status=?',
            (executor.RequestStatus.RUNNING.value, server_id, rid,
             executor.RequestStatus.PENDING.value)) == 1

    assert claim('srv-a') is True
    assert claim('srv-b') is False
    row = executor.get_request(rid)
    assert row['status'] == executor.RequestStatus.RUNNING
    assert row['server_id'] == 'srv-a'


def test_stale_requeue_only_dead_servers(exec_state):
    """Requests of a replica that stopped heartbeating re-queue; a
    live replica's requests are untouched."""
    from skypilot_tpu.server.requests import executor
    now = time.time()
    db = executor._db()
    db.execute('INSERT OR REPLACE INTO server_heartbeats VALUES (?,?)',
               ('dead-srv', now - 120))
    db.execute('INSERT OR REPLACE INTO server_heartbeats VALUES (?,?)',
               ('live-srv', now))
    rid_dead = executor.schedule_request('a', 'noop', {})
    rid_live = executor.schedule_request('b', 'noop', {})
    rid_pending = executor.schedule_request('c', 'noop', {})
    for rid, srv in ((rid_dead, 'dead-srv'), (rid_live, 'live-srv')):
        db.execute('UPDATE requests SET status=?, server_id=? '
                   'WHERE request_id=?',
                   (executor.RequestStatus.RUNNING.value, srv, rid))

    n = executor.requeue_stale_requests(stale_after=30)
    assert n == 1
    assert executor.get_request(rid_dead)['status'] == \
        executor.RequestStatus.PENDING
    assert executor.get_request(rid_dead)['server_id'] is None
    assert executor.get_request(rid_live)['status'] == \
        executor.RequestStatus.RUNNING
    assert executor.get_request(rid_pending)['status'] == \
        executor.RequestStatus.PENDING


def test_cancel_peer_request_does_not_touch_local_pids(exec_state):
    """Cancelling a request owned by ANOTHER replica marks the row
    (the owner's loop kills its own process) without signalling a
    same-numbered local pid."""
    from skypilot_tpu.server.requests import executor
    rid = executor.schedule_request('r', 'noop', {})
    executor._db().execute(
        'UPDATE requests SET status=?, server_id=?, pid=? '
        'WHERE request_id=?',
        (executor.RequestStatus.RUNNING.value, 'peer-srv', os.getpid(),
         rid))
    killed = []
    from skypilot_tpu.utils import subprocess_utils
    orig = subprocess_utils.kill_process_tree
    subprocess_utils.kill_process_tree = lambda pid: killed.append(pid)
    try:
        assert executor.cancel_request(rid) is True
    finally:
        subprocess_utils.kill_process_tree = orig
    assert killed == []  # our pid belongs to US, not the peer's worker
    assert executor.get_request(rid)['status'] == \
        executor.RequestStatus.CANCELLED


def test_advisory_lock_exclusive_and_released(tmp_path):
    from skypilot_tpu.utils import db_utils
    a = db_utils.AdvisoryLock('daemons', str(tmp_path))
    b = db_utils.AdvisoryLock('daemons', str(tmp_path))
    assert a.try_acquire() is True
    assert a.try_acquire() is True   # idempotent while held
    assert b.try_acquire() is False
    a.release()
    assert b.try_acquire() is True
    b.release()


def test_daemons_only_leader_runs(tmp_path, monkeypatch):
    from skypilot_tpu.server import daemons as daemons_lib
    from skypilot_tpu.utils import db_utils
    calls = {'a': 0, 'b': 0}
    monkeypatch.setattr(daemons_lib, '_refresh_cluster_status',
                        lambda: None)
    monkeypatch.setattr(daemons_lib, '_sweep_controllers', lambda: None)

    def make(tag):
        d = daemons_lib.ServerDaemons(
            status_interval=0.1, liveness_interval=3600,
            gc_interval=3600, stale_requeue_interval=3600, poll=0.03,
            leader_lock=db_utils.AdvisoryLock('d', str(tmp_path)))
        d._jobs[0][2] = lambda: calls.__setitem__(tag, calls[tag] + 1)
        return d

    d1, d2 = make('a'), make('b')
    d1.start()
    time.sleep(0.3)  # d1 takes leadership
    d2.start()
    try:
        time.sleep(1.0)
        assert calls['a'] >= 2
        assert calls['b'] == 0       # non-leader never ran a job
        d1.stop()
        d1._leader_lock.release()
        deadline = time.time() + 5
        while time.time() < deadline and calls['b'] < 1:
            time.sleep(0.05)
        assert calls['b'] >= 1       # leadership failed over
    finally:
        d1.stop()
        d2.stop()


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(('127.0.0.1', 0))
        return s.getsockname()[1]


@pytest.mark.slow
@pytest.mark.e2e
def test_two_server_failover_chaos(tmp_path):
    """Kill one of two replicas mid-request: the survivor's stale
    sweep re-queues the in-flight request and reruns it to completion
    — the client's original request_id resolves SUCCEEDED."""
    home = str(tmp_path / 'home')
    env = dict(os.environ)
    env.update({
        'SKYPILOT_TPU_HOME': home,
        'PYTHONPATH': f"{_REPO}:{os.path.join(_REPO, 'tests', 'unit_tests')}"
                      f":{env.get('PYTHONPATH', '')}",
        # Tight multi-replica timings; periodic jobs that would touch
        # clusters/controllers are disabled.
        'SKYPILOT_STATUS_REFRESH_INTERVAL': '0',
        'SKYPILOT_LIVENESS_SWEEP_INTERVAL': '0',
        'SKYPILOT_REQUEST_GC_INTERVAL': '0',
        'SKYPILOT_STALE_REQUEUE_INTERVAL': '1',
        'SKYPILOT_STALE_AFTER': '6',
    })
    ports = [_free_port(), _free_port()]
    procs = []
    try:
        for port in ports:
            procs.append(subprocess.Popen(
                [sys.executable, '-m', 'skypilot_tpu.server.server',
                 '--port', str(port)],
                cwd=_REPO, env=env, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True))
        for port, proc in zip(ports, procs):
            deadline = time.time() + 60
            while time.time() < deadline:
                try:
                    urllib.request.urlopen(
                        f'http://127.0.0.1:{port}/api/health', timeout=2)
                    break
                except OSError:
                    assert proc.poll() is None, proc.stdout.read()
                    time.sleep(0.5)

        # Schedule the slow request into the SHARED request DB (the
        # same sqlite file both replicas claim from).
        ins = subprocess.run(
            [sys.executable, '-c',
             'from skypilot_tpu.server.requests import executor;'
             "print(executor.schedule_request('slow', "
             "'_multi_server_entrypoints.slow_echo', "
             "{'seconds': 8, 'value': 'survived'}))"],
            cwd=_REPO, env=env, capture_output=True, text=True,
            timeout=60)
        assert ins.returncode == 0, ins.stdout + ins.stderr
        rid = ins.stdout.strip().splitlines()[-1]

        def get_req(port, timeout=0.2):
            # timeout=0 would make api_get block until terminal —
            # the poll needs to OBSERVE the RUNNING state.
            with urllib.request.urlopen(
                    f'http://127.0.0.1:{port}/api/get?request_id={rid}'
                    f'&timeout={timeout}', timeout=30) as r:
                return json.loads(r.read())

        # Wait until one replica claimed + started it.
        deadline = time.time() + 60
        owner = None
        while time.time() < deadline:
            rec = get_req(ports[0])
            if rec['status'] == 'RUNNING':
                owner = rec.get('server_id')
                break
            assert rec['status'] == 'PENDING', rec
            time.sleep(0.3)
        assert owner, 'request never claimed'
        victim = next(i for i, port in enumerate(ports)
                      if owner.endswith(f':{port}'))
        survivor = ports[1 - victim]

        # SIGKILL the owner AND its worker process (no drain — the
        # pod/host-death case; a worker is its own process group, so
        # killing just the server would leave it to finish the
        # request as an orphan, which is the SOFT-crash case).
        procs[victim].send_signal(signal.SIGKILL)
        procs[victim].wait(timeout=10)
        pid_q = subprocess.run(
            [sys.executable, '-c',
             'from skypilot_tpu.server.requests import executor;'
             f"print(executor.get_request('{rid}')['pid'])"],
            cwd=_REPO, env=env, capture_output=True, text=True,
            timeout=60)
        worker_pid = int(pid_q.stdout.strip().splitlines()[-1])
        if worker_pid > 0:
            import contextlib
            with contextlib.suppress(ProcessLookupError):
                # Already gone is fine (died with the server, or the
                # request finished under a slow, loaded host).
                os.kill(worker_pid, signal.SIGKILL)

        # The survivor re-queues (heartbeat stale after 6s), re-claims
        # and reruns; the ORIGINAL request id resolves SUCCEEDED.
        deadline = time.time() + 90
        rec = None
        while time.time() < deadline:
            rec = get_req(survivor, timeout=5)
            if rec['status'] in ('SUCCEEDED', 'FAILED', 'CANCELLED'):
                break
        assert rec and rec['status'] == 'SUCCEEDED', rec
        assert rec['return_value'] == 'survived'
        assert rec['server_id'].endswith(f':{survivor}')
    finally:
        for proc in procs:
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=10)


@pytest.mark.slow
@pytest.mark.e2e
def test_request_log_streams_from_owning_replica(tmp_path):
    """Request logs are replica-local files: a replica that does NOT
    have the file proxies /api/stream from the owner (server_id is
    host:port). Simulated by giving replica B a request row whose
    log_path does not exist locally and whose server_id names A."""
    env_base = {
        'PYTHONPATH': f"{_REPO}:"
                      f"{os.path.join(_REPO, 'tests', 'unit_tests')}:"
                      f"{os.environ.get('PYTHONPATH', '')}",
        'SKYPILOT_STATUS_REFRESH_INTERVAL': '0',
        'SKYPILOT_LIVENESS_SWEEP_INTERVAL': '0',
        'SKYPILOT_REQUEST_GC_INTERVAL': '0',
        'SKYPILOT_STALE_REQUEUE_INTERVAL': '0',
    }
    homes = [str(tmp_path / 'a'), str(tmp_path / 'b')]
    ports = [_free_port(), _free_port()]
    procs = []
    try:
        for home, port in zip(homes, ports):
            env = {**os.environ, **env_base, 'SKYPILOT_TPU_HOME': home}
            procs.append(subprocess.Popen(
                [sys.executable, '-m', 'skypilot_tpu.server.server',
                 '--port', str(port)],
                cwd=_REPO, env=env, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True))
        for port, proc in zip(ports, procs):
            deadline = time.time() + 120
            while time.time() < deadline:
                try:
                    urllib.request.urlopen(
                        f'http://127.0.0.1:{port}/api/health',
                        timeout=2)
                    break
                except OSError:
                    assert proc.poll() is None, proc.stdout.read()
                    time.sleep(0.5)

        # Run a real request on A (its log lands in A's home).
        env_a = {**os.environ, **env_base,
                 'SKYPILOT_TPU_HOME': homes[0]}
        ins = subprocess.run(
            [sys.executable, '-c',
             'from skypilot_tpu.server.requests import executor;'
             "print(executor.schedule_request('chk', "
             "'_multi_server_entrypoints.chatty', "
             "{'message': 'from-replica-a'}))"],
            cwd=_REPO, env=env_a, capture_output=True, text=True,
            timeout=60)
        assert ins.returncode == 0, ins.stdout + ins.stderr
        rid = ins.stdout.strip().splitlines()[-1]
        deadline = time.time() + 90
        while time.time() < deadline:
            with urllib.request.urlopen(
                    f'http://127.0.0.1:{ports[0]}/api/get?request_id='
                    f'{rid}&timeout=5', timeout=30) as r:
                rec = json.loads(r.read())
            if rec['status'] in ('SUCCEEDED', 'FAILED'):
                break
        assert rec['status'] == 'SUCCEEDED', rec
        owner = rec['server_id']

        # Replant the row in B's DB with A as owner and a log path
        # that does not exist on "B" — the cross-host shape.
        plant = subprocess.run(
            [sys.executable, '-c', f'''
from skypilot_tpu.server.requests import executor
rid = executor.schedule_request('chk', 'noop', {{}})
executor._db().execute(
    "UPDATE requests SET request_id=?, server_id=?, status=?, "
    "log_path=? WHERE request_id=?",
    ("{rid}", "{owner}", "SUCCEEDED", "/nonexistent/{rid}.log", rid))
print("ok")
'''],
            cwd=_REPO,
            env={**os.environ, **env_base,
                 'SKYPILOT_TPU_HOME': homes[1]},
            capture_output=True, text=True, timeout=60)
        assert plant.returncode == 0, plant.stdout + plant.stderr

        # Streaming from B transparently serves A's log content.
        with urllib.request.urlopen(
                f'http://127.0.0.1:{ports[1]}/api/stream?request_id='
                f'{rid}&follow=0', timeout=30) as r:
            body = r.read().decode()
        assert 'chatty says: from-replica-a' in body, body
        with urllib.request.urlopen(
                f'http://127.0.0.1:{ports[0]}/api/stream?request_id='
                f'{rid}&follow=0', timeout=30) as r:
            direct = r.read().decode()
        assert body == direct
    finally:
        for proc in procs:
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=10)
