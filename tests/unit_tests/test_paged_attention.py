"""Paged KV-cache attention: reference semantics vs dense attention.

The pallas kernel is TPU-only; on CPU the XLA reference defines the
semantics. These tests prove the paged layout (scattered pages, page
tables, per-row lengths) computes EXACTLY what dense causal decode
attention computes, including GQA and non-contiguous page assignment.
"""
import jax
import jax.numpy as jnp
import numpy as np

from skypilot_tpu.ops import paged_attention as pa

PAGE = 8
PAGES_PER_SEQ = 4
TOTAL_PAGES = 32
HKV, HQ, D = 2, 4, 16


def _dense_reference(q, k_hist, v_hist, lengths):
    """q: [B,H,D]; k/v_hist: [B,T,Hkv,D] (valid up to lengths[b])."""
    rep = q.shape[1] // k_hist.shape[2]
    k = jnp.repeat(k_hist, rep, axis=2)
    v = jnp.repeat(v_hist, rep, axis=2)
    s = jnp.einsum('bhd,bkhd->bhk', q.astype(jnp.float32),
                   k.astype(jnp.float32)) / (D ** 0.5)
    mask = (jnp.arange(k.shape[1])[None, :] < lengths[:, None])[:, None]
    s = jnp.where(mask, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum('bhk,bkhd->bhd', p, v.astype(jnp.float32))


def _build_paged(k_hist, v_hist, lengths, rng):
    """Scatter dense history into RANDOMLY-ordered physical pages."""
    batch, max_len = k_hist.shape[0], k_hist.shape[1]
    assert max_len == PAGES_PER_SEQ * PAGE
    perm = np.asarray(rng.permutation(TOTAL_PAGES))
    page_indices = perm[:batch * PAGES_PER_SEQ].reshape(
        batch, PAGES_PER_SEQ)
    k_pages = np.zeros((HKV, TOTAL_PAGES, PAGE, D), np.float32)
    v_pages = np.zeros((HKV, TOTAL_PAGES, PAGE, D), np.float32)
    for b in range(batch):
        for t in range(int(lengths[b])):
            phys = page_indices[b, t // PAGE]
            k_pages[:, phys, t % PAGE] = np.asarray(k_hist[b, t])
            v_pages[:, phys, t % PAGE] = np.asarray(v_hist[b, t])
    return (jnp.asarray(k_pages), jnp.asarray(v_pages),
            jnp.asarray(page_indices, jnp.int32))


def _rand(shape, key):
    return jax.random.normal(jax.random.PRNGKey(key), shape, jnp.float32)


def test_paged_matches_dense_varied_lengths():
    batch, max_len = 4, PAGES_PER_SEQ * PAGE
    q = _rand((batch, HQ, D), 0)
    k_hist = _rand((batch, max_len, HKV, D), 1)
    v_hist = _rand((batch, max_len, HKV, D), 2)
    lengths = jnp.asarray([1, 7, 20, 32], jnp.int32)  # cross-page mix
    rng = np.random.default_rng(0)
    k_pages, v_pages, page_indices = _build_paged(k_hist, v_hist,
                                                  lengths, rng)
    out = pa.paged_decode_attention(q, k_pages, v_pages, lengths,
                                    page_indices)
    ref = _dense_reference(q, k_hist, v_hist, lengths)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5)


def test_write_kv_then_attend_matches_dense_decode():
    """Simulate real decode: write_kv each step, attend, compare with
    the dense cached_decode path at every step."""
    batch = 3
    k_pages, v_pages = pa.init_pages(HKV, TOTAL_PAGES, PAGE, D,
                                     jnp.float32)
    alloc = pa.PageAllocator(TOTAL_PAGES, PAGES_PER_SEQ)
    page_indices = np.zeros((batch, PAGES_PER_SEQ), np.int32)
    owned = []
    for b in range(batch):
        pages = alloc.allocate(PAGES_PER_SEQ)
        owned.append(pages)
        page_indices[b] = pages
    page_indices = jnp.asarray(page_indices)

    steps = 2 * PAGE + 3  # crosses two page boundaries
    max_len = PAGES_PER_SEQ * PAGE
    k_hist = np.zeros((batch, max_len, HKV, D), np.float32)
    v_hist = np.zeros((batch, max_len, HKV, D), np.float32)
    for t in range(steps):
        q = _rand((batch, HQ, D), 100 + t)
        k_new = _rand((batch, HKV, D), 200 + t)
        v_new = _rand((batch, HKV, D), 300 + t)
        positions = jnp.full((batch,), t, jnp.int32)
        k_pages, v_pages = pa.write_kv(k_pages, v_pages, k_new, v_new,
                                       positions, page_indices)
        k_hist[:, t] = np.asarray(k_new)
        v_hist[:, t] = np.asarray(v_new)
        lengths = jnp.full((batch,), t + 1, jnp.int32)
        out = pa.paged_decode_attention(q, k_pages, v_pages, lengths,
                                        page_indices)
        ref = _dense_reference(q, jnp.asarray(k_hist),
                               jnp.asarray(v_hist), lengths)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5, err_msg=f'step {t}')


def test_rows_at_different_depths():
    """Continuous batching: rows write at DIFFERENT positions in one
    step (the per-row positions contract)."""
    batch = 2
    k_pages, v_pages = pa.init_pages(HKV, TOTAL_PAGES, PAGE, D,
                                     jnp.float32)
    page_indices = jnp.asarray([[0, 1, 2, 3], [4, 5, 6, 7]], jnp.int32)
    positions = jnp.asarray([2, PAGE + 1], jnp.int32)  # different pages
    k_new = _rand((batch, HKV, D), 1)
    v_new = _rand((batch, HKV, D), 2)
    k_pages, v_pages = pa.write_kv(k_pages, v_pages, k_new, v_new,
                                   positions, page_indices)
    # Row 0's token landed in physical page 0 slot 2:
    np.testing.assert_allclose(np.asarray(k_pages[:, 0, 2]),
                               np.asarray(k_new[0]), atol=0)
    # Row 1's token landed in physical page 5 slot 1:
    np.testing.assert_allclose(np.asarray(k_pages[:, 5, 1]),
                               np.asarray(k_new[1]), atol=0)


def test_allocator_lifecycle():
    alloc = pa.PageAllocator(total_pages=8, pages_per_seq=4)
    a = alloc.allocate(3)
    b = alloc.allocate(5)
    assert sorted(a + b) == list(range(8))
    assert not alloc.can_allocate(1)
    try:
        alloc.allocate(1)
        raise AssertionError('expected MemoryError')
    except MemoryError:
        pass
    alloc.release(a)
    assert alloc.free_pages == 3
    assert alloc.pages_needed(17, PAGE) == 3
    assert alloc.pages_needed(16, PAGE) == 2
    assert alloc.pages_needed(1, PAGE) == 1
