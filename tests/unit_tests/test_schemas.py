"""Schema-validation layer: actionable errors at the API boundary
(reference: sky/utils/schemas.py)."""
import pytest

from skypilot_tpu import exceptions
from skypilot_tpu import task as task_lib
from skypilot_tpu.utils import schemas


def test_valid_task_passes():
    schemas.validate_task_config({
        'name': 't', 'run': 'echo hi', 'num_nodes': 2,
        'resources': {'accelerators': 'tpu-v5e-8', 'use_spot': True},
        'volumes': {'/data': 'vol1'},
        'service': {'replica_policy': {'min_replicas': 1,
                                       'max_replicas': 3,
                                       'target_qps_per_replica': 2.5}},
    })


def test_typo_field_gets_hint():
    with pytest.raises(exceptions.InvalidTaskYAMLError) as e:
        task_lib.Task.from_yaml_config({'run': 'x',
                                        'accelerator': 'tpu-v5e-8'})
    msg = str(e.value)
    assert 'accelerator' in msg and "did you mean 'accelerators'?" in msg


def test_error_names_the_path():
    with pytest.raises(exceptions.InvalidTaskYAMLError) as e:
        schemas.validate_task_config({
            'resources': {'any_of': [{'use_spot': 'yes-please'}]}})
    msg = str(e.value)
    assert 'resources.any_of.0.use_spot' in msg
    assert 'boolean' in msg


def test_wrong_type_rejected_before_parse():
    with pytest.raises(exceptions.InvalidTaskYAMLError) as e:
        task_lib.Task.from_yaml_config({'run': 'x', 'num_nodes': 'two'})
    assert 'num_nodes' in str(e.value)


def test_volumes_shape_checked():
    with pytest.raises(exceptions.InvalidTaskYAMLError):
        schemas.validate_task_config({'volumes': {'/data': 5}})


def test_config_schema_rejects_unknown_section(tmp_path, monkeypatch):
    from skypilot_tpu import sky_config
    bad = tmp_path / 'bad.yaml'
    bad.write_text('gpc:\n  project_id: x\n')  # typo'd section
    monkeypatch.setenv('SKYPILOT_TPU_CONFIG', str(bad))
    with pytest.raises(ValueError) as e:
        sky_config.get_nested(('gcp', 'project_id'))
    assert 'gpc' in str(e.value)
