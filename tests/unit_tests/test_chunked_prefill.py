"""Stall-free serving scheduler: chunked prefill under a token budget
+ one-step host/device decode pipelining (models/batching.py).

Contracts under test:
  (a) a long prompt's prefill splits into >= 2 fixed-size chunks with
      decode steps interleaved between them (no whole-prompt stall);
  (b) per-iteration prefill work never exceeds the configured token
      budget;
  (c) chunked prefill composes with prefix-cache partial hits and
      with page-pressure preemption;
  (d) pipelined decode is token-for-token identical to the
      unpipelined loop at temperature 0 — and chunked prefill is
      bit-identical to the legacy whole-prompt prefill path (paged
      AND dense).

The deterministic tests drive the scheduler by hand (engine.stop()
right after construction kills the scheduler thread, the same idiom
as test_spec_batching's cancel-sweep test), so chunk/decode
interleaving is observable step by step instead of raced.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import flax.linen as nn

from skypilot_tpu.models.batching import ContinuousBatchingEngine


@pytest.fixture(scope='module')
def llama_tiny():
    from skypilot_tpu.models.llama import Llama, LlamaConfig
    cfg = LlamaConfig.tiny(dtype=jnp.float32, kv_page_size=8,
                           kv_total_pages=40)
    model = Llama(cfg)
    params = nn.meta.unbox(model.init(
        jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))['params'])
    return model, params


PROMPTS = [
    [5, 9, 2, 5, 9, 2, 5, 9],
    [3, 3, 3, 3],
    [17, 41, 7, 29, 23, 5],
]
LONG_PROMPT = list(range(2, 42))        # 40 tokens = 5 chunks of 8


def _drain(eng):
    futs = [eng.submit(p, max_new_tokens=8) for p in PROMPTS]
    return [f.result(timeout=300) for f in futs]


# -- (a) chunk splitting + interleaving (hand-driven scheduler) ----------


def test_long_prompt_prefills_in_chunks_with_decode_interleaved(
        llama_tiny):
    model, params = llama_tiny
    eng = ContinuousBatchingEngine(model, params, num_slots=2,
                                   max_total_len=96,
                                   prefill_chunk=8,
                                   pipeline_decode=False)
    eng.stop()  # freeze the scheduler thread: we drive it by hand
    short = [5, 9, 2, 17]
    f_short = eng.submit(short, max_new_tokens=16)
    f_long = eng.submit(LONG_PROMPT, max_new_tokens=4)
    assert eng._admit()
    # Both slots admitted: short first (FCFS), both PREFILLING, no
    # device work yet.
    assert eng.prefilling.sum() == 2 and not eng.active.any()
    assert eng.prefill_backlog_tokens() == len(short) + len(LONG_PROMPT)

    # Iteration 1: the budget (= one 8-token chunk) covers the short
    # prompt only; the long prompt hasn't started.
    eng._prefill_work()
    assert eng.active[0] and not eng.active[1]
    assert eng.last_prefill_tokens == len(short)
    assert int(eng.prefill_frontier[1]) == 0

    # Drive iterations: each runs ONE 8-token chunk of the long
    # prompt, and the short prompt's decode commits tokens BETWEEN
    # chunks — the stall-free property.
    chunk_ends = []
    generated_between = []
    while eng.prefilling[1]:
        before = len(eng.outputs[0]) - len(short)
        eng._prefill_work()
        eng._decode_step()
        chunk_ends.append(int(eng.prefill_frontier[1]))
        generated_between.append(len(eng.outputs[0]) - len(short) -
                                 before)
    assert chunk_ends == [8, 16, 24, 32, 40]    # 5 chunks, >= 2
    # Decode made progress during every gap between chunks.
    assert all(g >= 1 for g in generated_between)
    assert eng.prefill_chunks_run >= 6          # 1 short + 5 long
    assert eng.prefill_backlog_tokens() == 0
    # Both requests complete when the loop keeps running.
    while eng.active.any():
        eng._decode_step()
    assert f_short.result(timeout=5)[:len(short)] == short
    long_out = f_long.result(timeout=5)
    assert long_out[:len(LONG_PROMPT)] == LONG_PROMPT
    assert len(long_out) == len(LONG_PROMPT) + 4


# -- (b) token-budget accounting ----------------------------------------


def test_prefill_budget_is_never_exceeded(llama_tiny):
    model, params = llama_tiny
    eng = ContinuousBatchingEngine(model, params, num_slots=4,
                                   max_total_len=96,
                                   prefill_chunk=8, prefill_budget=12,
                                   pipeline_decode=False)
    eng.stop()
    futs = [eng.submit(list(range(2, 2 + n)), max_new_tokens=2)
            for n in (20, 24, 28, 16)]
    eng._admit()
    total = sum((20, 24, 28, 16))
    spent = 0
    iterations = 0
    while any(eng.prefilling):
        eng._prefill_work()
        # THE budget contract: no iteration runs more prefill tokens
        # than configured.
        assert eng.last_prefill_tokens <= 12
        spent += eng.last_prefill_tokens
        eng._decode_step()
        iterations += 1
        assert iterations < 100
    assert spent == total  # every suffix token ran exactly once
    while eng.active.any():
        eng._decode_step()
    for f, n in zip(futs, (20, 24, 28, 16)):
        assert len(f.result(timeout=5)) == n + 2

    with pytest.raises(ValueError, match='prefill_budget'):
        ContinuousBatchingEngine(model, params, max_total_len=96,
                                 prefill_chunk=16, prefill_budget=8)


# -- (c) composition: prefix cache + page pressure -----------------------


def test_chunked_prefill_composes_with_prefix_cache(llama_tiny):
    """Partial prefix-cache hits leave a mid-prompt offset; chunked
    prefill must resume exactly there with identical outputs and the
    same hit/miss accounting as the whole-suffix path."""
    model, params = llama_tiny
    sys_prompt = list(range(2, 34))     # 4 full 8-token pages

    def run(**kw):
        eng = ContinuousBatchingEngine(model, params, num_slots=4,
                                       max_total_len=96, **kw)
        assert eng.paged and eng.prefix_cache is not None
        outs = []
        for extra in ([40, 41], [50, 51, 52], [60], [40, 41, 99]):
            outs.append(eng.submit(sys_prompt + extra,
                                   max_new_tokens=6).result(timeout=300))
        stats = (eng.prefix_cache.hits, eng.prefix_cache.misses)
        eng.stop()
        return outs, stats

    legacy, legacy_stats = run(prefill_chunk=0, pipeline_decode=False)
    chunked, chunked_stats = run(prefill_chunk=8)
    assert chunked == legacy
    assert chunked_stats == legacy_stats == (12, 4)


def test_chunked_prefill_composes_with_page_pressure():
    """A pool too small for all slots still serves every request with
    chunked prefill on: preemption re-queues and re-prefills (now in
    chunks) instead of failing."""
    from skypilot_tpu.models.llama import Llama, LlamaConfig
    cfg = LlamaConfig.tiny(dtype=jnp.float32, kv_page_size=4,
                           kv_total_pages=16)
    model = Llama(cfg)
    params = nn.meta.unbox(model.init(
        jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))['params'])
    eng = ContinuousBatchingEngine(model, params, num_slots=3,
                                   max_total_len=28, prefill_chunk=4)
    assert eng.paged
    try:
        futs = [eng.submit(p, max_new_tokens=18) for p in PROMPTS]
        rows = [f.result(timeout=300) for f in futs]
    finally:
        eng.stop()
    for p, row in zip(PROMPTS, rows):
        assert row[:len(p)] == p
        assert len(row) == len(p) + 18
    assert eng.preemptions >= 1     # the pool really was too small


# -- (d) output identity --------------------------------------------------


@pytest.mark.parametrize('paged', [None, False])
def test_pipelined_decode_identical_to_unpipelined(llama_tiny, paged):
    model, params = llama_tiny

    def run(pipeline):
        eng = ContinuousBatchingEngine(model, params, num_slots=2,
                                       max_total_len=64, paged=paged,
                                       pipeline_decode=pipeline)
        assert eng.pipeline_decode is pipeline
        try:
            return _drain(eng)
        finally:
            eng.stop()

    assert run(True) == run(False)


@pytest.mark.parametrize('paged', [None, False])
def test_chunked_prefill_identical_to_whole_prompt(llama_tiny, paged):
    """Acceptance: temperature-0 outputs are bit-identical between the
    legacy whole-prompt prefill and chunked prefill, on the paged AND
    dense cache paths (dense exercises the new _dense_suffix_fn)."""
    model, params = llama_tiny
    prompts = PROMPTS + [LONG_PROMPT]

    def run(**kw):
        eng = ContinuousBatchingEngine(model, params, num_slots=2,
                                       max_total_len=64, paged=paged,
                                       **kw)
        try:
            futs = [eng.submit(p, max_new_tokens=6) for p in prompts]
            return [f.result(timeout=300) for f in futs]
        finally:
            eng.stop()

    whole = run(prefill_chunk=0, pipeline_decode=False)
    for chunk in (8, 16):
        assert run(prefill_chunk=chunk) == whole


def test_pipeline_rejects_multi_token_decode_modes(llama_tiny):
    model, params = llama_tiny
    with pytest.raises(ValueError, match='pipeline_decode'):
        ContinuousBatchingEngine(model, params, max_total_len=48,
                                 speculative_k=2, pipeline_decode=True)
    with pytest.raises(ValueError, match='pipeline_decode'):
        ContinuousBatchingEngine(model, params, max_total_len=48,
                                 decode_chunk=4, pipeline_decode=True)
    # Auto mode: pipelining turns itself off for those engines.
    eng = ContinuousBatchingEngine(model, params, max_total_len=48,
                                   speculative_k=2)
    assert eng.pipeline_decode is False
    eng.stop()
    eng = ContinuousBatchingEngine(model, params, max_total_len=48)
    assert eng.pipeline_decode is True
    eng.stop()


def test_cancel_mid_prefill_resolves_with_prompt(llama_tiny):
    """A request cancelled while still PREFILLING resolves with its
    prompt, frees the slot, and never poisons the prefix cache with
    half-written pages."""
    model, params = llama_tiny
    eng = ContinuousBatchingEngine(model, params, num_slots=2,
                                   max_total_len=96, prefill_chunk=8,
                                   pipeline_decode=False)
    eng.stop()
    fut = eng.submit(LONG_PROMPT, max_new_tokens=4)
    eng._admit()
    eng._prefill_work()                  # one 8-token chunk only
    assert eng.prefilling[0] and not eng.active[0]
    eng.cancel([fut])
    eng._apply_cancellations()
    assert fut.result(timeout=5) == LONG_PROMPT
    assert not eng.prefilling[0] and not eng.active[0]
    assert not eng._prefill_order
    # Half-prefilled prompt pages were NOT promoted into the cache.
    assert len(eng.prefix_cache.by_key) == 0
    # The slot serves a fresh request end to end.
    fut2 = eng.submit(PROMPTS[0], max_new_tokens=3)
    eng._admit()
    eng._prefill_work()
    while eng.active.any():
        eng._decode_step()
    assert len(fut2.result(timeout=5)) == len(PROMPTS[0]) + 3
