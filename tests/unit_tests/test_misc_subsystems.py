"""Smaller subsystems: spot placer, queue autoscaler, usage, volumes,
workspaces, recipes, config layering, timeline."""
import json
import os
import time

import pytest

from skypilot_tpu.serve.spot_placer import DynamicFallbackSpotPlacer


def test_spot_placer_avoids_hot_locations():
    locations = [('gcp', 'us-east5', 'us-east5-a'),
                 ('gcp', 'us-central2', 'us-central2-b'),
                 ('gcp', 'europe-west4', 'europe-west4-b')]
    placer = DynamicFallbackSpotPlacer(locations)
    first = placer.select(now=0)
    placer.handle_active(first)
    placer.handle_preemption(first)
    nxt = placer.select(now=time.time())
    assert nxt != first
    assert not placer.all_hot()
    for loc in locations:
        placer.handle_preemption(loc)
    assert placer.all_hot()
    # Still returns *something* (caller decides on-demand fallback).
    assert placer.select() in locations


def test_queue_length_autoscaler():
    from skypilot_tpu.serve.autoscalers import (
        AutoscalerDecisionOperator, QueueLengthAutoscaler)
    from skypilot_tpu.serve.service_spec import SkyServiceSpec
    spec = SkyServiceSpec(min_replicas=1, max_replicas=5,
                          target_qps_per_replica=1,
                          upscale_delay_seconds=0,
                          downscale_delay_seconds=0)
    a = QueueLengthAutoscaler(spec, target_queue_per_replica=2)
    a.collect_request_information(10)
    d = a.evaluate(num_ready=1, num_launching=0, now=100)
    assert d.operator == AutoscalerDecisionOperator.SCALE_UP
    assert a.target_num_replicas == 5
    for _ in range(10):
        a.request_done()
    d = a.evaluate(num_ready=5, num_launching=0, now=200)
    assert d.operator == AutoscalerDecisionOperator.SCALE_DOWN
    assert a.target_num_replicas == 1


def test_usage_records_redacted_events(isolated_state, monkeypatch):
    monkeypatch.delenv('SKYPILOT_DISABLE_USAGE_COLLECTION', raising=False)
    from skypilot_tpu.usage import usage_lib
    with usage_lib.entrypoint('launch', cloud='gcp',
                              accelerator='tpu-v5e-16'):
        pass
    with pytest.raises(ValueError):
        with usage_lib.entrypoint('launch'):
            raise ValueError('secret path /home/x')
    path = os.path.join(isolated_state, 'usage', 'usage.jsonl')
    with open(path, 'r', encoding='utf-8') as f:
        events = [json.loads(line) for line in f]
    assert len(events) == 2
    assert events[0]['name'] == 'launch'
    assert events[1]['error'] == 'ValueError'
    # Redaction: the message (with its path) is NOT recorded.
    assert 'secret' not in json.dumps(events)


def test_usage_opt_out(isolated_state, monkeypatch):
    monkeypatch.setenv('SKYPILOT_DISABLE_USAGE_COLLECTION', '1')
    from skypilot_tpu.usage import usage_lib
    usage_lib.record_event('x')
    assert not os.path.exists(
        os.path.join(isolated_state, 'usage', 'usage.jsonl'))


def test_volumes_crud(isolated_state):
    """Registry CRUD on the Local provider (real backing dir; the GCP
    PD path is fake-API-tested in test_gce_provisioner)."""
    from skypilot_tpu import exceptions
    from skypilot_tpu.volumes import core as volumes_core
    vol = volumes_core.apply('data', 500, 'local')
    assert vol['status'] == 'READY' and os.path.isdir(vol['path'])
    rows = volumes_core.ls()
    assert rows[0]['name'] == 'data' and rows[0]['size_gb'] == 500
    volumes_core.delete('data')
    assert volumes_core.ls() == []
    assert not os.path.isdir(vol['path'])
    with pytest.raises(exceptions.SkyError):
        volumes_core.delete('data')


def test_workspaces(isolated_state, monkeypatch, tmp_path):
    cfg = tmp_path / 'cfg.yaml'
    cfg.write_text(
        'workspaces:\n'
        '  ml-team:\n'
        '    allowed_clouds: [GCP]\n')
    monkeypatch.setenv('SKYPILOT_TPU_CONFIG', str(cfg))
    from skypilot_tpu.workspaces import core as ws
    assert ws.active_workspace() == 'default'
    assert ws.allowed_clouds('default') is None
    assert ws.allowed_clouds('ml-team') == ['gcp']
    monkeypatch.setenv('SKYPILOT_WORKSPACE', 'ml-team')
    assert ws.active_workspace() == 'ml-team'
    import skypilot_tpu.exceptions as exc
    with pytest.raises(exc.SkyError):
        ws.get_workspace('nope')


def test_recipes_registry():
    from skypilot_tpu.recipes import core as recipes_core
    names = {r['name'] for r in recipes_core.list_recipes()}
    assert {'nanogpt', 'llama3_8b_fsdp', 'mixtral_ep',
            'managed_job_checkpoint'}.issubset(names)
    path = recipes_core.get_recipe_path('nanogpt')
    assert os.path.exists(path)
    with pytest.raises(FileNotFoundError):
        recipes_core.get_recipe_path('nope')


def test_config_layering(isolated_state, monkeypatch, tmp_path):
    from skypilot_tpu import sky_config
    server_cfg = os.path.join(isolated_state, 'config.yaml')
    os.makedirs(isolated_state, exist_ok=True)
    with open(server_cfg, 'w', encoding='utf-8') as f:
        f.write('gcp:\n  project_id: base\n  labels: {team: a}\n')
    user_cfg = tmp_path / 'user.yaml'
    user_cfg.write_text('gcp:\n  project_id: override\n')
    monkeypatch.setenv('SKYPILOT_TPU_CONFIG', str(user_cfg))
    assert sky_config.get_nested(('gcp', 'project_id')) == 'override'
    assert sky_config.get_nested(('gcp', 'labels')) == {'team': 'a'}
    with sky_config.override({'gcp': {'project_id': 'runtime'}}):
        assert sky_config.get_nested(('gcp', 'project_id')) == 'runtime'
    assert sky_config.get_nested(('gcp', 'project_id')) == 'override'


def test_timeline_tracing(tmp_path, monkeypatch):
    from skypilot_tpu.utils import timeline
    out = tmp_path / 'trace.json'
    monkeypatch.setattr(timeline, '_enabled_path', str(out))
    monkeypatch.setattr(timeline, '_events', [])

    @timeline.event
    def traced():
        time.sleep(0.01)

    traced()
    with timeline.Event('manual', 'note'):
        pass
    timeline.save()
    data = json.loads(out.read_text())
    names = {e['name'] for e in data['traceEvents']}
    assert any('traced' in n for n in names), names  # qualname form
    assert 'manual' in names


def test_user_registry(isolated_state):
    from skypilot_tpu.users import core as users_core
    users_core.record_request('alice')
    users_core.record_request('alice')
    users_core.record_request('bob')
    users_core.record_request('unknown')  # ignored
    rows = {r['name']: r for r in users_core.ls()}
    assert set(rows) == {'alice', 'bob'}
    assert rows['alice']['request_count'] == 2
    assert rows['alice']['role'] == 'user'
    users_core.set_role('alice', 'admin')
    rows = {r['name']: r for r in users_core.ls()}
    assert rows['alice']['role'] == 'admin'
    import pytest as _pytest
    with _pytest.raises(ValueError):
        users_core.set_role('bob', 'root')


def test_spot_autoscaler_mix_and_fallback():
    """SpotRequestRateAutoscaler splits the target into spot + on-demand
    (base floor + dynamic back-fill; reference autoscalers.py:933)."""
    from skypilot_tpu.serve import autoscalers
    from skypilot_tpu.serve import service_spec as spec_lib

    spec = spec_lib.SkyServiceSpec(
        min_replicas=4, max_replicas=4,
        base_ondemand_fallback_replicas=1,
        dynamic_ondemand_fallback=True,
        autoscaler='spot_request_rate')
    scaler = autoscalers.SpotRequestRateAutoscaler(spec)
    scaler.target_num_replicas = 4

    # Healthy: 3 spot up -> 3 spot + 1 base on-demand.
    mix = scaler.desired_mix(num_ready_spot=3)
    assert (mix.spot, mix.ondemand) == (3, 1)
    # Two spot replicas preempted -> back-fill with on-demand.
    mix = scaler.desired_mix(num_ready_spot=1)
    assert (mix.spot, mix.ondemand) == (3, 3)
    # Spot fully recovered -> back-fills retire, floor remains.
    mix = scaler.desired_mix(num_ready_spot=3)
    assert mix.ondemand == 1

    # Without dynamic fallback: floor only, no back-fill.
    spec2 = spec_lib.SkyServiceSpec(
        min_replicas=4, max_replicas=4,
        base_ondemand_fallback_replicas=2)
    scaler2 = autoscalers.SpotRequestRateAutoscaler(spec2)
    scaler2.target_num_replicas = 4
    mix = scaler2.desired_mix(num_ready_spot=0)
    assert (mix.spot, mix.ondemand) == (2, 2)


def test_instance_aware_lb_weights():
    """instance_aware LB sends traffic proportional to capacity."""
    from skypilot_tpu.serve.load_balancing_policies import (
        InstanceAwareLeastLoadPolicy)
    lb = InstanceAwareLeastLoadPolicy()
    lb.set_ready_replicas(['big:80', 'small:80'])
    lb.set_replica_weights({'big:80': 4.0, 'small:80': 1.0})
    picks = [lb.select_replica() for _ in range(10)]  # no completions
    # With 4x the capacity, 'big' should absorb ~4x the in-flight load.
    assert picks.count('big:80') == 8 and picks.count('small:80') == 2


def test_spot_placer_full_cycle_release():
    """handle_release frees capacity without marking preemption."""
    from skypilot_tpu.serve.spot_placer import DynamicFallbackSpotPlacer
    locs = [('gcp', 'us-central1', 'a'), ('gcp', 'us-central1', 'b')]
    placer = DynamicFallbackSpotPlacer(locs)
    first = placer.select()
    placer.handle_active(first)
    # Next selection balances onto the other location.
    second = placer.select()
    assert second != first
    placer.handle_release(first)
    assert not placer.all_hot()


def test_cross_cloud_transfer_plans():
    """Transfer planning (reference: sky/data/data_transfer.py:40-194):
    small jobs stream via CLI, big S3->GCS jobs become server-side
    Storage Transfer Service requests."""
    from skypilot_tpu.data import transfer as transfer_lib

    plan = transfer_lib.transfer('s3://src-b', 'gs://dst-b',
                                 size_gigabytes=1, run=False)
    assert plan['method'] == 'stream'
    assert 'gcloud storage rsync' in plan['command']

    plan = transfer_lib.transfer('s3://src-b', 'gs://dst-b',
                                 size_gigabytes=500, project_id='proj',
                                 run=False)
    assert plan['method'] == 'sts'
    body = plan['request_body']
    assert body['transferSpec']['awsS3DataSource']['bucketName'] == 'src-b'
    assert body['transferSpec']['gcsDataSink']['bucketName'] == 'dst-b'
    assert body['projectId'] == 'proj'

    # gs->s3 always streams (STS pulls INTO GCS only).
    plan = transfer_lib.transfer('gs://a', 's3://b', size_gigabytes=500,
                                 project_id='proj', run=False)
    assert plan['method'] == 'stream'

    import pytest as _pytest
    from skypilot_tpu import exceptions as exc
    with _pytest.raises(exc.StorageSpecError):
        transfer_lib.transfer('ftp://x', 'gs://y', run=False)


def test_s3_mount_commands():
    from skypilot_tpu.data import storage as storage_lib
    st = storage_lib.Storage(source='s3://datasets',
                             mode=storage_lib.StorageMode.MOUNT)
    cmd = storage_lib.mount_command(st, '/data')
    assert 'rclone mount' in cmd and ':s3,env_auth=true:datasets' in cmd
    cached = storage_lib.Storage(
        source='s3://datasets', mode=storage_lib.StorageMode.MOUNT_CACHED)
    cmd = storage_lib.mount_command(cached, '/data')
    assert '--vfs-cache-mode writes' in cmd


def test_azure_store_commands():
    from skypilot_tpu.data import storage as storage_lib
    st = storage_lib.Storage(source='az://ckpts',
                             mode=storage_lib.StorageMode.MOUNT)
    assert st.store == storage_lib.StoreType.AZURE
    cmd = storage_lib.mount_command(st, '/data')
    assert ':azureblob,env_auth=true:ckpts' in cmd
    copy = storage_lib.Storage(source='az://ckpts',
                               mode=storage_lib.StorageMode.COPY)
    cmd = storage_lib.mount_command(copy, '/data')
    assert 'az storage blob download-batch' in cmd
    # Sub-path urls: the az CLI takes a bare container name; the
    # sub-path must become a --pattern filter, not part of -s.
    sub = storage_lib.Storage(source='az://ckpts/run1',
                              mode=storage_lib.StorageMode.COPY)
    cmd = storage_lib.mount_command(sub, '/data')
    assert '-s ckpts ' in cmd and "--pattern 'run1/*'" in cmd


def test_r2_store_commands(monkeypatch):
    from skypilot_tpu.data import storage as storage_lib
    monkeypatch.setenv('R2_ACCOUNT_ID', 'acct123')
    st = storage_lib.Storage(source='r2://models',
                             mode=storage_lib.StorageMode.MOUNT)
    assert st.store == storage_lib.StoreType.R2
    cmd = storage_lib.mount_command(st, '/models')
    # rclone connection-string values with ':' must be quoted.
    assert 'endpoint="https://acct123.r2.cloudflarestorage.com"' in cmd
    copy = storage_lib.Storage(source='r2://models',
                               mode=storage_lib.StorageMode.COPY)
    cmd = storage_lib.mount_command(copy, '/models')
    assert '--endpoint-url' in cmd and 'aws s3 sync' in cmd
    # No hardcoded profile: env credentials by default, profile opt-in.
    assert '--profile' not in cmd


def test_r2_requires_account_id(monkeypatch):
    import pytest as _pytest
    from skypilot_tpu import exceptions as exc
    from skypilot_tpu.data import storage as storage_lib
    monkeypatch.delenv('R2_ACCOUNT_ID', raising=False)
    st = storage_lib.Storage(source='r2://models',
                             mode=storage_lib.StorageMode.MOUNT)
    with _pytest.raises(exc.StorageSpecError):
        storage_lib.mount_command(st, '/models')


def test_storage_yaml_roundtrip_new_stores():
    from skypilot_tpu.data import storage as storage_lib
    for url, store in (('az://c1', 'AZURE'), ('r2://b1', 'R2')):
        st = storage_lib.Storage.from_yaml_config({'source': url})
        assert st.store.value == store
        assert storage_lib.Storage.from_yaml_config(
            st.to_yaml_config()).bucket_url == url


def test_hf_store_download_only():
    from skypilot_tpu.data import storage as storage_lib
    st = storage_lib.Storage(source='hf://meta-llama/Llama-3-8B',
                             mode=storage_lib.StorageMode.COPY)
    assert st.store == storage_lib.StoreType.HF
    cmd = storage_lib.mount_command(st, '/models/llama')
    assert 'huggingface-cli download' in cmd
    assert 'meta-llama/Llama-3-8B' in cmd
    assert '--repo-type dataset' not in cmd

    ds = storage_lib.Storage(source='hf://datasets/allenai/c4',
                             mode=storage_lib.StorageMode.COPY)
    dcmd = storage_lib.mount_command(ds, '/data/c4')
    assert '--repo-type dataset' in dcmd and 'allenai/c4' in dcmd

    import pytest as _pytest
    from skypilot_tpu import exceptions as exc
    with _pytest.raises(exc.StorageSpecError):
        storage_lib.Storage(source='hf://org/model')  # MOUNT default
    with _pytest.raises(exc.StorageSpecError):
        storage_lib.Storage(name='only-name',
                            store=storage_lib.StoreType.HF,
                            mode=storage_lib.StorageMode.COPY)


def test_jobgroup_hosts_block_and_injection(isolated_state, monkeypatch,
                                            tmp_path):
    """The managed hosts block is idempotent (marker replacement) and
    lands in SKYPILOT_HOSTS_FILE when /etc/hosts is not the target."""
    from skypilot_tpu.jobs import groups, state

    jid_a = state.submit_job('actor', {'name': 'actor'}, 'failover', 0, 'u')
    jid_b = state.submit_job('learner', {'name': 'learner'}, 'failover',
                             0, 'u')
    db = groups._db()
    for jid in (jid_a, jid_b):
        db.execute('UPDATE managed_jobs SET job_group=? WHERE job_id=?',
                   ('rl', jid))
    groups.publish_address(jid_a, '10.0.0.5')
    groups.publish_address(jid_b, '10.0.0.9')

    block = groups.hosts_block('rl')
    assert '10.0.0.5 actor.rl actor' in block
    assert '10.0.0.9 learner.rl learner' in block

    hosts = tmp_path / 'hosts'
    hosts.write_text('127.0.0.1 localhost\n')
    monkeypatch.setenv('SKYPILOT_HOSTS_FILE', str(hosts))

    class FakeRunner:
        def run(self, cmd, require_outputs=False, **kw):
            import subprocess
            p = subprocess.run(['bash', '-c', cmd], capture_output=True,
                               text=True)
            return p.returncode, p.stdout, p.stderr

    class FakeHandle:
        def get_command_runners(self):
            return [FakeRunner()]

    landed = groups.install_hosts_entries(FakeHandle(), 'rl')
    # The env-var contract is the fixed absolute path (valid on every
    # host); the SKYPILOT_HOSTS_FILE target ALSO gets the block.
    assert landed == '/tmp/skypilot-jobgroup-rl.hosts'
    assert 'actor.rl' in open(landed, encoding='utf-8').read()
    content = hosts.read_text()
    assert content.startswith('127.0.0.1 localhost')
    assert content.count('actor.rl') == 1

    # Recovery republish: new IP replaces the block, no duplication.
    groups.publish_address(jid_a, '10.0.0.77')
    groups.install_hosts_entries(FakeHandle(), 'rl')
    content = hosts.read_text()
    assert '10.0.0.77 actor.rl actor' in content
    assert '10.0.0.5' not in content
    assert content.count('actor.rl') == 1
    assert content.count('localhost') == 1

    # Cleanup strips the block and the fixed-path file (pool workers
    # are reused; stale name->IP mappings must not leak).
    groups.remove_hosts_entries(FakeHandle(), 'rl')
    assert not os.path.exists(landed)
    after = hosts.read_text()
    assert 'actor.rl' not in after and 'localhost' in after


def test_instance_aware_autoscaler_mixed_fleet():
    """Mixed v5e+v5p fleet scales on NORMALIZED QPS (reference:
    sky/serve/autoscalers.py:605): capacity comes from the
    per-accelerator map, upscale sizes by the largest class, and
    downscale covers the load with the biggest replicas first."""
    from skypilot_tpu.serve.autoscalers import (
        Autoscaler, AutoscalerDecisionOperator,
        InstanceAwareRequestRateAutoscaler)
    from skypilot_tpu.serve.service_spec import SkyServiceSpec
    spec = SkyServiceSpec(min_replicas=1, max_replicas=10,
                          target_qps_per_replica={'tpu-v5e-8': 4.0,
                                                  'tpu-v5p-8': 10.0},
                          upscale_delay_seconds=0,
                          downscale_delay_seconds=0)
    a = Autoscaler.make(spec)
    assert isinstance(a, InstanceAwareRequestRateAutoscaler)
    assert a.capacity_of('tpu-v5e-8') == 4.0
    assert a.capacity_of('tpu-v5p-8') == 10.0
    assert a.capacity_of('unknown-hw') == 10.0  # best-known class

    # 17.5 QPS against one ready v5e (4 qps): overflow 13.5 sized by
    # the LARGEST class (10) -> +2 replicas above the current 1.
    now = 1000.0
    a.collect_request_information(
        int(17.5 * a._QPS_WINDOW_SECONDS), timestamp=now)
    d = a.evaluate(num_ready=1, num_launching=0, now=now,
                   ready_capacities=[4.0])
    assert d.operator == AutoscalerDecisionOperator.SCALE_UP
    assert a.target_num_replicas == 3

    # Same 17.5 QPS with [10, 4, 4, 4] ready: 10+4+4 > 17.5 -> 3
    # replicas cover it (largest first); the 4th is surplus.
    d = a.evaluate(num_ready=4, num_launching=0, now=now,
                   ready_capacities=[4.0, 10.0, 4.0, 4.0])
    assert d.operator == AutoscalerDecisionOperator.SCALE_DOWN
    assert a.target_num_replicas == 3

    # A uniform v5p fleet needs only 2 replicas for the same load.
    d = a.evaluate(num_ready=4, num_launching=0, now=now,
                   ready_capacities=[10.0, 10.0, 10.0, 10.0])
    assert a.target_num_replicas == 2

    # No ready replicas but live load: size by the largest class
    # (ceil(17.5/10) = 2), never stall at zero.
    d = a.evaluate(num_ready=0, num_launching=0, now=now,
                   ready_capacities=[])
    assert a.target_num_replicas == 2


def test_instance_aware_composes_with_spot_mix():
    """The instance-aware scaler inherits the spot floor/backfill mix
    (unified, where the reference keeps separate classes)."""
    from skypilot_tpu.serve.autoscalers import (
        Autoscaler, InstanceAwareRequestRateAutoscaler)
    from skypilot_tpu.serve.service_spec import SkyServiceSpec
    spec = SkyServiceSpec(min_replicas=2, max_replicas=8,
                          target_qps_per_replica={'tpu-v5e-8': 4.0},
                          base_ondemand_fallback_replicas=1,
                          dynamic_ondemand_fallback=True,
                          upscale_delay_seconds=0,
                          downscale_delay_seconds=0)
    a = Autoscaler.make(spec)
    assert isinstance(a, InstanceAwareRequestRateAutoscaler)
    a.target_num_replicas = 4
    mix = a.desired_mix(num_ready_spot=1)
    # 1 on-demand floor + (3 spot target - 1 ready) dynamic backfill.
    assert mix.spot == 3 and mix.ondemand == 3


def test_service_spec_qps_map_roundtrip_and_validation():
    import pytest as _pytest

    from skypilot_tpu import exceptions
    from skypilot_tpu.serve.service_spec import SkyServiceSpec
    spec = SkyServiceSpec.from_yaml_config({
        'readiness_probe': '/health',
        'replica_policy': {
            'min_replicas': 1, 'max_replicas': 4,
            'target_qps_per_replica': {'tpu-v5e-8': 4,
                                       'tpu-v5p-8': '10'},
        }})
    assert spec.target_qps_per_replica == {'tpu-v5e-8': 4.0,
                                           'tpu-v5p-8': 10.0}
    assert spec.autoscaling_enabled
    round_tripped = SkyServiceSpec.from_yaml_config(spec.to_yaml_config())
    assert round_tripped.target_qps_per_replica == \
        spec.target_qps_per_replica
    with _pytest.raises(exceptions.InvalidTaskYAMLError):
        SkyServiceSpec(target_qps_per_replica={'v5e': -1})
    with _pytest.raises(exceptions.InvalidTaskYAMLError):
        SkyServiceSpec(target_qps_per_replica={})


def test_instance_aware_no_ratchet_while_launching():
    """In-flight launches are credited at the largest-class capacity:
    repeated evaluations during a slow provision must NOT ratchet the
    target toward max_replicas."""
    from skypilot_tpu.serve.autoscalers import Autoscaler
    from skypilot_tpu.serve.service_spec import SkyServiceSpec
    spec = SkyServiceSpec(min_replicas=1, max_replicas=10,
                          target_qps_per_replica={'tpu-v5e-8': 4.0,
                                                  'tpu-v5p-8': 10.0},
                          upscale_delay_seconds=0,
                          downscale_delay_seconds=0)
    a = Autoscaler.make(spec)
    now = 1000.0
    a.collect_request_information(int(20 * a._QPS_WINDOW_SECONDS),
                                  timestamp=now)
    a.evaluate(num_ready=1, num_launching=0, now=now,
               ready_capacities=[4.0])
    first_target = a.target_num_replicas  # 1 + ceil(16/10) = 3
    assert first_target == 3
    # The two launches are now in flight; the target must hold.
    for _ in range(5):
        a.collect_request_information(0, timestamp=now)
        a.evaluate(num_ready=1, num_launching=2, now=now,
                   ready_capacities=[4.0])
    assert a.target_num_replicas == first_target


def test_group_name_validation(isolated_state):
    from skypilot_tpu import exceptions
    from skypilot_tpu.jobs import groups
    bad = "x'; rm -rf $HOME; echo '"
    with pytest.raises(exceptions.SkyError, match='hostname-safe'):
        groups.launch_group(bad, [{'name': 'a', 'run': 'true'}], user='u')
    with pytest.raises(exceptions.SkyError, match='hostname-safe'):
        groups.launch_group('ok', [{'name': 'has space', 'run': 'true'}],
                            user='u')


def test_hosts_markers_are_group_scoped(isolated_state, monkeypatch,
                                        tmp_path):
    """Two groups sharing one hosts file must not wipe each other."""
    from skypilot_tpu.jobs import groups, state
    for grp, nm, ip in (('g1', 'actor', '10.0.0.1'),
                        ('g2', 'worker', '10.0.0.2')):
        jid = state.submit_job(nm, {'name': nm}, 'failover', 0, 'u')
        groups._db().execute(
            'UPDATE managed_jobs SET job_group=? WHERE job_id=?',
            (grp, jid))
        groups.publish_address(jid, ip)

    hosts = tmp_path / 'hosts'
    hosts.write_text('127.0.0.1 localhost\n')
    monkeypatch.setenv('SKYPILOT_HOSTS_FILE', str(hosts))

    class FakeRunner:
        def run(self, cmd, require_outputs=False, **kw):
            import subprocess
            p = subprocess.run(['bash', '-c', cmd], capture_output=True,
                               text=True)
            return p.returncode, p.stdout, p.stderr

    class FakeHandle:
        def get_command_runners(self):
            return [FakeRunner()]

    groups.install_hosts_entries(FakeHandle(), 'g1')
    groups.install_hosts_entries(FakeHandle(), 'g2')
    content = hosts.read_text()
    assert 'actor.g1' in content and 'worker.g2' in content
    groups.remove_hosts_entries(FakeHandle(), 'g2')
    content = hosts.read_text()
    assert 'actor.g1' in content          # g1 untouched
    assert 'worker.g2' not in content
    os.path.exists(groups.hosts_file_path('g1')) and \
        os.remove(groups.hosts_file_path('g1'))


def test_hosts_markers_dotted_group_name(isolated_state, monkeypatch,
                                         tmp_path):
    """'.' is legal in group names and a regex wildcard: removing
    group 'a.b' must not strip group 'aXb''s managed block (the awk
    marker patterns escape ERE metacharacters)."""
    from skypilot_tpu.jobs import groups, state
    for grp, nm, ip in (('a.b', 'actor', '10.0.0.1'),
                        ('aXb', 'worker', '10.0.0.2')):
        jid = state.submit_job(nm, {'name': nm}, 'failover', 0, 'u')
        groups._db().execute(
            'UPDATE managed_jobs SET job_group=? WHERE job_id=?',
            (grp, jid))
        groups.publish_address(jid, ip)

    hosts = tmp_path / 'hosts'
    hosts.write_text('127.0.0.1 localhost\n')
    monkeypatch.setenv('SKYPILOT_HOSTS_FILE', str(hosts))

    class FakeRunner:
        def run(self, cmd, require_outputs=False, **kw):
            import subprocess
            p = subprocess.run(['bash', '-c', cmd], capture_output=True,
                               text=True)
            return p.returncode, p.stdout, p.stderr

    class FakeHandle:
        def get_command_runners(self):
            return [FakeRunner()]

    groups.install_hosts_entries(FakeHandle(), 'aXb')
    groups.install_hosts_entries(FakeHandle(), 'a.b')
    content = hosts.read_text()
    assert 'worker.aXb' in content and 'actor.a.b' in content
    groups.remove_hosts_entries(FakeHandle(), 'a.b')
    content = hosts.read_text()
    assert 'worker.aXb' in content        # aXb untouched
    assert 'actor.a.b' not in content
    for g in ('a.b', 'aXb'):
        if os.path.exists(groups.hosts_file_path(g)):
            os.remove(groups.hosts_file_path(g))


def test_instance_aware_cold_start_from_zero():
    """min_replicas=0 + traffic: the instance-aware scaler must still
    produce a nonzero target with no ready/launching replicas."""
    from skypilot_tpu.serve.autoscalers import (Autoscaler,
                                                AutoscalerDecisionOperator)
    from skypilot_tpu.serve.service_spec import SkyServiceSpec
    spec = SkyServiceSpec(min_replicas=0, max_replicas=5,
                          target_qps_per_replica={'tpu-v5e-8': 4.0},
                          upscale_delay_seconds=0,
                          downscale_delay_seconds=0)
    a = Autoscaler.make(spec)
    a.target_num_replicas = 0
    now = 1000.0
    a.collect_request_information(int(6 * a._QPS_WINDOW_SECONDS),
                                  timestamp=now)
    d = a.evaluate(num_ready=0, num_launching=0, now=now,
                   ready_capacities=[])
    assert d.operator == AutoscalerDecisionOperator.SCALE_UP
    assert a.target_num_replicas == 2  # ceil(6/4)


def test_instance_aware_scales_to_zero_when_idle():
    """min_replicas=0 + NO traffic: the cover walk must not pin one
    ready replica alive forever (parity with the scalar scaler's
    ceil(0/x) == 0 path)."""
    from skypilot_tpu.serve.autoscalers import (Autoscaler,
                                                AutoscalerDecisionOperator)
    from skypilot_tpu.serve.service_spec import SkyServiceSpec
    spec = SkyServiceSpec(min_replicas=0, max_replicas=5,
                          target_qps_per_replica={'tpu-v5e-8': 4.0},
                          upscale_delay_seconds=0,
                          downscale_delay_seconds=0)
    a = Autoscaler.make(spec)
    a.target_num_replicas = 1
    now = 1000.0  # no requests collected: qps == 0
    d = a.evaluate(num_ready=1, num_launching=0, now=now,
                   ready_capacities=[4.0])
    assert d.operator == AutoscalerDecisionOperator.SCALE_DOWN
    assert a.target_num_replicas == 0


def test_hosts_legacy_unscoped_block_is_migrated(isolated_state,
                                                 monkeypatch, tmp_path):
    """Blocks written under the pre-scoping markers are stripped on the
    first scoped install (they would shadow refreshed entries)."""
    from skypilot_tpu.jobs import groups, state
    jid = state.submit_job('actor', {'name': 'actor'}, 'failover', 0, 'u')
    groups._db().execute(
        'UPDATE managed_jobs SET job_group=? WHERE job_id=?', ('g1', jid))
    groups.publish_address(jid, '10.0.0.9')

    hosts = tmp_path / 'hosts'
    hosts.write_text('127.0.0.1 localhost\n'
                     '# >>> skypilot-jobgroup >>>\n'
                     '10.0.0.1 actor.g1 actor\n'
                     '# <<< skypilot-jobgroup <<<\n')
    monkeypatch.setenv('SKYPILOT_HOSTS_FILE', str(hosts))

    class FakeRunner:
        def run(self, cmd, require_outputs=False, **kw):
            import subprocess
            p = subprocess.run(['bash', '-c', cmd], capture_output=True,
                               text=True)
            return p.returncode, p.stdout, p.stderr

    class FakeHandle:
        def get_command_runners(self):
            return [FakeRunner()]

    groups.install_hosts_entries(FakeHandle(), 'g1')
    content = hosts.read_text()
    assert '10.0.0.1' not in content        # legacy block gone
    assert '10.0.0.9 actor.g1 actor' in content
    assert content.count('actor.g1') == 1
    os.remove(groups.hosts_file_path('g1'))


def test_launch_daemon_pdeathsig_reaps_on_parent_kill(tmp_path):
    """With SKYPILOT_DAEMON_PDEATHSIG (test runs set it), a daemon dies
    when its launcher dies — a killed pytest run cannot strand
    agents/controllers (VERDICT r3 test-hygiene item)."""
    import signal
    import subprocess
    import sys
    import textwrap

    script = textwrap.dedent(f"""
        import os, sys, time
        sys.path.insert(0, {repr(os.getcwd())})
        # pid-matched: only daemons launched by THE PINNED PROCESS
        # get the parent-death tie.
        os.environ['SKYPILOT_DAEMON_PDEATHSIG'] = str(os.getpid())
        from skypilot_tpu.utils import subprocess_utils
        pid = subprocess_utils.launch_daemon(
            ['sleep', '600'], {repr(str(tmp_path / 'd.log'))})
        print(pid, flush=True)
        time.sleep(600)
    """)
    launcher = subprocess.Popen([sys.executable, '-c', script],
                                stdout=subprocess.PIPE, text=True)
    daemon_pid = int(launcher.stdout.readline())
    from skypilot_tpu.utils.subprocess_utils import process_alive
    assert process_alive(daemon_pid)
    launcher.kill()           # simulate a killed test run
    launcher.wait(timeout=10)
    deadline = time.time() + 10
    while time.time() < deadline and process_alive(daemon_pid):
        time.sleep(0.2)
    assert not process_alive(daemon_pid)


def test_log_aggregator_selection_and_config(isolated_state, monkeypatch,
                                             tmp_path):
    """logs.store gcp/aws selects a streaming aggregator with a
    fluent-bit pipeline tailing run.log AND per-rank logs; bucket URLs
    keep the driver's archive path (None here)."""
    from skypilot_tpu import logs as logs_lib

    cfg = tmp_path / 'cfg.yaml'
    monkeypatch.setenv('SKYPILOT_TPU_CONFIG', str(cfg))

    cfg.write_text('logs:\n  store: gs://bucket/logs\n')
    assert logs_lib.get_aggregator() is None  # driver handles buckets

    cfg.write_text('logs:\n  store: gcp\n  gcp:\n    project_id: p1\n')
    agg = logs_lib.get_aggregator()
    assert isinstance(agg, logs_lib.StackdriverAggregator)
    conf = agg.fluentbit_config('my-cluster')
    assert 'job_logs/*/*.log' in conf          # run.log + rank-N.log
    assert 'job_id' in conf and 'rank' in conf  # labels lifted from path
    assert 'stackdriver' in conf
    assert 'export_to_project_id p1' in conf
    assert 'cluster my-cluster' in conf
    cmds = agg.setup_commands('my-cluster')
    assert any('fluent-bit' in c for c in cmds)
    assert any('metadata.google.internal' in c or
               'GOOGLE_APPLICATION_CREDENTIALS' in c for c in cmds)

    cfg.write_text('logs:\n  store: aws\n  aws:\n    region: eu-west-1\n'
                   '    log_group_name: tpu-logs\n')
    agg = logs_lib.get_aggregator()
    assert isinstance(agg, logs_lib.CloudwatchAggregator)
    conf = agg.fluentbit_config('c2')
    assert 'cloudwatch_logs' in conf and 'eu-west-1' in conf
    assert 'tpu-logs' in conf

    cfg.write_text('logs: {}\n')
    assert logs_lib.get_aggregator() is None


def test_queue_autoscaler_target_from_spec():
    """target_queue_per_replica flows YAML -> spec -> autoscaler."""
    from skypilot_tpu.serve.autoscalers import QueueLengthAutoscaler
    from skypilot_tpu.serve.service_spec import SkyServiceSpec
    spec = SkyServiceSpec.from_yaml_config({
        'readiness_probe': '/',
        'autoscaler': 'queue_length',
        'replica_policy': {'min_replicas': 1, 'max_replicas': 5,
                           'target_qps_per_replica': 1,
                           'target_queue_per_replica': 9}})
    a = QueueLengthAutoscaler(spec)
    assert a.target_queue_per_replica == 9.0
    # Explicit constructor arg still overrides.
    assert QueueLengthAutoscaler(
        spec, target_queue_per_replica=2).target_queue_per_replica == 2
