"""run() must bind the caller-supplied host, not the replica identity.

Regression for the high-severity ADVICE.md finding: run() used to
overwrite its `host` parameter with SKYPILOT_API_SERVER_HOST /
gethostname() before web.run_app, so `run(host='127.0.0.1')` bound
whatever the hostname resolved to (a LAN IP on many distros) —
exposing an intended-loopback server, or refusing local clients. The
identity host must flow ONLY into executor.set_server_id().
"""
import pytest


class _Dummy:
    def __init__(self, *a, **kw):
        pass

    def start(self):
        pass


@pytest.fixture()
def quiet_run(monkeypatch, isolated_state):
    """Neutralize run()'s side-effecting collaborators and capture the
    bind host + replica identity."""
    from skypilot_tpu.server import server as server_mod
    from skypilot_tpu.server import daemons as daemons_lib
    from skypilot_tpu.server.requests import executor
    from skypilot_tpu.jobs import scheduler as jobs_scheduler
    from skypilot_tpu.serve import core as serve_core

    seen = {}
    monkeypatch.setattr(
        server_mod.web, 'run_app',
        lambda app, host=None, port=None, **kw: seen.update(
            bind_host=host, bind_port=port))
    monkeypatch.setattr(
        executor, 'set_server_id',
        lambda server_id: seen.update(server_id=server_id))
    monkeypatch.setattr(executor, 'RequestWorkerLoop', _Dummy)
    monkeypatch.setattr(daemons_lib, 'ServerDaemons', _Dummy)
    monkeypatch.setattr(jobs_scheduler, 'maybe_schedule_next_jobs',
                        lambda: None)
    monkeypatch.setattr(serve_core, 'reconcile_controllers',
                        lambda: None)
    monkeypatch.setattr(server_mod, 'create_app', lambda: object())
    return seen


def test_run_binds_loopback_despite_identity_env(quiet_run,
                                                 monkeypatch):
    from skypilot_tpu.server import server as server_mod
    monkeypatch.setenv('SKYPILOT_API_SERVER_HOST', '10.11.12.13')
    server_mod.run(host='127.0.0.1', port=45799)
    # The env var shapes the replica IDENTITY only...
    assert quiet_run['server_id'] == '10.11.12.13:45799'
    # ...while the socket binds the caller-supplied loopback.
    assert quiet_run['bind_host'] == '127.0.0.1'
    assert quiet_run['bind_port'] == 45799


def test_run_identity_defaults_to_hostname(quiet_run, monkeypatch):
    import socket
    from skypilot_tpu.server import server as server_mod
    monkeypatch.delenv('SKYPILOT_API_SERVER_HOST', raising=False)
    server_mod.run(host='0.0.0.0', port=45798)
    assert quiet_run['server_id'] == f'{socket.gethostname()}:45798'
    assert quiet_run['bind_host'] == '0.0.0.0'
