"""Request entrypoints for the multi-replica API-server tests.

Importable by server worker processes (the tests put this directory
on the servers' PYTHONPATH)."""
import time


def slow_echo(seconds: float, value: str) -> str:
    time.sleep(seconds)
    return value


def chatty(message: str) -> str:
    print(f'chatty says: {message}', flush=True)
    return message
