"""AWS EC2 provisioner against a fake Query API.

Mirrors test_gce_provisioner.py: the fake patches the `_request` seam
(post-XML dict shapes), so run/wait/query/terminate/get_cluster_info
and the error classifier are exercised without the network.
"""
import xml.etree.ElementTree as ET

import pytest

from skypilot_tpu import exceptions
from skypilot_tpu.provision import common
from skypilot_tpu.provision.aws import ec2_api
from skypilot_tpu.provision.aws import instance as aws_instance


class FakeEc2:

    def __init__(self):
        self.instances = {}  # id -> record
        self._n = 0
        self.ingress_calls = []
        self.fail_run_with = None  # (Code, Message)

    def request(self, region, action, params=None):
        params = params or {}
        if action == 'RunInstances':
            if self.fail_run_with:
                code, msg = self.fail_run_with
                category, scope = ec2_api._classify_error(code, msg)
                raise exceptions.ProvisionerError(
                    f'EC2 RunInstances in {region} -> {code}: {msg}',
                    category=category, scope=scope)
            self._n += 1
            iid = f'i-{self._n:08x}'
            tags = {}
            i = 1
            while f'TagSpecification.1.Tag.{i}.Key' in params:
                tags[params[f'TagSpecification.1.Tag.{i}.Key']] = \
                    params[f'TagSpecification.1.Tag.{i}.Value']
                i += 1
            rec = {
                'instanceId': iid,
                'instanceType': params['InstanceType'],
                'imageId': params['ImageId'],
                'instanceState': {'code': '0', 'name': 'pending'},
                '_polls': 0,
                'privateIpAddress': f'172.31.0.{self._n}',
                'ipAddress': f'54.1.0.{self._n}',
                'tagSet': [{'key': k, 'value': v}
                           for k, v in tags.items()],
                'groupSet': [{'groupId': 'sg-123', 'groupName': 'default'}],
                '_spot': params.get(
                    'InstanceMarketOptions.MarketType') == 'spot',
                '_zone': params.get('Placement.AvailabilityZone'),
                '_user_data': params.get('UserData'),
            }
            self.instances[iid] = rec
            return {'instancesSet': [rec]}
        if action == 'DescribeInstances':
            cluster = None
            i = 1
            while f'Filter.{i}.Name' in params:
                if params[f'Filter.{i}.Name'] == 'tag:skypilot-cluster':
                    cluster = params[f'Filter.{i}.Value.1']
                i += 1
            items = []
            for rec in self.instances.values():
                tags = {t['key']: t['value'] for t in rec['tagSet']}
                if cluster and tags.get('skypilot-cluster') != cluster:
                    continue
                # Simulate boot: two polls of pending, then running.
                if rec['instanceState']['name'] == 'pending':
                    rec['_polls'] += 1
                    if rec['_polls'] >= 2:
                        rec['instanceState']['name'] = 'running'
                items.append(rec)
            return {'reservationSet': [{'instancesSet': items}]}
        if action == 'TerminateInstances':
            for iid in self._ids(params):
                if iid in self.instances:
                    self.instances[iid]['instanceState']['name'] = \
                        'terminated'
            return {}
        if action == 'StopInstances':
            for iid in self._ids(params):
                self.instances[iid]['instanceState']['name'] = 'stopped'
            return {}
        if action == 'StartInstances':
            for iid in self._ids(params):
                self.instances[iid]['instanceState']['name'] = 'running'
                self.instances[iid]['_polls'] = 9
            return {}
        if action == 'AuthorizeSecurityGroupIngress':
            self.ingress_calls.append(params)
            return {}
        raise AssertionError(f'unhandled {action}')

    @staticmethod
    def _ids(params):
        out = []
        i = 1
        while f'InstanceId.{i}' in params:
            out.append(params[f'InstanceId.{i}'])
            i += 1
        return out


@pytest.fixture()
def fake_ec2(monkeypatch):
    fake = FakeEc2()
    monkeypatch.setattr(ec2_api, '_request', fake.request)
    monkeypatch.setattr(aws_instance, '_ssh_pub_key',
                        lambda: 'ssh-ed25519 AAAA test')
    monkeypatch.setattr(aws_instance.time, 'sleep', lambda s: None)
    return fake


def _config(count=1, **pc):
    base = {'region': 'us-east-1', 'zone': 'us-east-1a',
            'instance_type': 'p4d.24xlarge', 'num_nodes': count,
            'use_spot': False, 'disk_size': 100}
    base.update(pc)
    return common.ProvisionConfig(provider_config=base,
                                  authentication_config={}, count=count,
                                  tags={})


def test_run_wait_query_lifecycle(fake_ec2):
    record = aws_instance.run_instances('us-east-1', 'c1', _config(2))
    assert record.provider_name == 'aws'
    assert record.created_instance_ids == ['c1-0', 'c1-1']
    aws_instance.wait_instances('us-east-1', 'c1',
                                provider_config=record.provider_config,
                                poll=0)
    status = aws_instance.query_instances(
        'c1', provider_config=record.provider_config)
    assert status == {'c1-0': 'running', 'c1-1': 'running'}

    info = aws_instance.get_cluster_info(
        'us-east-1', 'c1', provider_config=record.provider_config)
    assert info.head_instance_id == 'c1-0'
    assert len(info.instances) == 2
    assert info.instances[0].internal_ip.startswith('172.31.')
    assert info.instances[0].external_ip.startswith('54.')
    # User-data cloud-init injected the ssh key (no key pairs).
    rec = next(iter(fake_ec2.instances.values()))
    assert rec['_user_data'] is not None


def test_stop_resume(fake_ec2):
    record = aws_instance.run_instances('us-east-1', 'c2', _config(1))
    aws_instance.wait_instances('us-east-1', 'c2',
                                provider_config=record.provider_config,
                                poll=0)
    aws_instance.stop_instances('c2',
                                provider_config=record.provider_config)
    assert aws_instance.query_instances(
        'c2', provider_config=record.provider_config) == {'c2': 'stopped'}
    # Re-running resumes the stopped node instead of creating a new one.
    record2 = aws_instance.run_instances('us-east-1', 'c2', _config(1))
    assert record2.resumed_instance_ids == ['c2']
    assert record2.created_instance_ids == []
    assert len(fake_ec2.instances) == 1


def test_terminate_then_cluster_info_raises(fake_ec2):
    record = aws_instance.run_instances('us-east-1', 'c3', _config(1))
    aws_instance.terminate_instances(
        'c3', provider_config=record.provider_config)
    with pytest.raises(exceptions.FetchClusterInfoError):
        aws_instance.get_cluster_info(
            'us-east-1', 'c3', provider_config=record.provider_config)


def test_open_ports_authorizes_group(fake_ec2):
    record = aws_instance.run_instances('us-east-1', 'c4', _config(1))
    aws_instance.open_ports('c4', ['8080', '9000-9010'],
                            provider_config=record.provider_config)
    # One call per port: a batched call is atomic, so one duplicate
    # rule would reject the whole batch and silently skip new ports.
    assert len(fake_ec2.ingress_calls) == 2
    first, second = fake_ec2.ingress_calls
    assert first['GroupId'] == 'sg-123'
    assert first['IpPermissions.1.FromPort'] == '8080'
    assert second['IpPermissions.1.FromPort'] == '9000'
    assert second['IpPermissions.1.ToPort'] == '9010'


def test_capacity_error_category(fake_ec2):
    fake_ec2.fail_run_with = ('InsufficientInstanceCapacity',
                              'No capacity in us-east-1a')
    with pytest.raises(exceptions.ProvisionerError) as e:
        aws_instance.run_instances('us-east-1', 'c5', _config(1))
    assert e.value.category == exceptions.ProvisionerError.CAPACITY
    assert not e.value.no_failover


def test_quota_error_blocks_region(fake_ec2):
    fake_ec2.fail_run_with = ('VcpuLimitExceeded', 'limit 0 vCPUs')
    with pytest.raises(exceptions.ProvisionerError) as e:
        aws_instance.run_instances('us-east-1', 'c6', _config(1))
    assert e.value.blocks_region


def test_auth_error_blocks_cloud(fake_ec2):
    # IAM/credential problems are account-wide for THIS cloud but
    # retryable elsewhere: scope=cloud, not abort (pattern library).
    fake_ec2.fail_run_with = ('UnauthorizedOperation', 'nope')
    with pytest.raises(exceptions.ProvisionerError) as e:
        aws_instance.run_instances('us-east-1', 'c7', _config(1))
    assert e.value.blocks_cloud and not e.value.no_failover


def test_classify_error_table():
    cases = {
        'InsufficientInstanceCapacity':
            exceptions.ProvisionerError.CAPACITY,
        'SpotMaxPriceTooLow': exceptions.ProvisionerError.CAPACITY,
        'InstanceLimitExceeded': exceptions.ProvisionerError.QUOTA,
        'MaxSpotInstanceCountExceeded': exceptions.ProvisionerError.QUOTA,
        'AuthFailure': exceptions.ProvisionerError.PERMISSION,
        'InvalidParameterValue': exceptions.ProvisionerError.CONFIG,
        'RequestLimitExceeded': exceptions.ProvisionerError.TRANSIENT,
        'InternalError': exceptions.ProvisionerError.TRANSIENT,
    }
    for code, want in cases.items():
        assert ec2_api._classify_error(code, '')[0] == want, code


def test_xml_to_obj_folds_items():
    xml = '''<DescribeInstancesResponse xmlns="http://ec2.amazonaws.com/">
      <reservationSet>
        <item>
          <instancesSet>
            <item><instanceId>i-1</instanceId>
              <instanceState><name>running</name></instanceState>
              <tagSet><item><key>Name</key><value>n</value></item></tagSet>
            </item>
          </instancesSet>
        </item>
      </reservationSet>
    </DescribeInstancesResponse>'''
    obj = ec2_api._xml_to_obj(ET.fromstring(xml))
    inst = obj['reservationSet'][0]['instancesSet'][0]
    assert inst['instanceId'] == 'i-1'
    assert ec2_api.instance_state(inst) == 'running'
    assert ec2_api.instance_tags(inst) == {'Name': 'n'}


def test_sigv4_headers_shape():
    headers = ec2_api._sigv4_headers(
        'us-east-1', 'ec2.us-east-1.amazonaws.com', 'Action=DescribeRegions',
        ('AKIDEXAMPLE', 'secret', None))
    auth = headers['Authorization']
    assert auth.startswith('AWS4-HMAC-SHA256 Credential=AKIDEXAMPLE/')
    assert 'SignedHeaders=content-type;host;x-amz-date' in auth
    assert 'Signature=' in auth
    assert 'X-Amz-Date' in headers
    # Session tokens add the header and the signed-headers entry.
    headers = ec2_api._sigv4_headers(
        'us-east-1', 'ec2.us-east-1.amazonaws.com', 'x',
        ('AKIDEXAMPLE', 'secret', 'TOKEN'))
    assert headers['X-Amz-Security-Token'] == 'TOKEN'
    assert 'x-amz-security-token' in headers['Authorization']


def test_load_credentials_env(monkeypatch):
    monkeypatch.setenv('AWS_ACCESS_KEY_ID', 'AK')
    monkeypatch.setenv('AWS_SECRET_ACCESS_KEY', 'SK')
    monkeypatch.delenv('AWS_SESSION_TOKEN', raising=False)
    assert ec2_api.load_credentials() == ('AK', 'SK', None)


def test_load_credentials_file(monkeypatch, tmp_path):
    monkeypatch.delenv('AWS_ACCESS_KEY_ID', raising=False)
    monkeypatch.delenv('AWS_SECRET_ACCESS_KEY', raising=False)
    creds = tmp_path / 'credentials'
    creds.write_text('[default]\naws_access_key_id = FK\n'
                     'aws_secret_access_key = FS\n')
    monkeypatch.setattr(ec2_api, '_CREDENTIALS_PATH', str(creds))
    assert ec2_api.load_credentials() == ('FK', 'FS', None)


def test_failover_engine_walks_aws_zones(fake_ec2, monkeypatch,
                                         isolated_state):
    """Capacity in one AZ -> next AZ; quota -> whole region blocked;
    mirrors the GCP failover test with the AWS classifier."""
    from skypilot_tpu import resources as resources_lib
    from skypilot_tpu import task as task_lib
    from skypilot_tpu.backends.tpu_backend import RetryingProvisioner

    task = task_lib.Task(run='true')
    # Pin the region: the walk orders regions cheapest-first, so the
    # zone-walk assertion needs a known starting point.
    r = resources_lib.Resources(infra='aws/us-east-1',
                                accelerators='A100:8').copy(
        instance_type='p4d.24xlarge')
    task.set_resources(r)

    real_request = fake_ec2.request
    failed_zones = []

    def capacity_in_1a(region, action, params=None):
        if action == 'RunInstances' and \
                params.get('Placement.AvailabilityZone') == 'us-east-1a':
            failed_zones.append('us-east-1a')
            raise exceptions.ProvisionerError(
                'EC2 RunInstances -> InsufficientInstanceCapacity',
                category=exceptions.ProvisionerError.CAPACITY)
        return real_request(region, action, params)

    monkeypatch.setattr(ec2_api, '_request', capacity_in_1a)
    # Skip the SSH/agent setup: only the provisioning walk is under
    # test (instances reach 'running' via the fake's poll model).
    prov = RetryingProvisioner()
    record, resolved, region = prov.provision_with_retries(
        task, r, 'awsf', 'awsf')
    assert failed_zones == ['us-east-1a']
    assert record.zone == 'us-east-1b'
    assert resolved.zone == 'us-east-1b'
    assert region.name == 'us-east-1'
    assert len(prov.failover_history) == 1

    # Quota error blocks the whole region: the next zone of the same
    # region is never tried; with the region unpinned the walk moves on
    # past every quota-blocked region in PRICE order (p4d: us-east-1 ==
    # us-west-2 at 32.77, name tie-break -> us-east-1 first; then
    # eu-west-1 and ap-northeast-1 at 35.40).
    fake_ec2.instances.clear()
    r_any = resources_lib.Resources(infra='aws',
                                    accelerators='A100:8').copy(
        instance_type='p4d.24xlarge')
    task.set_resources(r_any)
    tried = []

    def quota_in_east(region, action, params=None):
        if action == 'RunInstances':
            tried.append((region,
                          params.get('Placement.AvailabilityZone')))
            if region in ('ap-northeast-1', 'eu-west-1', 'us-east-1'):
                raise exceptions.ProvisionerError(
                    'EC2 RunInstances -> VcpuLimitExceeded',
                    category=exceptions.ProvisionerError.QUOTA)
        return real_request(region, action, params)

    monkeypatch.setattr(ec2_api, '_request', quota_in_east)
    prov = RetryingProvisioner()
    record, resolved, region = prov.provision_with_retries(
        task, r_any, 'awsq', 'awsq')
    # One attempt per quota-blocked region (us-east-1b skipped), then
    # success in us-west-2.
    assert tried == [('us-east-1', 'us-east-1a'),
                     ('us-west-2', 'us-west-2a')]
    assert region.name == 'us-west-2'
