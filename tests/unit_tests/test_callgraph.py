"""Call-graph builder tests: the ownership analysis' foundation.

SKY008's verdicts are only as good as the graph they are computed
over, so the graph is pinned down directly: thread-target resolution,
self-method call chains, decorator-registered handlers, hop
semantics, escape analysis, and — most importantly — unknown-callee
conservatism (an unresolvable call taints its function arguments to
ANY rather than silently dropping them).
"""
import ast

from skypilot_tpu.analysis import callgraph


def graph_of(src):
    return callgraph.build(ast.parse(src), src.splitlines())


# ---------------------------------------------------------------------------
# thread targets + self-method chains
# ---------------------------------------------------------------------------
def test_thread_target_and_self_method_chain():
    src = '''\
import threading

class Engine:
    def __init__(self):
        self._thread = threading.Thread(  # stpu: thread[scheduler]
            target=self._loop, daemon=True)

    def _loop(self):
        while True:
            self._step()

    def _step(self):
        self._commit()

    def _commit(self):
        pass
'''
    g = graph_of(src)
    assert g.roles('Engine._loop') == {'scheduler'}
    # Roles flow through self.method() chains to the leaves.
    assert g.roles('Engine._step') == {'scheduler'}
    assert g.roles('Engine._commit') == {'scheduler'}
    assert g.roles('Engine.__init__') == {callgraph.INIT_ROLE}


def test_unannotated_thread_target_gets_anonymous_role():
    src = '''\
import threading

class C:
    def __init__(self):
        threading.Thread(target=self._bg).start()

    def _bg(self):
        pass
'''
    g = graph_of(src)
    assert g.roles('C._bg') == {'thread:_bg'}


def test_executor_submit_and_run_in_executor_seed_entries():
    src = '''\
class C:
    def __init__(self, pool, loop):
        pool.submit(self._work)  # stpu: thread[watcher]
        loop.run_in_executor(None, self._aux)  # stpu: thread[lb]

    def _work(self):
        pass

    def _aux(self):
        pass
'''
    g = graph_of(src)
    assert g.roles('C._work') == {'watcher'}
    assert g.roles('C._aux') == {'lb'}


# ---------------------------------------------------------------------------
# handler conventions
# ---------------------------------------------------------------------------
def test_do_verb_methods_are_http_entries():
    src = '''\
class Handler:
    def do_GET(self):
        self._render()

    def _render(self):
        pass
'''
    g = graph_of(src)
    assert g.roles('Handler.do_GET') == {'http'}
    assert g.roles('Handler._render') == {'http'}


def test_decorator_registered_routes_are_http_entries():
    src = '''\
@routes.get('/status')
async def status(request):
    return _body()

def _body():
    return {}
'''
    g = graph_of(src)
    assert g.roles('status') == {'http'}
    assert g.roles('_body') == {'http'}


# ---------------------------------------------------------------------------
# entry / hop / role annotations
# ---------------------------------------------------------------------------
def test_entry_annotation_seeds_role():
    src = '''\
class R:
    def record(self, kind):  # stpu: entry[scheduler]
        self._push(kind)

    def _push(self, kind):
        pass
'''
    g = graph_of(src)
    assert g.roles('R.record') == {'scheduler'}
    assert g.roles('R._push') == {'scheduler'}


def test_hop_pins_function_arguments_to_hop_role():
    src = '''\
class Engine:
    def run_on_scheduler(self, fn):  # stpu: hop[scheduler]
        self._queue.append(fn)

    def export(self):  # stpu: entry[http]
        self.run_on_scheduler(self._do_export)

    def _do_export(self):
        pass
'''
    g = graph_of(src)
    assert g.hops['Engine.run_on_scheduler'] == 'scheduler'
    # The hopped fn runs under the hop role, NOT the caller's role —
    # the PR-13 control-queue pattern, machine-verified.
    assert g.roles('Engine._do_export') == {'scheduler'}
    # The hop itself is still reachable from its callers.
    assert 'http' in g.roles('Engine.run_on_scheduler')


def test_role_comment_pins_escaping_reference():
    src = '''\
class C:
    def __init__(self):
        self.cache = make_cache(
            fetch=self._fetch)  # stpu: role[scheduler]

    def _fetch(self):
        pass
'''
    g = graph_of(src)
    assert g.roles('C._fetch') == {'scheduler'}
    assert 'C._fetch' not in g.escaped


# ---------------------------------------------------------------------------
# unknown-callee conservatism + escapes
# ---------------------------------------------------------------------------
def test_unknown_callee_taints_function_args_to_any():
    src = '''\
class C:
    def __init__(self):
        register_somewhere(self._cb)

    def _cb(self):
        pass
'''
    g = graph_of(src)
    # `register_somewhere` is unresolvable: `_cb` may be invoked from
    # any thread, so it must carry ANY.
    assert callgraph.ANY in g.roles('C._cb')


def test_bare_reference_in_value_position_escapes():
    src = '''\
class C:
    def __init__(self):
        self.handler = self._on_event

    def _on_event(self):
        pass
'''
    g = graph_of(src)
    assert callgraph.ANY in g.roles('C._on_event')


def test_public_unannotated_method_defaults_to_any():
    src = '''\
class C:
    def poke(self):
        self._inner()

    def _inner(self):
        pass
'''
    g = graph_of(src)
    assert callgraph.ANY in g.roles('C.poke')
    assert callgraph.ANY in g.roles('C._inner')


def test_unreached_private_function_is_any():
    src = '''\
def _orphan():
    pass
'''
    g = graph_of(src)
    assert graph_of(src).roles('_orphan') == {callgraph.ANY}
    assert g.roles('no_such_function') == {callgraph.ANY}


# ---------------------------------------------------------------------------
# resolution details
# ---------------------------------------------------------------------------
def test_nested_function_resolution_prefers_innermost():
    src = '''\
def helper():
    pass

class C:
    def outer(self):  # stpu: entry[watcher]
        def helper():
            inner_leaf()
        helper()

def inner_leaf():
    pass
'''
    g = graph_of(src)
    # The call inside `outer` hits the nested def, not the module fn.
    assert g.roles('C.outer.<locals>.helper') == {'watcher'}
    assert 'watcher' in g.roles('inner_leaf')
    assert 'watcher' not in g.roles('helper')


def test_class_instantiation_edges_reach_init():
    src = '''\
class Worker:
    def __init__(self):
        pass

def spawn():  # stpu: entry[watcher]
    return Worker()
'''
    g = graph_of(src)
    assert 'watcher' in g.roles('Worker.__init__')


# ---------------------------------------------------------------------------
# ownership grammar parsing
# ---------------------------------------------------------------------------
def test_class_owned_attrs_map_and_comments():
    src = '''\
class Engine:
    _STPU_OWNERS = {
        'cache': 'scheduler!',
        'slots': 'scheduler',
    }

    def __init__(self):
        self.ring = []  # stpu: owner[scheduler]
        self.free = 0
'''
    tree = ast.parse(src)
    cls = tree.body[0]
    owners = callgraph.class_owned_attrs(cls, src.splitlines())
    assert set(owners) == {'cache', 'slots', 'ring'}
    assert owners['cache'].role == 'scheduler'
    assert owners['cache'].strict
    assert not owners['slots'].strict
    assert owners['ring'].role == 'scheduler'
    assert not owners['ring'].strict


def test_parse_role_strict_suffix():
    assert callgraph.parse_role('scheduler!') == ('scheduler', True)
    assert callgraph.parse_role('watcher') == ('watcher', False)
