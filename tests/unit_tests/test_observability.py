"""Unified metrics layer: registry primitives (concurrency, histogram
bucketing, exposition escaping), end-to-end scrapes of both /metrics
endpoints (API server + inference server), and the trainer's JSONL
step-metrics round-trip.

The test-side Prometheus parser below is intentionally independent of
the production renderer (it validates the FORMAT, not just
self-consistency)."""
import json
import re
import threading
import urllib.request

import pytest

from skypilot_tpu.observability import metrics as m
from skypilot_tpu.observability import catalog


# ---------------------------------------------------------------------------
# test-side exposition parser
# ---------------------------------------------------------------------------
_SAMPLE_RE = re.compile(
    r'^(?P<name>[a-zA-Z_][a-zA-Z0-9_]*)'
    r'(?:\{(?P<labels>.*)\})?'
    r' (?P<value>[^ ]+)$')
_LABEL_RE = re.compile(r'(\w+)="((?:[^"\\]|\\.)*)"')


def parse_prom(text):
    """Parse text exposition → ({(name, labels_frozenset): value},
    {family: type}). Raises on malformed lines (the acceptance
    criterion: the endpoints emit PARSEABLE exposition)."""
    samples = {}
    types = {}
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith('# TYPE '):
            _, _, family, typ = line.split(' ', 3)
            assert typ in ('counter', 'gauge', 'histogram', 'untyped')
            types[family] = typ
            continue
        if line.startswith('#'):
            continue
        match = _SAMPLE_RE.match(line)
        assert match, f'malformed sample line: {line!r}'
        labels = {}
        if match.group('labels'):
            consumed = _LABEL_RE.findall(match.group('labels'))
            labels = {k: v.replace('\\"', '"').replace('\\n', '\n')
                      .replace('\\\\', '\\') for k, v in consumed}
        raw = match.group('value')
        specials = {'NaN': float('nan'), '+Inf': float('inf'),
                    '-Inf': float('-inf')}
        value = specials[raw] if raw in specials else float(raw)
        samples[(match.group('name'),
                 frozenset(labels.items()))] = value
    return samples, types


# ---------------------------------------------------------------------------
# registry primitives
# ---------------------------------------------------------------------------
def test_counter_concurrent_increments():
    reg = m.Registry()
    counter = reg.get_or_create(m.Counter, 'skypilot_test_total',
                                'concurrency test', ('worker',))
    n_threads, per_thread = 8, 5000

    def worker(i):
        child = counter.labels(worker=str(i % 2))
        for _ in range(per_thread):
            child.inc()

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    total = (counter.labels(worker='0').value +
             counter.labels(worker='1').value)
    assert total == n_threads * per_thread
    with pytest.raises(ValueError):
        counter.labels(worker='0').inc(-1)  # counters only go up


def test_gauge_and_histogram_bucketing():
    reg = m.Registry()
    gauge = reg.get_or_create(m.Gauge, 'skypilot_test_gauge', 'g')
    gauge.set(5)
    gauge.inc(2)
    gauge.dec()
    assert gauge.value == 6

    hist = reg.get_or_create(m.Histogram, 'skypilot_test_seconds',
                             'h', (), buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.05, 0.5, 5.0, 50.0):
        hist.observe(v)
    samples, types = parse_prom(reg.render())
    assert types['skypilot_test_seconds'] == 'histogram'

    def bucket(le):
        return samples[('skypilot_test_seconds_bucket',
                        frozenset({('le', le)}))]

    assert bucket('0.1') == 2      # cumulative
    assert bucket('1') == 3
    assert bucket('10') == 4
    assert bucket('+Inf') == 5
    assert samples[('skypilot_test_seconds_count', frozenset())] == 5
    assert samples[('skypilot_test_seconds_sum',
                    frozenset())] == pytest.approx(55.6)


def test_exposition_escaping_roundtrip():
    reg = m.Registry()
    gauge = reg.get_or_create(m.Gauge, 'skypilot_test_escape',
                              'help with \\ backslash\nand newline',
                              ('path',))
    hostile = 'a"b\\c\nd'
    gauge.labels(path=hostile).set(1)
    text = reg.render()
    assert '\n\n' not in text.strip()  # escaped newline stays in-line
    samples, _ = parse_prom(text)
    assert samples[('skypilot_test_escape',
                    frozenset({('path', hostile)}))] == 1


def test_registry_conflicting_redeclaration_raises():
    reg = m.Registry()
    reg.get_or_create(m.Counter, 'skypilot_test_total', 'x', ('a',))
    # Same shape → same instance (idempotent).
    again = reg.get_or_create(m.Counter, 'skypilot_test_total', 'x',
                              ('a',))
    assert again is reg.get(name='skypilot_test_total')
    with pytest.raises(ValueError):
        reg.get_or_create(m.Gauge, 'skypilot_test_total', 'x', ('a',))
    with pytest.raises(ValueError):
        reg.get_or_create(m.Counter, 'skypilot_test_total', 'x',
                          ('a', 'b'))
    with pytest.raises(ValueError):
        reg.get_or_create(m.Counter, 'Bad-Name', 'x')


def test_catalog_instruments_constructible():
    """Every cataloged metric materializes in the default registry
    with its declared kind."""
    for name, spec in catalog.SPECS.items():
        metric = catalog._create(name)
        expected = {'counter': m.Counter, 'gauge': m.Gauge,
                    'histogram': m.Histogram,
                    'gauge_as_counter': m.Gauge}[spec[0]]
        assert type(metric) is expected, name


# ---------------------------------------------------------------------------
# end-to-end scrapes
# ---------------------------------------------------------------------------
def test_api_server_metrics_scrape(isolated_state):
    """GET /api/metrics returns parseable exposition including the
    orchestration gauges AND the per-route middleware series."""
    import asyncio

    from aiohttp.test_utils import TestClient, TestServer

    from skypilot_tpu.server import server as api_server

    async def scrape():
        app = api_server.create_app()
        async with TestClient(TestServer(app)) as client:
            assert (await client.get('/api/health')).status == 200
            resp = await client.get('/api/metrics')
            assert resp.status == 200
            return await resp.text()

    text = asyncio.new_event_loop().run_until_complete(scrape())
    samples, types = parse_prom(text)
    assert types['skypilot_services'] == 'gauge'
    assert types['skypilot_requests_total'] == 'counter'
    assert ('skypilot_services', frozenset()) in samples
    assert ('skypilot_server_rss_bytes', frozenset()) in samples
    assert samples[('skypilot_server_rss_bytes', frozenset())] > 0
    # Per-route middleware: the /api/health hit above is counted.
    key = ('skypilot_api_requests_total',
           frozenset({('route', '/api/health'), ('method', 'GET'),
                      ('code', '200')}))
    assert samples[key] >= 1
    assert types['skypilot_api_request_seconds'] == 'histogram'
    assert ('skypilot_api_requests_in_flight', frozenset()) in samples


@pytest.fixture(scope='module')
def tiny_inference_server():
    """A live inference HTTP server over a tiny llama + continuous
    engine (paged, prefix caching) on an ephemeral port."""
    import jax
    jax.config.update('jax_platforms', 'cpu')
    import flax.linen as nn
    import jax.numpy as jnp

    from skypilot_tpu.inference.http_server import make_server
    from skypilot_tpu.inference.runtime import InferenceRuntime
    from skypilot_tpu.models.batching import ContinuousBatchingEngine
    from skypilot_tpu.models.llama import Llama, LlamaConfig

    model = Llama(LlamaConfig.tiny(kv_page_size=8, kv_total_pages=40))
    params = nn.meta.unbox(model.init(
        jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))['params'])
    engine = ContinuousBatchingEngine(model, params, num_slots=2,
                                      max_total_len=64)
    rt = InferenceRuntime(
        model=model, params=params,
        vocab_size=model.config.vocab_size, model_name='llama-tiny',
        max_total_len=64, spec_total=64, speculative=0, engine=engine)
    server = make_server(rt, 0)
    port = server.server_address[1]
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    yield f'http://127.0.0.1:{port}', engine
    server.shutdown()
    engine.stop()


def test_inference_metrics_scrape(tiny_inference_server):
    """POST /generate then scrape /metrics: engine internals (queue,
    slots, page pool, prefix cache) and the request-path histograms
    (TTFT recorded for the NON-streaming request) all expose."""
    url, engine = tiny_inference_server
    req = urllib.request.Request(
        f'{url}/generate',
        data=json.dumps({'tokens': [[1, 2, 3, 4, 5, 6, 7, 8, 9]],
                         'max_new_tokens': 5}).encode(),
        headers={'Content-Type': 'application/json'})
    out = json.loads(urllib.request.urlopen(req, timeout=240).read())
    assert len(out['tokens'][0]) == 14

    text = urllib.request.urlopen(f'{url}/metrics',
                                  timeout=30).read().decode()
    samples, types = parse_prom(text)
    eng = frozenset({('engine', engine.engine_id)})
    assert samples[('skypilot_serving_admissions_total', eng)] >= 1
    assert samples[('skypilot_serving_tokens_committed_total',
                    eng)] >= 5
    assert samples[('skypilot_serving_num_slots', eng)] == 2
    assert samples[('skypilot_serving_queue_depth', eng)] == 0
    assert samples[('skypilot_serving_pages_free', eng)] >= 1
    assert ('skypilot_serving_prefix_cache_hits_total',
            eng) in samples
    assert types['skypilot_serving_decode_step_seconds'] == 'histogram'
    assert samples[('skypilot_serving_decode_step_seconds_count',
                    eng)] >= 1
    # Request path: non-streaming TTFT + token counters.
    assert samples[('skypilot_serving_ttft_seconds_count',
                    frozenset())] >= 1
    assert samples[('skypilot_serving_prompt_tokens_total',
                    frozenset())] >= 9
    assert samples[('skypilot_serving_completion_tokens_total',
                    frozenset())] >= 5


def test_inference_stats_surfaces_engine_counters(
        tiny_inference_server):
    """Satellite: /stats carries prefix-cache hits/misses/evictions,
    page-pool occupancy, preemptions, and documents its window."""
    url, _ = tiny_inference_server
    stats = json.loads(urllib.request.urlopen(f'{url}/stats',
                                              timeout=30).read())
    assert stats['engine'] == 'continuous'
    assert {'hits', 'misses', 'hit_rate', 'evictions',
            'resident_unreferenced'} <= set(stats['prefix_cache'])
    assert {'total', 'free', 'used', 'utilization'} <= \
        set(stats['page_pool'])
    assert stats['preemptions'] == 0
    serving = stats['serving']
    assert serving['window'] == 1024
    assert 'itl_ms_p50' in serving
    # The non-streaming request from the scrape test recorded TTFT.
    assert serving['requests'] >= 1


# ---------------------------------------------------------------------------
# trainer step metrics
# ---------------------------------------------------------------------------
def test_train_lm_metrics_file_end_to_end(tmp_path):
    """Acceptance: `train_lm --metrics-file` writes one JSONL record
    per logged step with step_time_s, tokens_per_sec, loss (and
    grad_norm), and --trace-file captures per-phase spans."""
    import os
    import subprocess
    import sys

    from skypilot_tpu.observability.step_metrics import read_jsonl

    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    out = tmp_path / 'steps.jsonl'
    trace = tmp_path / 'trace.json'
    env = {k: v for k, v in os.environ.items() if k != 'XLA_FLAGS'}
    proc = subprocess.run(
        [sys.executable, '-m', 'skypilot_tpu.recipes.train_lm',
         '--cpu', '--model', 'tiny', '--steps', '2', '--seq', '16',
         '--global-batch', '4', '--log-every', '1',
         '--metrics-file', str(out), '--trace-file', str(trace)],
        cwd=repo, env=env, capture_output=True, text=True,
        timeout=420)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    records = read_jsonl(str(out))
    assert [r['step'] for r in records] == [1, 2]
    for rec in records:
        assert rec['step_time_s'] > 0
        assert rec['tokens_per_sec'] > 0
        assert rec['loss'] > 0
        assert rec['grad_norm'] is not None and rec['grad_norm'] > 0
    with open(trace, 'r', encoding='utf-8') as f:
        spans = {e['name'] for e in json.load(f)['traceEvents']}
    assert {'train/init', 'train/data', 'train/step'} <= spans


def test_step_metrics_jsonl_roundtrip(tmp_path):
    from skypilot_tpu.observability.step_metrics import (StepMetrics,
                                                         read_jsonl)
    path = tmp_path / 'metrics' / 'steps.jsonl'
    with StepMetrics(str(path), n_params=1_000_000, n_devices=2,
                     peak_flops=1e12) as emitter:
        emitter.log(10, step_time_s=0.5, tokens=4096, loss=3.25,
                    grad_norm=1.5)
        emitter.log(20, step_time_s=0.25, tokens=4096, loss=3.0)
    records = read_jsonl(str(path))
    assert [r['step'] for r in records] == [10, 20]
    first = records[0]
    assert first['step_time_s'] == 0.5
    assert first['tokens_per_sec'] == pytest.approx(8192.0)
    assert first['loss'] == 3.25
    assert first['grad_norm'] == 1.5
    # mfu = 6 * 1e6 * 8192 / (1e12 * 2)
    assert first['mfu'] == pytest.approx(0.0246, abs=1e-4)
    assert records[1]['grad_norm'] is None
    # Append mode: a resumed run extends the same file.
    with StepMetrics(str(path), n_params=None) as emitter:
        rec = emitter.log(30, step_time_s=0.1, tokens=10, loss=2.0)
        assert rec['mfu'] is None  # no param count -> no estimate
    assert len(read_jsonl(str(path))) == 3
