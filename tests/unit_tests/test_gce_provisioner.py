"""GCE VM path of the GCP provisioner against a fake compute API."""
import re

import pytest

from skypilot_tpu import exceptions
from skypilot_tpu.provision import common
from skypilot_tpu.provision.gcp import gce_api
from skypilot_tpu.provision.gcp import instance as gcp_instance


class FakeGce:

    def __init__(self):
        self.instances = {}
        self._ip = 5

    def request(self, method, path, json_body=None, params=None):
        m = re.match(r'projects/([^/]+)/zones/([^/]+)/instances'
                     r'(?:/([^/]+))?(?:/(\w+))?$', path)
        assert m, path
        _, zone, name, action = m.groups()
        if method == 'POST' and name is None:
            n = json_body['name']
            self.instances[(zone, n)] = {
                'name': n,
                'status': 'PROVISIONING',
                '_polls': 0,
                'machineType': json_body['machineType'],
                'labels': json_body.get('labels', {}),
                'guestAccelerators': json_body.get('guestAccelerators'),
                'scheduling': json_body.get('scheduling', {}),
                'networkInterfaces': [{
                    'networkIP': f'10.1.0.{self._ip}',
                    'accessConfigs': [{'natIP': f'34.9.0.{self._ip}'}],
                }],
            }
            self._ip += 1
            return {'name': f'op-{n}'}
        if name and action == 'stop':
            self.instances[(zone, name)]['status'] = 'TERMINATED'
            return {}
        if name and action == 'start':
            self.instances[(zone, name)]['status'] = 'RUNNING'
            return {}
        if method == 'GET' and name:
            inst = self.instances.get((zone, name))
            if inst is None:
                raise exceptions.FetchClusterInfoError(
                    exceptions.FetchClusterInfoError.Reason.HEAD)
            if inst['status'] == 'PROVISIONING':
                inst['_polls'] += 1
                if inst['_polls'] >= 2:
                    inst['status'] = 'RUNNING'
            return inst
        if method == 'GET':
            items = [i for (z, _), i in self.instances.items() if z == zone]
            if params and params.get('filter'):
                label = params['filter'].split('=')[-1]
                items = [i for i in items
                         if i['labels'].get('skypilot-cluster') == label]
            return {'items': items}
        if method == 'DELETE' and name:
            if (zone, name) not in self.instances:
                raise exceptions.FetchClusterInfoError(
                    exceptions.FetchClusterInfoError.Reason.HEAD)
            del self.instances[(zone, name)]
            return {}
        raise AssertionError(f'unhandled {method} {path}')


@pytest.fixture()
def fake_gce(monkeypatch):
    fake = FakeGce()
    monkeypatch.setattr(gce_api, '_request',
                        lambda method, path, json_body=None, params=None:
                        fake.request(method, path, json_body=json_body,
                                     params=params))
    monkeypatch.setattr(gcp_instance, '_project', lambda *a, **k: 'p')
    monkeypatch.setattr(gcp_instance, '_ssh_pub_key', lambda: 'ssh-ed x')
    import skypilot_tpu.provision.gcp.gce_api as mod
    monkeypatch.setattr(mod.time, 'sleep', lambda s: None)
    return fake


def _config(count=1, accelerators=None, spot=False):
    return common.ProvisionConfig(
        provider_config={
            'zone': 'us-central1-a',
            'tpu_vm': False,
            'instance_type': 'n2-standard-8',
            'accelerators': accelerators or {},
            'use_spot': spot,
            'num_nodes': count,
            'disk_size': 100,
        },
        authentication_config={}, count=count, tags={})


def test_gce_create_wait_info(fake_gce):
    cfg = _config(count=2)
    record = gcp_instance.run_instances('us-central1', 'g1', cfg)
    assert record.created_instance_ids == ['g1-0', 'g1-1']
    gcp_instance.wait_instances('us-central1', 'g1',
                                provider_config=cfg.provider_config)
    info = gcp_instance.get_cluster_info('us-central1', 'g1',
                                         cfg.provider_config)
    assert info.num_instances == 2
    head = info.get_head_instance()
    assert head.external_ip.startswith('34.9.')
    assert head.internal_ip.startswith('10.1.')


def test_gce_gpu_and_spot_flags(fake_gce):
    cfg = _config(accelerators={'A100': 8}, spot=True)
    gcp_instance.run_instances('us-central1', 'g2', cfg)
    inst = fake_gce.instances[('us-central1-a', 'g2')]
    acc = inst['guestAccelerators'][0]
    assert acc['acceleratorType'].endswith('nvidia-tesla-a100')
    assert acc['acceleratorCount'] == 8
    assert inst['scheduling']['provisioningModel'] == 'SPOT'


def test_gce_stop_resume_query_terminate(fake_gce):
    cfg = _config()
    gcp_instance.run_instances('us-central1', 'g3', cfg)
    gcp_instance.stop_instances('g3', cfg.provider_config)
    assert gcp_instance.query_instances('g3', cfg.provider_config) == {
        'g3': 'stopped'}
    record = gcp_instance.run_instances('us-central1', 'g3', cfg)
    assert record.resumed_instance_ids == ['g3']
    gcp_instance.terminate_instances('g3', cfg.provider_config)
    assert not fake_gce.instances


class FakeDisks:
    """Fake compute API slice for the disk/volume lifecycle."""

    def __init__(self):
        self.disks = {}
        self.attached = {}  # instance -> [device names]

    def request(self, method, path, json_body=None, params=None):
        m = re.match(r'projects/([^/]+)/zones/([^/]+)/disks'
                     r'(?:/([^/]+))?$', path)
        if m:
            _, zone, name = m.groups()
            if method == 'POST':
                n = json_body['name']
                self.disks[(zone, n)] = {
                    'name': n, 'sizeGb': json_body['sizeGb'],
                    'type': json_body['type'], 'status': 'READY'}
                return {'name': f'op-{n}'}
            if method == 'GET':
                disk = self.disks.get((zone, name))
                if disk is None:
                    raise exceptions.FetchClusterInfoError(
                        exceptions.FetchClusterInfoError.Reason.HEAD)
                return disk
            if method == 'DELETE':
                if (zone, name) not in self.disks:
                    raise exceptions.FetchClusterInfoError(
                        exceptions.FetchClusterInfoError.Reason.HEAD)
                del self.disks[(zone, name)]
                return {}
        m = re.match(r'projects/([^/]+)/zones/([^/]+)/instances/([^/]+)/'
                     r'(attachDisk|detachDisk)$', path)
        assert m, path
        _, _zone, inst, action = m.groups()
        if action == 'attachDisk':
            self.attached.setdefault(inst, []).append(
                json_body['deviceName'])
        else:
            self.attached.get(inst, []).remove(params['deviceName'])
        return {}


def test_gcp_volume_lifecycle(isolated_state, monkeypatch):
    """PD create -> adopt (idempotent apply) -> attach -> delete via the
    routed volume ops (reference: sky/provision/__init__.py:235-310)."""
    from skypilot_tpu.volumes import core as volumes_core
    fake = FakeDisks()
    monkeypatch.setattr(gce_api, '_request',
                        lambda m, p, json_body=None, params=None:
                        fake.request(m, p, json_body, params))
    monkeypatch.setattr(gcp_instance, '_project', lambda *a, **k: 'p')

    vol = volumes_core.apply('ckpt', 200, infra='gcp/us-central2-b',
                             volume_type='pd-ssd')
    assert vol['status'] == 'READY' and vol['size_gb'] == 200
    assert ('us-central2-b', 'ckpt') in fake.disks
    # Idempotent re-apply adopts the existing disk.
    vol2 = volumes_core.apply('ckpt', 200, infra='gcp/us-central2-b')
    assert vol2['size_gb'] == 200
    assert len(fake.disks) == 1
    assert any(v['name'] == 'ckpt' for v in volumes_core.ls())

    # Attach returns the mountable device path.
    from skypilot_tpu import provision as provision_lib
    device = provision_lib.attach_volume('gcp', volumes_core.get('ckpt'),
                                         'vm-0')
    assert device == '/dev/disk/by-id/google-ckpt'
    assert fake.attached['vm-0'] == ['ckpt']

    volumes_core.delete('ckpt')
    assert fake.disks == {}
    assert volumes_core.get('ckpt') is None


def test_k8s_pvc_manifest():
    from skypilot_tpu.provision.kubernetes import instance as k8s
    pvc = k8s._pvc_manifest('ckpt', 50, storage_class='fast')
    assert pvc['kind'] == 'PersistentVolumeClaim'
    assert pvc['spec']['resources']['requests']['storage'] == '50Gi'
    assert pvc['spec']['storageClassName'] == 'fast'
    assert pvc['metadata']['labels']['skypilot-volume'] == 'ckpt'


def test_vm_zone_walk_failover(fake_gce, monkeypatch, isolated_state):
    """A GCE VM stockout in the first (cheapest) zone fails over to the
    NEXT CATALOG ZONE of the same region — the zone walk runs on real
    multi-zone catalog data, price-ordered (us-central1 a -> b)."""
    from skypilot_tpu import resources as resources_lib
    from skypilot_tpu import task as task_lib
    from skypilot_tpu.backends.tpu_backend import RetryingProvisioner

    real_request = fake_gce.request

    def stockout_in_a(method, path, json_body=None, params=None):
        if method == 'POST' and path.endswith('/instances') and \
                '/zones/us-central1-a/' in path:
            raise exceptions.ProvisionerError(
                'The zone does not have enough resources',
                category=exceptions.ProvisionerError.CAPACITY)
        return real_request(method, path, json_body=json_body,
                            params=params)

    monkeypatch.setattr(gce_api, '_request', stockout_in_a)

    task = task_lib.Task(run='true')
    r = resources_lib.Resources(infra='gcp', instance_type='n2-standard-8')
    task.set_resources(r)
    prov = RetryingProvisioner()
    record, resolved, region = prov.provision_with_retries(
        task, r, 'vmwalk', 'vmwalk')
    # Cheapest region first (us-central1), then its next real zone.
    assert region.name == 'us-central1'
    assert resolved.zone == 'us-central1-b'
    assert len(prov.failover_history) == 1
    assert ('us-central1-b', 'vmwalk-0') in fake_gce.instances or \
           ('us-central1-b', 'vmwalk') in fake_gce.instances
