"""`stpu check` static-analysis suite: framework + the SKY rules.

Three layers:
  1. fixture snippets asserting EXACT (rule, line) findings per rule;
  2. framework mechanics — suppression comments, baseline round-trip,
     select validation, the JSON/text reporters, the CLI;
  3. the tier-1 GATE: zero non-baselined findings across
     `skypilot_tpu/` (and no stale baseline rows), so a regression in
     async-safety / jit-purity / lock discipline / metric hygiene /
     exception hygiene fails CI the moment it lands.
"""
import asyncio
import json
import os

import pytest

from skypilot_tpu import analysis
from skypilot_tpu.analysis import core as acore

REPO_ROOT = acore.REPO_ROOT
PKG = os.path.join(REPO_ROOT, 'skypilot_tpu')


def rules_lines(src, path='snippet.py', select=None):
    return [(f.rule, f.line)
            for f in analysis.run_source(src, path, select)]


# ---------------------------------------------------------------------------
# SKY001: blocking-call-in-async
# ---------------------------------------------------------------------------
def test_sky001_flags_blocking_calls_in_async():
    src = '''\
import time, subprocess, requests

async def handler(request):
    time.sleep(1)
    subprocess.run(['true'])
    requests.get('http://x')
    with open('f') as f:
        pass
    body = path.read_text()
'''
    assert rules_lines(src, select=['SKY001']) == [
        ('SKY001', 4), ('SKY001', 5), ('SKY001', 6), ('SKY001', 7),
        ('SKY001', 9)]


def test_sky001_sync_and_nested_defs_are_clean():
    src = '''\
import time

def plain():
    time.sleep(1)

async def handler():
    def worker():
        time.sleep(1)  # runs in an executor, not on the loop
    await asyncio.to_thread(worker)
    await loop.run_in_executor(None, open, 'f')
'''
    assert rules_lines(src, select=['SKY001']) == []


def test_sky001_db_calls_need_db_receiver():
    src = '''\
async def handler(conn, planner):
    conn.execute('SELECT 1')
    planner.execute()
'''
    assert rules_lines(src, select=['SKY001']) == [('SKY001', 2)]


# ---------------------------------------------------------------------------
# SKY002: jit-purity
# ---------------------------------------------------------------------------
def test_sky002_decorated_and_wrapped_functions():
    src = '''\
import jax
import numpy as np
from functools import partial

@jax.jit
def step(x, y):
    print('tracing')
    v = x.item()
    f = float(y)
    a = np.asarray(x)
    return v + f + a

def raw(x):
    return int(x)

wrapped = jax.jit(raw, donate_argnums=(0,))
'''
    assert rules_lines(src, select=['SKY002']) == [
        ('SKY002', 7), ('SKY002', 8), ('SKY002', 9), ('SKY002', 10),
        ('SKY002', 14)]


def test_sky002_side_effects_and_static_argnums():
    src = '''\
import jax
from functools import partial

@partial(jax.jit, static_argnums={0})
def stepped(n, x):
    global COUNT
    return x

class Trainer:
    @jax.jit
    def update(self, x):
        self.calls = 1
        return x
'''
    assert rules_lines(src, select=['SKY002']) == [
        ('SKY002', 4), ('SKY002', 6), ('SKY002', 12)]


def test_sky002_clean_jit_and_non_jitted_code():
    src = '''\
import jax
import jax.numpy as jnp

@jax.jit
def step(x):
    jax.debug.print('x={x}', x=x)
    y = jnp.sum(x)
    return y

def host_side(x):
    print(x)          # not jitted: fine
    return x.item()   # not jitted: fine

fast = jax.jit(step, static_argnums=(0,))
'''
    assert rules_lines(src, select=['SKY002']) == []


# ---------------------------------------------------------------------------
# SKY003: lock discipline
# ---------------------------------------------------------------------------
_LOCKED_CLASS = '''\
import threading

class Engine:
    def __init__(self):
        self._lock = threading.Lock()
        self.queue = []
        self.count = 0

    def good(self, item):
        with self._lock:
            self.queue.append(item)
            self.count += 1

    def bad(self, item):
        self.queue.append(item)

    def also_bad(self):
        self.count += 1

    def _sweep_locked(self):
        self.queue.clear()  # caller holds the lock by convention

    def read_only(self):
        return len(self.queue)
'''


def test_sky003_flags_unlocked_mutations_only():
    assert rules_lines(_LOCKED_CLASS, select=['SKY003']) == [
        ('SKY003', 15), ('SKY003', 18)]


def test_sky003_class_without_lock_is_exempt():
    src = '''\
class Plain:
    def __init__(self):
        self.queue = []

    def push(self, item):
        self.queue.append(item)
'''
    assert rules_lines(src, select=['SKY003']) == []


def test_sky003_acquire_call_counts_as_disciplined():
    src = '''\
import threading

class C:
    def __init__(self):
        self._lock = threading.RLock()
        self.state = {}

    def update(self, k, v):
        self._lock.acquire()
        try:
            self.state[k] = v
        finally:
            self._lock.release()
'''
    assert rules_lines(src, select=['SKY003']) == []


# ---------------------------------------------------------------------------
# SKY004: metric-name hygiene
# ---------------------------------------------------------------------------
def test_sky004_literal_names_must_be_cataloged():
    src = '''\
from skypilot_tpu.observability.catalog import counter
from skypilot_tpu.observability import catalog

c1 = counter('skypilot_serving_requests_total')
c2 = counter('skypilot_bogus_total')
c3 = catalog.gauge('skypilot_clusters')
'''
    assert rules_lines(src, select=['SKY004']) == [('SKY004', 5)]


def test_sky004_dynamic_names_and_foreign_counters():
    src = '''\
from skypilot_tpu.observability import catalog, metrics as m
from collections import Counter

def f(name):
    bad = catalog.counter(f'skypilot_{name}_total')
    ok = Counter([1, 2, 3])
    cls = m.Counter('skypilot_not_in_catalog_total', 'help')
    reg = REGISTRY.get_or_create(m.Gauge, 'skypilot_undeclared', 'h')
'''
    assert rules_lines(src, select=['SKY004']) == [
        ('SKY004', 5), ('SKY004', 7), ('SKY004', 8)]


def test_sky004_catalog_parse_finds_real_names():
    from skypilot_tpu.analysis.checkers import metric_names
    names = metric_names.catalog_names()
    assert 'skypilot_serving_requests_total' in names
    assert 'skypilot_api_requests_total' in names
    assert len(names) >= 30


# ---------------------------------------------------------------------------
# SKY005: swallowed exceptions (control planes only)
# ---------------------------------------------------------------------------
_SWALLOW = '''\
def f():
    try:
        work()
    except Exception:
        pass
'''


def test_sky005_scoped_to_control_planes(tmp_path):
    sub = tmp_path / 'server'
    sub.mkdir()
    in_scope = sub / 'handlers.py'
    in_scope.write_text(_SWALLOW)
    out_of_scope = tmp_path / 'utils.py'
    out_of_scope.write_text(_SWALLOW)
    assert [(f.rule, f.line)
            for f in analysis.run_file(str(in_scope))] == [('SKY005', 4)]
    assert analysis.run_file(str(out_of_scope)) == []


def test_sky005_handled_forms_are_clean():
    src = '''\
import logging
logger = logging.getLogger(__name__)

def f():
    try:
        work()
    except Exception as e:
        logger.warning('failed: %s', e)
    try:
        work()
    except Exception:
        raise
    try:
        work()
    except Exception as e:
        return {'error': str(e)}
    try:
        work()
    except ValueError:
        pass  # narrow except: out of SKY005 scope
'''
    assert rules_lines(src, 'server/x.py', ['SKY005']) == []


def test_sky005_bare_except_flagged():
    src = '''\
def f():
    try:
        work()
    except:
        result = None
'''
    assert rules_lines(src, 'jobs/x.py', ['SKY005']) == [('SKY005', 4)]


# ---------------------------------------------------------------------------
# SKY006: pallas_call interpret-mode reachability
# ---------------------------------------------------------------------------
def test_sky006_missing_or_false_interpret_flagged():
    src = '''\
import jax.experimental.pallas as pl

def run(x):
    out = pl.pallas_call(kernel, grid=(4,))(x)
    out = pl.pallas_call(kernel, grid=(4,), interpret=False)(x)
    return out
'''
    assert rules_lines(src, 'ops/k.py', ['SKY006']) == [
        ('SKY006', 4), ('SKY006', 5)]


def test_sky006_plumbed_flag_and_true_are_clean():
    src = '''\
import jax.experimental.pallas as pl

def run(x, interpret=False):
    a = pl.pallas_call(kernel, grid=(4,), interpret=interpret)(x)
    b = pl.pallas_call(kernel, interpret=True)(x)
    c = pl.pallas_call(kernel, **opts)(x)
    return a, b, c
'''
    assert rules_lines(src, 'ops/k.py', ['SKY006']) == []


def test_sky006_tests_are_exempt():
    src = 'pl.pallas_call(kernel, grid=(1,))(x)\n'
    assert rules_lines(src, 'tests/unit_tests/t.py', ['SKY006']) == []
    assert rules_lines(src, 'pkg/tests/t.py', ['SKY006']) == []
    assert rules_lines(src, 'ops/k.py', ['SKY006']) == [('SKY006', 1)]


def test_sky006_repo_kernels_thread_interpret():
    """The in-repo fused kernels (ops/pallas_paged.py) must satisfy
    their own rule — zero SKY006 findings across the package."""
    from skypilot_tpu import analysis
    findings = analysis.run_paths(
        [os.path.join(REPO_ROOT, 'skypilot_tpu')], ['SKY006'])
    assert findings == []


# ---------------------------------------------------------------------------
# SKY007: span discipline
# ---------------------------------------------------------------------------
def test_sky007_flags_leaked_spans():
    src = '''\
from skypilot_tpu.observability import tracing

def leak(ctx):
    tracing.span('a', ctx)
    sp = tracing.start_span('b', ctx)
    sp.end()

def attr(self, ctx):
    self.sp = tracing.span('c', ctx)
'''
    # line 4: result discarded; line 5: .end() not under a finally;
    # line 9: stored onto an object (close unverifiable).
    assert rules_lines(src, select=['SKY007']) == [
        ('SKY007', 4), ('SKY007', 5), ('SKY007', 9)]


def test_sky007_clean_forms():
    src = '''\
from skypilot_tpu.observability import tracing

def ok(ctx):
    with tracing.span('a', ctx):
        pass
    sp = tracing.start_span('b', ctx)
    try:
        pass
    finally:
        sp.end(status=1)
    tracing.record_span('c', ctx, 0.1)

def factory(ctx):
    sp = tracing.start_span('d', ctx)
    return sp

def handoff(ctx, consume):
    sp = tracing.span('e', ctx)
    consume(sp)
'''
    assert rules_lines(src, select=['SKY007']) == []


def test_sky007_direct_imports_and_aliases():
    src = '''\
from skypilot_tpu.observability.tracing import span, start_span

def leak(ctx):
    span('a', ctx)
    s2 = start_span('b', ctx)
'''
    assert rules_lines(src, select=['SKY007']) == [
        ('SKY007', 4), ('SKY007', 5)]
    # Unrelated functions that happen to be named `span` are not
    # the tracing API.
    clean = '''\
def span(x):
    return x

def f():
    span(1)
'''
    assert rules_lines(clean, select=['SKY007']) == []


def test_sky007_tests_are_exempt():
    src = '''\
from skypilot_tpu.observability import tracing

def leak(ctx):
    tracing.span('a', ctx)
'''
    assert rules_lines(src, 'tests/unit_tests/t.py',
                       ['SKY007']) == []
    assert rules_lines(src, 'pkg/test_x.py', ['SKY007']) == []


def test_sky007_serving_plane_is_clean():
    """The tracing wiring this rule polices (LB, HTTP server, engine,
    stub) must satisfy its own contract — zero SKY007 findings."""
    findings = analysis.run_paths(
        [os.path.join(REPO_ROOT, 'skypilot_tpu')], ['SKY007'])
    assert findings == [], '\n'.join(f.render() for f in findings)


# ---------------------------------------------------------------------------
# framework: suppressions, baseline, select, reporters
# ---------------------------------------------------------------------------
def test_suppression_comment_exact_rule():
    src = '''\
import time

async def handler():
    time.sleep(1)  # stpu: ignore[SKY001]
    time.sleep(2)  # stpu: ignore[SKY003]
    time.sleep(3)  # stpu: ignore
'''
    assert rules_lines(src, select=['SKY001']) == [('SKY001', 5)]


def test_select_unknown_rule_raises():
    with pytest.raises(ValueError, match='SKY999'):
        analysis.resolve_select('SKY999')
    assert analysis.resolve_select('sky001') == {'SKY001'}
    assert len(analysis.resolve_select(None)) == 7


def test_syntax_error_reported_not_crashed():
    findings = analysis.run_source('def broken(:\n', 'x.py')
    assert [(f.rule, f.line) for f in findings] == [('SKY000', 1)]


def test_baseline_round_trip(tmp_path):
    findings = analysis.run_source(
        'import time\nasync def f():\n    time.sleep(1)\n', 'a.py')
    assert len(findings) == 1
    b = acore.Baseline.from_findings(findings, 'known issue')
    path = tmp_path / 'baseline.json'
    b.save(str(path))
    loaded = acore.Baseline.load(str(path))
    new, old = loaded.split(findings)
    assert new == [] and len(old) == 1
    assert loaded.stale_entries(findings) == []
    assert loaded.stale_entries([]) == loaded.entries
    # An entry without a justification is rejected outright.
    with pytest.raises(ValueError, match='justification'):
        acore.Baseline([{'rule': 'SKY001', 'path': 'a.py', 'line': 3,
                         'justification': ''}])


def test_reporters():
    findings = analysis.run_source(
        'import time\nasync def f():\n    time.sleep(1)\n', 'a.py')
    text = analysis.render_text(findings)
    assert 'a.py:3:4: SKY001' in text and '1 finding' in text
    data = json.loads(analysis.render_json(findings))
    assert data['count'] == 1
    assert data['findings'][0]['rule'] == 'SKY001'
    assert data['findings'][0]['line'] == 3


# ---------------------------------------------------------------------------
# the gate + self-check
# ---------------------------------------------------------------------------
def test_analysis_package_is_itself_clean():
    findings = analysis.run_paths(
        [os.path.join(PKG, 'analysis')])
    assert findings == [], '\n'.join(f.render() for f in findings)


def test_tier1_gate_zero_non_baselined_findings():
    """THE gate: `stpu check skypilot_tpu/` must be clean against the
    committed baseline — and the baseline must carry no stale rows."""
    findings = analysis.run_paths([PKG])
    baseline = acore.Baseline.load(acore.DEFAULT_BASELINE)
    new, _ = baseline.split(findings)
    assert new == [], ('new static-analysis findings (fix them or, for '
                       'a triaged false positive, baseline them with a '
                       'justification):\n' +
                       '\n'.join(f.render() for f in new))
    stale = baseline.stale_entries(findings)
    assert stale == [], ('baseline rows no longer matching any finding '
                         '(delete them):\n' +
                         '\n'.join(str(e) for e in stale))


def test_dashboard_sky001_findings_fixed_not_baselined():
    dashboard = os.path.join(PKG, 'server', 'dashboard.py')
    assert analysis.run_file(dashboard, ['SKY001']) == []
    baseline = acore.Baseline.load(acore.DEFAULT_BASELINE)
    assert not any(e['path'].endswith('dashboard.py')
                   for e in baseline.entries)


# ---------------------------------------------------------------------------
# the SKY001 dashboard fix, functionally
# ---------------------------------------------------------------------------
def test_dashboard_static_handlers_cached_off_loop():
    from skypilot_tpu.server import dashboard
    dashboard._static_text.cache_clear()
    resp = asyncio.run(dashboard.index(None))
    assert resp.status == 200
    assert '<' in resp.text  # the SPA shell
    resp_js = asyncio.run(dashboard.app_js(None))
    assert resp_js.content_type == 'application/javascript'
    # Second hit is served from the lru_cache, no disk read.
    assert dashboard._static_text.cache_info().hits >= 0
    before = dashboard._static_text.cache_info().misses
    asyncio.run(dashboard.index(None))
    assert dashboard._static_text.cache_info().misses == before


# ---------------------------------------------------------------------------
# CLI + SDK
# ---------------------------------------------------------------------------
def test_cli_check_static_json_smoke(tmp_path):
    from click.testing import CliRunner
    from skypilot_tpu.client import cli
    clean = tmp_path / 'clean.py'
    clean.write_text('def f():\n    return 1\n')
    r = CliRunner().invoke(cli.cli,
                           ['check', '--format', 'json', str(clean)])
    assert r.exit_code == 0, r.output
    data = json.loads(r.output)
    assert data['count'] == 0


def test_cli_check_nonzero_on_findings(tmp_path):
    from click.testing import CliRunner
    from skypilot_tpu.client import cli
    bad = tmp_path / 'server'
    bad.mkdir()
    f = bad / 'handler.py'
    f.write_text('import time\nasync def h():\n    time.sleep(1)\n')
    r = CliRunner().invoke(cli.cli, ['check', str(bad)])
    assert r.exit_code == 1
    assert 'SKY001' in r.output


def test_cli_check_select_filters(tmp_path):
    from click.testing import CliRunner
    from skypilot_tpu.client import cli
    bad = tmp_path / 'server'
    bad.mkdir()
    f = bad / 'handler.py'
    f.write_text('import time\nasync def h():\n    time.sleep(1)\n'
                 'def g():\n    try:\n        pass\n'
                 '    except Exception:\n        pass\n')
    r = CliRunner().invoke(cli.cli,
                           ['check', '--select', 'SKY005', str(bad)])
    assert r.exit_code == 1
    assert 'SKY005' in r.output and 'SKY001' not in r.output


def test_cli_check_cloud_mode_still_works(monkeypatch):
    from click.testing import CliRunner
    from skypilot_tpu.client import cli, sdk
    monkeypatch.setattr(sdk, 'check', lambda: 'req-1')
    monkeypatch.setattr(sdk, 'get', lambda rid: ['gcp'])
    r = CliRunner().invoke(cli.cli, ['check'])
    assert r.exit_code == 0
    assert 'Enabled clouds: gcp' in r.output
    r2 = CliRunner().invoke(cli.cli, ['check', 'aws'])
    assert r2.exit_code == 0
    assert 'aws: disabled' in r2.output


def test_sdk_static_check(tmp_path):
    from skypilot_tpu.client import sdk
    f = tmp_path / 'x.py'
    f.write_text('import time\nasync def h():\n    time.sleep(1)\n')
    rows = sdk.static_check([str(f)])
    assert [(r['rule'], r['line']) for r in rows] == [('SKY001', 3)]
    assert rows[0]['col'] == 4 and 'time.sleep' in rows[0]['message']
