"""`stpu check` static-analysis suite: framework + the SKY rules.

Three layers:
  1. fixture snippets asserting EXACT (rule, line) findings per rule;
  2. framework mechanics — suppression comments, baseline round-trip,
     select validation, the JSON/text reporters, the CLI;
  3. the tier-1 GATE: zero non-baselined findings across
     `skypilot_tpu/` (and no stale baseline rows), so a regression in
     async-safety / jit-purity / lock discipline / metric hygiene /
     exception hygiene / thread ownership / donation discipline /
     fault-point drift fails CI the moment it lands.
"""
import asyncio
import json
import os

import pytest

from skypilot_tpu import analysis
from skypilot_tpu.analysis import core as acore

REPO_ROOT = acore.REPO_ROOT
PKG = os.path.join(REPO_ROOT, 'skypilot_tpu')


def rules_lines(src, path='snippet.py', select=None):
    return [(f.rule, f.line)
            for f in analysis.run_source(src, path, select)]


# ---------------------------------------------------------------------------
# SKY001: blocking-call-in-async
# ---------------------------------------------------------------------------
def test_sky001_flags_blocking_calls_in_async():
    src = '''\
import time, subprocess, requests

async def handler(request):
    time.sleep(1)
    subprocess.run(['true'])
    requests.get('http://x')
    with open('f') as f:
        pass
    body = path.read_text()
'''
    assert rules_lines(src, select=['SKY001']) == [
        ('SKY001', 4), ('SKY001', 5), ('SKY001', 6), ('SKY001', 7),
        ('SKY001', 9)]


def test_sky001_sync_and_nested_defs_are_clean():
    src = '''\
import time

def plain():
    time.sleep(1)

async def handler():
    def worker():
        time.sleep(1)  # runs in an executor, not on the loop
    await asyncio.to_thread(worker)
    await loop.run_in_executor(None, open, 'f')
'''
    assert rules_lines(src, select=['SKY001']) == []


def test_sky001_db_calls_need_db_receiver():
    src = '''\
async def handler(conn, planner):
    conn.execute('SELECT 1')
    planner.execute()
'''
    assert rules_lines(src, select=['SKY001']) == [('SKY001', 2)]


# ---------------------------------------------------------------------------
# SKY002: jit-purity
# ---------------------------------------------------------------------------
def test_sky002_decorated_and_wrapped_functions():
    src = '''\
import jax
import numpy as np
from functools import partial

@jax.jit
def step(x, y):
    print('tracing')
    v = x.item()
    f = float(y)
    a = np.asarray(x)
    return v + f + a

def raw(x):
    return int(x)

wrapped = jax.jit(raw, donate_argnums=(0,))
'''
    assert rules_lines(src, select=['SKY002']) == [
        ('SKY002', 7), ('SKY002', 8), ('SKY002', 9), ('SKY002', 10),
        ('SKY002', 14)]


def test_sky002_side_effects_and_static_argnums():
    src = '''\
import jax
from functools import partial

@partial(jax.jit, static_argnums={0})
def stepped(n, x):
    global COUNT
    return x

class Trainer:
    @jax.jit
    def update(self, x):
        self.calls = 1
        return x
'''
    assert rules_lines(src, select=['SKY002']) == [
        ('SKY002', 4), ('SKY002', 6), ('SKY002', 12)]


def test_sky002_clean_jit_and_non_jitted_code():
    src = '''\
import jax
import jax.numpy as jnp

@jax.jit
def step(x):
    jax.debug.print('x={x}', x=x)
    y = jnp.sum(x)
    return y

def host_side(x):
    print(x)          # not jitted: fine
    return x.item()   # not jitted: fine

fast = jax.jit(step, static_argnums=(0,))
'''
    assert rules_lines(src, select=['SKY002']) == []


# ---------------------------------------------------------------------------
# SKY003: lock discipline
# ---------------------------------------------------------------------------
_LOCKED_CLASS = '''\
import threading

class Engine:
    def __init__(self):
        self._lock = threading.Lock()
        self.queue = []
        self.count = 0

    def good(self, item):
        with self._lock:
            self.queue.append(item)
            self.count += 1

    def bad(self, item):
        self.queue.append(item)

    def also_bad(self):
        self.count += 1

    def _sweep_locked(self):
        self.queue.clear()  # caller holds the lock by convention

    def read_only(self):
        return len(self.queue)
'''


def test_sky003_flags_unlocked_mutations_only():
    assert rules_lines(_LOCKED_CLASS, select=['SKY003']) == [
        ('SKY003', 15), ('SKY003', 18)]


def test_sky003_class_without_lock_is_exempt():
    src = '''\
class Plain:
    def __init__(self):
        self.queue = []

    def push(self, item):
        self.queue.append(item)
'''
    assert rules_lines(src, select=['SKY003']) == []


def test_sky003_acquire_call_counts_as_disciplined():
    src = '''\
import threading

class C:
    def __init__(self):
        self._lock = threading.RLock()
        self.state = {}

    def update(self, k, v):
        self._lock.acquire()
        try:
            self.state[k] = v
        finally:
            self._lock.release()
'''
    assert rules_lines(src, select=['SKY003']) == []


# ---------------------------------------------------------------------------
# SKY004: metric-name hygiene
# ---------------------------------------------------------------------------
def test_sky004_literal_names_must_be_cataloged():
    src = '''\
from skypilot_tpu.observability.catalog import counter
from skypilot_tpu.observability import catalog

c1 = counter('skypilot_serving_requests_total')
c2 = counter('skypilot_bogus_total')
c3 = catalog.gauge('skypilot_clusters')
'''
    assert rules_lines(src, select=['SKY004']) == [('SKY004', 5)]


def test_sky004_dynamic_names_and_foreign_counters():
    src = '''\
from skypilot_tpu.observability import catalog, metrics as m
from collections import Counter

def f(name):
    bad = catalog.counter(f'skypilot_{name}_total')
    ok = Counter([1, 2, 3])
    cls = m.Counter('skypilot_not_in_catalog_total', 'help')
    reg = REGISTRY.get_or_create(m.Gauge, 'skypilot_undeclared', 'h')
'''
    assert rules_lines(src, select=['SKY004']) == [
        ('SKY004', 5), ('SKY004', 7), ('SKY004', 8)]


def test_sky004_catalog_parse_finds_real_names():
    from skypilot_tpu.analysis.checkers import metric_names
    names = metric_names.catalog_names()
    assert 'skypilot_serving_requests_total' in names
    assert 'skypilot_api_requests_total' in names
    assert len(names) >= 30


# ---------------------------------------------------------------------------
# SKY005: swallowed exceptions (control planes only)
# ---------------------------------------------------------------------------
_SWALLOW = '''\
def f():
    try:
        work()
    except Exception:
        pass
'''


def test_sky005_scoped_to_control_planes(tmp_path):
    sub = tmp_path / 'server'
    sub.mkdir()
    in_scope = sub / 'handlers.py'
    in_scope.write_text(_SWALLOW)
    out_of_scope = tmp_path / 'utils.py'
    out_of_scope.write_text(_SWALLOW)
    assert [(f.rule, f.line)
            for f in analysis.run_file(str(in_scope))] == [('SKY005', 4)]
    assert analysis.run_file(str(out_of_scope)) == []


def test_sky005_handled_forms_are_clean():
    src = '''\
import logging
logger = logging.getLogger(__name__)

def f():
    try:
        work()
    except Exception as e:
        logger.warning('failed: %s', e)
    try:
        work()
    except Exception:
        raise
    try:
        work()
    except Exception as e:
        return {'error': str(e)}
    try:
        work()
    except ValueError:
        pass  # narrow except: out of SKY005 scope
'''
    assert rules_lines(src, 'server/x.py', ['SKY005']) == []


def test_sky005_bare_except_flagged():
    src = '''\
def f():
    try:
        work()
    except:
        result = None
'''
    assert rules_lines(src, 'jobs/x.py', ['SKY005']) == [('SKY005', 4)]


# ---------------------------------------------------------------------------
# SKY006: pallas_call interpret-mode reachability
# ---------------------------------------------------------------------------
def test_sky006_missing_or_false_interpret_flagged():
    src = '''\
import jax.experimental.pallas as pl

def run(x):
    out = pl.pallas_call(kernel, grid=(4,))(x)
    out = pl.pallas_call(kernel, grid=(4,), interpret=False)(x)
    return out
'''
    assert rules_lines(src, 'ops/k.py', ['SKY006']) == [
        ('SKY006', 4), ('SKY006', 5)]


def test_sky006_plumbed_flag_and_true_are_clean():
    src = '''\
import jax.experimental.pallas as pl

def run(x, interpret=False):
    a = pl.pallas_call(kernel, grid=(4,), interpret=interpret)(x)
    b = pl.pallas_call(kernel, interpret=True)(x)
    c = pl.pallas_call(kernel, **opts)(x)
    return a, b, c
'''
    assert rules_lines(src, 'ops/k.py', ['SKY006']) == []


def test_sky006_tests_are_exempt():
    src = 'pl.pallas_call(kernel, grid=(1,))(x)\n'
    assert rules_lines(src, 'tests/unit_tests/t.py', ['SKY006']) == []
    assert rules_lines(src, 'pkg/tests/t.py', ['SKY006']) == []
    assert rules_lines(src, 'ops/k.py', ['SKY006']) == [('SKY006', 1)]


def test_sky006_repo_kernels_thread_interpret():
    """The in-repo fused kernels (ops/pallas_paged.py) must satisfy
    their own rule — zero SKY006 findings across the package."""
    from skypilot_tpu import analysis
    findings = analysis.run_paths(
        [os.path.join(REPO_ROOT, 'skypilot_tpu')], ['SKY006'])
    assert findings == []


# ---------------------------------------------------------------------------
# SKY007: span discipline
# ---------------------------------------------------------------------------
def test_sky007_flags_leaked_spans():
    src = '''\
from skypilot_tpu.observability import tracing

def leak(ctx):
    tracing.span('a', ctx)
    sp = tracing.start_span('b', ctx)
    sp.end()

def attr(self, ctx):
    self.sp = tracing.span('c', ctx)
'''
    # line 4: result discarded; line 5: .end() not under a finally;
    # line 9: stored onto an object (close unverifiable).
    assert rules_lines(src, select=['SKY007']) == [
        ('SKY007', 4), ('SKY007', 5), ('SKY007', 9)]


def test_sky007_clean_forms():
    src = '''\
from skypilot_tpu.observability import tracing

def ok(ctx):
    with tracing.span('a', ctx):
        pass
    sp = tracing.start_span('b', ctx)
    try:
        pass
    finally:
        sp.end(status=1)
    tracing.record_span('c', ctx, 0.1)

def factory(ctx):
    sp = tracing.start_span('d', ctx)
    return sp

def handoff(ctx, consume):
    sp = tracing.span('e', ctx)
    consume(sp)
'''
    assert rules_lines(src, select=['SKY007']) == []


def test_sky007_direct_imports_and_aliases():
    src = '''\
from skypilot_tpu.observability.tracing import span, start_span

def leak(ctx):
    span('a', ctx)
    s2 = start_span('b', ctx)
'''
    assert rules_lines(src, select=['SKY007']) == [
        ('SKY007', 4), ('SKY007', 5)]
    # Unrelated functions that happen to be named `span` are not
    # the tracing API.
    clean = '''\
def span(x):
    return x

def f():
    span(1)
'''
    assert rules_lines(clean, select=['SKY007']) == []


def test_sky007_tests_are_exempt():
    src = '''\
from skypilot_tpu.observability import tracing

def leak(ctx):
    tracing.span('a', ctx)
'''
    assert rules_lines(src, 'tests/unit_tests/t.py',
                       ['SKY007']) == []
    assert rules_lines(src, 'pkg/test_x.py', ['SKY007']) == []


def test_sky007_serving_plane_is_clean():
    """The tracing wiring this rule polices (LB, HTTP server, engine,
    stub) must satisfy its own contract — zero SKY007 findings."""
    findings = analysis.run_paths(
        [os.path.join(REPO_ROOT, 'skypilot_tpu')], ['SKY007'])
    assert findings == [], '\n'.join(f.render() for f in findings)


# ---------------------------------------------------------------------------
# SKY008: thread ownership
# ---------------------------------------------------------------------------
_OWNED_ENGINE = '''\
import threading

class Engine:
    _STPU_OWNERS = {
        'cache': 'scheduler!',
        'slots': 'scheduler',
    }

    def __init__(self):
        self.cache = {}
        self.slots = []
        self._thread = threading.Thread(  # stpu: thread[scheduler]
            target=self._loop, daemon=True)

    def _loop(self):
        self.slots.append(1)
        return len(self.cache)

    def do_GET(self):
        self.slots.append(2)
        return len(self.cache)
'''


def test_sky008_cross_thread_write_and_strict_read_flagged():
    findings = analysis.run_source(_OWNED_ENGINE, 'm.py', ['SKY008'])
    # do_GET runs on http: the write to `slots` and the READ of the
    # strict-owned `cache` are both violations; the non-strict read
    # of `slots`' owner is fine, and `_loop` (scheduler) is clean.
    assert [(f.rule, f.line, f.symbol) for f in findings] == [
        ('SKY008', 20, 'Engine.do_GET'),
        ('SKY008', 21, 'Engine.do_GET')]
    assert 'owned by scheduler' in findings[0].message
    assert 'http' in findings[0].message


def test_sky008_is_the_pr13_control_queue_detector():
    """Non-vacuity: the exact bug class the control queue fixed —
    an HTTP export touching scheduler-owned state directly — is
    caught, and hopping through `run_on_scheduler` clears it."""
    buggy = '''\
import threading

class Engine:
    _STPU_OWNERS = {'cache': 'scheduler!'}

    def __init__(self):
        self.cache = {}
        threading.Thread(  # stpu: thread[scheduler]
            target=self._loop).start()

    def _loop(self):
        self.cache['k'] = 1

    def export(self):  # stpu: entry[http]
        return dict(self.cache)
'''
    assert rules_lines(buggy, select=['SKY008']) == [('SKY008', 15)]
    hopped = buggy.replace(
        "        return dict(self.cache)",
        "        return self.run_on_scheduler(self._do_export)\n"
        "\n"
        "    def run_on_scheduler(self, fn):  # stpu: hop[scheduler]\n"
        "        return fn()\n"
        "\n"
        "    def _do_export(self):\n"
        "        return dict(self.cache)")
    assert rules_lines(hopped, select=['SKY008']) == []


def test_sky008_lock_holders_and_unowned_classes_exempt():
    src = '''\
import threading

class Engine:
    _STPU_OWNERS = {'slots': 'scheduler'}

    def __init__(self):
        self._lock = threading.Lock()
        self.slots = []
        threading.Thread(  # stpu: thread[scheduler]
            target=self._loop).start()

    def _loop(self):
        self.slots.append(1)

    def poke(self):
        with self._lock:
            self.slots.append(2)

class Plain:
    def __init__(self):
        self.slots = []

    def poke(self):
        self.slots.append(1)
'''
    assert rules_lines(src, select=['SKY008']) == []


def test_sky008_ownership_drift_declared_but_never_assigned():
    src = '''\
class Engine:
    _STPU_OWNERS = {'ghost': 'scheduler'}

    def __init__(self):
        self.real = 1
'''
    findings = analysis.run_source(src, 'm.py', ['SKY008'])
    assert [(f.rule, f.line) for f in findings] == [('SKY008', 2)]
    assert 'ghost' in findings[0].message


def test_sky008_owner_declared_attrs_leave_sky003():
    """The migration contract: ownership replaces lock discipline
    for declared attributes — SKY003 no longer fires on them."""
    src = '''\
import threading

class Engine:
    _STPU_OWNERS = {'slots': 'scheduler'}

    def __init__(self):
        self._lock = threading.Lock()
        self.slots = []
        self.other = 0

    def mutate(self):
        self.slots.append(1)
        self.other += 1
'''
    assert rules_lines(src, select=['SKY003']) == [('SKY003', 13)]


# ---------------------------------------------------------------------------
# SKY009: donation discipline
# ---------------------------------------------------------------------------
def test_sky009_use_after_donation_flagged_rebind_clean():
    src = '''\
import jax
from functools import partial

@partial(jax.jit, donate_argnums=(0,))
def step(cache, x):
    return cache

def bad(cache, x):
    out = step(cache, x)
    return cache.shape

def good(cache, x):
    cache = step(cache, x)
    return cache.shape
'''
    findings = analysis.run_source(src, 'm.py', ['SKY009'])
    assert [(f.rule, f.line, f.symbol) for f in findings] == [
        ('SKY009', 10, 'bad')]
    assert 'donated' in findings[0].message


def test_sky009_tracks_jit_assignments_and_self_attrs():
    src = '''\
import jax

class Engine:
    def _pin_cache_out(self):
        return {}

    def __init__(self, f):
        self._fn = jax.jit(f, donate_argnums=(0,),
                           out_shardings=None)

    def drive(self, cache, x):
        y = self._fn(cache, x)
        return cache.sum()
'''
    findings = analysis.run_source(src, 'm.py', ['SKY009'])
    assert [(f.rule, f.line, f.symbol) for f in findings] == [
        ('SKY009', 13, 'Engine.drive')]
    assert 'self.cache' not in findings[0].message  # local, not attr


def test_sky009_missing_cache_pin_flagged_in_pin_classes():
    src = '''\
import jax

class Engine:
    def _pin_cache_out(self):
        return {}

    def __init__(self, f, g):
        self._a = jax.jit(f, donate_argnums=(0,))
        self._b = jax.jit(g, donate_argnums=(0,),
                          **self._pin_cache_out(0))
'''
    findings = analysis.run_source(src, 'm.py', ['SKY009'])
    assert [(f.rule, f.line) for f in findings] == [('SKY009', 8)]
    assert '_pin_cache_out' in findings[0].message
    # Outside a pin-aware class the pin rule does not apply.
    free = '''\
import jax

def make(f):
    return jax.jit(f, donate_argnums=(0,))
'''
    assert rules_lines(free, select=['SKY009']) == []


# ---------------------------------------------------------------------------
# SKY010: fault-point drift
# ---------------------------------------------------------------------------
def test_sky010_unknown_and_dynamic_point_names():
    from skypilot_tpu.analysis.checkers import fault_points
    fault_points.reset_caches()
    src = '''\
from skypilot_tpu.robustness import faults

def f(name):
    faults.point('engine.decode_step')
    faults.point('engine.nope')
    faults.point(name)
'''
    findings = analysis.run_source(src, 'm.py', ['SKY010'])
    assert [(f.rule, f.line) for f in findings] == [
        ('SKY010', 5), ('SKY010', 6)]
    assert 'engine.nope' in findings[0].message


def test_sky010_direct_import_and_unrelated_point_fns():
    src = '''\
from skypilot_tpu.robustness.faults import point

def f():
    point('engine.bogus')
'''
    assert rules_lines(src, select=['SKY010']) == [('SKY010', 4)]
    clean = '''\
def point(name):
    return name

def f():
    point('whatever.name')
'''
    assert rules_lines(clean, select=['SKY010']) == []


def test_sky010_catalog_matches_docs_table():
    """KNOWN_POINTS <-> internals.md section 11 must agree exactly
    (that IS the rule); checked here directly so a drift shows up
    even if someone disables the checker."""
    from skypilot_tpu.analysis.checkers import fault_points
    fault_points.reset_caches()
    known = set(fault_points.known_points())
    documented = fault_points.documented_points()
    assert documented is not None, 'docs/internals.md table missing'
    assert known == set(documented)
    assert len(known) >= 10


def test_sky010_every_point_has_a_fire_site():
    """Reverse direction of drift: a cataloged point that nothing
    fires is dead weight. Every KNOWN_POINTS name (minus derived
    rule-only points) must appear at a `faults.point(...)` call
    site somewhere in the package."""
    import re
    from skypilot_tpu.analysis.checkers import fault_points
    fired = set()
    pat = re.compile(r'''\bpoint\(\s*['"]([A-Za-z0-9_.]+)['"]''')
    for path in acore.iter_python_files([PKG]):
        with open(path, 'r', encoding='utf-8') as f:
            fired.update(pat.findall(f.read()))
    needed = (set(fault_points.known_points()) -
              fault_points.DERIVED_POINTS)
    missing = needed - fired
    assert not missing, f'cataloged but never fired: {sorted(missing)}'


# ---------------------------------------------------------------------------
# framework: suppressions, baseline, select, reporters
# ---------------------------------------------------------------------------
def test_suppression_comment_exact_rule():
    src = '''\
import time

async def handler():
    time.sleep(1)  # stpu: ignore[SKY001]
    time.sleep(2)  # stpu: ignore[SKY003]
    time.sleep(3)  # stpu: ignore
'''
    assert rules_lines(src, select=['SKY001']) == [('SKY001', 5)]


def test_select_unknown_rule_raises():
    with pytest.raises(ValueError, match='SKY999'):
        analysis.resolve_select('SKY999')
    assert analysis.resolve_select('sky001') == {'SKY001'}
    assert len(analysis.resolve_select(None)) == 10


def test_syntax_error_reported_not_crashed():
    findings = analysis.run_source('def broken(:\n', 'x.py')
    assert [(f.rule, f.line) for f in findings] == [('SKY000', 1)]


def test_baseline_round_trip(tmp_path):
    findings = analysis.run_source(
        'import time\nasync def f():\n    time.sleep(1)\n', 'a.py')
    assert len(findings) == 1
    b = acore.Baseline.from_findings(findings, 'known issue')
    path = tmp_path / 'baseline.json'
    b.save(str(path))
    loaded = acore.Baseline.load(str(path))
    new, old = loaded.split(findings)
    assert new == [] and len(old) == 1
    assert loaded.stale_entries(findings) == []
    assert loaded.stale_entries([]) == loaded.entries
    # An entry without a justification is rejected outright.
    with pytest.raises(ValueError, match='justification'):
        acore.Baseline([{'rule': 'SKY001', 'path': 'a.py', 'line': 3,
                         'justification': ''}])


def test_findings_carry_enclosing_symbol():
    src = '''\
import time

class Svc:
    async def handler(self):
        time.sleep(1)

async def top():
    time.sleep(1)
'''
    findings = analysis.run_source(src, 'a.py', ['SKY001'])
    assert [f.symbol for f in findings] == ['Svc.handler', 'top']


def test_baseline_v2_symbol_match_survives_line_shift(tmp_path):
    src = 'import time\nasync def f():\n    time.sleep(1)\n'
    findings = analysis.run_source(src, 'a.py')
    b = acore.Baseline.from_findings(findings, 'triaged')
    path = tmp_path / 'baseline.json'
    b.save(str(path))
    data = json.loads(path.read_text())
    assert data['version'] == 2
    assert 'rule_versions' in data
    assert data['entries'][0]['symbol'] == 'f'
    assert 'line' not in data['entries'][0]
    # The same finding three lines further down still matches: v2
    # keys on (rule, path, symbol), not line numbers.
    shifted = analysis.run_source('\n\n\n' + src, 'a.py')
    loaded = acore.Baseline.load(str(path))
    new, old = loaded.split(shifted)
    assert new == [] and len(old) == 1
    assert loaded.stale_entries(shifted) == []


def test_baseline_v1_line_keyed_rows_still_match(tmp_path):
    path = tmp_path / 'baseline.json'
    path.write_text(json.dumps({'version': 1, 'entries': [
        {'rule': 'SKY001', 'path': 'a.py', 'line': 3,
         'justification': 'legacy row'}]}))
    loaded = acore.Baseline.load(str(path))
    findings = analysis.run_source(
        'import time\nasync def f():\n    time.sleep(1)\n', 'a.py')
    new, old = loaded.split(findings)
    assert new == [] and len(old) == 1
    # ...but a line shift breaks a v1 row (the reason v2 exists).
    shifted = analysis.run_source(
        '\nimport time\nasync def f():\n    time.sleep(1)\n', 'a.py')
    new2, _ = loaded.split(shifted)
    assert len(new2) == 1


def test_baseline_migrate_v1_to_v2(tmp_path):
    findings = analysis.run_source(
        'import time\nasync def f():\n    time.sleep(1)\n', 'a.py')
    v1 = acore.Baseline([
        {'rule': 'SKY001', 'path': 'a.py', 'line': 3,
         'justification': 'keep me'},
        {'rule': 'SKY001', 'path': 'gone.py', 'line': 9,
         'justification': 'stale: file deleted'}])
    migrated = v1.migrated(findings)
    # The matching row is rekeyed by symbol (justification intact);
    # the unmatched row is dropped as stale.
    assert [e['symbol'] for e in migrated.entries] == ['f']
    assert migrated.entries[0]['justification'] == 'keep me'
    path = tmp_path / 'baseline.json'
    migrated.save(str(path))
    assert json.loads(path.read_text())['version'] == 2
    new, old = acore.Baseline.load(str(path)).split(findings)
    assert new == [] and len(old) == 1


def test_baseline_rule_version_bump_invalidates_rows():
    findings = analysis.run_source(
        'import time\nasync def f():\n    time.sleep(1)\n', 'a.py')
    entry = {'rule': 'SKY001', 'path': 'a.py', 'symbol': 'f',
             'message': findings[0].message, 'justification': 'j'}
    current = acore.Baseline([dict(entry)],
                             acore.checker_versions())
    assert current.split(findings)[0] == []
    # A stored version behind the checker's current one means the
    # row was triaged against old logic: it no longer matches.
    outdated = acore.Baseline([dict(entry)], {'SKY001': 0})
    new, old = outdated.split(findings)
    assert len(new) == 1 and old == []


def test_reporters():
    findings = analysis.run_source(
        'import time\nasync def f():\n    time.sleep(1)\n', 'a.py')
    text = analysis.render_text(findings)
    assert 'a.py:3:4: SKY001' in text and '1 finding' in text
    data = json.loads(analysis.render_json(findings))
    assert data['count'] == 1
    assert data['findings'][0]['rule'] == 'SKY001'
    assert data['findings'][0]['line'] == 3


# ---------------------------------------------------------------------------
# the gate + self-check
# ---------------------------------------------------------------------------
def test_analysis_package_is_itself_clean():
    findings = analysis.run_paths(
        [os.path.join(PKG, 'analysis')])
    assert findings == [], '\n'.join(f.render() for f in findings)


def test_tier1_gate_zero_non_baselined_findings():
    """THE gate: `stpu check skypilot_tpu/` must be clean against the
    committed baseline — and the baseline must carry no stale rows."""
    findings = analysis.run_paths([PKG])
    baseline = acore.Baseline.load(acore.DEFAULT_BASELINE)
    new, _ = baseline.split(findings)
    assert new == [], ('new static-analysis findings (fix them or, for '
                       'a triaged false positive, baseline them with a '
                       'justification):\n' +
                       '\n'.join(f.render() for f in new))
    stale = baseline.stale_entries(findings)
    assert stale == [], ('baseline rows no longer matching any finding '
                         '(delete them):\n' +
                         '\n'.join(str(e) for e in stale))


def test_new_rules_clean_repo_wide_without_baseline():
    """SKY008/SKY009/SKY010 repo-wide, no baseline: the ownership
    migration left the package fully clean — violations of the new
    rules are fixed (or inline-justified), never grandfathered."""
    findings = analysis.run_paths([PKG],
                                  ['SKY008', 'SKY009', 'SKY010'])
    assert findings == [], '\n'.join(f.render() for f in findings)


def test_committed_baseline_is_v2_and_nearly_empty():
    """The 74 SKY003 rows batching.py used to carry are gone: the
    scheduler-ownership declarations replaced them. The committed
    baseline must stay v2 and small (<= 10 rows) so it never again
    becomes a dumping ground."""
    with open(acore.DEFAULT_BASELINE, 'r', encoding='utf-8') as f:
        data = json.load(f)
    assert data['version'] == 2
    assert len(data['entries']) <= 10
    assert all('symbol' in e for e in data['entries'])


def test_dashboard_sky001_findings_fixed_not_baselined():
    dashboard = os.path.join(PKG, 'server', 'dashboard.py')
    assert analysis.run_file(dashboard, ['SKY001']) == []
    baseline = acore.Baseline.load(acore.DEFAULT_BASELINE)
    assert not any(e['path'].endswith('dashboard.py')
                   for e in baseline.entries)


# ---------------------------------------------------------------------------
# the SKY001 dashboard fix, functionally
# ---------------------------------------------------------------------------
def test_dashboard_static_handlers_cached_off_loop():
    from skypilot_tpu.server import dashboard
    dashboard._static_text.cache_clear()
    resp = asyncio.run(dashboard.index(None))
    assert resp.status == 200
    assert '<' in resp.text  # the SPA shell
    resp_js = asyncio.run(dashboard.app_js(None))
    assert resp_js.content_type == 'application/javascript'
    # Second hit is served from the lru_cache, no disk read.
    assert dashboard._static_text.cache_info().hits >= 0
    before = dashboard._static_text.cache_info().misses
    asyncio.run(dashboard.index(None))
    assert dashboard._static_text.cache_info().misses == before


# ---------------------------------------------------------------------------
# CLI + SDK
# ---------------------------------------------------------------------------
def test_cli_check_static_json_smoke(tmp_path):
    from click.testing import CliRunner
    from skypilot_tpu.client import cli
    clean = tmp_path / 'clean.py'
    clean.write_text('def f():\n    return 1\n')
    r = CliRunner().invoke(cli.cli,
                           ['check', '--format', 'json', str(clean)])
    assert r.exit_code == 0, r.output
    data = json.loads(r.output)
    assert data['count'] == 0


def test_cli_check_nonzero_on_findings(tmp_path):
    from click.testing import CliRunner
    from skypilot_tpu.client import cli
    bad = tmp_path / 'server'
    bad.mkdir()
    f = bad / 'handler.py'
    f.write_text('import time\nasync def h():\n    time.sleep(1)\n')
    r = CliRunner().invoke(cli.cli, ['check', str(bad)])
    assert r.exit_code == 1
    assert 'SKY001' in r.output


def test_cli_check_select_filters(tmp_path):
    from click.testing import CliRunner
    from skypilot_tpu.client import cli
    bad = tmp_path / 'server'
    bad.mkdir()
    f = bad / 'handler.py'
    f.write_text('import time\nasync def h():\n    time.sleep(1)\n'
                 'def g():\n    try:\n        pass\n'
                 '    except Exception:\n        pass\n')
    r = CliRunner().invoke(cli.cli,
                           ['check', '--select', 'SKY005', str(bad)])
    assert r.exit_code == 1
    assert 'SKY005' in r.output and 'SKY001' not in r.output


def test_cli_check_json_reports_per_rule_timings(tmp_path):
    from click.testing import CliRunner
    from skypilot_tpu.client import cli
    clean = tmp_path / 'clean.py'
    clean.write_text('def f():\n    return 1\n')
    r = CliRunner().invoke(cli.cli,
                           ['check', '--format', 'json', str(clean)])
    assert r.exit_code == 0, r.output
    timings = json.loads(r.output)['timings_ms']
    for rule in ('SKY001', 'SKY008', 'SKY009', 'SKY010'):
        assert rule in timings
        assert timings[rule] >= 0


def test_cli_check_changed_empty_scope_exits_zero(tmp_path):
    # Nothing in the repo's `git diff` intersects a tmp scope, so
    # --changed short-circuits cleanly without analyzing anything.
    from click.testing import CliRunner
    from skypilot_tpu.client import cli
    r = CliRunner().invoke(
        cli.cli, ['check', '--changed', str(tmp_path)])
    assert r.exit_code == 0, r.output
    assert 'no changed .py files' in r.output


def test_cli_check_changed_analyzes_diffed_files(tmp_path,
                                                 monkeypatch):
    from click.testing import CliRunner
    from skypilot_tpu.client import cli
    bad = tmp_path / 'server'
    bad.mkdir()
    f = bad / 'handler.py'
    f.write_text('import time\nasync def h():\n    time.sleep(1)\n')
    seen = {}

    def fake_changed(scope, base):
        seen['base'] = base
        return [str(f)]

    monkeypatch.setattr(cli, '_changed_python_files', fake_changed)
    r = CliRunner().invoke(
        cli.cli, ['check', '--changed', '--base', 'main~1',
                  str(tmp_path)])
    assert seen['base'] == 'main~1'
    assert r.exit_code == 1
    assert 'SKY001' in r.output


def test_cli_check_migrate_baseline(tmp_path):
    from click.testing import CliRunner
    from skypilot_tpu.client import cli
    target = tmp_path / 'a.py'
    target.write_text(
        'import time\nasync def f():\n    time.sleep(1)\n')
    bpath = tmp_path / 'baseline.json'
    bpath.write_text(json.dumps({'version': 1, 'entries': [
        {'rule': 'SKY001', 'path': str(target), 'line': 3,
         'justification': 'legacy'},
        {'rule': 'SKY001', 'path': str(target), 'line': 99,
         'justification': 'stale'}]}))
    r = CliRunner().invoke(
        cli.cli, ['check', '--migrate-baseline',
                  '--baseline', str(bpath), str(target)])
    assert r.exit_code == 0, r.output
    assert 'Migrated' in r.output and '1 stale dropped' in r.output
    data = json.loads(bpath.read_text())
    assert data['version'] == 2
    assert [e['symbol'] for e in data['entries']] == ['f']
    # Post-migration the check is clean against the new baseline.
    r2 = CliRunner().invoke(
        cli.cli, ['check', '--baseline', str(bpath), str(target)])
    assert r2.exit_code == 0, r2.output


def test_cli_check_cloud_mode_still_works(monkeypatch):
    from click.testing import CliRunner
    from skypilot_tpu.client import cli, sdk
    monkeypatch.setattr(sdk, 'check', lambda: 'req-1')
    monkeypatch.setattr(sdk, 'get', lambda rid: ['gcp'])
    r = CliRunner().invoke(cli.cli, ['check'])
    assert r.exit_code == 0
    assert 'Enabled clouds: gcp' in r.output
    r2 = CliRunner().invoke(cli.cli, ['check', 'aws'])
    assert r2.exit_code == 0
    assert 'aws: disabled' in r2.output


def test_sdk_static_check(tmp_path):
    from skypilot_tpu.client import sdk
    f = tmp_path / 'x.py'
    f.write_text('import time\nasync def h():\n    time.sleep(1)\n')
    rows = sdk.static_check([str(f)])
    assert [(r['rule'], r['line']) for r in rows] == [('SKY001', 3)]
    assert rows[0]['col'] == 4 and 'time.sleep' in rows[0]['message']
