"""Self-supervising trainer guards (robustness/train_guard.py).

Tier-1 coverage of the three guard paths and the typed-exit
contract:

  - preemption-notice watcher: fake metadata server, SIGTERM, and
    fault-injected notices (incl. resume-scoped rules);
  - on-device NaN/spike guard: a tiny guarded ShardedTrainer really
    skips the poisoned update (params/opt_state unchanged) while the
    host-side SpikeGuard escalates to rollback after K;
  - step watchdog: stack dump + typed abort code, beats keep it
    quiet;
  - exit-code mapping: rc 83/84 -> PREEMPTED/WATCHDOG_ABORT agent
    statuses -> the controller's PREEMPTING -> RECOVERING path
    WITHOUT consuming the user-failure restart budget;
  - train_lm CLI: injected-NaN skip + rollback-after-K end-to-end in
    a subprocess, exiting rc 0.

The full managed-job chaos runs (notice mid-run -> graceful
checkpoint -> controller recovery with <=1 step lost; watchdog rc 84
through a real process) live in tests/test_chaos.py (slow tier).
"""
import http.server
import io
import json
import math
import os
import signal
import subprocess
import sys
import threading
import time

import pytest

from skypilot_tpu.robustness import faults
from skypilot_tpu.robustness import train_guard

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


@pytest.fixture(autouse=True)
def _clean_plan():
    faults.clear()
    yield
    faults.clear()


# ---------------------------------------------------------------------------
# SpikeGuard (host-side policy)
# ---------------------------------------------------------------------------
def test_spike_guard_warmup_then_ema_threshold():
    g = train_guard.SpikeGuard(spike_factor=4.0, warmup_steps=3,
                               rollback_after=2, ema_beta=0.5)
    assert g.threshold() == math.inf
    for step, gnorm in enumerate((1.0, 1.0, 1.0)):
        assert g.observe(step, 2.0, gnorm, False) == 'ok'
    # EMA of all-1.0 norms is 1.0 -> threshold = factor * 1.0.
    assert g.threshold() == pytest.approx(4.0)
    # A good step with a larger norm moves the EMA up.
    g.observe(3, 2.0, 3.0, False)
    assert g.threshold() == pytest.approx(4.0 * 2.0)


def test_spike_guard_rollback_after_k_and_reset():
    g = train_guard.SpikeGuard(spike_factor=4.0, warmup_steps=1,
                               rollback_after=3)
    assert g.observe(0, 2.0, 1.0, False) == 'ok'
    assert g.observe(1, math.nan, math.nan, True) == 'skipped'
    assert g.observe(2, math.nan, math.nan, True) == 'skipped'
    assert g.observe(3, math.nan, math.nan, True) == 'rollback'
    assert g.skipped_total == 3
    # A good step in between resets the consecutive counter.
    g2 = train_guard.SpikeGuard(rollback_after=2)
    assert g2.observe(0, math.nan, math.nan, True) == 'skipped'
    assert g2.observe(1, 2.0, 1.0, False) == 'ok'
    assert g2.observe(2, math.nan, math.nan, True) == 'skipped'
    assert g2.consecutive_bad == 1
    # Rollback re-warms the EMA (restored params may grad on a
    # different scale than the one the threshold latched onto).
    g.reset_after_rollback()
    assert g.rollbacks == 1
    assert g.consecutive_bad == 0
    assert g.threshold() == math.inf


def test_spike_guard_skip_counter_metric():
    from skypilot_tpu.observability import catalog
    child = catalog.counter('skypilot_train_guard_skipped_steps_total')
    before = child.value
    g = train_guard.SpikeGuard(rollback_after=5)
    g.observe(0, math.nan, math.nan, True)
    assert child.value == before + 1


# ---------------------------------------------------------------------------
# StepWatchdog
# ---------------------------------------------------------------------------
def test_watchdog_abort_dumps_stacks_and_exits_typed(tmp_path):
    from skypilot_tpu.observability import catalog
    counter = catalog.counter('skypilot_train_watchdog_aborts_total')
    before = counter.value
    codes = []
    # faulthandler writes through a REAL fd, not a StringIO.
    with open(tmp_path / 'wd.log', 'w+', encoding='utf-8') as stream:
        wd = train_guard.StepWatchdog(deadline_s=0.15,
                                      poll_interval_s=0.02,
                                      exit_fn=codes.append,
                                      stream=stream)
        wd.beat('data')
        wd.start()
        deadline = time.time() + 5
        while not codes and time.time() < deadline:
            time.sleep(0.02)
        wd.stop()
        stream.seek(0)
        out = stream.read()
    assert codes == [train_guard.EXIT_WATCHDOG_ABORT]
    assert wd.fired
    assert "phase 'data' stalled" in out
    assert 'File "' in out  # faulthandler stack frames
    assert counter.value == before + 1


def test_watchdog_beats_prevent_abort_and_override_deadline():
    codes = []
    wd = train_guard.StepWatchdog(deadline_s=0.1,
                                  poll_interval_s=0.02,
                                  exit_fn=codes.append,
                                  stream=io.StringIO())
    wd.start()
    for _ in range(10):
        wd.beat('step')
        time.sleep(0.03)
    assert not codes
    # A per-beat override (the compile-grace path) holds past the
    # base deadline.
    wd.beat('step', deadline_s=5.0)
    time.sleep(0.3)
    assert not codes
    wd.stop()


# ---------------------------------------------------------------------------
# PreemptionNotice
# ---------------------------------------------------------------------------
def test_preempt_notice_injected_and_resume_scoped():
    from skypilot_tpu.observability import catalog
    counter = catalog.counter('skypilot_train_preempt_notices_total')
    before = counter.value
    faults.install_plan({'rules': [
        {'point': 'train.preempt_notice', 'action': 'drop',
         'scope': {'resume': '0'}, 'after': 1}]})
    # A resumed run (resume=1) is scoped OUT: no notice, no hits.
    resumed = train_guard.PreemptionNotice(
        poll_interval_s=0.01, metadata_url='http://127.0.0.1:9/x',
        install_sigterm=False, ctx={'resume': '1'})
    resumed.start()
    time.sleep(0.15)
    resumed.stop()
    assert not resumed.notice.is_set()
    assert faults.stats()['train.preempt_notice']['hits'] == 0
    # The first launch (resume=0) gets the notice on poll 2.
    fresh = train_guard.PreemptionNotice(
        poll_interval_s=0.01, metadata_url='http://127.0.0.1:9/x',
        install_sigterm=False, ctx={'resume': '0'})
    fresh.start()
    assert fresh.notice.wait(timeout=5)
    fresh.stop()
    assert fresh.reason == 'injected'
    assert counter.value == before + 1


def test_preempt_notice_sigterm():
    notice = train_guard.PreemptionNotice(poll_interval_s=30.0,
                                          install_sigterm=True)
    notice.start()
    try:
        os.kill(os.getpid(), signal.SIGTERM)
        assert notice.notice.wait(timeout=5)
        assert notice.reason == 'sigterm'
    finally:
        notice.stop()  # restores the previous SIGTERM handler
    assert signal.getsignal(signal.SIGTERM) is not \
        notice._handle_sigterm


def test_preempt_notice_fake_metadata_server():
    """The GCE poll path: FALSE answers keep training; the first
    TRUE latches the notice with reason 'metadata'."""
    answers = ['FALSE', 'FALSE', 'TRUE']

    class _Meta(http.server.BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802
            assert self.headers.get('Metadata-Flavor') == 'Google'
            body = (answers.pop(0) if answers else 'TRUE').encode()
            self.send_response(200)
            self.send_header('Content-Length', str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):  # quiet
            pass

    server = http.server.ThreadingHTTPServer(('127.0.0.1', 0), _Meta)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    url = f'http://127.0.0.1:{server.server_address[1]}/preempted'
    notice = train_guard.PreemptionNotice(poll_interval_s=0.02,
                                          metadata_url=url,
                                          install_sigterm=False)
    notice.start()
    try:
        assert notice.notice.wait(timeout=10)
        assert notice.reason == 'metadata'
        assert notice.polls >= 3
    finally:
        notice.stop()
        server.shutdown()


def test_preempt_notice_metadata_unreachable_disables_polling():
    """Off-GCE (nothing listens): the poller gives up on the
    endpoint after a few strikes instead of spamming forever, but
    keeps polling the fault point."""
    notice = train_guard.PreemptionNotice(
        poll_interval_s=0.01,
        metadata_url='http://127.0.0.1:9/preempted',  # discard port
        install_sigterm=False)
    notice.start()
    time.sleep(0.3)
    notice.stop()
    assert not notice.notice.is_set()
    assert notice._metadata_failures >= train_guard._METADATA_MAX_FAILURES \
        or notice._metadata_failures == train_guard._METADATA_MAX_FAILURES
    assert notice.polls > train_guard._METADATA_MAX_FAILURES


# ---------------------------------------------------------------------------
# Guarded device step (parallel/train.py)
# ---------------------------------------------------------------------------
@pytest.fixture(scope='module')
def guarded_trainer():
    import jax
    import jax.numpy as jnp
    from skypilot_tpu.models.gpt import GPT, GPTConfig
    from skypilot_tpu.parallel import mesh as mesh_lib
    from skypilot_tpu.parallel.train import ShardedTrainer

    cfg = GPTConfig.tiny()
    mesh = mesh_lib.make_mesh(mesh_lib.MeshConfig.auto())
    trainer = ShardedTrainer(GPT(cfg), mesh, guard=True)
    example = jnp.zeros((8, 16), jnp.int32)
    state = trainer.init(jax.random.PRNGKey(0), example)
    step_fn = trainer.make_train_step(example, donate=False)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 16), 0,
                                cfg.vocab_size)
    return trainer, state, step_fn, tokens


def _leaves_equal(a, b):
    import jax
    import numpy as np
    return all(np.array_equal(x, y) for x, y in
               zip(jax.tree.leaves(a), jax.tree.leaves(b)))


def test_guard_forces_grad_norm_collection(guarded_trainer):
    trainer = guarded_trainer[0]
    assert trainer.guard and trainer.collect_grad_norm


def test_guarded_step_good_step_applies_update(guarded_trainer):
    import numpy as np
    _, state, step_fn, tokens = guarded_trainer
    new_state, (loss, gnorm, bad) = step_fn(state, tokens)
    assert not bool(bad)
    assert np.isfinite(float(loss)) and float(gnorm) > 0
    assert int(new_state.step) == int(state.step) + 1
    assert not _leaves_equal(new_state.params, state.params)


def test_guarded_step_skips_nan_loss(guarded_trainer):
    """loss_scale=NaN poisons loss AND grads through the real
    value_and_grad — the on-device isfinite guard must select the
    old params/opt_state while still consuming the step."""
    _, state, step_fn, tokens = guarded_trainer
    new_state, (loss, gnorm, bad) = step_fn(state, tokens,
                                            loss_scale=float('nan'))
    assert bool(bad)
    assert math.isnan(float(loss)) and math.isnan(float(gnorm))
    assert int(new_state.step) == int(state.step) + 1
    assert _leaves_equal(new_state.params, state.params)
    assert _leaves_equal(new_state.opt_state, state.opt_state)


def test_guarded_step_skips_grad_norm_spike(guarded_trainer):
    """A finite step whose global norm exceeds the host threshold is
    a spike: skipped exactly like a NaN."""
    _, state, step_fn, tokens = guarded_trainer
    new_state, (loss, gnorm, bad) = step_fn(state, tokens,
                                            max_grad_norm=1e-9)
    assert bool(bad)
    assert math.isfinite(float(loss)) and float(gnorm) > 1e-9
    assert _leaves_equal(new_state.params, state.params)


def test_unguarded_trainer_signature_unchanged():
    """No guard: the step fn keeps its (state, tokens) -> (state,
    loss) contract — existing callers (multi-step, pipeline tests)
    see no difference."""
    import jax
    import jax.numpy as jnp
    from skypilot_tpu.models.gpt import GPT, GPTConfig
    from skypilot_tpu.parallel import mesh as mesh_lib
    from skypilot_tpu.parallel.train import ShardedTrainer
    trainer = ShardedTrainer(GPT(GPTConfig.tiny()),
                             mesh_lib.make_mesh(
                                 mesh_lib.MeshConfig.auto()))
    assert not trainer.guard and not trainer.collect_grad_norm
    example = jnp.zeros((8, 16), jnp.int32)
    state = trainer.init(jax.random.PRNGKey(0), example)
    step_fn = trainer.make_train_step(example, donate=False)
    _, aux = step_fn(state, example)
    assert aux.shape == ()  # bare loss, no tuple


def test_committed_example_train_guard_plan_installs():
    """The shipped chaos plan names only known points and installs
    cleanly (an unknown point would fail at install, not by silently
    never firing)."""
    path = os.path.join(REPO, 'examples', 'fault_plans',
                        'train_guard_chaos.json')
    plan = faults.install_plan(path)
    assert plan is not None
    stats = faults.stats()
    assert {'train.step', 'train.data_next',
            'train.preempt_notice'} <= set(stats)


# ---------------------------------------------------------------------------
# Typed exit codes: agent status + controller recovery mapping
# ---------------------------------------------------------------------------
def test_exit_code_status_mapping():
    from skypilot_tpu.agent import job_lib
    assert job_lib.status_for_exit_code(
        train_guard.EXIT_PREEMPTED_GRACEFUL) == \
        job_lib.JobStatus.PREEMPTED
    assert job_lib.status_for_exit_code(
        train_guard.EXIT_WATCHDOG_ABORT) == \
        job_lib.JobStatus.WATCHDOG_ABORT
    assert job_lib.status_for_exit_code(1) is None
    assert job_lib.status_for_exit_code(0) is None
    for st in (job_lib.JobStatus.PREEMPTED,
               job_lib.JobStatus.WATCHDOG_ABORT):
        assert st.is_terminal()
        assert st.is_recoverable()
        assert st in job_lib.JobStatus.terminal_statuses()
    assert not job_lib.JobStatus.FAILED.is_recoverable()


def test_managed_status_preempting_not_terminal():
    from skypilot_tpu.jobs import state
    assert not state.ManagedJobStatus.PREEMPTING.is_terminal()
    assert not state.ManagedJobStatus.PREEMPTING.is_failed()


@pytest.mark.parametrize('typed_status,metric,expect_preemption', [
    ('PREEMPTED', 'skypilot_train_preempt_notices_total', True),
    ('WATCHDOG_ABORT', 'skypilot_train_watchdog_aborts_total', False),
])
def test_controller_typed_exit_takes_recovery_path(
        monkeypatch, typed_status, metric, expect_preemption):
    """A typed trainer exit must drive PREEMPTING -> _recover()
    (counted in its own catalog row) WITHOUT consuming the
    user-failure restart budget: stage_max_restarts=0 here, so the
    old FAILED mapping would have ended the job instead."""
    from skypilot_tpu.agent import job_lib as agent_job_lib
    from skypilot_tpu.jobs import controller as ctrl_mod
    from skypilot_tpu.jobs import failure_sources
    from skypilot_tpu.jobs import state
    from skypilot_tpu.observability import catalog

    monkeypatch.setattr(ctrl_mod, '_POLL_SECONDS', 0.005)
    monkeypatch.setattr(failure_sources, 'check_failed',
                        lambda name: None)
    status_log = []
    monkeypatch.setattr(state, 'set_status',
                        lambda jid, st, **kw: status_log.append(st))
    monkeypatch.setattr(state, 'set_stage', lambda jid, s: None)
    monkeypatch.setattr(state, 'set_agent_job_id', lambda jid, a: None)

    ctrl = ctrl_mod.JobController.__new__(ctrl_mod.JobController)
    ctrl.job_id = 1
    ctrl.cluster_name = 'typed-exit-c'
    ctrl.group = None
    ctrl.pooled = False
    ctrl.stage = 0
    ctrl.stage_configs = [{}]
    ctrl.stage_max_restarts = 0
    ctrl._stage_restarts = 0
    ctrl._cancelled = False

    recovered = []

    class _Agent:
        def get_job(self, agent_job_id):
            st = (agent_job_lib.JobStatus.SUCCEEDED if recovered
                  else agent_job_lib.JobStatus[typed_status])
            return {'status': st}

    ctrl._agent = lambda: _Agent()
    ctrl._cleanup = lambda cancel_job: None

    def _recover(preemption=True):
        recovered.append(preemption)
        return 2

    ctrl._recover = _recover
    child = catalog.counter(metric)
    before = child.value
    final = ctrl._monitor_loop(agent_job_id=1)
    assert final == state.ManagedJobStatus.SUCCEEDED
    assert recovered == [expect_preemption]
    assert state.ManagedJobStatus.PREEMPTING in status_log
    assert child.value == before + 1
    # The typed exit never touched the user-failure restart budget.
    assert ctrl._stage_restarts == 0


def test_recover_skips_zone_preemption_counter_for_watchdog(
        monkeypatch):
    """_recover(preemption=False) still records the recovery event
    (latency accounting) but must not inflate the zone spot-storm
    signal."""
    from skypilot_tpu.jobs import controller as ctrl_mod
    from skypilot_tpu.jobs import state
    from skypilot_tpu.observability import catalog

    events = []
    monkeypatch.setattr(state, 'set_status',
                        lambda jid, st, **kw: None)
    monkeypatch.setattr(state, 'bump_recovery', lambda jid: None)
    monkeypatch.setattr(state, 'record_preemption',
                        lambda jid, z: events.append(('pre', z)))
    monkeypatch.setattr(state, 'record_recovered',
                        lambda jid: events.append(('rec', None)))
    monkeypatch.setattr(state, 'set_agent_job_id',
                        lambda jid, a: None)

    ctrl = ctrl_mod.JobController.__new__(ctrl_mod.JobController)
    ctrl.job_id = 7
    ctrl.cluster_name = 'wd-c'
    ctrl.group = None
    ctrl._zone = lambda: 'test-zone-wd'

    class _Exec:
        def recover(self):
            return 3

    ctrl.executor = _Exec()
    zone_child = catalog.counter(
        'skypilot_jobs_preemptions_total').labels(zone='test-zone-wd')
    before = zone_child.value
    assert ctrl._recover(preemption=False) == 3
    assert zone_child.value == before
    assert ('pre', 'test-zone-wd') in events and ('rec', None) in events
    assert ctrl._recover(preemption=True) == 3
    assert zone_child.value == before + 1


# ---------------------------------------------------------------------------
# train_lm CLI: injected NaN -> skip -> rollback-after-K, rc 0
# ---------------------------------------------------------------------------
def test_train_lm_nan_skip_and_rollback_e2e(tmp_path):
    """Chaos acceptance: a fault plan poisons steps 4-6 with NaN; the
    guard skips each (loss=nan printed, params protected), the third
    consecutive skip rolls back to the last checkpoint — step 4,
    BEFORE the streak, so the step sequence really rewinds — and the
    run still completes rc=0 with every step covered."""
    from skypilot_tpu.observability.step_metrics import read_jsonl
    ckpt = tmp_path / 'ckpt'
    metrics = tmp_path / 'steps.jsonl'
    env = {k: v for k, v in os.environ.items() if k != 'XLA_FLAGS'}
    env['STPU_FAULT_PLAN'] = json.dumps({'rules': [
        {'point': 'train.step', 'action': 'drop', 'at': [5, 6, 7]}]})
    proc = subprocess.run(
        [sys.executable, '-m', 'skypilot_tpu.recipes.train_lm',
         '--cpu', '--model', 'tiny', '--steps', '10', '--seq', '16',
         '--global-batch', '4', '--log-every', '1', '--guard',
         '--guard-warmup', '1', '--rollback-after', '3',
         '--ckpt-dir', str(ckpt), '--ckpt-every', '4',
         '--metrics-file', str(metrics)],
        cwd=REPO, env=env, capture_output=True, text=True,
        timeout=420)
    out = proc.stdout + proc.stderr
    assert proc.returncode == 0, out
    assert out.count('injected NaN into step') == 3
    assert 'update skipped' in out
    assert 'rolling back' in out
    assert 'rolled back to last checkpoint (step 4)' in out
    assert "'skipped_steps': 3, 'rollbacks': 1" in out
    records = read_jsonl(str(metrics))
    steps = [r['step'] for r in records]
    # The rollback rewinds the step sequence once (back to the
    # checkpoint at step 4), then the rerun covers everything
    # through the final step.
    assert steps[-1] == 10
    assert any(b <= a for a, b in zip(steps, steps[1:])), steps
    assert set(steps) >= set(range(1, 11)), steps
    # Post-rollback steps are clean: the last record's loss is finite.
    assert math.isfinite(records[-1]['loss'])


def test_train_lm_watchdog_stall_aborts_rc84(tmp_path):
    """Chaos acceptance: a delayed train.data_next (stalled loader)
    trips the step watchdog within its deadline — thread stacks are
    dumped and the process exits with the typed code 84."""
    env = {k: v for k, v in os.environ.items() if k != 'XLA_FLAGS'}
    env['STPU_FAULT_PLAN'] = json.dumps({'rules': [
        {'point': 'train.data_next', 'action': 'delay',
         'delay_s': 300, 'after': 2, 'times': 1}]})
    t0 = time.time()
    proc = subprocess.run(
        [sys.executable, '-m', 'skypilot_tpu.recipes.train_lm',
         '--cpu', '--model', 'tiny', '--steps', '6', '--seq', '16',
         '--global-batch', '4', '--guard',
         '--watchdog-deadline', '3',
         '--watchdog-compile-deadline', '120'],
        cwd=REPO, env=env, capture_output=True, text=True,
        timeout=420)
    out = proc.stdout + proc.stderr
    assert proc.returncode == train_guard.EXIT_WATCHDOG_ABORT, out
    # Aborted within the deadline (+ compile + slack), not the 300s
    # injected stall.
    assert time.time() - t0 < 200
    assert "step-watchdog: phase 'data' stalled" in out
    assert 'next_tokens' in out  # the stalled frame is in the dump


def test_train_lm_preempt_notice_rc83_then_resume(tmp_path):
    """Chaos acceptance: an injected preemption notice (scoped to
    resume=0) makes the trainer checkpoint NOW and exit rc 83; the
    SAME command relaunched resumes from that checkpoint, survives
    (the scoped rule ignores resume=1), and finishes every step."""
    from skypilot_tpu.observability.step_metrics import read_jsonl
    ckpt = tmp_path / 'ckpt'
    metrics = tmp_path / 'steps.jsonl'
    env = {k: v for k, v in os.environ.items() if k != 'XLA_FLAGS'}
    env['STPU_FAULT_PLAN'] = json.dumps({'rules': [
        {'point': 'train.preempt_notice', 'action': 'drop',
         'scope': {'resume': '0'}, 'after': 1}]})
    cmd = [sys.executable, '-m', 'skypilot_tpu.recipes.train_lm',
           '--cpu', '--model', 'tiny', '--steps', '6', '--seq', '16',
           '--global-batch', '4', '--log-every', '1', '--guard',
           '--preempt-poll', '0.3', '--ckpt-dir', str(ckpt),
           '--ckpt-every', '100', '--metrics-file', str(metrics)]
    first = subprocess.run(cmd, cwd=REPO, env=env,
                           capture_output=True, text=True,
                           timeout=420)
    out = first.stdout + first.stderr
    assert first.returncode == train_guard.EXIT_PREEMPTED_GRACEFUL, out
    assert 'preemption notice (injected)' in out
    saved = [int(d) for d in os.listdir(ckpt) if d.isdigit()]
    assert saved, 'graceful exit must leave a checkpoint behind'
    second = subprocess.run(cmd, cwd=REPO, env=env,
                            capture_output=True, text=True,
                            timeout=420)
    out2 = second.stdout + second.stderr
    assert second.returncode == 0, out2
    assert f'resumed from checkpoint step {max(saved)}' in out2
    assert 'training done' in out2
    # <=1 optimizer step lost: the resumed run's first logged step
    # continues at (or past) the last step logged before the exit.
    steps = [r['step'] for r in read_jsonl(str(metrics))]
    assert steps[-1] == 6
    assert steps == sorted(steps), steps  # no rewound work
    assert len(steps) == len(set(steps)), steps  # no step run twice
