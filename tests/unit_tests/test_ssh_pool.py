"""SSH node pools: parsing, allocation bookkeeping, release."""
import pytest

from skypilot_tpu import exceptions
from skypilot_tpu.clouds import ssh as ssh_cloud
from skypilot_tpu.provision import common
from skypilot_tpu.provision.ssh import instance as ssh_instance


@pytest.fixture()
def pool_file(isolated_state, tmp_path, monkeypatch):
    path = tmp_path / 'pools.yaml'
    path.write_text("""
pools:
  lab:
    user: ubuntu
    identity_file: ~/.ssh/lab_key
    hosts:
      - 10.9.0.1
      - 10.9.0.2
      - ip: 10.9.0.3
        user: admin
        port: 2222
""")
    monkeypatch.setattr(ssh_cloud, 'POOLS_PATH', str(path))
    return str(path)


def test_pool_parsing(pool_file):
    pools = ssh_cloud.load_pools(pool_file)
    hosts = pools['lab']['hosts']
    assert len(hosts) == 3
    assert hosts[0] == {'ip': '10.9.0.1', 'user': 'ubuntu',
                        'identity_file': '~/.ssh/lab_key', 'port': 22}
    assert hosts[2]['user'] == 'admin' and hosts[2]['port'] == 2222


def _config(count):
    return common.ProvisionConfig(provider_config={'pool': 'lab'},
                                  authentication_config={}, count=count,
                                  tags={})


def test_allocation_and_release(pool_file):
    rec = ssh_instance.run_instances('lab', 'c1', _config(2))
    assert rec.created_instance_ids == ['10.9.0.1', '10.9.0.2']
    info = ssh_instance.get_cluster_info('lab', 'c1', rec.provider_config)
    assert info.num_instances == 2
    assert info.ssh_user == 'ubuntu'
    assert info.get_head_instance().ssh_port == 22

    # Second cluster gets the remaining host; a third request overflows.
    rec2 = ssh_instance.run_instances('lab', 'c2', _config(1))
    assert rec2.created_instance_ids == ['10.9.0.3']
    with pytest.raises(exceptions.ProvisionerError) as exc_info:
        ssh_instance.run_instances('lab', 'c3', _config(1))
    assert exc_info.value.category == exceptions.ProvisionerError.CAPACITY

    # Idempotent re-run returns the same allocation.
    again = ssh_instance.run_instances('lab', 'c1', _config(2))
    assert again.created_instance_ids == rec.created_instance_ids

    # Release frees capacity.
    ssh_instance.terminate_instances('c1')
    rec3 = ssh_instance.run_instances('lab', 'c3', _config(2))
    assert set(rec3.created_instance_ids) == {'10.9.0.1', '10.9.0.2'}
    assert ssh_instance.query_instances('c2') == {'10.9.0.3': 'running'}


def test_feasibility_respects_pool_size(pool_file):
    cloud = ssh_cloud.SSH()
    from skypilot_tpu.resources import Resources
    r = Resources()
    feas = cloud.get_feasible_launchable_resources(r, num_nodes=3)
    assert feas.resources_list
    feas = cloud.get_feasible_launchable_resources(r, num_nodes=4)
    assert not feas.resources_list
    with pytest.raises(ValueError):
        cloud.validate_region_zone('nope', None)
