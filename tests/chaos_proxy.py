"""A TCP chaos proxy: forwards to a target, randomly killing flows.

Reference: tests/chaos/chaos_proxy.py — sits between the SDK and the
API server and injects the failures a flaky network would: refused
connects (drop-on-accept) and mid-stream resets. Deterministic via
`seed` so failures reproduce.
"""
from __future__ import annotations

import random
import socket
import threading
from typing import Optional


class ChaosProxy:

    def __init__(self, target_host: str, target_port: int,
                 drop_prob: float = 0.3, reset_prob: float = 0.1,
                 seed: int = 0) -> None:
        self.target = (target_host, target_port)
        self.drop_prob = drop_prob
        self.reset_prob = reset_prob
        self.rng = random.Random(seed)
        self.listener = socket.socket()
        self.listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.listener.bind(('127.0.0.1', 0))
        self.listener.listen(64)
        self.port = self.listener.getsockname()[1]
        self._stop = threading.Event()
        self.stats = {'accepted': 0, 'dropped': 0, 'reset': 0}
        self._thread = threading.Thread(target=self._accept_loop,
                                        daemon=True)
        self._thread.start()

    def close(self) -> None:
        self._stop.set()
        try:
            self.listener.close()
        except OSError:
            pass

    # -- internals -----------------------------------------------------------
    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                client, _ = self.listener.accept()
            except OSError:
                return
            self.stats['accepted'] += 1
            if self.rng.random() < self.drop_prob:
                # Refused-connection flavor: close before any bytes.
                self.stats['dropped'] += 1
                client.close()
                continue
            reset_at: Optional[int] = None
            if self.rng.random() < self.reset_prob:
                reset_at = self.rng.randint(1, 2048)
                self.stats['reset'] += 1
            threading.Thread(target=self._pipe_pair,
                             args=(client, reset_at), daemon=True).start()

    def _pipe_pair(self, client: socket.socket,
                   reset_at: Optional[int]) -> None:
        try:
            upstream = socket.create_connection(self.target, timeout=10)
        except OSError:
            client.close()
            return

        budget = [reset_at]  # shared mid-stream reset byte budget

        def pipe(src: socket.socket, dst: socket.socket) -> None:
            try:
                while True:
                    data = src.recv(65536)
                    if not data:
                        break
                    if budget[0] is not None:
                        if len(data) >= budget[0]:
                            raise OSError('chaos reset')
                        budget[0] -= len(data)
                    dst.sendall(data)
            except OSError:
                pass
            finally:
                for s in (src, dst):
                    try:
                        s.shutdown(socket.SHUT_RDWR)
                    except OSError:
                        pass
                    try:
                        s.close()
                    except OSError:
                        pass

        threading.Thread(target=pipe, args=(client, upstream),
                         daemon=True).start()
        pipe(upstream, client)
