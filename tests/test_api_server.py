"""API server tests: a real server process driven through the SDK.

Reference strategy: in-process FastAPI testclient
(tests/common_test_fixtures.py:33-40); here the server is cheap enough
to run for real — a subprocess with an isolated home — which also
covers the executor's process model and the auto-start path.
"""
import os
import socket
import subprocess
import sys
import time

import pytest
import requests

import skypilot_tpu
from skypilot_tpu import constants
from skypilot_tpu import exceptions
from skypilot_tpu.client import sdk
from skypilot_tpu.task import Task


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(('127.0.0.1', 0))
        return s.getsockname()[1]


@pytest.fixture()
def api_server(isolated_state, monkeypatch):
    port = _free_port()
    url = f'http://127.0.0.1:{port}'
    env = dict(os.environ)
    env['SKYPILOT_TPU_HOME'] = isolated_state
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env['PYTHONPATH'] = f"{repo_root}:{env.get('PYTHONPATH', '')}"
    proc = subprocess.Popen(
        [sys.executable, '-m', 'skypilot_tpu.server.server',
         '--port', str(port)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    monkeypatch.setenv(constants.API_SERVER_URL_ENV_VAR, url)
    deadline = time.time() + 30
    while time.time() < deadline:
        if sdk.api_info(url) is not None:
            break
        if proc.poll() is not None:
            out = proc.stdout.read().decode()
            raise RuntimeError(f'server died: {out[-2000:]}')
        time.sleep(0.3)
    else:
        raise RuntimeError('server did not come up')
    yield url
    proc.terminate()
    try:
        proc.wait(timeout=15)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.wait(timeout=5)


@pytest.mark.slow
def test_health_and_async_requests(api_server):
    info = sdk.api_info()
    assert info['status'] == 'healthy'

    # check (SHORT queue)
    rid = sdk.check()
    assert sdk.get(rid) == ['local']

    # status on empty state
    assert sdk.get(sdk.status()) == []

    # request bookkeeping
    rows = sdk.api_status()
    names = {r['name'] for r in rows}
    assert {'check', 'status'}.issubset(names)
    assert all(r['status'] == 'SUCCEEDED' for r in rows)


@pytest.mark.slow
def test_launch_exec_logs_down_via_server(api_server):
    sdk.get(sdk.check())
    task = Task(name='t', run='echo via-server-rank-$SKYPILOT_NODE_RANK')
    task.set_resources(skypilot_tpu.Resources(infra='local',
                                              accelerators='tpu-v5e-16'))
    rid = sdk.launch(task, cluster_name='srv1')
    result = sdk.get(rid)
    assert result['job_id'] == 1
    assert result['handle']['num_hosts'] == 2

    # Wait for job to finish, then pull logs through the server proxy.
    import io
    deadline = time.time() + 60
    while time.time() < deadline:
        jobs = sdk.get(sdk.queue('srv1'))
        if jobs and jobs[0]['status'] in ('SUCCEEDED', 'FAILED'):
            break
        time.sleep(1)
    assert jobs[0]['status'] == 'SUCCEEDED'
    buf = io.StringIO()
    sdk.tail_logs('srv1', 1, follow=False, output=buf)
    logs = buf.getvalue()
    assert 'via-server-rank-0' in logs and 'via-server-rank-1' in logs

    # Failed request propagates as the original typed error.
    rid = sdk.exec(Task(run='true'), 'does-not-exist')
    with pytest.raises(exceptions.ClusterDoesNotExist):
        sdk.get(rid)

    sdk.get(sdk.down('srv1'))
    assert sdk.get(sdk.status()) == []


@pytest.mark.slow
def test_request_cancel(api_server):
    sdk.get(sdk.check())
    # A launch that will sit provisioning? Local provisions instantly, so
    # cancel a long-running status refresh instead: use launch of a task
    # with a long-running setup, then cancel the request mid-flight.
    task = Task(name='slow-setup', run='true', setup='sleep 120')
    task.set_resources(skypilot_tpu.Resources(infra='local'))
    rid = sdk.launch(task, cluster_name='srv2')
    # wait until RUNNING
    deadline = time.time() + 30
    while time.time() < deadline:
        rows = {r['request_id']: r for r in sdk.api_status()}
        if rows.get(rid, {}).get('status') == 'RUNNING':
            break
        time.sleep(0.5)
    assert sdk.api_cancel(rid) is True
    with pytest.raises(exceptions.RequestCancelled):
        sdk.get(rid)
    # cleanup
    try:
        sdk.get(sdk.down('srv2'))
    except exceptions.SkyError:
        pass


@pytest.mark.slow
def test_rbac_tokens_and_enforcement(api_server, monkeypatch):
    """Service-token identity + role enforcement (reference:
    sky/users/permission.py, sky/server/auth/). Issuing the first token
    flips auth on; identity is derived from the token, not the header;
    `user` role cannot mutate another user's cluster or admin routes."""
    url = api_server

    # Open mode: no tokens yet, anyone is admin — mint alice (admin)
    # and bob (user).
    alice = sdk.token_issue('alice', role='admin')
    # First token exists -> unauthenticated requests are now rejected.
    r = requests.get(f'{url}/users', timeout=10)
    assert r.status_code == 401
    with pytest.raises(exceptions.PermissionDeniedError):
        sdk.token_ls()

    monkeypatch.setenv('SKYPILOT_API_TOKEN', alice['token'])
    bob = sdk.token_issue('bob', role='user')
    assert {t['user_hash'] for t in sdk.token_ls()} == {'alice', 'bob'}

    # Alice launches a cluster; identity must come from her token even
    # though the spoofable header says otherwise.
    monkeypatch.setenv('SKYPILOT_USER', 'mallory')
    task = Task(run='true')
    task.set_resources(skypilot_tpu.Resources(infra='local'))
    sdk.get(sdk.launch(task, cluster_name='rbac-c'))
    recs = sdk.get(sdk.status())
    rec = next(r for r in recs if r['name'] == 'rbac-c')
    assert rec['user'] == 'alice'

    # Bob (role user) may not down alice's cluster: 403 at scheduling.
    monkeypatch.setenv('SKYPILOT_API_TOKEN', bob['token'])
    with pytest.raises(exceptions.PermissionDeniedError):
        sdk.down('rbac-c')
    # ...nor touch admin-only routes.
    with pytest.raises(exceptions.PermissionDeniedError):
        sdk.token_issue('eve', role='admin')
    with pytest.raises(exceptions.PermissionDeniedError):
        sdk.users_set_role('bob', 'admin')
    # Bob can read and manage his own things.
    assert any(r['name'] == 'rbac-c' for r in sdk.get(sdk.status()))
    sdk.get(sdk.launch(Task(run='true'), cluster_name='rbac-bob'))

    # Alice (admin) downs everything, then revokes bob's token.
    monkeypatch.setenv('SKYPILOT_API_TOKEN', alice['token'])
    sdk.get(sdk.down('rbac-bob'))
    sdk.get(sdk.down('rbac-c'))
    assert sdk.token_revoke(bob['token_id'])
    monkeypatch.setenv('SKYPILOT_API_TOKEN', bob['token'])
    with pytest.raises(exceptions.PermissionDeniedError):
        sdk.token_ls()


@pytest.mark.slow
def test_serve_logs_route_404(api_server):
    r = requests.get(f'{api_server}/serve/logs',
                     params={'service': 'nope'}, timeout=10)
    assert r.status_code == 404


@pytest.mark.slow
def test_server_plugin_routes(isolated_state, monkeypatch, tmp_path):
    """api_server.plugins modules get register(app) called at startup
    (reference: sky/server/plugin_hooks.py)."""
    plug_dir = tmp_path / 'plugins'
    plug_dir.mkdir()
    (plug_dir / 'my_plugin.py').write_text(
        'from aiohttp import web\n'
        'async def hello(request):\n'
        "    return web.json_response({'plugin': 'alive'})\n"
        'def register(app):\n'
        "    app.router.add_get('/plugin/hello', hello)\n")
    cfg = tmp_path / 'cfg.yaml'
    cfg.write_text('api_server:\n  plugins: [my_plugin]\n')

    port = _free_port()
    env = dict(os.environ)
    env['SKYPILOT_TPU_HOME'] = isolated_state
    env['SKYPILOT_TPU_CONFIG'] = str(cfg)
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env['PYTHONPATH'] = f"{repo_root}:{plug_dir}:{env.get('PYTHONPATH', '')}"
    proc = subprocess.Popen(
        [sys.executable, '-m', 'skypilot_tpu.server.server',
         '--port', str(port)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    try:
        url = f'http://127.0.0.1:{port}'
        deadline = time.time() + 30
        while time.time() < deadline:
            try:
                if requests.get(f'{url}/api/health', timeout=2).ok:
                    break
            except requests.RequestException:
                pass
            assert proc.poll() is None, proc.stdout.read().decode()[-1500:]
            time.sleep(0.3)
        resp = requests.get(f'{url}/plugin/hello', timeout=10)
        assert resp.json() == {'plugin': 'alive'}
    finally:
        proc.terminate()
        proc.wait(timeout=15)


@pytest.mark.slow
def test_metrics_orchestration_gauges(api_server):
    sdk.get(sdk.check())
    task = Task(run='true')
    task.set_resources(skypilot_tpu.Resources(infra='local'))
    sdk.get(sdk.launch(task, cluster_name='met-c'))
    text = requests.get(f'{api_server}/api/metrics', timeout=10).text
    assert 'skypilot_clusters{status="up"} 1' in text
    assert 'skypilot_managed_jobs' in text
    assert 'skypilot_services 0' in text
    assert 'skypilot_server_rss_bytes' in text
    sdk.get(sdk.down('met-c'))


@pytest.mark.slow
def test_websocket_attach_interactive_shell(api_server):
    """The /attach websocket bridges a PTY shell on the cluster head
    (reference: the server-side websocket SSH tunnel): commands typed
    over the WS execute in the sandbox and output streams back."""
    import asyncio
    import json as json_lib

    import aiohttp

    url = api_server
    rid = requests.post(f'{url}/launch', json={
        'task_config': {'run': 'true', 'resources': {'infra': 'local'}},
        'cluster_name': 'att-c',
    }, timeout=10).json()['request_id']
    deadline = time.time() + 120
    while time.time() < deadline:
        rec = requests.get(f'{url}/api/get',
                           params={'request_id': rid, 'timeout': 5},
                           timeout=30).json()
        if rec['status'] in ('SUCCEEDED', 'FAILED'):
            break
    assert rec['status'] == 'SUCCEEDED', rec

    ws_url = 'ws' + url[len('http'):] + '/attach?cluster=att-c&node=0'

    async def drive() -> str:
        out = b''
        async with aiohttp.ClientSession() as session:
            async with session.ws_connect(ws_url, max_msg_size=0) as ws:
                await ws.send_str(json_lib.dumps({'resize': [24, 80]}))
                await ws.send_bytes(b'echo at$((40+2))tach\n')
                deadline2 = time.time() + 30
                while time.time() < deadline2:
                    try:
                        msg = await ws.receive(timeout=5)
                    except asyncio.TimeoutError:
                        continue
                    if msg.type == aiohttp.WSMsgType.BINARY:
                        out += msg.data
                        if b'at42tach' in out:
                            break
                    elif msg.type in (aiohttp.WSMsgType.CLOSED,
                                      aiohttp.WSMsgType.ERROR):
                        break
                await ws.send_bytes(b'exit\n')
        return out.decode(errors='replace')

    out = asyncio.new_event_loop().run_until_complete(drive())
    assert 'at42tach' in out, out

    # Unknown cluster -> 404, not a ws upgrade.
    resp = requests.get(f'{url}/attach', params={'cluster': 'nope'},
                        timeout=10)
    assert resp.status_code == 404

    # WAIT for the down to finish: firing it and tearing the server
    # down kills the worker mid-terminate and leaks the cluster's
    # agent process (observed: one orphaned agent per run).
    rid = requests.post(f'{url}/down', json={'cluster_name': 'att-c'},
                        timeout=10).json()['request_id']
    deadline = time.time() + 120
    while time.time() < deadline:
        rec = requests.get(f'{url}/api/get',
                           params={'request_id': rid, 'timeout': 5},
                           timeout=30).json()
        if rec['status'] in ('SUCCEEDED', 'FAILED', 'CANCELLED'):
            break
    assert rec['status'] == 'SUCCEEDED', rec
