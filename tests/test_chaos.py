"""Chaos + robustness harness for the API server.

Reference strategy: tests/chaos/chaos_proxy.py (SDK↔server TCP-drop
proxy), tests/smoke_tests/backward_compat (client/server version
skew), and the executor's restart-recovery scan.
"""
import json
import os
import signal
import socket
import subprocess
import sys
import time

import pytest
import requests

from skypilot_tpu import constants
from skypilot_tpu import exceptions
from skypilot_tpu.client import sdk

from tests.chaos_proxy import ChaosProxy


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(('127.0.0.1', 0))
        return s.getsockname()[1]


def _start_server(home: str, port: int) -> subprocess.Popen:
    env = dict(os.environ)
    env['SKYPILOT_TPU_HOME'] = home
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env['PYTHONPATH'] = f"{repo_root}:{env.get('PYTHONPATH', '')}"
    proc = subprocess.Popen(
        [sys.executable, '-m', 'skypilot_tpu.server.server',
         '--port', str(port)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    deadline = time.time() + 30
    url = f'http://127.0.0.1:{port}'
    while time.time() < deadline:
        try:
            if requests.get(f'{url}/api/health', timeout=2).ok:
                return proc
        except requests.RequestException:
            pass
        if proc.poll() is not None:
            raise RuntimeError(
                f'server died: {proc.stdout.read().decode()[-1500:]}')
        time.sleep(0.3)
    raise RuntimeError('server did not come up')


@pytest.fixture()
def chaos_server(isolated_state, monkeypatch):
    port = _free_port()
    proc = _start_server(isolated_state, port)
    yield isolated_state, port, proc
    if proc.poll() is None:
        proc.terminate()
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()


@pytest.mark.slow
def test_sdk_survives_chaos_proxy(chaos_server, monkeypatch):
    """Requests through a connection-dropping proxy still complete:
    the SDK's retry loop rides out refused connects and mid-stream
    resets (reference: tests/chaos/chaos_proxy.py)."""
    _home, port, _proc = chaos_server
    proxy = ChaosProxy('127.0.0.1', port, drop_prob=0.3, reset_prob=0.15,
                       seed=7)
    monkeypatch.setenv(constants.API_SERVER_URL_ENV_VAR,
                       f'http://127.0.0.1:{proxy.port}')
    try:
        ok = 0
        for _ in range(10):
            rid = sdk.check()          # schedules through the proxy
            assert sdk.get(rid) == ['local']
            ok += 1
        assert ok == 10
        # The proxy really did inject failures we rode out.
        assert proxy.stats['dropped'] + proxy.stats['reset'] > 0
    finally:
        proxy.close()


@pytest.mark.slow
def test_executor_restart_fails_inflight_requests(chaos_server,
                                                  monkeypatch):
    """A server killed mid-request marks the orphaned request FAILED on
    restart instead of leaving it RUNNING forever (executor.py start()
    recovery scan; reference: sky/server/requests/executor.py)."""
    home, port, proc = chaos_server
    url = f'http://127.0.0.1:{port}'
    monkeypatch.setenv(constants.API_SERVER_URL_ENV_VAR, url)

    # A request that stays in-flight (detach_run=False waits on the
    # 300s job) until we crash the whole server host-side.
    rid = requests.post(f'{url}/launch', json={
        'task_config': {'run': 'sleep 300',
                        'resources': {'infra': 'local'}},
        'cluster_name': 'chaos-c',
        'detach_run': False,
    }, timeout=10).json()['request_id']
    deadline = time.time() + 60
    while time.time() < deadline:
        rec = requests.get(f'{url}/api/get',
                           params={'request_id': rid, 'timeout': 0.1},
                           timeout=10).json()
        if rec['status'] == 'RUNNING':
            break
        time.sleep(0.5)
    assert rec['status'] == 'RUNNING'

    # Crash the server AND its worker (workers run in their own process
    # group and deliberately survive a server-only crash — that is the
    # in-flight-request-completes path; here we simulate host loss).
    import sqlite3
    db = sqlite3.connect(os.path.join(home, 'api_server', 'requests.db'))
    worker_pid = db.execute(
        'SELECT pid FROM requests WHERE request_id=?', (rid,)).fetchone()[0]
    db.close()
    proc.send_signal(signal.SIGKILL)
    proc.wait(timeout=10)
    from skypilot_tpu.utils import subprocess_utils
    subprocess_utils.kill_process_tree(worker_pid)
    deadline = time.time() + 10
    while time.time() < deadline and \
            subprocess_utils.process_alive(worker_pid):
        time.sleep(0.2)

    port2 = _free_port()
    proc2 = _start_server(home, port2)
    try:
        url2 = f'http://127.0.0.1:{port2}'
        rec = requests.get(f'{url2}/api/get',
                           params={'request_id': rid, 'timeout': 0.1},
                           timeout=10).json()
        assert rec['status'] == 'FAILED'
        assert 'restarted' in json.dumps(rec.get('error', ''))
        # Best-effort teardown of the half-launched cluster.
        try:
            cleanup = requests.post(
                f'{url2}/down',
                json={'cluster_name': 'chaos-c', 'purge': True},
                timeout=10).json()
            requests.get(f'{url2}/api/get',
                         params={'request_id': cleanup['request_id'],
                                 'timeout': 30}, timeout=40)
        except Exception:  # pylint: disable=broad-except
            pass
    finally:
        proc2.terminate()
        proc2.wait(timeout=10)


@pytest.mark.slow
def test_managed_job_chaos_preemption_checkpoint_resume(
        isolated_state, monkeypatch):
    """End-to-end chaos: a fault plan (inherited via STPU_FAULT_PLAN
    by the spawned controller) DROPS the controller's agent probes
    mid-run — a synthetic preemption. The controller must walk its
    real unreachable-grace machinery into recovery (terminate +
    relaunch), and the job must RESUME from its checkpoint file
    rather than restart from scratch (the SURVEY §2.6 contract the
    reference can only smoke-test on real spot instances)."""
    from skypilot_tpu import check
    from skypilot_tpu.jobs import core as jobs_core
    from skypilot_tpu.jobs import state

    monkeypatch.setenv('SKYPILOT_JOBS_POLL_SECONDS', '1')
    monkeypatch.setenv('SKYPILOT_JOBS_UNREACHABLE_GRACE_SECONDS', '3')
    # Probes 1-2 succeed (the job gets running time), then every
    # probe drops until the recovery relaunch consumes the budget.
    monkeypatch.setenv('STPU_FAULT_PLAN', json.dumps({'rules': [
        {'point': 'jobs.monitor_probe', 'action': 'drop',
         'after': 2, 'times': 8}]}))
    check.check(quiet=True)

    ckpt = os.path.join(isolated_state, 'chaos-ckpt')
    log = os.path.join(isolated_state, 'chaos-steps')
    # Checkpoint-resume workload: every (re)start continues from the
    # last checkpointed step; log BEFORE checkpointing so a kill
    # between the two at worst repeats one boundary step.
    run = (f'c=$(cat {ckpt} 2>/dev/null || echo 0); '
           f'for i in $(seq $((c+1)) 6); do '
           f'echo step-$i >> {log}; echo $i > {ckpt}; sleep 1; done')
    result = jobs_core.launch(
        {'name': 'chaos-mj', 'resources': {'infra': 'local'},
         'run': run}, user='t')
    job_id = result['job_id']

    deadline = time.time() + 300
    final = None
    while time.time() < deadline:
        job = state.get_job(job_id)
        if job['status'].is_terminal():
            final = job['status']
            break
        time.sleep(1)
    job = state.get_job(job_id)
    assert final == state.ManagedJobStatus.SUCCEEDED, job
    # The synthetic preemption really drove recovery...
    assert job['recovery_count'] >= 1, job
    # ...and the workload RESUMED from its checkpoint: all six steps
    # ran, in non-decreasing order (a from-scratch restart would
    # rewind the sequence), ending at the checkpointed step 6.
    with open(log, 'r', encoding='utf-8') as f:
        steps = [int(line.split('-')[1]) for line in f
                 if line.startswith('step-')]
    assert steps == sorted(steps), steps
    assert set(steps) == set(range(1, 7)), steps
    with open(ckpt, 'r', encoding='utf-8') as f:
        assert f.read().strip() == '6'
    jobs_core.cancel([job_id])


def _run_train_guard_managed_job(isolated_state, monkeypatch, *,
                                 fault_rules, steps, log_marker,
                                 extra_flags=''):
    """Launch a guarded train_lm as a managed job under a fault plan,
    wait for SUCCEEDED, and return (job record, metric steps,
    controller log). Shared by the preemption-notice and watchdog
    chaos runs: both must end in SUCCESS via the typed-exit recovery
    path, with the step log proving <=1 optimizer step lost."""
    import glob

    from skypilot_tpu import check, constants
    from skypilot_tpu.jobs import core as jobs_core
    from skypilot_tpu.jobs import state
    from skypilot_tpu.observability.step_metrics import read_jsonl

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    monkeypatch.setenv('SKYPILOT_JOBS_POLL_SECONDS', '1')
    monkeypatch.setenv('STPU_FAULT_PLAN',
                       json.dumps({'rules': fault_rules}))
    check.check(quiet=True)

    ckpt = os.path.join(isolated_state, 'guard-ckpt')
    metrics = os.path.join(isolated_state, 'guard-steps.jsonl')
    # XLA_FLAGS= : the pytest process forces 8 host devices for mesh
    # tests; the in-job trainer must see the real (1-device) CPU.
    run = (f'cd {repo} && env PYTHONPATH={repo} JAX_PLATFORMS=cpu '
           f'XLA_FLAGS= '
           f'python -m skypilot_tpu.recipes.train_lm --cpu '
           f'--model tiny --steps {steps} --seq 16 --global-batch 2 '
           f'--log-every 1 --guard --preempt-poll 0.5 '
           f'--ckpt-dir {ckpt} --metrics-file {metrics} '
           f'{extra_flags}')
    result = jobs_core.launch(
        {'name': 'guard-mj', 'resources': {'infra': 'local'},
         'run': run}, user='t')
    job_id = result['job_id']

    deadline = time.time() + 420
    final = None
    while time.time() < deadline:
        job = state.get_job(job_id)
        if job['status'].is_terminal():
            final = job['status']
            break
        time.sleep(1)
    job = state.get_job(job_id)
    assert final == state.ManagedJobStatus.SUCCEEDED, job
    # The typed exit really drove the recovery...
    assert job['recovery_count'] >= 1, job
    log_path = job.get('log_path') or os.path.join(
        constants.sky_home(), f'managed-{job_id}.log')
    candidates = [log_path] if os.path.exists(log_path) else \
        glob.glob(os.path.join(constants.sky_home(), 'managed-*.log'))
    ctrl_log = ''
    for path in candidates:
        with open(path, 'r', encoding='utf-8') as f:
            ctrl_log += f.read()
    assert log_marker in ctrl_log, ctrl_log[-2000:]
    # ...and the step log proves <=1 optimizer step lost: every step
    # ran exactly once, in order, through the final step (a
    # from-scratch restart would rewind; an untyped FAILED would
    # never finish with max_restarts_on_errors=0).
    steps_logged = [r['step'] for r in read_jsonl(metrics)]
    assert steps_logged[-1] == steps, steps_logged
    assert steps_logged == sorted(steps_logged), steps_logged
    assert len(steps_logged) == len(set(steps_logged)), steps_logged
    jobs_core.cancel([job_id])
    return job, steps_logged, ctrl_log


@pytest.mark.slow
def test_managed_job_preempt_notice_graceful_recovery(
        isolated_state, monkeypatch):
    """End-to-end tentpole chaos: a fault plan injects a preemption
    notice (scoped to the FIRST launch) mid-run. The trainer
    checkpoints inside the notice window and exits rc 83; the driver
    maps it to agent status PREEMPTED; the controller answers with
    PREEMPTING -> RECOVERING (never FAILED) and relaunches; the
    resumed run (scope resume=1 exempts it) finishes every step with
    none lost or repeated — all replayable from the plan alone."""
    # Pace steps (~0.4s each) so the notice lands mid-run, after the
    # compile window; the notice rule ignores the resumed process.
    rules = [
        {'point': 'train.data_next', 'action': 'delay',
         'delay_s': 0.4},
        {'point': 'train.preempt_notice', 'action': 'drop',
         'scope': {'resume': '0'}, 'after': 30}]
    _run_train_guard_managed_job(
        isolated_state, monkeypatch, fault_rules=rules, steps=30,
        log_marker='trainer exited PREEMPTED (typed recoverable '
                   'exit)')


@pytest.mark.slow
def test_managed_job_watchdog_abort_recovery(isolated_state,
                                             monkeypatch):
    """End-to-end watchdog chaos: a 300s stall injected into the
    first launch's data loader trips the 3s step watchdog (stack
    dump + rc 84); the controller maps WATCHDOG_ABORT to recovery
    and the relaunched run (resume-scoped out of the stall) resumes
    from the per-step checkpoint and completes."""
    rules = [
        {'point': 'train.data_next', 'action': 'delay',
         'delay_s': 300, 'scope': {'resume': '0'}, 'after': 3,
         'times': 1}]
    # --ckpt-every 1: a checkpoint exists before the stall, so the
    # relaunch resumes (resume=1) clear of the scoped stall rule.
    _run_train_guard_managed_job(
        isolated_state, monkeypatch, fault_rules=rules, steps=8,
        log_marker='trainer exited WATCHDOG_ABORT (typed '
                   'recoverable exit)',
        extra_flags='--ckpt-every 1 --watchdog-deadline 3 '
                    '--watchdog-compile-deadline 120')


@pytest.mark.slow
def test_api_version_negotiation(chaos_server, monkeypatch):
    """Version skew contract (reference: sky/server/versions.py):
    in-range versions negotiate, below-minimum clients get an
    actionable 400, and responses advertise the server version."""
    from skypilot_tpu.server import versions
    _home, port, _proc = chaos_server
    url = f'http://127.0.0.1:{port}'

    # Matching client: fine, response carries the server version.
    resp = requests.get(f'{url}/api/health',
                        headers={versions.HEADER:
                                 str(versions.API_VERSION)},
                        timeout=10)
    assert resp.ok
    assert resp.headers[versions.HEADER] == str(versions.API_VERSION)
    # Legacy client without the header: still in range (v1).
    assert requests.get(f'{url}/api/status', timeout=10).ok
    # Ancient client below the minimum: rejected with guidance.
    resp = requests.post(f'{url}/check', json={},
                         headers={versions.HEADER: '0'}, timeout=10)
    assert resp.status_code == 400
    assert 'upgrade the client' in resp.json()['error']
    # SDK-side check: a too-old server raises.
    monkeypatch.setattr(versions, 'MIN_COMPATIBLE_API_VERSION', 99)
    with pytest.raises(exceptions.ApiVersionMismatchError):
        sdk.api_info(url)


@pytest.mark.slow
def test_dashboard_admin_surfaces(chaos_server, monkeypatch):
    """Users/tokens are manageable and workspaces viewable from the
    SPA's API surface: set role, issue + revoke a service token, list
    workspaces with their cloud allow-lists."""
    home, port, _proc = chaos_server
    url = f'http://127.0.0.1:{port}'
    monkeypatch.setenv(constants.API_SERVER_URL_ENV_VAR, url)

    # Workspaces view (registry + allow-list; default always present).
    ws = requests.get(f'{url}/dashboard/api/workspaces', timeout=10)
    assert ws.ok
    body = ws.json()
    assert 'default' in body['workspaces']
    assert body['active']

    # Seed a user, set their role from the admin surface.
    requests.get(f'{url}/api/status', timeout=10,
                 headers={'X-Skypilot-User': 'dash-admin'})
    r = requests.post(f'{url}/users/role',
                      json={'user': 'dash-admin', 'role': 'admin'},
                      timeout=10)
    assert r.ok, r.text
    users = requests.get(f'{url}/users', timeout=10).json()['users']
    by_name = {u['name']: u for u in users}
    assert by_name['dash-admin']['role'] == 'admin'

    # Token lifecycle: issue (secret shown once), list, revoke.
    tok = requests.post(f'{url}/users/tokens',
                        json={'user': 'dash-admin', 'role': 'admin'},
                        timeout=10)
    assert tok.ok, tok.text
    secret = tok.json()['token']
    assert secret
    auth = {'Authorization': f'Bearer {secret}'}
    listed = requests.get(f'{url}/users/tokens', timeout=10,
                          headers=auth).json()['tokens']
    token_id = next(t['token_id'] for t in listed
                    if t['user_hash'] == 'dash-admin')
    rev = requests.post(f'{url}/users/tokens/revoke',
                        json={'token_id': token_id}, timeout=10,
                        headers=auth)
    assert rev.ok and rev.json()['revoked'] is True
    # The revoked token no longer authenticates (tokens now exist, so
    # auth is required and the stale secret is rejected).
    denied = requests.get(f'{url}/users/tokens', timeout=10,
                          headers=auth)
    assert denied.status_code in (401, 403)


@pytest.mark.slow
def test_dashboard_spa_serves_live_data(chaos_server, monkeypatch):
    """The dashboard SPA assets load and /dashboard/api/summary carries
    live cluster data (reference: sky/dashboard)."""
    home, port, _proc = chaos_server
    url = f'http://127.0.0.1:{port}'
    monkeypatch.setenv(constants.API_SERVER_URL_ENV_VAR, url)

    rid = requests.post(f'{url}/launch', json={
        'task_config': {'run': 'true', 'resources': {'infra': 'local'}},
        'cluster_name': 'dash-c',
    }, timeout=10).json()['request_id']
    deadline = time.time() + 120
    while time.time() < deadline:
        rec = requests.get(f'{url}/api/get',
                           params={'request_id': rid, 'timeout': 5},
                           timeout=30).json()
        if rec['status'] in ('SUCCEEDED', 'FAILED'):
            break
    assert rec['status'] == 'SUCCEEDED', rec

    page = requests.get(f'{url}/dashboard', timeout=10)
    assert page.ok and 'app.js' in page.text
    js = requests.get(f'{url}/dashboard/app.js', timeout=10)
    assert js.ok and 'summary' in js.text
    summary = requests.get(f'{url}/dashboard/api/summary',
                           timeout=10).json()
    names = [c['name'] for c in summary['clusters']]
    assert 'dash-c' in names
    cluster = summary['clusters'][names.index('dash-c')]
    assert cluster['status'] == 'UP' and cluster['events']
    assert summary['counts']['clusters'] >= 1

    # Per-entity drill-down endpoints (detail pages).
    detail = requests.get(f'{url}/dashboard/api/cluster/dash-c',
                          timeout=10).json()
    assert detail['num_hosts'] >= 1 and detail['events']
    assert any(j.get('job_id') for j in detail['jobs'])
    assert requests.get(f'{url}/dashboard/api/cluster/nope',
                        timeout=10).status_code == 404
    assert requests.get(f'{url}/dashboard/api/service/nope',
                        timeout=10).status_code == 404

    # Per-rank log streaming (the detail page's rank selector).
    combined = requests.get(
        f'{url}/logs', params={'cluster': 'dash-c', 'follow': '0'},
        timeout=15)
    assert combined.ok
    rank0 = requests.get(
        f'{url}/logs', params={'cluster': 'dash-c', 'follow': '0',
                               'rank': '0'}, timeout=15)
    assert rank0.ok and '(rank' not in rank0.text  # un-prefixed own file

    # Action round-trip: the SPA's stop button POSTs /stop.
    rid = requests.post(f'{url}/stop', json={'cluster_name': 'dash-c'},
                        timeout=10).json()['request_id']
    deadline = time.time() + 120
    while time.time() < deadline:
        rec = requests.get(f'{url}/api/get',
                           params={'request_id': rid, 'timeout': 5},
                           timeout=30).json()
        if rec['status'] in ('SUCCEEDED', 'FAILED'):
            break
    assert rec['status'] == 'SUCCEEDED', rec
    summary = requests.get(f'{url}/dashboard/api/summary',
                           timeout=10).json()
    names = [c['name'] for c in summary['clusters']]
    assert summary['clusters'][names.index('dash-c')]['status'] == \
        'STOPPED'

    # Wait out the down: a worker killed mid-terminate at fixture
    # teardown can leak cluster processes/state.
    rid = requests.post(f'{url}/down', json={'cluster_name': 'dash-c'},
                        timeout=10).json()['request_id']
    deadline = time.time() + 120
    while time.time() < deadline:
        rec = requests.get(f'{url}/api/get',
                           params={'request_id': rid, 'timeout': 5},
                           timeout=30).json()
        if rec['status'] in ('SUCCEEDED', 'FAILED'):
            break

    # Costs tab data path: the async /cost_report round-trip the SPA
    # performs — the downed cluster appears in the history with an
    # accrued cost field.
    rid = requests.post(f'{url}/cost_report', json={},
                        timeout=10).json()['request_id']
    deadline = time.time() + 60
    rows = None
    while time.time() < deadline:
        rec = requests.get(f'{url}/api/get',
                           params={'request_id': rid, 'timeout': 2},
                           timeout=30).json()
        if rec['status'] == 'SUCCEEDED':
            rows = rec['return_value']
            break
        assert rec['status'] in ('PENDING', 'RUNNING'), rec
    assert rows is not None
    names = [r['name'] for r in rows]
    assert 'dash-c' in names
    row = rows[names.index('dash-c')]
    assert 'cost' in row and 'duration' in row
