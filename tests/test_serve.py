"""Serve on the Local cloud: replicas, LB, readiness, autoscaler units.

Reference strategy: unit tests for autoscaler/policies
(tests/unit_tests/test_serve_autoscaler.py) + smoke tests on real
clouds; here the smoke equivalent runs real replica clusters
(sandbox hosts) behind a real aiohttp LB.
"""
import time

import pytest
import requests

from skypilot_tpu.serve import autoscalers
from skypilot_tpu.serve import core as serve_core
from skypilot_tpu.serve import load_balancing_policies as lb
from skypilot_tpu.serve import serve_state
from skypilot_tpu.serve.service_spec import SkyServiceSpec

# ---------------------------------------------------------------------------
# Pure-unit: autoscaler + policies
# ---------------------------------------------------------------------------


def _spec(**kw):
    return SkyServiceSpec(min_replicas=1, max_replicas=4,
                          target_qps_per_replica=2.0,
                          upscale_delay_seconds=10,
                          downscale_delay_seconds=20, **kw)


def test_request_rate_autoscaler_hysteresis():
    a = autoscalers.RequestRateAutoscaler(_spec())
    t0 = 1000.0
    # 300 requests over the 60s window = 5 qps -> desired ceil(5/2)=3,
    # committed only after upscale_delay.
    for i in range(30):
        a.collect_request_information(10, timestamp=t0 + i)
    d = a.evaluate(num_ready=1, num_launching=0, now=t0 + 5)
    assert a.target_num_replicas == 1  # delay not yet passed
    d = a.evaluate(num_ready=1, num_launching=0, now=t0 + 31)
    assert a.target_num_replicas == 3
    assert d.operator == autoscalers.AutoscalerDecisionOperator.SCALE_UP

    # Load vanishes: downscale only after downscale_delay.
    t1 = t0 + 200
    a.collect_request_information(0, timestamp=t1)
    a.evaluate(num_ready=3, num_launching=0, now=t1)
    assert a.target_num_replicas == 3
    d = a.evaluate(num_ready=3, num_launching=0, now=t1 + 21)
    assert a.target_num_replicas == 1
    assert d.operator == autoscalers.AutoscalerDecisionOperator.SCALE_DOWN


def test_fixed_autoscaler():
    spec = SkyServiceSpec(min_replicas=2, max_replicas=2)
    a = autoscalers.Autoscaler.make(spec)
    assert type(a) is autoscalers.Autoscaler
    d = a.evaluate(num_ready=0, num_launching=1)
    assert d.operator == autoscalers.AutoscalerDecisionOperator.SCALE_UP
    assert d.target_num_replicas == 2


def test_round_robin_policy():
    p = lb.RoundRobinPolicy()
    assert p.select_replica() is None
    p.set_ready_replicas(['a:1', 'b:2'])
    picks = [p.select_replica() for _ in range(4)]
    assert picks == ['a:1', 'b:2', 'a:1', 'b:2']


def test_least_load_policy():
    p = lb.LeastLoadPolicy()
    p.set_ready_replicas(['a:1', 'b:2'])
    r1 = p.select_replica()
    r2 = p.select_replica()
    assert {r1, r2} == {'a:1', 'b:2'}  # spreads while both in flight
    p.request_done(r1)
    assert p.select_replica() == r1


def test_service_spec_yaml_round_trip():
    spec = SkyServiceSpec.from_yaml_config({
        'readiness_probe': {'path': '/health',
                            'initial_delay_seconds': 30},
        'replica_policy': {'min_replicas': 1, 'max_replicas': 3,
                           'target_qps_per_replica': 5},
        'port': 9000,
    })
    again = SkyServiceSpec.from_yaml_config(spec.to_yaml_config())
    assert again.readiness_path == '/health'
    assert again.max_replicas == 3
    assert again.port == 9000
    assert again.autoscaling_enabled


# ---------------------------------------------------------------------------
# E2E on Local cloud
# ---------------------------------------------------------------------------
_SERVER_RUN = (
    'python3 -c "'
    "import http.server, os, json\n"
    "class H(http.server.BaseHTTPRequestHandler):\n"
    "    def do_GET(self):\n"
    "        body = json.dumps({'rank': os.environ.get("
    "'SKYPILOT_NODE_RANK'), 'pid': os.getpid()}).encode()\n"
    "        self.send_response(200)\n"
    "        self.send_header('Content-Length', str(len(body)))\n"
    "        self.end_headers()\n"
    "        self.wfile.write(body)\n"
    "    def log_message(self, *a):\n"
    "        pass\n"
    "http.server.HTTPServer(('127.0.0.1', "
    "int(os.environ['SKYPILOT_SERVE_PORT'])), H).serve_forever()\n"
    '"')


@pytest.fixture()
def serve_env(isolated_state, monkeypatch):
    monkeypatch.setenv('SKYPILOT_SERVE_RECONCILE_SECONDS', '2')
    from skypilot_tpu import check
    check.check(quiet=True)
    yield isolated_state
    for s in serve_state.get_services():
        try:
            serve_core.down(s['name'], purge=True)
        except Exception:  # pylint: disable=broad-except
            pass


def _task_config(replicas=2):
    return {
        'name': 'echo',
        'resources': {'infra': 'local'},
        'run': _SERVER_RUN,
        'service': {
            'readiness_probe': {'path': '/', 'initial_delay_seconds': 60},
            'replicas': replicas,
        },
    }


def _wait_ready(name, want, timeout=150):
    deadline = time.time() + timeout
    while time.time() < deadline:
        rows = serve_core.status([name])
        if rows:
            ready = [r for r in rows[0]['replicas']
                     if r['status'] == 'READY']
            if len(ready) >= want:
                return rows[0]
        time.sleep(2)
    raise TimeoutError(f'service {name} never got {want} ready replicas: '
                       f'{serve_core.status([name])}')


@pytest.mark.slow
def test_serve_up_lb_down(serve_env):
    result = serve_core.up(_task_config(replicas=2), 'svc1', user='t')
    endpoint = result['endpoint']
    row = _wait_ready('svc1', 2)
    assert row['status'] == 'READY'

    # LB round-robins across both replicas.
    seen_pids = set()
    for _ in range(6):
        resp = requests.get(endpoint + '/', timeout=10)
        assert resp.status_code == 200
        seen_pids.add(resp.json()['pid'])
    assert len(seen_pids) == 2, seen_pids

    # Replica loss is replaced (self-healing).
    from skypilot_tpu import core as sky_core
    victims = row['replicas']
    sky_core.down(victims[0]['cluster_name'])
    _wait_ready('svc1', 2, timeout=150)

    serve_core.down('svc1')
    assert serve_core.status(['svc1']) == []
    # All replica clusters cleaned up.
    from skypilot_tpu import global_state
    names = [c['name'] for c in global_state.get_clusters()]
    assert not any(n.startswith('svc1-') for n in names), names


_SSE_RUN = (
    'python3 -c "'
    "import http.server, os, time, json\n"
    "class H(http.server.BaseHTTPRequestHandler):\n"
    "    def do_GET(self):\n"
    "        if self.path != '/sse':\n"
    "            body = json.dumps({'pid': os.getpid()}).encode()\n"
    "            self.send_response(200)\n"
    "            self.send_header('Content-Length', str(len(body)))\n"
    "            self.end_headers()\n"
    "            self.wfile.write(body)\n"
    "            return\n"
    "        self.send_response(200)\n"
    "        self.send_header('Content-Type', 'text/event-stream')\n"
    "        self.end_headers()\n"
    "        for i in range(5):\n"
    "            self.wfile.write(f'data: {i}\\n\\n'.encode())\n"
    "            self.wfile.flush()\n"
    "            time.sleep(0.5)\n"
    "    def log_message(self, *a):\n"
    "        pass\n"
    "http.server.HTTPServer(('127.0.0.1', "
    "int(os.environ['SKYPILOT_SERVE_PORT'])), H).serve_forever()\n"
    '"')


@pytest.mark.slow
def test_serve_lb_streams_sse(serve_env):
    """The LB proxy must PASS SSE THROUGH incrementally (StreamResponse
    + chunked relay), not buffer the body: first frame arrives well
    before the stream completes — the property token streaming from
    serve_lm replicas depends on."""
    cfg = {
        'name': 'sse',
        'resources': {'infra': 'local'},
        'run': _SSE_RUN,
        'service': {
            'readiness_probe': {'path': '/',
                                'initial_delay_seconds': 60},
            'replicas': 1,
        },
    }
    result = serve_core.up(cfg, 'svc-sse', user='t')
    endpoint = result['endpoint']
    _wait_ready('svc-sse', 1)
    t0 = time.time()
    stamps = []
    with requests.get(endpoint + '/sse', stream=True,
                      timeout=60) as resp:
        assert resp.status_code == 200
        assert resp.headers['Content-Type'].startswith(
            'text/event-stream')
        for line in resp.iter_lines():
            if line.startswith(b'data: '):
                stamps.append(time.time() - t0)
    assert len(stamps) == 5, stamps
    # Frames arrived over ~2s of wall time, not in one burst at the
    # end (allow generous slack for a loaded 1-core host).
    assert stamps[0] < 0.5 * stamps[-1], stamps
    serve_core.down('svc-sse')


_VERSIONED_RUN = (
    'python3 -c "'
    "import http.server, os, json\n"
    "class H(http.server.BaseHTTPRequestHandler):\n"
    "    def do_GET(self):\n"
    "        body = json.dumps({'version': os.environ.get('APP_VERSION'),"
    " 'pid': os.getpid()}).encode()\n"
    "        self.send_response(200)\n"
    "        self.send_header('Content-Length', str(len(body)))\n"
    "        self.end_headers()\n"
    "        self.wfile.write(body)\n"
    "    def log_message(self, *a):\n"
    "        pass\n"
    "http.server.HTTPServer(('127.0.0.1', "
    "int(os.environ['SKYPILOT_SERVE_PORT'])), H).serve_forever()\n"
    '"')


def _versioned_config(app_version: str):
    return {
        'name': 'echo',
        'resources': {'infra': 'local'},
        'envs': {'APP_VERSION': app_version},
        'run': _VERSIONED_RUN,
        'service': {
            'readiness_probe': {'path': '/', 'initial_delay_seconds': 60},
            'replicas': 2,
        },
    }


@pytest.mark.slow
def test_serve_rolling_update(serve_env):
    result = serve_core.up(_versioned_config('v1'), 'svc2', user='t')
    endpoint = result['endpoint']
    _wait_ready('svc2', 2)
    resp = requests.get(endpoint + '/', timeout=10)
    assert resp.json()['version'] == 'v1'

    serve_core.update(_versioned_config('v2'), 'svc2')
    # Roll completes: all traffic moves to v2 while the service stays up.
    deadline = time.time() + 240
    while time.time() < deadline:
        versions = set()
        try:
            for _ in range(4):
                r = requests.get(endpoint + '/', timeout=10)
                if r.status_code == 200:
                    versions.add(r.json()['version'])
        except requests.RequestException:
            pass
        if versions == {'v2'}:
            break
        time.sleep(3)
    assert versions == {'v2'}, versions

    # Old replicas culled: exactly the target count remains active.
    rows = serve_core.status(['svc2'])[0]
    active = [r for r in rows['replicas']
              if r['status'] not in ('SHUTDOWN', 'FAILED')]
    assert len(active) == 2, rows['replicas']
    serve_core.down('svc2')


def test_spot_placer_steers_replica_launch(isolated_state, monkeypatch):
    """Preemption history shifts where the next spot replica lands, and
    all-hot falls back to on-demand (reference: spot_placer.py:254
    wired via replica_managers.py:610)."""
    from skypilot_tpu.serve import service as service_mod
    from skypilot_tpu.serve import spot_placer as placer_lib

    task_config = {
        'name': 'sp', 'run': 'true',
        'resources': {'cloud': 'gcp', 'accelerators': 'tpu-v5e-8',
                      'use_spot': True},
    }
    spec = SkyServiceSpec(min_replicas=1, max_replicas=2).to_yaml_config()
    serve_state.add_service('sp', task_config, spec, user='t')

    controller = service_mod.ServeController('sp')
    assert controller._spot_requested

    locs = [('gcp', 'us-central1', 'us-central1-a'),
            ('gcp', 'us-east5', 'us-east5-b')]
    placer = placer_lib.DynamicFallbackSpotPlacer(locs)
    controller._spot_placer = placer

    launched = []

    def fake_launch(task, cluster_name=None, **kw):
        launched.append({r for r in task.resources})
        raise RuntimeError('stop after recording')  # no real provisioning

    monkeypatch.setattr(service_mod.execution, 'launch', fake_launch)

    # First replica goes to some location; mark it preempted.
    serve_state.add_replica('sp', 1, 'sp-rep1', version=1)
    controller._launch_replica(1, 1)
    (res1,) = launched[-1]
    first_zone = res1.zone
    assert res1.use_spot and first_zone is not None
    placer.handle_preemption(
        next(l for l in locs if l[2] == first_zone))

    # Next replica avoids the preempted zone.
    serve_state.add_replica('sp', 2, 'sp-rep2', version=1)
    controller._launch_replica(2, 1)
    (res2,) = launched[-1]
    assert res2.use_spot and res2.zone != first_zone

    # Every candidate hot -> on-demand fallback.
    for loc in locs:
        placer.handle_preemption(loc)
    serve_state.add_replica('sp', 3, 'sp-rep3', version=1)
    controller._launch_replica(3, 1)
    (res3,) = launched[-1]
    assert not res3.use_spot


@pytest.mark.slow
def test_serve_controller_crash_respawns(serve_env):
    """HA for serve: a kill -9'd controller is respawned on the SAME
    ports (clients keep their endpoint) and the service keeps serving —
    the serve analog of managed-jobs re-adoption."""
    import os
    import signal
    from skypilot_tpu.utils import subprocess_utils

    result = serve_core.up(_task_config(replicas=1), 'svc-ha', user='t')
    endpoint = result['endpoint']
    _wait_ready('svc-ha', 1)
    assert requests.get(endpoint + '/', timeout=10).status_code == 200

    record = serve_state.get_service('svc-ha')
    pid = record['controller_pid']
    assert pid > 0 and subprocess_utils.process_alive(pid)
    os.kill(pid, signal.SIGKILL)
    deadline = time.time() + 15
    while time.time() < deadline and subprocess_utils.process_alive(pid):
        time.sleep(0.2)

    # Reconcile (what API-server startup runs) respawns it.
    assert serve_core.reconcile_controllers() == 1
    new_record = serve_state.get_service('svc-ha')
    assert new_record['controller_pid'] != pid
    assert new_record['lb_port'] == record['lb_port']

    # Same endpoint serves again (LB restarts within the new process).
    deadline = time.time() + 90
    ok = False
    while time.time() < deadline:
        try:
            if requests.get(endpoint + '/', timeout=5).status_code == 200:
                ok = True
                break
        except requests.RequestException:
            pass
        time.sleep(2)
    assert ok
    # A second reconcile is a no-op (controller alive).
    assert serve_core.reconcile_controllers() == 0
    serve_core.down('svc-ha')


# ---------------------------------------------------------------------------
# Multi-replica serving plane: real serve_lm fleet + chaos
# ---------------------------------------------------------------------------
@pytest.mark.slow
def test_replica_plane_chaos_real_serve_lm():
    """The full chaos loop on REAL serve_lm processes: a fault plan
    (robustness/faults.py) kills one of 3 replicas' engine scheduler
    mid-stream -> the LB truncates only that stream and retries the
    next request onto a live replica -> the fleet controller replaces
    the dead replica -> the client saw no 5xx beyond the dead
    replica's in-flight work. (The deterministic tier-1 twin with
    stub replicas lives in tests/unit_tests/test_replica_plane.py.)
    """
    import json as json_lib
    import os
    import subprocess
    import sys

    from skypilot_tpu.inference import affinity
    from skypilot_tpu.serve.replica_plane import (FleetController,
                                                  ReplicaManager,
                                                  make_lb_server)
    from skypilot_tpu.serve.replica_plane import replica_manager as rm

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env['PYTHONPATH'] = f"{repo}:{env.get('PYTHONPATH', '')}"
    # SystemExit is a BaseException: the scheduler loop cannot soft-
    # recover it, so the 21st decode round kills the engine thread —
    # /readyz flips 503, in-flight futures fail, the process idles.
    plan = json_lib.dumps({'rules': [{
        'point': 'engine.decode_step', 'action': 'raise',
        'exc': 'SystemExit', 'after': 20}]})
    base = [sys.executable, '-m', 'skypilot_tpu.recipes.serve_lm',
            '--model', 'llama-tiny', '--cpu',
            '--max-total-len', '64', '--continuous-batching',
            '--num-slots', '4']

    def factory(rid, port):
        cmd = base + ['--port', str(port)]
        if rid == 2:
            cmd += ['--fault-plan', plan]
        return subprocess.Popen(cmd, env=env,
                                stdout=subprocess.DEVNULL,
                                stderr=subprocess.STDOUT)

    policy = lb.PrefixAffinityPolicy()
    mgr = ReplicaManager(factory, drain_grace_s=30.0,
                         startup_grace_s=600.0)
    auto = autoscalers.EngineMetricsAutoscaler(
        SkyServiceSpec(min_replicas=3, max_replicas=3))
    ctl = FleetController(mgr, policy, auto, interval_s=0.5)
    lb_port = rm.free_port()
    lb_server = make_lb_server(policy, lb_port,
                               policy_name='prefix_affinity',
                               manager=mgr)
    import threading
    threading.Thread(target=lb_server.serve_forever,
                     daemon=True).start()
    url = f'http://127.0.0.1:{lb_port}'
    try:
        for _ in range(3):
            mgr.spawn()
        assert ctl.wait_ready(3, timeout_s=600), \
            [v.to_dict() for v in mgr.views()]
        victim = mgr.view(2)

        # A prompt whose affinity target is the sabotaged replica.
        prompt = None
        for i in range(500):
            cand = [3000 + i] * 16 + [7, 8]
            key = affinity.token_affinity_key(cand)
            if policy.affinity_target(key) == victim.endpoint:
                prompt = cand
                break
        assert prompt is not None

        # 1) Mid-stream death: the victim commits ~20 tokens of the
        # requested 40, then its engine dies. The stream truncates;
        # the HTTP status the client got was 200 (headers were out).
        tokens = []
        with requests.post(f'{url}/generate', json={
                'tokens': [prompt], 'max_new_tokens': 40,
                'stream': True}, stream=True, timeout=600) as resp:
            assert resp.status_code == 200
            try:
                for raw in resp.iter_lines():
                    if raw.startswith(b'data: ') and b'"token"' in raw:
                        tokens.append(raw)
            except requests.RequestException:
                pass  # truncation may surface as a broken read
        assert len(tokens) < 40  # died mid-generation

        # 2) Next keyed request: the ready set still lists the dead
        # replica (scrape lag); serve_lm answers 503 EngineDead and
        # the LB retries it onto a live replica -> the client sees
        # 200, not 5xx.
        r = requests.post(f'{url}/generate', json={
            'tokens': [prompt], 'max_new_tokens': 4}, timeout=600)
        assert r.status_code == 200
        assert lb_server.lb_metrics.snapshot()['retried'] >= 1

        # 3) The controller replaces the dead replica (full serve_lm
        # startup for the replacement).
        deadline = time.time() + 600
        replaced = False
        while time.time() < deadline:
            ctl.tick()
            ready = mgr.ready_endpoints()
            if len(ready) >= 3 and victim.endpoint not in ready:
                replaced = True
                break
            time.sleep(1.0)
        assert replaced, [v.to_dict() for v in mgr.views()]
        assert max(v.replica_id for v in mgr.views()) == 4

        # 4) Steady state: the same keyed prompt now routes fine.
        r = requests.post(f'{url}/generate', json={
            'tokens': [prompt], 'max_new_tokens': 4}, timeout=600)
        assert r.status_code == 200
    finally:
        ctl.shutdown()
        lb_server.shutdown()


@pytest.mark.slow
def test_fleet_controller_sigkill_restart_adopts_state_dir(tmp_path):
    """Crash-only control plane, end to end through the serve_fleet
    ENTRYPOINT: a stub fleet runs with --state-dir, the controller
    process is SIGKILL'd (the journal's fsync-per-event is the only
    thing that survives), and a restarted serve_fleet with the same
    --state-dir adopts every replica — same pids, same ports, zero
    healthy replicas killed, zero extra 5xx for clients, zero leaked
    processes after shutdown."""
    import json as json_lib
    import os
    import signal as signal_lib
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env['PYTHONPATH'] = f"{repo}:{env.get('PYTHONPATH', '')}"
    state_dir = str(tmp_path / 'fleet-state')
    from skypilot_tpu.serve.replica_plane import replica_manager as rm
    lb_port = rm.free_port()
    cmd = [sys.executable, '-m', 'skypilot_tpu.recipes.serve_fleet',
           '--stub-replicas', '--replicas', '2',
           '--lb-port', str(lb_port), '--state-dir', state_dir,
           '--scrape-interval', '0.2']
    url = f'http://127.0.0.1:{lb_port}'

    def wait_fleet_ready(n, timeout=60):
        deadline = time.time() + timeout
        while time.time() < deadline:
            try:
                status = requests.get(f'{url}/fleet/status',
                                      timeout=5).json()
                ready = [r for r in status['replicas']
                         if r['state'] == 'READY' and r['ready']]
                if len(ready) >= n:
                    return status
            except requests.RequestException:
                pass
            time.sleep(0.2)
        raise AssertionError(f'fleet not ready within {timeout}s')

    def post_ok():
        r = requests.post(f'{url}/generate', json={
            'tokens': [list(range(16)) + [1]], 'max_new_tokens': 3},
            timeout=30)
        return r.status_code

    ctl1 = subprocess.Popen(cmd, env=env,
                            stdout=subprocess.DEVNULL,
                            stderr=subprocess.STDOUT)
    stub_pids = []
    try:
        status = wait_fleet_ready(2)
        assert post_ok() == 200
        # The stub pids live in the journal — they must survive the
        # controller's death.
        with open(os.path.join(state_dir, 'fleet.journal'), 'r',
                  encoding='utf-8') as f:
            for line in f:
                ev = json_lib.loads(line)
                if ev.get('event') == 'spawn':
                    stub_pids.append(ev['pid'])
        stub_pids = sorted(set(stub_pids))
        assert len(stub_pids) == 2
        pre_endpoints = sorted(r['endpoint']
                               for r in status['replicas'])

        # SIGKILL the controller: no drain, no cleanup, nothing.
        ctl1.kill()
        ctl1.wait(timeout=30)
        # The replicas are orphans now — but alive and serving.
        for pid in stub_pids:
            os.kill(pid, 0)  # raises if gone

        # Restart with the SAME state dir: the new controller must
        # adopt, not respawn (same endpoints = same pids).
        ctl2 = subprocess.Popen(cmd, env=env,
                                stdout=subprocess.DEVNULL,
                                stderr=subprocess.STDOUT)
        try:
            status = wait_fleet_ready(2)
            post_endpoints = sorted(r['endpoint']
                                    for r in status['replicas'])
            assert post_endpoints == pre_endpoints  # adopted, not new
            assert all(r['adopted'] for r in status['replicas'])
            for pid in stub_pids:
                os.kill(pid, 0)  # zero healthy replicas killed
            # Zero extra 5xx: clients are served by the adopted fleet.
            codes = [post_ok() for _ in range(6)]
            assert codes == [200] * 6
        finally:
            ctl2.terminate()
            ctl2.wait(timeout=60)
        # Graceful shutdown drained the fleet: zero leaked processes.
        deadline = time.time() + 30
        while time.time() < deadline:
            if not any(rm.pid_alive(pid) for pid in stub_pids):
                break
            time.sleep(0.2)
        assert not any(rm.pid_alive(pid) for pid in stub_pids)
    finally:
        if ctl1.poll() is None:
            ctl1.kill()
            ctl1.wait(timeout=30)
        for pid in stub_pids:
            try:
                os.kill(pid, signal_lib.SIGKILL)
            except (OSError, TypeError):
                pass


@pytest.mark.slow
def test_replica_plane_adapter_chaos_hot_load_on_retry(tmp_path):
    """Multi-LoRA chaos on REAL serve_lm replicas: two replicas share
    an --adapter-dir; the affinity target for an adapter request is
    sabotaged (fault plan kills its engine mid-stream) -> the stream
    truncates; the NEXT request for the SAME adapter is retried by
    the LB onto the surviving replica, which HOT-LOADS the adapter on
    first use and answers 200 — a tenant's fine-tune survives replica
    death with no operator action."""
    import json as json_lib
    import os
    import subprocess
    import sys
    import threading

    import jax.numpy as jnp

    from skypilot_tpu.inference import affinity
    from skypilot_tpu.models import lora as lora_lib
    from skypilot_tpu.models.llama import LlamaConfig
    from skypilot_tpu.serve.replica_plane import (FleetController,
                                                  ReplicaManager,
                                                  make_lb_server)
    from skypilot_tpu.serve.replica_plane import replica_manager as rm

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env['PYTHONPATH'] = f"{repo}:{env.get('PYTHONPATH', '')}"

    # Two adapters shared by the whole fleet (the artifact dir is the
    # distribution mechanism — replicas hot-load on first use).
    adapter_dir = str(tmp_path / 'adapters')
    spec = lora_lib.LoraSpec(rank=4, alpha=8.0)
    cfg = LlamaConfig.tiny(dtype=jnp.float32)
    for i in range(2):
        lora_lib.save_adapter(
            os.path.join(adapter_dir, f'tenant{i}'),
            lora_lib.random_adapter_params(i, cfg, spec), spec,
            base_model='llama-tiny')

    plan = json_lib.dumps({'rules': [{
        'point': 'engine.decode_step', 'action': 'raise',
        'exc': 'SystemExit', 'after': 12}]})
    base = [sys.executable, '-m', 'skypilot_tpu.recipes.serve_lm',
            '--model', 'llama-tiny', '--cpu',
            '--max-total-len', '64', '--continuous-batching',
            '--num-slots', '4', '--adapter-dir', adapter_dir,
            '--max-adapters', '4']

    def factory(rid, port):
        cmd = base + ['--port', str(port)]
        if rid == 2:
            cmd += ['--fault-plan', plan]
        return subprocess.Popen(cmd, env=env,
                                stdout=subprocess.DEVNULL,
                                stderr=subprocess.STDOUT)

    policy = lb.PrefixAffinityPolicy()
    mgr = ReplicaManager(factory, drain_grace_s=30.0,
                         startup_grace_s=600.0)
    auto = autoscalers.EngineMetricsAutoscaler(
        SkyServiceSpec(min_replicas=2, max_replicas=2))
    ctl = FleetController(mgr, policy, auto, interval_s=0.5)
    lb_port = rm.free_port()
    lb_server = make_lb_server(policy, lb_port,
                               policy_name='prefix_affinity',
                               manager=mgr)
    threading.Thread(target=lb_server.serve_forever,
                     daemon=True).start()
    url = f'http://127.0.0.1:{lb_port}'
    try:
        for _ in range(2):
            mgr.spawn()
        assert ctl.wait_ready(2, timeout_s=600), \
            [v.to_dict() for v in mgr.views()]
        victim = mgr.view(2)
        survivor = mgr.view(1)

        # A (prompt, adapter) pair whose SALTED affinity key targets
        # the sabotaged replica.
        prompt = None
        for i in range(500):
            cand = [3000 + i] * 16 + [7, 8]
            key = affinity.request_affinity_key(
                '/generate', {'tokens': [cand], 'model': 'tenant0'})
            if policy.affinity_target(key) == victim.endpoint:
                prompt = cand
                break
        assert prompt is not None

        # 1) Mid-stream engine death on the adapter request: the
        # victim hot-loads tenant0, commits ~12 tokens, dies. The
        # client sees truncation (200, headers were out).
        tokens = []
        with requests.post(f'{url}/generate', json={
                'tokens': [prompt], 'max_new_tokens': 40,
                'model': 'tenant0', 'stream': True}, stream=True,
                timeout=600) as resp:
            assert resp.status_code == 200
            try:
                for raw in resp.iter_lines():
                    if raw.startswith(b'data: ') and b'"token"' in raw:
                        tokens.append(raw)
            except requests.RequestException:
                pass  # truncation may surface as a broken read
        assert len(tokens) < 40  # died mid-generation

        # 2) Same tenant again: the LB's affinity target is still the
        # dead replica; serve_lm answers 503 (engine dead) and the LB
        # retries onto the survivor, which hot-loads tenant0 on this
        # very request -> 200.
        r = requests.post(f'{url}/generate', json={
            'tokens': [prompt], 'max_new_tokens': 4,
            'model': 'tenant0'}, timeout=600)
        assert r.status_code == 200
        assert lb_server.lb_metrics.snapshot()['retried'] >= 1

        # 3) The survivor really holds the adapter now (scraped into
        # the fleet view), and serves a second tenant too.
        stats = requests.get(
            f'http://{survivor.endpoint}/stats', timeout=30).json()
        assert 'tenant0' in (stats.get('adapters') or {}).get(
            'loaded', [])
        mgr.scrape_once()
        assert 'tenant0' in mgr.view(1).adapters_loaded
        assert mgr.view(1).adapters_inventory == 2
        r = requests.post(f'{url}/generate', json={
            'tokens': [prompt], 'max_new_tokens': 4,
            'model': 'tenant1'}, timeout=600)
        assert r.status_code == 200
        # Unknown tenants still 404 through the LB.
        r = requests.post(f'{url}/generate', json={
            'tokens': [prompt], 'max_new_tokens': 4,
            'model': 'tenant9'}, timeout=600)
        assert r.status_code == 404
    finally:
        ctl.shutdown()
        lb_server.shutdown()
