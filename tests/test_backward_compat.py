"""Backward compatibility: OLD clients from git history drive the
CURRENT server.

Reference analog: tests/smoke_tests/backward_compat/ — pins an old
released client against the new server to catch wire-format breaks.
Here the old client is exported straight from git history (the
round-1 client speaks legacy v1 with no version header; a mid-round-2
client speaks v2 with idempotent POSTs), so any non-additive change to
the request/response schemas fails this suite.
"""
import os
import subprocess
import sys
import textwrap

import pytest

from test_api_server import api_server  # noqa: F401  (fixture)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# (label, revision) — revisions are permanent history of this repo.
OLD_REVISIONS = [
    ('round1-final-v1-client', '6b40257'),
    ('round2-mid-v2-client', 'aa15745'),
]


@pytest.fixture(scope='module', params=OLD_REVISIONS,
                ids=[r[0] for r in OLD_REVISIONS])
def old_client_tree(request, tmp_path_factory):
    label, rev = request.param
    dest = tmp_path_factory.mktemp(f'oldclient-{label}')
    archive = subprocess.run(
        ['git', 'archive', rev, 'skypilot_tpu'],
        cwd=REPO, capture_output=True)
    if archive.returncode != 0:
        pytest.skip(f'git archive {rev} failed: '
                    f'{archive.stderr.decode()[:200]}')
    tar = subprocess.run(['tar', '-x', '-C', str(dest)],
                         input=archive.stdout, capture_output=True)
    assert tar.returncode == 0, tar.stderr.decode()
    return str(dest)


def _run_old_client(tree, server_url, code):
    env = dict(os.environ)
    env['PYTHONPATH'] = tree
    env['SKYPILOT_API_SERVER_ENDPOINT'] = server_url
    proc = subprocess.run(
        [sys.executable, '-c', textwrap.dedent(code)],
        env=env, capture_output=True, text=True, timeout=120)
    return proc


def test_old_client_status(api_server, old_client_tree):  # noqa: F811
    proc = _run_old_client(old_client_tree, api_server, '''
        from skypilot_tpu.client import sdk
        records = sdk.get(sdk.status())
        assert records == [], records
        print('STATUS_OK')
    ''')
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert 'STATUS_OK' in proc.stdout


def test_old_client_dryrun_launch(api_server, old_client_tree):  # noqa: F811
    proc = _run_old_client(old_client_tree, api_server, '''
        from skypilot_tpu import task as task_lib
        from skypilot_tpu.client import sdk
        task = task_lib.Task(run='echo hi', name='compat')
        rid = sdk.launch(task, cluster_name='compat-c', dryrun=True)
        result = sdk.get(rid)
        assert result is None or isinstance(result, dict), result
        print('LAUNCH_OK')
    ''')
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert 'LAUNCH_OK' in proc.stdout


def test_old_client_accelerators(api_server, old_client_tree):  # noqa: F811
    proc = _run_old_client(old_client_tree, api_server, '''
        from skypilot_tpu.client import sdk
        accs = sdk.get(sdk.list_accelerators('tpu-v5e'))
        assert any('tpu-v5e' in a for a in accs), list(accs)[:5]
        print('ACCS_OK')
    ''')
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert 'ACCS_OK' in proc.stdout


def test_too_old_client_is_rejected_cleanly(api_server):  # noqa: F811
    """A client below MIN_COMPATIBLE must get an actionable 400, not a
    mis-parse."""
    import requests

    from skypilot_tpu.server import versions
    resp = requests.post(
        f'{api_server}/status', json={},
        headers={versions.HEADER: '0'}, timeout=10)
    assert resp.status_code == 400
    assert 'version' in resp.json()['error'].lower()
