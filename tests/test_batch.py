"""Batch map-over-dataset on the Local cloud."""
import json
import os
import time

import pytest

from skypilot_tpu.batch import core as batch_core


@pytest.fixture()
def batch_env(isolated_state):
    from skypilot_tpu import check
    check.check(quiet=True)
    yield isolated_state
    for row in batch_core.ls():
        batch_core.cancel(row['name'])


@pytest.mark.slow
def test_batch_maps_shards_to_outputs(batch_env, tmp_path):
    # Input: 20 JSONL rows with integers; task doubles them.
    input_path = tmp_path / 'input.jsonl'
    with open(input_path, 'w') as f:
        for i in range(20):
            f.write(json.dumps({'x': i}) + '\n')
    output_dir = tmp_path / 'out'

    task_config = {
        'name': 'double',
        'resources': {'infra': 'local'},
        'run': ('python3 -c "'
                "import json, os\n"
                "rows = [json.loads(l) for l in "
                "open(os.environ['SKYPILOT_BATCH_SHARD'])]\n"
                "with open(os.environ['SKYPILOT_BATCH_OUTPUT'], 'w') as f:\n"
                "    for r in rows:\n"
                "        f.write(json.dumps({'y': r['x'] * 2}) + '\\n')\n"
                '"'),
    }
    batch_core.launch(task_config, 'b1', str(input_path), str(output_dir),
                      num_workers=2, num_shards=4)
    deadline = time.time() + 240
    while time.time() < deadline:
        row = batch_core.get('b1')
        if row['status'].is_terminal():
            break
        time.sleep(2)
    assert row['status'] == batch_core.BatchStatus.SUCCEEDED, row
    assert row['shards_done'] == 4

    # All 20 rows doubled across output shards.
    ys = []
    for fname in os.listdir(output_dir):
        with open(output_dir / fname) as f:
            ys += [json.loads(l)['y'] for l in f]
    assert sorted(ys) == [i * 2 for i in range(20)]

    # Workers torn down.
    from skypilot_tpu import global_state
    names = [c['name'] for c in global_state.get_clusters()]
    assert not any(n.startswith('batch-b1') for n in names), names


def test_batch_split_and_registry(batch_env, tmp_path):
    input_path = tmp_path / 'in.jsonl'
    with open(input_path, 'w') as f:
        for i in range(7):
            f.write(json.dumps({'i': i}) + '\n')
    paths = batch_core.split_jsonl(str(input_path), str(tmp_path / 's'), 3)
    counts = [len(open(p).readlines()) for p in paths]
    assert sum(counts) == 7 and max(counts) - min(counts) <= 1

    cfg = {'resources': {'infra': 'local'}, 'run': 'true'}
    batch_core.launch(cfg, 'bx', str(input_path), str(tmp_path / 'o'),
                      num_workers=1, num_shards=1)
    with pytest.raises(Exception, match='already exists'):
        batch_core.launch(cfg, 'bx', str(input_path), str(tmp_path / 'o'))
    assert [r['name'] for r in batch_core.ls()] == ['bx']
    batch_core.cancel('bx')
