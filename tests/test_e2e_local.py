"""End-to-end pipeline on the Local cloud (no cloud account).

The analog of the reference's dryrun + kind-cluster strategy
(SURVEY §4), upgraded: the Local provisioner runs real agents and
real gang execution, so launch→exec→cancel→autostop→down are
exercised against live processes.
"""
import time

import pytest

import skypilot_tpu as sky
from skypilot_tpu import core
from skypilot_tpu.agent import job_lib
from skypilot_tpu.utils.status_lib import ClusterStatus


@pytest.fixture()
def local_cluster(isolated_state):
    """A 2-host (emulated tpu-v5e-16) Local cluster named t-e2e."""
    from skypilot_tpu import check
    check.check(quiet=True)
    task = sky.Task(name='boot', run='true')
    task.set_resources(sky.Resources(infra='local',
                                     accelerators='tpu-v5e-16'))
    job_id, handle = sky.launch(task, cluster_name='t-e2e',
                                _quiet_optimizer=True)
    assert job_id == 1
    yield handle
    try:
        core.down('t-e2e')
    except Exception:  # pylint: disable=broad-except
        pass


@pytest.mark.slow
def test_launch_gang_env(local_cluster):
    handle = local_cluster
    assert handle.num_hosts == 2

    task = sky.Task(run='echo "R$SKYPILOT_NODE_RANK/$SKYPILOT_NUM_NODES '
                        'J$JAX_PROCESS_ID W$TPU_WORKER_ID '
                        'C=$JAX_COORDINATOR_ADDRESS"')
    job_id, _ = sky.exec(task, 't-e2e')
    agent = handle.agent()
    status = agent.wait_job(job_id, timeout=60)
    assert status == job_lib.JobStatus.SUCCEEDED
    logs = ''.join(agent.stream_job_logs(job_id, follow=False))
    assert 'R0/2' in logs and 'R1/2' in logs
    assert 'J0 W0' in logs and 'J1 W1' in logs
    assert 'C=127.0.0.1:8476' in logs


@pytest.mark.slow
def test_gang_failure_cancels_all(local_cluster):
    handle = local_cluster
    bad = sky.Task(run='if [ "$SKYPILOT_NODE_RANK" = "1" ]; then exit 3; '
                       'else sleep 120; fi')
    job_id, _ = sky.exec(bad, 't-e2e', detach_run=True)
    status = handle.agent().wait_job(job_id, timeout=60)
    assert status == job_lib.JobStatus.FAILED


@pytest.mark.slow
def test_queue_cancel_and_status(local_cluster):
    handle = local_cluster
    job_id, _ = sky.exec(sky.Task(run='sleep 300'), 't-e2e', detach_run=True)
    # wait until running
    agent = handle.agent()
    deadline = time.time() + 60
    while time.time() < deadline:
        job = agent.get_job(job_id)
        if job['status'] == job_lib.JobStatus.RUNNING:
            break
        time.sleep(1)
    core.cancel('t-e2e', [job_id])
    status = agent.wait_job(job_id, timeout=30)
    assert status == job_lib.JobStatus.CANCELLED

    records = core.status(refresh=True)
    assert records[0]['name'] == 't-e2e'
    assert records[0]['status'] == ClusterStatus.UP


@pytest.mark.slow
def test_stop_refresh_down(local_cluster):
    core.stop('t-e2e')
    records = core.status(refresh=True)
    assert records[0]['status'] == ClusterStatus.STOPPED
    core.start('t-e2e')
    records = core.status(refresh=True)
    assert records[0]['status'] == ClusterStatus.UP
    core.down('t-e2e')
    assert core.status() == []
    # history recorded
    hist = core.cost_report()
    assert hist and hist[0]['name'] == 't-e2e'


@pytest.mark.slow
def test_exec_on_missing_cluster(isolated_state):
    with pytest.raises(sky.exceptions.ClusterDoesNotExist):
        sky.exec(sky.Task(run='true'), 'nope')


def test_launch_dryrun(isolated_state):
    from skypilot_tpu import check
    check.check(quiet=True)
    task = sky.Task(name='d', run='true')
    task.set_resources(sky.Resources(infra='local'))
    job_id, handle = sky.launch(task, cluster_name='t-dry', dryrun=True,
                                _quiet_optimizer=True)
    assert job_id is None and handle is None
    assert core.status() == []


@pytest.mark.slow
def test_agent_rejects_unauthenticated(local_cluster):
    """Every mutating agent endpoint requires the per-cluster secret.

    Reference posture: skylet is only reachable over SSH/authed gRPC
    (sky/backends/cloud_vm_ray_backend.py:2888-3086); our HTTP agent
    must therefore reject token-less requests outright.
    """
    import requests as req

    handle = local_cluster
    addr = handle.head_agent_addr
    assert getattr(handle, 'agent_secret', None), 'cluster has no secret'

    # Liveness probe stays open (provision wait loops use it).
    r = req.get(f'http://{addr}/health', timeout=5)
    assert r.status_code == 200

    # No token -> 401 on every sensitive route, and nothing executes.
    r = req.post(f'http://{addr}/exec',
                 json={'job_id': 999, 'script': 'touch /tmp/pwned'},
                 timeout=5)
    assert r.status_code == 401
    r = req.post(f'http://{addr}/jobs/submit',
                 json={'name': 'x', 'spec': {}}, timeout=5)
    assert r.status_code == 401
    r = req.get(f'http://{addr}/jobs', timeout=5)
    assert r.status_code == 401

    # Wrong token -> 401 too.
    r = req.get(f'http://{addr}/jobs', timeout=5,
                headers={'X-Agent-Token': 'not-the-secret'})
    assert r.status_code == 401

    # The authed client path still works.
    assert handle.agent().health()['status'] == 'ok'
    assert isinstance(handle.agent().get_jobs(), list)


@pytest.mark.slow
def test_volume_mounts_and_persists(isolated_state):
    """A Local volume attaches into the sandbox, survives cluster
    teardown, and carries data to the next cluster (the dev analog of
    GCP PD attach, reference sky/provision/__init__.py:235-310)."""
    from skypilot_tpu import check
    from skypilot_tpu.volumes import core as volumes_core
    check.check(quiet=True)

    vol = volumes_core.apply('vol1', 1, infra='local')
    assert vol['status'] == 'READY'

    writer = sky.Task(run='echo persisted-data > data/out.txt')
    writer.set_resources(sky.Resources(infra='local'))
    writer.volumes = {'data': 'vol1'}
    sky.launch(writer, cluster_name='t-vol-w', _quiet_optimizer=True)
    agent = core._get_handle('t-vol-w').agent()
    assert agent.wait_job(1, timeout=60) == job_lib.JobStatus.SUCCEEDED
    core.down('t-vol-w')

    reader = sky.Task(run='cat data/out.txt')
    reader.set_resources(sky.Resources(infra='local'))
    reader.volumes = {'data': 'vol1'}
    _, handle = sky.launch(reader, cluster_name='t-vol-r',
                           _quiet_optimizer=True)
    agent = handle.agent()
    assert agent.wait_job(1, timeout=60) == job_lib.JobStatus.SUCCEEDED
    logs = ''.join(agent.stream_job_logs(1, follow=False))
    assert 'persisted-data' in logs
    core.down('t-vol-r')
    volumes_core.delete('vol1')
    assert volumes_core.ls() == []


@pytest.mark.slow
def test_log_shipping_to_store(isolated_state, monkeypatch, tmp_path):
    """logs.store config ships finished jobs' logs off-cluster
    (reference: sky/logs/__init__.py aggregators)."""
    from skypilot_tpu import check
    store = tmp_path / 'logstore'
    cfg = tmp_path / 'cfg.yaml'
    cfg.write_text(f'logs:\n  store: {store}\n')
    monkeypatch.setenv('SKYPILOT_TPU_CONFIG', str(cfg))
    check.check(quiet=True)

    task = sky.Task(run='echo shipped-line')
    task.set_resources(sky.Resources(infra='local'))
    _, handle = sky.launch(task, cluster_name='t-ship',
                           _quiet_optimizer=True)
    assert handle.agent().wait_job(1, timeout=60) == \
        job_lib.JobStatus.SUCCEEDED
    # Driver ships at job finish; give it a beat.
    deadline = time.time() + 15
    shipped = None
    while time.time() < deadline:
        hits = list(store.glob('*/1/run.log'))
        if hits:
            shipped = hits[0]
            break
        time.sleep(0.5)
    assert shipped is not None, list(store.rglob('*'))
    assert 'shipped-line' in shipped.read_text()
    core.down('t-ship')


@pytest.mark.slow
def test_multislice_megascale_env(isolated_state):
    """A num_nodes=2 (two-slice) launch injects the MEGASCALE/DCN
    bootstrap env into every host: slice count, per-host slice id,
    and the shared coordinator address (SURVEY §2.4 megascale rows)."""
    from skypilot_tpu import check
    check.check(quiet=True)
    task = sky.Task(
        name='ms',
        run='echo "S$MEGASCALE_SLICE_ID/N$MEGASCALE_NUM_SLICES '
            'W$TPU_WORKER_ID C=$MEGASCALE_COORDINATOR_ADDRESS '
            'R$SKYPILOT_NODE_RANK/$SKYPILOT_NUM_NODES"',
        num_nodes=2)
    task.set_resources(sky.Resources(infra='local',
                                     accelerators='tpu-v5e-8'))
    job_id, handle = sky.launch(task, cluster_name='t-ms',
                                _quiet_optimizer=True)
    try:
        agent = handle.agent()
        status = agent.wait_job(job_id, timeout=120)
        assert status == job_lib.JobStatus.SUCCEEDED
        logs = ''.join(agent.stream_job_logs(job_id, follow=False))
        # Both slices report, worker id restarts per slice, one shared
        # coordinator, global ranks span the slices.
        assert 'S0/N2 W0 C=127.0.0.1' in logs, logs
        assert 'S1/N2 W0 C=127.0.0.1' in logs, logs
        assert 'R0/2' in logs and 'R1/2' in logs, logs
    finally:
        try:
            core.down('t-ms')
        except Exception:  # pylint: disable=broad-except
            pass
