"""API server load test: concurrency + memory envelope.

Reference: tests/load_tests/ + test_api_server_benchmark.py:16-39 —
the server must handle concurrent requests and keep peak RSS bounded.
Our envelope: 50 concurrent short requests complete correctly and
server+workers RSS stays under 2 GB (reference baseline allows ~3 GB
idle on a 16 GB host).
"""
import concurrent.futures
import time

import pytest

from skypilot_tpu.client import sdk

from tests.test_api_server import api_server  # fixture reuse  # noqa: F401


@pytest.mark.slow
def test_concurrent_requests_and_rss(api_server):  # noqa: F811
    import requests as req

    sdk.get(sdk.check())

    def one_status(i):
        rid = sdk.status(refresh=False)
        out = sdk.get(rid)
        return i, out

    start = time.time()
    with concurrent.futures.ThreadPoolExecutor(max_workers=16) as pool:
        results = list(pool.map(one_status, range(50)))
    elapsed = time.time() - start
    assert len(results) == 50
    assert all(out == [] for _, out in results)

    # Requests all recorded and succeeded.
    rows = sdk.api_status(limit=200)
    succeeded = [r for r in rows if r['status'] == 'SUCCEEDED']
    assert len(succeeded) >= 51  # 50 status + check

    # Memory envelope from the server's own metrics.
    metrics = req.get(f'{api_server}/api/metrics', timeout=10).text
    rss = 0
    for line in metrics.splitlines():
        if line.startswith(('skypilot_server_rss_bytes',
                            'skypilot_workers_rss_bytes')):
            rss += float(line.split()[-1])
    assert rss < 2 * 1024 ** 3, f'RSS {rss / 1e9:.2f} GB exceeds envelope'
    # Throughput sanity: 50 round-tripped requests shouldn't crawl.
    assert elapsed < 120, f'50 requests took {elapsed:.0f}s'
