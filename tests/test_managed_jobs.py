"""Managed jobs on the Local cloud, including preemption recovery.

The reference can only test this against real spot instances (smoke
tests); here preemption is simulated by killing the cluster's agents
via the local provisioner — the controller must detect loss, recover
the cluster, and resubmit (SURVEY §2.6 contract).
"""
import os
import time

import pytest

from skypilot_tpu.jobs import core as jobs_core
from skypilot_tpu.jobs import state
from skypilot_tpu.utils import common_utils


@pytest.fixture()
def jobs_env(isolated_state, monkeypatch):
    monkeypatch.setenv('SKYPILOT_JOBS_POLL_SECONDS', '1')
    monkeypatch.setenv('SKYPILOT_JOBS_UNREACHABLE_GRACE_SECONDS', '5')
    from skypilot_tpu import check
    check.check(quiet=True)
    yield isolated_state
    # Ensure no controllers outlive the test.
    for j in state.get_jobs():
        jobs_core.cancel([j['job_id']])


def _wait_status(job_id, statuses, timeout=120):
    deadline = time.time() + timeout
    while time.time() < deadline:
        job = state.get_job(job_id)
        if job['status'] in statuses:
            return job['status']
        time.sleep(1)
    raise TimeoutError(
        f'job {job_id} stuck in {state.get_job(job_id)["status"]}; '
        f'wanted {statuses}')


def _task_config(run: str, **resource_kw):
    resources = {'infra': 'local', **resource_kw}
    return {'name': 'mj', 'resources': resources, 'run': run}


@pytest.mark.slow
def test_managed_job_succeeds_and_cleans_up(jobs_env):
    result = jobs_core.launch(_task_config('echo managed-ok'), user='t')
    job_id = result['job_id']
    final = _wait_status(job_id, [state.ManagedJobStatus.SUCCEEDED,
                                  state.ManagedJobStatus.FAILED,
                                  state.ManagedJobStatus.FAILED_CONTROLLER])
    assert final == state.ManagedJobStatus.SUCCEEDED
    # Cluster cleaned up after success.
    from skypilot_tpu import global_state
    deadline = time.time() + 30
    while time.time() < deadline:
        if global_state.get_cluster(f'managed-{job_id}') is None:
            break
        time.sleep(1)
    assert global_state.get_cluster(f'managed-{job_id}') is None


@pytest.mark.slow
def test_managed_job_recovers_from_preemption(jobs_env):
    marker = os.path.join(jobs_env, 'mj-ran')
    # The job appends one line per start: recovery = 2 lines.
    run = f'echo started >> {marker}; sleep 300'
    result = jobs_core.launch(_task_config(run), user='t')
    job_id = result['job_id']
    _wait_status(job_id, [state.ManagedJobStatus.RUNNING], timeout=90)
    # Let the job actually start once.
    deadline = time.time() + 60
    while time.time() < deadline and not os.path.exists(marker):
        time.sleep(1)
    assert os.path.exists(marker)

    # Simulate preemption: kill the cluster's agents.
    cluster_name = f'managed-{job_id}'
    name_on_cloud = common_utils.make_cluster_name_on_cloud(cluster_name)
    from skypilot_tpu.provision.local import instance as local_instance
    local_instance.stop_instances(name_on_cloud)

    _wait_status(job_id, [state.ManagedJobStatus.RECOVERING], timeout=60)
    _wait_status(job_id, [state.ManagedJobStatus.RUNNING], timeout=120)
    job = state.get_job(job_id)
    assert job['recovery_count'] >= 1

    # Job restarted on the recovered cluster.
    deadline = time.time() + 60
    while time.time() < deadline:
        with open(marker, 'r', encoding='utf-8') as f:
            if len(f.readlines()) >= 2:
                break
        time.sleep(1)
    with open(marker, 'r', encoding='utf-8') as f:
        assert len(f.readlines()) >= 2

    # Cancel tears everything down.
    jobs_core.cancel([job_id])
    final = _wait_status(job_id, [state.ManagedJobStatus.CANCELLED],
                         timeout=60)
    assert final == state.ManagedJobStatus.CANCELLED


@pytest.mark.slow
def test_managed_job_user_failure_no_retry(jobs_env):
    result = jobs_core.launch(_task_config('exit 7'), user='t')
    job_id = result['job_id']
    final = _wait_status(job_id, [state.ManagedJobStatus.FAILED,
                                  state.ManagedJobStatus.SUCCEEDED],
                         timeout=120)
    assert final == state.ManagedJobStatus.FAILED


@pytest.mark.slow
def test_managed_job_restarts_on_errors(jobs_env):
    marker = os.path.join(jobs_env, 'mj-retry')
    # Fails the first time, succeeds the second.
    run = (f'if [ -f {marker} ]; then echo ok; else touch {marker}; '
           'exit 1; fi')
    cfg = _task_config(run)
    cfg['resources']['job_recovery'] = {'strategy': 'failover',
                                        'max_restarts_on_errors': 2}
    result = jobs_core.launch(cfg, user='t')
    job_id = result['job_id']
    final = _wait_status(job_id, [state.ManagedJobStatus.SUCCEEDED,
                                  state.ManagedJobStatus.FAILED],
                         timeout=180)
    assert final == state.ManagedJobStatus.SUCCEEDED
    assert state.get_job(job_id)['recovery_count'] >= 1


def test_queue_and_cancel_pending(jobs_env, monkeypatch):
    # Force scheduler to keep jobs pending by setting limits to 0.
    from skypilot_tpu.jobs import scheduler
    monkeypatch.setattr(scheduler, 'MAX_STARTING_JOBS', 0)
    result = jobs_core.launch(_task_config('true'), user='t')
    job_id = result['job_id']
    rows = jobs_core.queue()
    assert rows[-1]['job_id'] == job_id
    assert rows[-1]['status'] == 'PENDING'
    assert jobs_core.cancel([job_id]) == [job_id]
    assert state.get_job(job_id)['status'] == \
        state.ManagedJobStatus.CANCELLED
