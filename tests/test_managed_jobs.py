"""Managed jobs on the Local cloud, including preemption recovery.

The reference can only test this against real spot instances (smoke
tests); here preemption is simulated by killing the cluster's agents
via the local provisioner — the controller must detect loss, recover
the cluster, and resubmit (SURVEY §2.6 contract).
"""
import os
import time

import pytest

from skypilot_tpu.jobs import core as jobs_core
from skypilot_tpu.jobs import state
from skypilot_tpu.utils import common_utils


@pytest.fixture()
def jobs_env(isolated_state, monkeypatch):
    monkeypatch.setenv('SKYPILOT_JOBS_POLL_SECONDS', '1')
    monkeypatch.setenv('SKYPILOT_JOBS_UNREACHABLE_GRACE_SECONDS', '5')
    from skypilot_tpu import check
    check.check(quiet=True)
    yield isolated_state
    # Ensure no controllers outlive the test.
    for j in state.get_jobs():
        jobs_core.cancel([j['job_id']])


def _wait_status(job_id, statuses, timeout=120):
    deadline = time.time() + timeout
    while time.time() < deadline:
        job = state.get_job(job_id)
        if job['status'] in statuses:
            return job['status']
        time.sleep(1)
    raise TimeoutError(
        f'job {job_id} stuck in {state.get_job(job_id)["status"]}; '
        f'wanted {statuses}')


def _task_config(run: str, **resource_kw):
    resources = {'infra': 'local', **resource_kw}
    return {'name': 'mj', 'resources': resources, 'run': run}


@pytest.mark.slow
def test_managed_job_succeeds_and_cleans_up(jobs_env):
    result = jobs_core.launch(_task_config('echo managed-ok'), user='t')
    job_id = result['job_id']
    final = _wait_status(job_id, [state.ManagedJobStatus.SUCCEEDED,
                                  state.ManagedJobStatus.FAILED,
                                  state.ManagedJobStatus.FAILED_CONTROLLER])
    assert final == state.ManagedJobStatus.SUCCEEDED
    # Cluster cleaned up after success.
    from skypilot_tpu import global_state
    deadline = time.time() + 30
    while time.time() < deadline:
        if global_state.get_cluster(f'managed-{job_id}') is None:
            break
        time.sleep(1)
    assert global_state.get_cluster(f'managed-{job_id}') is None


@pytest.mark.slow
def test_managed_job_recovers_from_preemption(jobs_env):
    marker = os.path.join(jobs_env, 'mj-ran')
    # The job appends one line per start: recovery = 2 lines.
    run = f'echo started >> {marker}; sleep 300'
    result = jobs_core.launch(_task_config(run), user='t')
    job_id = result['job_id']
    _wait_status(job_id, [state.ManagedJobStatus.RUNNING], timeout=90)
    # Let the job actually start once.
    deadline = time.time() + 60
    while time.time() < deadline and not os.path.exists(marker):
        time.sleep(1)
    assert os.path.exists(marker)

    # Simulate preemption: kill the cluster's agents.
    cluster_name = f'managed-{job_id}'
    name_on_cloud = common_utils.make_cluster_name_on_cloud(cluster_name)
    from skypilot_tpu.provision.local import instance as local_instance
    local_instance.stop_instances(name_on_cloud)

    _wait_status(job_id, [state.ManagedJobStatus.RECOVERING], timeout=60)
    _wait_status(job_id, [state.ManagedJobStatus.RUNNING], timeout=120)
    job = state.get_job(job_id)
    assert job['recovery_count'] >= 1

    # Job restarted on the recovered cluster.
    deadline = time.time() + 60
    while time.time() < deadline:
        with open(marker, 'r', encoding='utf-8') as f:
            if len(f.readlines()) >= 2:
                break
        time.sleep(1)
    with open(marker, 'r', encoding='utf-8') as f:
        assert len(f.readlines()) >= 2

    # Cancel tears everything down.
    jobs_core.cancel([job_id])
    final = _wait_status(job_id, [state.ManagedJobStatus.CANCELLED],
                         timeout=60)
    assert final == state.ManagedJobStatus.CANCELLED


@pytest.mark.slow
def test_managed_job_user_failure_no_retry(jobs_env):
    result = jobs_core.launch(_task_config('exit 7'), user='t')
    job_id = result['job_id']
    final = _wait_status(job_id, [state.ManagedJobStatus.FAILED,
                                  state.ManagedJobStatus.SUCCEEDED],
                         timeout=120)
    assert final == state.ManagedJobStatus.FAILED


@pytest.mark.slow
def test_managed_job_restarts_on_errors(jobs_env):
    marker = os.path.join(jobs_env, 'mj-retry')
    # Fails the first time, succeeds the second.
    run = (f'if [ -f {marker} ]; then echo ok; else touch {marker}; '
           'exit 1; fi')
    cfg = _task_config(run)
    cfg['resources']['job_recovery'] = {'strategy': 'failover',
                                        'max_restarts_on_errors': 2}
    result = jobs_core.launch(cfg, user='t')
    job_id = result['job_id']
    final = _wait_status(job_id, [state.ManagedJobStatus.SUCCEEDED,
                                  state.ManagedJobStatus.FAILED],
                         timeout=180)
    assert final == state.ManagedJobStatus.SUCCEEDED
    assert state.get_job(job_id)['recovery_count'] >= 1


def test_queue_and_cancel_pending(jobs_env, monkeypatch):
    # Force scheduler to keep jobs pending by setting limits to 0.
    from skypilot_tpu.jobs import scheduler
    monkeypatch.setattr(scheduler, 'MAX_STARTING_JOBS', 0)
    result = jobs_core.launch(_task_config('true'), user='t')
    job_id = result['job_id']
    rows = jobs_core.queue()
    assert rows[-1]['job_id'] == job_id
    assert rows[-1]['status'] == 'PENDING'
    assert jobs_core.cancel([job_id]) == [job_id]
    assert state.get_job(job_id)['status'] == \
        state.ManagedJobStatus.CANCELLED


@pytest.mark.slow
def test_controller_crash_readopts_running_job(jobs_env):
    """HA: kill -9 the controller mid-job; the scheduler re-adopts the
    running on-cluster job and it completes without relaunching
    (reference: sky/jobs/managed_job_refresh_thread.py)."""
    import signal
    from skypilot_tpu.jobs import scheduler
    from skypilot_tpu.utils import subprocess_utils

    result = jobs_core.launch(_task_config('sleep 12; echo survived'),
                              user='t')
    job_id = result['job_id']
    _wait_status(job_id, [state.ManagedJobStatus.RUNNING])
    job = state.get_job(job_id)
    # Wait until the controller has recorded its intent (agent job id).
    deadline = time.time() + 30
    while time.time() < deadline and \
            (state.get_job(job_id).get('agent_job_id') or -1) <= 0:
        time.sleep(0.5)
    job = state.get_job(job_id)
    assert (job.get('agent_job_id') or -1) > 0
    pid = job['controller_pid']
    assert pid > 0 and subprocess_utils.process_alive(pid)

    os.kill(pid, signal.SIGKILL)  # hard crash, no cleanup
    deadline = time.time() + 15
    while time.time() < deadline and subprocess_utils.process_alive(pid):
        time.sleep(0.2)

    # The scheduler notices the dead controller and re-adopts.
    scheduler.maybe_schedule_next_jobs()
    job = state.get_job(job_id)
    assert job['controller_pid'] != pid  # a new controller took over
    final = _wait_status(job_id, [state.ManagedJobStatus.SUCCEEDED,
                                  state.ManagedJobStatus.FAILED,
                                  state.ManagedJobStatus.FAILED_CONTROLLER],
                         timeout=120)
    assert final == state.ManagedJobStatus.SUCCEEDED
    # Adoption, not relaunch: no recovery was needed.
    assert state.get_job(job_id)['recovery_count'] == 0


@pytest.mark.slow
def test_job_group_atomic_launch_and_peer_addresses(jobs_env, monkeypatch,
                                                    tmp_path):
    """A 2-task group launches atomically; each task's env carries the
    other's head address AND each member cluster gets stable peer
    hostnames `<task>.<group>` via the managed hosts block (reference:
    sky/jobs/job_group_networking.py:1-21)."""
    from skypilot_tpu.jobs import groups

    # Local cloud: route the hosts injection into a temp file instead
    # of the real /etc/hosts (same script path, different target).
    hosts_file = tmp_path / 'hosts'
    monkeypatch.setenv('SKYPILOT_HOSTS_FILE', str(hosts_file))

    def member(name):
        peer = 'learner' if name == 'actor' else 'actor'
        return {'name': name, 'resources': {'infra': 'local'},
                'run': ('echo '
                        'actor=$SKYPILOT_JOBGROUP_ADDR_ACTOR '
                        'learner=$SKYPILOT_JOBGROUP_ADDR_LEARNER '
                        'group=$SKYPILOT_JOBGROUP '
                        f'> /tmp/rl1-{name}.out; '
                        # Resolve the PEER by its stable name from the
                        # injected hosts block.
                        f'awk \'/ {peer}.rl1 /{{print "peer="$1}}\' '
                        '"$SKYPILOT_JOBGROUP_HOSTS_FILE" '
                        f'>> /tmp/rl1-{name}.out')}

    out = jobs_core.group_launch('rl1', [member('actor'),
                                         member('learner')], user='t')
    assert len(out['job_ids']) == 2
    for job_id in out['job_ids']:
        final = _wait_status(job_id,
                             [state.ManagedJobStatus.SUCCEEDED,
                              state.ManagedJobStatus.FAILED,
                              state.ManagedJobStatus.FAILED_CONTROLLER],
                             timeout=240)
        assert final == state.ManagedJobStatus.SUCCEEDED, \
            state.get_job(job_id)

    # Both members published addresses; each task saw the peer's.
    members = groups.members('rl1')
    assert all(m['head_ip'] for m in members)
    for name in ('actor', 'learner'):
        with open(f'/tmp/rl1-{name}.out', 'r', encoding='utf-8') as f:
            seen = f.read()
        assert 'actor=127.0.0.1' in seen and 'learner=127.0.0.1' in seen, \
            seen
        # The job resolved its PEER's stable hostname from the block.
        assert 'peer=127.0.0.1' in seen, seen
        os.remove(f'/tmp/rl1-{name}.out')
    # The injected block carries both stable names (non-pooled members
    # keep it — their clusters are terminated whole; pooled workers
    # strip it on release, covered by the unit test).
    injected = hosts_file.read_text()
    assert 'actor.rl1 actor' in injected and 'learner.rl1 learner' in \
        injected, injected
    # Group status + duplicate-name rejection.
    rows = jobs_core.group_status('rl1')
    assert {r['name'] for r in rows} == {'actor', 'learner'}
    with pytest.raises(Exception):
        jobs_core.group_launch('rlx', [member('a'), member('a')],
                               user='t')


@pytest.mark.slow
def test_pipeline_runs_stages_sequentially(jobs_env, tmp_path):
    """A list task_config is a pipeline: stages run in order, each on
    its own cluster, and stage N+1 only starts after N succeeds
    (reference: `sky jobs launch pipeline.yaml`)."""
    marker = tmp_path / 'order.txt'

    def stage(name, line):
        return {'name': name, 'resources': {'infra': 'local'},
                'run': f'echo {line} >> {marker}'}

    result = jobs_core.launch(
        [stage('prep', 'one'), stage('train', 'two'),
         stage('eval', 'three')], user='t')
    job_id = result['job_id']
    final = _wait_status(job_id, [state.ManagedJobStatus.SUCCEEDED,
                                  state.ManagedJobStatus.FAILED,
                                  state.ManagedJobStatus.FAILED_CONTROLLER],
                         timeout=240)
    assert final == state.ManagedJobStatus.SUCCEEDED
    assert marker.read_text().split() == ['one', 'two', 'three']
    job = state.get_job(job_id)
    assert int(job['stage']) == 2  # finished on the last stage
    # Every stage cluster cleaned up.
    from skypilot_tpu import global_state
    assert all(global_state.get_cluster(f'managed-{job_id}-s{k}') is None
               for k in range(3))


@pytest.mark.slow
def test_pipeline_stops_at_failing_stage(jobs_env, tmp_path):
    marker = tmp_path / 'failorder.txt'
    stages = [
        {'name': 'ok', 'resources': {'infra': 'local'},
         'run': f'echo ran >> {marker}'},
        {'name': 'boom', 'resources': {'infra': 'local'}, 'run': 'exit 3'},
        {'name': 'never', 'resources': {'infra': 'local'},
         'run': f'echo never >> {marker}'},
    ]
    result = jobs_core.launch(stages, user='t')
    final = _wait_status(result['job_id'],
                         [state.ManagedJobStatus.SUCCEEDED,
                          state.ManagedJobStatus.FAILED,
                          state.ManagedJobStatus.FAILED_CONTROLLER],
                         timeout=240)
    assert final == state.ManagedJobStatus.FAILED
    assert marker.read_text().split() == ['ran']  # stage 3 never ran
    assert int(state.get_job(result['job_id'])['stage']) == 1


def test_failure_sources_module(monkeypatch):
    """Source loading + isolation: broken paths/sources are skipped,
    reports match by name or dict, nothing ever raises."""
    from skypilot_tpu.jobs import failure_sources
    from skypilot_tpu import sky_config
    calls = {'n': 0}

    def fake_get_nested(keys, default=None, **kw):
        if keys == ('jobs', 'failure_sources'):
            return ['tests_fake_mod.nope', 'os.path.join',  # join(!) -> TypeError on call
                    'test_managed_jobs._fake_source']
        return default

    monkeypatch.setattr(sky_config, 'get_nested', fake_get_nested)
    failure_sources.reset()
    try:
        global _fake_source_reports
        _fake_source_reports = [{'cluster': 'c1', 'reason': 'maint'},
                                'c2']
        assert failure_sources.check_failed('c1') == 'maint'
        assert failure_sources.check_failed('c2') == 'external source'
        assert failure_sources.check_failed('c3') is None
        _fake_source_reports = []
        assert failure_sources.check_failed('c1') is None
    finally:
        failure_sources.reset()


_fake_source_reports = []


def _fake_source():
    return list(_fake_source_reports)


@pytest.mark.slow
def test_managed_job_recovers_on_external_failure_report(jobs_env,
                                                         monkeypatch):
    """An external failure source (jobs.failure_sources plugin)
    reporting the job's cluster triggers IMMEDIATE recovery — no probe
    timeout, no unreachable grace (the cluster's agents are still
    alive and healthy)."""
    import sys
    import yaml
    # Plugin module + its report file live in the isolated home; the
    # controller subprocess imports it via PYTHONPATH.
    plugin = os.path.join(jobs_env, 'ext_fail_plugin.py')
    report = os.path.join(jobs_env, 'failed_clusters.txt')
    with open(plugin, 'w', encoding='utf-8') as f:
        f.write(
            'import os\n'
            f'_REPORT = {report!r}\n'
            'def failed():\n'
            '    if not os.path.exists(_REPORT):\n'
            '        return []\n'
            '    with open(_REPORT) as f:\n'
            '        return [l.strip() for l in f if l.strip()]\n')
    with open(os.path.join(jobs_env, 'config.yaml'), 'w',
              encoding='utf-8') as f:
        yaml.safe_dump(
            {'jobs': {'failure_sources': ['ext_fail_plugin.failed']}},
            f)
    monkeypatch.setenv(
        'PYTHONPATH', f"{jobs_env}:{os.environ.get('PYTHONPATH', '')}")

    marker = os.path.join(jobs_env, 'mj-ext')
    run = f'echo started >> {marker}; sleep 300'
    result = jobs_core.launch(_task_config(run), user='t')
    job_id = result['job_id']
    _wait_status(job_id, [state.ManagedJobStatus.RUNNING], timeout=90)
    deadline = time.time() + 60
    while time.time() < deadline and not os.path.exists(marker):
        time.sleep(1)
    assert os.path.exists(marker)

    # The external system declares the cluster failed (agents are
    # still perfectly healthy — only the report drives recovery).
    with open(report, 'w', encoding='utf-8') as f:
        f.write(f'managed-{job_id}\n')
    # Recovery observably started (the status may transit RECOVERING
    # -> RUNNING between polls; the bump is the durable signal)...
    deadline = time.time() + 60
    while time.time() < deadline and \
            state.get_job(job_id)['recovery_count'] < 1:
        time.sleep(0.5)
    assert state.get_job(job_id)['recovery_count'] >= 1
    # ...then clear the report so the recovered cluster isn't
    # immediately re-reported.
    os.unlink(report)
    _wait_status(job_id, [state.ManagedJobStatus.RUNNING], timeout=120)

    deadline = time.time() + 60
    while time.time() < deadline:
        with open(marker, 'r', encoding='utf-8') as f:
            if len(f.readlines()) >= 2:
                break
        time.sleep(1)
    with open(marker, 'r', encoding='utf-8') as f:
        assert len(f.readlines()) >= 2

    jobs_core.cancel([job_id])
    _wait_status(job_id, [state.ManagedJobStatus.CANCELLED], timeout=60)
