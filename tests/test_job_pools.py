"""Managed-job pools on the Local cloud: reuse, saturation, release."""
import time

import pytest

from skypilot_tpu.jobs import core as jobs_core
from skypilot_tpu.jobs import pools
from skypilot_tpu.jobs import state


@pytest.fixture()
def pool_env(isolated_state, monkeypatch):
    monkeypatch.setenv('SKYPILOT_JOBS_POLL_SECONDS', '1')
    monkeypatch.setenv('SKYPILOT_JOBS_UNREACHABLE_GRACE_SECONDS', '5')
    from skypilot_tpu import check
    check.check(quiet=True)
    yield isolated_state
    for j in state.get_jobs():
        jobs_core.cancel([j['job_id']])


def _wait(job_id, statuses, timeout=120):
    deadline = time.time() + timeout
    while time.time() < deadline:
        job = state.get_job(job_id)
        if job['status'] in statuses:
            return job['status']
        time.sleep(1)
    raise TimeoutError(f'job {job_id}: {state.get_job(job_id)["status"]}')


@pytest.mark.slow
def test_pool_reuse_and_saturation(pool_env):
    template = {'name': 'w', 'resources': {'infra': 'local'}}
    result = pools.apply('p1', template, num_workers=1)
    assert result['workers'] == ['pool-p1-w0']
    from skypilot_tpu import global_state
    assert global_state.get_cluster('pool-p1-w0') is not None

    job_cfg = {'name': 'j', 'resources': {'infra': 'local'},
               'run': 'sleep 3; echo done'}
    r1 = jobs_core.launch(dict(job_cfg), user='t', pool='p1')
    r2 = jobs_core.launch(dict(job_cfg), user='t', pool='p1')

    # Both jobs run on the SAME worker, serialized by pool capacity.
    s1 = _wait(r1['job_id'], [state.ManagedJobStatus.SUCCEEDED])
    assert s1 == state.ManagedJobStatus.SUCCEEDED
    # Trigger scheduling for the queued second job.
    from skypilot_tpu.jobs import scheduler
    scheduler.maybe_schedule_next_jobs()
    s2 = _wait(r2['job_id'], [state.ManagedJobStatus.SUCCEEDED])
    assert s2 == state.ManagedJobStatus.SUCCEEDED
    j1, j2 = state.get_job(r1['job_id']), state.get_job(r2['job_id'])
    assert j1['pool_worker'] == j2['pool_worker'] == 'pool-p1-w0'

    # Worker survives both jobs (released, not destroyed).
    assert global_state.get_cluster('pool-p1-w0') is not None

    rows = pools.ls()
    assert rows[0]['name'] == 'p1' and rows[0]['busy_workers'] == 0

    # Per-worker status view (CLI `stpu jobs pool status`).
    st = pools.status('p1')
    assert st == [{'worker': 'pool-p1-w0', 'status': 'UP',
                   'job_id': None}]

    pools.down('p1')
    assert global_state.get_cluster('pool-p1-w0') is None
    assert pools.get('p1') is None


def test_pool_missing_rejected(pool_env):
    with pytest.raises(Exception, match='not found'):
        jobs_core.launch({'resources': {'infra': 'local'}, 'run': 'true'},
                         pool='nope')


@pytest.mark.slow
def test_pool_shrink_tears_down_surplus(pool_env):
    """apply() with a smaller size must release the surplus workers
    (ADVICE round 1: shrinking leaked clusters that kept billing)."""
    from skypilot_tpu import global_state
    template = {'name': 'w', 'resources': {'infra': 'local'}}
    pools.apply('p2', template, num_workers=2)
    assert global_state.get_cluster('pool-p2-w0') is not None
    assert global_state.get_cluster('pool-p2-w1') is not None

    pools.apply('p2', template, num_workers=1)
    assert global_state.get_cluster('pool-p2-w0') is not None
    assert global_state.get_cluster('pool-p2-w1') is None
    assert pools.get('p2')['num_workers'] == 1
    pools.down('p2')
