"""Async SDK against a real API server process.

Reference analog: sky/client/sdk_async.py tests — same verb surface as
the sync SDK; here we prove coroutines can fan out concurrent
control-plane calls over one session.
"""
import asyncio

import pytest

from skypilot_tpu.client.sdk_async import AsyncClient
from skypilot_tpu.task import Task

from test_api_server import api_server  # noqa: F401  (fixture)


def _run(coro):
    return asyncio.new_event_loop().run_until_complete(coro)


def test_async_status_roundtrip(api_server):  # noqa: F811

    async def main():
        async with AsyncClient(api_server) as client:
            rid = await client.status()
            assert isinstance(rid, str)
            records = await client.get(rid)
            assert records == []

    _run(main())


def test_async_dryrun_launch_and_stream(api_server):  # noqa: F811

    async def main():
        async with AsyncClient(api_server) as client:
            task = Task(run='echo hi', name='async-dry')
            rid = await client.launch(task, cluster_name='async-c',
                                      dryrun=True)
            result = await client.stream_and_get(rid)
            assert result is None or isinstance(result, dict)

    _run(main())


def test_async_concurrent_fanout(api_server):  # noqa: F811
    """Many verbs in flight at once over one session."""

    async def main():
        async with AsyncClient(api_server) as client:
            rids = await asyncio.gather(
                client.status(),
                client.cost_report(),
                client.list_accelerators(name_filter='tpu-v5e'),
                client.storage_ls(),
                client.jobs_queue(),
                client.serve_status(),
            )
            assert len(set(rids)) == len(rids)
            results = await asyncio.gather(*[client.get(r) for r in rids])
            accs = results[2]
            assert any('tpu-v5e' in name for name in accs)

    _run(main())


def test_async_get_unknown_request_404(api_server):  # noqa: F811
    from skypilot_tpu import exceptions

    async def main():
        async with AsyncClient(api_server) as client:
            with pytest.raises(exceptions.RequestNotFoundError):
                await client.get('nonexistent-request-id')

    _run(main())
