#!/usr/bin/env python
"""Flagship benchmark: GPT-2 124M training throughput (tokens/sec).

Runs the recipe-model train step (skypilot_tpu/models/gpt.py via the
sharded trainer) on whatever accelerator is present — the real TPU
chip under the driver, CPU with --smoke. Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

The reference orchestrator publishes no model-throughput numbers
(BASELINE.md: "published": {}), so vs_baseline is measured against
this repo's own recorded number in BENCH_BASELINE.json when present
(ratio >1 = faster than the recorded baseline), else 1.0.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument('--smoke', action='store_true',
                        help='tiny model + CPU-friendly shapes')
    parser.add_argument('--steps', type=int, default=10)
    parser.add_argument('--warmup', type=int, default=2)
    parser.add_argument('--batch', type=int, default=0,
                        help='global batch size (0 = auto)')
    parser.add_argument('--seq', type=int, default=0)
    args = parser.parse_args()

    if args.smoke:
        os.environ.setdefault(
            'XLA_FLAGS', '--xla_force_host_platform_device_count=1')

    import jax
    if args.smoke:
        jax.config.update('jax_platforms', 'cpu')
    import jax.numpy as jnp

    from skypilot_tpu.models.gpt import GPT, GPTConfig
    from skypilot_tpu.parallel import mesh as mesh_lib
    from skypilot_tpu.parallel.train import (ShardedTrainer,
                                             default_optimizer, shard_batch)

    devices = jax.devices()
    n_dev = len(devices)
    platform = devices[0].platform

    if args.smoke:
        cfg = GPTConfig.tiny()
        batch = args.batch or 8
        seq = args.seq or 128
    else:
        cfg = GPTConfig.gpt2_124m(remat=False)
        batch = args.batch or 8 * n_dev
        seq = args.seq or 1024

    mesh = mesh_lib.make_mesh(mesh_lib.MeshConfig.auto(n_dev))
    model = GPT(cfg)

    # OOM-resilient warmup: halve the batch until the step fits (the
    # driver runs this unattended on whatever chip is present).
    rng = jax.random.PRNGKey(1)
    while True:
        try:
            trainer = ShardedTrainer(model, mesh, tx=default_optimizer())
            example = jnp.zeros((batch, seq), jnp.int32)
            state = trainer.init(jax.random.PRNGKey(0), example)
            step = trainer.make_train_step(example)
            tokens = shard_batch(
                jax.random.randint(rng, (batch, seq), 0, cfg.vocab_size,
                                   jnp.int32), mesh)
            for _ in range(args.warmup):
                state, loss = step(state, tokens)
            jax.block_until_ready(loss)
            break
        except Exception as e:  # pylint: disable=broad-except
            if 'RESOURCE_EXHAUSTED' in str(e) and batch > n_dev:
                batch = max(n_dev, batch // 2)
                print(f'# OOM; retrying with batch={batch}',
                      file=sys.stderr)
                continue
            raise

    start = time.perf_counter()
    for _ in range(args.steps):
        state, loss = step(state, tokens)
    jax.block_until_ready(loss)
    elapsed = time.perf_counter() - start

    tokens_per_sec = batch * seq * args.steps / elapsed
    per_chip = tokens_per_sec / n_dev

    # Model FLOPs utilization (6*N*T approximation for training).
    n_params = cfg.num_params()
    flops_per_token = 6 * n_params
    achieved_tflops = tokens_per_sec * flops_per_token / 1e12

    baseline = None
    base_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             'BENCH_BASELINE.json')
    if os.path.exists(base_path):
        with open(base_path, 'r', encoding='utf-8') as f:
            recorded = json.load(f)
            baseline = recorded.get('value')
    vs_baseline = (per_chip / baseline) if baseline else 1.0

    result = {
        'metric': 'gpt2_124m_train_tokens_per_sec_per_chip',
        'value': round(per_chip, 1),
        'unit': 'tokens/s/chip',
        'vs_baseline': round(vs_baseline, 3),
    }
    # Extra context on stderr (driver reads the stdout JSON line only).
    print(f'# platform={platform} n_dev={n_dev} batch={batch} seq={seq} '
          f'steps={args.steps} elapsed={elapsed:.2f}s '
          f'loss={float(loss):.3f} ~{achieved_tflops:.1f} TFLOP/s total',
          file=sys.stderr)
    print(json.dumps(result))


if __name__ == '__main__':
    main()
