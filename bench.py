#!/usr/bin/env python
"""Flagship benchmark: GPT-2 124M training throughput (tokens/sec).

Runs the recipe-model train step (skypilot_tpu/models/gpt.py via the
sharded trainer) on whatever accelerator is present — the real TPU
chip under the driver, CPU with --smoke. Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

The reference orchestrator publishes no model-throughput numbers
(BASELINE.md: "published": {}), so vs_baseline is measured against
this repo's own recorded number in BENCH_BASELINE.json when present
(ratio >1 = faster than the recorded baseline), else 1.0.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time


def _smoke_argv(args) -> list:
    """argv for the CPU-fallback re-exec, preserving user overrides."""
    # The fallback smoke run always carries the inner sweep: when the
    # TPU record is unreachable, the dispatch-amortization curve is
    # the platform-independent evidence of the multi-step win.
    argv = [sys.executable, os.path.abspath(__file__), '--smoke',
            '--sweep-inner', '--sweep-xent',
            '--steps', str(args.steps), '--warmup', str(args.warmup),
            '--repeats', str(args.repeats)]
    if args.no_fused_xent:
        argv += ['--no-fused-xent']
    if args.batch:
        argv += ['--batch', str(args.batch)]
    if args.seq:
        argv += ['--seq', str(args.seq)]
    if args.inner:
        argv += ['--inner', str(args.inner)]
    return argv


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument('--smoke', action='store_true',
                        help='tiny model + CPU-friendly shapes')
    parser.add_argument('--steps', type=int, default=10)
    parser.add_argument('--warmup', type=int, default=2)
    parser.add_argument('--repeats', type=int, default=3,
                        help='timed repeats of the --steps window; the '
                             'JSON line reports the MEDIAN (and stdev) '
                             'so a one-off host stall cannot read as a '
                             'regression — or mask one (the 8%% '
                             'unexplained r03->r04 CPU drift was '
                             'single-shot noise)')
    parser.add_argument('--batch', type=int, default=0,
                        help='global batch size (0 = auto)')
    parser.add_argument('--seq', type=int, default=0)
    parser.add_argument('--inner', type=int, default=0,
                        help='optimizer steps per jitted call via '
                             'lax.scan (0 = auto: 8 off-CPU, 1 on CPU); '
                             'amortizes per-dispatch host overhead')
    parser.add_argument('--sweep-inner', action='store_true',
                        help='measure tokens/s at inner=1/2/4/8 (the '
                             'lax.scan multi-step dispatch-overhead '
                             'amortization) before the headline run; '
                             'results go to stderr, the JSON line is '
                             'unchanged')
    parser.add_argument('--sweep-xent', action='store_true',
                        help='compare the fused blockwise LM-head '
                             'cross-entropy (ops/fused_xent.py) '
                             'against the naive [B,S,V]-materializing '
                             'path on the qwen-tiny config: peak temp '
                             'memory from compiled memory_analysis() '
                             'plus tokens/s for loss+backward, to '
                             'stderr; the JSON line is unchanged')
    parser.add_argument('--no-fused-xent', action='store_true',
                        help='run the headline trainer with the naive '
                             'dense LM-head loss instead of the fused '
                             'blockwise path (A/B escape hatch)')
    parser.add_argument('--sweep-pipeline', action='store_true',
                        help='sweep pipeline schedule x microbatches '
                             '(gpipe/1f1b/interleaved over a stage=4 '
                             'mesh, fixed global batch): step time, '
                             'bubble fraction, peak live activations '
                             'and the activation-memory budget '
                             'verdict per arm; results go to stderr '
                             'and --sweep-pipeline-out, the headline '
                             'JSON line is unchanged')
    parser.add_argument('--sweep-pipeline-out', default=None,
                        metavar='PATH',
                        help='write the --sweep-pipeline arms as one '
                             'JSON artifact (the committed '
                             'BENCH_pipe_* files)')
    parser.add_argument('--profile', default=None, metavar='DIR',
                        help='jax.profiler trace of the FIRST timed '
                             'repeat into DIR (TensorBoard/Perfetto) — '
                             'the MFU triage artifact')
    parser.add_argument('--retries', type=int, default=1,
                        help='accelerator probe retries before CPU fallback')
    parser.add_argument('--init-timeout', type=float, default=300.0,
                        help='seconds to wait for accelerator backend init '
                             '(probed in a subprocess: a wedged TPU relay '
                             'HANGS instead of raising)')
    args = parser.parse_args()

    if args.smoke:
        # The pipeline sweep needs a stage axis: 4 virtual devices.
        count = 4 if args.sweep_pipeline else 1
        os.environ.setdefault(
            'XLA_FLAGS',
            f'--xla_force_host_platform_device_count={count}')

    import jax
    if args.smoke:
        jax.config.update('jax_platforms', 'cpu')
    import jax.numpy as jnp

    from skypilot_tpu.models.gpt import GPT, GPTConfig
    from skypilot_tpu.parallel import mesh as mesh_lib
    from skypilot_tpu.parallel.train import (ShardedTrainer,
                                             default_optimizer, shard_batch,
                                             shard_batch_stack)

    # The TPU relay can WEDGE (hang in backend init without raising), so
    # the probe runs in a killable subprocess with a hard timeout. Only
    # after the probe proves the backend answers does this process touch
    # it; otherwise we pin CPU so the driver always gets a JSON line.
    if not args.smoke:
        import subprocess
        probe_ok = False
        for attempt in range(args.retries + 1):
            try:
                probe = subprocess.run(
                    [sys.executable, '-c',
                     'import jax; d = jax.devices(); '
                     'print(d[0].platform, len(d))'],
                    capture_output=True, text=True,
                    timeout=args.init_timeout, check=False)
                if probe.returncode == 0:
                    print(f'# accelerator probe: {probe.stdout.strip()}',
                          file=sys.stderr)
                    probe_ok = True
                    break
                print(f'# accelerator probe rc={probe.returncode}: '
                      f'{probe.stderr[-300:]}', file=sys.stderr)
            except subprocess.TimeoutExpired:
                print(f'# accelerator probe hung >{args.init_timeout:.0f}s '
                      f'(attempt {attempt + 1})', file=sys.stderr)
            if attempt < args.retries:
                # A killed mid-claim probe wedges the single-session
                # relay for minutes; wait it out before re-probing.
                time.sleep(90)
        if not probe_ok:
            # Full GPT-2 shapes are hopeless on the 1-vCPU host; the
            # CPU record is the smoke config (vs_baseline stays
            # platform-matched via BENCH_BASELINE.json).
            print('# WEDGE DIAGNOSIS: the axon TPU relay accepted no '
                  'backend-init within the probe timeout (it hangs '
                  'instead of raising when a prior session died '
                  'mid-claim; observed to persist for hours). The '
                  'single-chip TPU record in BENCH_BASELINE.json '
                  '(55,480 tok/s/chip, MFU 24.2%, pre-optimization) '
                  'predates the multi-step + bf16-logits + '
                  'XLA-attention changes, whose effect is therefore '
                  'measured on CPU below (vs_baseline stays '
                  'platform-matched).', file=sys.stderr)
            print('# accelerator unavailable; re-exec in CPU smoke mode',
                  file=sys.stderr)
            sys.stderr.flush()
            os.execv(sys.executable, _smoke_argv(args))
        else:
            # Last line of defense: if the relay wedges BETWEEN the
            # probe and our own init, re-exec into CPU smoke mode so
            # the driver still gets a JSON line (execv replaces the
            # process even while the main thread is stuck in C++).
            import threading

            def _cpu_reexec():
                print('# backend init wedged after a healthy probe; '
                      're-exec in CPU smoke mode', file=sys.stderr)
                sys.stderr.flush()
                os.execv(sys.executable, _smoke_argv(args))

            watchdog = threading.Timer(args.init_timeout, _cpu_reexec)
            watchdog.daemon = True
            watchdog.start()
    devices = jax.devices()
    n_dev = len(devices)
    platform = devices[0].platform
    if not args.smoke and probe_ok:
        watchdog.cancel()

    if args.smoke:
        cfg = GPTConfig.tiny()
        batch = args.batch or 8
        seq = args.seq or 128
    else:
        cfg = GPTConfig.gpt2_124m(remat=False)
        batch = args.batch or 8 * n_dev
        seq = args.seq or 1024

    mesh = mesh_lib.make_mesh(mesh_lib.MeshConfig.auto(n_dev))
    model = GPT(cfg)
    inner = args.inner or (1 if platform == 'cpu' else 8)

    def build_step(batch_, inner_):
        # fused_xent=None → auto (on): --smoke defaults through the
        # fused blockwise loss, so BENCH rounds track the shipping
        # training hot path; --no-fused-xent pins the naive one.
        trainer = ShardedTrainer(
            model, mesh, tx=default_optimizer(),
            fused_xent=False if args.no_fused_xent else None)
        example = jnp.zeros((batch_, seq), jnp.int32)
        state_ = trainer.init(jax.random.PRNGKey(0), example)
        data = jax.random.randint(jax.random.PRNGKey(1),
                                  (inner_, batch_, seq), 0,
                                  cfg.vocab_size, jnp.int32)
        if inner_ > 1:
            # lax.scan keeps all `inner` optimizer steps in ONE
            # jitted call — one dispatch per timed iteration.
            step_ = trainer.make_multi_step(example, inner_)
            tokens_ = shard_batch_stack(data, mesh)
        else:
            step_ = trainer.make_train_step(example)
            tokens_ = shard_batch(data[0], mesh)
        return state_, step_, tokens_

    def timed_run(state_, step_, tokens_, steps_):
        # The step donates its state buffer: thread the NEW state back
        # or the next call executes on a deleted buffer.
        start_ = time.perf_counter()
        loss_ = None
        for _ in range(steps_):
            state_, loss_ = step_(state_, tokens_)
        jax.block_until_ready(loss_)
        return time.perf_counter() - start_, state_, loss_

    if args.sweep_xent:
        # Fused-vs-naive LM-head loss evidence on the qwen-tiny config
        # (the Qwen2 family is where the [B,S,V] logits hurt most at
        # scale: 152k vocab). Reports XLA's own peak-temp accounting
        # (compiled memory_analysis) and loss+backward throughput.
        import flax.linen as fnn
        from skypilot_tpu.models.llama import Llama, LlamaConfig
        from skypilot_tpu.ops import fused_xent as fx
        from skypilot_tpu.parallel.train import next_token_loss
        qcfg = LlamaConfig.tiny(qkv_bias=True)
        qmodel = Llama(qcfg)
        xb, xs = (4, 128) if args.smoke else (8, 256)
        xtok = jax.random.randint(jax.random.PRNGKey(2), (xb, xs), 0,
                                  qcfg.vocab_size, jnp.int32)
        qparams = fnn.meta.unbox(
            qmodel.init(jax.random.PRNGKey(0), xtok)['params'])
        xhid = qmodel.apply({'params': qparams}, xtok,
                            return_hidden=True)
        xhead = qparams['lm_head']
        xblk = max(64, qcfg.vocab_size // 4)

        def _naive_loss(h, w, t):
            logits = jnp.einsum(
                'bse,ev->bsv', h.astype(qcfg.dtype),
                w.astype(qcfg.dtype),
                preferred_element_type=jnp.float32)
            return next_token_loss(logits, t)

        def _fused_loss(h, w, t):
            return fx.fused_next_token_loss(
                h, w, t, vocab_in_rows=False, block_size=xblk)

        for xname, xfn in (('naive', _naive_loss),
                           (f'fused[block={xblk}]', _fused_loss)):
            try:
                xjit = jax.jit(jax.value_and_grad(xfn, argnums=(0, 1)))
                xmem = xjit.lower(xhid, xhead, xtok).compile() \
                    .memory_analysis()
                xtemp = getattr(xmem, 'temp_size_in_bytes', None)
                xloss, xg = xjit(xhid, xhead, xtok)
                jax.block_until_ready(xg)
                xt0 = time.perf_counter()
                for _ in range(max(1, args.steps)):
                    xloss, xg = xjit(xhid, xhead, xtok)
                jax.block_until_ready(xg)
                xdt = time.perf_counter() - xt0
                xtps = xb * xs * max(1, args.steps) / xdt / n_dev
                print(f'# sweep-xent {xname}: peak_temp_bytes={xtemp} '
                      f'loss={float(xloss):.4f} '
                      f'loss+bwd tokens/s/chip={xtps:,.0f}',
                      file=sys.stderr)
            except Exception as e:  # pylint: disable=broad-except
                # Evidence-only: never kill the headline run.
                print(f'# sweep-xent {xname}: skipped '
                      f'({type(e).__name__}: {e})', file=sys.stderr)

    if args.sweep_inner:
        # Dispatch-amortization evidence (per VERDICT r3: when the TPU
        # relay is wedged, at least quantify the multi-step win on the
        # platform at hand; on TPU the relay's ~80ms/dispatch overhead
        # makes this the dominant term).
        for inner_v in (1, 2, 4, 8):
            try:
                s_state, s_step, s_tokens = build_step(batch, inner_v)
                _, s_state, _ = timed_run(s_state, s_step, s_tokens, 1)
                sweep_elapsed, _, _ = timed_run(
                    s_state, s_step, s_tokens,
                    max(1, args.steps // inner_v))
            except Exception as e:  # pylint: disable=broad-except
                # The sweep must never kill the headline run (which
                # has its own OOM-halving loop below).
                print(f'# sweep inner={inner_v}: skipped '
                      f'({type(e).__name__})', file=sys.stderr)
                if 'RESOURCE_EXHAUSTED' not in str(e):
                    break
                continue
            tps = (batch * seq * max(1, args.steps // inner_v) * inner_v
                   / sweep_elapsed)
            print(f'# sweep inner={inner_v}: {tps / n_dev:.1f} '
                  f'tokens/s/chip', file=sys.stderr)

    if args.sweep_pipeline:
        # Schedule x microbatch sweep at FIXED global batch: the
        # schedule picker evidence. Bubble fraction and peak live
        # activations come from the schedule object (exact, platform-
        # independent); step time is measured on whatever devices are
        # present; MFU stays null off-TPU. The budget model: a stage
        # can afford S live chunk inputs — exactly what 1F1B
        # guarantees — so GPipe arms with M > S exceed it and their
        # bubble floor is pinned at M = S, while 1f1b/interleaved
        # keep raising M (shrinking the bubble) inside the same
        # memory.
        from skypilot_tpu.parallel.pipeline import PipelinedLM
        from skypilot_tpu.parallel import pipeline_schedule as psched
        pstages = min(4, n_dev)
        psweep_cfg = GPTConfig(
            vocab_size=512, block_size=128, num_layers=8,
            num_heads=4, embed_dim=128, dtype=jnp.float32,
            logits_dtype=jnp.float32)
        pmodel = GPT(psweep_cfg)
        pseq, pbatch = 64, 16
        pmesh = mesh_lib.make_mesh(mesh_lib.MeshConfig(
            stage=pstages, data=n_dev // pstages))
        ptok = jax.random.randint(jax.random.PRNGKey(3),
                                  (pbatch, pseq), 0,
                                  psweep_cfg.vocab_size, jnp.int32)
        arms = []
        for style, vstages in (('gpipe', 1), ('1f1b', 1),
                               ('interleaved', 2)):
            for mcount in (4, 8, 16):
                try:
                    pp = PipelinedLM(pmodel, pmesh,
                                     num_microbatches=mcount,
                                     schedule=style,
                                     virtual_stages=vstages)
                    ptx = default_optimizer()
                    pstate = pp.init(jax.random.PRNGKey(0), ptok, ptx)
                    pstep = pp.make_train_step(ptx)
                    pstate, ploss = pstep(pstate, ptok)  # compile
                    jax.block_until_ready(ploss)
                    pt0 = time.perf_counter()
                    for _ in range(max(2, args.steps // 2)):
                        pstate, ploss = pstep(pstate, ptok)
                    jax.block_until_ready(ploss)
                    pdt = (time.perf_counter() - pt0) / max(
                        2, args.steps // 2)
                except Exception as e:  # pylint: disable=broad-except
                    print(f'# sweep-pipeline {style} M={mcount}: '
                          f'skipped ({type(e).__name__}: {e})',
                          file=sys.stderr)
                    continue
                sch = pp.schedule
                mb_tokens = pbatch // (mcount *
                                       pmesh.shape['data']) * pseq
                arm = {
                    'style': style,
                    'virtual_stages': vstages,
                    'microbatches': mcount,
                    'ticks': sch.num_ticks,
                    'bubble_frac': round(sch.bubble_fraction, 4),
                    'peak_live_activations':
                        sch.peak_live_activations,
                    'act_bytes_proxy': sch.activation_bytes(
                        mb_tokens, psweep_cfg.embed_dim),
                    'fits_budget':
                        sch.peak_live_activations <= pstages,
                    'step_time_s': round(pdt, 4),
                    'tokens_per_sec': round(pbatch * pseq / pdt, 1),
                    'loss': round(float(ploss), 4),
                }
                arms.append(arm)
                print(f'# sweep-pipeline {style} v={vstages} '
                      f'M={mcount}: {pdt * 1e3:.0f} ms/step '
                      f'bubble={arm["bubble_frac"]:.1%} '
                      f'peak_live={arm["peak_live_activations"]} '
                      f'fits_budget={arm["fits_budget"]}',
                      file=sys.stderr)
        # The scoreboard claim, machine-checkable: best in-budget
        # bubble per style family vs gpipe's in-budget floor.
        def best_frac(pred):
            fit = [a for a in arms if a['fits_budget'] and pred(a)]
            return min((a['bubble_frac'] for a in fit), default=None)
        summary = {
            'budget_live_activations': pstages,
            'gpipe_bubble_at_budget':
                best_frac(lambda a: a['style'] == 'gpipe'),
            'best_bubble_at_budget':
                best_frac(lambda a: a['style'] != 'gpipe'),
        }
        artifact = {
            'metric': 'pipeline_schedule_sweep',
            'platform': platform,
            'n_dev': n_dev,
            'stages': pstages,
            'seq': pseq,
            'global_batch': pbatch,
            'model': 'gpt-8l-128d',
            'mfu': None if platform != 'tpu' else 'see-arms',
            'closed_form': 'ticks = 2(M*v + S - 1); '
                           'bubble_frac = (S-1)/(M*v + S - 1)',
            'summary': summary,
            'arms': arms,
        }
        if args.sweep_pipeline_out:
            with open(args.sweep_pipeline_out, 'w',
                      encoding='utf-8') as f:
                json.dump(artifact, f, indent=1)
            print(f'# sweep-pipeline artifact -> '
                  f'{args.sweep_pipeline_out}', file=sys.stderr)

    # OOM-resilient warmup: halve the batch until the step fits (the
    # driver runs this unattended on whatever chip is present).
    while True:
        try:
            state, step, tokens = build_step(batch, inner)
            # At least one untimed step always runs: it both compiles the
            # step and surfaces OOM before the timed section (--warmup 0
            # must not leave `loss` unbound).
            for _ in range(max(1, args.warmup)):
                state, loss = step(state, tokens)
            jax.block_until_ready(loss)
            break
        except Exception as e:  # pylint: disable=broad-except
            if 'RESOURCE_EXHAUSTED' in str(e) and batch > n_dev:
                batch = max(n_dev, batch // 2)
                print(f'# OOM; retrying with batch={batch}',
                      file=sys.stderr)
                continue
            raise

    # >=1 timed repeats of the same window: median defeats one-off
    # host stalls; stdev quantifies whether a cross-round delta is
    # signal (a 5% regression is only detectable if spread << 5%).
    import statistics
    per_chip_runs = []
    elapsed = None
    for r in range(max(1, args.repeats)):
        if args.profile and r == 0:
            jax.profiler.start_trace(args.profile)
        elapsed, state, loss = timed_run(state, step, tokens,
                                         args.steps)
        if args.profile and r == 0:
            jax.profiler.stop_trace()
            print(f'# profile trace -> {args.profile}',
                  file=sys.stderr)
        run_tps = batch * seq * args.steps * inner / elapsed / n_dev
        per_chip_runs.append(run_tps)
        print(f'# repeat {r + 1}/{args.repeats}: {run_tps:.1f} '
              f'tokens/s/chip ({elapsed:.2f}s)', file=sys.stderr)
    per_chip = statistics.median(per_chip_runs)
    spread = (statistics.stdev(per_chip_runs)
              if len(per_chip_runs) > 1 else 0.0)

    # Training FLOPs/token: 6*N for the weights plus the attention
    # quadratic term 12 * layers * embed * seq (fwd QK^T+AV and their
    # backward, per the PaLM appendix accounting).
    n_params = cfg.num_params()
    flops_per_token = 6 * n_params + 12 * cfg.num_layers * cfg.embed_dim * seq
    achieved_tflops_chip = per_chip * flops_per_token / 1e12

    # bf16 peak per chip by TPU generation; MFU is only meaningful on TPU.
    peaks = {'v4': 275., 'v5 lite': 197., 'v5e': 197., 'v5p': 459.,
             'v6e': 918., 'v6 lite': 918.}
    mfu = None
    if platform == 'tpu':
        kind = devices[0].device_kind.lower()
        peak = next((v for k, v in peaks.items() if k in kind), 197.)
        mfu = achieved_tflops_chip / peak

    base_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             'BENCH_BASELINE.json')
    baseline = None
    recorded = {}
    if os.path.exists(base_path):
        with open(base_path, 'r', encoding='utf-8') as f:
            recorded = json.load(f)
        # Only compare like with like: a CPU smoke number must not be
        # scored against a recorded TPU baseline.
        if recorded.get('platform') == platform:
            baseline = recorded.get('value')
    vs_baseline = (per_chip / baseline) if baseline else 1.0

    result = {
        'metric': 'gpt2_124m_train_tokens_per_sec_per_chip',
        'value': round(per_chip, 1),
        'unit': 'tokens/s/chip',
        'vs_baseline': round(vs_baseline, 3),
        'median': round(per_chip, 1),
        'stdev': round(spread, 1),
        'repeats': len(per_chip_runs),
    }
    # First successful run on each platform becomes the recorded
    # baseline later rounds are scored against (comparisons are
    # platform-matched above; a TPU run REPLACES a CPU-only baseline).
    if baseline is None:
        recorded_platform = recorded.get('platform')
        if recorded_platform is None or (platform == 'tpu' and
                                         recorded_platform != 'tpu'):
            with open(base_path, 'w', encoding='utf-8') as f:
                json.dump({**result, 'platform': platform,
                           'mfu': round(mfu, 4) if mfu is not None
                           else None,
                           'batch': batch, 'seq': seq,
                           'inner': inner}, f, indent=1)
    last_loss = loss if getattr(loss, 'ndim', 0) == 0 else loss[-1]
    # Extra context on stderr (driver reads the stdout JSON line only).
    print(f'# platform={platform} n_dev={n_dev} batch={batch} seq={seq} '
          f'steps={args.steps}x{inner} elapsed={elapsed:.2f}s '
          f'loss={float(last_loss):.3f} {achieved_tflops_chip:.1f} TFLOP/s/chip'
          + (f' MFU={mfu:.1%}' if mfu is not None else ''),
          file=sys.stderr)
    print(json.dumps(result))


if __name__ == '__main__':
    main()
