"""Global constants and paths.

Reference analog: sky/skylet/constants.py (the runtime contract).
"""
from __future__ import annotations

import os

# Base state directory (server-side). Overridable for test isolation.
def sky_home() -> str:
    return os.path.expanduser(
        os.environ.get('SKYPILOT_TPU_HOME', '~/.sky-tpu'))


def state_db_path() -> str:
    return os.path.join(sky_home(), 'state.db')


def cluster_yaml_dir() -> str:
    return os.path.join(sky_home(), 'generated')


def api_server_dir() -> str:
    return os.path.join(sky_home(), 'api_server')


def local_clusters_dir() -> str:
    return os.path.join(sky_home(), 'local_clusters')


def logs_dir() -> str:
    return os.path.join(sky_home(), 'logs')


# ---------------------------------------------------------------------------
# Env var contract injected into every task (reference:
# sky/skylet/constants.py:521-526 + JAX multi-host additions).
# ---------------------------------------------------------------------------
NODE_RANK_ENV_VAR = 'SKYPILOT_NODE_RANK'
NODE_IPS_ENV_VAR = 'SKYPILOT_NODE_IPS'
NUM_NODES_ENV_VAR = 'SKYPILOT_NUM_NODES'
NUM_GPUS_PER_NODE_ENV_VAR = 'SKYPILOT_NUM_GPUS_PER_NODE'
TASK_ID_ENV_VAR = 'SKYPILOT_TASK_ID'
CLUSTER_INFO_ENV_VAR = 'SKYPILOT_CLUSTER_INFO'

# JAX multi-host bootstrap (TPU-native additions; SURVEY §2.4):
JAX_COORDINATOR_ADDR_ENV_VAR = 'JAX_COORDINATOR_ADDRESS'
JAX_COORDINATOR_PORT = 8476
JAX_NUM_PROCESSES_ENV_VAR = 'JAX_NUM_PROCESSES'
JAX_PROCESS_ID_ENV_VAR = 'JAX_PROCESS_ID'
TPU_WORKER_ID_ENV_VAR = 'TPU_WORKER_ID'
TPU_WORKER_HOSTNAMES_ENV_VAR = 'TPU_WORKER_HOSTNAMES'
TPU_ACCELERATOR_TYPE_ENV_VAR = 'SKYPILOT_TPU_ACCELERATOR_TYPE'
TPU_NUM_SLICES_ENV_VAR = 'MEGASCALE_NUM_SLICES'
TPU_SLICE_ID_ENV_VAR = 'MEGASCALE_SLICE_ID'
MEGASCALE_COORDINATOR_ENV_VAR = 'MEGASCALE_COORDINATOR_ADDRESS'

# On-cluster runtime layout (the agent's world).
SKY_REMOTE_HOME = '~/.sky-tpu-agent'
SKY_REMOTE_LOGS_ROOT = '~/sky_logs'
SKY_REMOTE_WORKDIR = '~/sky_workdir'
AGENT_PORT = 8477          # agent HTTP control port on the head host
AGENT_VERSION = 1

# API server defaults.
API_SERVER_PORT = 46580
API_SERVER_URL_ENV_VAR = 'SKYPILOT_API_SERVER_ENDPOINT'

# Provisioning.
PROVISION_TIMEOUT_SECONDS = 1800
SSH_WAIT_TIMEOUT_SECONDS = 600
