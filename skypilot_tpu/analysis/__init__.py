"""`stpu check`: project-specific AST static analysis.

Rules:
  SKY001  blocking call inside `async def` (event-loop stall)
  SKY002  jit-purity / retrace hazards in jitted functions
  SKY003  lock discipline: unlocked mutation of shared instance state
  SKY004  metric-name hygiene: names must come from the catalog
  SKY005  swallowed exceptions in control planes
  SKY006  pallas_call must be reachable with interpret=True
  SKY007  span discipline on traced control planes
  SKY008  thread ownership: role-owned state touched cross-thread
          (call-graph verified; grammar in analysis/callgraph.py)
  SKY009  donation discipline: donated args referenced after
          dispatch; unpinned donating engine jits
  SKY010  fault-point drift: fire sites vs KNOWN_POINTS vs the
          internals §11 table

See docs/internals.md §10 for the rule book and suppression story.
"""
from skypilot_tpu.analysis.core import (
    Baseline,
    Checker,
    DEFAULT_BASELINE,
    Finding,
    all_checkers,
    checker_versions,
    register,
    render_json,
    render_text,
    resolve_select,
    run_file,
    run_paths,
    run_source,
)

__all__ = [
    'Baseline', 'Checker', 'DEFAULT_BASELINE', 'Finding', 'all_checkers',
    'checker_versions', 'register', 'render_json', 'render_text',
    'resolve_select', 'run_file', 'run_paths', 'run_source',
]
