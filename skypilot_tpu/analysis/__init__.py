"""`stpu check`: project-specific AST static analysis.

Rules:
  SKY001  blocking call inside `async def` (event-loop stall)
  SKY002  jit-purity / retrace hazards in jitted functions
  SKY003  lock discipline: unlocked mutation of shared instance state
  SKY004  metric-name hygiene: names must come from the catalog
  SKY005  swallowed exceptions in control planes
  SKY006  pallas_call must be reachable with interpret=True

See docs/internals.md §10 for the rule book and suppression story.
"""
from skypilot_tpu.analysis.core import (
    Baseline,
    Checker,
    DEFAULT_BASELINE,
    Finding,
    all_checkers,
    register,
    render_json,
    render_text,
    resolve_select,
    run_file,
    run_paths,
    run_source,
)

__all__ = [
    'Baseline', 'Checker', 'DEFAULT_BASELINE', 'Finding', 'all_checkers',
    'register', 'render_json', 'render_text', 'resolve_select',
    'run_file', 'run_paths', 'run_source',
]
