"""Module-level call graph + thread-ownership symbol table.

The serving engine's correctness story rests on OWNERSHIP, not locks:
"only the scheduler thread touches the donated cache and the slot
arrays" used to be 74 hand-justified baseline rows. This module turns
it into a machine-checked property. It builds an AST call graph for
one module (methods, nested functions, thread targets, executor
submits, HTTP handlers), seeds each entry point with a *role*, and
propagates roles to every reachable function — so a checker can ask
"which threads can execute this statement?".

Roles are small strings naming a thread class (the repo's canon:
``scheduler``, ``http``, ``control-queue``, ``watcher``, ``lb``).
Two pseudo-roles exist:

  ``init``  construction (`__init__`/`__new__`/`__del__`): runs
            happens-before sharing, exempt from ownership checks.
  ``*``     ANY — the conservative unknown. A public function with no
            annotated entry role, an unreached private function, or a
            function whose reference ESCAPES (passed to an
            unresolvable callee, stored on an object) is callable
            from any thread.

The ownership grammar (all machine-read, all grep-able):

  class-level map     ``_STPU_OWNERS = {'cache': 'scheduler!', ...}``
                      attribute -> owning role; a trailing ``!``
                      makes ownership STRICT (cross-role READS are
                      violations too — the donated-cache case, where
                      even a read races the dispatch that consumes
                      the buffer).
  owner comment       ``self.x = ...  # stpu: owner[scheduler]`` on
                      an ``__init__`` assignment (same meaning,
                      per-attribute form).
  thread role         ``threading.Thread(target=self._loop)
                      # stpu: thread[scheduler]`` names the role of
                      the spawned thread; unannotated targets get the
                      anonymous role ``thread:<name>``.
  entry role          ``def record(...):  # stpu: entry[scheduler]``
                      declares a cross-module contract: "callers
                      invoke this on the scheduler thread only".
  hop                 ``def run_on_scheduler(self, fn):
                      # stpu: hop[scheduler]`` — a function passed TO
                      a hop executes under the hop's role (the
                      control-queue pattern: the op runs between
                      decode rounds on the owner thread, regardless
                      of which thread enqueued it).
  role comment        ``cb=self._fetch  # stpu: role[scheduler]`` —
                      a function reference consumed on this line runs
                      under the named role (callback registrations
                      whose consumer is known to be role-bound),
                      instead of escaping to ANY.

Unknown-callee conservatism: a call that cannot be resolved taints
every known-function argument to ANY — `helper(self._m)` means `_m`
may run anywhere, so ownership violations inside it fire unless a
``role[...]`` comment pins the registration.
"""
from __future__ import annotations

import ast
import dataclasses
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

ANY = '*'
INIT_ROLE = 'init'

_CONSTRUCTORS = {'__init__', '__new__', '__del__', '__post_init__'}

_ANN_RE = re.compile(
    r'#\s*stpu:\s*(owner|thread|entry|hop|role)\['
    r'\s*([A-Za-z0-9_:!\-]+)\s*\]')

# HTTP handler conventions: http.server-style do_VERB methods, and
# decorator-registered aiohttp/flask-style routes.
_DO_VERB_RE = re.compile(r'^do_[A-Z]+$')
_ROUTE_DECORATORS = {'get', 'post', 'put', 'delete', 'patch', 'head',
                     'route', 'view'}


@dataclasses.dataclass(frozen=True)
class OwnerSpec:
    """One owned attribute: role + whether reads are policed too."""
    attr: str
    role: str
    strict: bool
    line: int


def parse_role(spec: str) -> Tuple[str, bool]:
    """'scheduler!' -> ('scheduler', strict=True)."""
    if spec.endswith('!'):
        return spec[:-1], True
    return spec, False


def _annotations_on(lines: Sequence[str], lo: int,
                    hi: Optional[int]) -> List[Tuple[str, str]]:
    """(kind, value) for every `# stpu: kind[value]` on source lines
    lo..hi (1-based, inclusive; hi None = lo)."""
    out: List[Tuple[str, str]] = []
    for i in range(lo, (hi or lo) + 1):
        if 1 <= i <= len(lines):
            out.extend(_ANN_RE.findall(lines[i - 1]))
    return out


def _annotation(lines: Sequence[str], node: ast.AST,
                kind: str) -> Optional[str]:
    for k, v in _annotations_on(lines, node.lineno,
                                getattr(node, 'end_lineno', None)):
        if k == kind:
            return v
    return None


def _dotted(node: ast.AST) -> Optional[str]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return '.'.join(reversed(parts))
    return None


def class_owned_attrs(node: ast.ClassDef,
                      lines: Sequence[str]) -> Dict[str, OwnerSpec]:
    """Ownership declarations of one class: the `_STPU_OWNERS` map
    plus `# stpu: owner[...]` comments on `__init__` assignments.
    Shared with SKY003, which exempts owner-declared attributes from
    lock discipline (ownership IS the synchronization story)."""
    out: Dict[str, OwnerSpec] = {}
    for stmt in node.body:
        if (isinstance(stmt, ast.Assign) and
                len(stmt.targets) == 1 and
                isinstance(stmt.targets[0], ast.Name) and
                stmt.targets[0].id == '_STPU_OWNERS' and
                isinstance(stmt.value, ast.Dict)):
            for k, v in zip(stmt.value.keys, stmt.value.values):
                if (isinstance(k, ast.Constant) and
                        isinstance(k.value, str) and
                        isinstance(v, ast.Constant) and
                        isinstance(v.value, str)):
                    role, strict = parse_role(v.value)
                    out[k.value] = OwnerSpec(k.value, role, strict,
                                             k.lineno)
    for stmt in node.body:
        if isinstance(stmt, ast.FunctionDef) and \
                stmt.name == '__init__':
            for sub in ast.walk(stmt):
                if not isinstance(sub, ast.Assign):
                    continue
                spec = _annotation(lines, sub, 'owner')
                if spec is None:
                    continue
                for target in sub.targets:
                    if (isinstance(target, ast.Attribute) and
                            isinstance(target.value, ast.Name) and
                            target.value.id == 'self'):
                        role, strict = parse_role(spec)
                        out[target.attr] = OwnerSpec(
                            target.attr, role, strict, sub.lineno)
    return out


@dataclasses.dataclass
class FuncInfo:
    qualname: str
    name: str
    node: ast.AST                     # FunctionDef / AsyncFunctionDef
    cls: Optional[str]                # enclosing class qualname
    parent: Optional[str]             # enclosing function qualname


class ModuleGraph:
    """Call graph + role assignment for one parsed module."""

    def __init__(self, tree: ast.Module,
                 lines: Sequence[str]) -> None:
        self.lines = lines
        self.functions: Dict[str, FuncInfo] = {}
        # class qualname -> {method name -> qualname}
        self.class_methods: Dict[str, Dict[str, str]] = {}
        # class qualname -> ClassDef node
        self.classes: Dict[str, ast.ClassDef] = {}
        # bare class name -> qualname (for instantiation edges)
        self._class_names: Dict[str, str] = {}
        self.module_funcs: Dict[str, str] = {}
        self.edges: Dict[str, Set[str]] = {}
        self.seeds: Dict[str, Set[str]] = {}
        self.hops: Dict[str, str] = {}           # qualname -> role
        self.escaped: Set[str] = set()
        self.owners: Dict[str, Dict[str, OwnerSpec]] = {}  # cls -> map
        self._roles: Optional[Dict[str, Set[str]]] = None
        self._collect(tree)
        for cls in self.classes:
            self.owners[cls] = self._parse_owners(cls)
        for info in self.functions.values():
            self._scan_body(info)
        self._seed_defaults()

    # -- pass 1: the symbol table -------------------------------------------
    def _collect(self, tree: ast.Module) -> None:
        def walk(node: ast.AST, cls: Optional[str],
                 func: Optional[str]) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    qual = (f'{cls}.{child.name}' if cls
                            else child.name)
                    self.classes[qual] = child
                    self.class_methods.setdefault(qual, {})
                    self._class_names.setdefault(child.name, qual)
                    walk(child, qual, None)
                elif isinstance(child, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                    if func is not None:
                        qual = f'{func}.<locals>.{child.name}'
                    elif cls is not None:
                        qual = f'{cls}.{child.name}'
                        self.class_methods[cls][child.name] = qual
                    else:
                        qual = child.name
                        self.module_funcs[child.name] = qual
                    self.functions[qual] = FuncInfo(
                        qual, child.name, child, cls, func)
                    walk(child, cls, qual)
                else:
                    walk(child, cls, func)
        walk(tree, None, None)

    def _parse_owners(self, cls: str) -> Dict[str, OwnerSpec]:
        return class_owned_attrs(self.classes[cls], self.lines)

    # -- pass 2: edges, entries, escapes ------------------------------------
    def _scan_body(self, info: FuncInfo) -> None:
        self.edges.setdefault(info.qualname, set())
        node = info.node
        # Entry annotations on the def line / decorators.
        entry = _annotation(
            self.lines, node,
            'entry') or self._decorator_entry(node)
        if entry is None and info.cls is not None and \
                _DO_VERB_RE.match(info.name):
            entry = 'http'
        if entry is not None:
            self.seeds.setdefault(info.qualname, set()).add(entry)
        hop = _annotation(self.lines, node, 'hop')
        if hop is not None:
            self.hops[info.qualname] = hop
        if info.name in _CONSTRUCTORS:
            self.seeds.setdefault(info.qualname, set()).add(INIT_ROLE)
        consumed: Set[int] = set()
        for sub in self.own_nodes(node):
            if isinstance(sub, ast.Call):
                self._scan_call(info, sub, consumed)
        # Escape analysis: function references in value position that
        # no thread/submit/hop/call construct consumed.
        for sub in self.own_nodes(node):
            if id(sub) in consumed:
                continue
            if not isinstance(sub, (ast.Name, ast.Attribute)):
                continue
            if not isinstance(getattr(sub, 'ctx', None), ast.Load):
                continue
            target = self.resolve_ref(info, sub)
            if target is None:
                continue
            role = _annotation(self.lines, sub, 'role')
            if role is not None:
                self.seeds.setdefault(target, set()).add(role)
            else:
                self.escaped.add(target)

    def _scan_call(self, info: FuncInfo, call: ast.Call,
                   consumed: Set[int]) -> None:
        callee = self.resolve_callee(info, call.func)
        consumed.add(id(call.func))
        name = _dotted(call.func) or ''
        leaf = name.split('.')[-1]
        # threading.Thread(target=...) / executor.submit(fn, ...) /
        # loop.run_in_executor(None, fn, ...): the referenced function
        # becomes a thread entry point, not an escape.
        target_refs: List[ast.AST] = []
        if leaf == 'Thread':
            target_refs = [kw.value for kw in call.keywords
                           if kw.arg == 'target']
        elif leaf == 'submit' and call.args:
            target_refs = [call.args[0]]
        elif leaf == 'run_in_executor' and len(call.args) >= 2:
            target_refs = [call.args[1]]
        for ref in target_refs:
            fn = self.resolve_ref(info, ref)
            consumed.add(id(ref))
            if fn is None:
                continue
            role = (_annotation(self.lines, call, 'thread') or
                    f'thread:{self.functions[fn].name}')
            self.seeds.setdefault(fn, set()).add(role)
        if target_refs:
            return
        if callee is not None:
            hop_role = self.hops.get(callee)
            if hop_role is None and callee in self.functions:
                # Hop annotations are parsed lazily per callee (the
                # callee may not have been body-scanned yet).
                ann = _annotation(self.lines,
                                  self.functions[callee].node, 'hop')
                if ann is not None:
                    self.hops[callee] = ann
                    hop_role = ann
            self.edges.setdefault(info.qualname, set()).add(callee)
            if hop_role is not None:
                # Function arguments to a hop run under the hop role.
                for arg in list(call.args) + \
                        [kw.value for kw in call.keywords]:
                    fn = self.resolve_ref(info, arg)
                    if fn is not None:
                        consumed.add(id(arg))
                        self.seeds.setdefault(fn, set()).add(hop_role)
            return
        # Unknown callee: every known-function argument is tainted to
        # ANY (it may be stored and invoked from any thread) — unless
        # a `# stpu: role[...]` comment on the line pins it.
        for arg in list(call.args) + \
                [kw.value for kw in call.keywords]:
            fn = self.resolve_ref(info, arg)
            if fn is None:
                continue
            consumed.add(id(arg))
            role = _annotation(self.lines, arg, 'role')
            if role is not None:
                self.seeds.setdefault(fn, set()).add(role)
            else:
                self.escaped.add(fn)

    def _decorator_entry(self, node: ast.AST) -> Optional[str]:
        """`@routes.get('/x')`-style registration -> role 'http'."""
        for dec in getattr(node, 'decorator_list', ()):
            if isinstance(dec, ast.Call):
                name = _dotted(dec.func)
                if name is not None and \
                        name.split('.')[-1] in _ROUTE_DECORATORS:
                    return 'http'
        return None

    # -- resolution ----------------------------------------------------------
    @staticmethod
    def _self_attr(node: ast.AST) -> Optional[str]:
        if (isinstance(node, ast.Attribute) and
                isinstance(node.value, ast.Name) and
                node.value.id == 'self'):
            return node.attr
        return None

    def resolve_callee(self, info: FuncInfo,
                        func: ast.AST) -> Optional[str]:
        """Qualname the call dispatches to, or None (unknown)."""
        if isinstance(func, ast.Name):
            # Innermost first: local nested defs up the lexical chain.
            scope: Optional[str] = info.qualname
            while scope is not None:
                local = f'{scope}.<locals>.{func.id}'
                if local in self.functions:
                    return local
                scope = self.functions[scope].parent
            if func.id in self.module_funcs:
                return self.module_funcs[func.id]
            if func.id in self._class_names:
                cls = self._class_names[func.id]
                return self.class_methods.get(cls, {}).get('__init__')
            return None
        attr = self._self_attr(func)
        if attr is not None and info.cls is not None:
            return self.class_methods.get(info.cls, {}).get(attr)
        name = _dotted(func)
        if name is not None and '.' in name:
            head, leaf = name.rsplit('.', 1)
            if head in self._class_names:
                cls = self._class_names[head]
                return self.class_methods.get(cls, {}).get(leaf)
        return None

    def resolve_ref(self, info: FuncInfo,
                     node: ast.AST) -> Optional[str]:
        """Qualname for a *reference* to a known function (a value,
        not a call)."""
        if isinstance(node, (ast.Name, ast.Attribute)):
            return self.resolve_callee(info, node)
        return None

    def own_nodes(self, func: ast.AST):
        """Nodes of `func`'s own body, excluding nested def/class
        bodies (those are separate graph nodes)."""
        stack = list(ast.iter_child_nodes(func))
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef, ast.ClassDef)):
                continue
            yield node
            stack.extend(ast.iter_child_nodes(node))

    # -- role propagation -----------------------------------------------------
    def _seed_defaults(self) -> None:
        """Public functions with no explicit contract are callable
        from anywhere (the conservative cross-module default)."""
        for qual, info in self.functions.items():
            if qual in self.seeds or qual in self.escaped:
                continue
            public = not info.name.startswith('_')
            if public and info.name not in _CONSTRUCTORS and \
                    '<locals>' not in qual:
                self.escaped.add(qual)

    def roles(self, qualname: str) -> Set[str]:
        """Roles whose threads may execute `qualname` (fixpoint over
        call edges; `{ANY}` = unknown/any)."""
        if self._roles is None:
            self._roles = self._propagate()
        return self._roles.get(qualname, {ANY})

    def _propagate(self) -> Dict[str, Set[str]]:
        roles: Dict[str, Set[str]] = {
            q: set(s) for q, s in self.seeds.items()}
        for q in self.escaped:
            roles.setdefault(q, set()).add(ANY)
        changed = True
        while changed:
            changed = False
            for caller, callees in self.edges.items():
                src = roles.get(caller)
                if not src:
                    continue
                for callee in callees:
                    dst = roles.setdefault(callee, set())
                    add = src - dst
                    if add:
                        dst.update(add)
                        changed = True
        # Unreached functions are unknown: any thread may call them.
        for q in self.functions:
            if not roles.get(q):
                roles[q] = {ANY}
        return roles


def build(tree: ast.Module, source_lines: Sequence[str]) -> ModuleGraph:
    return ModuleGraph(tree, source_lines)
