"""Built-in checkers. Importing this package registers every rule."""
from skypilot_tpu.analysis.checkers import async_blocking  # noqa: F401
from skypilot_tpu.analysis.checkers import donation  # noqa: F401
from skypilot_tpu.analysis.checkers import exception_hygiene  # noqa: F401
from skypilot_tpu.analysis.checkers import fault_points  # noqa: F401
from skypilot_tpu.analysis.checkers import jit_purity  # noqa: F401
from skypilot_tpu.analysis.checkers import lock_discipline  # noqa: F401
from skypilot_tpu.analysis.checkers import metric_names  # noqa: F401
from skypilot_tpu.analysis.checkers import pallas_interpret  # noqa: F401
from skypilot_tpu.analysis.checkers import span_discipline  # noqa: F401
from skypilot_tpu.analysis.checkers import thread_ownership  # noqa: F401
