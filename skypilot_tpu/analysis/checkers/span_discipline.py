"""SKY007: tracing spans must be closed.

A span opened with `tracing.span(...)` or `tracing.start_span(...)`
records its Chrome-trace event only on `end()` — a leaked span is a
silent hole in the merged trace (the request "disappears" mid-flight)
and, at volume, an unbounded pile of never-recorded Span objects. The
rule enforces the tracing module's own contract at every open site in
non-test code:

  - `with tracing.span(...):` — closed by `__exit__`; always clean.
  - `sp = tracing.start_span(...)` + `sp.end()` inside a `finally`
    in the same function — clean (the manual-lifetime idiom).
  - `sp.end()` NOT under a `finally` — finding: any exception between
    open and close leaks the span.
  - result discarded (`tracing.span(...)` as a bare statement) or
    stored where the checker cannot see the close (attribute,
    subscript, tuple target) — finding.

Passing the freshly opened span to another call or returning it
transfers ownership and is out of scope (a factory is not a leak).
`tracing.record_span(...)` — the retroactive already-measured-interval
API — creates no open span and is exempt by construction.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from skypilot_tpu.analysis import core

_OPENERS = ('span', 'start_span')


def _is_test_path(path: str) -> bool:
    return path.startswith('tests/') or '/tests/' in path or \
        path.split('/')[-1].startswith('test_')


@core.register
class SpanDisciplineChecker(core.Checker):
    rule = 'SKY007'
    name = 'span-discipline'
    description = ('Spans from tracing.span/start_span must be closed '
                   'via `with` or `.end()` in a finally.')

    def __init__(self, ctx: core.FileContext) -> None:
        super().__init__(ctx)
        # Names bound to the tracing module ('tracing', aliases) and
        # names bound directly to span/start_span by import.
        self._mod_names: Set[str] = set()
        self._fn_names: Set[str] = set()

    @classmethod
    def applies_to(cls, path: str) -> bool:
        return not _is_test_path(path)

    # -- import tracking ---------------------------------------------
    def visit_Import(self, node: ast.Import) -> None:
        for alias in node.names:
            if alias.name.split('.')[-1] == 'tracing' and \
                    'observability' in alias.name:
                self._mod_names.add(alias.asname or alias.name)
        self.generic_visit(node)

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        mod = node.module or ''
        for alias in node.names:
            if alias.name == 'tracing' and \
                    mod.endswith('observability'):
                self._mod_names.add(alias.asname or 'tracing')
            elif alias.name in _OPENERS and mod.endswith('tracing'):
                self._fn_names.add(alias.asname or alias.name)
        self.generic_visit(node)

    # -- span-open detection -----------------------------------------
    def _is_open(self, node: ast.AST) -> bool:
        if not isinstance(node, ast.Call):
            return False
        name = core.dotted_name(node.func)
        if name is None:
            return False
        parts = name.split('.')
        if parts[-1] not in _OPENERS:
            return False
        if len(parts) == 1:
            return parts[0] in self._fn_names
        return '.'.join(parts[:-1]) in self._mod_names

    # -- scope analysis ----------------------------------------------
    def visit_Module(self, node: ast.Module) -> None:
        # Imports register via generic visiting; scopes are analyzed
        # from the top so each statement is owned by exactly one
        # function (or the module body).
        for stmt in node.body:
            self.visit(stmt)
        self._check_scope(node.body)

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self.generic_visit(node)  # nested defs get their own scope
        self._check_scope(node.body)

    def visit_AsyncFunctionDef(self,
                               node: ast.AsyncFunctionDef) -> None:
        self.generic_visit(node)
        self._check_scope(node.body)

    def _walk_scope(self, body: List[ast.stmt]):
        """Every node of this scope, not descending into nested
        function/class definitions (those are their own scopes)."""
        stack: List[ast.AST] = list(body)
        while stack:
            node = stack.pop()
            yield node
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef,
                                 ast.ClassDef, ast.Lambda)):
                continue
            stack.extend(ast.iter_child_nodes(node))

    def _check_scope(self, body: List[ast.stmt]) -> None:
        opens: Dict[str, ast.Call] = {}  # var -> open call
        flagged: List[Tuple[ast.AST, str]] = []
        with_closed: Set[ast.Call] = set()
        # end-calls: var name -> under a finally?
        ends: Dict[str, bool] = {}
        finally_nodes: Set[int] = set()
        for node in self._walk_scope(body):
            if isinstance(node, ast.Try):
                for stmt in node.finalbody:
                    for sub in ast.walk(stmt):
                        finally_nodes.add(id(sub))
        for node in self._walk_scope(body):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    if self._is_open(item.context_expr):
                        with_closed.add(item.context_expr)
            elif isinstance(node, ast.Assign) and \
                    self._is_open(node.value):
                if len(node.targets) == 1 and \
                        isinstance(node.targets[0], ast.Name):
                    opens[node.targets[0].id] = node.value
                else:
                    flagged.append(
                        (node, 'span stored where its close cannot '
                               'be verified; bind it to a local and '
                               '`.end()` it in a finally, or use '
                               '`with`'))
            elif isinstance(node, ast.Expr) and \
                    self._is_open(node.value):
                flagged.append(
                    (node, 'span result discarded — it can never be '
                           'closed; use `with span(...)`'))
            elif isinstance(node, ast.Call):
                name = core.dotted_name(node.func)
                if name and name.endswith('.end') and \
                        len(name.split('.')) == 2:
                    var = name.split('.')[0]
                    ends[var] = ends.get(var, False) or \
                        id(node) in finally_nodes
        for var, call in opens.items():
            if call in with_closed:
                continue
            if var not in ends:
                # No visible `.end()` at all: only flag when the
                # variable never escapes this scope (passing or
                # returning it transfers ownership).
                if self._escapes(body, var):
                    continue
                flagged.append(
                    (call, f'span {var!r} is never closed; call '
                           f'{var}.end() in a finally or use `with`'))
            elif not ends[var]:
                flagged.append(
                    (call, f'{var}.end() is not under a finally: an '
                           f'exception between open and close leaks '
                           f'the span'))
        for node, msg in flagged:
            self.add(node, msg)

    def _escapes(self, body: List[ast.stmt], var: str) -> bool:
        """True when `var` is returned, yielded, passed to a call, or
        stored onto an object — ownership leaves this scope."""
        for node in self._walk_scope(body):
            if isinstance(node, (ast.Return, ast.Yield)) and \
                    isinstance(node.value, ast.Name) and \
                    node.value.id == var:
                return True
            if isinstance(node, ast.Call):
                for arg in list(node.args) + \
                        [kw.value for kw in node.keywords]:
                    if isinstance(arg, ast.Name) and arg.id == var:
                        return True
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if isinstance(tgt, (ast.Attribute, ast.Subscript)):
                        val = node.value
                        if isinstance(val, ast.Name) and \
                                val.id == var:
                            return True
        return False
