"""SKY001: blocking calls inside `async def` bodies.

One synchronous `open()`/`requests.get()`/`time.sleep()` in a handler
stalls EVERY in-flight request on the event loop — the failure mode
only shows up under load, which is exactly when it hurts. The fix is
`await asyncio.to_thread(...)` / `loop.run_in_executor(...)` (or an
async-native client).

Calls inside a nested synchronous `def` are not flagged: that function
runs wherever it is invoked — typically handed to an executor.
"""
from __future__ import annotations

import ast
from typing import List, Optional

from skypilot_tpu.analysis import core

# Exact dotted names that block.
_BLOCKING_EXACT = {
    'open',
    'input',
    'time.sleep',
    'sqlite3.connect',
    'socket.create_connection',
    'socket.getaddrinfo',
    'urllib.request.urlopen',
    'os.system',
    'os.wait',
    'os.waitpid',
}
# Any attribute of these modules blocks (requests.get/post/...,
# subprocess.run/check_output/Popen/...).
_BLOCKING_MODULES = {'subprocess', 'requests'}
# shutil is mixed: get_terminal_size/which are ioctl/stat-cheap, the
# tree operations genuinely block — list those explicitly.
_BLOCKING_EXACT.update({
    'shutil.copy', 'shutil.copy2', 'shutil.copyfile', 'shutil.copytree',
    'shutil.rmtree', 'shutil.move', 'shutil.make_archive',
    'shutil.unpack_archive',
})
# Method names that block regardless of receiver (pathlib file IO,
# DB cursors, socket receive).
_BLOCKING_METHODS = {
    'read_text', 'write_text', 'read_bytes', 'write_bytes',
    'executemany', 'executescript', 'fetchall', 'fetchone',
}
# Receiver-qualified: `.execute`/`.commit` block on sqlite/DB
# connections but are too generic alone (aiosqlite, executors, ...);
# only flag them on receivers whose name says "db"/"conn"/"cursor".
_DB_METHODS = {'execute', 'commit'}
_DB_RECEIVER_HINTS = ('db', 'conn', 'cursor', 'sqlite')


@core.register
class AsyncBlockingChecker(core.Checker):
    rule = 'SKY001'
    name = 'blocking-call-in-async'
    description = ('Blocking call inside an async def; wrap in '
                   'asyncio.to_thread()/run_in_executor().')

    def __init__(self, ctx: core.FileContext) -> None:
        super().__init__(ctx)
        # Stack of (function node, is_async); the INNERMOST frame
        # decides whether a call runs on the event loop.
        self._func_stack: List[ast.AST] = []

    # -- scope tracking -----------------------------------------------------
    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._func_stack.append(node)
        self.generic_visit(node)
        self._func_stack.pop()

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._func_stack.append(node)
        self.generic_visit(node)
        self._func_stack.pop()

    def visit_Lambda(self, node: ast.Lambda) -> None:
        # A lambda body runs when called — usually from an executor.
        self._func_stack.append(node)
        self.generic_visit(node)
        self._func_stack.pop()

    def _in_async_frame(self) -> Optional[ast.AsyncFunctionDef]:
        if self._func_stack and isinstance(self._func_stack[-1],
                                           ast.AsyncFunctionDef):
            return self._func_stack[-1]
        return None

    # -- the check ----------------------------------------------------------
    def visit_Call(self, node: ast.Call) -> None:
        frame = self._in_async_frame()
        if frame is not None:
            blocked = self._blocking_reason(node)
            if blocked:
                self.add(node,
                         f'blocking call {blocked}() inside '
                         f'async def {frame.name}; use '
                         f'asyncio.to_thread()/run_in_executor()')
        self.generic_visit(node)

    def _blocking_reason(self, node: ast.Call) -> Optional[str]:
        name = core.dotted_name(node.func)
        if name is not None:
            if name in _BLOCKING_EXACT:
                return name
            parts = name.split('.')
            if parts[0] in _BLOCKING_MODULES and len(parts) > 1:
                return name
        if isinstance(node.func, ast.Attribute):
            attr = node.func.attr
            if attr in _BLOCKING_METHODS:
                return f'.{attr}'
            if attr in _DB_METHODS:
                recv = core.dotted_name(node.func.value) or ''
                low = recv.lower()
                if any(h in low for h in _DB_RECEIVER_HINTS):
                    return f'{recv}.{attr}'
        return None
