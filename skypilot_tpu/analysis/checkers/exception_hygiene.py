"""SKY005: swallowed exceptions in the control planes.

Scoped to `server/`, `jobs/`, `serve/`, `inference/` — the layers
where a silently dropped error turns into a cluster stuck in a
phantom state with nothing in any log. A broad handler
(`except Exception` / bare `except`) must do at least one of:

  - re-raise (bare `raise` or `raise X`),
  - log (any `logger.*`/`logging.*`/`*.exception()` call,
    `traceback.print_exc/format_exc`, `ux_utils.log`, click stderr),
  - or USE the bound exception (`except ... as e` where `e` is
    referenced) — surfacing the error in a response/result counts as
    handling it.

`except Exception: pass` in a control plane is always a finding:
best-effort cleanup that is genuinely fine gets an inline
`# stpu: ignore[SKY005]` with the reviewer's eyes on it.
"""
from __future__ import annotations

import ast


from skypilot_tpu.analysis import core

_SCOPES = ('server/', 'jobs/', 'serve/', 'inference/')

_BROAD = {'Exception', 'BaseException'}
_LOG_ROOTS = {'logger', 'logging', 'log', 'ux_utils', 'traceback'}
_LOG_METHODS = {'debug', 'info', 'warning', 'warn', 'error',
                'exception', 'critical', 'log', 'print_exc',
                'format_exc', 'secho', 'echo'}


def _is_broad(handler: ast.ExceptHandler) -> bool:
    if handler.type is None:
        return True
    types = (handler.type.elts if isinstance(handler.type, ast.Tuple)
             else [handler.type])
    for t in types:
        name = core.dotted_name(t)
        if name is not None and name.split('.')[-1] in _BROAD:
            return True
    return False


def _handles(handler: ast.ExceptHandler) -> bool:
    """True when the handler re-raises, logs, or uses the exception."""
    bound = handler.name
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if bound and isinstance(node, ast.Name) and node.id == bound \
                and isinstance(node.ctx, ast.Load):
            return True
        if isinstance(node, ast.Call):
            name = core.dotted_name(node.func)
            if name is None:
                continue
            parts = name.split('.')
            if parts[0] in _LOG_ROOTS or \
                    parts[0].endswith(('logger', '_log')):
                return True
            if len(parts) > 1 and parts[-1] in _LOG_METHODS:
                return True
    return False


@core.register
class ExceptionHygieneChecker(core.Checker):
    rule = 'SKY005'
    name = 'swallowed-exception'
    description = ('Broad except in a control plane must log, '
                   're-raise, or use the exception.')

    @classmethod
    def applies_to(cls, path: str) -> bool:
        return any(scope in path for scope in _SCOPES)

    def visit_ExceptHandler(self, node: ast.ExceptHandler) -> None:
        if _is_broad(node) and not _handles(node):
            what = ('bare except' if node.type is None
                    else 'except Exception')
            self.add(node,
                     f'{what} swallows the error: log it, re-raise, '
                     f'or use the bound exception')
        self.generic_visit(node)
