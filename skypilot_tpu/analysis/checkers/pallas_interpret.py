"""SKY006: every pallas_call must be reachable in interpret mode.

TPU Pallas kernels only compile on real TPU backends, so the ONLY way
tier-1 (CPU) tests can pin their numerics is `interpret=True`. A
`pl.pallas_call(...)` that hard-codes `interpret=False` — or omits the
kwarg entirely — is a kernel that cannot be A/B-tested off-TPU: its
first execution ever is in production. The repo contract (see
ops/pallas_paged.py) is that library kernels thread an `interpret`
flag from the caller:

    pl.pallas_call(kernel, grid_spec=..., interpret=interpret)(...)

Flagged: a call whose dotted callee ends in `pallas_call` where the
`interpret` keyword is missing or is the constant `False`. Any other
value (a plumbed variable, `True`, an expression) passes — the rule
checks reachability, not which mode a given call site runs in. Test
files are exempt (a test may legitimately pin compiled-only
behaviour behind a TPU-gated skip).
"""
from __future__ import annotations

import ast
from typing import List

from skypilot_tpu.analysis import core


@core.register
class PallasInterpretChecker(core.Checker):
    rule = 'SKY006'
    name = 'pallas-interpret'
    description = ('pallas_call outside tests must be reachable with '
                   'interpret=True (kwarg present and not constant '
                   'False).')

    @classmethod
    def applies_to(cls, path: str) -> bool:
        return not (path.startswith('tests/') or '/tests/' in path)

    def visit_Call(self, node: ast.Call) -> None:
        name = core.dotted_name(node.func)
        if name is not None and name.split('.')[-1] == 'pallas_call':
            kw = next((k for k in node.keywords
                       if k.arg == 'interpret'), None)
            has_splat = any(k.arg is None for k in node.keywords)
            if kw is None and not has_splat:
                self.add(node,
                         'pallas_call without an interpret= kwarg: '
                         'kernel is untestable on CPU; thread an '
                         'interpret flag through (interpret=interpret)')
            elif kw is not None and (
                    isinstance(kw.value, ast.Constant) and
                    kw.value.value is False):
                self.add(kw.value,
                         'pallas_call with hard-coded interpret=False '
                         'can never run in interpret mode; plumb the '
                         'flag from the caller instead')
        self.generic_visit(node)
