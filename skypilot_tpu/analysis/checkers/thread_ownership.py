"""SKY008: cross-thread access to role-owned state.

A class that declares thread ownership (a `_STPU_OWNERS` map or
`# stpu: owner[role]` comments — see analysis/callgraph.py) has made
a machine-checkable claim: "attribute X is touched only by the ROLE
thread". This checker builds the module call graph, assigns each
function the set of roles whose threads can reach it, and flags:

  - a WRITE to an owned attribute from a method reachable by a role
    other than the owner (and not `init` — construction happens-before
    sharing), unless the method holds one of the class's declared
    locks (lock-protected cross-thread access is SKY003's domain, not
    a race);
  - a READ of a STRICT (`role!`) attribute under the same conditions
    — the donated-cache case, where even observing the buffer races
    the dispatch that consumes it;
  - an owner declaration for an attribute the class never assigns
    (ownership drift: the attribute was renamed but the declaration
    was not).

The safe cross-thread patterns are all visible to the call graph: hop
through a `# stpu: hop[role]` function (`run_on_scheduler` — the
closure runs on the owner thread), hold a declared lock, or pin a
callback registration with `# stpu: role[...]`. Everything else needs
an inline `# stpu: ignore[SKY008]` with a comment saying why the race
is benign.

Classes that declare no owners are untouched — this rule is opt-in
per class, by design: the grammar is the contract.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from skypilot_tpu.analysis import callgraph, core

_LOCK_TYPES = {'Lock', 'RLock', 'Condition', 'Semaphore',
               'BoundedSemaphore'}
_MUTATORS = {'append', 'appendleft', 'extend', 'extendleft', 'insert',
             'pop', 'popleft', 'popitem', 'remove', 'discard', 'clear',
             'add', 'update', 'setdefault', 'sort', 'reverse'}


def _self_attr(node: ast.AST) -> Optional[str]:
    if (isinstance(node, ast.Attribute) and
            isinstance(node.value, ast.Name) and
            node.value.id == 'self'):
        return node.attr
    return None


def _class_locks(node: ast.ClassDef) -> Set[str]:
    """Attrs assigned a Lock/RLock/Condition/Semaphore anywhere in
    the class body (mirrors SKY003's collection)."""
    locks: Set[str] = set()
    for sub in ast.walk(node):
        if not isinstance(sub, ast.Assign):
            continue
        if not isinstance(sub.value, ast.Call):
            continue
        name = core.dotted_name(sub.value.func)
        if name is not None and name.split('.')[-1] in _LOCK_TYPES:
            for target in sub.targets:
                attr = _self_attr(target)
                if attr is not None:
                    locks.add(attr)
    return locks


def _acquires_lock(method: ast.AST, locks: Set[str]) -> bool:
    for node in ast.walk(method):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                expr = item.context_expr
                if _self_attr(expr) in locks:
                    return True
                if (isinstance(expr, ast.Call) and
                        isinstance(expr.func, ast.Attribute) and
                        _self_attr(expr.func.value) in locks):
                    return True
        if isinstance(node, ast.Call):
            if (isinstance(node.func, ast.Attribute) and
                    node.func.attr in ('acquire', 'wait', 'notify',
                                       'notify_all') and
                    _self_attr(node.func.value) in locks):
                return True
    return False


@core.register
class ThreadOwnershipChecker(core.Checker):
    rule = 'SKY008'
    name = 'thread-ownership'
    description = ('Role-owned attributes must only be touched from '
                   'the owning thread role (call-graph verified).')
    version = 1

    @classmethod
    def applies_to(cls, path: str) -> bool:
        return not path.startswith('tests/')

    def check(self, tree: ast.Module) -> List[core.Finding]:
        graph = callgraph.build(tree, self.ctx.lines)
        for cls_qual, owners in graph.owners.items():
            if owners:
                self._check_class(graph, cls_qual, owners)
        return self.findings

    def _check_class(self, graph: callgraph.ModuleGraph,
                     cls_qual: str,
                     owners: Dict[str, callgraph.OwnerSpec]) -> None:
        node = graph.classes[cls_qual]
        locks = _class_locks(node)
        assigned: Set[str] = set()
        # Methods AND their nested functions (both carry cls).
        methods = [(q, info) for q, info in graph.functions.items()
                   if info.cls == cls_qual]
        for qual, info in methods:
            for sub in ast.walk(info.node):
                if isinstance(sub, ast.Assign):
                    for target in sub.targets:
                        self._collect_assigned(target, assigned)
                elif isinstance(sub, (ast.AugAssign, ast.AnnAssign)):
                    self._collect_assigned(sub.target, assigned)
        for spec in owners.values():
            if spec.attr not in assigned:
                self.findings.append(core.Finding(
                    self.rule, self.ctx.path, spec.line, 0,
                    f'{node.name} declares owner[{spec.role}] for '
                    f'attribute {spec.attr!r} that is never assigned '
                    f'in the class (ownership drift)'))
        for qual, info in methods:
            root = graph.functions[self._root(graph, info)]
            if root.name in ('__init__', '__new__', '__del__',
                             '__post_init__'):
                continue
            roles = graph.roles(qual) - {callgraph.INIT_ROLE}
            if not roles:
                continue
            if _acquires_lock(info.node, locks):
                continue
            self._flag_accesses(graph, info, owners, roles)

    @staticmethod
    def _root(graph: callgraph.ModuleGraph,
              info: callgraph.FuncInfo) -> str:
        """Qualname of the outermost enclosing function (nested defs
        inherit their method's exemptions)."""
        qual = info.qualname
        while graph.functions[qual].parent is not None:
            qual = graph.functions[qual].parent
        return qual

    @staticmethod
    def _collect_assigned(target: ast.AST, out: Set[str]) -> None:
        if isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                ThreadOwnershipChecker._collect_assigned(elt, out)
            return
        if isinstance(target, (ast.Subscript, ast.Starred)):
            target = target.value
        attr = _self_attr(target)
        if attr is not None:
            out.add(attr)

    def _flag_accesses(self, graph: callgraph.ModuleGraph,
                       info: callgraph.FuncInfo,
                       owners: Dict[str, callgraph.OwnerSpec],
                       roles: Set[str]) -> None:
        flagged: Set[Tuple[int, int]] = set()
        for node in graph.own_nodes(info.node):
            attr = None
            if isinstance(node, ast.Assign):
                for target in node.targets:
                    attr = attr or self._store_attr(target, owners)
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                attr = self._store_attr(node.target, owners)
            elif isinstance(node, ast.Call):
                if (isinstance(node.func, ast.Attribute) and
                        node.func.attr in _MUTATORS):
                    cand = _self_attr(node.func.value)
                    if cand in owners:
                        attr = cand
            if attr is None:
                continue
            spec = owners[attr]
            foreign = roles - {spec.role}
            if foreign:
                flagged.add((node.lineno, node.col_offset))
                self._violation(node, info, spec, foreign, 'writes')
        # Strict owners police reads too.
        for node in graph.own_nodes(info.node):
            if not isinstance(node, ast.Attribute):
                continue
            if not isinstance(node.ctx, ast.Load):
                continue
            attr = _self_attr(node)
            if attr is None or attr not in owners:
                continue
            spec = owners[attr]
            if not spec.strict:
                continue
            if (node.lineno, node.col_offset) in flagged:
                continue
            foreign = roles - {spec.role}
            if foreign:
                self._violation(node, info, spec, foreign, 'reads')

    def _store_attr(self, target: ast.AST,
                    owners: Dict[str, callgraph.OwnerSpec]
                    ) -> Optional[str]:
        if isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                attr = self._store_attr(elt, owners)
                if attr is not None:
                    return attr
            return None
        if isinstance(target, (ast.Subscript, ast.Starred)):
            target = target.value
        attr = _self_attr(target)
        if attr is not None and attr in owners:
            return attr
        return None

    def _violation(self, node: ast.AST, info: callgraph.FuncInfo,
                   spec: callgraph.OwnerSpec, foreign: Set[str],
                   verb: str) -> None:
        roles = ', '.join(sorted(foreign))
        self.add(node,
                 f'{info.qualname} {verb} self.{spec.attr} (owned by '
                 f'{spec.role}{"!" if spec.strict else ""}) but is '
                 f'reachable from role(s) {roles}; hop through a '
                 f'stpu:hop function, hold a declared lock, or pin '
                 f'the caller with stpu:role[...]')
